"""tracelint: an AST linter for the jitted gossip engine's bug classes.

Pure stdlib (``ast`` + ``json``) — importing this module never pulls in
jax, so the lint runs in milliseconds from CI hooks.

What it knows that a generic linter does not:

- **Which functions are traced.**  Roots are functions passed to
  ``jax.jit`` / ``lax.scan`` / ``lax.fori_loop`` / ``lax.cond`` /
  ``lax.while_loop`` / ``jax.vmap`` (and friends), functions *returned by*
  a factory that is itself jitted (the engine's ``_make_run`` pattern),
  and ``@jax.jit``-decorated defs.  The call graph then propagates
  tracedness through repo-internal calls (module functions, ``self.``
  methods including subclass overrides — a variant's ``_round`` override
  is as traced as the base's).  Functions handed to ``io_callback`` /
  ``pure_callback`` / ``debug.callback`` are HOST sinks and are excluded
  even when defined inside a traced region.

- **Which values are traced.**  Inside a traced function the parameters
  (minus ``self``/``cls``) are tainted; taint propagates through
  assignments, ``jnp.*``/``jax.*`` call results, and any call fed a
  tainted argument.  Shape-static reads (``x.shape``, ``x.ndim``,
  ``x.dtype``, ``len(x)``, ``is None`` tests) deliberately do NOT taint —
  ``int(x.shape[0])`` is fine, ``int(x[0])`` is not.

Rules (ids are stable, grep-able, and the suppression currency):

=================== =====================================================
id                  fires on
=================== =====================================================
host-coerce         ``float()``/``int()``/``bool()`` (or ``.item()`` /
                    ``.tolist()``) of a traced value in a traced region —
                    a ConcretizationTypeError at best, a silently
                    trace-time-frozen constant at worst
host-branch         ``if``/``while``/``for``/``assert``/ternary on a
                    traced value in a traced region (branch must be
                    ``lax.cond``/``jnp.where``; iteration ``fori_loop``)
np-in-trace         ``np.*``/``math.*`` called ON a traced value in a
                    traced region: numpy silently concretizes and
                    constant-folds the tracer
traced-slice        a Python slice ``x[a:b]`` whose bound is traced —
                    shapes must be static; use ``lax.dynamic_slice``
use-after-donate    a buffer passed to a donating call
                    (``donate_state=True`` by default on ``start``, or
                    ``donate_argnums``) is read again afterwards — the
                    donated input is invalidated
registry-field      a ``probe_*``/``health_*``/``chaos_*``/``perf_*``
                    per-round stat
                    key that is missing from the report registry
                    (``PER_ROUND_FIELDS``/``STATIC_FIELDS``) — it would
                    silently vanish from save/load/concatenate
schema-tolerance    ``JSONLinesReceiver.SCHEMA`` was bumped past the
                    versions ``parse_line`` tolerates
metrics-in-trace    a call that resolves into ``telemetry.metrics``
                    (registry/counter/histogram APIs) reachable from a
                    traced root — metrics are host-side sinks, same
                    contract as io_callback bodies; record after the
                    run, or from inside a host callback
trace-in-trace      a call that resolves into ``telemetry.tracing``
                    (span/counter/tracer APIs) reachable from a traced
                    root — the span tracer is a host-side sink under the
                    same contract; span host segments, not jitted code
ledger-in-trace     a call that resolves into ``telemetry.ledger``
                    (RunLedger appends, ingest adapters) reachable from
                    a traced root — the run ledger is a host-side sink
                    under the same contract; append digest rows after
                    the run, never inside jitted code
=================== =====================================================

Suppression: append ``# tracelint: disable=<rule>[,<rule>...]`` (or
``disable=all``) to the offending line.  Pre-existing findings live in the
committed ``analysis/baseline.json`` (finding identity = rule + file +
hash of the stripped source line, so baselined findings survive line-number
drift); ``python -m gossipy_tpu.analysis`` fails only on NEW findings.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

ALL_RULES = {
    "host-coerce": "host coercion (float/int/bool/.item) of a traced value",
    "host-branch": "host control flow (if/while/for/assert) on a traced value",
    "np-in-trace": "np.*/math.* call on a traced value (silent constant fold)",
    "traced-slice": "Python slice with a traced bound (non-static shape)",
    "use-after-donate": "donated buffer read after the donating call",
    "registry-field": "per-round stat key missing from the report registry",
    "schema-tolerance": "JSONL SCHEMA bumped past parse_line's tolerance",
    "metrics-in-trace": "telemetry.metrics registry call in a traced region",
    "trace-in-trace": "telemetry.tracing span/tracer call in a traced region",
    "ledger-in-trace": "telemetry.ledger append/ingest call in a traced "
                       "region",
}

# The SLO metrics registry (telemetry.metrics) is a HOST sink by
# contract — the same boundary io_callback bodies live under. Any call
# that resolves into this module from a traced region is a finding: at
# best it concretizes a tracer into a counter, at worst it silently
# records trace-time constants once per compile instead of run values.
_METRICS_MODULE = "gossipy_tpu/telemetry/metrics.py"

# The span tracer (telemetry.tracing) is the SAME kind of host sink:
# spans time host segments around jitted calls, never inside them. A
# tracer call reachable from a traced root would record trace-time
# nonsense once per compile — and wall timestamps are meaningless inside
# a trace anyway.
_TRACING_MODULE = "gossipy_tpu/telemetry/tracing.py"

# The run ledger (telemetry.ledger) is the SAME kind of host sink:
# digest rows are appended after a run finishes (engine start() tail,
# service tenant finalize), never from jitted code. A ledger call
# reachable from a traced root would fsync a file once per COMPILE with
# trace-time constants — and stall the trace on disk I/O besides.
_LEDGER_MODULE = "gossipy_tpu/telemetry/ledger.py"

# Call-name suffix -> positions of function-valued operands that are traced.
# None means "every positional argument from index 0" (switch: from 1).
_TRACING_CALLS = {
    "jit": (0,),
    "scan": (0,),
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "cond": (1, 2),
    "switch": "tail",     # lax.switch(index, branches...) / branch list
    "vmap": (0,),
    "pmap": (0,),
    "checkpoint": (0,),
    "remat": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
    "named_call": (0,),
    "associative_scan": (0,),
    "shard_map": (0,),
    "custom_jvp": (0,),
    "custom_vjp": (0,),
}

# Functions whose function-valued first argument runs on the HOST even when
# the call site is traced (callbacks). Never propagate tracedness into them.
_HOST_SINKS = {"io_callback", "pure_callback", "callback", "debug_callback"}

# Attribute reads that are shape-static on a tracer (do not carry taint).
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                 "weak_type", "itemsize"}

# Generic container/str method names: an ``obj.m(...)`` call with one of
# these names never resolves to a repo method (keeps ``stats.update(...)``
# from tainting every handler ``update``).
_METHOD_DENYLIST = {
    "append", "add", "extend", "insert", "pop", "remove", "clear", "copy",
    "get", "items", "keys", "values", "setdefault", "update", "split",
    "join", "strip", "startswith", "endswith", "format", "encode", "decode",
    "write", "read", "close", "flush", "sum", "mean", "max", "min", "all",
    "any", "astype", "reshape", "tolist", "item", "index", "count", "sort",
    "total",
}

_STAT_KEY_RE = re.compile(r"^(probe|health|chaos|perf|cohort)_[a-z0-9_]+$")
_SUPPRESS_RE = re.compile(r"#\s*tracelint:\s*disable=([a-z\-,\s]+|all)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*tracelint:\s*disable-file=([a-z\-,\s]+|all)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    col: int
    message: str
    snippet: str       # stripped source line (finding identity basis)

    @property
    def key(self) -> str:
        digest = hashlib.sha1(self.snippet.encode()).hexdigest()[:12]
        return f"{self.rule}|{self.path}|{digest}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet, "key": self.key}

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


# ---------------------------------------------------------------------------
# Module model


@dataclass
class _Func:
    module: str                       # relpath
    qualname: str
    node: ast.AST                     # FunctionDef / AsyncFunctionDef / Lambda
    class_name: Optional[str]
    parent: Optional["_Func"]         # lexically enclosing function

    @property
    def uid(self) -> tuple:
        return (self.module, self.qualname)

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")


class _Module:
    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        # Qualnames are NOT unique (e.g. the same nested name defined in
        # both branches of an if) — every index maps to a list.
        self.funcs: dict[str, list] = {}
        self.by_node: dict[int, _Func] = {}
        self.classes: dict[str, dict] = {}   # name -> {bases, methods}
        self.imports: dict[str, str] = {}    # local name -> dotted module
        self.from_imports: dict[str, tuple] = {}  # name -> (module, orig)

    def dotted(self) -> str:
        return self.relpath[:-3].replace("/", ".")


def _resolve_relative(module_dotted: str, node: ast.ImportFrom) -> str:
    if node.level == 0:
        return node.module or ""
    parts = module_dotted.split(".")
    base = parts[: len(parts) - node.level]
    if node.module:
        base.append(node.module)
    return ".".join(base)


class _Indexer(ast.NodeVisitor):
    """One pass per module: functions (with lexical parents), classes,
    imports."""

    def __init__(self, mod: _Module):
        self.mod = mod
        self.class_stack: list[str] = []
        self.func_stack: list[_Func] = []

    def _qual(self, name: str) -> str:
        if self.func_stack:
            return self.func_stack[-1].qualname + ".<locals>." + name
        if self.class_stack:
            return ".".join(self.class_stack) + "." + name
        return name

    def _visit_func(self, node):
        fn = _Func(self.mod.relpath, self._qual(node.name), node,
                   self.class_stack[-1] if self.class_stack
                   and not self.func_stack else None,
                   self.func_stack[-1] if self.func_stack else None)
        self.mod.funcs.setdefault(fn.qualname, []).append(fn)
        self.mod.by_node[id(node)] = fn
        if fn.class_name is not None:
            self.mod.classes[fn.class_name]["methods"].setdefault(
                node.name, []).append(fn)
        self.func_stack.append(fn)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef):
        bases = [b.id if isinstance(b, ast.Name) else b.attr
                 for b in node.bases
                 if isinstance(b, (ast.Name, ast.Attribute))]
        self.mod.classes.setdefault(node.name,
                                    {"bases": bases, "methods": {}})
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            self.mod.imports[alias.asname or alias.name.split(".")[0]] = \
                alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom):
        src = _resolve_relative(self.mod.dotted(), node)
        for alias in node.names:
            self.mod.from_imports[alias.asname or alias.name] = \
                (src, alias.name)


# ---------------------------------------------------------------------------
# Repo model: all modules, cross-module resolution, traced-set propagation


class _Repo:
    def __init__(self, modules: list[_Module]):
        self.modules = {m.relpath: m for m in modules}
        self.by_dotted = {m.dotted(): m for m in modules}
        self.method_index: dict[str, list[_Func]] = {}
        self.subclasses: dict[str, set] = {}   # class name -> subclass names
        self.class_home: dict[str, _Module] = {}
        for m in modules:
            for cname, info in m.classes.items():
                self.class_home.setdefault(cname, m)
                for fns in info["methods"].values():
                    for fn in fns:
                        self.method_index.setdefault(fn.name,
                                                     []).append(fn)
        for m in modules:
            for cname, info in m.classes.items():
                for b in info["bases"]:
                    self.subclasses.setdefault(b, set()).add(cname)

    def transitive_subclasses(self, cname: str) -> set:
        out, todo = set(), [cname]
        while todo:
            c = todo.pop()
            for s in self.subclasses.get(c, ()):
                if s not in out:
                    out.add(s)
                    todo.append(s)
        return out

    def class_chain(self, cname: str) -> list:
        """cname + its repo base classes, transitively (MRO-ish order)."""
        out, todo = [], [cname]
        while todo:
            c = todo.pop(0)
            if c in out:
                continue
            out.append(c)
            home = self.class_home.get(c)
            if home is not None:
                todo.extend(home.classes[c]["bases"])
        return out

    def find_method(self, cname: str, mname: str) -> list:
        """Resolve ``self.mname`` inside class ``cname``: the defining class
        up the chain, PLUS every subclass override below ``cname`` (a traced
        base method means the variant overrides trace too)."""
        hits = []
        for c in self.class_chain(cname):
            home = self.class_home.get(c)
            if home is not None and mname in home.classes[c]["methods"]:
                hits.extend(home.classes[c]["methods"][mname])
                break
        for sub in self.transitive_subclasses(cname):
            home = self.class_home.get(sub)
            if home is not None and mname in home.classes[sub]["methods"]:
                hits.extend(home.classes[sub]["methods"][mname])
        return hits

    def module_func(self, mod: _Module, name: str,
                    context: Optional[_Func]) -> list:
        # Lexical chain: nested defs of the context (and its ancestors),
        # then module level, then repo-internal imports.
        seen = context
        while seen is not None:
            q = seen.qualname + ".<locals>." + name
            if q in mod.funcs:
                return list(mod.funcs[q])
            seen = seen.parent
        if name in mod.funcs:
            return list(mod.funcs[name])
        if name in mod.from_imports:
            src, orig = mod.from_imports[name]
            target = self.by_dotted.get(src)
            if target is not None and orig in target.funcs:
                return list(target.funcs[orig])
            # ``from .x import SomeClass`` — methods resolve via attr calls.
        return []

    def resolve_call(self, mod: _Module, call: ast.Call,
                     context: Optional[_Func]) -> list:
        f = call.func
        if isinstance(f, ast.Name):
            return self.module_func(mod, f.id, context)
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                root = f.value.id
                if root in ("self", "cls"):
                    cname = _enclosing_class(context)
                    if cname is not None:
                        return self.find_method(cname, f.attr)
                    return []
                if root in mod.imports:
                    # An imported module: resolve inside it when it is a
                    # repo module, NEVER fall through to the generic method
                    # index (jax.random.uniform must not resolve to some
                    # repo method named ``uniform``).
                    target = self.by_dotted.get(mod.imports[root])
                    if target is not None and f.attr in target.funcs:
                        return list(target.funcs[f.attr])
                    return []
                if root in mod.from_imports:  # imported repo class/submodule
                    src, orig = mod.from_imports[root]
                    target = self.by_dotted.get(src + "." + orig) \
                        or self.by_dotted.get(src)
                    if target is not None:
                        if f.attr in target.funcs:
                            return list(target.funcs[f.attr])
                        if orig in target.classes:
                            return self.find_method(orig, f.attr)
                    return []
            elif not isinstance(f.value, (ast.Attribute, ast.Call)):
                return []
            else:
                # Nested chain (a.b.m / f().m): external when it roots at
                # an imported non-repo module (jax.random.uniform).
                root = f.value
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in mod.imports \
                        and mod.imports[root.id] not in self.by_dotted:
                    return []
            # obj.m(...): any repo method named m (heuristic; the generic
            # names in the denylist stay host-side).
            if f.attr not in _METHOD_DENYLIST:
                return self.method_index.get(f.attr, [])
        return []


def _enclosing_class(fn: Optional[_Func]) -> Optional[str]:
    while fn is not None:
        if fn.class_name is not None:
            return fn.class_name
        fn = fn.parent
    return None


def _callee_suffix(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_jax_chain(expr: ast.AST) -> bool:
    """Does this callee chain plausibly root at jax/lax/jnp?"""
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return isinstance(expr, ast.Name) and expr.id in (
        "jax", "lax", "jnp", "pjit", "xla")


def _static_argnames(call_kw: list) -> frozenset:
    """Parameter names declared static via ``static_argnames`` (argnums
    resolve to names later, at lint time, via the function's arg list)."""
    names = []
    for k in call_kw:
        if k.arg == "static_argnames":
            vals = k.value.elts if isinstance(k.value,
                                              (ast.Tuple, ast.List)) \
                else [k.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    names.append(v.value)
    return frozenset(names)


def _static_argnums(call_kw: list) -> frozenset:
    nums = []
    for k in call_kw:
        if k.arg == "static_argnums":
            vals = k.value.elts if isinstance(k.value,
                                              (ast.Tuple, ast.List)) \
                else [k.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    nums.append(v.value)
    return frozenset(nums)


class _RootFinder(ast.NodeVisitor):
    """Find traced-region roots + host-sink functions in one module."""

    def __init__(self, mod: _Module, repo: _Repo):
        self.mod = mod
        self.repo = repo
        self.roots: list[tuple] = []          # (_Func, static_names, nums)
        self.lambda_roots: list[tuple] = []   # (Lambda node, context)
        self.host_sink_nodes: set = set()     # id() of def/lambda nodes
        self.factory_jitted: list[_Func] = []
        self.func_stack: list[_Func] = []

    def _context(self) -> Optional[_Func]:
        return self.func_stack[-1] if self.func_stack else None

    def _visit_func(self, node):
        fn = self.mod.by_node[id(node)]
        self.func_stack.append(fn)
        # @jax.jit / @jit / @partial(jax.jit, ...) decorated defs are roots.
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            kw = dec.keywords if isinstance(dec, ast.Call) else []
            if isinstance(dec, ast.Call) and dec.args \
                    and _callee_suffix(dec) == "partial":
                target = dec.args[0]
            if _callee_suffix_expr(target) in ("jit", "vmap", "pmap",
                                               "checkpoint", "remat"):
                self.roots.append((fn, _static_argnames(kw),
                                   _static_argnums(kw)))
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _mark_arg(self, arg: ast.AST, static_names=frozenset(),
                  static_nums=frozenset()):
        ctx = self._context()
        if isinstance(arg, ast.Lambda):
            self.lambda_roots.append((arg, ctx))
        elif isinstance(arg, ast.Name):
            for fn in self.repo.module_func(self.mod, arg.id, ctx):
                self.roots.append((fn, static_names, static_nums))
        elif isinstance(arg, ast.Attribute) and \
                isinstance(arg.value, ast.Name) and \
                arg.value.id in ("self", "cls"):
            cname = _enclosing_class(ctx)
            if cname is not None:
                for fn in self.repo.find_method(cname, arg.attr):
                    self.roots.append((fn, static_names, static_nums))
        elif isinstance(arg, ast.Call):
            # Factory pattern: jax.jit(self._make_run(...)) — the traced
            # function is whatever the factory RETURNS.
            for fac in self.repo.resolve_call(self.mod, arg, ctx):
                self.factory_jitted.append(fac)
        elif isinstance(arg, (ast.List, ast.Tuple)):
            for el in arg.elts:
                self._mark_arg(el, static_names, static_nums)

    def visit_Call(self, node: ast.Call):
        suffix = _callee_suffix(node)
        if suffix in _HOST_SINKS and node.args:
            sink = node.args[0]
            if isinstance(sink, (ast.Lambda,)):
                self.host_sink_nodes.add(id(sink))
            elif isinstance(sink, ast.Name):
                for fn in self.repo.module_func(self.mod, sink.id,
                                                self._context()):
                    self.host_sink_nodes.add(id(fn.node))
        elif suffix in _TRACING_CALLS and (
                _is_jax_chain(node.func) or isinstance(node.func, ast.Name)):
            spec = _TRACING_CALLS[suffix]
            statics = _static_argnames(node.keywords)
            nums = _static_argnums(node.keywords)
            if spec == "tail":
                for arg in node.args[1:]:
                    self._mark_arg(arg, statics, nums)
            else:
                for pos in spec:
                    if pos < len(node.args):
                        self._mark_arg(node.args[pos], statics, nums)
        self.generic_visit(node)


def _callee_suffix_expr(expr: ast.AST) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _own_nodes(fn_node: ast.AST):
    """Walk a function's OWN code: descend lambdas (they execute inline
    during trace) but never nested ``def``s — those are separate regions
    that become traced only via the call graph (an io_callback body defined
    inside a traced method stays host-side)."""
    todo = list(ast.iter_child_nodes(fn_node))
    while todo:
        node = todo.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            todo.extend(ast.iter_child_nodes(node))


def _returned_nested_defs(fn: _Func, mod: _Module) -> list:
    """Nested defs a factory function returns (by name)."""
    out = []
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            q = fn.qualname + ".<locals>." + node.value.id
            if q in mod.funcs:
                out.extend(mod.funcs[q])
    return out


# ---------------------------------------------------------------------------
# Taint-based rules inside one traced function


_STATIC_PARAM_NAMES = {"self", "cls", "cfg", "config", "axis_name",
                       "mesh"}
_STATIC_ANNOTATIONS = {"bool", "int", "float", "str", "bytes", "dict",
                       "list", "tuple", "set", "Mesh", "Topology",
                       "SparseTopology"}


def _param_is_static(a: ast.arg) -> bool:
    """Parameters that are static-by-contract in a traced function: config
    objects and python-scalar-annotated knobs resolve at trace time."""
    if a.arg in _STATIC_PARAM_NAMES:
        return True
    if a.annotation is None:
        return False
    names = {n.id for n in ast.walk(a.annotation)
             if isinstance(n, ast.Name)}
    names |= {n.attr for n in ast.walk(a.annotation)
              if isinstance(n, ast.Attribute)}
    for n in ast.walk(a.annotation):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            names.add(n.value)
    return any(n in _STATIC_ANNOTATIONS or n.endswith("Config")
               for n in names)


class _TaintLinter(ast.NodeVisitor):
    def __init__(self, mod: _Module, fn_node: ast.AST,
                 host_sinks: set, findings: list,
                 static_names=frozenset(), static_nums=frozenset()):
        self.mod = mod
        self.findings = findings
        self.host_sinks = host_sinks
        self.tainted: set = set()
        self.containers: set = set()   # host containers of traced values
        args = fn_node.args
        ordered = args.posonlyargs + args.args
        by_num = {i: a.arg for i, a in enumerate(ordered)}
        static = set(static_names) | {by_num[i] for i in static_nums
                                      if i in by_num}
        for a in (ordered + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            if a.arg not in static and not _param_is_static(a):
                self.tainted.add(a.arg)
        body = fn_node.body
        self._nodes = body if isinstance(body, list) else [body]

    def run(self):
        for stmt in self._nodes:
            self.visit(stmt)

    # -- taint query ------------------------------------------------------

    def _is_tainted(self, expr: ast.AST) -> bool:
        if expr is None:
            return False
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            return self._is_tainted(expr.value)
        if isinstance(expr, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
                return False  # identity tests are static on tracers
            return self._is_tainted(expr.left) or \
                any(self._is_tainted(c) for c in expr.comparators)
        if isinstance(expr, ast.Call):
            suffix = _callee_suffix(expr)
            if suffix in ("len", "isinstance", "getattr", "hasattr",
                          "type", "id", "repr", "str"):
                return False
            if _is_jax_chain(expr.func):
                return True
            return self._is_tainted(expr.func) or \
                any(self._is_tainted(a) for a in expr.args) or \
                any(self._is_tainted(k.value) for k in expr.keywords)
        if isinstance(expr, (ast.BinOp,)):
            return self._is_tainted(expr.left) or self._is_tainted(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self._is_tainted(expr.operand)
        if isinstance(expr, ast.BoolOp):
            return any(self._is_tainted(v) for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return self._is_tainted(expr.body) or \
                self._is_tainted(expr.orelse)
        if isinstance(expr, ast.Subscript):
            return self._is_tainted(expr.value)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self._is_tainted(e) for e in expr.elts)
        if isinstance(expr, ast.Dict):
            return any(self._is_tainted(v) for v in expr.values)
        if isinstance(expr, ast.Starred):
            return self._is_tainted(expr.value)
        if isinstance(expr, ast.NamedExpr):
            return self._is_tainted(expr.value)
        return False

    def _taint_target(self, target: ast.AST):
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._taint_target(el)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value)

    def _untaint_target(self, target: ast.AST):
        if isinstance(target, ast.Name):
            self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._untaint_target(el)

    # -- findings ---------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 1)
        text = self.mod.lines[line - 1].strip() \
            if 0 < line <= len(self.mod.lines) else ""
        self.findings.append(Finding(
            rule=rule, path=self.mod.relpath, line=line,
            col=getattr(node, "col_offset", 0), message=message,
            snippet=text))

    # -- statements (visited in order; one flat scope) --------------------

    def _is_container_expr(self, expr) -> bool:
        return (isinstance(expr, ast.Call)
                and _callee_suffix(expr) in self._CONTAINER_ITERS) or \
            (isinstance(expr, ast.Name) and expr.id in self.containers) or \
            isinstance(expr, (ast.Tuple, ast.List, ast.ListComp))

    def visit_Assign(self, node: ast.Assign):
        self.visit(node.value)
        if self._is_tainted(node.value):
            for t in node.targets:
                self._taint_target(t)
                if self._is_container_expr(node.value) and \
                        isinstance(t, ast.Name):
                    self.containers.add(t.id)
        else:
            for t in node.targets:
                self._untaint_target(t)
                if isinstance(t, ast.Name):
                    self.containers.discard(t.id)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.value is not None:
            self.visit(node.value)
            if self._is_tainted(node.value):
                self._taint_target(node.target)
            else:
                self._untaint_target(node.target)

    def visit_AugAssign(self, node: ast.AugAssign):
        self.visit(node.value)
        if self._is_tainted(node.value):
            self._taint_target(node.target)

    def visit_NamedExpr(self, node: ast.NamedExpr):
        self.visit(node.value)
        if self._is_tainted(node.value):
            self._taint_target(node.target)

    def visit_If(self, node: ast.If):
        if self._is_tainted(node.test):
            self._emit("host-branch", node,
                       "`if` on a traced value — the branch is resolved at "
                       "trace time (use lax.cond / jnp.where)")
        self.visit(node.test)
        for s in node.body + node.orelse:
            self.visit(s)

    def visit_While(self, node: ast.While):
        if self._is_tainted(node.test):
            self._emit("host-branch", node,
                       "`while` on a traced value (use lax.while_loop)")
        self.generic_visit(node)

    # Iterating these yields a HOST container whose *elements* may be
    # traced — the loop itself is trace-safe (pytree leaves, zips of leaf
    # lists). The loop targets inherit the taint instead.
    _CONTAINER_ITERS = {"leaves", "tree_leaves", "tree_flatten", "flatten",
                        "enumerate", "zip", "reversed", "sorted", "list",
                        "tuple", "items", "keys", "values", "split"}

    def visit_For(self, node: ast.For):
        if self._is_tainted(node.iter):
            if self._is_container_expr(node.iter):
                self._taint_target(node.target)
            else:
                self._emit("host-branch", node,
                           "`for` over a traced value — the loop unrolls "
                           "(or fails) at trace time (use "
                           "lax.fori_loop/scan)")
                self._taint_target(node.target)
        self.visit(node.iter)
        for s in node.body + node.orelse:
            self.visit(s)

    def visit_Assert(self, node: ast.Assert):
        if self._is_tainted(node.test):
            self._emit("host-branch", node,
                       "`assert` on a traced value (use "
                       "checkify / debug.check, or assert on static shape)")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp):
        if self._is_tainted(node.test):
            self._emit("host-branch", node,
                       "ternary on a traced value (use jnp.where)")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        suffix = _callee_suffix(node)
        if isinstance(node.func, ast.Name) and \
                node.func.id in ("float", "int", "bool") and node.args and \
                self._is_tainted(node.args[0]):
            self._emit("host-coerce", node,
                       f"`{node.func.id}()` of a traced value concretizes "
                       "the tracer (compute in-graph, coerce after the run)")
        elif isinstance(node.func, ast.Attribute) and \
                suffix in ("item", "tolist") and \
                self._is_tainted(node.func.value):
            self._emit("host-coerce", node,
                       f"`.{suffix}()` of a traced value pulls it to host "
                       "at trace time")
        elif isinstance(node.func, ast.Attribute):
            root = node.func.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and \
                    root.id in ("np", "numpy", "math") and \
                    (any(self._is_tainted(a) for a in node.args)
                     or any(self._is_tainted(k.value)
                            for k in node.keywords)):
                self._emit("np-in-trace", node,
                           f"`{root.id}.{suffix}` on a traced value — numpy "
                           "concretizes and silently constant-folds the "
                           "tracer (use jnp)")
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        slices = []
        if isinstance(node.slice, ast.Slice):
            slices = [node.slice]
        elif isinstance(node.slice, ast.Tuple):
            slices = [e for e in node.slice.elts
                      if isinstance(e, ast.Slice)]
        for sl in slices:
            for bound in (sl.lower, sl.upper, sl.step):
                if bound is not None and self._is_tainted(bound):
                    self._emit("traced-slice", node,
                               "slice bound is a traced value — result "
                               "shape would be dynamic (use "
                               "lax.dynamic_slice)")
                    break
        self.generic_visit(node)

    def _skip_nested(self, node):
        # Nested defs/lambdas get their own traced-region pass (via the
        # call graph) or are host sinks; don't lint them with THIS scope's
        # taint.
        pass

    visit_FunctionDef = _skip_nested
    visit_AsyncFunctionDef = _skip_nested
    visit_Lambda = _skip_nested


# ---------------------------------------------------------------------------
# use-after-donate (host-side rule, every function)


class _DonateLinter:
    def __init__(self, mod: _Module, findings: list):
        self.mod = mod
        self.findings = findings

    @staticmethod
    def _donating_call(call: ast.Call) -> Optional[str]:
        """The donated first-positional-arg name, if this call donates."""
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        donates = False
        if "donate_argnums" in kw:
            donates = True
        if "donate_state" in kw:
            v = kw["donate_state"]
            donates = not (isinstance(v, ast.Constant) and v.value is False)
        elif _callee_suffix(call) == "start" and \
                isinstance(call.func, ast.Attribute) and call.args:
            donates = True  # engine start() donates by default
        # jax.jit(f, donate_argnums=...)(state, ...) — donation lands on
        # the OUTER call's positionals.
        if isinstance(call.func, ast.Call):
            inner_kw = {k.arg for k in call.func.keywords}
            if "donate_argnums" in inner_kw:
                donates = True
        if donates and call.args and isinstance(call.args[0], ast.Name):
            return call.args[0].id
        return None

    def lint_function(self, fn_node: ast.AST):
        body = getattr(fn_node, "body", None)
        if not isinstance(body, list):
            return
        donated: dict[str, int] = {}   # name -> line of donating call

        def names_loaded(expr) -> set:
            return {n.id for n in ast.walk(expr)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)}

        def names_stored(stmt) -> set:
            out = set()
            for n in ast.walk(stmt):
                if isinstance(n, ast.Name) and \
                        isinstance(n.ctx, (ast.Store, ast.Del)):
                    out.add(n.id)
            return out

        for stmt in _linear_statements(body):
            calls = [n for n in ast.walk(stmt)
                     if isinstance(n, ast.Call)]
            # Reads first: a use of an already-donated buffer fires even
            # when this statement re-donates/rebinds it.
            used = set()
            for n in ast.walk(stmt):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                        and n.id in donated:
                    used.add((n.id, n.lineno, n.col_offset))
            for name, line, col in sorted(used):
                text = self.mod.lines[line - 1].strip() \
                    if 0 < line <= len(self.mod.lines) else ""
                self.findings.append(Finding(
                    rule="use-after-donate", path=self.mod.relpath,
                    line=line, col=col,
                    message=f"`{name}` was donated at line "
                            f"{donated[name]} (donate_state/donate_argnums "
                            "invalidates the buffer); rebind the result or "
                            "pass donate_state=False",
                    snippet=text))
            stored = names_stored(stmt)
            for s in stored:
                donated.pop(s, None)
            for call in calls:
                name = self._donating_call(call)
                if name is not None and name not in stored:
                    donated[name] = call.lineno


def _linear_statements(body: list) -> list:
    """Flatten a function body into a linear statement order (branches and
    loop bodies in source order — a deliberate approximation)."""
    out = []
    for stmt in body:
        out.append(stmt)
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
                out.extend(_linear_statements(sub))
        for h in getattr(stmt, "handlers", []) or []:
            out.extend(_linear_statements(h.body))
    return out


# ---------------------------------------------------------------------------
# Registry rules (repo-level)


def _literal_str_tuples(mod: _Module) -> dict[str, tuple]:
    """Module-level ``NAME = ("a", "b", ...)`` assignments, resolving
    ``A + B`` concatenations of previously seen names."""
    out: dict[str, tuple] = {}

    def eval_expr(expr) -> Optional[tuple]:
        if isinstance(expr, (ast.Tuple, ast.List)):
            vals = []
            for el in expr.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    vals.append(el.value)
                else:
                    return None
            return tuple(vals)
        if isinstance(expr, ast.Name):
            return out.get(expr.id)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            left, right = eval_expr(expr.left), eval_expr(expr.right)
            if left is not None and right is not None:
                return left + right
        return None

    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            val = eval_expr(node.value)
            if val is not None:
                out[node.targets[0].id] = val
    return out


def _registry_rule(modules: dict, findings: list):
    report = modules.get("gossipy_tpu/simulation/report.py")
    if report is None:
        return
    tuples = _literal_str_tuples(report)
    registry = set(tuples.get("PER_ROUND_FIELDS", ())) | \
        set(tuples.get("STATIC_FIELDS", ()))
    if not registry:
        return

    def check_key(key: str, mod: _Module, node: ast.AST):
        if _STAT_KEY_RE.match(key) and key not in registry:
            line = getattr(node, "lineno", 1)
            text = mod.lines[line - 1].strip() \
                if 0 < line <= len(mod.lines) else ""
            findings.append(Finding(
                rule="registry-field", path=mod.relpath, line=line,
                col=getattr(node, "col_offset", 0),
                message=f"per-round stat key {key!r} is not in "
                        "report.PER_ROUND_FIELDS/STATIC_FIELDS — it would "
                        "be silently dropped by "
                        "to_dict/from_dict/concatenate",
                snippet=text))

    for relpath, mod in modules.items():
        if not (relpath.startswith("gossipy_tpu/simulation/")
                or relpath.startswith("gossipy_tpu/telemetry/")):
            continue
        # (a) declared stat-key tuples (PROBE_STAT_KEYS & co.)
        for name, vals in _literal_str_tuples(mod).items():
            if name.endswith(("_KEYS", "_FIELDS")) and \
                    relpath != "gossipy_tpu/simulation/report.py":
                for node in mod.tree.body:
                    if isinstance(node, ast.Assign) and \
                            isinstance(node.targets[0], ast.Name) and \
                            node.targets[0].id == name:
                        for key in vals:
                            check_key(key, mod, node)
        # (b) direct stores into the round stats dict:
        #     stats["health_x"] = ... / extras["probe_y"] = ...
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in ("stats", "extras") and \
                            isinstance(t.slice, ast.Constant) and \
                            isinstance(t.slice.value, str):
                        check_key(t.slice.value, mod, node)


def _schema_rule(modules: dict, findings: list):
    mod = modules.get("gossipy_tpu/simulation/events.py")
    if mod is None:
        return
    schema_val, schema_node = None, None
    tolerated = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and \
                node.name == "JSONLinesReceiver":
            for item in node.body:
                if isinstance(item, ast.Assign) and \
                        isinstance(item.targets[0], ast.Name) and \
                        item.targets[0].id == "SCHEMA" and \
                        isinstance(item.value, ast.Constant):
                    schema_val, schema_node = item.value.value, item
                if isinstance(item, ast.FunctionDef) and \
                        item.name == "parse_line":
                    for cmp in ast.walk(item):
                        if isinstance(cmp, ast.Compare) and \
                                len(cmp.ops) == 1 and \
                                isinstance(cmp.ops[0], (ast.Lt, ast.LtE)):
                            c = cmp.comparators[0]
                            if isinstance(c, ast.Constant) and \
                                    isinstance(c.value, int):
                                bound = c.value
                                if isinstance(cmp.ops[0], ast.LtE):
                                    bound += 1
                                tolerated.append(bound)
    if schema_val is None:
        return
    max_tol = max(tolerated) if tolerated else 1
    if schema_val > max_tol:
        line = schema_node.lineno
        findings.append(Finding(
            rule="schema-tolerance", path=mod.relpath, line=line,
            col=schema_node.col_offset,
            message=f"JSONLinesReceiver.SCHEMA = {schema_val} but "
                    f"parse_line only tolerates versions < {max_tol + 1} "
                    f"(add an `if schema < {schema_val}:` defaulting branch "
                    "for the new fields)",
            snippet=mod.lines[line - 1].strip()))


# ---------------------------------------------------------------------------
# Driver


def _file_disabled(mod: _Module) -> set:
    """Rules disabled for the whole file via a ``# tracelint:
    disable-file=...`` pragma in the first 30 lines ({"all"} disables
    everything)."""
    out: set = set()
    for line in mod.lines[:30]:
        m = _SUPPRESS_FILE_RE.search(line)
        if m:
            rules = m.group(1).strip()
            if rules == "all":
                return {"all"}
            out |= {r.strip() for r in rules.split(",")}
    return out


def _suppressed(mod: _Module, finding: Finding) -> bool:
    disabled = _file_disabled(mod)
    if "all" in disabled or finding.rule in disabled:
        return True
    if not (0 < finding.line <= len(mod.lines)):
        return False
    m = _SUPPRESS_RE.search(mod.lines[finding.line - 1])
    if not m:
        return False
    rules = m.group(1).strip()
    if rules == "all":
        return True
    return finding.rule in {r.strip() for r in rules.split(",")}


def run_tracelint(root, sources: Optional[dict] = None,
                  package: str = "gossipy_tpu") -> list:
    """Lint every ``.py`` under ``root/package``.

    ``sources`` maps repo-relative posix paths to replacement text —
    the meta-tests use it to inject violations without touching disk.
    Returns unsuppressed findings sorted by (path, line).
    """
    root = Path(root)
    texts: dict[str, str] = {}
    for p in sorted((root / package).rglob("*.py")):
        rel = p.relative_to(root).as_posix()
        if "__pycache__" in rel:
            continue
        texts[rel] = p.read_text()
    if sources:
        texts.update(sources)

    modules: dict[str, _Module] = {}
    for rel, text in texts.items():
        try:
            mod = _Module(rel, text)
        except SyntaxError as e:
            raise SyntaxError(f"{rel}: {e}") from e
        _Indexer(mod).visit(mod.tree)
        modules[rel] = mod
    repo = _Repo(list(modules.values()))

    # Roots + host sinks, repo-wide.
    traced: dict[int, _Func] = {}        # id(node) -> _Func
    static_info: dict[int, tuple] = {}   # id(node) -> (names, nums)
    lambda_regions: list[tuple] = []
    host_sinks: set = set()
    worklist: list[_Func] = []

    def add(fn: _Func, statics=frozenset(), nums=frozenset()):
        if id(fn.node) in host_sinks:
            return
        if statics or nums:
            static_info.setdefault(id(fn.node), (statics, nums))
        if id(fn.node) not in traced:
            traced[id(fn.node)] = fn
            worklist.append(fn)

    finders = {}
    for rel, mod in modules.items():
        finder = _RootFinder(mod, repo)
        finder.visit(mod.tree)
        finders[rel] = finder
        host_sinks.update(finder.host_sink_nodes)
    for rel, finder in finders.items():
        for fn, statics, nums in finder.roots:
            add(fn, statics, nums)
        for fac in finder.factory_jitted:
            for fn in _returned_nested_defs(fac, modules[fac.module]):
                add(fn)
        lambda_regions.extend(
            (modules[rel], lam) for lam, _ in finder.lambda_roots)

    findings: list[Finding] = []

    def _host_sink_finding(rule: str, message: str, mod: _Module,
                           node: ast.Call):
        line = getattr(node, "lineno", 1)
        text = mod.lines[line - 1].strip() \
            if 0 < line <= len(mod.lines) else ""
        findings.append(Finding(
            rule=rule, path=mod.relpath, line=line,
            col=getattr(node, "col_offset", 0), message=message,
            snippet=text))

    def _metrics_finding(mod: _Module, node: ast.Call):
        _host_sink_finding(
            "metrics-in-trace",
            "telemetry.metrics registry call reachable from a "
            "traced root — metrics are host-side sinks (same "
            "contract as io_callback bodies); record after the "
            "run or from inside a host callback", mod, node)

    def _tracing_finding(mod: _Module, node: ast.Call):
        _host_sink_finding(
            "trace-in-trace",
            "telemetry.tracing span/tracer call reachable from a "
            "traced root — the span tracer is a host-side sink (same "
            "contract as io_callback bodies and the metrics registry); "
            "span the host segment around the jitted call instead",
            mod, node)

    def _ledger_finding(mod: _Module, node: ast.Call):
        _host_sink_finding(
            "ledger-in-trace",
            "telemetry.ledger append/ingest call reachable from a "
            "traced root — the run ledger is a host-side sink (same "
            "contract as io_callback bodies, the metrics registry and "
            "the span tracer); append the digest row after the run "
            "finishes, never from jitted code", mod, node)

    # Propagate tracedness through repo-internal calls. Only a function's
    # OWN code propagates — nested defs are separate regions reached via
    # resolve_call (so an io_callback body inside a traced method never
    # drags its host-side helpers into the traced set). A call resolving
    # into telemetry.metrics, telemetry.tracing or telemetry.ledger does
    # NOT propagate — it is reported as a metrics-in-trace /
    # trace-in-trace / ledger-in-trace finding instead (all three are
    # host sinks by contract; tracing into them would also mis-lint
    # their own host code).
    while worklist:
        fn = worklist.pop()
        mod = modules[fn.module]
        for node in _own_nodes(fn.node):
            if isinstance(node, ast.Call):
                for callee in repo.resolve_call(mod, node, fn):
                    if callee.module == _METRICS_MODULE:
                        _metrics_finding(mod, node)
                    elif callee.module == _TRACING_MODULE:
                        _tracing_finding(mod, node)
                    elif callee.module == _LEDGER_MODULE:
                        _ledger_finding(mod, node)
                    else:
                        add(callee)
    for fn in traced.values():
        mod = modules[fn.module]
        statics, nums = static_info.get(id(fn.node),
                                        (frozenset(), frozenset()))
        _TaintLinter(mod, fn.node, host_sinks, findings,
                     static_names=statics, static_nums=nums).run()
        # Lambdas inside a traced function execute during the trace
        # (tree.map leaf ops, key-fold helpers) — lint them as traced
        # regions of their own unless they are host-callback sinks.
        for node in _own_nodes(fn.node):
            if isinstance(node, ast.Lambda) and id(node) not in host_sinks:
                lambda_regions.append((mod, node))
    for mod, lam in lambda_regions:
        if id(lam) not in host_sinks:
            _TaintLinter(mod, lam, host_sinks, findings).run()
    for rel, mod in modules.items():
        dl = _DonateLinter(mod, findings)
        for fns in mod.funcs.values():
            for fn in fns:
                if fn.parent is None:   # lint each top-level scope once
                    dl.lint_function(fn.node)
    _registry_rule(modules, findings)
    _schema_rule(modules, findings)

    out = [f for f in findings if not _suppressed(modules[f.path], f)]
    # The same (rule, path, line) can fire through several traced paths
    # (e.g. a method traced via two roots) — report it once.
    seen, unique = set(), []
    for f in sorted(out, key=lambda f: (f.path, f.line, f.col, f.rule)):
        k = (f.rule, f.path, f.line, f.col)
        if k not in seen:
            seen.add(k)
            unique.append(f)
    return unique


# ---------------------------------------------------------------------------
# Baseline


def baseline_from_findings(findings: list) -> dict:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return {"version": 1, "findings": counts}


def load_baseline(path) -> dict:
    p = Path(path)
    if not p.exists():
        return {"version": 1, "findings": {}}
    return json.loads(p.read_text())


def filter_baselined(findings: list, baseline: dict) -> list:
    """Findings NOT covered by the baseline (per-key occurrence budget)."""
    budget = dict(baseline.get("findings", {}))
    new = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            new.append(f)
    return new
