"""HLO-stability helpers: canonicalized StableHLO fingerprints + the gate
matrix.

Two fragile invariants hold this codebase together (docs/analysis.md):

1. **Opt-in features are HLO-neutral when off** — ``probes=None``,
   ``sentinels=None`` and ``chaos=None`` must trace the byte-identical
   round program.  :func:`assert_identical_hlo` is the one shared helper
   behind every such test (previously four ad-hoc copies in
   tests/test_probes.py, test_health.py ×2, test_chaos.py).

2. **The round program only changes on purpose** — ``scripts/hlo_gate.py``
   fingerprints the program across the feature-flag grid (probes /
   sentinels / chaos × on/off, history dtypes, All2All formulations) and
   compares against the committed golden manifest
   (``analysis/hlo_golden.json``).  Hashes are only compared when the
   recorded jax version/backend match the current process (HLO text is not
   stable across jax releases); the identity *pairs* are enforced
   unconditionally.

Canonicalization keeps the comparison byte-meaningful across hosts:
location metadata and blank lines are stripped, whitespace normalized —
but NOTHING structural is erased, so any real program change (a new op, a
changed layout, a donation difference) moves the fingerprint.
"""

from __future__ import annotations

import hashlib
import os
import re
from typing import Optional

_LOC_RE = re.compile(r'\s*loc\((?:[^()"]|"[^"]*")*\)')
_HASH_LEN = 16


def canonicalize_hlo(text: str) -> str:
    """Normalize lowered StableHLO text for fingerprinting: drop location
    metadata (absolute paths differ across hosts) and surrounding
    whitespace, keep every instruction."""
    lines = []
    for line in text.splitlines():
        if line.lstrip().startswith("#loc"):
            continue
        line = _LOC_RE.sub("", line).rstrip()
        if line.strip():
            lines.append(line.strip())
    return "\n".join(lines)


def fingerprint_text(text: str) -> str:
    """Short stable hash of canonicalized HLO text."""
    canon = canonicalize_hlo(text)
    return hashlib.sha256(canon.encode()).hexdigest()[:_HASH_LEN]


def lower_text(sim, state=None, key=None, n_rounds: int = 2) -> str:
    """The simulator's ``n_rounds`` round-scan program as StableHLO text
    (AOT-lowered — nothing is compiled or executed)."""
    import jax
    key = key if key is not None else jax.random.PRNGKey(0)
    if state is None:
        state = sim.init_nodes(key)
    return sim.lower_start(state, n_rounds=n_rounds, key=key).as_text()


def compiled_text(sim, state=None, key=None, n_rounds: int = 2) -> str:
    """The POST-compilation HLO text of the round program (named scopes
    and fusion decisions live here; the StableHLO from :func:`lower_text`
    predates them). Compiles for real — costlier than lowering."""
    import jax
    key = key if key is not None else jax.random.PRNGKey(0)
    if state is None:
        state = sim.init_nodes(key)
    return sim.lower_start(state, n_rounds=n_rounds,
                           key=key).compile().as_text()


def hlo_fingerprint(sim, state=None, key=None,
                    n_rounds: int = 2) -> tuple[str, str]:
    """``(fingerprint, canonical_text)`` of the simulator's round program."""
    text = lower_text(sim, state, key, n_rounds)
    canon = canonicalize_hlo(text)
    return hashlib.sha256(canon.encode()).hexdigest()[:_HASH_LEN], canon


def first_divergence(text_a: str, text_b: str,
                     label_a: str = "a", label_b: str = "b"
                     ) -> Optional[dict]:
    """First divergent instruction between two canonicalized HLO programs.

    Returns ``None`` when identical, else a dict naming the 1-based
    canonical instruction index and both sides' instruction text (one side
    is ``"<end of program>"`` on a pure length divergence).
    """
    a, b = canonicalize_hlo(text_a).split("\n"), \
        canonicalize_hlo(text_b).split("\n")
    for i, (la, lb) in enumerate(zip(a, b)):
        if la != lb:
            return {"instruction": i + 1, label_a: la, label_b: lb,
                    f"{label_a}_total": len(a), f"{label_b}_total": len(b)}
    if len(a) != len(b):
        i = min(len(a), len(b))
        return {"instruction": i + 1,
                label_a: a[i] if i < len(a) else "<end of program>",
                label_b: b[i] if i < len(b) else "<end of program>",
                f"{label_a}_total": len(a), f"{label_b}_total": len(b)}
    return None


def assert_identical_hlo(sim_a, sim_b, state=None, key=None,
                         n_rounds: int = 2, label: str = "") -> None:
    """Assert two simulators trace the SAME round program, naming the
    first divergent instruction on failure.  The shared backbone of every
    "feature off is HLO-neutral" test."""
    import jax
    key = key if key is not None else jax.random.PRNGKey(0)
    if state is None:
        state = sim_a.init_nodes(key)
    ta = lower_text(sim_a, state, key, n_rounds)
    tb = lower_text(sim_b, state, key, n_rounds)
    if canonicalize_hlo(ta) == canonicalize_hlo(tb):
        return
    div = first_divergence(ta, tb, "sim_a", "sim_b")
    raise AssertionError(
        f"HLO divergence{f' ({label})' if label else ''} at canonical "
        f"instruction {div['instruction']}:\n"
        f"  sim_a: {div['sim_a']}\n"
        f"  sim_b: {div['sim_b']}\n"
        f"  ({div['sim_a_total']} vs {div['sim_b_total']} instructions)")


def _iter_subjaxprs(params: dict):
    """Yield every sub-jaxpr reachable from an eqn's params (scan/cond/
    while bodies, pjit calls, custom-vjp closures...). Duck-typed — an
    object with ``.jaxpr`` is a ClosedJaxpr wrapper, one with ``.eqns`` a
    Jaxpr — so no version-specific jax.core imports."""
    for v in params.values():
        stack = [v]
        while stack:
            x = stack.pop()
            if hasattr(x, "jaxpr"):
                x = x.jaxpr
            if hasattr(x, "eqns"):
                yield x
            elif isinstance(x, (tuple, list)):
                stack.extend(x)


def _count_eqns(jaxpr, primitive_name: str) -> int:
    n = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == primitive_name:
            n += 1
        for sub in _iter_subjaxprs(eqn.params):
            n += _count_eqns(sub, primitive_name)
    return n


def pallas_launch_count(sim, state=None, key=None, n_rounds: int = 2) -> int:
    """STATIC pallas-kernel-launch count of the round program.

    Counts ``pallas_call`` eqns in the traced jaxpr of the same
    ``n_rounds`` scan :func:`lower_text` lowers — the scan body is traced
    once, so this is launches *per round program* regardless of
    ``n_rounds``, and both branches of a ``lax.cond`` count (they are both
    in the program). Works identically in interpret mode (the jaxpr
    predates lowering), which is what lets CI assert the single-launch
    fused-deliver property on CPU where the StableHLO carries no
    custom-call marker.
    """
    import jax
    key = key if key is not None else jax.random.PRNGKey(0)
    if state is None:
        state = sim.init_nodes(key)
    args = (state, key, sim.data)
    if sim.sentinels is not None:
        args = args + (sim._health_zero_carry(),)
    jaxpr = jax.make_jaxpr(sim._make_run(n_rounds, live=False))(*args)
    return _count_eqns(jaxpr.jaxpr, "pallas_call")


# ---------------------------------------------------------------------------
# The gate matrix (scripts/hlo_gate.py drives this)

_N, _D = 16, 6


def _make_data(seed=0, n_samples=320):
    import numpy as np
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_samples, _D)).astype(np.float32)
    y = (X @ rng.normal(size=_D) > 0).astype(np.int64)
    return X, y


def _make_sim(cls=None, *, all2all=False, sparse_mix_form=None, **kwargs):
    import optax

    from ..core import (AntiEntropyProtocol, CreateModelMode,
                        SparseTopology, Topology, uniform_mixing)
    from ..data import ClassificationDataHandler, DataDispatcher
    from ..handlers import SGDHandler, losses
    from ..models import LogisticRegression
    from ..simulation import All2AllGossipSimulator, GossipSimulator

    X, y = _make_data()
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=_N, eval_on_user=False)
    topo = Topology.random_regular(_N, 4, seed=3)
    handler = SGDHandler(model=LogisticRegression(_D, 2),
                         loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.1), local_epochs=1,
                         batch_size=8, n_classes=2, input_shape=(_D,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    if all2all:
        if sparse_mix_form is not None:
            topo = SparseTopology.random_regular(_N, 4, seed=3)
            kwargs["sparse_mix_form"] = sparse_mix_form
        mixing = uniform_mixing(topo)
        return All2AllGossipSimulator(handler, topo, disp.stacked(),
                                      delta=20, mixing=mixing, **kwargs)
    cls = cls or GossipSimulator
    return cls(handler, topo, disp.stacked(), delta=20,
               protocol=AntiEntropyProtocol.PUSH, **kwargs)


def _tmp_ledger():
    import tempfile

    from ..telemetry.ledger import RunLedger
    return RunLedger(os.path.join(tempfile.mkdtemp(prefix="hlo_ledger_"),
                                  "ledger.jsonl"))


def _small_chaos():
    from ..simulation import ChaosConfig, PartitionEpisode
    half = tuple(range(_N // 2)), tuple(range(_N // 2, _N))
    return ChaosConfig(partitions=(PartitionEpisode(
        components=half, start=1, stop=3),), horizon=4)


def gate_cases() -> dict:
    """The full gate matrix.

    Returns ``{"identity": [(name, build_default, build_off)],
    "fingerprint": [(name, build)]}`` — identity pairs must trace the
    byte-identical program; fingerprint cases hash against the golden
    manifest.  Builders are zero-arg callables so the driver controls
    construction cost and ordering.
    """
    identity = [
        ("engine/probes-off",
         lambda: _make_sim(), lambda: _make_sim(probes=None)),
        ("engine/sentinels-off",
         lambda: _make_sim(), lambda: _make_sim(sentinels=None)),
        ("engine/chaos-off",
         lambda: _make_sim(), lambda: _make_sim(chaos=None)),
        # Active-cohort mode off must be ABSENT: cohort=None builds the
        # byte-identical materialized round program (cohort ON is a
        # different world — host-driven [C] segments — so only the off
        # identity is meaningful here).
        ("engine/cohort-off",
         lambda: _make_sim(), lambda: _make_sim(cohort=None)),
        ("engine/perf-off",
         lambda: _make_sim(), lambda: _make_sim(perf=None)),
        # perf is host-side only, so even perf ON must be HLO-neutral —
        # stronger than the other layers' off-identity contract.
        ("engine/perf-on",
         lambda: _make_sim(), lambda: _make_sim(perf=True)),
        # metrics (telemetry.metrics) is host-side only, like perf: the
        # SLO registry feed must be HLO-invisible even when ON.
        ("engine/metrics-on",
         lambda: _make_sim(), lambda: _make_sim(metrics=True)),
        # span tracing (telemetry.tracing) is host-side only, like perf
        # and metrics: a live tracer must be HLO-invisible even when ON.
        ("engine/tracing-on",
         lambda: _make_sim(), lambda: _make_sim(tracing=True)),
        # run-ledger feed (telemetry.ledger) is host-side only, same
        # contract: an attached ledger (post-run digest appends) must be
        # HLO-invisible even when ON.
        ("engine/ledger-on",
         lambda: _make_sim(), lambda: _make_sim(ledger=_tmp_ledger())),
        # Fused-deliver off must be ABSENT: fused_merge=False builds the
        # byte-identical per-slot deliver loop (fused ON is fingerprinted
        # and launch-gated below).
        ("engine/fused-multi-off",
         lambda: _make_sim(), lambda: _make_sim(fused_merge=False)),
        ("all2all/sentinels-off",
         lambda: _make_sim(all2all=True),
         lambda: _make_sim(all2all=True, sentinels=None)),
    ]
    fingerprint = [
        ("engine/base", lambda: _make_sim()),
        ("engine/probes-on", lambda: _make_sim(probes=True)),
        ("engine/sentinels-on", lambda: _make_sim(sentinels=True)),
        ("engine/chaos-on", lambda: _make_sim(chaos=_small_chaos())),
        ("engine/history-bf16",
         lambda: _make_sim(history_dtype="bfloat16")),
        ("engine/history-int8", lambda: _make_sim(history_dtype="int8")),
        ("all2all/dense", lambda: _make_sim(all2all=True)),
        ("all2all/sparse-padded",
         lambda: _make_sim(all2all=True, sparse_mix_form="padded")),
        ("all2all/sparse-segment",
         lambda: _make_sim(all2all=True, sparse_mix_form="segment")),
        ("engine/fused-multi",
         lambda: _make_sim(fused_merge=True, mailbox_slots=4)),
        ("engine/fused-compact",
         lambda: _make_sim(fused_merge=True, compact_deliver=8,
                           mailbox_slots=4)),
    ]
    # Launch-count gate: the one-pass fused deliver drains all K mailbox
    # slots in EXACTLY one multi-slot kernel launch per deliver program
    # (two with compact co-enabled: the gathered-batch branch and the wide
    # fallback branch are both in the lax.cond). Unfused delivers with
    # gathers only — zero pallas launches. Counted on the traced jaxpr, so
    # it gates on CPU interpret mode too (see pallas_launch_count).
    launch = [
        ("engine/unfused", lambda: _make_sim(mailbox_slots=4), 0),
        ("engine/fused-multi",
         lambda: _make_sim(fused_merge=True, mailbox_slots=4), 1),
        ("engine/fused-compact",
         lambda: _make_sim(fused_merge=True, compact_deliver=8,
                           mailbox_slots=4), 2),
    ]
    return {"identity": identity, "fingerprint": fingerprint,
            "launch": launch}
