"""Pallas TPU kernels for the simulation hot paths."""

from .attention import flash_attention, flash_hop_update
from .merge import (gather_merge_flat, gather_merge_multi,
                    gather_merge_multi_pytree, gather_merge_pytree)

__all__ = ["flash_attention", "flash_hop_update", "gather_merge_flat",
           "gather_merge_multi", "gather_merge_multi_pytree",
           "gather_merge_pytree"]
