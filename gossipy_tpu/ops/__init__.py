"""Pallas TPU kernels for the simulation hot paths."""

from .merge import gather_merge_flat, gather_merge_pytree

__all__ = ["gather_merge_flat", "gather_merge_pytree"]
