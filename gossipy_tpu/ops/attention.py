"""Flash-attention pallas kernel for the ring-attention hop update.

:func:`gossipy_tpu.parallel.collectives.ring_attention` streams key/value
chunks around the ICI ring, maintaining per-query softmax statistics
``(running max m, normalizer l, weighted-value accumulator acc)``. Its hop
body composed from jnp primitives is two MXU matmuls (``q @ k_c.T`` and
``p @ v_c``) with the ``[sl, sl]`` score/probability block materialized
between them — XLA does not fuse across matmul boundaries, so for long
per-device chunks that block round-trips HBM every hop.

This kernel fuses one whole hop update: each ``block_q``-row program keeps
its score block in VMEM from QK^T through the streaming-softmax rescale to
the PV product and never writes it out. Same blockwise-softmax math as the
public flash-attention/ring-attention formulation; layout follows
pallas_guide.md (full-array trailing block dims; ``[rows, 1]`` carry
vectors so the last block dim equals the array dim; ``broadcasted_iota``
for position ids; f32 accumulation regardless of input dtype).

Differentiation: ``pallas_call`` has no automatic reverse-mode, so the hop
update carries a ``jax.custom_vjp`` whose backward re-derives the vjp from
an identical jnp formulation of the same math (flash-style recompute — the
score block is rebuilt from the saved inputs on the backward pass only).
Gradient parity with the jnp path is tested in interpreter mode.

Off-TPU the kernel runs in pallas interpreter mode (the CPU test mesh), and
installs without pallas entirely via the jnp reference path — mirroring
``ops/merge.py``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is TPU/GPU-oriented; import guarded so CPU-only installs work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# Finite stand-in for -inf: exp() stays nan-free (matches collectives.py).
_NEG = -1e30
# Default query rows per program. 128 rows x 128-lane tiles feed the MXU
# full systolic-array slices; chunks shorter than this run as one block.
BLOCK_Q = 128
# Default key rows per inner block. The kernel's VMEM footprint per program
# is O(block_q·block_k) scores + O(block_k·D) keys/values regardless of the
# chunk length, so long sequences never overflow VMEM.
BLOCK_K = 512


def hop_update_reference(q, k_c, v_c, m, l, acc, q_off, k_off, scale,
                         causal: bool):
    """The jnp hop update (identical math to collectives.ring_attention's
    inline body): returns the rescaled ``(m, l, acc)`` after absorbing one
    key/value chunk. Differentiable; the kernel's custom-vjp backward and
    the off-pallas install path both use it."""
    qf = q.astype(jnp.float32)
    s = (qf @ k_c.T.astype(jnp.float32)) * scale  # [sl_q, sl_k]
    if causal:
        q_pos = q_off + jnp.arange(q.shape[0])
        k_pos = k_off + jnp.arange(k_c.shape[0])
        s = jnp.where(k_pos[None, :] > q_pos[:, None], _NEG, s)
    m_new = jnp.maximum(m, s.max(axis=1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[:, None])
    acc = acc * alpha[:, None] + p @ v_c.astype(jnp.float32)
    l = l * alpha + p.sum(axis=1)
    return m_new, l, acc


def _hop_kernel(scale, causal, block_q, block_k, n_k, sl_k,
                offs_ref, q_ref, k_ref, v_ref, m_ref, l_ref, a_ref,
                om_ref, ol_ref, oa_ref,
                m_scr, l_scr, a_scr):
    """Grid (q_blocks, k_blocks), k fastest: the streaming-softmax carry
    lives in VMEM scratch across a q row's k steps — per-program VMEM is
    O(block_q·block_k), independent of the chunk length."""
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():  # load the incoming carry for this q block
        m_scr[:] = m_ref[:]
        l_scr[:] = l_ref[:]
        a_scr[:] = a_ref[:]

    q = q_ref[:].astype(jnp.float32)                        # [bq, D]
    k = k_ref[:].astype(jnp.float32)                        # [bk, D]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale
    # Padded key rows (chunk length not divisible by block_k) are always
    # masked; causal masking is by global position.
    k_pos = (j * block_k
             + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
    invalid = k_pos >= sl_k
    if causal:
        q_pos = (offs_ref[0] + i * block_q
                 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k),
                                            0))
        invalid = invalid | ((offs_ref[1] + k_pos) > q_pos)
    s = jnp.where(invalid, _NEG, s)
    m_in = m_scr[:][:, 0]                                   # [bq]
    l_in = l_scr[:][:, 0]
    m_new = jnp.maximum(m_in, s.max(axis=1))
    alpha = jnp.exp(m_in - m_new)
    p = jnp.exp(s - m_new[:, None])                         # stays in VMEM
    # A fully-masked block at m_in == _NEG degenerates to p == exp(0); the
    # zero-alpha rescale keeps it harmless only when some earlier block was
    # real — guard explicitly so the padded tail cannot poison the carry.
    p = jnp.where(invalid, 0.0, p)
    a_scr[:] = a_scr[:] * alpha[:, None] + p @ v_ref[:].astype(jnp.float32)
    m_scr[:] = m_new[:, None]
    l_scr[:] = (l_in * alpha + p.sum(axis=1))[:, None]

    @pl.when(j == n_k - 1)
    def _flush():
        om_ref[:] = m_scr[:]
        ol_ref[:] = l_scr[:]
        oa_ref[:] = a_scr[:]


@functools.partial(jax.jit,
                   static_argnames=("scale", "causal", "interpret",
                                    "block_q", "block_k"))
def _hop_update_pallas(q, k_c, v_c, m, l, acc, offs, scale, causal,
                       interpret, block_q, block_k):
    sl_q, dim = q.shape
    sl_k = k_c.shape[0]
    dv = v_c.shape[1]
    bq = min(block_q, sl_q)
    bk = min(block_k, sl_k)
    pad = (-sl_q) % bq
    if pad:  # pad query rows; padded rows are sliced off below
        q = jnp.pad(q, ((0, pad), (0, 0)))
        m = jnp.pad(m, (0, pad), constant_values=_NEG)
        l = jnp.pad(l, (0, pad))
        acc = jnp.pad(acc, ((0, pad), (0, 0)))
    slp = sl_q + pad
    pad_k = (-sl_k) % bk
    if pad_k:  # padded key rows are masked inside the kernel (k_pos bound)
        k_c = jnp.pad(k_c, ((0, pad_k), (0, 0)))
        v_c = jnp.pad(v_c, ((0, pad_k), (0, 0)))
    n_k = (sl_k + pad_k) // bk

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # (q_off, k_off) int32[2]
        grid=(slp // bq, n_k),
        in_specs=[
            pl.BlockSpec((bq, dim), lambda i, j, o: (i, 0)),       # q
            pl.BlockSpec((bk, dim), lambda i, j, o: (j, 0)),       # k block
            pl.BlockSpec((bk, dv), lambda i, j, o: (j, 0)),        # v block
            pl.BlockSpec((bq, 1), lambda i, j, o: (i, 0)),         # m
            pl.BlockSpec((bq, 1), lambda i, j, o: (i, 0)),         # l
            pl.BlockSpec((bq, dv), lambda i, j, o: (i, 0)),        # acc
        ],
        out_specs=[
            pl.BlockSpec((bq, 1), lambda i, j, o: (i, 0)),
            pl.BlockSpec((bq, 1), lambda i, j, o: (i, 0)),
            pl.BlockSpec((bq, dv), lambda i, j, o: (i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, dv), jnp.float32),
        ],
    )
    # Under shard_map's varying-axes checking the out avals must declare
    # which mesh axes they vary over: the union of the inputs' (outside
    # shard_map the attribute is absent/empty and plain structs suffice).
    try:
        vma = frozenset().union(*(jax.typeof(x).vma
                                  for x in (q, k_c, v_c, m, l, acc)))
    except (AttributeError, TypeError):
        vma = None

    def sds(shape):
        if vma:
            return jax.ShapeDtypeStruct(shape, jnp.float32, vma=vma)
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    n_q = slp // bq
    om, ol, oa = pl.pallas_call(
        functools.partial(_hop_kernel, scale, causal, bq, bk, n_k, sl_k),
        grid_spec=grid_spec,
        out_shape=[sds((slp, 1)), sds((slp, 1)), sds((slp, dv))],
        # Scheduler hint (pallas_guide.md §13): 2·bq·bk·(dim + dv) MXU
        # flops per program, exp dominates the transcendentals.
        cost_estimate=pl.CostEstimate(
            flops=2 * n_q * n_k * bq * bk * (dim + dv),
            bytes_accessed=(slp * (dim + dv + 2) + n_q * n_k * bk
                            * (dim + dv)) * 4,
            transcendentals=n_q * n_k * bq * (bk + 1)),
        interpret=interpret,
    )(offs.astype(jnp.int32), q, k_c, v_c,
      m.astype(jnp.float32)[:, None], l.astype(jnp.float32)[:, None],
      acc.astype(jnp.float32))
    return om[:sl_q, 0], ol[:sl_q, 0], oa[:sl_q]


def _hop_bwd_math(scale, causal, res, g):
    """Hand-derived vjp of the hop update (flash-style: the score block is
    recomputed from the saved inputs, never stored). A nested ``jax.vjp``
    of the jnp formulation would compute the same thing but does not trace
    through eager ``shard_map``, and jitting interpreter-mode pallas under
    grad explodes compile time — so the math is written out.

    With s = scale·qk^T (masked to ``_NEG``), M = max(m_in, rowmax(s)),
    A = exp(m_in − M), P = exp(s − M):
        acc_out = A·acc_in + P v,   l_out = A·l_in + rowsum(P),  m_out = M.
    """
    q, k_c, v_c, m_in, l_in, acc_in, offs = res
    gm, gl, gacc = [x.astype(jnp.float32) for x in g]
    qf = q.astype(jnp.float32)
    kf = k_c.astype(jnp.float32)
    vf = v_c.astype(jnp.float32)

    s = (qf @ kf.T) * scale
    if causal:
        q_pos = offs[0] + jnp.arange(q.shape[0])
        k_pos = offs[1] + jnp.arange(k_c.shape[0])
        masked = k_pos[None, :] > q_pos[:, None]
        s = jnp.where(masked, _NEG, s)
    smax = s.max(axis=1)
    M = jnp.maximum(m_in, smax)
    A = jnp.exp(m_in - M)
    P = jnp.exp(s - M[:, None])

    dacc_in = gacc * A[:, None]
    dA = (gacc * acc_in).sum(axis=1) + gl * l_in
    dP = gacc @ vf.T + gl[:, None]
    dv = P.T @ gacc
    ds = dP * P                      # ∂P/∂s = P elementwise
    dM = gm - dA * A - ds.sum(axis=1)
    # Route the max: to m_in where it won, else to s's argmax entries
    # (ties split evenly, matching reduce_max's autodiff convention).
    sel = m_in >= smax
    dm_in = dA * A + jnp.where(sel, dM, 0.0)
    eq = (s == smax[:, None]).astype(jnp.float32)
    onehot = eq / jnp.maximum(eq.sum(axis=1, keepdims=True), 1.0)
    ds = ds + jnp.where(sel, 0.0, dM)[:, None] * onehot
    if causal:
        ds = jnp.where(masked, 0.0, ds)
    dq = (ds * scale) @ kf
    dk = (ds * scale).T @ qf
    dl_in = gl * A
    d_offs = np.zeros(offs.shape, jax.dtypes.float0)
    return (dq.astype(q.dtype), dk.astype(k_c.dtype), dv.astype(v_c.dtype),
            dm_in, dl_in, dacc_in, d_offs)


@functools.lru_cache(maxsize=None)
def _make_hop_update(scale: float, causal: bool, interpret: bool,
                     block_q: int, block_k: int):
    """Build the custom-vjp'd hop update for static (scale, causal, mode).

    Forward runs the pallas kernel; backward is :func:`_hop_bwd_math`.
    """
    @jax.custom_vjp
    def f(q, k_c, v_c, m, l, acc, offs):
        return _hop_update_pallas(q, k_c, v_c, m, l, acc, offs, scale,
                                  causal, interpret, block_q, block_k)

    def fwd(q, k_c, v_c, m, l, acc, offs):
        return f(q, k_c, v_c, m, l, acc, offs), (q, k_c, v_c, m, l, acc,
                                                 offs)

    def bwd(res, g):
        return _hop_bwd_math(scale, causal, res, g)

    f.defvjp(fwd, bwd)
    return f


def flash_hop_update(q, k_c, v_c, m, l, acc, q_off, k_off, scale,
                     causal: bool = False,
                     interpret: Optional[bool] = None,
                     block_q: int = BLOCK_Q, block_k: int = BLOCK_K):
    """One ring-attention hop as a fused pallas kernel.

    ``q`` [sl_q, D] resident query block; ``k_c``/``v_c`` [sl_k, D]/[sl_k,
    Dv] the chunk in flight; ``m``/``l``/[sl_q] and ``acc`` [sl_q, Dv] the
    f32 streaming-softmax carry; ``q_off``/``k_off`` the chunks' global
    row offsets (traced scalars — causal masking is by global position).
    Returns the updated ``(m, l, acc)``. ``interpret=None`` auto-selects
    interpreter mode off-TPU; without pallas installed, falls back to the
    jnp formulation.
    """
    if not _HAS_PALLAS:
        return hop_update_reference(q, k_c, v_c, m, l, acc, q_off, k_off,
                                    scale, causal)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    offs = jnp.stack([jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)])
    fn = _make_hop_update(float(scale), bool(causal), bool(interpret),
                          int(block_q), int(block_k))
    return fn(q, k_c, v_c, m, l, acc, offs)


def flash_attention(q, k, v, causal: bool = False,
                    interpret: Optional[bool] = None,
                    block_q: int = BLOCK_Q, block_k: int = BLOCK_K):
    """Single-device flash attention: softmax(q k^T / sqrt(D)) v with the
    score matrix blocked through VMEM (one hop over the full sequence).

    [S, D] inputs, one attention head; ``jax.vmap`` over heads/batch. The
    sequence-parallel form is ``collectives.ring_attention(flash=True)``,
    which runs this update once per ring hop.
    """
    s_len, dim = q.shape
    scale = 1.0 / np.sqrt(dim)
    m0 = jnp.full((s_len,), _NEG, jnp.float32)
    l0 = jnp.zeros((s_len,), jnp.float32)
    acc0 = jnp.zeros((s_len, v.shape[1]), jnp.float32)
    m, l, acc = flash_hop_update(q, k, v, m0, l0, acc0, 0, 0, scale,
                                 causal=causal, interpret=interpret,
                                 block_q=block_q, block_k=block_k)
    return (acc / jnp.maximum(l, 1e-30)[:, None]).astype(q.dtype)
