"""Fused peer-gather + weighted-merge pallas kernel.

The deliver phase of the gossip engine is HBM-bandwidth bound: for every
receiver ``i`` it reads the sender's snapshot row ``H[flat_idx[i]]`` from the
params-history ring and blends it with the receiver's own row,

    out[i] = w_self[i] * P[i] + w_peer[i] * H[flat_idx[i]]

(the pytree form of ``TorchModelHandler._merge``'s uniform average, reference
gossipy/model/handler.py:260-280, with the gather standing in for the
reference's ``CACHE.pop`` model fetch). Composed from jnp primitives this is
a gather (one full HBM round-trip to materialize the peer copy) followed by
an elementwise blend (a second read + write). The pallas kernel fuses them:
each (row, feature-block) program DMAs the sender block HBM->VMEM directly
(its row chosen by a scalar-prefetched index map) and writes the blended
block — the gathered peer copy is never materialized.

Layout notes (pallas_guide.md): feature blocks of 512 lanes (multiple of the
128-lane tile), scalar prefetch for the row indices and blend weights so the
DMA source of each grid step is known before the body runs. Rows are
processed one per grid step; to satisfy the TPU tiling rule (second-to-last
block dim must be 8-divisible OR equal the array dim) the operands carry a
unit middle axis — ``[rows, 1, features]`` with ``(1, 1, block_f)`` blocks.
Off-TPU the same kernel runs in interpreter mode (used by the CPU test
mesh).

Quantized history rings (``GossipSimulator(history_dtype=...)``) store ``h``
in a reduced-precision wire format — bf16 (plain cast) or int8 with a
symmetric per-row scale sidecar. The dequantizing kernel variant widens the
peer block to the receiver dtype INSIDE the kernel (and applies the
scalar-prefetched per-receiver scale for int8), so the fp32 peer copy is
never materialized in HBM: the gather moves 2-4x fewer bytes and the merge
math stays fp32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

BLOCK_F = 512


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


try:  # pallas is TPU/GPU-oriented; import guarded so CPU-only installs work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _kernel(idx_ref, w_self_ref, w_peer_ref, p_ref, h_ref, o_ref):
    i = pl.program_id(0)
    o_ref[:] = w_self_ref[i] * p_ref[:] + w_peer_ref[i] * h_ref[:]


def _dq_kernel(idx_ref, w_self_ref, w_peer_ref, scale_ref, p_ref, h_ref,
               o_ref):
    # Dequantizing variant: the history block arrives in its wire dtype
    # (bf16/int8) and is widened to the receiver dtype in VMEM; for int8
    # the per-receiver scale (already gathered host-of-kernel to [N]) is a
    # scalar-prefetch operand. scale == 1 for bf16.
    i = pl.program_id(0)
    peer = h_ref[:].astype(o_ref.dtype) * scale_ref[i]
    o_ref[:] = w_self_ref[i] * p_ref[:] + w_peer_ref[i] * peer


def gather_merge_reference(p: jax.Array, h: jax.Array, idx: jax.Array,
                           w_self: jax.Array, w_peer: jax.Array,
                           scale: Optional[jax.Array] = None) -> jax.Array:
    """jnp fallback: materializes the gather (what XLA does un-fused).

    ``scale`` is the optional [M] per-history-row dequantization scale
    (int8 wire format); bf16 rows dequantize by the plain cast.
    """
    peer = h[idx].astype(p.dtype)
    if scale is not None:
        peer = peer * scale[idx].astype(p.dtype)[:, None]
    return w_self[:, None] * p + w_peer[:, None] * peer


@functools.partial(jax.jit, static_argnames=("interpret", "block_f"))
def _gather_merge_pallas(p, h, idx, w_self, w_peer, scale, interpret: bool,
                         block_f: int):
    n, f = p.shape
    pad = (-f) % block_f
    if pad:
        p = jnp.pad(p, ((0, 0), (0, pad)))
        h = jnp.pad(h, ((0, 0), (0, pad)))
    fp = f + pad
    p3 = p.reshape(n, 1, fp)
    h3 = h.reshape(h.shape[0], 1, fp)
    dequant = (h.dtype != p.dtype) or (scale is not None)

    if dequant:
        # Per-RECEIVER scale: gathering scale[idx] outside the kernel keeps
        # the scalar-prefetch operand at [N] (one SMEM word per grid row)
        # instead of the whole [M] sidecar, and spares the kernel a second
        # indirection. Ones when the wire format needs only the cast (bf16).
        scale_g = (jnp.ones((n,), p.dtype) if scale is None
                   else scale[idx].astype(p.dtype))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(n, fp // block_f),
            in_specs=[
                pl.BlockSpec((1, 1, block_f),
                             lambda i, j, s, w1, w2, sc: (i, 0, j)),
                pl.BlockSpec((1, 1, block_f),
                             lambda i, j, s, w1, w2, sc: (s[i], 0, j)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_f),
                                   lambda i, j, s, w1, w2, sc: (i, 0, j)),
        )
        out = pl.pallas_call(
            _dq_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((n, 1, fp), p.dtype),
            interpret=interpret,
        )(idx.astype(jnp.int32), w_self.astype(p.dtype),
          w_peer.astype(p.dtype), scale_g, p3, h3)
        return out.reshape(n, fp)[:, :f] if pad else out.reshape(n, fp)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n, fp // block_f),
        in_specs=[
            pl.BlockSpec((1, 1, block_f), lambda i, j, s, w1, w2: (i, 0, j)),
            pl.BlockSpec((1, 1, block_f), lambda i, j, s, w1, w2: (s[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_f), lambda i, j, s, w1, w2: (i, 0, j)),
    )
    out = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, 1, fp), p.dtype),
        interpret=interpret,
    )(idx.astype(jnp.int32), w_self.astype(p.dtype), w_peer.astype(p.dtype),
      p3, h3)
    return out.reshape(n, fp)[:, :f] if pad else out.reshape(n, fp)


def gather_merge_flat(p: jax.Array, h: jax.Array, idx: jax.Array,
                      w_self: jax.Array, w_peer: jax.Array,
                      scale: Optional[jax.Array] = None,
                      interpret: Optional[bool] = None,
                      block_f: int = BLOCK_F) -> jax.Array:
    """``out[i] = w_self[i] * p[i] + w_peer[i] * dequant(h[idx[i]])``.

    ``p`` is [N, F]; ``h`` is [M, F] (e.g. the [D*N, F]-flattened history
    ring) in fp32 or a wire format (bf16/int8 — dequantized inside the
    kernel, the fp32 peer copy never touches HBM); ``idx`` int32 [N] in
    [0, M); weights are [N]; ``scale`` optional [M] per-row dequant scales
    (required semantics for int8 rings). ``interpret=None`` auto-selects
    interpreter mode off-TPU.
    """
    if not _HAS_PALLAS:
        return gather_merge_reference(p, h, idx, w_self, w_peer, scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _gather_merge_pallas(p, h, idx, w_self, w_peer, scale, interpret,
                                int(block_f))


def gather_merge_pytree(params, history, flat_idx: jax.Array,
                        w_self: jax.Array, w_peer: jax.Array,
                        scales=None, interpret: Optional[bool] = None):
    """Leafwise fused (dequantizing) gather-merge over a stacked params pytree.

    ``params`` leaves are [N, ...]; ``history`` leaves are [D, N, ...]
    (the engine's snapshot ring, fp32 or a wire format); ``flat_idx[i] =
    (send_round_i % D) * N + sender_i`` addresses the ring as a flat
    [D*N, F] table. ``scales`` is the optional matching pytree of [D, N]
    per-(round-slot, node, leaf) dequant scales (int8 rings).
    """
    def leaf(pl_, hl, sl=None):
        n = pl_.shape[0]
        f = int(np.prod(pl_.shape[1:])) if pl_.ndim > 1 else 1
        flat_scale = (None if sl is None
                      else sl.reshape(sl.shape[0] * sl.shape[1]))
        out = gather_merge_flat(pl_.reshape(n, f),
                                hl.reshape(hl.shape[0] * hl.shape[1], f),
                                flat_idx, w_self, w_peer, scale=flat_scale,
                                interpret=interpret)
        return out.reshape(pl_.shape)

    if scales is None:
        return jax.tree.map(leaf, params, history)
    return jax.tree.map(leaf, params, history, scales)
