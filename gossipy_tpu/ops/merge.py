"""Fused peer-gather + weighted-merge pallas kernel.

The deliver phase of the gossip engine is HBM-bandwidth bound: for every
receiver ``i`` it reads the sender's snapshot row ``H[flat_idx[i]]`` from the
params-history ring and blends it with the receiver's own row,

    out[i] = w_self[i] * P[i] + w_peer[i] * H[flat_idx[i]]

(the pytree form of ``TorchModelHandler._merge``'s uniform average, reference
gossipy/model/handler.py:260-280, with the gather standing in for the
reference's ``CACHE.pop`` model fetch). Composed from jnp primitives this is
a gather (one full HBM round-trip to materialize the peer copy) followed by
an elementwise blend (a second read + write). The pallas kernel fuses them:
each (row, feature-block) program DMAs the sender block HBM->VMEM directly
(its row chosen by a scalar-prefetched index map) and writes the blended
block — the gathered peer copy is never materialized.

Layout notes (pallas_guide.md): feature blocks of 512 lanes (multiple of the
128-lane tile), scalar prefetch for the row indices and blend weights so the
DMA source of each grid step is known before the body runs. Rows are
processed one per grid step; to satisfy the TPU tiling rule (second-to-last
block dim must be 8-divisible OR equal the array dim) the operands carry a
unit middle axis — ``[rows, 1, features]`` with ``(1, 1, block_f)`` blocks.
Off-TPU the same kernel runs in interpreter mode (used by the CPU test
mesh).

Quantized history rings (``GossipSimulator(history_dtype=...)``) store ``h``
in a reduced-precision wire format — bf16 (plain cast) or int8 with a
symmetric per-row scale sidecar. The dequantizing kernel variant widens the
peer block to the receiver dtype INSIDE the kernel (and applies the
scalar-prefetched per-receiver scale for int8), so the fp32 peer copy is
never materialized in HBM: the gather moves 2-4x fewer bytes and the merge
math stays fp32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

BLOCK_F = 512


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


try:  # pallas is TPU/GPU-oriented; import guarded so CPU-only installs work
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


def _kernel(idx_ref, w_self_ref, w_peer_ref, p_ref, h_ref, o_ref):
    i = pl.program_id(0)
    o_ref[:] = w_self_ref[i] * p_ref[:] + w_peer_ref[i] * h_ref[:]


def _dq_kernel(idx_ref, w_self_ref, w_peer_ref, scale_ref, p_ref, h_ref,
               o_ref):
    # Dequantizing variant: the history block arrives in its wire dtype
    # (bf16/int8) and is widened to the receiver dtype in VMEM; for int8
    # the per-receiver scale (already gathered host-of-kernel to [N]) is a
    # scalar-prefetch operand. scale == 1 for bf16.
    i = pl.program_id(0)
    peer = h_ref[:].astype(o_ref.dtype) * scale_ref[i]
    o_ref[:] = w_self_ref[i] * p_ref[:] + w_peer_ref[i] * peer


def _multi_kernel(idx_ref, ws_ref, wp_ref, p_ref, h_ref, o_ref):
    # Multi-slot variant: grid (rows, feature-blocks, K) with the SLOT axis
    # minor, so the output block (i, 0, j) is revisited across consecutive k
    # steps and accumulates in VMEM — one read of p and one write of out per
    # (row, block) no matter how many mailbox slots drain. Per-slot math is
    # the same two-way blend as _kernel applied left-to-right, so the result
    # is bit-identical to K iterated single-slot launches.
    i = pl.program_id(0)
    k = pl.program_id(2)
    w = wp_ref[i, k]
    # An empty slot carries weight 0 but its (clipped) index may point at an
    # arbitrary ring row; 0 * row must stay inert even for a non-finite row
    # (the iterated path discards such products via its per-slot select).
    contrib = jnp.where(w != 0, w * h_ref[:], 0.0)

    @pl.when(k == 0)
    def _init():
        o_ref[:] = ws_ref[i, 0] * p_ref[:] + contrib

    @pl.when(k > 0)
    def _accum():
        o_ref[:] = ws_ref[i, k] * o_ref[:] + contrib


def _multi_dq_kernel(lmap_ref, idx_ref, ws_ref, wp_ref, scale_ref, p_ref,
                     h_ref, o_ref):
    # Dequantizing multi-slot variant. The concatenated-pytree caller packs
    # several leaves (each with its OWN per-row int8 scale sidecar) into one
    # feature axis; ``lmap`` maps each feature block to its leaf so the
    # [N, K, L] scale table is indexed per (receiver, slot, leaf). The
    # single-array caller passes L=1 with an all-zero lmap.
    i = pl.program_id(0)
    j = pl.program_id(1)
    k = pl.program_id(2)
    w = wp_ref[i, k]
    peer = h_ref[:].astype(o_ref.dtype) * scale_ref[i, k, lmap_ref[j]]
    contrib = jnp.where(w != 0, w * peer, 0.0)

    @pl.when(k == 0)
    def _init():
        o_ref[:] = ws_ref[i, 0] * p_ref[:] + contrib

    @pl.when(k > 0)
    def _accum():
        o_ref[:] = ws_ref[i, k] * o_ref[:] + contrib


def gather_merge_reference(p: jax.Array, h: jax.Array, idx: jax.Array,
                           w_self: jax.Array, w_peer: jax.Array,
                           scale: Optional[jax.Array] = None) -> jax.Array:
    """jnp fallback: materializes the gather (what XLA does un-fused).

    ``scale`` is the optional [M] per-history-row dequantization scale
    (int8 wire format); bf16 rows dequantize by the plain cast.
    """
    peer = h[idx].astype(p.dtype)
    if scale is not None:
        peer = peer * scale[idx].astype(p.dtype)[:, None]
    return w_self[:, None] * p + w_peer[:, None] * peer


@functools.partial(jax.jit, static_argnames=("interpret", "block_f"))
def _gather_merge_pallas(p, h, idx, w_self, w_peer, scale, interpret: bool,
                         block_f: int):
    n, f = p.shape
    pad = (-f) % block_f
    if pad:
        p = jnp.pad(p, ((0, 0), (0, pad)))
        h = jnp.pad(h, ((0, 0), (0, pad)))
    fp = f + pad
    p3 = p.reshape(n, 1, fp)
    h3 = h.reshape(h.shape[0], 1, fp)
    dequant = (h.dtype != p.dtype) or (scale is not None)

    if dequant:
        # Per-RECEIVER scale: gathering scale[idx] outside the kernel keeps
        # the scalar-prefetch operand at [N] (one SMEM word per grid row)
        # instead of the whole [M] sidecar, and spares the kernel a second
        # indirection. Ones when the wire format needs only the cast (bf16).
        scale_g = (jnp.ones((n,), p.dtype) if scale is None
                   else scale[idx].astype(p.dtype))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(n, fp // block_f),
            in_specs=[
                pl.BlockSpec((1, 1, block_f),
                             lambda i, j, s, w1, w2, sc: (i, 0, j)),
                pl.BlockSpec((1, 1, block_f),
                             lambda i, j, s, w1, w2, sc: (s[i], 0, j)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_f),
                                   lambda i, j, s, w1, w2, sc: (i, 0, j)),
        )
        out = pl.pallas_call(
            _dq_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((n, 1, fp), p.dtype),
            interpret=interpret,
        )(idx.astype(jnp.int32), w_self.astype(p.dtype),
          w_peer.astype(p.dtype), scale_g, p3, h3)
        return out.reshape(n, fp)[:, :f] if pad else out.reshape(n, fp)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n, fp // block_f),
        in_specs=[
            pl.BlockSpec((1, 1, block_f), lambda i, j, s, w1, w2: (i, 0, j)),
            pl.BlockSpec((1, 1, block_f), lambda i, j, s, w1, w2: (s[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_f), lambda i, j, s, w1, w2: (i, 0, j)),
    )
    out = pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, 1, fp), p.dtype),
        interpret=interpret,
    )(idx.astype(jnp.int32), w_self.astype(p.dtype), w_peer.astype(p.dtype),
      p3, h3)
    return out.reshape(n, fp)[:, :f] if pad else out.reshape(n, fp)


def gather_merge_flat(p: jax.Array, h: jax.Array, idx: jax.Array,
                      w_self: jax.Array, w_peer: jax.Array,
                      scale: Optional[jax.Array] = None,
                      interpret: Optional[bool] = None,
                      block_f: int = BLOCK_F) -> jax.Array:
    """``out[i] = w_self[i] * p[i] + w_peer[i] * dequant(h[idx[i]])``.

    ``p`` is [N, F]; ``h`` is [M, F] (e.g. the [D*N, F]-flattened history
    ring) in fp32 or a wire format (bf16/int8 — dequantized inside the
    kernel, the fp32 peer copy never touches HBM); ``idx`` int32 [N] in
    [0, M); weights are [N]; ``scale`` optional [M] per-row dequant scales
    (required semantics for int8 rings). ``interpret=None`` auto-selects
    interpreter mode off-TPU.
    """
    if not _HAS_PALLAS:
        return gather_merge_reference(p, h, idx, w_self, w_peer, scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _gather_merge_pallas(p, h, idx, w_self, w_peer, scale, interpret,
                                int(block_f))


def gather_merge_pytree(params, history, flat_idx: jax.Array,
                        w_self: jax.Array, w_peer: jax.Array,
                        scales=None, interpret: Optional[bool] = None):
    """Leafwise fused (dequantizing) gather-merge over a stacked params pytree.

    ``params`` leaves are [N, ...]; ``history`` leaves are [D, N, ...]
    (the engine's snapshot ring, fp32 or a wire format); ``flat_idx[i] =
    (send_round_i % D) * N + sender_i`` addresses the ring as a flat
    [D*N, F] table. ``scales`` is the optional matching pytree of [D, N]
    per-(round-slot, node, leaf) dequant scales (int8 rings).
    """
    def leaf(pl_, hl, sl=None):
        n = pl_.shape[0]
        f = int(np.prod(pl_.shape[1:])) if pl_.ndim > 1 else 1
        flat_scale = (None if sl is None
                      else sl.reshape(sl.shape[0] * sl.shape[1]))
        out = gather_merge_flat(pl_.reshape(n, f),
                                hl.reshape(hl.shape[0] * hl.shape[1], f),
                                flat_idx, w_self, w_peer, scale=flat_scale,
                                interpret=interpret)
        return out.reshape(pl_.shape)

    if scales is None:
        return jax.tree.map(leaf, params, history)
    return jax.tree.map(leaf, params, history, scales)


# ---------------------------------------------------------------------------
# Multi-slot form: drain K mailbox slots in ONE kernel launch.

def gather_merge_multi_reference(p: jax.Array, h: jax.Array, idx: jax.Array,
                                 w_self: jax.Array, w_peer: jax.Array,
                                 scale: Optional[jax.Array] = None
                                 ) -> jax.Array:
    """jnp fallback for the multi-slot kernel: the left-to-right fold of K
    two-way blends (``idx``/``w_self``/``w_peer`` are [N, K]).

    Zero-weight slots are hard-masked (``where``) rather than multiplied,
    so a garbage row behind an empty slot's clipped index stays inert even
    when it is non-finite — matching the kernel, and the per-slot engine
    path's select-based discard.
    """
    out = p
    for k in range(idx.shape[1]):
        peer = h[idx[:, k]].astype(p.dtype)
        if scale is not None:
            peer = peer * scale[idx[:, k]].astype(p.dtype)[:, None]
        wp = w_peer[:, k].astype(p.dtype)[:, None]
        contrib = jnp.where(wp != 0, wp * peer, jnp.zeros_like(peer))
        out = w_self[:, k].astype(p.dtype)[:, None] * out + contrib
    return out


@functools.partial(jax.jit, static_argnames=("interpret", "block_f"))
def _gather_merge_multi_pallas(p, h, idx, w_self, w_peer, scale_g, lmap,
                               interpret: bool, block_f: int):
    """One multi-slot launch. ``scale_g`` is ``None`` (no dequant) or the
    pre-gathered ``[N, K, L]`` per-(receiver, slot, leaf) scale table with
    ``lmap`` the ``[F/block_f]`` block->leaf map (``None`` = single leaf;
    requires the feature axis pre-padded to a block multiple when given)."""
    n, f = p.shape
    pad = (-f) % block_f
    assert lmap is None or pad == 0, \
        "segmented scale tables require block-aligned features"
    if pad:
        p = jnp.pad(p, ((0, 0), (0, pad)))
        h = jnp.pad(h, ((0, 0), (0, pad)))
    fp = f + pad
    p3 = p.reshape(n, 1, fp)
    h3 = h.reshape(h.shape[0], 1, fp)

    if scale_g is not None:
        if lmap is None:
            lmap = jnp.zeros((fp // block_f,), jnp.int32)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(n, fp // block_f, idx.shape[1]),
            in_specs=[
                pl.BlockSpec((1, 1, block_f),
                             lambda i, j, k, lm, s, w1, w2, sc: (i, 0, j)),
                pl.BlockSpec((1, 1, block_f),
                             lambda i, j, k, lm, s, w1, w2, sc:
                             (s[i, k], 0, j)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_f),
                                   lambda i, j, k, lm, s, w1, w2, sc:
                                   (i, 0, j)),
        )
        out = pl.pallas_call(
            _multi_dq_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((n, 1, fp), p.dtype),
            interpret=interpret,
        )(lmap.astype(jnp.int32), idx.astype(jnp.int32),
          w_self.astype(p.dtype), w_peer.astype(p.dtype),
          scale_g.astype(p.dtype), p3, h3)
        return out.reshape(n, fp)[:, :f] if pad else out.reshape(n, fp)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(n, fp // block_f, idx.shape[1]),
        in_specs=[
            pl.BlockSpec((1, 1, block_f),
                         lambda i, j, k, s, w1, w2: (i, 0, j)),
            pl.BlockSpec((1, 1, block_f),
                         lambda i, j, k, s, w1, w2: (s[i, k], 0, j)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_f),
                               lambda i, j, k, s, w1, w2: (i, 0, j)),
    )
    out = pl.pallas_call(
        _multi_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, 1, fp), p.dtype),
        interpret=interpret,
    )(idx.astype(jnp.int32), w_self.astype(p.dtype), w_peer.astype(p.dtype),
      p3, h3)
    return out.reshape(n, fp)[:, :f] if pad else out.reshape(n, fp)


def gather_merge_multi(p: jax.Array, h: jax.Array, idx: jax.Array,
                       w_self: jax.Array, w_peer: jax.Array,
                       scale: Optional[jax.Array] = None,
                       interpret: Optional[bool] = None,
                       block_f: int = BLOCK_F) -> jax.Array:
    """K-slot gather-merge in one launch: the left-to-right fold

        ``out = p``; for each slot ``k``:
        ``out = w_self[:, k] * out + w_peer[:, k] * dequant(h[idx[:, k]])``

    with ``idx``/``w_self``/``w_peer`` [N, K] tables (one column per
    mailbox slot; empty slots carry ``(w_self, w_peer) = (1, 0)`` and any
    in-range index). Where :func:`gather_merge_flat` costs K launches — K
    full reads of ``p`` and writes of ``out`` — to drain a K-slot mailbox,
    this reads ``p`` and writes ``out`` exactly once, accumulating the K
    peer blocks in VMEM. Per-slot math is bit-identical to the iterated
    single-slot kernel. ``scale``/``interpret`` as in
    :func:`gather_merge_flat`.
    """
    if idx.ndim != 2:
        raise ValueError(f"idx must be [N, K], got shape {idx.shape}")
    if not _HAS_PALLAS:
        return gather_merge_multi_reference(p, h, idx, w_self, w_peer, scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale_g = None
    if (h.dtype != p.dtype) or (scale is not None):
        scale_g = (jnp.ones((p.shape[0], idx.shape[1], 1), p.dtype)
                   if scale is None
                   else scale[idx].astype(p.dtype)[:, :, None])
    return _gather_merge_multi_pallas(p, h, idx, w_self, w_peer, scale_g,
                                      None, interpret, int(block_f))


def gather_merge_multi_pytree(params, history, flat_idx: jax.Array,
                              w_self: jax.Array, w_peer: jax.Array,
                              scales=None, interpret: Optional[bool] = None,
                              block_f: int = BLOCK_F):
    """ONE :func:`gather_merge_multi` launch over a whole stacked params
    pytree: all leaves flatten-concatenate into a single ``[N, sum(F)]``
    matrix (and the ring into ``[D*N, sum(F)]``) so a K-slot deliver for
    the full model is exactly one kernel launch — per-leaf launches would
    re-pay the launch and the scalar-prefetch table per leaf.

    Same layout contract as :func:`gather_merge_pytree`, with ``flat_idx``
    and the weights widened to [N, K] slot tables: ``flat_idx[i, k] =
    (send_round_ik % D) * N + sender_ik``. With int8 ``scales`` each leaf
    keeps its own per-row sidecar: leaves are padded to feature-block
    multiples so every block belongs to one leaf, and the kernel picks the
    leaf's scale through a block->leaf map (see ``_multi_dq_kernel``).
    """
    leaves, treedef = jax.tree_util.tree_flatten(params)
    hleaves = jax.tree_util.tree_leaves(history)
    n = leaves[0].shape[0]
    # Ring rows come from the HISTORY shape: under compact deliver the
    # params rows are a gathered [cap] subset while the ring stays [D, N].
    m = hleaves[0].shape[0] * hleaves[0].shape[1]
    if not _HAS_PALLAS:
        return gather_merge_multi_reference_pytree(
            params, history, flat_idx, w_self, w_peer, scales)
    widths = [int(np.prod(l.shape[1:])) if l.ndim > 1 else 1 for l in leaves]

    if scales is None:
        # Shared (or absent) wire transform across the whole row: plain
        # concat, the kernel's fp pad covers block alignment.
        p_cat = jnp.concatenate(
            [l.reshape(n, f) for l, f in zip(leaves, widths)], axis=1)
        h_cat = jnp.concatenate(
            [hl.reshape(m, f) for hl, f in zip(hleaves, widths)], axis=1)
        out = gather_merge_multi(p_cat, h_cat, flat_idx, w_self, w_peer,
                                 interpret=interpret, block_f=block_f)
        splits = jnp.split(out, np.cumsum(widths)[:-1], axis=1)
        return jax.tree_util.tree_unflatten(
            treedef, [s.reshape(l.shape) for s, l in zip(splits, leaves)])

    sleaves = jax.tree_util.tree_leaves(scales)
    padded = [_cdiv(f, block_f) * block_f for f in widths]
    p_cat = jnp.concatenate(
        [jnp.pad(l.reshape(n, f), ((0, 0), (0, w - f)))
         for l, f, w in zip(leaves, widths, padded)], axis=1)
    h_cat = jnp.concatenate(
        [jnp.pad(hl.reshape(m, f), ((0, 0), (0, w - f)))
         for hl, f, w in zip(hleaves, widths, padded)], axis=1)
    scale_g = jnp.stack([sl.reshape(m)[flat_idx] for sl in sleaves],
                        axis=-1).astype(p_cat.dtype)  # [N, K, L]
    lmap = jnp.asarray(
        np.repeat(np.arange(len(leaves)), [w // block_f for w in padded]),
        jnp.int32)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out = _gather_merge_multi_pallas(p_cat, h_cat, flat_idx, w_self, w_peer,
                                     scale_g, lmap, interpret, int(block_f))
    splits = jnp.split(out, np.cumsum(padded)[:-1], axis=1)
    return jax.tree_util.tree_unflatten(
        treedef, [s[:, :f].reshape(l.shape)
                  for s, f, l in zip(splits, widths, leaves)])


def gather_merge_multi_reference_pytree(params, history, flat_idx: jax.Array,
                                        w_self: jax.Array, w_peer: jax.Array,
                                        scales=None):
    """:func:`gather_merge_multi_reference` over a stacked params pytree —
    the pure-jnp twin of :func:`gather_merge_multi_pytree` (probe-side
    recomputation must not add kernel launches to the round program)."""
    def leaf(pl_, hl, sl=None):
        n = pl_.shape[0]
        f = int(np.prod(pl_.shape[1:])) if pl_.ndim > 1 else 1
        flat_scale = (None if sl is None
                      else sl.reshape(sl.shape[0] * sl.shape[1]))
        out = gather_merge_multi_reference(
            pl_.reshape(n, f), hl.reshape(hl.shape[0] * hl.shape[1], f),
            flat_idx, w_self, w_peer, scale=flat_scale)
        return out.reshape(pl_.shape)

    if scales is None:
        return jax.tree.map(leaf, params, history)
    return jax.tree.map(leaf, params, history, scales)
