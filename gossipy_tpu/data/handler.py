"""Data handlers: typed train/eval containers with seeded splits.

Re-design of ``gossipy/data/handler.py``. Handlers stay host-side numpy (they
run once at setup); the device-side view is produced by the dispatcher's
``stacked()`` (padded per-node shards + masks). API parity:

- :class:`ClassificationDataHandler` — seeded train/eval split
  (reference handler.py:25-134)
- :class:`ClusteringDataHandler` — eval set == train set (handler.py:138-164)
- :class:`RegressionDataHandler` — float labels (handler.py:168-178; its
  ``at`` forgetting the return statement is fixed here)
- :class:`RecSysDataHandler` — per-user rating lists with positional
  train/test split (handler.py:181-245)
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class DataHandler:
    """Abstract base (reference data/__init__.py:55-161)."""

    def size(self, dim: int = 0) -> int:
        raise NotImplementedError

    def get_train_set(self):
        raise NotImplementedError

    def get_eval_set(self):
        raise NotImplementedError

    def eval_size(self) -> int:
        raise NotImplementedError


class ClassificationDataHandler(DataHandler):
    """Classification data with a seeded train/eval split.

    Mirrors reference handler.py:25-134: ``test_size`` fraction split via a
    seeded permutation; ``at(idx, eval_set)`` returns (X[idx], y[idx]).
    """

    def __init__(self,
                 X: np.ndarray,
                 y: np.ndarray,
                 X_te: Optional[np.ndarray] = None,
                 y_te: Optional[np.ndarray] = None,
                 test_size: float = 0.2,
                 seed: int = 42):
        assert 0 <= test_size < 1, "test_size must be in [0, 1)"
        X = np.asarray(X)
        y = np.asarray(y)
        if X_te is not None:
            assert y_te is not None, "y_te must be provided along with X_te"
            self.Xtr, self.ytr = X, y
            self.Xte, self.yte = np.asarray(X_te), np.asarray(y_te)
        elif test_size > 0:
            rng = np.random.default_rng(seed)
            perm = rng.permutation(X.shape[0])
            n_te = int(X.shape[0] * test_size)
            te, tr = perm[:n_te], perm[n_te:]
            self.Xtr, self.ytr = X[tr], y[tr]
            self.Xte, self.yte = X[te], y[te]
        else:
            self.Xtr, self.ytr = X, y
            self.Xte, self.yte = None, None
        self.n_classes = int(len(np.unique(y)))

    def __getitem__(self, idx):
        return self.at(idx)

    def at(self, idx, eval_set: bool = False):
        if eval_set:
            if self.Xte is None or (hasattr(idx, "__len__") and len(idx) == 0):
                return None  # reference handler.py:104-107
            return self.Xte[idx], self.yte[idx]
        return self.Xtr[idx], self.ytr[idx]

    def size(self, dim: int = 0) -> int:
        return self.Xtr.shape[dim]

    def get_train_set(self):
        return self.Xtr, self.ytr

    def get_eval_set(self):
        return (self.Xte, self.yte) if self.Xte is not None else None

    def eval_size(self) -> int:
        return 0 if self.Xte is None else self.Xte.shape[0]


class ClusteringDataHandler(ClassificationDataHandler):
    """Unsupervised: the evaluation set IS the training set (handler.py:138-164)."""

    def __init__(self, X: np.ndarray, y: np.ndarray):
        super().__init__(X, y, test_size=0)
        self.Xte, self.yte = self.Xtr, self.ytr

    def get_eval_set(self):
        return self.Xtr, self.ytr

    def eval_size(self) -> int:
        return self.size()


class RegressionDataHandler(ClassificationDataHandler):
    """Float labels; ``at`` fixed to actually return (cf. handler.py:175-178)."""

    def at(self, idx, eval_set: bool = False):
        out = super().at(idx, eval_set)
        if out is None:
            return None
        X, y = out
        return X, y.astype(np.float32)


class RecSysDataHandler(DataHandler):
    """Per-user rating lists, positional train/test split (handler.py:181-245).

    ``ratings`` maps user id -> list of (item_id, rating). Each user's list is
    permuted with a seeded RNG and split at ``1 - test_size``.
    """

    def __init__(self, ratings: dict[int, list[tuple[int, float]]],
                 n_users: int, n_items: int,
                 test_size: float = 0.2, seed: int = 42):
        self.n_users = n_users
        self.n_items = n_items
        rng = np.random.default_rng(seed)
        self.ratings = {}
        self._test_offset = {}
        for u in range(n_users):
            r = list(ratings.get(u, []))
            perm = rng.permutation(len(r))
            r = [r[i] for i in perm]
            self.ratings[u] = r
            self._test_offset[u] = max(int(round(len(r) * (1 - test_size))), 0)

    def __getitem__(self, u: int):
        return self.ratings[u][: self._test_offset[u]]

    def at(self, u: int, eval_set: bool = False):
        if eval_set:
            return self.ratings[u][self._test_offset[u]:]
        return self.ratings[u][: self._test_offset[u]]

    def size(self, dim: int = 0) -> int:
        return self.n_users

    def get_train_set(self):
        return self.ratings

    def get_eval_set(self):
        return None

    def eval_size(self) -> int:
        return 0
