"""Data layer: loaders, non-IID assignment, and the shard dispatcher.

Re-design of ``gossipy/data/__init__.py`` (778 LoC). Assignment and loading
stay host-side numpy (they run once at setup, reference SURVEY §7 stage 8);
what changes is the *output*: :meth:`DataDispatcher.stacked` pads every
node's shard to one static length and returns stacked device arrays
``(X [N, S, ...], y [N, S], mask [N, S])`` so the whole network's local
training is a single vmapped program. ``mask`` flags real rows (padding
contributes nothing to losses/metrics).

Non-IID partitioners mirror ``AssignmentHandler``
(reference data/__init__.py:164-373) algorithm-for-algorithm.

Dataset loaders: sklearn built-ins work offline; UCI/torchvision/MovieLens
downloads are attempted and fall back to deterministic synthetic datasets of
the same shape when the environment has no egress (the fallback is flagged
in the returned metadata and by a warning).
"""

from __future__ import annotations

import os
import warnings
from typing import Callable, Optional

import numpy as np

from .. import LOG  # package logger (DuplicateFilter wiring lives there)
from .handler import (
    ClassificationDataHandler,
    ClusteringDataHandler,
    DataHandler,
    RecSysDataHandler,
    RegressionDataHandler,
)

__all__ = [
    "AssignmentHandler", "DataDispatcher", "RecSysDataDispatcher",
    "ClassificationDataHandler", "ClusteringDataHandler",
    "RegressionDataHandler", "RecSysDataHandler", "DataHandler",
    "load_classification_dataset", "load_recsys_dataset",
    "get_CIFAR10", "get_FashionMNIST", "get_FEMNIST",
    "SYNTHETIC_DATA_VERSION",
]

# UCI datasets the reference downloads (data/__init__.py:45-52): name ->
# (n_samples, n_features, n_classes) used for the synthetic fallback shapes.
UCI_SHAPES = {
    "spambase": (4601, 57, 2),
    "sonar": (208, 60, 2),
    "ionosphere": (351, 34, 2),
    "abalone": (4177, 8, 3),
    "banknote": (1372, 4, 2),
    # Joachims' svmlight example corpus (what the reference calls "reuters"):
    # 2000 train + 600 test rows, train side 9947 features.
    "reuters": (2600, 9947, 2),
}

# (url, label_column) per downloadable UCI name — mirrors the reference's
# UCI_URL_AND_CLASS (data/__init__.py:45-52), including its abalone quirk:
# column 0 (sex M/F/I) is the LABEL, the 8 measurements are features.
UCI_URLS = {
    "spambase": ("https://archive.ics.uci.edu/ml/machine-learning-databases/"
                 "spambase/spambase.data", 57),
    "sonar": ("https://archive.ics.uci.edu/ml/machine-learning-databases/"
              "undocumented/connectionist-bench/sonar/sonar.all-data", 60),
    "ionosphere": ("https://archive.ics.uci.edu/ml/machine-learning-databases/"
                   "ionosphere/ionosphere.data", 34),
    "abalone": ("https://archive.ics.uci.edu/ml/machine-learning-databases/"
                "abalone/abalone.data", 0),
    "banknote": ("https://archive.ics.uci.edu/ml/machine-learning-databases/"
                 "00267/data_banknote_authentication.txt", 4),
}


# ---------------------------------------------------------------------------
# Non-IID assignment (reference data/__init__.py:164-373)
# ---------------------------------------------------------------------------

class AssignmentHandler:
    """Partitioners mapping labels -> per-client index arrays.

    Each method mirrors the same-named reference method; all randomness goes
    through one ``numpy.random.Generator`` seeded at construction (the
    reference seeds the global numpy/torch RNGs, data/__init__.py:165-167).
    """

    def __init__(self, seed: int = 42):
        self.rng = np.random.default_rng(seed)

    def uniform(self, y: np.ndarray, n: int) -> list[np.ndarray]:
        """Equal-size random shards (reference :170-189)."""
        ex_client = y.shape[0] // n
        idx = self.rng.permutation(y.shape[0])
        return [idx[ex_client * i: ex_client * (i + 1)] for i in range(n)]

    def quantity_skew(self, y: np.ndarray, n: int, min_quantity: int = 2,
                      alpha: float = 4.0) -> list[np.ndarray]:
        """Power-law shard sizes, ``min_quantity`` guaranteed (reference :191-228)."""
        assert min_quantity * n <= y.shape[0], \
            "# of instances must be > than min_quantity*n"
        assert min_quantity > 0, "min_quantity must be >= 1"
        s = (self.rng.power(alpha, y.shape[0] - min_quantity * n) * n).astype(int)
        m = np.repeat(np.arange(n), min_quantity)
        assignment = np.concatenate([s, m])
        self.rng.shuffle(assignment)
        return [np.where(assignment == i)[0] for i in range(n)]

    def classwise_quantity_skew(self, y: np.ndarray, n: int, min_quantity: int = 2,
                                alpha: float = 4.0) -> list[np.ndarray]:
        """Per-class power-law splits (reference :230-255)."""
        assert min_quantity * n <= y.shape[0], \
            "# of instances must be > than min_quantity*n"
        assert min_quantity > 0, "min_quantity must be >= 1"
        labels = np.unique(y)
        lens = [int((y == c).sum()) for c in labels]
        assert min(lens) >= n, "Under represented class!"
        res: list[list[int]] = [[] for _ in range(n)]
        for c, ln in zip(labels, lens):
            s = (self.rng.power(alpha, ln - n) * n).astype(int)
            ass = np.concatenate([s, np.arange(n)])
            self.rng.shuffle(ass)
            idc = np.where(y == c)[0]
            for i in range(n):
                res[i].extend(idc[np.where(ass == i)[0]])
        return [np.array(sorted(r), dtype=int) for r in res]

    def label_quantity_skew(self, y: np.ndarray, n: int,
                            class_per_client: int = 2) -> list[np.ndarray]:
        """k-classes-per-client split (reference :257-298, Li et al. 2021)."""
        labels = set(np.unique(y).tolist())
        assert 0 < class_per_client <= len(labels), \
            "class_per_client must be > 0 and <= #classes"
        assert class_per_client * n >= len(labels), \
            "class_per_client * n must be >= #classes"
        nlbl = [self.rng.choice(len(labels), class_per_client, replace=False)
                for _ in range(n)]
        covered = set().union(*[set(a.tolist()) for a in nlbl])
        while len(covered) < len(labels):
            for missing in labels - covered:
                nlbl[self.rng.integers(0, n)][self.rng.integers(0, class_per_client)] = missing
            covered = set().union(*[set(a.tolist()) for a in nlbl])
        class_map = {c: [u for u, lbl in enumerate(nlbl) if c in lbl] for c in labels}
        assignment = np.zeros(y.shape[0], dtype=int)
        for lbl, users in class_map.items():
            ids = np.where(y == lbl)[0]
            assignment[ids] = self.rng.choice(users, len(ids))
        return [np.where(assignment == i)[0] for i in range(n)]

    def label_dirichlet_skew(self, y: np.ndarray, n: int,
                             beta: float = 0.1) -> list[np.ndarray]:
        """Dirichlet(beta) class allocation (reference :300-335); each client
        gets at least one example of each class (the ``ids[:n]`` seeding)."""
        assert beta > 0, "beta must be > 0"
        labels = np.unique(y)
        assignment = np.zeros(y.shape[0], dtype=int)
        for c in labels:
            pk = self.rng.dirichlet([beta] * n)
            ids = np.where(y == c)[0]
            self.rng.shuffle(ids)
            assignment[ids[n:]] = self.rng.choice(n, size=max(len(ids) - n, 0), p=pk)
            assignment[ids[:n]] = np.arange(min(n, len(ids)))
        return [np.where(assignment == i)[0] for i in range(n)]

    def label_pathological_skew(self, y: np.ndarray, n: int,
                                shards_per_client: int = 2) -> list[np.ndarray]:
        """Sorted-shard split à la McMahan 2017 (reference :337-373)."""
        sorted_ids = np.argsort(y, kind="stable")
        n_shards = int(shards_per_client * n)
        shard_size = int(np.ceil(len(y) / n_shards))
        assignment = np.zeros(y.shape[0], dtype=int)
        perm = self.rng.permutation(n_shards)
        j = 0
        for i in range(n):
            for _ in range(shards_per_client):
                left = perm[j] * shard_size
                right = min((perm[j] + 1) * shard_size, len(y))
                assignment[sorted_ids[left:right]] = i
                j += 1
        return [np.where(assignment == i)[0] for i in range(n)]


# ---------------------------------------------------------------------------
# Dispatchers
# ---------------------------------------------------------------------------

class DataDispatcher:
    """Assigns data shards to nodes and emits stacked padded device arrays.

    API parity with reference data/__init__.py:376-510 (``__getitem__(idx) ->
    (train, test)``, ``get_eval_set``, ``has_test``, ``size``), plus the
    TPU-native :meth:`stacked` view used by the simulation engine.
    """

    def __init__(self, data_handler, n: int = 0, eval_on_user: bool = True,
                 auto_assign: bool = True,
                 assignment: Optional[Callable] = None,
                 **assignment_kwargs):
        assert data_handler.size() >= n, "Not enough data to dispatch"
        self.data_handler = data_handler
        self.n = n if n > 0 else data_handler.size()
        self.eval_on_user = eval_on_user
        self.tr_assignments: Optional[list[np.ndarray]] = None
        self.te_assignments: Optional[list[np.ndarray]] = None
        self._assignment_fn = assignment
        self._assignment_kwargs = assignment_kwargs
        if auto_assign:
            self.assign()

    def assign(self, seed: int = 42) -> None:
        """Split train (and optionally eval) indices across the n nodes
        (reference :435-451, default uniform)."""
        handler = AssignmentHandler(seed)
        fn = self._assignment_fn or AssignmentHandler.uniform
        _, ytr = self.data_handler.get_train_set()
        self.tr_assignments = fn(handler, np.asarray(ytr), self.n,
                                 **self._assignment_kwargs)
        if self.eval_on_user and self.data_handler.eval_size() > 0:
            ev = self.data_handler.get_eval_set()
            self.te_assignments = AssignmentHandler(seed).uniform(
                np.asarray(ev[1]), self.n)
        else:
            self.te_assignments = [np.array([], dtype=int) for _ in range(self.n)]

    def set_assignments(self, tr: list[np.ndarray],
                        te: Optional[list[np.ndarray]] = None) -> None:
        """Custom splits (reference :472-481, used by main_onoszko's
        contiguous dispatcher)."""
        assert len(tr) == self.n
        self.tr_assignments = [np.asarray(a, dtype=int) for a in tr]
        if te is not None:
            self.te_assignments = [np.asarray(a, dtype=int) for a in te]
        else:
            self.te_assignments = [np.array([], dtype=int) for _ in range(self.n)]

    def __getitem__(self, idx: int):
        """Node idx's (train, test) shards (reference :454-470)."""
        assert 0 <= idx < self.n, "Index %d out of range [0, %d)" % (idx, self.n)
        return (self.data_handler.at(self.tr_assignments[idx]),
                self.data_handler.at(self.te_assignments[idx], eval_set=True))

    def size(self) -> int:
        return self.n

    def get_eval_set(self):
        return self.data_handler.get_eval_set()

    def has_test(self) -> bool:
        return self.data_handler.eval_size() > 0

    # -- TPU-native stacked view -------------------------------------------

    @staticmethod
    def _pad_stack(arrs: list[np.ndarray], pad_to: Optional[int] = None):
        """Stack variable-length arrays into [N, S, ...] + mask [N, S]."""
        s_max = max((a.shape[0] for a in arrs), default=0)
        if pad_to is not None:
            s_max = max(s_max, pad_to)
        s_max = max(s_max, 1)
        n = len(arrs)
        out = np.zeros((n, s_max) + arrs[0].shape[1:], dtype=arrs[0].dtype)
        mask = np.zeros((n, s_max), dtype=np.float32)
        for i, a in enumerate(arrs):
            out[i, : a.shape[0]] = a
            mask[i, : a.shape[0]] = 1.0
        return out, mask

    def stacked(self, pad_to: Optional[int] = None) -> dict:
        """Stacked padded shards for the whole network.

        Returns a dict of numpy arrays (engine moves them to device):
        ``xtr [N,S,...], ytr [N,S], mtr [N,S]`` and, when eval data exists,
        ``xte/yte/mte`` (per-node) and ``x_eval/y_eval`` (the global eval
        set, shared by all nodes).
        """
        assert self.tr_assignments is not None, "call assign() first"
        Xtr, ytr = self.data_handler.get_train_set()
        Xtr, ytr = np.asarray(Xtr), np.asarray(ytr)
        xs = [Xtr[a] for a in self.tr_assignments]
        ys = [ytr[a] for a in self.tr_assignments]
        x_stack, mask = self._pad_stack(xs, pad_to)
        y_stack, _ = self._pad_stack(ys, x_stack.shape[1])
        out = {"xtr": x_stack, "ytr": y_stack, "mtr": mask}
        if self.has_test():
            Xte, yte = self.data_handler.get_eval_set()
            Xte, yte = np.asarray(Xte), np.asarray(yte)
            if self.eval_on_user:
                xs = [Xte[a] for a in self.te_assignments]
                ys = [yte[a] for a in self.te_assignments]
                x_stack, mask = self._pad_stack(xs)
                y_stack, _ = self._pad_stack(ys, x_stack.shape[1])
                out.update({"xte": x_stack, "yte": y_stack, "mte": mask})
            out.update({"x_eval": Xte, "y_eval": yte})
        return out

    def __str__(self) -> str:
        return (f"DataDispatcher(handler={self.data_handler.__class__.__name__}, "
                f"n={self.n}, eval_on_user={self.eval_on_user})")


class RecSysDataDispatcher(DataDispatcher):
    """One user-row per node, permuted (reference data/__init__.py:513-558)."""

    def __init__(self, data_handler: RecSysDataHandler):
        self.data_handler = data_handler
        self.n = data_handler.size()
        self.eval_on_user = True
        self.assign()

    def assign(self, seed: int = 42) -> None:
        rng = np.random.default_rng(seed)
        self.assignments = rng.permutation(self.n)

    def __getitem__(self, idx: int):
        u = int(self.assignments[idx])
        return (self.data_handler.at(u), self.data_handler.at(u, eval_set=True))

    def has_test(self) -> bool:
        return True

    def get_eval_set(self):
        return None

    def stacked(self, pad_to: Optional[int] = None) -> dict:
        """Per-node rating shards: ``items [N,S], ratings [N,S], mask [N,S]``
        for train and eval splits."""
        def pack(eval_set: bool):
            items, rates = [], []
            for i in range(self.n):
                r = self.data_handler.at(int(self.assignments[i]), eval_set=eval_set)
                items.append(np.array([it for it, _ in r], dtype=np.int32))
                rates.append(np.array([v for _, v in r], dtype=np.float32))
            it_stack, mask = self._pad_stack(items, pad_to)
            rt_stack, _ = self._pad_stack(rates, pad_to)
            return it_stack, rt_stack, mask

        itr, rtr, mtr = pack(False)
        ite, rte, mte = pack(True)
        return {"xtr": itr, "ytr": rtr, "mtr": mtr,
                "xte": ite, "yte": rte, "mte": mte}


# ---------------------------------------------------------------------------
# Dataset loaders (reference data/__init__.py:561-778)
# ---------------------------------------------------------------------------

# Version of the DETERMINISTIC SYNTHETIC data generators below
# (_synthetic_classification / _synthetic_images / the recsys fallback).
# Benchmarks in egress-less environments run on these stand-ins, so any
# change to their recipe shifts accuracy-regime comparability ACROSS
# bench rows while leaving throughput untouched — bench.py stamps this
# into every emitted row (``raw.data_version``) so mixed-generation rows
# can't be averaged silently. Bump on ANY change to the generated values:
#   1: original name-seeded Gaussian mixtures (unbounded separation)
#   2: Bayes-accuracy-calibrated center separation (round-4 verdict
#      weak-#5) + the c > 1 rescale guard
SYNTHETIC_DATA_VERSION = 2


def _name_seeded_rng(name: str) -> np.random.Generator:
    """RNG deterministically keyed on a dataset name (crc32, not ``hash`` —
    Python string hashing is salted per process)."""
    import zlib
    return np.random.default_rng(zlib.crc32(name.encode()))


def _synthetic_classification(name: str, n: int, d: int, c: int,
                              seed: Optional[int] = None,
                              bayes_accuracy: float = 0.90):
    """Deterministic synthetic stand-in for a non-downloadable dataset.

    A Gaussian-mixture classification problem keyed on the dataset name so
    shapes and difficulty are stable across runs. Class centers are rescaled
    so the CLOSEST pair sits at the separation whose two-class Bayes
    accuracy is ``bayes_accuracy`` (unit-variance isotropic Gaussians:
    ``acc = Phi(||mu_i - mu_j|| / 2)``) — without this, random centers in
    high dimension are ~``sqrt(2 d) * scale`` apart and any linear model
    hits 1.0 in a round or two, which hollows out convergence-time metrics
    (round-4 verdict weak-#5). With the default 0.90 ceiling a LogReg
    converges over tens of gossip rounds and final accuracy carries signal.
    """
    from statistics import NormalDist
    rng = _name_seeded_rng(name) if seed is None else np.random.default_rng(seed)
    centers = rng.normal(size=(c, d))
    # Min pairwise center distance governs the hardest class confusion; the
    # multiclass ceiling sits slightly above Phi(sep/2) because most pairs
    # land farther apart than the closest one.
    if c > 1:
        # Separation calibration needs a closest PAIR; with c == 1 the
        # diagonal-filled distance matrix is all-inf and the rescale would
        # silently zero the single center (sep / inf) — skip it, the
        # one-class problem has no Bayes-accuracy knob to calibrate.
        diffs = centers[:, None, :] - centers[None, :, :]
        dists = np.sqrt((diffs ** 2).sum(-1))
        np.fill_diagonal(dists, np.inf)
        sep = 2.0 * NormalDist().inv_cdf(bayes_accuracy)
        centers *= sep / dists.min()
    per = n // c
    Xs, ys = [], []
    for k in range(c):
        cnt = per + (1 if k < n % c else 0)
        Xs.append(rng.normal(loc=centers[k], scale=1.0, size=(cnt, d)))
        ys.append(np.full(cnt, k))
    X = np.concatenate(Xs).astype(np.float32)
    y = np.concatenate(ys).astype(np.int64)
    perm = rng.permutation(n)
    return X[perm], y[perm]


def load_classification_dataset(name: str = "spambase", normalize: bool = True,
                                allow_synthetic: bool = True):
    """Load a classification dataset as (X [n, d] float32, y [n] int64).

    Mirrors reference data/__init__.py:561-624: sklearn built-ins
    (iris/breast/digits/wine) load locally; the UCI names
    (spambase/sonar/ionosphere/abalone/banknote/reuters) are downloaded by
    the reference — in an egress-less environment we substitute a
    deterministic synthetic dataset with the same shape and warn. A ``name``
    that is an existing file path loads as svmlight format (the reference's
    else-branch, data/__init__.py:614-616).
    """
    raw_name = name  # un-lowered: file paths are case-sensitive
    name = name.lower()
    if name == "iris":
        from sklearn.datasets import load_iris
        X, y = load_iris(return_X_y=True)
    elif name in ("breast", "breast_cancer"):
        from sklearn.datasets import load_breast_cancer
        X, y = load_breast_cancer(return_X_y=True)
    elif name == "digits":
        from sklearn.datasets import load_digits
        X, y = load_digits(return_X_y=True)
    elif name == "wine":
        from sklearn.datasets import load_wine
        X, y = load_wine(return_X_y=True)
    elif name in UCI_SHAPES:
        X, y = _load_uci_or_synthetic(name, allow_synthetic)
    elif os.path.isfile(raw_name):
        # After the known names, like the reference's else-branch
        # (data/__init__.py:614-616): an existing file loads as svmlight
        # format. Checked last so a stray local file named like a dataset
        # cannot shadow a built-in loader.
        from sklearn.datasets import load_svmlight_file
        Xs, y = load_svmlight_file(raw_name)
        X = np.asarray(Xs.todense())
        y = _label_encode(np.asarray(y).tolist())
    else:
        raise ValueError(f"Unknown dataset: {name}")

    X = np.asarray(X, dtype=np.float32)
    y = np.asarray(y, dtype=np.int64)
    if normalize:
        from sklearn.preprocessing import StandardScaler
        X = StandardScaler().fit_transform(X).astype(np.float32)
    return X, y


def _fetch_to(url: str, path: str, timeout: float = 30.0) -> None:
    """Download ``url`` to ``path`` with a socket timeout (urlretrieve has
    none — a half-open connection would hang the loader forever)."""
    import shutil
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r, \
            open(path, "wb") as f:
        shutil.copyfileobj(r, f)


def data_cache_dir() -> str:
    """Persistent archive cache (override with ``GOSSIPY_TPU_DATA_DIR``).

    The reference re-downloads into ``./data`` per script
    (utils.py:98-149 + ``shutil.rmtree``); here every loader caches under
    one user-level directory and reuses the archive on subsequent calls.
    """
    d = os.environ.get("GOSSIPY_TPU_DATA_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "gossipy_tpu_data")
    os.makedirs(d, exist_ok=True)
    return d


def _fetch_cached(url: str, filename: str, timeout: float = 30.0) -> str:
    """Download ``url`` once into :func:`data_cache_dir`; reuse afterwards.

    Partial downloads cannot poison the cache: the fetch lands in a
    ``.part`` file and is renamed into place only on success.
    """
    import tempfile

    path = os.path.join(data_cache_dir(), filename)
    if os.path.isfile(path) and os.path.getsize(path) > 0:
        return path
    # Unique temp name per fetch: two concurrent processes must not
    # interleave writes into one .part file (os.replace is atomic, so the
    # last complete download wins).
    fd, part = tempfile.mkstemp(dir=data_cache_dir(),
                                suffix=".part", prefix=filename + ".")
    os.close(fd)
    try:
        _fetch_to(url, part, timeout)
        os.replace(part, path)
    finally:
        if os.path.exists(part):
            os.unlink(part)
    return path


def _label_encode(values) -> np.ndarray:
    """Sorted-unique label encoding (sklearn LabelEncoder semantics)."""
    classes = {v: i for i, v in enumerate(sorted(set(values)))}
    return np.array([classes[v] for v in values], dtype=np.int64)


def _load_reuters():
    """Joachims' svmlight example corpus (reference data/__init__.py:598-607):
    train.dat + test.dat stacked, the narrower side zero-padded to the wider
    feature count, labels {-1, +1} label-encoded to {0, 1}."""
    import tarfile
    import tempfile
    import urllib.request

    from sklearn.datasets import load_svmlight_file

    url = "http://download.joachims.org/svm_light/examples/example1.tar.gz"
    arc = _fetch_cached(url, "example1.tar.gz")
    with tempfile.TemporaryDirectory() as tmp:
        with tarfile.open(arc) as tf:
            tf.extractall(tmp, filter="data")  # refuse path traversal
        folder = os.path.join(tmp, "example1")
        X_tr, y_tr = load_svmlight_file(os.path.join(folder, "train.dat"))
        X_te, y_te = load_svmlight_file(os.path.join(folder, "test.dat"))
    X_tr, X_te = X_tr.toarray(), X_te.toarray()
    d = max(X_tr.shape[1], X_te.shape[1])
    X_tr = np.pad(X_tr, [(0, 0), (0, d - X_tr.shape[1])])
    X_te = np.pad(X_te, [(0, 0), (0, d - X_te.shape[1])])
    X = np.vstack([X_tr, X_te])
    y = _label_encode(np.concatenate([y_tr, y_te]).tolist())
    return X, y


def _load_uci_or_synthetic(name: str, allow_synthetic: bool):
    n, d, c = UCI_SHAPES[name]
    try:  # pragma: no cover - no egress in CI
        import urllib.request

        if name == "reuters":
            return _load_reuters()
        url, label_col = UCI_URLS[name]
        raw = urllib.request.urlopen(url, timeout=10).read().decode()
        rows = [r.split(",") for r in raw.strip().split("\n")]
        y = _label_encode([r[label_col].strip() for r in rows])
        X = np.array([[float(v) for i, v in enumerate(r) if i != label_col]
                      for r in rows], dtype=np.float32)
        return X, y
    except Exception:
        if not allow_synthetic:
            raise
        warnings.warn(
            f"Dataset '{name}' could not be downloaded (no egress?); using a "
            f"deterministic synthetic stand-in of shape ({n}, {d}).")
        return _synthetic_classification(name, n, d, c)


def _load_movielens(name: str):
    """Download + parse a MovieLens archive (reference data/__init__.py:628-681):
    ratings keyed by dense re-mapped user id, items dense re-mapped in first-
    appearance order."""
    import tempfile
    import urllib.request
    import zipfile

    files = {"ml-100k": ("u.data", "\t"), "ml-1m": ("ratings.dat", "::"),
             "ml-10m": ("ratings.dat", "::"), "ml-20m": ("ratings.csv", ",")}
    filename, sep = files[name]
    url = f"https://files.grouplens.org/datasets/movielens/{name}.zip"
    ratings: dict[int, list[tuple[int, float]]] = {}
    umap: dict[int, int] = {}
    imap: dict[int, int] = {}
    arc = _fetch_cached(url, f"{name}.zip")
    with zipfile.ZipFile(arc) as zf:
        member = next(m for m in zf.namelist()
                      if m.endswith("/" + filename) or m == filename)
        with zf.open(member) as f:
            for line in f.read().decode().strip().split("\n"):
                if name == "ml-20m" and line.startswith("userId"):
                    continue  # csv header
                u, i, r = line.strip().split(sep)[:3]
                u, i, r = int(u), int(i), float(r)
                if u not in umap:
                    umap[u] = len(umap)
                    ratings[umap[u]] = []
                if i not in imap:
                    imap[i] = len(imap)
                ratings[umap[u]].append((imap[i], r))
    return ratings, len(umap), len(imap)


def load_recsys_dataset(name: str = "ml-100k", allow_synthetic: bool = True):
    """MovieLens ratings as {user: [(item, rating)]}, n_users, n_items.

    Mirrors reference data/__init__.py:628-681 (zip download + dense id
    remapping); when the download is unavailable (no egress) and
    ``allow_synthetic``, a synthetic low-rank rating matrix with matching
    sparsity is generated instead.
    """
    sizes = {"ml-100k": (943, 1682, 100_000), "ml-1m": (6040, 3706, 1_000_000),
             "ml-10m": (69_878, 10_677, 10_000_054),
             "ml-20m": (138_493, 26_744, 20_000_263)}
    if name not in sizes:
        raise ValueError(f"Unknown recsys dataset: {name}")
    n_users, n_items, n_ratings = sizes[name]
    try:  # pragma: no cover - no egress in CI
        return _load_movielens(name)
    except Exception:
        if not allow_synthetic:
            raise
    warnings.warn(f"RecSys dataset '{name}' substituted with a synthetic "
                  "low-rank rating matrix (no egress).")
    rng = _name_seeded_rng(name)
    k = 6
    U = rng.normal(size=(n_users, k)) / np.sqrt(k)
    V = rng.normal(size=(n_items, k)) / np.sqrt(k)
    ratings: dict[int, list[tuple[int, float]]] = {}
    per_user = max(n_ratings // n_users, 5)
    for u in range(n_users):
        items = rng.choice(n_items, size=min(per_user, n_items), replace=False)
        raw = U[u] @ V[items].T
        r = np.clip(np.round(3.0 + 1.5 * raw), 1, 5)
        ratings[u] = [(int(i), float(v)) for i, v in zip(items, r)]
    return ratings, n_users, n_items


def _synthetic_images(name: str, n: int, shape: tuple, c: int):
    """Class-dependent Gaussian-blob images, deterministic per name."""
    rng = _name_seeded_rng(name)
    y = rng.integers(0, c, size=n).astype(np.int64)
    X = rng.normal(0.0, 1.0, size=(n,) + shape).astype(np.float32)
    h, w = shape[0], shape[1]
    yy, xx = np.mgrid[0:h, 0:w]
    for k in range(c):  # stamp a class-specific blob so the task is learnable
        cy, cx = (k * 7) % h, (k * 11) % w
        blob = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / 18.0)).astype(np.float32)
        X[y == k] += 2.5 * blob[..., None]
    return X, y


def _download_cifar10():
    """CIFAR-10 from the canonical plain-URL tar.gz (python pickle batches) —
    no torchvision needed. Returns NHWC float32 in [0, 1]."""
    import pickle
    import tarfile

    url = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
    arc = _fetch_cached(url, "cifar-10-python.tar.gz")

    def batch(tf, member):
        d = pickle.load(tf.extractfile(member), encoding="bytes")
        X = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return X.astype(np.float32) / 255.0, np.array(d[b"labels"],
                                                      dtype=np.int64)
    with tarfile.open(arc) as tf:
        members = {m.name: m for m in tf.getmembers()}
        tr = [batch(tf, members[f"cifar-10-batches-py/data_batch_{i}"])
              for i in range(1, 6)]
        Xte, yte = batch(tf, members["cifar-10-batches-py/test_batch"])
    Xtr = np.concatenate([x for x, _ in tr])
    ytr = np.concatenate([y for _, y in tr])
    return (Xtr, ytr), (Xte, yte)


def get_CIFAR10(allow_synthetic: bool = True):
    """CIFAR-10 train/test as NHWC float32.

    The reference uses torchvision downloads (data/__init__.py:684-726);
    here the canonical plain-URL archive is parsed directly (no torchvision
    dependency). Without egress and with ``allow_synthetic``, a
    deterministic synthetic 32x32x3 10-class set of the same shape is
    substituted.
    """
    try:  # pragma: no cover - no egress in CI
        return _download_cifar10()
    except Exception:
        if not allow_synthetic:
            raise
    warnings.warn("CIFAR-10 substituted with synthetic 32x32x3 data (no egress).")
    Xtr, ytr = _synthetic_images("cifar10-train", 50_000, (32, 32, 3), 10)
    Xte, yte = _synthetic_images("cifar10-test", 10_000, (32, 32, 3), 10)
    return (Xtr, ytr), (Xte, yte)


def _download_fashion_mnist():
    """FashionMNIST from the canonical idx-format files (no torchvision).
    Returns NHWC float32 in [0, 1]."""
    import gzip

    base = "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/"

    def fetch(fname):
        with open(_fetch_cached(base + fname, f"fashion-{fname}"), "rb") as f:
            return gzip.decompress(f.read())

    def images(buf):
        n = int.from_bytes(buf[4:8], "big")
        X = np.frombuffer(buf, dtype=np.uint8, offset=16).reshape(n, 28, 28, 1)
        return X.astype(np.float32) / 255.0

    def labels(buf):
        return np.frombuffer(buf, dtype=np.uint8, offset=8).astype(np.int64)

    Xtr = images(fetch("train-images-idx3-ubyte.gz"))
    ytr = labels(fetch("train-labels-idx1-ubyte.gz"))
    Xte = images(fetch("t10k-images-idx3-ubyte.gz"))
    yte = labels(fetch("t10k-labels-idx1-ubyte.gz"))
    return (Xtr, ytr), (Xte, yte)


def get_FashionMNIST(allow_synthetic: bool = True):
    """FashionMNIST equivalent of :func:`get_CIFAR10` (reference :729-762)."""
    try:  # pragma: no cover - no egress in CI
        return _download_fashion_mnist()
    except Exception:
        if not allow_synthetic:
            raise
    warnings.warn("FashionMNIST substituted with synthetic 28x28x1 data (no egress).")
    Xtr, ytr = _synthetic_images("fmnist-train", 60_000, (28, 28, 1), 10)
    Xte, yte = _synthetic_images("fmnist-test", 10_000, (28, 28, 1), 10)
    return (Xtr, ytr), (Xte, yte)


def _download_femnist(n_writers: int):
    """FEMNIST from the tao-shen torch archive the reference uses
    (data/__init__.py:765-778), with the cursor fix applied: writer ``i``
    gets rows ``[cursor_i, cursor_i + n_i)``, cursors advancing."""
    import tarfile
    import tempfile

    import torch

    url = ("https://raw.githubusercontent.com/tao-shen/FEMNIST_pytorch/"
           "master/femnist.tar.gz")

    def to_numpy(X, y, ids, limit):
        X = np.asarray(X, dtype=np.float32)
        if X.max() > 1.5:  # stored as uint8 grays
            X = X / 255.0
        if X.ndim == 3:
            X = X[..., None]  # NHWC single channel
        y = np.asarray(y, dtype=np.int64)
        assignment, cursor = [], 0
        for ni in list(ids)[:limit]:
            ni = int(ni)
            assignment.append(np.arange(cursor, cursor + ni))
            cursor += ni
        return X[:cursor], y[:cursor], assignment

    def load_pt(path):
        # weights_only=True: the archive comes from a third-party GitHub
        # repo — never let torch.load unpickle arbitrary objects from it.
        # Tensor-tuple payloads load fine under weights_only; if the
        # archive ever needs richer types, fail rather than deserialize.
        return torch.load(path, map_location="cpu", weights_only=True)

    arc = _fetch_cached(url, "femnist.tar.gz")
    with tempfile.TemporaryDirectory() as tmp:
        with tarfile.open(arc) as tf:
            tf.extractall(tmp, filter="data")  # refuse path traversal
        paths = [os.path.join(root, f)
                 for root, _, files in os.walk(tmp) for f in files
                 if f.endswith((".pt", ".pth"))]
        tr_path = next(p for p in paths if "train" in os.path.basename(p))
        te_path = next(p for p in paths if "test" in os.path.basename(p))
        Xtr, ytr, ids_tr = load_pt(tr_path)
        Xte, yte, ids_te = load_pt(te_path)
    return (to_numpy(Xtr, ytr, ids_tr, n_writers),
            to_numpy(Xte, yte, ids_te, n_writers))


def get_FEMNIST(n_writers: int = 100, allow_synthetic: bool = True):
    """Federated EMNIST: per-writer shards of 28x28 character images.

    Mirrors reference ``get_FEMNIST`` (data/__init__.py:765-778), which
    downloads a per-writer tar and returns ``(X, y, assignment)`` per split,
    where ``assignment[i]`` is writer ``i``'s index list. The reference's
    loop never advances its ``sum_tr``/``sum_te`` cursors so every writer is
    assigned the FIRST writer's rows (the ``sum_tr = sum_te = 0`` bug); here
    the cursors advance — an intentional, documented fix.

    Without egress and with ``allow_synthetic``, a deterministic synthetic
    per-writer dataset is substituted (62 classes as in EMNIST-byclass;
    writer shard sizes vary log-normally like real handwriting corpora).
    """
    try:  # pragma: no cover - no egress in CI
        return _download_femnist(n_writers)
    except Exception:
        if not allow_synthetic:
            raise
    warnings.warn("FEMNIST substituted with synthetic per-writer 28x28 data "
                  "(no egress).")
    rng = _name_seeded_rng("femnist")
    n_classes = 62
    sizes_tr = np.maximum((rng.lognormal(4.5, 0.4, n_writers)).astype(int), 8)
    sizes_te = np.maximum(sizes_tr // 5, 2)

    def build(sizes, tag):
        X, y = _synthetic_images(f"femnist-{tag}", int(sizes.sum()),
                                 (28, 28, 1), n_classes)
        assignment, cursor = [], 0
        for s in sizes:
            assignment.append(np.arange(cursor, cursor + int(s)))
            cursor += int(s)
        return X, y, assignment

    Xtr, ytr, tr_assignment = build(sizes_tr, "train")
    Xte, yte, te_assignment = build(sizes_te, "test")
    return (Xtr, ytr, tr_assignment), (Xte, yte, te_assignment)
