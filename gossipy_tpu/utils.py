"""Pure-JAX evaluation metrics and misc utilities.

The reference computes metrics with sklearn on host (accuracy / macro
precision / recall / F1 / binary ROC-AUC at gossipy/model/handler.py:282-334,
NMI at handler.py:632-636, RMSE at handler.py:570-573). Those run once per
node per round — on TPU we instead evaluate ALL nodes in one vmapped call, so
every metric here is a jit-safe pure function over (scores, labels, mask)
with static class counts. ``mask`` marks valid rows (1.0) vs padding (0.0),
because per-node shards are padded to a common static length.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def confusion_matrix(y_true: jax.Array, y_pred: jax.Array, n_classes: int,
                     mask: jax.Array | None = None) -> jax.Array:
    """Masked confusion matrix [n_classes, n_classes] via one-hot matmul (MXU-friendly)."""
    oh_t = jax.nn.one_hot(y_true, n_classes)
    oh_p = jax.nn.one_hot(y_pred, n_classes)
    if mask is not None:
        oh_t = oh_t * mask[:, None]
    return oh_t.T @ oh_p


def _safe_div(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.where(b > 0, a / jnp.where(b > 0, b, 1.0), 0.0)


def accuracy(y_true, y_pred, mask=None):
    ok = (y_true == y_pred).astype(jnp.float32)
    if mask is None:
        return ok.mean()
    return _safe_div((ok * mask).sum(), mask.sum())


def macro_prf1(y_true: jax.Array, y_pred: jax.Array, n_classes: int,
               mask: jax.Array | None = None) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Macro-averaged precision/recall/F1 with sklearn ``zero_division=0`` semantics.

    Matches ``precision_score(..., average="macro", zero_division=0)`` as used
    at reference handler.py:320-322: classes with zero predicted (resp. true)
    support contribute 0 to macro precision (resp. recall); macro averages run
    over ALL n_classes classes.
    """
    cm = confusion_matrix(y_true, y_pred, n_classes, mask)
    tp = jnp.diag(cm)
    pred_tot = cm.sum(axis=0)
    true_tot = cm.sum(axis=1)
    prec = _safe_div(tp, pred_tot)
    rec = _safe_div(tp, true_tot)
    f1 = _safe_div(2 * prec * rec, prec + rec)
    return prec.mean(), rec.mean(), f1.mean()


def binary_auc(scores: jax.Array, y_true: jax.Array,
               mask: jax.Array | None = None) -> jax.Array:
    """ROC-AUC for binary labels via the rank (Mann-Whitney U) formula with midranks.

    Equivalent to sklearn's ``roc_auc_score`` (reference handler.py:325-331)
    including tie handling. ``y_true`` in {0,1}. Sort-free-of-host: O(E log E).
    Returns 0.5 if either class is absent (degenerate case).
    """
    scores = scores.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones_like(scores)
    mask = mask.astype(jnp.float32)
    pos = (y_true > 0).astype(jnp.float32) * mask
    neg = (y_true <= 0).astype(jnp.float32) * mask
    # Midranks via ONE sort carrying the positive-indicator as a payload
    # operand, then associative scans over the sorted array. The rank-sum of
    # positives is order-independent, so no argsort index materialization,
    # no gather, and no inverse scatter are needed — those two [N*E] ops
    # were the single hottest fusions of the whole round program on TPU.
    # Masked entries are pushed to +inf: valid entries' ranks in the full
    # array then equal their ranks among valid entries alone.
    e = scores.shape[0]
    s = jnp.where(mask > 0, scores, jnp.inf)
    s_sorted, pos_sorted = jax.lax.sort((s, pos), num_keys=1)
    idx = jnp.arange(e, dtype=jnp.float32)
    new_grp = jnp.concatenate([jnp.ones(1, bool), s_sorted[1:] != s_sorted[:-1]])
    grp_first = jax.lax.associative_scan(jnp.maximum, jnp.where(new_grp, idx, 0.0))
    end_grp = jnp.concatenate([s_sorted[1:] != s_sorted[:-1], jnp.ones(1, bool)])
    grp_last = jax.lax.associative_scan(
        jnp.minimum, jnp.where(end_grp, idx, float(e) - 1.0), reverse=True)
    midrank_sorted = (grp_first + grp_last) / 2.0 + 1.0  # 1-based average rank
    n_pos = pos.sum()
    n_neg = neg.sum()
    rank_sum_pos = (midrank_sorted * pos_sorted).sum()
    u = rank_sum_pos - n_pos * (n_pos + 1.0) / 2.0
    auc = _safe_div(u, n_pos * n_neg)
    return jnp.where((n_pos > 0) & (n_neg > 0), auc, 0.5)


def classification_metrics(scores: jax.Array, y_true: jax.Array, n_classes: int,
                           mask: jax.Array | None = None) -> dict[str, jax.Array]:
    """The reference's classification metric dict (handler.py:318-331), pure-JAX.

    ``scores`` is [E, C] model outputs; prediction is argmax. When C == 2 the
    dict includes "auc" computed from scores[:, 1].
    """
    y_pred = jnp.argmax(scores, axis=-1)
    if y_true.ndim > 1:  # one-hot labels (reference handler.py:310-313)
        y_true = jnp.argmax(y_true, axis=-1)
    prec, rec, f1 = macro_prf1(y_true, y_pred, n_classes, mask)
    res = {
        "accuracy": accuracy(y_true, y_pred, mask),
        "precision": prec,
        "recall": rec,
        "f1_score": f1,
    }
    if scores.shape[-1] == 2:
        res["auc"] = binary_auc(scores[:, 1], y_true, mask)
    return res


def signed_binary_metrics(scores: jax.Array, y_true: jax.Array,
                          mask: jax.Array | None = None) -> dict[str, jax.Array]:
    """Metrics for ±1-labelled linear models (AdaLine/Pegasos).

    Mirrors ``AdaLineHandler.evaluate`` (reference handler.py:375-391):
    prediction = sign(score) mapped to {-1, +1}; macro P/R/F1 over the two
    classes; AUC from raw scores.
    """
    y01 = (y_true > 0).astype(jnp.int32)
    pred01 = (scores >= 0).astype(jnp.int32)
    prec, rec, f1 = macro_prf1(y01, pred01, 2, mask)
    return {
        "accuracy": accuracy(y01, pred01, mask),
        "precision": prec,
        "recall": rec,
        "f1_score": f1,
        "auc": binary_auc(scores, y01, mask),
    }


def nmi(y_true: jax.Array, y_pred: jax.Array, n_true: int, n_pred: int,
        mask: jax.Array | None = None) -> jax.Array:
    """Normalized mutual information (arithmetic normalization).

    Pure-JAX equivalent of sklearn's ``normalized_mutual_info_score`` used by
    the k-means handler (reference handler.py:632-636).
    """
    cm = confusion_matrix(y_true, y_pred, max(n_true, n_pred), mask)
    n = cm.sum()
    pij = _safe_div(cm, n)
    pi = pij.sum(axis=1, keepdims=True)
    pj = pij.sum(axis=0, keepdims=True)
    outer = pi * pj
    mi = jnp.where((pij > 0) & (outer > 0),
                   pij * jnp.log(_safe_div(pij, jnp.where(outer > 0, outer, 1.0))),
                   0.0).sum()
    h_i = -jnp.where(pi > 0, pi * jnp.log(jnp.where(pi > 0, pi, 1.0)), 0.0).sum()
    h_j = -jnp.where(pj > 0, pj * jnp.log(jnp.where(pj > 0, pj, 1.0)), 0.0).sum()
    denom = (h_i + h_j) / 2.0
    return jnp.where(denom > 0, mi / jnp.where(denom > 0, denom, 1.0), 0.0)


def rmse(pred: jax.Array, target: jax.Array, mask: jax.Array | None = None) -> jax.Array:
    """Masked RMSE (MF recommender metric, reference handler.py:570-573)."""
    err2 = (pred - target) ** 2
    if mask is None:
        return jnp.sqrt(err2.mean())
    return jnp.sqrt(_safe_div((err2 * mask).sum(), mask.sum()))


def choice_not_n(mn: int, mx: int, notn: int, key: jax.Array) -> jax.Array:
    """A uniform random int in [mn, mx] excluding ``notn`` (reference
    utils.py:41-64, which rejection-samples). Shift-based (draw from a range
    one smaller and step over the excluded value), so it is jit-safe with no
    data-dependent loop. The engine itself never needs this — peer sampling
    masks self via the adjacency diagonal — it is provided for users porting
    reference code."""
    if not mn <= notn <= mx:
        return jax.random.randint(key, (), mn, mx + 1)
    if mn >= mx:  # host-side check on static ints; survives python -O
        raise ValueError(
            f"no value in [{mn}, {mx}] left after excluding {notn}")
    v = jax.random.randint(key, (), mn, mx)  # [mn, mx-1]
    return jnp.where(v >= notn, v + 1, v)


def params_allclose(p1, p2, rtol: float = 1e-5, atol: float = 1e-7) -> bool:
    """Pytree parameter equality (replaces ``torch_models_eq``, reference utils.py:67-95)."""
    leaves1, tree1 = jax.tree_util.tree_flatten(p1)
    leaves2, tree2 = jax.tree_util.tree_flatten(p2)
    if tree1 != tree2:
        return False
    return all(bool(jnp.allclose(a, b, rtol=rtol, atol=atol))
               for a, b in zip(leaves1, leaves2))


def download_and_unzip(url: str, extract_to: str = ".") -> list[str]:
    """Download a zip archive and extract it (reference utils.py:98-122,
    without the SSL-verification bypass fallback). Returns extracted names."""
    import io
    import urllib.request
    import zipfile

    with urllib.request.urlopen(url, timeout=30) as r:
        data = r.read()
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        zf.extractall(extract_to)
        return zf.namelist()


def download_and_untar(url: str, extract_to: str = ".") -> list[str]:
    """Download a tar(.gz) archive and extract it (reference utils.py:125-149,
    without the SSL-verification bypass fallback). Returns extracted names."""
    import io
    import tarfile
    import urllib.request

    with urllib.request.urlopen(url, timeout=30) as r:
        data = r.read()
    with tarfile.open(fileobj=io.BytesIO(data)) as tf:
        try:
            # filter="data" rejects path traversal / absolute / link members.
            tf.extractall(extract_to, filter="data")
        except TypeError:
            # Pre-PEP-706 interpreters (< 3.10.12 / 3.11.4) have no safe
            # extraction filter; a hand-rolled name check cannot catch
            # symlink-relative escapes, so refuse rather than extract
            # unsafely.
            raise RuntimeError(
                "tar extraction needs a Python with the PEP 706 extraction "
                "filter (>= 3.10.12 / 3.11.4); refusing unfiltered extractall")
        return tf.getnames()


def plot_evaluation(evals: list[list[dict[str, float]]], title: str = "Untitled plot",
                    path: str | None = None):
    """Mean±std curves per metric (reference utils.py:152-183)."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    if not evals or not evals[0] or not evals[0][0]:
        return None
    fig = plt.figure()
    for k in evals[0][0]:
        series = np.array([[d[k] for d in rep] for rep in evals], dtype=float)
        mu, sd = series.mean(axis=0), series.std(axis=0)
        plt.fill_between(range(1, len(mu) + 1), mu - sd, mu + sd, alpha=0.2)
        plt.plot(range(1, len(mu) + 1), mu, label=k)
    plt.legend(loc="lower right")
    plt.title(title)
    plt.xlabel("round")
    if path:
        plt.savefig(path, bbox_inches="tight")
    return fig
