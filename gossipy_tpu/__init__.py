"""gossipy_tpu — a TPU-native gossip-learning / decentralized-FL framework.

A ground-up JAX/XLA re-design of the capabilities of makgyver/gossipy
(reference mounted at /root/reference). Instead of N Python node objects
exchanging deep-copied models through a global cache
(reference: gossipy/__init__.py:283-387, gossipy/simul.py:366-458), the whole
simulated network lives in ONE stacked pytree with a leading ``node`` axis,
sharded over a ``jax.sharding.Mesh``; a simulation round is a single jitted
program and peer-to-peer model exchange compiles to gathers/collectives over
TPU ICI.

Layout (mirrors the reference's layer map, see SURVEY.md §1; modules marked
[planned] land later in the build):

- :mod:`gossipy_tpu.core`        — enums, topologies, delay models, mixing matrices
- :mod:`gossipy_tpu.models`      — flax model definitions (MLP, LogReg, CNN, AdaLine, ...)
- :mod:`gossipy_tpu.handlers`    — pure-function train/merge/eval model handlers
- :mod:`gossipy_tpu.data`        — dataset loading, non-IID assignment, dispatching [planned]
- :mod:`gossipy_tpu.simulation`  — the round engine (vanilla / tokenized / all2all) [planned]
- :mod:`gossipy_tpu.flow_control`— token-account flow control (Danner 2018)
- :mod:`gossipy_tpu.parallel`    — mesh construction and node-axis sharding [planned]
- :mod:`gossipy_tpu.utils`       — pure-JAX metrics, plotting, misc
"""

from __future__ import annotations

import logging
import random as _py_random

import jax
import numpy as np

__version__ = "0.1.0"


class DuplicateFilter(logging.Filter):
    """Suppress repeated log records (reference gossipy/__init__.py:94-108).

    The reference wraps its rich logger with a filter that drops messages
    already seen; same behavior here on the stdlib logger (rich is not a
    dependency of this package)."""

    def __init__(self):
        super().__init__()
        self._seen: set[str] = set()

    def filter(self, record: logging.LogRecord) -> bool:
        msg = record.getMessage()
        if msg in self._seen:
            return False
        self._seen.add(msg)
        return True


LOG = logging.getLogger("gossipy_tpu")
LOG.addFilter(DuplicateFilter())


def set_seed(seed: int = 42) -> jax.Array:
    """Seed host-side RNGs and return a root JAX PRNG key.

    The reference seeds ``random``/``numpy``/``torch`` globally
    (gossipy/__init__.py:118-131). Here device-side randomness is purely
    functional (``jax.random``), so this seeds the host RNGs used by data
    assignment/topology generation and returns the root key from which the
    simulation derives all per-(round, purpose, node) keys via ``fold_in``.
    """
    _py_random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


# Persistent-compilation-cache bookkeeping: the active cache dir (None =
# not enabled through this package) and per-event counters harvested from
# jax.monitoring ('/jax/compilation_cache/cache_hits' etc.) — surfaced in
# the telemetry RunManifest so a run record says whether its cold compile
# was a disk load or a real XLA compile.
_COMPILATION_CACHE_DIR: str | None = None
_COMPILATION_CACHE_EVENTS: dict[str, int] = {}
_CACHE_LISTENER_REGISTERED = False


def _register_cache_listener() -> None:
    global _CACHE_LISTENER_REGISTERED
    if _CACHE_LISTENER_REGISTERED:
        return

    def listener(event: str, **kwargs) -> None:
        if "compilation_cache" in event:
            short = event.rsplit("/", 1)[-1]
            _COMPILATION_CACHE_EVENTS[short] = \
                _COMPILATION_CACHE_EVENTS.get(short, 0) + 1

    try:
        jax.monitoring.register_event_listener(listener)
        _CACHE_LISTENER_REGISTERED = True
    except Exception:  # monitoring API drift must not break imports
        pass


def compilation_cache_stats() -> dict:
    """Where the persistent compilation cache points and what it did so far
    this process: ``{"enabled": bool, "dir": path|None, "events":
    {"cache_hits": n, ...}}``. Recorded in every RunManifest."""
    return {"enabled": _COMPILATION_CACHE_DIR is not None,
            "dir": _COMPILATION_CACHE_DIR,
            "events": dict(_COMPILATION_CACHE_EVENTS)}


def enable_compilation_cache(path: str | None = None) -> str:
    """Enable JAX's persistent compilation cache.

    The round program for a CNN-sized config takes ~1-2 min to compile on a
    fresh process; with the cache, re-runs of the same config (benchmarks,
    resumed experiments, the example scripts) load the compiled binary in
    milliseconds. Defaults to ``~/.cache/gossipy_tpu_xla``.

    Also opt-in via the environment: setting ``GOSSIPY_TPU_COMPILATION_CACHE``
    enables the cache at package import — ``1``/``true`` selects the default
    directory, any other value is used as the cache path. Cache hits are
    counted (jax.monitoring) and stamped into the RunManifest via
    :func:`compilation_cache_stats`.
    """
    import os
    global _COMPILATION_CACHE_DIR
    path = path or os.path.join(os.path.expanduser("~"), ".cache",
                                "gossipy_tpu_xla")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _COMPILATION_CACHE_DIR = path
        _register_cache_listener()
    except OSError as e:  # read-only HOME etc. — the cache is best-effort
        LOG.warning("compilation cache disabled (%s unwritable: %s)", path, e)
    return path


def _maybe_enable_cache_from_env() -> None:
    import os
    val = os.environ.get("GOSSIPY_TPU_COMPILATION_CACHE", "").strip()
    if not val or val.lower() in ("0", "false", "no"):
        return
    enable_compilation_cache(
        None if val.lower() in ("1", "true", "yes") else val)


_maybe_enable_cache_from_env()


class GlobalSettings:
    """Minimal stand-in for the reference's device singleton.

    The reference's ``GlobalSettings`` (gossipy/__init__.py:46-91) holds the
    torch device. In JAX, placement is controlled by shardings/jit, so this
    class only records a preferred platform string for documentation and a
    default mesh (see :mod:`gossipy_tpu.parallel`).
    """

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._platform = None
        return cls._instance

    def set_device(self, platform: str | None = None) -> None:
        self._platform = platform

    def auto_device(self) -> str:
        """Pick the best available backend (reference ``auto_device``
        prefers CUDA over CPU, gossipy/__init__.py:57-66; here TPU > GPU >
        CPU, which is what jax's default backend already resolves to)."""
        self._platform = jax.default_backend()
        return self._platform

    def get_device(self) -> str:
        return self._platform or jax.default_backend()
