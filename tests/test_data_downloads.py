"""Offline proofs of the real-data download parsers.

The environment has no egress, so these tests serve tiny in-memory
fixtures in each loader's REAL wire format (UCI csv, svmlight tar.gz,
MovieLens zip, CIFAR pickle tar.gz, FashionMNIST idx gzip, FEMNIST torch
tar.gz) through a monkeypatched ``urllib.request.urlopen`` — proving the
parsing/label semantics that mirror reference data/__init__.py:561-778
without the network.
"""

import gzip
import io
import pickle
import tarfile
import zipfile

import numpy as np
import pytest

import gossipy_tpu.data as gdata


class FakeResponse(io.BytesIO):
    """urlopen stand-in: context manager + read(), like http.client."""


@pytest.fixture(autouse=True)
def isolated_cache(monkeypatch, tmp_path):
    """Every test gets a fresh archive cache: the persistent
    ~/.cache/gossipy_tpu_data dir would otherwise leak state between tests
    (and a cached archive would mask a loader's URL fetch entirely)."""
    monkeypatch.setenv("GOSSIPY_TPU_DATA_DIR", str(tmp_path / "data_cache"))


def serve(monkeypatch, table):
    """Patch urllib.request.urlopen to serve ``table[url] -> bytes``."""
    import urllib.request

    def fake_urlopen(url, timeout=None):
        if url not in table:
            raise AssertionError(f"unexpected URL fetched: {url}")
        return FakeResponse(table[url])

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)


class TestUCI:
    def test_abalone_label_column_zero(self, monkeypatch):
        """Reference quirk (UCI_URL_AND_CLASS): abalone's LABEL is column 0
        (sex M/F/I); the 8 measurements are the features."""
        rows = ["M,0.455,0.365,0.095,0.514,0.2245,0.101,0.15,15",
                "F,0.53,0.42,0.135,0.677,0.2565,0.1415,0.21,9",
                "I,0.44,0.365,0.125,0.516,0.2155,0.114,0.155,10",
                "M,0.35,0.265,0.09,0.2255,0.0995,0.0485,0.07,7"]
        url = gdata.UCI_URLS["abalone"][0]
        serve(monkeypatch, {url: "\n".join(rows).encode()})
        X, y = gdata.load_classification_dataset("abalone", normalize=False,
                                                 allow_synthetic=False)
        assert X.shape == (4, 8)
        # LabelEncoder semantics: sorted unique -> F=0, I=1, M=2.
        assert y.tolist() == [2, 0, 1, 2]
        assert X[0, 0] == pytest.approx(0.455)  # sex column removed
        assert X[0, 7] == pytest.approx(15.0)   # rings is a FEATURE here

    def test_spambase_label_column_last(self, monkeypatch):
        # spambase has 57 features; build 3 rows of 57 + label.
        rows = [",".join(["0.5"] * 57 + [lab]) for lab in ("1", "0", "1")]
        url = gdata.UCI_URLS["spambase"][0]
        serve(monkeypatch, {url: "\n".join(rows).encode()})
        X, y = gdata.load_classification_dataset("spambase", normalize=False,
                                                 allow_synthetic=False)
        assert X.shape == (3, 57)
        assert y.tolist() == [1, 0, 1]

    def test_sonar_string_labels(self, monkeypatch):
        rows = [",".join(["0.1"] * 60 + [lab]) for lab in ("R", "M", "R")]
        url = gdata.UCI_URLS["sonar"][0]
        serve(monkeypatch, {url: "\n".join(rows).encode()})
        X, y = gdata.load_classification_dataset("sonar", normalize=False,
                                                 allow_synthetic=False)
        assert X.shape == (3, 60)
        assert y.tolist() == [1, 0, 1]  # M=0, R=1 (sorted)


class TestReuters:
    def test_svmlight_stack_and_pad(self, monkeypatch):
        """train/test stacked; the narrower side zero-padded (the reference
        hardcodes the 17-column pad; we compute it)."""
        train = b"+1 1:0.5 4:0.25\n-1 2:1.0\n"
        test = b"-1 1:0.1 2:0.2\n"  # max feature 2 < train's 4 -> padded
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            for name, data in [("example1/train.dat", train),
                               ("example1/test.dat", test)]:
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        url = "http://download.joachims.org/svm_light/examples/example1.tar.gz"
        serve(monkeypatch, {url: buf.getvalue()})
        X, y = gdata.load_classification_dataset("reuters", normalize=False,
                                                 allow_synthetic=False)
        assert X.shape == (3, 4)
        assert y.tolist() == [1, 0, 0]  # {-1, +1} -> {0, 1}
        assert X[2, 0] == pytest.approx(0.1)
        assert (X[2, 2:] == 0).all()  # test rows zero-padded to train width


class TestMovieLens:
    def test_ml100k_zip_parse_and_remap(self, monkeypatch):
        udata = b"5\t10\t4.0\t881250949\n5\t20\t3.0\t881250950\n" \
                b"9\t10\t5.0\t881250951\n"
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w") as zf:
            zf.writestr("ml-100k/u.data", udata)
        url = "https://files.grouplens.org/datasets/movielens/ml-100k.zip"
        serve(monkeypatch, {url: buf.getvalue()})
        ratings, n_users, n_items = gdata.load_recsys_dataset(
            "ml-100k", allow_synthetic=False)
        # Dense remapping in first-appearance order (reference :628-681).
        assert (n_users, n_items) == (2, 2)
        assert ratings[0] == [(0, 4.0), (1, 3.0)]  # user 5 -> 0
        assert ratings[1] == [(0, 5.0)]            # user 9 -> 1, item 10 -> 0


class TestCIFAR10:
    def test_pickle_batches_parse(self, monkeypatch):
        def batch_bytes(n, seed):
            rng = np.random.default_rng(seed)
            return pickle.dumps({
                b"data": rng.integers(0, 256, (n, 3072), dtype=np.uint8),
                b"labels": rng.integers(0, 10, n).tolist()})

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            for i in range(1, 6):
                data = batch_bytes(2, i)
                info = tarfile.TarInfo(f"cifar-10-batches-py/data_batch_{i}")
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
            data = batch_bytes(3, 9)
            info = tarfile.TarInfo("cifar-10-batches-py/test_batch")
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
        url = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
        serve(monkeypatch, {url: buf.getvalue()})
        (Xtr, ytr), (Xte, yte) = gdata.get_CIFAR10(allow_synthetic=False)
        assert Xtr.shape == (10, 32, 32, 3) and Xte.shape == (3, 32, 32, 3)
        assert Xtr.dtype == np.float32 and 0.0 <= Xtr.min() <= Xtr.max() <= 1.0
        assert ytr.shape == (10,) and yte.dtype == np.int64


class TestFashionMNIST:
    def test_idx_parse(self, monkeypatch):
        def images_bytes(n, seed):
            rng = np.random.default_rng(seed)
            header = (2051).to_bytes(4, "big") + n.to_bytes(4, "big") \
                + (28).to_bytes(4, "big") + (28).to_bytes(4, "big")
            body = rng.integers(0, 256, n * 28 * 28, dtype=np.uint8).tobytes()
            return gzip.compress(header + body)

        def labels_bytes(n, seed):
            rng = np.random.default_rng(seed)
            header = (2049).to_bytes(4, "big") + n.to_bytes(4, "big")
            return gzip.compress(
                header + rng.integers(0, 10, n, dtype=np.uint8).tobytes())

        base = "http://fashion-mnist.s3-website.eu-central-1.amazonaws.com/"
        serve(monkeypatch, {
            base + "train-images-idx3-ubyte.gz": images_bytes(4, 0),
            base + "train-labels-idx1-ubyte.gz": labels_bytes(4, 1),
            base + "t10k-images-idx3-ubyte.gz": images_bytes(2, 2),
            base + "t10k-labels-idx1-ubyte.gz": labels_bytes(2, 3),
        })
        (Xtr, ytr), (Xte, yte) = gdata.get_FashionMNIST(allow_synthetic=False)
        assert Xtr.shape == (4, 28, 28, 1) and Xte.shape == (2, 28, 28, 1)
        assert 0.0 <= Xtr.min() <= Xtr.max() <= 1.0
        assert ytr.dtype == np.int64 and set(yte.tolist()) <= set(range(10))


class TestFEMNIST:
    def test_torch_archive_with_cursor_fix(self, monkeypatch):
        import torch

        def pt_bytes(n, ids, seed):
            rng = np.random.default_rng(seed)
            X = torch.tensor(rng.integers(0, 256, (n, 28, 28)),
                             dtype=torch.uint8)
            y = torch.tensor(rng.integers(0, 62, n))
            buf = io.BytesIO()
            torch.save((X, y, ids), buf)
            return buf.getvalue()

        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            for name, data in [("femnist_train.pt", pt_bytes(5, [2, 3], 0)),
                               ("femnist_test.pt", pt_bytes(3, [1, 2], 1))]:
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        url = ("https://raw.githubusercontent.com/tao-shen/FEMNIST_pytorch/"
               "master/femnist.tar.gz")
        serve(monkeypatch, {url: buf.getvalue()})
        (Xtr, ytr, a_tr), (Xte, yte, a_te) = gdata.get_FEMNIST(
            n_writers=2, allow_synthetic=False)
        assert Xtr.shape == (5, 28, 28, 1) and Xtr.dtype == np.float32
        # Cursor fix: writer shards are consecutive DISJOINT ranges
        # (the reference bug assigned every writer the first rows).
        assert a_tr[0].tolist() == [0, 1] and a_tr[1].tolist() == [2, 3, 4]
        assert a_te[0].tolist() == [0] and a_te[1].tolist() == [1, 2]


def test_offline_fallback_still_works(monkeypatch):
    """When the download fails, loaders warn and fall back — deterministic
    via an empty fixture table (any fetch raises), independent of whether
    the machine actually has egress."""
    serve(monkeypatch, {})
    with pytest.warns(UserWarning, match="synthetic"):
        X, y = gdata.load_classification_dataset("banknote")
    assert X.shape == (1372, 4)


class TestCacheAndPaths:
    def test_archives_cached_once(self, monkeypatch):
        """Round-3 (VERDICT next #9): a second load reuses the cached
        archive instead of re-downloading."""
        import urllib.request

        url = "http://download.joachims.org/svm_light/examples/example1.tar.gz"
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            for name, rows in [("example1/train.dat",
                                ["+1 1:0.5 3:1.0", "-1 2:0.25"]),
                               ("example1/test.dat", ["+1 1:1.0"])]:
                data = ("\n".join(rows) + "\n").encode()
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        calls = []
        real_table = {url: buf.getvalue()}

        def fake_urlopen(u, timeout=None):
            calls.append(u)
            return FakeResponse(real_table[u])

        monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
        X1, y1 = gdata.load_classification_dataset("reuters",
                                                   allow_synthetic=False)
        X2, y2 = gdata.load_classification_dataset("reuters",
                                                   allow_synthetic=False)
        assert len(calls) == 1  # second load served from the cache
        np.testing.assert_array_equal(y1, y2)

    def test_partial_download_not_cached(self, monkeypatch):
        """A fetch that dies MID-TRANSFER (after the file is open and some
        bytes are written) must not leave a poisoned cache entry: the next
        load must re-fetch, not serve a truncated archive."""
        import os
        import urllib.request

        class MidTransferDeath(io.BytesIO):
            def read(self, *a):
                raise OSError("connection reset mid-transfer")

        def dying_urlopen(u, timeout=None):
            return MidTransferDeath(b"partial")

        monkeypatch.setattr(urllib.request, "urlopen", dying_urlopen)
        with pytest.raises(OSError):
            gdata.load_classification_dataset("reuters",
                                              allow_synthetic=False)
        cache = os.environ["GOSSIPY_TPU_DATA_DIR"]
        leftovers = os.listdir(cache) if os.path.isdir(cache) else []
        # No completed archive may exist; stray .part files are tolerable
        # (unique-named), the final name is not.
        assert "example1.tar.gz" not in leftovers

    def test_svmlight_local_path(self, tmp_path):
        """A file path loads as svmlight format (the reference's
        else-branch, data/__init__.py:614-616) — no network involved."""
        from sklearn.datasets import dump_svmlight_file

        rng = np.random.default_rng(0)
        X = rng.normal(size=(20, 6))
        y = np.where(rng.random(20) > 0.5, 1, -1)
        p = tmp_path / "Local.SVM"  # mixed case: paths must not be lowered
        dump_svmlight_file(X, y, str(p))
        X2, y2 = gdata.load_classification_dataset(str(p), normalize=False)
        assert X2.shape == (20, 6) and X2.dtype == np.float32
        assert set(np.unique(y2)) == {0, 1}  # ±1 label-encoded
        np.testing.assert_allclose(X2, X.astype(np.float32), rtol=1e-5)
