"""Quantized params-history ring (GossipSimulator(history_dtype=...)).

The ring is the engine's dominant persistent state term and the deliver
phase's HBM traffic; ``history_dtype`` stores snapshots in a reduced wire
format (bf16 cast / int8 + symmetric per-(round-slot, node, leaf) scales)
and dequantizes on gather, so merge math stays fp32. Contracts pinned here:

- ``"float32"`` (the default) is bit-identical to the pre-feature engine
  (encode/decode are the identity — the golden/parity suites double as the
  regression net);
- bf16/int8 runs track the fp32 accuracy curve within a small band on the
  100-node bench-shaped config;
- the pallas dequantizing kernel (interpreter mode on CPU) agrees with the
  jnp reference for both wire formats;
- ``memory_budget()`` prices the ring at its wire itemsize and includes the
  int8 sidecar.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
    SparseTopology, Topology, UniformDelay, uniform_mixing
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, WeightedSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.ops import gather_merge_flat
from gossipy_tpu.ops.merge import gather_merge_reference
from gossipy_tpu.simulation import All2AllGossipSimulator, \
    CacheNeighGossipSimulator, GossipSimulator

DTYPES = ("float32", "bfloat16", "int8")


def make_dataset(n=480, d=12, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    return X, y


def make_sim(history_dtype, n_nodes=16, d=12, seed=0, sim_cls=GossipSimulator,
             handler_cls=SGDHandler, topology=None, **kw):
    X, y = make_dataset(n=30 * n_nodes, d=d, seed=seed)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes)
    handler = handler_cls(model=LogisticRegression(d, 2),
                          loss=losses.cross_entropy,
                          optimizer=optax.sgd(0.5), local_epochs=1,
                          batch_size=8, n_classes=2, input_shape=(d,),
                          create_model_mode=CreateModelMode.MERGE_UPDATE)
    if topology is None:
        topology = Topology.clique(n_nodes)
    return sim_cls(handler, topology, disp.stacked(), delta=10,
                   history_dtype=history_dtype, **kw)


def final_acc(sim, key, rounds=8):
    st = sim.init_nodes(key)
    st, rep = sim.start(st, n_rounds=rounds, key=key)
    return float(rep.curves(local=False)["accuracy"][-1]), st


class TestEncodeDecode:
    def test_int8_roundtrip_error_bound(self, key):
        sim = make_sim("int8")
        params = {"w": jax.random.normal(key, (16, 7, 3)) * 5.0,
                  "b": jax.random.normal(jax.random.fold_in(key, 1), (16, 3))}
        stored, scales = sim._encode_history_rows(params)
        assert stored["w"].dtype == jnp.int8
        assert scales["w"].shape == (16,)
        out = sim._decode_history_rows(stored, scales)
        for k in params:
            x = np.asarray(params[k])
            err = np.abs(np.asarray(out[k]) - x)
            # Symmetric grid: |err| <= scale/2 per row = amax/254.
            amax = np.abs(x).reshape(16, -1).max(axis=1)
            bound = amax / 254.0 + 1e-7
            assert (err.reshape(16, -1) <= bound[:, None] + 1e-6).all()

    def test_int8_zero_rows_are_safe(self):
        sim = make_sim("int8")
        params = {"w": jnp.zeros((4, 5))}
        stored, scales = sim._encode_history_rows(params)
        out = sim._decode_history_rows(stored, scales)
        assert np.isfinite(np.asarray(out["w"])).all()
        np.testing.assert_array_equal(np.asarray(out["w"]), 0.0)

    def test_int8_requantize_is_lossless(self, key):
        # CacheNeigh re-encodes already-dequantized payloads when parking;
        # the symmetric grid maps its own outputs back to themselves.
        sim = make_sim("int8")
        params = {"w": jax.random.normal(key, (8, 11))}
        stored1, scales1 = sim._encode_history_rows(params)
        once = sim._decode_history_rows(stored1, scales1)
        stored2, scales2 = sim._encode_history_rows(once)
        twice = sim._decode_history_rows(stored2, scales2)
        np.testing.assert_allclose(np.asarray(once["w"]),
                                   np.asarray(twice["w"]), atol=1e-6)

    def test_float32_is_identity(self, key):
        sim = make_sim("float32")
        params = {"w": jax.random.normal(key, (4, 3))}
        stored, scales = sim._encode_history_rows(params)
        assert stored["w"] is params["w"]
        assert scales == ()

    def test_unknown_dtype_rejected(self):
        with pytest.raises(ValueError, match="history_dtype"):
            make_sim("fp8")


class TestBitExactDefault:
    def test_explicit_float32_matches_default(self, key):
        """history_dtype='float32' must reproduce the default-constructed
        engine bit for bit (same PRNG streams, identity encode/decode)."""
        sim_a = make_sim("float32")
        X, y = make_dataset(n=30 * 16, d=12, seed=0)
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=16)
        handler = SGDHandler(model=LogisticRegression(12, 2),
                             loss=losses.cross_entropy,
                             optimizer=optax.sgd(0.5), local_epochs=1,
                             batch_size=8, n_classes=2, input_shape=(12,),
                             create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim_b = GossipSimulator(handler, Topology.clique(16), disp.stacked(),
                                delta=10)
        assert sim_b.history_dtype == "float32"
        _, sa = final_acc(sim_a, key)
        _, sb = final_acc(sim_b, key)
        for la, lb in zip(jax.tree_util.tree_leaves(sa.model.params),
                          jax.tree_util.tree_leaves(sb.model.params)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


class TestAccuracyParity:
    def test_100node_quantized_tracks_fp32(self, key):
        """bf16/int8 rings on the 100-node bench-shaped config (spambase
        dimensionality, 20-regular graph) stay within a small band of the
        fp32 accuracy curve — the acceptance contract's CPU-sized stand-in
        (bench.py --history-dtype measures the full config)."""
        accs = {}
        topo = Topology.random_regular(100, 20, seed=42)
        for hd in DTYPES:
            sim = make_sim(hd, n_nodes=100, d=57, seed=4, topology=topo)
            accs[hd], _ = final_acc(sim, key, rounds=10)
        assert accs["float32"] > 0.8, accs
        assert abs(accs["bfloat16"] - accs["float32"]) < 0.01, accs
        assert abs(accs["int8"] - accs["float32"]) < 0.01, accs

    def test_delays_and_replies_with_int8(self, key):
        sim = make_sim("int8", protocol=AntiEntropyProtocol.PUSH_PULL,
                       delay=UniformDelay(0, 15))
        acc, _ = final_acc(sim, key, rounds=8)
        assert acc > 0.8

    def test_compact_deliver_equivalent_under_int8(self, key):
        """The compacted slot pass gathers dequantized rows; on/off must
        not change an int8 trajectory (same contract as fp32 compaction)."""
        topo = Topology.random_regular(16, 6, seed=7)
        sim_off = make_sim("int8", topology=topo, compact_deliver=False)
        sim_on = make_sim("int8", topology=topo, compact_deliver=4)
        _, s_off = final_acc(sim_off, key, rounds=6)
        _, s_on = final_acc(sim_on, key, rounds=6)
        for a, b in zip(jax.tree_util.tree_leaves(s_off.model.params),
                        jax.tree_util.tree_leaves(s_on.model.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=1e-6)


class TestDequantKernel:
    @pytest.mark.parametrize("n,m,f", [(16, 48, 116), (8, 8, 512), (5, 10, 1)])
    def test_bf16_matches_reference(self, n, m, f):
        rng = np.random.default_rng(0)
        p = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
        h = jnp.asarray(rng.normal(size=(m, f)).astype(np.float32)
                        ).astype(jnp.bfloat16)
        idx = jnp.asarray(rng.integers(0, m, n).astype(np.int32))
        w1 = jnp.asarray(rng.uniform(size=n).astype(np.float32))
        got = gather_merge_flat(p, h, idx, w1, 1.0 - w1)
        want = gather_merge_reference(p, h, idx, w1, 1.0 - w1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("n,m,f", [(16, 48, 116), (8, 8, 512), (5, 10, 1)])
    def test_int8_matches_reference(self, n, m, f):
        rng = np.random.default_rng(1)
        p = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
        h = jnp.asarray(rng.integers(-127, 128, (m, f)).astype(np.int8))
        scale = jnp.asarray(rng.uniform(0.01, 2.0, m).astype(np.float32))
        idx = jnp.asarray(rng.integers(0, m, n).astype(np.int32))
        w1 = jnp.asarray(rng.uniform(size=n).astype(np.float32))
        got = gather_merge_flat(p, h, idx, w1, 1.0 - w1, scale=scale)
        want = gather_merge_reference(p, h, idx, w1, 1.0 - w1, scale)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("history_dtype", ["bfloat16", "int8"])
    def test_fused_engine_path_matches_unfused(self, key, history_dtype):
        """fused_merge over a quantized ring (kernel dequant) == the
        gather->decode->blend path (same PRNG streams, fp reassociation
        only). "per_slot" keeps the slot-interleaved semantics this
        clique config needs; the multi-slot path's parity matrix is in
        test_fused_deliver.py."""
        sim_a = make_sim(history_dtype, n_nodes=12, fused_merge=False,
                         compact_deliver=False)
        sim_b = make_sim(history_dtype, n_nodes=12, fused_merge="per_slot")
        _, sa = final_acc(sim_a, key, rounds=6)
        _, sb = final_acc(sim_b, key, rounds=6)
        for la, lb in zip(jax.tree_util.tree_leaves(sa.model.params),
                          jax.tree_util.tree_leaves(sb.model.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-4, atol=1e-5)


class TestMemoryBudget:
    def test_ring_bytes_scale_with_format(self):
        budgets = {hd: make_sim(hd, n_nodes=100, d=57).memory_budget()
                   for hd in DTYPES}
        f32 = budgets["float32"]["history_ring_bytes"]
        bf16 = budgets["bfloat16"]["history_ring_bytes"]
        i8 = budgets["int8"]["history_ring_bytes"]
        # Acceptance bands: >= 2x under bf16, >= 3.5x under int8 (sidecar
        # INCLUDED in the int8 ring term).
        assert f32 / bf16 >= 2.0, (f32, bf16)
        assert f32 / i8 >= 3.5, (f32, i8)
        assert budgets["int8"]["history_ring_sidecar"] > 0
        assert budgets["float32"]["history_ring_sidecar"] == 0
        assert budgets["int8"]["history_dtype"] == "int8"
        # Depth must not depend on the storage format.
        assert len({b["history_depth"] for b in budgets.values()}) == 1

    def test_wire_bytes_per_message(self):
        sims = {hd: make_sim(hd, d=57) for hd in DTYPES}
        # LogReg(57, 2): 116 scalars over 2 leaves.
        assert sims["float32"].wire_bytes_per_message() == 116 * 4
        assert sims["bfloat16"].wire_bytes_per_message() == 116 * 2
        assert sims["int8"].wire_bytes_per_message() == 116 + 2 * 4

    def test_manifest_records_history_dtype(self):
        sim = make_sim("int8")
        manifest = sim.run_manifest()
        assert manifest.config["history_dtype"] == "int8"
        assert manifest.to_dict()["config"]["history_dtype"] == "int8"


class TestVariantsWireFormat:
    @pytest.mark.parametrize("history_dtype", ["bfloat16", "int8"])
    def test_all2all_learns_under_quantized_wire(self, key, history_dtype):
        topo = Topology.clique(16)
        sim = make_sim(history_dtype, sim_cls=All2AllGossipSimulator,
                       handler_cls=WeightedSGDHandler, topology=topo,
                       mixing=uniform_mixing(topo))
        acc, _ = final_acc(sim, key, rounds=8)
        sim_f = make_sim("float32", sim_cls=All2AllGossipSimulator,
                         handler_cls=WeightedSGDHandler, topology=topo,
                         mixing=uniform_mixing(topo))
        acc_f, _ = final_acc(sim_f, key, rounds=8)
        assert abs(acc - acc_f) < 0.05, (acc, acc_f)
        assert acc > 0.8

    def test_cacheneigh_parks_in_wire_format(self, key):
        sim = make_sim("int8", n_nodes=12, sim_cls=CacheNeighGossipSimulator,
                       topology=Topology.random_regular(12, 4, seed=3))
        st = sim.init_nodes(key)
        leaves = jax.tree_util.tree_leaves(st.aux["cache_params"])
        assert all(l.dtype == jnp.int8 for l in leaves)
        assert "cache_scale" in st.aux
        st, rep = sim.start(st, n_rounds=8, key=key)
        assert rep.curves(local=False)["accuracy"][-1] > 0.75

    def test_cacheneigh_fp32_aux_unchanged(self, key):
        sim = make_sim("float32", n_nodes=12,
                       sim_cls=CacheNeighGossipSimulator,
                       topology=Topology.random_regular(12, 4, seed=3))
        st = sim.init_nodes(key)
        assert "cache_scale" not in st.aux
        assert all(l.dtype == jnp.float32 for l in
                   jax.tree_util.tree_leaves(st.aux["cache_params"]))


class TestNeighborTableDuplicates:
    def _dup_topology(self):
        # The 0-1 edge listed twice: a multigraph (each node's CSR row
        # repeats its peer; reference semantics = doubled sampling weight).
        return SparseTopology(2, np.array([[0, 1], [0, 1]]))

    def test_default_accepts_multigraph(self):
        from gossipy_tpu.simulation.nodes import build_neighbor_table
        nbr = build_neighbor_table(self._dup_topology())
        assert (nbr[0] == [1, 1]).all()

    def test_opt_in_rejects_duplicates(self):
        from gossipy_tpu.simulation.nodes import build_neighbor_table
        with pytest.raises(ValueError, match="more than once"):
            build_neighbor_table(self._dup_topology(), reject_duplicates=True)

    def test_cacheneigh_still_rejects(self, key):
        with pytest.raises(ValueError, match="more than once"):
            make_sim("float32", n_nodes=2, sim_cls=CacheNeighGossipSimulator,
                     topology=self._dup_topology())


class TestCompactSafeAttribute:
    def _subclassed_sim(self, cls, **kw):
        return make_sim("float32", n_nodes=64, sim_cls=cls,
                        topology=Topology.random_regular(64, 6, seed=1), **kw)

    def test_unsafe_decode_extra_override_disables_auto(self):
        class Unsafe(GossipSimulator):
            def _decode_extra(self, extra):
                return extra

        sim = self._subclassed_sim(Unsafe)
        assert sim._compact_cap is None  # auto stayed off

    def test_unsafe_override_rejects_explicit_compaction(self):
        class Unsafe(GossipSimulator):
            def _decode_extra(self, extra):
                return extra

        with pytest.raises(AssertionError, match="_compact_safe"):
            self._subclassed_sim(Unsafe, compact_deliver=4)

    def test_declared_safe_override_auto_enables(self):
        class Safe(GossipSimulator):
            _compact_safe = True

            def _decode_extra(self, extra):
                return extra

        sim = self._subclassed_sim(Safe)
        assert sim._compact_cap is not None


class TestDonation:
    def test_donated_state_is_invalidated(self, key):
        sim = make_sim("float32", n_nodes=8)
        st = sim.init_nodes(key)
        st2, _ = sim.start(st, n_rounds=2, key=key)  # donates st
        assert np.isfinite(np.asarray(
            jax.tree_util.tree_leaves(st2.model.params)[0])).all()
        with pytest.raises(RuntimeError):
            np.asarray(jax.tree_util.tree_leaves(st.model.params)[0])

    def test_donate_false_keeps_input_alive(self, key):
        sim = make_sim("float32", n_nodes=8)
        st = sim.init_nodes(key)
        _, r1 = sim.start(st, n_rounds=2, key=key, donate_state=False)
        _, r2 = sim.start(st, n_rounds=2, key=key, donate_state=False)
        np.testing.assert_allclose(r1.curves(local=False)["accuracy"],
                                   r2.curves(local=False)["accuracy"])


class TestCompilationCacheStats:
    def test_stats_shape_and_manifest_field(self):
        from gossipy_tpu import compilation_cache_stats
        stats = compilation_cache_stats()
        assert set(stats) == {"enabled", "dir", "events"}
        sim = make_sim("float32")
        d = sim.run_manifest().to_dict()
        assert "compilation_cache" in d
