"""The driver-contract entry points must never hang on a wedged backend."""

import os
import subprocess



def test_entry_pins_cpu_when_probe_wedges(monkeypatch):
    """A hung backend-init probe must pin the process to the cpu platform
    (env var for children + live jax config for this interpreter) so the
    driver's in-process compile check of entry() cannot hang."""
    import __graft_entry__ as g

    calls = {}

    def fake_run(cmd, timeout=None, **kwargs):
        calls["timeout"] = timeout
        raise subprocess.TimeoutExpired(cmd=cmd, timeout=timeout)

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    updates = []
    import jax
    monkeypatch.setattr(jax.config, "update",
                        lambda k, v: updates.append((k, v)))
    g._fall_back_to_cpu_if_backend_wedged()
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    assert ("jax_platforms", "cpu") in updates
    assert calls["timeout"] and calls["timeout"] <= 300


def test_entry_leaves_platform_alone_when_probe_ok(monkeypatch):
    import __graft_entry__ as g

    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: subprocess.CompletedProcess(a, returncode=0))
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    g._fall_back_to_cpu_if_backend_wedged()
    assert os.environ["JAX_PLATFORMS"] == "axon"


def test_entry_returns_jittable(monkeypatch):
    """entry() must return (fn, args) that jit-compile. The test env is
    already CPU-pinned, so the probe is stubbed to 'healthy'."""
    import __graft_entry__ as g

    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: subprocess.CompletedProcess(a, returncode=0))
    import jax

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)
