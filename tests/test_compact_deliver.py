"""Compacted deliver-phase equivalence (engine.compact_deliver).

The compacted slot pass gathers each mailbox slot's live receivers into a
static small batch before the merge+train pass instead of running the pass
full-width under a mask (the round-4 verdict's #1 MFU lever: at Poisson(~1)
fan-in the masked passes waste ~3/4 of the deliver-phase FLOPs). These
tests pin the contract: trajectories are IDENTICAL with compaction on or
off — including when the static capacity overflows at runtime and the
engine falls back to the full-width pass mid-scan — because per-node PRNG
streams are preserved and overflow dispatch is a ``lax.cond``.
"""

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
    Topology, UniformDelay
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, SamplingSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import CacheNeighGossipSimulator, \
    GossipSimulator, PassThroughGossipSimulator, SamplingGossipSimulator


def make_sim(compact, n_nodes=16, protocol=AntiEntropyProtocol.PUSH,
             sim_cls=GossipSimulator, handler_cls=SGDHandler, topology=None,
             **sim_kwargs):
    rng = np.random.default_rng(3)
    d = 10
    w = rng.normal(size=d)
    X = rng.normal(size=(20 * n_nodes, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes)
    kw = {}
    if handler_cls is SamplingSGDHandler:
        kw["sample_size"] = 0.5
    handler = handler_cls(model=LogisticRegression(d, 2),
                          loss=losses.cross_entropy,
                          optimizer=optax.sgd(0.1), local_epochs=1,
                          batch_size=16, n_classes=2, input_shape=(d,),
                          create_model_mode=CreateModelMode.MERGE_UPDATE,
                          **kw)
    if topology is None:
        topology = Topology.random_regular(n_nodes, 6, seed=7)
    return sim_cls(handler, topology, disp.stacked(), delta=20,
                   protocol=protocol, compact_deliver=compact, **sim_kwargs)


def run(sim, key, rounds=6):
    st = sim.init_nodes(key)
    st, report = sim.start(st, n_rounds=rounds, key=jax.random.fold_in(key, 1))
    return st, report


def assert_same_trajectory(key, rounds=6, **kwargs):
    cap = kwargs.pop("cap", 4)
    s_off, r_off = run(make_sim(False, **kwargs), key, rounds)
    s_on, r_on = run(make_sim(cap, **kwargs), key, rounds)
    for a, b in zip(jax.tree_util.tree_leaves(s_off.model.params),
                    jax.tree_util.tree_leaves(s_on.model.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-6)
    assert r_off.sent_messages == r_on.sent_messages
    assert r_off.failed_messages == r_on.failed_messages
    co = r_off.curves(local=False)["accuracy"]
    cn = r_on.curves(local=False)["accuracy"]
    np.testing.assert_allclose(co, cn, atol=1e-6)


class TestCompactEquivalence:
    def test_small_cap_overflow_falls_back(self, key):
        # cap=2 on a 16-node population: slot 0 overflows the capacity
        # nearly every round (the full-width cond branch runs), higher
        # slots fit (the compact branch runs) — both paths are exercised
        # and the trajectory must not budge.
        assert_same_trajectory(key, cap=2)

    def test_full_cap_never_overflows(self, key):
        assert_same_trajectory(key, cap=16)

    def test_with_faults_and_delay(self, key):
        assert_same_trajectory(key, cap=6, drop_prob=0.2, online_prob=0.8,
                               delay=UniformDelay(0, 35))

    def test_push_pull_replies(self, key):
        # Replies route through _receive_slot_apply too (reply phase);
        # PUSH_PULL exercises both mailboxes under compaction.
        assert_same_trajectory(key, cap=4,
                               protocol=AntiEntropyProtocol.PUSH_PULL)

    def test_decode_extra_variant(self, key):
        # SamplingGossipSimulator overrides _decode_extra (per-message
        # sample seeds) but not _apply_receive: the decoded arg must be
        # gathered per compacted row, preserving each receiver's mask.
        assert_same_trajectory(key, cap=5, sim_cls=SamplingGossipSimulator,
                               handler_cls=SamplingSGDHandler)

    def test_receive_rows_variant(self, key):
        # PassThrough customizes receive via the row-aligned
        # _receive_rows contract (per-row accept draw, node_ids-gathered
        # degrees) — compaction must preserve its trajectory too.
        assert_same_trajectory(key, cap=5,
                               sim_cls=PassThroughGossipSimulator)


class TestCompactRepetitions:
    """The seed-vmapped megabatch program COMPACTS: the slot-overflow
    predicate is reduced across the batch axis (``lax.pmax`` under the
    vmap's axis name) before the ``lax.cond``, so the dispatch stays
    batch-uniform — one branch executes — instead of a batched predicate
    silently adding the compact pass on top of every wide one (which is
    why earlier rounds forced compaction off here)."""

    def test_seed_vmapped_program_compacts_and_matches(self, key):
        # cap == population: every slot fits on every lane, so the whole
        # batch takes the compact branch — the counters must prove it —
        # and the curves must equal the never-compacting sim's.
        keys = jax.random.split(key, 3)
        sim_on = make_sim(16)
        sim_off = make_sim(False)
        _, reps_on = sim_on.run_repetitions(5, keys)
        _, reps_off = sim_off.run_repetitions(5, keys)
        assert sim_on._compact_cap == 16
        assert sim_on._batch_axis_name is None  # restored after the run
        compact = sum(int(np.asarray(r.compact_slots_per_round).sum())
                      for r in reps_on)
        wide = sum(int(np.asarray(r.wide_slots_per_round).sum())
                   for r in reps_on)
        assert compact > 0 and wide == 0, (compact, wide)
        for a, b in zip(reps_on, reps_off):
            np.testing.assert_allclose(a.curves(local=False)["accuracy"],
                                       b.curves(local=False)["accuracy"],
                                       atol=1e-6)

    def test_mixed_overflow_stays_batch_uniform_and_matches(self, key):
        # cap=2 on 16 nodes: slot 0 overflows on some lane nearly every
        # round (every lane then takes the wide pass — the pmax makes the
        # overflow decision collective), higher slots fit on all lanes
        # (compact). Both branches execute across the run; per-seed
        # trajectories must equal the never-compacting program's.
        keys = jax.random.split(key, 3)
        _, reps_on = make_sim(2).run_repetitions(5, keys)
        _, reps_off = make_sim(False).run_repetitions(5, keys)
        compact = sum(int(np.asarray(r.compact_slots_per_round).sum())
                      for r in reps_on)
        wide = sum(int(np.asarray(r.wide_slots_per_round).sum())
                   for r in reps_on)
        assert compact > 0 and wide > 0, (compact, wide)
        for a, b in zip(reps_on, reps_off):
            np.testing.assert_allclose(a.curves(local=False)["accuracy"],
                                       b.curves(local=False)["accuracy"],
                                       atol=1e-6)


class TestCompactFused:
    """compact_deliver composed with the single-pass fused deliver
    (fused_merge="multi"): the live-count cond dispatches the SAME
    multi-slot kernel over the [cap] gathered batch, so the trajectory
    must be bit-identical to the uncompacted fused run — and the legacy
    per-slot fused path must refuse to co-enable."""

    def _run(self, compact, key, rounds=6):
        sim = make_sim(compact, fused_merge="multi")
        return (*run(sim, key, rounds), sim)

    def test_fused_dispatch_matches_uncompacted(self, key):
        s_off, r_off, _ = self._run(False, key)
        s_on, r_on, sim_on = self._run(16, key)
        assert sim_on._compact_cap == 16
        for a, b in zip(jax.tree_util.tree_leaves(s_off.model.params),
                        jax.tree_util.tree_leaves(s_on.model.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert r_off.sent_messages == r_on.sent_messages
        assert r_off.failed_messages == r_on.failed_messages
        # cap == population: every round takes the compact branch.
        assert int(np.asarray(r_on.compact_slots_per_round).sum()) > 0
        assert int(np.asarray(r_on.wide_slots_per_round).sum()) == 0

    def test_fused_overflow_falls_back(self, key):
        # cap=2 on 16 nodes overflows most rounds: both cond branches run
        # across the trajectory, which must still match bit-for-bit.
        s_off, r_off, _ = self._run(False, key)
        s_on, r_on, _ = self._run(2, key)
        for a, b in zip(jax.tree_util.tree_leaves(s_off.model.params),
                        jax.tree_util.tree_leaves(s_on.model.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(np.asarray(r_on.wide_slots_per_round).sum()) > 0

    def test_per_slot_with_compact_rejected(self, key):
        with pytest.raises(AssertionError, match="per_slot|per-slot"):
            make_sim(4, fused_merge="per_slot")


class TestCompactSharded:
    def test_sharded_matches_unsharded(self, key):
        # The compacted path's argsort/gather/scatter must compile and run
        # under a node-sharded mesh (the driver's dryrun config) and give
        # the unsharded trajectory.
        import jax
        from gossipy_tpu.parallel import make_mesh, shard_data, shard_state
        n = 64
        rng = np.random.default_rng(5)
        d = 10
        X = rng.normal(size=(n * 8, d)).astype(np.float32)
        y = (X @ rng.normal(size=d) > 0).astype(np.int64)
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=n)
        topo = Topology.random_regular(n, 6, seed=0)

        def handler():
            return SGDHandler(model=LogisticRegression(d, 2),
                              loss=losses.cross_entropy,
                              optimizer=optax.sgd(0.5), local_epochs=1,
                              batch_size=8, n_classes=2, input_shape=(d,),
                              create_model_mode=CreateModelMode.MERGE_UPDATE)

        mesh = make_mesh()
        sim = GossipSimulator(handler(), topo,
                              shard_data(disp.stacked(), mesh), delta=8)
        assert sim._compact_cap is not None  # auto-on at N=64
        st = shard_state(sim.init_nodes(key), mesh)
        _, rep = sim.start(st, n_rounds=3, key=jax.random.fold_in(key, 1))
        sim_u = GossipSimulator(handler(), topo, disp.stacked(), delta=8)
        st_u = sim_u.init_nodes(key)
        _, rep_u = sim_u.start(st_u, n_rounds=3,
                               key=jax.random.fold_in(key, 1))
        np.testing.assert_allclose(rep.curves(local=False)["accuracy"],
                                   rep_u.curves(local=False)["accuracy"],
                                   atol=1e-5)


class TestCompactGating:
    def test_auto_off_below_population_floor(self, key):
        assert make_sim(None)._compact_cap is None  # 16 < 48

    def test_explicit_cap_clamped_to_population(self, key):
        assert make_sim(64)._compact_cap == 16

    def test_negative_cap_rejected(self, key):
        with pytest.raises(ValueError, match="capacity must be >= 1"):
            make_sim(-2)

    def test_variant_override_rejected(self, key):
        # CacheNeigh overrides _apply_receive (it parks peers in
        # positional aux slots) — incompatible with compaction.
        with pytest.raises(AssertionError, match="base _apply_receive"):
            make_sim(True, sim_cls=CacheNeighGossipSimulator)

    def test_variant_auto_silently_off(self, key):
        # n_nodes=64 clears the population floor, so the ONLY reason
        # compaction can stay off is the override gate (at the default 16
        # the size gate would mask a broken variant check).
        sim = make_sim(None, n_nodes=64, sim_cls=CacheNeighGossipSimulator)
        assert sim._compact_cap is None
        assert make_sim(None, n_nodes=64)._compact_cap is not None

    def test_derived_cap_at_scale(self):
        # At 100 nodes / degree 20 / PUSH the worst-case fan-in is ~1:
        # the derived capacity sits well under the population (the whole
        # point) but above the mean second-arrival count.
        sim = make_sim(True, n_nodes=100)
        assert sim._compact_cap is not None
        assert 24 <= sim._compact_cap < 75

    def test_hub_topology_still_compacts(self):
        # The capacity derives from PER-NODE fan-in tails: a BA hub's
        # enormous lam is one node, not a reason to disable compaction
        # for the population (the hub's slots overflow to the full pass
        # at runtime).
        sim = make_sim(True, n_nodes=64,
                       topology=Topology.barabasi_albert(64, 3, seed=1))
        assert sim._compact_cap is not None
        assert sim._compact_cap < 48  # well under 0.75 * N

    def test_faults_shrink_the_cap(self):
        # Dropped messages never scatter and offline receivers mask their
        # slots invalid, so the live count the capacity protects is
        # statically smaller under faults.
        healthy = make_sim(True, n_nodes=100)._compact_cap
        faulty = make_sim(True, n_nodes=100, drop_prob=0.5,
                          online_prob=0.5)._compact_cap
        assert faulty is not None and faulty < healthy
