"""Regression coverage for bench.py modes that run off the driver path."""

import json

import numpy as np
import pytest


@pytest.fixture(scope="module")
def bench():
    import bench as b  # conftest puts the repo root on sys.path
    return b


def last_json(capfd):
    out, _ = capfd.readouterr()
    return json.loads([l for l in out.strip().splitlines()
                       if l.startswith("{")][-1])


def test_scale_small_n_keeps_fractional_split(bench, capfd):
    """The 2048-sample eval cap is a cap, not a floor: small --scale runs
    must keep a valid (<1.0) test fraction instead of crashing — and the
    JSON row carries the backend label and build time."""
    bench.bench_scale(64, rounds=2)
    row = last_json(capfd)
    assert row["metric"] == "sim_rounds_per_sec_64nodes"
    assert np.isfinite(row["raw"]["final_global_accuracy"])
    assert row["raw"]["backend"] in ("cpu", "tpu")
    assert row["unit"] == "rounds/s" and row["value"] > 0
    assert row["raw"]["topology_build_seconds"] >= 0


@pytest.mark.slow
def test_mfu_wide_json_contract(bench, capfd, monkeypatch):
    """--mfu-wide (the compaction-off A/B control) emits its own metric
    name AND actually reaches the simulator with compact_deliver=False —
    at the smoke N the auto default is also off, so the wiring is
    asserted at the constructor, not via the (vacuous) derived cap."""
    import gossipy_tpu.simulation as sim_mod
    seen = []
    orig = sim_mod.GossipSimulator

    class Spy(orig):
        def __init__(self, *a, **kw):
            seen.append(kw.get("compact_deliver", "MISSING"))
            super().__init__(*a, **kw)

    monkeypatch.setattr(sim_mod, "GossipSimulator", Spy)
    monkeypatch.setattr(bench, "DEGRADED", True)
    bench.bench_mfu(rounds=1, n_nodes=4, n_train=64, n_test=32,
                    compact=False)
    row = last_json(capfd)
    assert row["metric"] == "mfu_cifar10_100nodes_cnn_widepass"
    assert row["raw"]["compact_cap"] is None
    assert seen and all(v is False for v in seen), seen


@pytest.mark.slow
def test_mfu_reps_json_contract(bench, capfd, monkeypatch):
    """--mfu-reps (seed-batched throughput): metric suffix, seed_batch
    field, and executed FLOPs scaled by the batch."""
    monkeypatch.setattr(bench, "DEGRADED", True)
    bench.bench_mfu(rounds=1, n_nodes=4, n_train=64, n_test=32, reps=2)
    row = last_json(capfd)
    assert row["metric"] == "mfu_cifar10_100nodes_cnn_reps2"
    raw = row["raw"]
    assert raw["seed_batch"] == 2
    assert raw["xla_flops_executed_total"] == \
        pytest.approx(2 * raw["xla_flops_per_round_with_eval"])


@pytest.mark.slow
@pytest.mark.parametrize("variant,metric", [
    ("vanilla", "mfu_cifar10_100nodes_cnn"),
    ("all2all", "mfu_cifar10_100nodes_cnn_all2all"),
])
def test_mfu_json_contract(bench, capfd, monkeypatch, variant, metric):
    """--mfu / --mfu-all2all must work first-try when the tunnel returns:
    assert the JSON shape on a tiny CPU run — MFU is null off-TPU (unknown
    device kind, loud warning) but ms/round must be finite and the line
    fully labeled. (CNN compile is ~30 s on this host: slow lane.)"""
    monkeypatch.setattr(bench, "DEGRADED", True)  # fp32 + 1 round
    bench.bench_mfu(rounds=1, n_nodes=4, n_train=64, n_test=32,
                    variant=variant)
    row = last_json(capfd)
    assert row["metric"] == metric
    assert row["raw"]["protocol"] == variant
    assert row["unit"] == "fraction_of_peak"
    raw = row["raw"]
    assert raw["degraded"] is True and raw["backend"] in ("cpu", "tpu")
    assert np.isfinite(raw["ms_per_round"]) and raw["ms_per_round"] > 0
    if raw["device_kind"] not in bench.PEAK_FLOPS:
        assert row["value"] is None
        assert raw["peak_tflops_per_sec"] is None
    else:
        assert row["value"] is not None and row["value"] > 0


@pytest.mark.slow
def test_mfu_flop_decomposition(bench, capfd, monkeypatch):
    """The non-degraded path decomposes per-round FLOPs into base + eval via
    two 1-round compiles; executed FLOPs must respect the eval_every
    amortization (this is the branch that runs on the real chip — it must
    work first-try when the tunnel opens)."""
    monkeypatch.setattr(bench, "DEGRADED", False)
    bench.bench_mfu(rounds=3, n_nodes=4, n_train=64, n_test=32,
                    eval_every=2)
    raw = last_json(capfd)["raw"]
    assert raw["eval_every"] == 2
    assert raw["n_eval_rounds"] == 2  # rounds 1 and 2 (final forced)
    f_with, f_base = raw["xla_flops_per_round_with_eval"], \
        raw["xla_flops_per_round_base"]
    assert f_with is not None and f_base is not None and f_base < f_with
    assert raw["xla_flops_executed_total"] == \
        pytest.approx(3 * f_base + 2 * (f_with - f_base))


@pytest.mark.slow
def test_fused_regime_json_contract(bench, capfd):
    """--fused-regime off-TPU: plain timing is measured, the wall-clock
    fused legs are skipped with an explicit reason in raw.error, and the
    deliver-phase / bytes-moved columns are stamped for all three legs
    (plain / per_slot / multi). (CNN compile is ~30 s on this host: slow
    lane.)"""
    import jax
    bench.bench_fused_regime(rounds=1, n=4)
    row = last_json(capfd)
    assert row["metric"] == "fused_merge_speedup_cnn_clique"
    raw = row["raw"]
    assert np.isfinite(raw["plain_ms_per_round"])
    assert raw["mailbox_slots"] == 4
    bytes_moved = raw["deliver_bytes_moved"]
    assert set(bytes_moved) >= {"plain", "per_slot", "multi",
                                "wire_bytes_per_message"}
    # The K->1 HBM collapse must be visible in the model: one pass over
    # the params matrix instead of K, gather term unchanged.
    assert bytes_moved["multi"] < bytes_moved["per_slot"] \
        <= bytes_moved["plain"]
    assert bytes_moved["wire_bytes_per_message"] > 0
    assert set(raw["deliver_ms_per_round"]) == {"plain", "per_slot", "multi"}
    if jax.default_backend() != "tpu":
        assert row["value"] is None
        assert raw["fused_ms_per_round"] is None
        assert raw["per_slot_ms_per_round"] is None
        assert "skipped off-TPU" in raw["error"]
        assert raw["deliver_timing_mode"] == "cpu_interpreter"


@pytest.mark.slow
def test_to_acc_mode_reports_target_round(bench, capsys):
    """--to-acc runs the chunked accuracy search and reports the hit round
    (100-node program: slow lane)."""
    X, y = bench.make_data()
    bench.bench_to_accuracy(X, y, target=0.5)
    out = capsys.readouterr().out
    assert "[to-acc]" in out
    assert "reached at round" in out, out


def test_scale_all2all_json_contract(bench, capfd):
    bench.bench_scale_all2all(64, rounds=2)
    row = last_json(capfd)
    assert row["metric"] == "all2all_rounds_per_sec_64nodes"
    assert row["unit"] == "rounds/s" and row["value"] > 0
    assert np.isfinite(row["raw"]["final_global_accuracy"])
    assert row["raw"]["topology_and_mixing_build_seconds"] >= 0


def test_eval_memory_warning_fires_at_scale_trap():
    """The engine warns at construction for the [nodes x samples] eval
    blow-up the scale bench hit (16 GB at 50k nodes x 40k samples)."""
    import optax

    from gossipy_tpu.core import SparseTopology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    rng = np.random.default_rng(0)
    d, n = 4, 4096
    X = rng.normal(size=(8 * n, d)).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.int64)
    handler = SGDHandler(model=LogisticRegression(d, 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.1),
                         local_epochs=1, batch_size=4, n_classes=2,
                         input_shape=(d,))
    topo = SparseTopology.ring(n, 2)
    # 4096 nodes x ~6554 eval samples x 3 f32 buffers ~= 0.3 GB -> quiet;
    # scale the estimate into warning range via full-population eval of a
    # large synthetic eval split by faking more nodes is expensive, so
    # instead check both sides around the 2 GB threshold directly.
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.2),
                          n=n, eval_on_user=False)
    data = disp.stacked()
    import warnings as w
    with w.catch_warnings():
        w.simplefilter("error", UserWarning)  # below threshold: stay quiet
        GossipSimulator(handler, topo, data, delta=10)

    sim = GossipSimulator.__new__(GossipSimulator)  # threshold math only
    sim.has_global_eval = True
    sim.n_nodes = 50_000
    sim.sampling_eval = 0.0
    sim.data = {"x_eval": np.zeros((40_000, 1), np.float32)}
    with pytest.warns(UserWarning, match="likely OOM"):
        sim._warn_if_eval_memory_large()
    sim.sampling_eval = 0.01  # the fix: 500 eval nodes -> quiet
    with w.catch_warnings():
        w.simplefilter("error", UserWarning)
        sim._warn_if_eval_memory_large()


@pytest.mark.slow
def test_watchdog_degrades_on_wedged_accel_run():
    """A mid-run wedge — probe succeeds, then the accelerator run never
    finishes (observed 2026-07-31 on the tunneled runtime) — must still end
    in a labeled degraded CPU row, not rc!=0. Forced here by a 1-second
    deadline: the watchdog kills the inner run and re-execs the CPU
    fallback."""
    import json as j
    import os
    import subprocess
    import sys

    from _virtual_mesh import virtual_mesh_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = virtual_mesh_env(1, extra_path=repo)
    env["GOSSIPY_TPU_BENCH_DEADLINE"] = "1"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--scale", "64"], cwd=repo, env=env,
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "wedged" in proc.stderr
    row = j.loads([l for l in proc.stdout.strip().splitlines()
                   if l.startswith("{")][-1])
    assert row["raw"]["degraded"] is True
    assert row["raw"]["backend"] == "cpu"
    assert row["raw"]["degrade_reason"] == "wedged_after_probe"
    assert row["value"] > 0


def test_attention_parity_helper(bench):
    """_attention_parity (the on-chip fwd+bwd parity row for --ring-attn)
    must pass for identical implementations and fail for a subtly wrong
    one — exercised off-TPU so the first on-chip run cannot be its first
    run ever."""
    import jax
    import jax.numpy as jnp
    key = jax.random.PRNGKey(0)
    s, d = 64, 16
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (s, d))
               for i in range(3))

    def dense(q_, k_, v_):
        sc = (q_ @ k_.T) / np.sqrt(d)
        i = jnp.arange(s)
        sc = jnp.where(i[None, :] > i[:, None], -1e30, sc)
        return jax.nn.softmax(sc, axis=-1) @ v_

    good = bench._attention_parity(dense, dense, q, k, v)
    assert good["pass"] and good["fwd_max_abs_err"] == 0.0

    def broken(q_, k_, v_):  # wrong scale: the classic kernel bug shape
        return dense(q_, k_, v_) * 1.05

    bad = bench._attention_parity(dense, broken, q, k, v)
    assert not bad["pass"] and bad["fwd_max_abs_err"] > 1e-3

    def nan_kernel(q_, k_, v_):  # NaN output: must fail AND stay strict JSON
        return dense(q_, k_, v_) * jnp.nan

    import json
    nan_row = bench._attention_parity(dense, nan_kernel, q, k, v)
    assert nan_row["pass"] is False
    json.loads(json.dumps(nan_row, allow_nan=False))  # RFC-8259-strict
    # The stderr line must also survive string-typed (sanitized) errors.
    assert "nan" in bench._parity_desc(nan_row)
    assert "e" in bench._parity_desc(good)  # floats format as %.2e


def test_backend_poll_before_degrade(bench, monkeypatch):
    """VERDICT r3 #4: the watchdog polls the probe before degrading so the
    driver-visible row is a TPU row whenever a window opens mid-run.
    PROBE_POLL=0 must disable polling (the evidence script's setting); a
    probe that comes alive mid-poll must return True."""
    calls = []

    def probe_seq(results):
        it = iter(results)
        return lambda: (calls.append(1), next(it))[1]

    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    # Disabled polling: one probe, immediate degrade.
    monkeypatch.setenv("GOSSIPY_TPU_BENCH_PROBE_POLL", "0")
    monkeypatch.setattr(bench, "_backend_alive", probe_seq([False]))
    assert bench._backend_alive_with_poll(1000.0) is False
    assert len(calls) == 1
    # Tunnel opens on the third probe inside the budget.
    calls.clear()
    monkeypatch.setenv("GOSSIPY_TPU_BENCH_PROBE_POLL", "600")
    monkeypatch.setattr(bench, "_backend_alive",
                        probe_seq([False, False, True]))
    assert bench._backend_alive_with_poll(1000.0) is True
    assert len(calls) == 3


def test_ring_attn_json_contract(bench, capfd, monkeypatch):
    """--ring-attn off-TPU: dense timing measured, flash leg skipped with
    an explicit reason; shrunk sizes under the degraded label."""
    monkeypatch.setattr(bench, "DEGRADED", True)
    bench.bench_ring_attention(s_len=64)
    row = last_json(capfd)
    assert row["metric"] == "flash_attention_speedup"
    raw = row["raw"]
    assert raw["s_len"] == 64 and raw["dense_ms"] > 0
    import jax
    if jax.default_backend() != "tpu":
        assert row["value"] is None
        assert "skipped off-TPU" in raw["error"]
    else:
        assert row["value"] is not None
