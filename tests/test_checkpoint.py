"""Checkpoint/resume tests (replaces reference save/load, simul.py:460-494)."""

import jax
import numpy as np
import pytest

from gossipy_tpu.checkpoint import (
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import PegasosHandler
from gossipy_tpu.models import AdaLine
from gossipy_tpu.simulation import GossipSimulator


def make_sim(n_nodes=8, seed=0, **kw):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=6)
    X = rng.normal(size=(160, 6)).astype(np.float32)
    y = (2 * (X @ w > 0) - 1).astype(np.float32)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes)
    handler = PegasosHandler(AdaLine(6), learning_rate=0.01,
                             create_model_mode=CreateModelMode.UPDATE)
    return GossipSimulator(handler, Topology.clique(n_nodes), disp.stacked(),
                           delta=10, protocol=AntiEntropyProtocol.PUSH, **kw)


def states_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


class TestSaveRestore:
    def test_roundtrip(self, tmp_path, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        st, _ = sim.start(st, n_rounds=3, key=key)
        path = save_checkpoint(str(tmp_path / "ckpt"), st, key=key)

        template = sim.init_nodes(jax.random.PRNGKey(7))
        restored, rkey = restore_checkpoint(path, template, key)
        assert states_equal(st, restored)
        assert np.array_equal(np.asarray(rkey), np.asarray(key))
        assert int(np.asarray(restored.round)) == 3

    def test_resume_continues_identically(self, tmp_path, key):
        """split run (3 + 4 rounds via checkpoint) == straight 7-round run.

        Round randomness is keyed on the absolute round number, so resuming
        from a restored state must reproduce the unbroken run exactly.
        """
        sim = make_sim()
        st0 = sim.init_nodes(key)
        full, _ = sim.start(st0, n_rounds=7, key=key, donate_state=False)

        part, _ = sim.start(st0, n_rounds=3, key=key)
        path = save_checkpoint(str(tmp_path / "ckpt"), part, key=key)
        template = sim.init_nodes(jax.random.PRNGKey(7))
        restored, rkey = restore_checkpoint(path, template, key)
        resumed, _ = sim.start(restored, n_rounds=4, key=rkey)

        assert states_equal(full.model, resumed.model)


class TestCheckpointManager:
    def test_periodic_and_retention(self, tmp_path, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        mgr = CheckpointManager(str(tmp_path / "run"), interval=2, max_to_keep=2)
        reports = []
        final = mgr.run(sim, st, until_round=6, key=key, reports=reports)
        assert int(np.asarray(final.round)) == 6
        assert mgr.checkpoints() == [4, 6]  # retention pruned round 2
        assert sum(len(r.get_evaluation(local=True)) for r in reports) == 6

    def test_resume_from_latest(self, tmp_path, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        mgr = CheckpointManager(str(tmp_path / "run"), interval=2, max_to_keep=3)
        mid = mgr.run(sim, st, until_round=4, key=key)
        assert mgr.latest() == 4

        # A fresh manager on the same dir resumes from round 4, not 0.
        mgr2 = CheckpointManager(str(tmp_path / "run"), interval=2, max_to_keep=3)
        final = mgr2.run(sim, sim.init_nodes(jax.random.PRNGKey(9)),
                         until_round=8, key=key)
        assert int(np.asarray(final.round)) == 8

        straight = mgr_free_run(sim, st, 8, key)
        assert states_equal(straight.model, final.model)


def mgr_free_run(sim, st, n_rounds, key):
    st, _ = sim.start(st, n_rounds=n_rounds, key=key)
    return st


class TestRestoreWithoutTemplateKey:
    def test_docstring_usage_works(self, tmp_path, key):
        """restore_checkpoint(path, template) with NO template_key must work
        for checkpoints saved WITH a key (the documented usage)."""
        sim = make_sim()
        st = sim.init_nodes(key)
        path = save_checkpoint(str(tmp_path / "ck"), st, key=key)
        restored, rkey = restore_checkpoint(path, sim.init_nodes(jax.random.PRNGKey(3)))
        assert states_equal(st, restored)
        assert np.array_equal(np.asarray(rkey), np.asarray(key))

    def test_keyless_checkpoint_restores(self, tmp_path, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        path = save_checkpoint(str(tmp_path / "ck"), st)  # no key saved
        restored, rkey = restore_checkpoint(path, sim.init_nodes(jax.random.PRNGKey(3)))
        assert states_equal(st, restored)
        assert rkey is None

    @pytest.mark.parametrize("history_dtype", ["bfloat16", "int8"])
    def test_quantized_ring_roundtrips(self, tmp_path, key, history_dtype):
        """A wire-format history ring (and its int8 scale sidecar)
        checkpoints at its reduced dtype and restores bit-exactly into a
        same-config template; the resumed run equals the unbroken one."""
        import jax.numpy as jnp

        sim = make_sim(history_dtype=history_dtype)
        st0 = sim.init_nodes(key)
        full, _ = sim.start(st0, n_rounds=5, key=key, donate_state=False)

        part, _ = sim.start(st0, n_rounds=2, key=key)
        ring_leaf = jax.tree_util.tree_leaves(part.history_params)[0]
        assert ring_leaf.dtype == (jnp.bfloat16 if history_dtype == "bfloat16"
                                   else jnp.int8)
        path = save_checkpoint(str(tmp_path / "ckpt"), part, key=key)
        template = sim.init_nodes(jax.random.PRNGKey(7))
        restored, rkey = restore_checkpoint(path, template, key)
        assert states_equal(part, restored)
        resumed, _ = sim.start(restored, n_rounds=3, key=rkey)
        assert states_equal(full.model, resumed.model)
