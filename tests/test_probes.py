"""Gossip-dynamics probes: consensus, staleness, mixing diagnostics.

Covers the ISSUE-3 acceptance criteria:

- ``probes=None`` leaves the round program and its report untouched, and
  enabling probes does not perturb the simulated trajectory;
- consensus distance is monotone-decreasing on a connected static
  topology with training disabled (pure averaging);
- the staleness histogram's row sums equal the per-round accepted-message
  counts bit-for-bit (fault-free AND faulty/delayed configs);
- jitted-vs-sequential probe parity on a small topology;
- the report field registry: every array attribute survives
  save → load → concatenate;
- JSONL schema v1/v2/v3 reader versioning and the ``update_probes``
  event stream (replay and live).
"""

import json

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, ConstantDelay, \
    CreateModelMode, Topology, UniformDelay, uniform_mixing
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, WeightedSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import (
    All2AllGossipSimulator,
    GossipSimulator,
    JSONLinesReceiver,
    SequentialGossipSimulator,
    SimulationEventReceiver,
)
from gossipy_tpu.simulation.report import (
    PER_ROUND_FIELDS,
    SimulationReport,
    STATIC_FIELDS,
)
from gossipy_tpu.telemetry import ProbeConfig
from gossipy_tpu.telemetry.probes import consensus_stats, param_layer_names

N, D = 16, 6


def make_data(seed=0, n_samples=320):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_samples, D)).astype(np.float32)
    y = (X @ rng.normal(size=D) > 0).astype(np.int64)
    return X, y


def make_handler(lr=0.1):
    return SGDHandler(model=LogisticRegression(D, 2),
                      loss=losses.cross_entropy, optimizer=optax.sgd(lr),
                      local_epochs=1, batch_size=8, n_classes=2,
                      input_shape=(D,),
                      create_model_mode=CreateModelMode.MERGE_UPDATE)


def make_sim(cls=GossipSimulator, lr=0.1, topo=None, n=N, **kwargs):
    X, y = make_data()
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n, eval_on_user=False)
    topo = topo or Topology.random_regular(n, 4, seed=3)
    return cls(make_handler(lr), topo, disp.stacked(), delta=20,
               protocol=kwargs.pop("protocol", AntiEntropyProtocol.PUSH),
               **kwargs)


def run(sim, rounds=6, key=None, **init_kw):
    key = key if key is not None else jax.random.PRNGKey(0)
    st = sim.init_nodes(key, **init_kw)
    return sim.start(st, n_rounds=rounds, key=key)[1]


class TestProbeConfig:
    def test_coerce(self):
        assert ProbeConfig.coerce(None) is None
        assert ProbeConfig.coerce(False) is None
        assert ProbeConfig.coerce(True) == ProbeConfig()
        cfg = ProbeConfig(consensus=False)
        assert ProbeConfig.coerce(cfg) is cfg
        assert ProbeConfig.coerce(
            ProbeConfig(consensus=False, staleness=False,
                        mixing=False)) is None
        with pytest.raises(TypeError):
            ProbeConfig.coerce("consensus")
        with pytest.raises(ValueError):
            ProbeConfig(staleness_buckets=1)


class TestProbesOffIsUntouched:
    def test_default_report_has_no_probe_fields(self):
        rep = run(make_sim())
        for name in PER_ROUND_FIELDS:
            if name.startswith("probe_"):
                assert getattr(rep, name) is None, name
        assert rep.probe_layer_names is None
        assert rep.to_dict()["probe_consensus_mean"] is None

    def test_probes_do_not_perturb_the_trajectory(self):
        rep_off = run(make_sim())
        rep_on = run(make_sim(probes=True))
        np.testing.assert_array_equal(rep_off.sent_per_round,
                                      rep_on.sent_per_round)
        np.testing.assert_array_equal(rep_off.failed_per_round,
                                      rep_on.failed_per_round)
        np.testing.assert_array_equal(np.asarray(rep_off._global),
                                      np.asarray(rep_on._global))

    def test_probes_off_hlo_identical(self):
        """The probes=None trace is the same program as one built without
        the argument at all (the feature's additions are all behind the
        trace-time gate). Shares the hlo_gate backbone — on divergence the
        first differing instruction is named (scripts/hlo_gate.py runs the
        same pair in CI)."""
        from gossipy_tpu.analysis import assert_identical_hlo
        assert_identical_hlo(make_sim(), make_sim(probes=None),
                             label="probes=None")


class TestConsensus:
    def test_monotone_decreasing_under_pure_averaging(self):
        # lr=0 turns the local update into a no-op on the params: the run
        # is pure gossip averaging, whose consensus distance must decay on
        # a connected static topology (the acceptance-criterion sanity).
        rep = run(make_sim(lr=0.0, probes=True), rounds=25)
        cm = rep.probe_consensus_mean
        assert cm[0] > 0
        diffs = np.diff(cm)
        assert (diffs <= 1e-6 * cm[0]).all(), cm
        assert cm[-1] < 0.2 * cm[0]  # substantial contraction

    def test_per_layer_breakdown_and_names(self):
        rep = run(make_sim(probes=True))
        L = rep.probe_consensus_per_layer.shape[1]
        assert len(rep.probe_layer_names) == L
        assert all(isinstance(s, str) for s in rep.probe_layer_names)
        # Total distance dominates any single layer's mean distance; all
        # finite and non-negative.
        assert (rep.probe_consensus_per_layer >= 0).all()
        assert np.isfinite(rep.probe_consensus_per_layer).all()
        assert (rep.probe_consensus_max + 1e-6
                >= rep.probe_consensus_mean).all()

    def test_consensus_stats_matches_numpy_reference(self):
        rng = np.random.default_rng(0)
        params = {"a": rng.normal(size=(8, 3)).astype(np.float32),
                  "b": rng.normal(size=(8, 2, 2)).astype(np.float32)}
        cm, cx, cl = jax.jit(consensus_stats)(params)
        flat = np.concatenate([params["a"].reshape(8, -1),
                               params["b"].reshape(8, -1)], axis=1)
        dist = np.linalg.norm(flat - flat.mean(0), axis=1)
        assert np.isclose(float(cm), dist.mean(), atol=1e-5)
        assert np.isclose(float(cx), dist.max(), atol=1e-5)
        layer_a = np.linalg.norm(
            params["a"].reshape(8, -1)
            - params["a"].reshape(8, -1).mean(0), axis=1).mean()
        assert np.isclose(float(cl[0]), layer_a, atol=1e-5)
        assert param_layer_names(params) == ["a", "b"]


class TestStaleness:
    def test_hist_sums_to_accepted_count_faulty_delayed(self):
        rep = run(make_sim(probes=True, delay=UniformDelay(0, 60),
                           drop_prob=0.2, online_prob=0.9), rounds=12)
        hist_sums = rep.probe_stale_hist.sum(axis=1)
        accepted = rep.probe_accepted_per_node.sum(axis=1)
        np.testing.assert_array_equal(hist_sums, accepted)
        assert hist_sums.sum() > 0
        assert (rep.probe_stale_max >= 0).all()
        # Mean staleness is consistent with the histogram.
        b = np.arange(rep.probe_stale_hist.shape[1])
        with np.errstate(invalid="ignore"):
            mean_from_hist = (rep.probe_stale_hist * b).sum(1) \
                / np.maximum(hist_sums, 1)
        np.testing.assert_allclose(rep.probe_stale_mean, mean_from_hist,
                                   atol=1e-5)

    def test_zero_delay_is_all_bucket_zero(self):
        rep = run(make_sim(probes=True), rounds=5)
        assert (rep.probe_stale_hist[:, 1:] == 0).all()
        assert (rep.probe_stale_max == 0).all()
        assert (rep.probe_stale_mean == 0).all()

    def test_push_pull_replies_are_counted(self):
        rep = run(make_sim(probes=True,
                           protocol=AntiEntropyProtocol.PUSH_PULL),
                  rounds=5)
        accepted = rep.probe_accepted_per_node.sum(axis=1)
        # PUSH_PULL merges both the pushed model and the reply: strictly
        # more accepted merges than nodes after the pipeline fills.
        assert accepted[2:].min() > N
        np.testing.assert_array_equal(rep.probe_stale_hist.sum(axis=1),
                                      accepted)


class TestMixing:
    def test_expected_fanin_matches_realized_on_fault_free_clique(self):
        rep = run(make_sim(topo=Topology.clique(N), probes=True), rounds=8)
        # Fault-free: every send is accepted; totals are exactly N per
        # round and the expected-fanin vector sums to N.
        np.testing.assert_array_equal(
            rep.probe_accepted_per_node.sum(axis=1), np.full(8, N))
        assert np.isclose(rep.probe_expected_fanin.sum(), N)
        realized = rep.probe_accepted_per_node.mean(axis=0)
        # Uniform sampling on a clique: per-node realized rate within a
        # loose band of the expected 1.0.
        assert abs(realized.mean() - rep.probe_expected_fanin.mean()) < 1e-9

    def test_merge_and_train_deltas_finite_and_gossip_dominates_early(self):
        rep = run(make_sim(probes=True), rounds=6)
        assert np.isfinite(rep.probe_merge_delta).all()
        assert np.isfinite(rep.probe_train_delta).all()
        # Independent random inits: the first rounds' movement is merge-
        # dominated (averaging away init disagreement beats one SGD step).
        assert rep.probe_merge_delta[0] > rep.probe_train_delta[0]

    def test_custom_receive_variant_reports_nan_deltas(self):
        from gossipy_tpu.simulation import PassThroughGossipSimulator
        rep = run(make_sim(cls=PassThroughGossipSimulator, probes=True),
                  rounds=4)
        # PassThrough overrides _receive_rows: the merge/train split is
        # not exact, so the columns are NaN — but counts/staleness live.
        assert np.isnan(rep.probe_merge_delta).all()
        assert np.isnan(rep.probe_train_delta).all()
        assert rep.probe_accepted_per_node.sum() > 0
        # And the NaN columns survive strict-JSON serialization.
        d = rep.to_dict()
        assert d["probe_merge_delta"][0] is None


class TestAll2AllProbes:
    def _run(self, **kwargs):
        X, y = make_data()
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=N, eval_on_user=False)
        topo = Topology.random_regular(N, 4, seed=3)
        handler = WeightedSGDHandler(
            model=LogisticRegression(D, 2), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.1), local_epochs=1, batch_size=8,
            n_classes=2, input_shape=(D,),
            create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim = All2AllGossipSimulator(handler, topo, disp.stacked(),
                                     delta=20, mixing=uniform_mixing(topo),
                                     **kwargs)
        return run(sim, rounds=5)

    def test_accepted_counts_and_hist(self):
        rep = self._run(probes=True)
        # Fault-free sync broadcast: every node receives from every
        # in-neighbor every round.
        np.testing.assert_array_equal(
            rep.probe_accepted_per_node, np.full((5, N), 4))
        np.testing.assert_array_equal(rep.probe_stale_hist[:, 0],
                                      np.full(5, 4 * N))
        np.testing.assert_array_equal(rep.probe_expected_fanin,
                                      np.full(N, 4.0))
        assert np.isfinite(rep.probe_merge_delta).all()
        assert np.isfinite(rep.probe_consensus_mean).all()

    def test_probes_do_not_perturb(self):
        rep_off = self._run()
        rep_on = self._run(probes=True)
        np.testing.assert_array_equal(np.asarray(rep_off._global),
                                      np.asarray(rep_on._global))


class TestSequentialParity:
    """Jitted-vs-sequential probe parity (ISSUE-3 satellite): in the
    deterministic common-init pure-averaging regime the two engines must
    agree — consensus within fp tolerance, staleness histograms and
    accepted-merge counts exactly."""

    def _pair(self, delay, rounds=5):
        reps = {}
        for cls, name in ((GossipSimulator, "jit"),
                          (SequentialGossipSimulator, "seq")):
            sim = make_sim(cls=cls, lr=0.0, topo=Topology.clique(N),
                           probes=True, delay=delay)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key, local_train=False, common_init=True)
            reps[name] = sim.start(st, n_rounds=rounds, key=key)[1]
        return reps["jit"], reps["seq"]

    def test_zero_delay_parity(self):
        jit, seq = self._pair(ConstantDelay(0))
        # Common init + lr 0: all nodes identical forever — consensus is
        # exactly 0 on both engines (fp tolerance per the criterion).
        np.testing.assert_allclose(jit.probe_consensus_mean,
                                   seq.probe_consensus_mean, atol=1e-6)
        np.testing.assert_allclose(jit.probe_merge_delta,
                                   seq.probe_merge_delta, atol=1e-5)
        # Accepted-merge counts and staleness histograms agree EXACTLY
        # (fault-free clique: one accepted merge per node per round).
        np.testing.assert_array_equal(
            jit.probe_accepted_per_node.sum(axis=1),
            seq.probe_accepted_per_node.sum(axis=1))
        np.testing.assert_array_equal(jit.probe_stale_hist,
                                      seq.probe_stale_hist)

    def test_one_round_delay_parity(self):
        # ConstantDelay(delta): every message lands exactly one round
        # later on both engines — staleness is 1 for every accepted
        # message from round 2 on, and round 1 accepts nothing.
        jit, seq = self._pair(ConstantDelay(20))
        np.testing.assert_array_equal(jit.probe_stale_hist,
                                      seq.probe_stale_hist)
        assert jit.probe_stale_hist[0].sum() == 0
        assert (jit.probe_stale_hist[1:, 1] == N).all()
        np.testing.assert_array_equal(
            jit.probe_accepted_per_node.sum(axis=1),
            seq.probe_accepted_per_node.sum(axis=1))
        np.testing.assert_allclose(jit.probe_stale_mean,
                                   seq.probe_stale_mean, atol=1e-6)

    def test_sequential_expected_fanin_matches_engine(self):
        jit, seq = self._pair(ConstantDelay(0), rounds=2)
        np.testing.assert_allclose(jit.probe_expected_fanin,
                                   seq.probe_expected_fanin, atol=1e-9)


class TestReportRegistry:
    def test_every_array_attribute_round_trips(self, tmp_path):
        """The ISSUE-3 registry contract: EVERY ndarray attribute of a
        probe-enabled report must survive save → load → concatenate — a
        new per-round array that is not registered fails here instead of
        being silently dropped."""
        rep = run(make_sim(probes=True, delay=UniformDelay(0, 40)),
                  rounds=5)
        array_attrs = {k: v for k, v in vars(rep).items()
                       if isinstance(v, np.ndarray)}
        assert len(array_attrs) >= 12  # evals, counters, probes...
        path = str(tmp_path / "report.json")
        rep.save(path)
        loaded = SimulationReport.load(path)
        for k, v in array_attrs.items():
            lv = getattr(loaded, k)
            assert lv is not None, f"{k} dropped by save/load"
            np.testing.assert_allclose(
                np.asarray(lv, np.float64), np.asarray(v, np.float64),
                atol=1e-6, equal_nan=True, err_msg=k)
        cat = SimulationReport.concatenate([loaded, loaded])
        for k, v in array_attrs.items():
            if k in ("sent_per_round", "failed_per_round") \
                    or k in PER_ROUND_FIELDS or k in ("_local", "_global"):
                cv = getattr(cat, k)
                assert cv is not None, f"{k} dropped by concatenate"
                assert cv.shape[0] == 2 * v.shape[0], k
        # Static fields carry over from the first segment.
        assert cat.probe_layer_names == rep.probe_layer_names
        np.testing.assert_array_equal(cat.probe_expected_fanin,
                                      rep.probe_expected_fanin)
        # failed_per_cause (dict-valued) concatenates too.
        for c, arr in rep.failed_per_cause.items():
            assert cat.failed_per_cause[c].shape[0] == 2 * arr.shape[0]

    def test_unknown_extra_field_raises(self):
        with pytest.raises(TypeError, match="unknown report field"):
            SimulationReport(metric_names=["accuracy"], local_evals=None,
                             global_evals=None, sent=np.zeros(1),
                             failed=np.zeros(1), total_size=0,
                             probe_new_thing=np.zeros(1))

    def test_registry_names_are_disjoint(self):
        assert not set(PER_ROUND_FIELDS) & set(STATIC_FIELDS)


class ProbeRecorder(SimulationEventReceiver):
    def __init__(self, live=False):
        self.live = live
        self.rows = []

    def update_probes(self, round, probes):
        self.rows.append((round, probes))


class TestEventsAndJSONL:
    def test_update_probes_replay_and_live_agree(self):
        X, y = make_data()
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=N, eval_on_user=False)

        def go(live):
            sim = GossipSimulator(make_handler(), Topology.clique(N),
                                  disp.stacked(), delta=20, probes=True)
            rec = ProbeRecorder(live=live)
            sim.add_receiver(rec)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key)
            sim.start(st, n_rounds=3, key=key)
            return rec.rows

        replay, live = go(False), go(True)
        assert [r for r, _ in replay] == [1, 2, 3]
        assert replay == live
        for _, row in replay:
            assert set(row) >= {"consensus_mean", "stale_hist",
                                "accepted_total", "merge_delta"}
            assert sum(row["stale_hist"]) == row["accepted_total"]

    def test_jsonl_v3_rows_and_version_tolerant_reader(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        sim = make_sim(probes=True)
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rx)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key)
            sim.start(st, n_rounds=3, key=key)
        rows = [JSONLinesReceiver.parse_line(l) for l in open(path)]
        assert all(r["schema"] == JSONLinesReceiver.SCHEMA for r in rows)
        assert all(r["probes"] is not None for r in rows)
        assert all(sum(r["probes"]["stale_hist"])
                   == r["probes"]["accepted_total"] for r in rows)
        # v1..v3 lines (as historic writers produced them) normalize to
        # the CURRENT shape: predating fields come back None, values
        # intact.
        v1 = json.dumps({"schema": 1, "round": 7, "sent": 5, "failed": 1,
                         "size": 10, "local": None, "global": None})
        v2 = json.dumps({"schema": 2, "round": 8, "sent": 5, "failed": 1,
                         "failed_by_cause": {"drop": 1, "offline": 0,
                                             "overflow": 0},
                         "size": 10, "local": None, "global": None})
        v3 = json.dumps({"schema": 3, "round": 9, "sent": 5, "failed": 1,
                         "failed_by_cause": None,
                         "probes": {"consensus_mean": 0.5},
                         "size": 10, "local": None, "global": None})
        r1, r2, r3 = (JSONLinesReceiver.parse_line(v)
                      for v in (v1, v2, v3))
        assert r1["failed_by_cause"] is None and r1["probes"] is None
        assert r1["health"] is None
        assert r1["round"] == 7 and r1["sent"] == 5
        assert r2["failed_by_cause"]["drop"] == 1 and r2["probes"] is None
        assert r2["health"] is None
        # A v3 line predates the health field; its probe row is intact.
        assert r3["health"] is None
        assert r3["probes"]["consensus_mean"] == 0.5
        # A hypothetical future line with unknown fields passes through.
        v9 = json.dumps({"schema": 9, "round": 1, "sent": 0, "failed": 0,
                         "failed_by_cause": None, "probes": None,
                         "size": 0, "local": None, "global": None,
                         "widget": 42})
        assert JSONLinesReceiver.parse_line(v9)["widget"] == 42

    def test_jsonl_without_probes_has_null_probes(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        sim = make_sim()
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rx)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key)
            sim.start(st, n_rounds=2, key=key)
        rows = [JSONLinesReceiver.parse_line(l) for l in open(path)]
        assert all(r["probes"] is None for r in rows)

    def test_probes_summary_lands_in_telemetry_sink(self):
        from gossipy_tpu.telemetry import TelemetrySink, get_sink, set_sink
        prev = set_sink(TelemetrySink())
        try:
            run(make_sim(probes=True), rounds=3)
            evs = get_sink().events(kind="probes_summary")
            assert len(evs) == 1
            assert evs[0].data["accepted_total"] > 0
            assert "consensus_last" in evs[0].data
        finally:
            set_sink(prev)

    def test_manifest_records_probe_config(self):
        sim_on = make_sim(probes=ProbeConfig(staleness_buckets=4))
        sim_off = make_sim()
        assert sim_on.run_manifest().to_dict()["config"]["probes"][
            "staleness_buckets"] == 4
        assert sim_off.run_manifest().to_dict()["config"]["probes"] is None


class TestRepetitionsAndSegments:
    def test_run_repetitions_carries_probes_per_seed(self):
        sim = make_sim(probes=True)
        keys = jax.random.split(jax.random.PRNGKey(0), 3)
        _, reports = sim.run_repetitions(4, keys)
        assert len(reports) == 3
        for rep in reports:
            assert rep.probe_consensus_mean.shape == (4,)
            np.testing.assert_array_equal(
                rep.probe_stale_hist.sum(axis=1),
                rep.probe_accepted_per_node.sum(axis=1))

    def test_segmented_start_concatenates_probe_arrays(self):
        sim = make_sim(probes=True)
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key)
        st, r1 = sim.start(st, n_rounds=3, key=key)
        st, r2 = sim.start(st, n_rounds=2, key=key)
        cat = SimulationReport.concatenate([r1, r2])
        assert cat.probe_consensus_mean.shape == (5,)
        assert cat.probe_stale_hist.shape[0] == 5
