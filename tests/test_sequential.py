"""Sequential high-fidelity engine (simulation/sequential.py): semantics,
per-message events, same-tick token reactions, and agreement with the bulk
engine. The torch-reference comparison lives in the parity lane
(test_sequential_parity.py)."""

import numpy as np
import jax
import optax

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
    Topology, UniformDelay
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.flow_control import SimpleTokenAccount
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import GossipSimulator, \
    SequentialGossipSimulator, SimulationEventReceiver

N, D, DELTA = 16, 12, 20


def make_handler():
    return SGDHandler(model=LogisticRegression(D, 2),
                      loss=losses.cross_entropy, optimizer=optax.sgd(0.1),
                      local_epochs=1, batch_size=32, n_classes=2,
                      input_shape=(D,),
                      create_model_mode=CreateModelMode.MERGE_UPDATE)


def make_parts(seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(480, D)).astype(np.float32)
    y = (X @ rng.normal(size=D) > 0).astype(np.int64)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=N, eval_on_user=False)
    return disp.stacked(), Topology.random_regular(N, 6, seed=7)


class Recorder(SimulationEventReceiver):
    def __init__(self):
        self.sent = []      # (t, round, sender, receiver, type)
        self.failed = 0
        self.rounds = 0

    def update_single_message(self, failed, m):
        if failed:
            self.failed += 1
        else:
            self.sent.append((m.t, m.round, m.sender, m.receiver, m.msg_type))

    def update_timestep(self, r):
        self.rounds += 1


class TestSequentialSemantics:
    def test_push_message_accounting_and_per_message_events(self, key):
        data, topo = make_parts()
        sim = SequentialGossipSimulator(make_handler(), topo, data,
                                        delta=DELTA)
        rec = Recorder()
        sim.add_receiver(rec)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=6, key=jax.random.fold_in(key, 1))
        # Every sync node fires exactly once per round; no faults => every
        # send is also a per-message observer event (reference
        # notify_message granularity).
        assert report.sent_messages == 6 * N
        assert len(rec.sent) == 6 * N
        assert rec.failed == 0
        assert rec.rounds == 6
        # Learning happens through the public metric surface.
        acc = report.curves(local=False)["accuracy"]
        assert np.isfinite(acc).all()
        assert acc[-1] > acc[0]

    def test_faults_counted(self, key):
        data, topo = make_parts()
        sim = SequentialGossipSimulator(make_handler(), topo, data,
                                        delta=DELTA, drop_prob=0.3,
                                        online_prob=0.7,
                                        delay=UniformDelay(0, 30))
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=6, key=jax.random.fold_in(key, 1))
        assert report.failed_messages > 0
        assert np.isfinite(report.curves(local=False)["accuracy"]).all()

    def test_push_pull_replies_flow(self, key):
        data, topo = make_parts()
        sim = SequentialGossipSimulator(make_handler(), topo, data,
                                        delta=DELTA,
                                        protocol=AntiEntropyProtocol.PUSH_PULL)
        rec = Recorder()
        sim.add_receiver(rec)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=5, key=jax.random.fold_in(key, 1))
        from gossipy_tpu.core import MessageType
        replies = [m for m in rec.sent if m[4] == MessageType.REPLY]
        assert len(replies) > 0
        # Replies counted in the totals (reference counts both legs).
        assert report.sent_messages == len(rec.sent)

    def test_tokenized_same_tick_reactions(self, key):
        data, topo = make_parts()
        sim = SequentialGossipSimulator(
            make_handler(), topo, data, delta=DELTA,
            token_account=SimpleTokenAccount(C=4))
        rec = Recorder()
        sim.add_receiver(rec)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=6, key=jax.random.fold_in(key, 1))
        # A reaction is emitted at the RECEIVE tick, which (zero delay) is
        # the trigger's send tick — so some send happens at a tick that is
        # NOT the sender's own phase offset. The bulk engine can only
        # deliver reactions next round; this is the same-tick fidelity the
        # mode exists for (reference simul.py:631-648).
        phases = st.phase
        off_phase = [m for m in rec.sent
                     if m[0] % DELTA != int(phases[m[2]])]
        assert len(off_phase) > 0, "no same-tick reactive sends observed"

    def test_isolated_node_skips_not_aborts(self, key):
        # Reference bug: an isolated sender `break`s the whole send sweep
        # (simul.py:398-399); here it only skips itself — everyone else
        # still sends every round.
        adj = np.zeros((4, 4))
        adj[0, 1] = adj[1, 0] = adj[1, 2] = adj[2, 1] = 1
        rng = np.random.default_rng(0)
        X = rng.normal(size=(80, D)).astype(np.float32)
        y = (X @ rng.normal(size=D) > 0).astype(np.int64)
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=4, eval_on_user=False)
        sim = SequentialGossipSimulator(make_handler(), Topology(adj),
                                        disp.stacked(), delta=DELTA)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=4, key=jax.random.fold_in(key, 1))
        assert report.sent_messages == 4 * 3  # node 3 isolated, 3 senders


class TestSequentialVsBulk:
    def test_mean_curves_agree(self, key):
        """The two engines' divergences (snapshots, next-round reactions)
        are bounded: 3-seed mean accuracy curves agree within 0.06 on the
        small config (measured gap ~0.03; sequential runs slightly ahead
        late — in-round freshness propagates information faster)."""
        data, topo = make_parts()
        seq, blk = [], []
        for s in range(3):
            k = jax.random.PRNGKey(100 + s)
            sim_s = SequentialGossipSimulator(make_handler(), topo, data,
                                              delta=DELTA)
            st = sim_s.init_nodes(k)
            _, rp = sim_s.start(st, n_rounds=8, key=jax.random.fold_in(k, 1))
            seq.append(rp.curves(local=False)["accuracy"])
            sim_b = GossipSimulator(make_handler(), topo, data, delta=DELTA)
            stb = sim_b.init_nodes(k)
            _, rb = sim_b.start(stb, n_rounds=8, key=jax.random.fold_in(k, 1))
            blk.append(rb.curves(local=False)["accuracy"])
        gap = np.max(np.abs(np.mean(seq, 0) - np.mean(blk, 0)))
        assert gap < 0.06, f"sequential/bulk mean-curve gap {gap:.3f}"
        # Same message volume on the fault-free PUSH config.
        assert rp.sent_messages == rb.sent_messages
