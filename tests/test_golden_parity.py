"""Golden parity: our engine vs the actual reference implementation.

Runs the SAME config (data, model, topology, protocol, hyperparameters)
through the reference's eager PyTorch simulator (imported from
/root/reference) and through the jitted gossipy_tpu engine, and compares the
learning outcomes. Bitwise transcripts cannot match (bulk-synchronous rounds
vs the reference's shuffled sequential loop, different RNGs — SURVEY.md
§7(c)), so the contract is distributional: both must learn the task to the
same quality band.
"""

import sys
import types

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import GossipSimulator

# Torch-reference comparisons dominate the suite's wall-clock; they run in
# the opt-in second lane (`pytest -m parity`) so the default lane stays fast.
pytestmark = pytest.mark.parity

N_NODES = 16
D = 12
ROUNDS = 6


def make_dataset(n=480, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=D)
    X = rng.normal(size=(n, D)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    return X, y


def import_reference():
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    # gossipy.data imports torchvision at module import purely for its
    # download helpers; stub it (absent in this image).
    if "torchvision" not in sys.modules:
        tv = types.ModuleType("torchvision")
        tv.datasets = types.ModuleType("torchvision.datasets")
        tv.transforms = types.ModuleType("torchvision.transforms")
        sys.modules["torchvision"] = tv
        sys.modules["torchvision.datasets"] = tv.datasets
        sys.modules["torchvision.transforms"] = tv.transforms
    import gossipy  # noqa: F401
    # Newer sklearn returns plain floats from roc_auc_score; the reference
    # calls .astype on the result (handler.py:328).
    import gossipy.model.handler as mh
    if not getattr(mh, "_auc_shimmed", False):
        orig = mh.roc_auc_score
        mh.roc_auc_score = lambda *a, **k: np.float64(orig(*a, **k))
        mh._auc_shimmed = True
    return True


def make_sent_per_round_receiver(delta: int, rounds: int):
    """Reference-side per-message counter -> per-round sent-count curve
    (shared by the envelope and sequential parity suites). Requires
    ``import_reference()`` to have run."""
    import numpy as _np
    from gossipy.simul import SimulationEventReceiver as RefRx

    class SentPerRound(RefRx):
        def __init__(self):
            self.counts = _np.zeros(rounds, _np.int64)

        def update_message(self, failed, msg=None):
            if not failed and msg is not None:
                r = int(msg.timestamp) // delta
                if r < rounds:
                    self.counts[r] += 1

        def update_timestep(self, t):  # abstract in the reference ABC
            pass

        def update_end(self):
            pass

    return SentPerRound()


def run_reference(X, y) -> float:
    """Final global test accuracy from the reference simulator."""
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = TorchModelHandler(
        net=RefLogReg(D, 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.5}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=8,
        create_model_mode=RefMode.MERGE_UPDATE)
    nodes = GossipNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    report = SimulationReport()
    sim.add_receiver(report)
    sim.init_nodes(seed=42)
    import contextlib
    import io
    with contextlib.redirect_stdout(io.StringIO()):
        sim.start(n_rounds=ROUNDS)
    evals = report.get_evaluation(False)
    return float(evals[-1][1]["accuracy"])


def run_ours(X, y) -> float:
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(D, 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                         local_epochs=1, batch_size=8, n_classes=2,
                         input_shape=(D,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = GossipSimulator(handler, Topology.clique(N_NODES), disp.stacked(),
                          delta=20, protocol=AntiEntropyProtocol.PUSH)
    key = jax.random.PRNGKey(42)
    st = sim.init_nodes(key)
    st, report = sim.start(st, n_rounds=ROUNDS, key=key)
    return float(report.curves(local=False)["accuracy"][-1])


def run_reference_pegasos(X, y) -> float:
    """Reference Pegasos config (main_ormandi_2013.py:21-53 at small scale:
    +/-1 labels, AdaLine weights, clique, PUSH, no faults)."""
    import contextlib
    import io

    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import PegasosHandler as RefPegasos
    from gossipy.model.nn import AdaLine as RefAdaLine
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    y_pm = 2 * y - 1  # main_ormandi_2013.py:25
    dh = RefCDH(torch.tensor(X), torch.tensor(y_pm, dtype=torch.float32),
                test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = RefPegasos(net=RefAdaLine(D), learning_rate=0.01,
                       create_model_mode=RefMode.UPDATE)
    nodes = GossipNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    report = SimulationReport()
    sim.add_receiver(report)
    sim.init_nodes(seed=42)
    with contextlib.redirect_stdout(io.StringIO()):
        sim.start(n_rounds=ROUNDS)
    return float(report.get_evaluation(False)[-1][1]["accuracy"])


def run_ours_pegasos(X, y) -> float:
    from gossipy_tpu.handlers import PegasosHandler
    from gossipy_tpu.models import AdaLine

    y_pm = (2 * y - 1).astype(np.float32)
    dh = ClassificationDataHandler(X, y_pm, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = PegasosHandler(AdaLine(D), 0.01,
                             create_model_mode=CreateModelMode.UPDATE)
    sim = GossipSimulator(handler, Topology.clique(N_NODES), disp.stacked(),
                          delta=20, protocol=AntiEntropyProtocol.PUSH)
    key = jax.random.PRNGKey(42)
    st = sim.init_nodes(key)
    st, report = sim.start(st, n_rounds=ROUNDS, key=key)
    return float(report.curves(local=False)["accuracy"][-1])


def run_reference_tokenized_partitioned(X, y) -> float:
    """Reference Hegedus-2021 config at small scale: partitioned LogReg
    exchange + randomized token accounts (main_hegedus_2021.py:28-69)."""
    import contextlib
    import io

    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.flow_control import RandomizedTokenAccount as RefRTA
    from gossipy.model.handler import PartitionedTMH
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.model.sampling import TorchModelPartition
    from gossipy.node import PartitioningBasedNode
    from gossipy.simul import SimulationReport, TokenizedGossipSimulator as RefTGS

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    net = RefLogReg(D, 2)
    proto = PartitionedTMH(
        net=net, tm_partition=TorchModelPartition(net, 4),
        optimizer=torch.optim.SGD,
        optimizer_params={"lr": 1, "weight_decay": 0.001},
        criterion=torch.nn.CrossEntropyLoss(),
        create_model_mode=RefMode.UPDATE)
    nodes = PartitioningBasedNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True)
    sim = RefTGS(nodes=nodes, data_dispatcher=disp,
                 token_account=RefRTA(C=20, A=10),
                 utility_fun=lambda mh1, mh2, msg: 1,
                 delta=20, protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    report = SimulationReport()
    sim.add_receiver(report)
    sim.init_nodes(seed=42)
    with contextlib.redirect_stdout(io.StringIO()):
        sim.start(n_rounds=TOKEN_ROUNDS)
    return float(report.get_evaluation(False)[-1][1]["accuracy"])


# Token accounts throttle early sends (the proactive ramp starts below
# capacity), so this config needs more rounds than the plain ones to reach
# a stable accuracy band.
TOKEN_ROUNDS = 36


def run_ours_tokenized_partitioned(X, y) -> float:
    from gossipy_tpu.compression import ModelPartition
    from gossipy_tpu.flow_control import RandomizedTokenAccount
    from gossipy_tpu.handlers import PartitionedSGDHandler
    from gossipy_tpu.simulation import TokenizedPartitioningGossipSimulator

    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    model = LogisticRegression(D, 2)
    template = model.init(jax.random.PRNGKey(0),
                          jax.numpy.zeros((1, D)))["params"]
    handler = PartitionedSGDHandler(
        ModelPartition(template, 4), model=model, loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(0.001), optax.sgd(1.0)),
        local_epochs=1, batch_size=8, n_classes=2, input_shape=(D,),
        create_model_mode=CreateModelMode.UPDATE)
    sim = TokenizedPartitioningGossipSimulator(
        handler, Topology.clique(N_NODES), disp.stacked(), delta=20,
        protocol=AntiEntropyProtocol.PUSH,
        token_account=RandomizedTokenAccount(C=20, A=10))
    key = jax.random.PRNGKey(42)
    st = sim.init_nodes(key)
    st, report = sim.start(st, n_rounds=TOKEN_ROUNDS, key=key)
    return float(report.curves(local=False)["accuracy"][-1])


class TestGoldenParity:
    def test_same_config_same_quality(self):
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset()
        acc_ref = run_reference(X, y)
        acc_ours = run_ours(X, y)
        # Both sides must actually learn, and land in the same band.
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_tokenized_partitioned_same_quality(self):
        """Hegedus-2021-style partitioned exchange + token accounts."""
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=2)
        acc_ref = run_reference_tokenized_partitioned(X, y)
        acc_ours = run_ours_tokenized_partitioned(X, y)
        # The token ramp throttles early communication, so absolute accuracy
        # at TOKEN_ROUNDS is modest on both sides; the contract is the same
        # quality band, clearly above chance (0.5).
        assert abs(acc_ours - acc_ref) < 0.12, (acc_ours, acc_ref)
        assert acc_ref > 0.6, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.6, f"ours failed to learn: {acc_ours}"

    def test_pegasos_same_quality(self):
        """Ormandi-2013-style Pegasos SVM: reference vs ours on one config."""
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=1)
        acc_ref = run_reference_pegasos(X, y)
        acc_ours = run_ours_pegasos(X, y)
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)
