"""Test configuration: run on CPU with 8 virtual devices.

Multi-chip TPU hardware is not available in CI; sharded code paths run on
``--xla_force_host_platform_device_count=8`` CPU devices — the same XLA
partitioner and collectives as a real mesh.

The environment may pre-initialize a TPU backend at interpreter startup via a
sitecustomize hook on PYTHONPATH (so setting env vars here would be too
late). In that case we re-exec pytest once with a cleaned environment. The
re-exec happens in ``pytest_configure`` with global capture stopped so the
child process writes to the real stdout/stderr.
"""

import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from _virtual_mesh import TEST_DEVICE_COUNT, provisioned_device_count, \
    virtual_mesh_env  # noqa: E402 (jax-free; safe before re-exec)


def _needs_reexec() -> bool:
    if os.environ.get("_GOSSIPY_TPU_TEST_REEXEC") == "1":
        return False
    return (os.environ.get("JAX_PLATFORMS") != "cpu"
            or provisioned_device_count(os.environ.get("XLA_FLAGS", ""))
            != TEST_DEVICE_COUNT)


_DO_REEXEC = _needs_reexec()

if not _DO_REEXEC:
    import jax

    assert jax.default_backend() == "cpu", \
        f"tests must run on CPU, got {jax.default_backend()}"


def pytest_configure(config):
    if not _DO_REEXEC:
        # Persistent XLA compilation cache: the suite's wall-clock is
        # dominated by per-config scan compiles; identical HLO across runs
        # (and across same-shaped tests) loads from disk instead.
        from gossipy_tpu import enable_compilation_cache
        enable_compilation_cache()
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    env = virtual_mesh_env(TEST_DEVICE_COUNT)
    env["_GOSSIPY_TPU_TEST_REEXEC"] = "1"
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)


@pytest.fixture
def key():
    import jax
    return jax.random.PRNGKey(0)
