"""Test configuration: run on CPU with 8 virtual devices.

Multi-chip TPU hardware is not available in CI; sharded code paths (as they
land) run on ``--xla_force_host_platform_device_count=8`` CPU devices — the
same XLA partitioner and collectives as a real mesh.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)
import pytest  # noqa: E402


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)
