"""Test configuration: run on CPU with 8 virtual devices.

Multi-chip TPU hardware is not available in CI; sharded code paths run on
``--xla_force_host_platform_device_count=8`` CPU devices — the same XLA
partitioner and collectives as a real mesh.

The environment may pre-initialize a TPU backend at interpreter startup via a
sitecustomize hook on PYTHONPATH (so setting env vars here would be too
late). In that case we re-exec pytest once with a cleaned environment. The
re-exec happens in ``pytest_configure`` with global capture stopped so the
child process writes to the real stdout/stderr.
"""

import os
import sys

import pytest

_WANT_FLAG = "--xla_force_host_platform_device_count=8"


def _needs_reexec() -> bool:
    if os.environ.get("_GOSSIPY_TPU_TEST_REEXEC") == "1":
        return False
    return (os.environ.get("JAX_PLATFORMS") != "cpu"
            or _WANT_FLAG not in os.environ.get("XLA_FLAGS", ""))


_DO_REEXEC = _needs_reexec()

if not _DO_REEXEC:
    import jax

    assert jax.default_backend() == "cpu", \
        f"tests must run on CPU, got {jax.default_backend()}"


def pytest_configure(config):
    if not _DO_REEXEC:
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + _WANT_FLAG).strip()
    env["_GOSSIPY_TPU_TEST_REEXEC"] = "1"
    # Drop TPU-plugin sitecustomize entries (e.g. .axon_site) so the child
    # interpreter starts clean on CPU.
    path_entries = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                    if p and "axon" not in p]
    env["PYTHONPATH"] = os.pathsep.join(path_entries)
    sys.stdout.flush()
    sys.stderr.flush()
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)


@pytest.fixture
def key():
    import jax
    return jax.random.PRNGKey(0)
