"""Token-account policies vs the reference formulas (flow_control.py:85-236)."""

import jax
import jax.numpy as jnp
import numpy as np

from gossipy_tpu.flow_control import (
    GeneralizedTokenAccount,
    PurelyProactiveTokenAccount,
    PurelyReactiveTokenAccount,
    RandomizedTokenAccount,
    SimpleTokenAccount,
)


def test_purely_proactive():
    a = PurelyProactiveTokenAccount()
    b = jnp.asarray([0, 5, 100])
    assert (np.asarray(a.proactive(b)) == 1.0).all()
    assert (np.asarray(a.reactive(b, jnp.ones(3), jax.random.PRNGKey(0))) == 0).all()


def test_purely_reactive():
    a = PurelyReactiveTokenAccount(k=3)
    b = jnp.asarray([0, 5, 100])
    assert (np.asarray(a.proactive(b)) == 0.0).all()
    u = jnp.asarray([0.0, 1.0, 2.0])
    assert list(np.asarray(a.reactive(b, u, jax.random.PRNGKey(0)))) == [0, 3, 6]


def test_simple_token_account():
    a = SimpleTokenAccount(C=3)
    b = jnp.asarray([0, 2, 3, 7])
    assert list(np.asarray(a.proactive(b))) == [0.0, 0.0, 1.0, 1.0]
    u = jnp.ones(4)
    assert list(np.asarray(a.reactive(b, u, jax.random.PRNGKey(0)))) == [0, 1, 1, 1]


def test_generalized_reactive_formula():
    a = GeneralizedTokenAccount(C=20, A=4)
    balance = jnp.arange(0, 25)
    useful = a.reactive(balance, jnp.ones(25), jax.random.PRNGKey(0))
    useless = a.reactive(balance, jnp.zeros(25), jax.random.PRNGKey(0))
    for i in range(25):
        # reference flow_control.py:187-189
        assert int(useful[i]) == (4 - 1 + i) // 4
        assert int(useless[i]) == (4 - 1 + i) // 8


def test_randomized_proactive_ramp():
    a = RandomizedTokenAccount(C=20, A=10)
    b = jnp.asarray([0, 8, 9, 15, 20, 25])
    p = np.asarray(a.proactive(b))
    # reference flow_control.py:223-229: 0 below A-1, linear to C, then 1.
    assert p[0] == 0.0 and p[1] == 0.0
    assert np.isclose(p[2], 0.0)
    assert np.isclose(p[3], (15 - 9) / 11)
    assert np.isclose(p[4], 1.0)
    assert p[5] == 1.0


def test_randomized_reactive_rand_round():
    a = RandomizedTokenAccount(C=20, A=10)
    key = jax.random.PRNGKey(0)
    balance = jnp.full((2000,), 15)  # r = 1.5 -> mean reaction 1.5
    r = np.asarray(a.reactive(balance, jnp.ones(2000), key))
    assert set(np.unique(r)).issubset({1, 2})
    assert abs(r.mean() - 1.5) < 0.1
    # Useless messages never trigger reactions (flow_control.py:232-236).
    r0 = np.asarray(a.reactive(balance, jnp.zeros(2000), key))
    assert (r0 == 0).all()
