"""Pallas fused gather+merge kernel tests (gossipy_tpu/ops)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology, UniformDelay
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import LimitedMergeSGDHandler, SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.ops import (gather_merge_flat, gather_merge_multi,
                             gather_merge_multi_pytree, gather_merge_pytree)
from gossipy_tpu.ops.merge import gather_merge_reference
from gossipy_tpu.simulation import GossipSimulator


class TestKernel:
    @pytest.mark.parametrize("n,m,f", [(16, 48, 116), (8, 8, 512), (5, 10, 1),
                                       (32, 96, 640)])
    def test_matches_reference(self, n, m, f):
        rng = np.random.default_rng(0)
        p = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
        h = jnp.asarray(rng.normal(size=(m, f)).astype(np.float32))
        idx = jnp.asarray(rng.integers(0, m, n).astype(np.int32))
        w1 = jnp.asarray(rng.uniform(size=n).astype(np.float32))
        got = gather_merge_flat(p, h, idx, w1, 1.0 - w1)
        want = gather_merge_reference(p, h, idx, w1, 1.0 - w1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("wire", ["float32", "bfloat16", "int8"])
    @pytest.mark.parametrize("n,m,f,k", [(16, 48, 116, 4), (8, 8, 512, 2),
                                         (5, 10, 1, 3)])
    def test_multi_matches_iterated_flat(self, n, m, f, k, wire):
        """The K-slot kernel's left-to-right fold must be BIT-identical to
        iterating the single-slot kernel K times — including zero-weight
        (empty) slots, which both paths hard-mask."""
        rng = np.random.default_rng(2)
        p = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
        scale = None
        if wire == "int8":
            h = jnp.asarray(rng.integers(-127, 128, (m, f)).astype(np.int8))
            scale = jnp.asarray(rng.uniform(0.01, 2.0, m).astype(np.float32))
        else:
            h = jnp.asarray(rng.normal(size=(m, f)).astype(np.float32)
                            ).astype(jnp.dtype(wire))
        idx = jnp.asarray(rng.integers(0, m, (n, k)).astype(np.int32))
        wp = jnp.asarray(rng.uniform(size=(n, k)).astype(np.float32))
        # ~1/3 of the slots empty: (w_self, w_peer) = (1, 0).
        empty = rng.uniform(size=(n, k)) < 0.34
        wp = jnp.where(jnp.asarray(empty), 0.0, wp)
        ws = 1.0 - wp
        got = gather_merge_multi(p, h, idx, ws, wp, scale=scale)
        want = p
        for j in range(k):
            want = gather_merge_flat(want, h, idx[:, j], ws[:, j], wp[:, j],
                                     scale=scale)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_multi_k1_matches_flat(self):
        rng = np.random.default_rng(3)
        n, m, f = 12, 24, 70
        p = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
        h = jnp.asarray(rng.normal(size=(m, f)).astype(np.float32))
        idx = jnp.asarray(rng.integers(0, m, n).astype(np.int32))
        wp = jnp.asarray(rng.uniform(size=n).astype(np.float32))
        got = gather_merge_multi(p, h, idx[:, None], (1 - wp)[:, None],
                                 wp[:, None])
        want = gather_merge_flat(p, h, idx, 1 - wp, wp)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_multi_empty_slot_inert_to_nonfinite_rows(self):
        """A garbage (NaN/inf) ring row behind an empty slot's clipped
        index must not leak: zero-weight slots are where-masked, not
        multiplied."""
        rng = np.random.default_rng(4)
        n, m, f, k = 6, 12, 40, 3
        p = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
        h = np.asarray(rng.normal(size=(m, f)).astype(np.float32))
        h[0] = np.nan
        h[1] = np.inf
        h = jnp.asarray(h)
        # Slot 0 live pointing at clean rows; slots 1..k empty pointing at
        # the poisoned rows.
        idx = np.full((n, k), 0, np.int32)
        idx[:, 0] = rng.integers(2, m, n)
        wp = np.zeros((n, k), np.float32)
        wp[:, 0] = 0.5
        out = gather_merge_multi(p, h, jnp.asarray(idx),
                                 jnp.asarray(1 - wp), jnp.asarray(wp))
        assert np.isfinite(np.asarray(out)).all()
        want = gather_merge_flat(p, h, jnp.asarray(idx[:, 0]),
                                 jnp.asarray(1 - wp[:, 0]),
                                 jnp.asarray(wp[:, 0]))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))

    def test_pytree_form(self):
        rng = np.random.default_rng(1)
        n, d_hist = 6, 3
        params = {"w": jnp.asarray(rng.normal(size=(n, 4, 5)).astype(np.float32)),
                  "b": jnp.asarray(rng.normal(size=(n, 5)).astype(np.float32))}
        hist = jax.tree.map(
            lambda l: jnp.asarray(rng.normal(
                size=(d_hist,) + l.shape).astype(np.float32)), params)
        flat_idx = jnp.asarray(rng.integers(0, d_hist * n, n).astype(np.int32))
        w1 = jnp.full((n,), 0.5, jnp.float32)
        out = gather_merge_pytree(params, hist, flat_idx, w1, 1.0 - w1)
        for k in params:
            hflat = hist[k].reshape((d_hist * n,) + params[k].shape[1:])
            want = 0.5 * params[k] + 0.5 * hflat[flat_idx]
            np.testing.assert_allclose(np.asarray(out[k]), np.asarray(want),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("wire", ["float32", "int8"])
    def test_multi_pytree_single_launch_matches_leafwise(self, wire):
        """The concat single-launch pytree form (all leaves in one [N,
        sum(F)] matrix; int8 routes per-leaf scales through the
        block->leaf map) must be BIT-identical to iterating the
        single-slot kernel per leaf per slot."""
        rng = np.random.default_rng(5)
        n, d_hist, k = 8, 3, 4
        shapes = {"w": (7, 11), "b": (5,)}
        params = {kk: jnp.asarray(rng.normal(size=(n,) + s)
                                  .astype(np.float32))
                  for kk, s in shapes.items()}
        scales = None
        if wire == "int8":
            hist = {kk: jnp.asarray(rng.integers(
                -127, 128, (d_hist, n) + s).astype(np.int8))
                for kk, s in shapes.items()}
            scales = {kk: jnp.asarray(rng.uniform(
                0.01, 2.0, (d_hist, n)).astype(np.float32))
                for kk in shapes}
        else:
            hist = {kk: jnp.asarray(rng.normal(
                size=(d_hist, n) + s).astype(np.float32))
                for kk, s in shapes.items()}
        flat_idx = jnp.asarray(rng.integers(0, d_hist * n, (n, k))
                               .astype(np.int32))
        wp = jnp.asarray(rng.uniform(size=(n, k)).astype(np.float32))
        wp = jnp.where(jnp.asarray(rng.uniform(size=(n, k)) < 0.3), 0.0, wp)
        ws = 1.0 - wp
        out = gather_merge_multi_pytree(params, hist, flat_idx, ws, wp,
                                        scales=scales)
        for kk, s in shapes.items():
            f = int(np.prod(s))
            want = params[kk].reshape(n, f)
            hflat = hist[kk].reshape(d_hist * n, f)
            sflat = (None if scales is None
                     else scales[kk].reshape(d_hist * n))
            for j in range(k):
                want = gather_merge_flat(want, hflat, flat_idx[:, j],
                                         ws[:, j], wp[:, j], scale=sflat)
            np.testing.assert_array_equal(
                np.asarray(out[kk]).reshape(n, f), np.asarray(want))


def make_sim(fused, key, d=8, n_nodes=12, **kw):
    rng = np.random.default_rng(3)
    w = rng.normal(size=d)
    X = rng.normal(size=(240, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes)
    handler = SGDHandler(model=LogisticRegression(d, 2), loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
                         n_classes=2, input_shape=(d,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    return GossipSimulator(handler, Topology.clique(n_nodes), disp.stacked(),
                           delta=10, fused_merge=fused, **kw)


class TestEngineFusedPath:
    def test_fused_equals_unfused(self, key):
        """The per-slot fused pallas deliver must reproduce the gather+blend
        path (same PRNG streams; fp reassociation only). Pinned to
        "per_slot": on a clique fan-in exceeds 1, where the default
        "multi" path applies the documented compound-merge semantics
        (merge all slots, train once) instead of interleaving — its
        parity contract lives in test_fused_deliver.py on fan-in-1
        topologies."""
        sim_a = make_sim(False, key)
        sim_b = make_sim("per_slot", key)
        st_a = sim_a.init_nodes(key)
        st_b = sim_b.init_nodes(key)
        fa, ra = sim_a.start(st_a, n_rounds=6, key=key)
        fb, rb = sim_b.start(st_b, n_rounds=6, key=key)
        for la, lb in zip(jax.tree_util.tree_leaves(fa.model.params),
                          jax.tree_util.tree_leaves(fb.model.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(ra.curves(local=False)["accuracy"],
                                   rb.curves(local=False)["accuracy"],
                                   rtol=1e-4, atol=1e-5)

    def test_fused_with_delays_and_replies(self, key):
        sim = make_sim(True, key, protocol=AntiEntropyProtocol.PUSH_PULL,
                       delay=UniformDelay(0, 15))
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=8, key=key)
        assert rep.curves(local=False)["accuracy"][-1] > 0.8

    def test_fused_rejects_non_uniform_merge_handler(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(60, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=4)
        handler = LimitedMergeSGDHandler(
            model=LogisticRegression(4, 2), loss=losses.cross_entropy,
            n_classes=2, input_shape=(4,))
        with pytest.raises(AssertionError):
            GossipSimulator(handler, Topology.clique(4), disp.stacked(),
                            fused_merge=True)
