"""Pallas fused gather+merge kernel tests (gossipy_tpu/ops)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology, UniformDelay
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import LimitedMergeSGDHandler, SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.ops import gather_merge_flat, gather_merge_pytree
from gossipy_tpu.ops.merge import gather_merge_reference
from gossipy_tpu.simulation import GossipSimulator


class TestKernel:
    @pytest.mark.parametrize("n,m,f", [(16, 48, 116), (8, 8, 512), (5, 10, 1),
                                       (32, 96, 640)])
    def test_matches_reference(self, n, m, f):
        rng = np.random.default_rng(0)
        p = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
        h = jnp.asarray(rng.normal(size=(m, f)).astype(np.float32))
        idx = jnp.asarray(rng.integers(0, m, n).astype(np.int32))
        w1 = jnp.asarray(rng.uniform(size=n).astype(np.float32))
        got = gather_merge_flat(p, h, idx, w1, 1.0 - w1)
        want = gather_merge_reference(p, h, idx, w1, 1.0 - w1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_pytree_form(self):
        rng = np.random.default_rng(1)
        n, d_hist = 6, 3
        params = {"w": jnp.asarray(rng.normal(size=(n, 4, 5)).astype(np.float32)),
                  "b": jnp.asarray(rng.normal(size=(n, 5)).astype(np.float32))}
        hist = jax.tree.map(
            lambda l: jnp.asarray(rng.normal(
                size=(d_hist,) + l.shape).astype(np.float32)), params)
        flat_idx = jnp.asarray(rng.integers(0, d_hist * n, n).astype(np.int32))
        w1 = jnp.full((n,), 0.5, jnp.float32)
        out = gather_merge_pytree(params, hist, flat_idx, w1, 1.0 - w1)
        for k in params:
            hflat = hist[k].reshape((d_hist * n,) + params[k].shape[1:])
            want = 0.5 * params[k] + 0.5 * hflat[flat_idx]
            np.testing.assert_allclose(np.asarray(out[k]), np.asarray(want),
                                       rtol=1e-5, atol=1e-6)


def make_sim(fused, key, d=8, n_nodes=12, **kw):
    rng = np.random.default_rng(3)
    w = rng.normal(size=d)
    X = rng.normal(size=(240, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes)
    handler = SGDHandler(model=LogisticRegression(d, 2), loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
                         n_classes=2, input_shape=(d,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    return GossipSimulator(handler, Topology.clique(n_nodes), disp.stacked(),
                           delta=10, fused_merge=fused, **kw)


class TestEngineFusedPath:
    def test_fused_equals_unfused(self, key):
        """The fused pallas deliver path must reproduce the gather+blend path
        (same PRNG streams; fp reassociation only)."""
        sim_a = make_sim(False, key)
        sim_b = make_sim(True, key)
        st_a = sim_a.init_nodes(key)
        st_b = sim_b.init_nodes(key)
        fa, ra = sim_a.start(st_a, n_rounds=6, key=key)
        fb, rb = sim_b.start(st_b, n_rounds=6, key=key)
        for la, lb in zip(jax.tree_util.tree_leaves(fa.model.params),
                          jax.tree_util.tree_leaves(fb.model.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(ra.curves(local=False)["accuracy"],
                                   rb.curves(local=False)["accuracy"],
                                   rtol=1e-4, atol=1e-5)

    def test_fused_with_delays_and_replies(self, key):
        sim = make_sim(True, key, protocol=AntiEntropyProtocol.PUSH_PULL,
                       delay=UniformDelay(0, 15))
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=8, key=key)
        assert rep.curves(local=False)["accuracy"][-1] > 0.8

    def test_fused_rejects_non_uniform_merge_handler(self):
        rng = np.random.default_rng(3)
        X = rng.normal(size=(60, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int64)
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=4)
        handler = LimitedMergeSGDHandler(
            model=LogisticRegression(4, 2), loss=losses.cross_entropy,
            n_classes=2, input_shape=(4,))
        with pytest.raises(AssertionError):
            GossipSimulator(handler, Topology.clique(4), disp.stacked(),
                            fused_merge=True)
