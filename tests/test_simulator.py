"""End-to-end simulator tests: the minimum slice (SURVEY §7 stage 3) and up."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.core import (
    AntiEntropyProtocol,
    CreateModelMode,
    Topology,
    UniformDelay,
)
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import PegasosHandler, SGDHandler, losses
from gossipy_tpu.models import AdaLine, LogisticRegression
from gossipy_tpu.simulation import GossipSimulator


def make_dataset(n=400, d=10, seed=0, signed=False):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    if signed:
        y = (2 * y - 1).astype(np.float32)
    return X, y


def make_sim(n_nodes=16, protocol=AntiEntropyProtocol.PUSH, signed=True,
             handler=None, delta=20, topo=None, n_samples=400, **sim_kwargs):
    X, y = make_dataset(n=n_samples, signed=signed)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes)
    if topo is None:
        topo = Topology.clique(n_nodes)
    if handler is None:
        handler = PegasosHandler(AdaLine(X.shape[1]), learning_rate=0.01,
                                 create_model_mode=CreateModelMode.UPDATE)
    return GossipSimulator(handler, topo, disp.stacked(), delta=delta,
                           protocol=protocol, **sim_kwargs)


class TestMinimumSlice:
    """Ormandi 2013 semantics: Pegasos + clique + PUSH (main_ormandi_2013.py)."""

    def test_push_gossip_learns(self, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=15, key=jax.random.fold_in(key, 1))
        curves = report.curves(local=False)
        acc = curves["accuracy"]
        assert np.isfinite(acc).all()
        assert acc[-1] > 0.85
        # Messages flow: one per node per round on a clique.
        assert report.sent_messages >= 15 * 16

    def test_report_round_api(self, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=5)
        ev = report.get_evaluation(local=True)
        assert len(ev) == 5
        rnd, metrics = ev[0]
        assert rnd == 1
        assert "accuracy" in metrics and "auc" in metrics

    def test_deterministic_given_key(self, key):
        sim = make_sim()
        st0 = sim.init_nodes(key)
        _, r1 = sim.start(st0, n_rounds=4, key=jax.random.fold_in(key, 9),
                          donate_state=False)
        _, r2 = sim.start(st0, n_rounds=4, key=jax.random.fold_in(key, 9))
        np.testing.assert_allclose(
            r1.curves(local=False)["accuracy"], r2.curves(local=False)["accuracy"])

    def test_async_mode(self, key):
        sim = make_sim(sync=False)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=10)
        assert report.sent_messages > 0
        assert np.isfinite(report.curves(local=False)["accuracy"][-1])

    def test_run_repetitions_matches_serial(self, key):
        """S vmapped repetitions produce the same per-seed results as S
        serial init+start runs with the same key splits."""
        sim = make_sim(n_nodes=8)
        keys = jax.random.split(key, 3)
        _, reports = sim.run_repetitions(4, keys)
        assert len(reports) == 3
        for i in range(3):
            k_init, k_run = jax.random.split(keys[i])
            sim_s = make_sim(n_nodes=8)
            st = sim_s.init_nodes(k_init)
            _, rep = sim_s.start(st, n_rounds=4, key=k_run)
            np.testing.assert_allclose(
                reports[i].curves(local=False)["accuracy"],
                rep.curves(local=False)["accuracy"], rtol=1e-6)
            assert reports[i].sent_messages == rep.sent_messages
        # Different seeds actually differ (not one run broadcast S times).
        assert (reports[0].curves(local=False)["accuracy"][0]
                != reports[1].curves(local=False)["accuracy"][0])

    def test_interpreted_equals_jitted(self, key):
        """SURVEY §4 test plan: the same seeds give the same round metrics
        whether the round program runs compiled or op-by-op (guards the
        scan/fori_loop rewrite against trace-vs-eager divergence)."""
        run_key = jax.random.fold_in(key, 3)
        # Small world, 2 rounds: under disable_jit every lax.scan/vmap runs
        # as a Python loop, so eager cost ~ total samples x rounds (~15 s at
        # the suite's default 400-sample dataset). Round 2 already covers
        # delivery of round-1 sends, where trace-vs-eager divergence would
        # hide.
        sim = make_sim(n_nodes=8, n_samples=96)
        st = sim.init_nodes(key)
        _, rep_jit = sim.start(st, n_rounds=2, key=run_key)
        sim2 = make_sim(n_nodes=8, n_samples=96)
        st2 = sim2.init_nodes(key)
        with jax.disable_jit():
            _, rep_eager = sim2.start(st2, n_rounds=2, key=run_key)
        np.testing.assert_allclose(rep_jit.curves(local=False)["accuracy"],
                                   rep_eager.curves(local=False)["accuracy"],
                                   rtol=1e-5)
        assert rep_jit.sent_messages == rep_eager.sent_messages

    def test_common_init(self, key):
        """common_init=True starts every node from the same weights (pre
        local training); default re-rolls per node as the reference does."""
        handler = SGDHandler(model=LogisticRegression(10, 2),
                             loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                             local_epochs=1, batch_size=8, n_classes=2,
                             input_shape=(10,),
                             create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim = make_sim(signed=False, handler=handler)
        st_c = sim.init_nodes(key, local_train=False, common_init=True)
        leaves = jax.tree_util.tree_leaves(st_c.model.params)
        for l in leaves:
            np.testing.assert_array_equal(np.asarray(l[0]), np.asarray(l[1]))
        st_d = sim.init_nodes(key, local_train=False)
        assert any(not np.array_equal(np.asarray(l[0]), np.asarray(l[1]))
                   for l in jax.tree_util.tree_leaves(st_d.model.params))

    def test_eval_every(self, key):
        """eval_every=3 evaluates rounds 3 and 6 only; other rounds are
        omitted from the report (NaN rows dropped)."""
        sim = make_sim(eval_every=3)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=6, key=jax.random.fold_in(key, 2))
        rounds = [r for r, _ in report.get_evaluation(local=False)]
        assert rounds == [3, 6], rounds
        # The run's final round always evaluates even when off-cadence.
        st7 = sim.init_nodes(key)
        _, rep7 = sim.start(st7, n_rounds=7, key=jax.random.fold_in(key, 2))
        assert [r for r, _ in rep7.get_evaluation(local=False)] == [3, 6, 7]
        assert list(rep7.eval_rounds(local=False)) == [3, 6, 7]
        assert len(rep7.curves(local=False)["accuracy"]) == 3  # NaN rows dropped
        # Same simulation, same metrics at the evaluated rounds.
        full = make_sim()
        stf = full.init_nodes(key)
        stf, rep_full = full.start(stf, n_rounds=6, key=jax.random.fold_in(key, 2))
        acc_full = {r: m["accuracy"] for r, m in rep_full.get_evaluation(local=False)}
        for r, m in report.get_evaluation(local=False):
            np.testing.assert_allclose(m["accuracy"], acc_full[r], rtol=1e-6)

    def test_async_fast_nodes_fire_per_period(self, key):
        """A node whose period fits k times in the round window sends k
        messages per round (reference node.py:111-125 fires at every
        multiple of the period), up to the static cap."""
        sim = make_sim(n_nodes=8, sync=False, delta=20,
                       max_fires_per_round=4)
        st = sim.init_nodes(key)
        # Periods 10 and 5: 2 and 4 multiples per 20-tick round.
        st = st._replace(phase=jnp.full((8,), 10, dtype=jnp.int32))
        _, rep2 = sim.start(st, n_rounds=4, key=jax.random.fold_in(key, 1),
                            donate_state=False)
        assert rep2.sent_messages == 4 * 8 * 2, rep2.sent_messages
        st = st._replace(phase=jnp.full((8,), 5, dtype=jnp.int32))
        _, rep4 = sim.start(st, n_rounds=4, key=jax.random.fold_in(key, 1))
        assert rep4.sent_messages == 4 * 8 * 4, rep4.sent_messages
        # The cap truncates: period 5 with cap 1 = one send per round.
        sim1 = make_sim(n_nodes=8, sync=False, delta=20,
                        max_fires_per_round=1)
        st1 = sim1.init_nodes(key)
        st1 = st1._replace(phase=jnp.full((8,), 5, dtype=jnp.int32))
        _, rep1 = sim1.start(st1, n_rounds=4, key=jax.random.fold_in(key, 1))
        assert rep1.sent_messages == 4 * 8, rep1.sent_messages


class TestSGDGossip:
    def make_handler(self, d=10, mode=CreateModelMode.MERGE_UPDATE):
        return SGDHandler(model=LogisticRegression(d, 2), loss=losses.cross_entropy,
                          optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
                          n_classes=2, input_shape=(d,), create_model_mode=mode)

    def test_merge_update_gossip_learns(self, key):
        sim = make_sim(signed=False, handler=self.make_handler())
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=10)
        acc = report.curves(local=False)["accuracy"]
        assert acc[-1] > 0.85

    def test_gossip_beats_isolation(self, key):
        """Gossip (exchange on) must beat isolated local training from the
        same init — the core value proposition of GL."""
        handler = self.make_handler()
        X, y = make_dataset(n=320, seed=3)
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=32)  # tiny shards: ~7 samples each
        data = disp.stacked()
        topo = Topology.clique(32)

        sim = GossipSimulator(handler, topo, data, delta=20)
        st = sim.init_nodes(key)
        _, rep_gossip = sim.start(st, n_rounds=12)

        sim_iso = GossipSimulator(handler, topo, data, delta=20, drop_prob=0.99)
        st_iso = sim_iso.init_nodes(key)
        _, rep_iso = sim_iso.start(st_iso, n_rounds=12)

        acc_g = rep_gossip.curves(local=False)["accuracy"][-1]
        acc_i = rep_iso.curves(local=False)["accuracy"][-1]
        assert acc_g > acc_i + 0.02


class TestProtocolsAndFaults:
    def test_pull_and_push_pull(self, key):
        for proto in (AntiEntropyProtocol.PULL, AntiEntropyProtocol.PUSH_PULL):
            sim = make_sim(protocol=proto)
            st = sim.init_nodes(key)
            st, report = sim.start(st, n_rounds=8)
            acc = report.curves(local=False)["accuracy"]
            assert np.isfinite(acc[-1])
            assert acc[-1] > 0.8
            # replies double the traffic
            assert report.sent_messages > 8 * 16

    def test_drop_and_churn_reduce_messages(self, key):
        sim_ok = make_sim()
        sim_bad = make_sim(drop_prob=0.5, online_prob=0.5)
        st, rep_ok = sim_ok.start(sim_ok.init_nodes(key), n_rounds=8)
        st, rep_bad = sim_bad.start(sim_bad.init_nodes(key), n_rounds=8)
        assert rep_bad.failed_messages > rep_ok.failed_messages
        assert rep_bad.failed_messages > 0

    def test_delayed_delivery(self, key):
        # Delays beyond one round still deliver (ring mailbox depth).
        sim = make_sim(delay=UniformDelay(0, 45), delta=20)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=10)
        acc = report.curves(local=False)["accuracy"]
        assert acc[-1] > 0.8
        assert report.failed_messages < report.sent_messages * 0.2

    def test_linear_delay_history_ring_is_small(self, key):
        # Regression: size-dependent delays must size the history ring from
        # the REAL model size (10 scalars here), not a sentinel.
        from gossipy_tpu.core import LinearDelay
        sim = make_sim(delay=LinearDelay(0.1, 5), delta=20)
        st = sim.init_nodes(key)
        assert st.history_ages.shape[0] <= 4

    def test_sampling_eval(self, key):
        sim = make_sim(sampling_eval=0.25)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=5)
        assert len(report.get_evaluation(local=False)) == 5


class TestMemoryBudget:
    def test_terms_and_total(self, key):
        sim = make_sim()
        b = sim.memory_budget()
        # Independent total: name every term explicitly so a term silently
        # dropping out of (or double-counting into) the engine's own sum
        # fails here instead of passing a tautological re-sum.
        assert b["total_bytes"] == (
            b["model_and_opt_bytes"] + b["history_ring_bytes"]
            + b["history_ages_bytes"] + b["aux_bytes"]
            + b["mailbox_bytes"] + b["reply_box_bytes"]
            + b["data_bytes"] + b["eval_peak_bytes"])
        # [D, N, K] x 4 int32 fields, mailbox and reply box.
        assert b["mailbox_bytes"] == 4 * 4 * b["history_depth"] * 16 * sim.K
        assert b["reply_box_bytes"] == 4 * 4 * b["history_depth"] * 16 * sim.Kr
        assert b["eval_peak_bytes"] == sim._eval_peak_bytes()
        assert b["aux_bytes"] == 0  # base engine carries no aux state

    def test_aux_counted_for_variants(self, key):
        from gossipy_tpu.simulation import CacheNeighGossipSimulator
        import optax
        from gossipy_tpu.handlers import SGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        X, y = make_dataset()
        dh = ClassificationDataHandler(X, y.astype(np.int64), test_size=0.25,
                                       seed=1)
        disp = DataDispatcher(dh, n=16)
        h = SGDHandler(model=LogisticRegression(X.shape[1], 2),
                       loss=losses.cross_entropy, optimizer=optax.sgd(0.1),
                       local_epochs=1, batch_size=16, n_classes=2,
                       input_shape=(X.shape[1],),
                       create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim = CacheNeighGossipSimulator(h, Topology.random_regular(16, 6,
                                                                   seed=3),
                                        disp.stacked(), delta=20)
        b = sim.memory_budget()
        # CacheNeigh parks up to max_deg model copies per node: the aux
        # term must exceed the model term by roughly the degree factor.
        assert b["aux_bytes"] is not None
        assert b["aux_bytes"] > 2 * b["model_and_opt_bytes"]

    def test_check_refuses_below_predicted_and_names_dominant(self, key):
        """Predict-and-refuse regression: the refusal pins the predicted
        bytes to the budget total, names the largest term, and the exact
        total passes — the boundary is the budget itself, not a fudge."""
        from gossipy_tpu.simulation import MemoryBudgetExceeded
        sim = make_sim()
        b = sim.memory_budget()
        total = int(b["total_bytes"])
        with pytest.raises(MemoryBudgetExceeded) as ei:
            sim.check_memory_budget(limit_bytes=total - 1)
        e = ei.value
        assert e.predicted_bytes == total
        assert e.limit_bytes == total - 1
        terms = {k: v for k, v in b.items()
                 if k.endswith("_bytes") and k != "total_bytes"
                 and v is not None}
        assert e.dominant_term == max(terms, key=terms.get)
        assert e.dominant_term in str(e)  # the ladder verdict's name
        assert e.budget["total_bytes"] == total
        # Exactly at the limit: fits, returns the budget dict.
        ok = sim.check_memory_budget(limit_bytes=total)
        assert ok["total_bytes"] == total

    def test_check_env_limit_hook(self, key, monkeypatch):
        from gossipy_tpu.simulation import MemoryBudgetExceeded
        sim = make_sim()
        monkeypatch.setenv("GOSSIPY_TPU_MEMORY_LIMIT", "4096")
        with pytest.raises(MemoryBudgetExceeded):
            sim.check_memory_budget()
        monkeypatch.setenv("GOSSIPY_TPU_MEMORY_LIMIT", str(2**40))
        assert sim.check_memory_budget()["total_bytes"] \
            == sim.memory_budget()["total_bytes"]
        # Explicit argument wins over the env hook.
        with pytest.raises(MemoryBudgetExceeded):
            sim.check_memory_budget(limit_bytes=4096)


class TestMessageAccounting:
    def test_sizes_accumulate(self, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=4)
        # Pegasos model = 10 scalars; every PUSH carries one model.
        assert report.total_size == report.sent_messages * 10

    def test_pull_requests_are_small(self, key):
        sim = make_sim(protocol=AntiEntropyProtocol.PULL)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=4)
        # Requests cost 1, replies cost the model size: strictly less than
        # every message carrying a model.
        assert report.total_size < report.sent_messages * 10

    def test_mailbox_warning_regimes(self, key):
        """The undersized-mailbox warning fires exactly in the dangerous
        regimes: hub fan-in (BA-style star of low-degree senders) and
        lowered slot counts — and stays quiet for regular topologies at the
        default capacity (expected fan-in ~1, Poisson tail ~1e-4)."""
        import warnings as w

        from gossipy_tpu.core import SparseTopology

        with w.catch_warnings():
            w.simplefilter("error")  # quiet case: any warning -> failure
            make_sim(n_nodes=16, topo=Topology.ring(16, k=3))
        # Same hub shape through the CSR (SparseTopology) lambda path.
        edges = np.array([[i, 0] for i in range(1, 12)])
        with pytest.warns(UserWarning, match="may overflow"):
            make_sim(n_nodes=12, topo=SparseTopology(12, edges),
                     mailbox_slots=2)
        # DIRECTED star: fan-in is a column sum (who targets me), not a row
        # sum (whom I target). 40 spokes all aiming at node 0: the DERIVED
        # default must size the mailbox for the hub (Poisson(40) tail
        # < 1e-3 needs ~60 slots) with no warning — hub topologies are
        # correct by default.
        n = 41
        adj = np.zeros((n, n), dtype=bool)
        adj[1:, 0] = True
        adj[0, 1] = True
        with w.catch_warnings():
            w.simplefilter("error")
            sim = make_sim(n_nodes=n, topo=Topology(adj))
        assert sim.K > 40
        # Explicitly lowered slots on the same hub still warn.
        with pytest.warns(UserWarning, match="fan-in 40"):
            make_sim(n_nodes=n, topo=Topology(adj), mailbox_slots=6)
        # A hub hotter than the derivation cap (200 spokes > _SLOT_CAP):
        # the cap binds and the warning fires.
        n = 201
        adj = np.zeros((n, n), dtype=bool)
        adj[1:, 0] = True
        adj[0, 1] = True
        with pytest.warns(UserWarning, match="fan-in 200"):
            sim = make_sim(n_nodes=n, topo=Topology(adj))
        assert sim.K == sim._SLOT_CAP

    def test_no_faults_no_failures(self, key):
        """drop=0, online=1, zero delay, mailbox >= fan-in: every message
        delivers (mailbox_slots sized to n-1 so overflow is impossible)."""
        sim = make_sim(mailbox_slots=16)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=6)
        assert report.failed_messages == 0

    def test_mailbox_overflow_counts_failed(self, key):
        """A star topology (everyone sends to node 0) with 1 mailbox slot:
        per round, all but one incoming message overflows and is counted
        failed — conservation of sent = delivered + failed."""
        n = 8
        adj = np.zeros((n, n), dtype=bool)
        adj[1:, 0] = True  # spokes only know the hub
        adj[0, 1] = True   # hub sends to node 1 (keeps every row nonempty)
        with pytest.warns(UserWarning, match="mailbox_slots=1 may overflow"):
            sim = make_sim(n_nodes=n, topo=Topology(adj), mailbox_slots=1)
        st = sim.init_nodes(key)
        rounds = 5
        st, report = sim.start(st, n_rounds=rounds, key=key)
        assert report.sent_messages == rounds * n
        # Node 0 receives n-1 messages/round into 1 slot -> n-2 overflow.
        assert report.failed_messages == rounds * (n - 2)
