"""Tests for enums, topologies, delays, mixing matrices (gossipy_tpu.core)."""

import jax
import numpy as np
import pytest

from gossipy_tpu.core import (
    AntiEntropyProtocol,
    ConstantDelay,
    CreateModelMode,
    LinearDelay,
    MessageType,
    Topology,
    UniformDelay,
    metropolis_hastings_mixing,
    uniform_mixing,
)


def test_enums_match_reference_values():
    # reference core.py:31-75
    assert CreateModelMode.UPDATE == 1
    assert CreateModelMode.MERGE_UPDATE == 2
    assert CreateModelMode.UPDATE_MERGE == 3
    assert CreateModelMode.PASS == 4
    assert AntiEntropyProtocol.PUSH == 1
    assert MessageType.REPLY == 3


def test_clique_topology():
    t = Topology.clique(5)
    assert t.num_nodes == 5
    assert (t.degrees == 4).all()
    assert not t.adjacency.diagonal().any()
    assert t.get_peers(2) == [0, 1, 3, 4]
    # Node 0 reports its true degree (fixes reference core.py:346-349 quirk).
    assert t.size(0) == 4
    assert t.size() == 5


def test_ring_topology():
    t = Topology.ring(6, k=1)
    assert (t.degrees == 2).all()
    assert t.get_peers(0) == [1, 5]


def test_random_regular_and_ba():
    t = Topology.random_regular(20, 4, seed=1)
    assert (t.degrees == 4).all()
    ba = Topology.barabasi_albert(30, 2, seed=1)
    assert ba.num_nodes == 30
    assert (np.asarray(ba.adjacency) == np.asarray(ba.adjacency).T).all()


def test_auto_backend_switch_warns(caplog):
    """backend='auto' silently changing the RNG stream above the native
    threshold is a reproducibility foot-gun; it must log loudly."""
    import logging

    from gossipy_tpu import LOG, native

    if not native.available():
        pytest.skip("native graphgen unavailable")
    n = Topology.NATIVE_THRESHOLD
    # The package logger carries a process-global DuplicateFilter; lift it
    # so this test does not depend on being the first emitter.
    saved = LOG.filters[:]
    for f in saved:
        LOG.removeFilter(f)
    try:
        with caplog.at_level(logging.WARNING, logger="gossipy_tpu"):
            Topology.random_regular(n, 4, seed=1, backend="auto")
        assert any("backend='auto'" in r.getMessage() for r in caplog.records)
        # Explicit pins stay quiet.
        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="gossipy_tpu"):
            Topology.random_regular(64, 4, seed=1, backend="networkx")
            Topology.random_regular(64, 4, seed=1, backend="native")
        assert not [r for r in caplog.records if "backend" in r.getMessage()]
    finally:
        for f in saved:
            LOG.addFilter(f)


def test_backends_learning_quality_band(key):
    """Edge sets differ between networkx and native generators (documented),
    but a gossip run over either must land in the same quality band."""
    import optax

    from gossipy_tpu import native
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    if not native.available():
        pytest.skip("native graphgen unavailable")
    rng = np.random.default_rng(0)
    d, n = 8, 32
    w = rng.normal(size=d)
    X = rng.normal(size=(n * 12, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                          n=n)
    handler = SGDHandler(model=LogisticRegression(d, 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                         local_epochs=1, batch_size=8, n_classes=2,
                         input_shape=(d,))
    accs = {}
    for backend in ("networkx", "native"):
        topo = Topology.random_regular(n, 6, seed=3, backend=backend)
        sim = GossipSimulator(handler, topo, disp.stacked(), delta=10)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=12, key=key)
        accs[backend] = float(rep.curves(local=False)["accuracy"][-1])
    assert all(a > 0.8 for a in accs.values()), accs
    assert abs(accs["networkx"] - accs["native"]) < 0.1, accs


def test_sample_peers_respects_adjacency(key):
    t = Topology.ring(8, k=1)
    for i in range(20):
        peers = np.asarray(t.sample_peers(jax.random.fold_in(key, i)))
        for n in range(8):
            assert t.adjacency[n, peers[n]]


def test_sample_peers_isolated_node(key):
    a = np.zeros((3, 3), dtype=bool)
    a[0, 1] = a[1, 0] = True
    t = Topology(a)
    peers = np.asarray(t.sample_peers(key))
    assert peers[2] == -1
    assert peers[0] == 1 and peers[1] == 0


def test_delays(key):
    assert ConstantDelay(3).max_delay(100) == 3
    assert (np.asarray(ConstantDelay(3).sample(key, (5,), 10)) == 3).all()

    d = UniformDelay(0, 10)
    s = np.asarray(d.sample(key, (1000,), 10))
    assert s.min() >= 0 and s.max() <= 10
    assert d.max_delay(10) == 10

    # LinearDelay(0, x) == ConstantDelay(x)  (reference core.py:269-271)
    ld = LinearDelay(0.0, 4)
    assert (np.asarray(ld.sample(key, (5,), 123)) == 4).all()
    # delay = floor(timexunit*size) + overhead (reference core.py:285-304)
    assert LinearDelay(0.5, 2).max_delay(11) == 7


def test_uniform_mixing_rows_sum_to_one():
    t = Topology.ring(6, k=1)
    w = np.asarray(uniform_mixing(t))
    assert np.allclose(w.sum(axis=1), 1.0)
    # self weight equals peer weight: 1/(deg+1)  (reference core.py:419-434)
    assert np.allclose(np.diag(w), 1.0 / 3.0)


def test_mh_mixing_doubly_stochastic():
    t = Topology.barabasi_albert(12, 2, seed=3)
    w = np.asarray(metropolis_hastings_mixing(t))
    assert np.allclose(w.sum(axis=1), 1.0, atol=1e-6)
    assert np.allclose(w.sum(axis=0), 1.0, atol=1e-6)
    assert np.allclose(w, w.T)
    assert (np.diag(w) >= 0).all()


def test_mixing_weight_rows_layout():
    """Reference-layout per-node vectors: [self weight, peer weights...],
    zero-padded to max degree (reference MixingMatrix.__getitem__)."""
    import numpy as np

    from gossipy_tpu.core import Topology, mixing_weight_rows, uniform_mixing

    topo = Topology.ring(6, k=1)  # degree 2 everywhere
    w = uniform_mixing(topo)
    rows = np.asarray(mixing_weight_rows(w, topo))
    assert rows.shape == (6, 3)
    w_np = np.asarray(w)
    for i in range(6):
        peers = np.where(np.asarray(topo.adjacency)[i])[0]
        assert rows[i, 0] == w_np[i, i]
        np.testing.assert_allclose(rows[i, 1:1 + len(peers)], w_np[i, peers])
