"""Pure-JAX metrics vs sklearn ground truth (gossipy_tpu.utils)."""

import numpy as np
import pytest
from sklearn.metrics import (
    accuracy_score,
    f1_score,
    normalized_mutual_info_score,
    precision_score,
    recall_score,
    roc_auc_score,
)

from gossipy_tpu.utils import (
    binary_auc,
    classification_metrics,
    nmi,
    rmse,
    signed_binary_metrics,
)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_classification_metrics_match_sklearn(seed):
    rng = np.random.default_rng(seed)
    n, c = 200, 4
    scores = rng.normal(size=(n, c)).astype(np.float32)
    y = rng.integers(0, c, size=n)
    res = classification_metrics(scores, y, c)
    y_pred = scores.argmax(axis=1)
    assert np.isclose(float(res["accuracy"]), accuracy_score(y, y_pred))
    assert np.isclose(float(res["precision"]),
                      precision_score(y, y_pred, zero_division=0, average="macro"),
                      atol=1e-6)
    assert np.isclose(float(res["recall"]),
                      recall_score(y, y_pred, zero_division=0, average="macro"),
                      atol=1e-6)
    assert np.isclose(float(res["f1_score"]),
                      f1_score(y, y_pred, zero_division=0, average="macro"),
                      atol=1e-6)


@pytest.mark.parametrize("seed", [0, 1])
def test_binary_auc_matches_sklearn(seed):
    rng = np.random.default_rng(seed)
    n = 300
    s = rng.normal(size=n).astype(np.float32)
    # Introduce ties to exercise midrank handling.
    s = np.round(s, 1)
    y = rng.integers(0, 2, size=n)
    assert np.isclose(float(binary_auc(s, y)), roc_auc_score(y, s), atol=1e-6)


def test_binary_auc_respects_mask():
    rng = np.random.default_rng(3)
    n = 100
    s = rng.normal(size=n).astype(np.float32)
    y = rng.integers(0, 2, size=n)
    mask = (rng.random(n) < 0.7).astype(np.float32)
    keep = mask > 0
    expect = roc_auc_score(y[keep], s[keep])
    assert np.isclose(float(binary_auc(s, y, mask)), expect, atol=1e-6)


def test_binary_metrics_includes_auc():
    rng = np.random.default_rng(0)
    scores = rng.normal(size=(50, 2)).astype(np.float32)
    y = rng.integers(0, 2, size=50)
    res = classification_metrics(scores, y, 2)
    assert "auc" in res
    assert np.isclose(float(res["auc"]), roc_auc_score(y, scores[:, 1]), atol=1e-6)


def test_signed_binary_metrics():
    rng = np.random.default_rng(1)
    s = rng.normal(size=80).astype(np.float32)
    y = np.where(rng.random(80) < 0.5, -1.0, 1.0).astype(np.float32)
    res = signed_binary_metrics(s, y)
    y01 = (y > 0).astype(int)
    pred = (s >= 0).astype(int)
    assert np.isclose(float(res["accuracy"]), accuracy_score(y01, pred))
    assert np.isclose(float(res["auc"]), roc_auc_score(y01, s), atol=1e-6)


def test_nmi_matches_sklearn():
    rng = np.random.default_rng(2)
    y_true = rng.integers(0, 3, size=200)
    y_pred = rng.integers(0, 3, size=200)
    assert np.isclose(float(nmi(y_true, y_pred, 3, 3)),
                      normalized_mutual_info_score(y_true, y_pred), atol=1e-5)
    # Perfect agreement => 1 (up to float32 log precision).
    assert np.isclose(float(nmi(y_true, y_true, 3, 3)), 1.0, atol=1e-4)


def test_rmse_masked():
    pred = np.array([1.0, 2.0, 100.0], dtype=np.float32)
    tgt = np.array([1.0, 4.0, 0.0], dtype=np.float32)
    mask = np.array([1.0, 1.0, 0.0], dtype=np.float32)
    assert np.isclose(float(rmse(pred, tgt, mask)), np.sqrt(2.0), atol=1e-6)
