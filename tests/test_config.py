"""Experiment config system: dataclass <-> JSON <-> live simulator."""

import json
import warnings

import numpy as np
import pytest

from gossipy_tpu.config import ExperimentConfig, build_experiment, run_experiment


def tiny_data(n=240, d=6, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    return X, y


def _tiny_cifar(allow_synthetic=True):
    rng = np.random.default_rng(0)
    def split(n):
        return (rng.normal(size=(n, 32, 32, 3)).astype(np.float32),
                rng.integers(0, 10, n).astype(np.int64))
    return split(400), split(80)


def tiny_cfg(**kw):
    base = dict(n_nodes=8, topology="ring", topology_params={"k": 2},
                delta=10, batch_size=8, learning_rate=0.5, n_rounds=8)
    base.update(kw)
    return ExperimentConfig(**base)


class TestSerialization:
    def test_json_roundtrip(self, tmp_path):
        cfg = tiny_cfg(model="mlp", model_params={"hidden_dims": [16]})
        p = tmp_path / "exp.json"
        cfg.to_json(str(p))
        cfg2 = ExperimentConfig.from_json(str(p))
        assert cfg2 == cfg

    def test_from_json_string(self):
        cfg = ExperimentConfig.from_json('{"n_nodes": 4, "model": "logreg"}')
        assert cfg.n_nodes == 4

    def test_unknown_field_rejected(self):
        with pytest.raises(ValueError, match="unknown config fields"):
            ExperimentConfig.from_dict({"n_nodez": 4})

    def test_json_is_complete(self):
        d = json.loads(tiny_cfg().to_json())
        assert d["protocol"] == "PUSH" and d["delta"] == 10


class TestBuild:
    def test_build_gossip(self):
        sim, disp = build_experiment(tiny_cfg(), data=tiny_data())
        assert sim.n_nodes == 8 and sim.delta == 10

    def test_build_tokenized_with_account(self):
        cfg = tiny_cfg(simulator="tokenized", token_account="simple",
                       token_account_params={"C": 3})
        sim, _ = build_experiment(cfg, data=tiny_data())
        assert sim.account.C == 3

    def test_build_all2all(self):
        cfg = tiny_cfg(simulator="all2all", handler="weighted",
                       topology="clique", topology_params={})
        sim, _ = build_experiment(cfg, data=tiny_data())
        assert sim.mixing.shape == (8, 8)

    def test_build_sparse_topology(self):
        cfg = tiny_cfg(sparse_topology=True, topology="random_regular",
                       topology_params={"degree": 4})
        sim, _ = build_experiment(cfg, data=tiny_data())
        from gossipy_tpu.core import SparseTopology
        assert isinstance(sim.topology, SparseTopology)

    def test_clear_errors(self):
        with pytest.raises(ValueError, match="unknown topology"):
            build_experiment(tiny_cfg(topology="hypercube"), data=tiny_data())
        with pytest.raises(ValueError, match="unknown model"):
            build_experiment(tiny_cfg(model="resnet50"), data=tiny_data())
        with pytest.raises(ValueError, match="unknown simulator"):
            build_experiment(tiny_cfg(simulator="quantum"), data=tiny_data())
        with pytest.raises(ValueError, match="unknown handler"):
            build_experiment(tiny_cfg(handler="adam?"), data=tiny_data())


class TestRun:
    def test_run_learns(self):
        state, report = run_experiment(tiny_cfg(), data=tiny_data())
        assert report.curves(local=False)["accuracy"][-1] > 0.8

    def test_run_sequential_simulator(self):
        # The opt-in high-fidelity engine is config-reachable; with a token
        # account it runs the same-tick reactive path.
        state, report = run_experiment(
            tiny_cfg(simulator="sequential", n_rounds=5,
                     token_account="simple", token_account_params={"C": 2}),
            data=tiny_data())
        acc = report.curves(local=False)["accuracy"]
        assert np.isfinite(acc).all() and len(acc) == 5

    def test_sequential_rejects_eval_every(self):
        with pytest.raises(ValueError, match="eval_every"):
            build_experiment(tiny_cfg(simulator="sequential", eval_every=3),
                             data=tiny_data())

    def test_sequential_repetitions(self):
        states, reports = run_experiment(
            tiny_cfg(simulator="sequential", n_rounds=3, repetitions=2),
            data=tiny_data())
        assert len(reports) == 2
        for r in reports:
            assert np.isfinite(r.curves(local=False)["accuracy"]).all()

    def test_compact_deliver_via_simulator_params(self):
        sim, _ = build_experiment(
            tiny_cfg(simulator_params={"compact_deliver": 4}),
            data=tiny_data())
        assert sim._compact_cap == 4

    def test_run_from_json_reproducible(self, tmp_path):
        cfg = tiny_cfg()
        p = tmp_path / "exp.json"
        cfg.to_json(str(p))
        _, r1 = run_experiment(ExperimentConfig.from_json(str(p)),
                               data=tiny_data())
        _, r2 = run_experiment(ExperimentConfig.from_json(str(p)),
                               data=tiny_data())
        a1 = r1.curves(local=False)["accuracy"]
        a2 = r2.curves(local=False)["accuracy"]
        assert np.allclose(a1, a2)

    def test_run_repetitions_batch(self):
        cfg = tiny_cfg(repetitions=3, n_rounds=5)
        states, reports = run_experiment(cfg, data=tiny_data())
        assert len(reports) == 3
        curves = [r.curves(local=False)["accuracy"] for r in reports]
        assert all(np.isfinite(c).all() for c in curves)
        # Different seeds -> different trajectories (vmapped, not copies).
        # Full curves, not final values: finals quantize to 1/len(test-set)
        # and can collide across seeds.
        assert not all(np.allclose(curves[0], c) for c in curves[1:])

    def test_repetitions_must_be_positive(self):
        with pytest.raises(ValueError, match="repetitions"):
            tiny_cfg(repetitions=0)

    @pytest.mark.slow
    def test_image_dataset_cnn_builds_and_steps(self, monkeypatch):
        """The flagship CIFAR config is expressible as JSON: image dataset
        + CNN + Dirichlet split. The full-size synthetic CIFAR substitute
        (50k images, ~600 MB) is swapped for a tiny stand-in — the real
        parser is proven in test_data_downloads; this test covers the
        config wiring. (CNN program: ~20 s on this host -> slow lane.)"""
        import gossipy_tpu.data as gdata
        monkeypatch.setattr(gdata, "get_CIFAR10", _tiny_cifar, raising=True)
        cfg = ExperimentConfig(
            dataset="cifar10", n_nodes=4, model="cifar10net",
            assignment="label_dirichlet_skew",
            assignment_params={"beta": 0.5}, subsample=120,
            topology="ring", topology_params={"k": 1}, delta=10,
            batch_size=16, learning_rate=0.05, n_rounds=1)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, report = run_experiment(cfg)
        assert np.isfinite(report.curves(local=False)["accuracy"][-1])

    def test_cnn_conv_impl_is_configurable(self):
        """The CNN's conv lowering is a config knob (strictly validated):
        experiments can pin conv_impl in JSON; typos still raise."""
        from gossipy_tpu.config import _model
        m = _model("cifar10net", {"conv_impl": "conv"}, 32, 10)
        assert m.conv_impl == "conv"
        assert _model("cifar10net", {}, 32, 10).conv_impl == "auto"
        with pytest.raises(ValueError, match="unknown model_params"):
            _model("cifar10net", {"oops": 1}, 32, 10)

    def test_shipped_configs_parse_and_validate(self):
        import glob
        import os
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = glob.glob(os.path.join(repo, "examples", "configs", "*.json"))
        # The 8 reference main_* reproductions plus the flagship configs.
        assert len(paths) >= 10
        for p in paths:
            cfg = ExperimentConfig.from_json(p)
            assert cfg.n_nodes >= 0  # 0 = one node per sample

    def test_shipped_reproduction_configs_build(self):
        """Every shipped non-image reproduction config BUILDS a live
        simulator (shrunk: subsample + tiny rounds keep it a smoke test;
        image configs are parse-checked above and the cifar10 path builds
        in test_image_dataset_cnn_builds_and_steps)."""
        import dataclasses
        import glob
        import os
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        built = 0
        for p in sorted(glob.glob(
                os.path.join(repo, "examples", "configs", "*.json"))):
            cfg = ExperimentConfig.from_json(p)
            if cfg.dataset in ("cifar10", "fashion_mnist"):
                continue  # full-size synthetic image sets: parse-only here
            if cfg.task != "recsys":
                cfg = dataclasses.replace(cfg, subsample=200)
            cfg = dataclasses.replace(cfg, n_rounds=2)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                sim, disp = build_experiment(cfg)
            assert sim.n_nodes == disp.size() > 0, p
            built += 1
        assert built >= 6

    def test_run_with_dataset_name(self):
        cfg = tiny_cfg(dataset="breast", n_nodes=8)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, report = run_experiment(cfg)
        assert np.isfinite(report.curves(local=False)["accuracy"][-1])


class TestNewFamilies:
    """Config coverage of the kmeans / MF / femnist / clustering families
    (round-2 VERDICT missing #2: main_berta_2014 / main_hegedus_2020 had no
    JSON equivalent)."""

    def test_clustering_kmeans_runs(self):
        cfg = ExperimentConfig(
            task="clustering", dataset="spambase", n_nodes=24,
            handler="kmeans",
            handler_params={"k": 2, "alpha": 0.1, "matching": "hungarian"},
            create_model_mode="MERGE_UPDATE", topology="clique",
            topology_params={}, subsample=120, delta=10, n_rounds=3)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, report = run_experiment(cfg)
        nmi = report.curves(local=False)["nmi"][-1]
        assert np.isfinite(nmi) and 0.0 <= nmi <= 1.0

    def test_recsys_mf_runs(self):
        # Tiny synthetic ratings via the data= override (the full ml-100k
        # synthetic substitute is 943 users — needless here; the loader
        # itself is proven in test_data_downloads).
        rng = np.random.default_rng(3)
        n_users, n_items = 24, 40
        ratings = {u: [(int(i), float(rng.integers(1, 6)))
                       for i in rng.choice(n_items, 8, replace=False)]
                   for u in range(n_users)}
        cfg = ExperimentConfig(
            task="recsys", dataset="ml-100k", handler="mf",
            handler_params={"dim": 4}, learning_rate=0.01,
            create_model_mode="MERGE_UPDATE", topology="random_regular",
            topology_params={"degree": 8, "seed": 0}, test_size=0.2,
            delta=10, sampling_eval=0.2, n_rounds=2)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            state, report = run_experiment(cfg, data=(ratings, n_users,
                                                      n_items))
        rmse = report.curves(local=True)["rmse"][-1]
        assert np.isfinite(rmse) and rmse > 0

    def test_femnist_builds_with_writer_shards(self):
        cfg = ExperimentConfig(
            dataset="femnist", n_nodes=10, model="mlp",
            model_params={"hidden_dims": [16]}, eval_on_user=True,
            topology="ring", topology_params={"k": 2}, delta=10, n_rounds=1)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            sim, disp = build_experiment(cfg)
        assert disp.size() == 10
        # Writer shards are ragged: at least two different shard sizes.
        sizes = {len(a) for a in disp.tr_assignments}
        assert len(sizes) > 1

    def test_one_node_per_sample(self):
        X, y = tiny_data(n=60)
        cfg = ExperimentConfig(n_nodes=0, handler="pegasos",
                               learning_rate=0.01, topology="clique",
                               topology_params={}, test_size=0.25,
                               delta=10, n_rounds=1)
        sim, disp = build_experiment(cfg, data=(X, y))
        assert sim.n_nodes == disp.size() == 45  # one per TRAIN sample

    def test_partitioned_tokenized_builds(self):
        cfg = tiny_cfg(handler="partitioned", handler_params={"n_parts": 3},
                       simulator="tokenized_partitioning",
                       token_account="randomized",
                       token_account_params={"C": 20, "A": 10},
                       create_model_mode="UPDATE")
        sim, _ = build_experiment(cfg, data=tiny_data())
        assert sim.handler.partition.n_parts == 3

    def test_strict_model_and_topology_params(self):
        with pytest.raises(ValueError, match="accepts no model_params"):
            build_experiment(tiny_cfg(model_params={"oops": 1}),
                             data=tiny_data())
        with pytest.raises(ValueError, match="accepts no params"):
            build_experiment(tiny_cfg(topology="clique",
                                      topology_params={"degree": 2}),
                             data=tiny_data())

    def test_task_handler_consistency(self):
        with pytest.raises(ValueError, match="requires handler 'mf'"):
            ExperimentConfig(task="recsys", handler="sgd")
        with pytest.raises(ValueError, match="requires task 'recsys'"):
            ExperimentConfig(handler="mf")
        with pytest.raises(ValueError, match="unknown task"):
            ExperimentConfig(task="regression?")
