"""Core primitives: GlobalSettings singleton, LOG duplicate filter, set_seed.

Parity targets: reference gossipy/__init__.py:37-131 (Singleton metaclass,
GlobalSettings, DuplicateFilter + LOG, set_seed).
"""

import logging
import random

import jax
import numpy as np

from gossipy_tpu import DuplicateFilter, GlobalSettings, LOG, set_seed


def test_global_settings_is_singleton():
    a = GlobalSettings()
    b = GlobalSettings()
    assert a is b


def test_global_settings_device_roundtrip():
    gs = GlobalSettings()
    prev = gs._platform
    try:
        gs.set_device("tpu")
        assert gs.get_device() == "tpu"
        gs.set_device(None)
        # Falls back to the live backend (CPU under the test mesh).
        assert gs.get_device() == jax.default_backend()
    finally:
        gs.set_device(prev)


def test_auto_device():
    gs = GlobalSettings()
    prev = gs._platform
    try:
        assert gs.auto_device() == jax.default_backend()
        assert gs.get_device() == jax.default_backend()
    finally:
        gs.set_device(prev)


def test_download_helpers_roundtrip(tmp_path):
    """download_and_unzip/untar extract archives served from a file:// URL
    (reference utils.py:98-149; no egress needed)."""
    import tarfile
    import zipfile

    src = tmp_path / "payload.txt"
    src.write_text("hello")
    zpath = tmp_path / "a.zip"
    with zipfile.ZipFile(zpath, "w") as zf:
        zf.write(src, "payload.txt")
    tpath = tmp_path / "a.tar.gz"
    with tarfile.open(tpath, "w:gz") as tf:
        tf.add(src, "payload.txt")

    from gossipy_tpu.utils import download_and_untar, download_and_unzip
    out1 = tmp_path / "out_zip"
    names = download_and_unzip(zpath.as_uri(), str(out1))
    assert names == ["payload.txt"]
    assert (out1 / "payload.txt").read_text() == "hello"
    out2 = tmp_path / "out_tar"
    names = download_and_untar(tpath.as_uri(), str(out2))
    assert "payload.txt" in names
    assert (out2 / "payload.txt").read_text() == "hello"


def test_duplicate_filter_suppresses_repeats():
    f = DuplicateFilter()

    def rec(msg):
        return logging.LogRecord("t", logging.INFO, __file__, 1, msg, None, None)

    assert f.filter(rec("hello"))
    assert not f.filter(rec("hello"))
    assert f.filter(rec("world"))


def test_log_has_duplicate_filter():
    assert any(isinstance(flt, DuplicateFilter) for flt in LOG.filters)


def test_set_seed_reproducible():
    k1 = set_seed(123)
    host1 = (random.random(), float(np.random.standard_normal()))
    k2 = set_seed(123)
    host2 = (random.random(), float(np.random.standard_normal()))
    assert host1 == host2
    assert jax.numpy.array_equal(jax.random.key_data(k1),
                                 jax.random.key_data(k2))
    draws1 = jax.random.normal(k1, (4,))
    draws2 = jax.random.normal(k2, (4,))
    np.testing.assert_array_equal(np.asarray(draws1), np.asarray(draws2))


def test_set_seed_distinct_seeds_differ():
    ka = set_seed(1)
    kb = set_seed(2)
    assert not jax.numpy.array_equal(jax.random.key_data(ka),
                                     jax.random.key_data(kb))


def test_choice_not_n_excludes_and_covers():
    from gossipy_tpu.utils import choice_not_n

    seen = set()
    for i in range(200):
        v = int(choice_not_n(0, 5, 3, jax.random.PRNGKey(i)))
        assert 0 <= v <= 5 and v != 3
        seen.add(v)
    assert seen == {0, 1, 2, 4, 5}
    # Excluded value outside the range: plain uniform over [mn, mx].
    vals = {int(choice_not_n(0, 2, 9, jax.random.PRNGKey(i))) for i in range(60)}
    assert vals == {0, 1, 2}


def test_choice_not_n_empty_range_raises():
    """mn == mx == notn leaves nothing to draw: a real ValueError (not a
    strippable assert) must stop the silent contract violation."""
    import pytest

    from gossipy_tpu.utils import choice_not_n

    with pytest.raises(ValueError, match="no value"):
        choice_not_n(3, 3, 3, jax.random.PRNGKey(0))
