"""SLO metrics registry (telemetry.metrics) + the service signal plane.

Covers the ISSUE-11 acceptance surface:

- counter/gauge/histogram semantics, the zero-label sugar and the label
  cardinality guard (overflow series aggregates, totals stay right);
- percentile estimation accuracy against numpy on synthetic samples
  (bounded by the ~1.78x log-bucket resolution, clamped to min/max);
- OpenMetrics text golden + snapshot JSON round-trip;
- cross-process merge associativity/commutativity and exact-sum
  equivalence to a single-registry reference;
- the engine's host-side ``metrics=`` feed (counters match the report,
  JSONL v7 rows carry cumulative totals, v1–v7 parse_line tolerance);
- the TelemetrySink terminal ``metrics_snapshot`` event;
- loadgen end-to-end: N small Poisson-arriving tenants through the
  incremental service session -> a sane ``service_slo`` row with every
  admitted tenant's time-to-first-round recorded.
"""

import json
import math

import numpy as np
import pytest

from gossipy_tpu.telemetry.metrics import (
    DEFAULT_BUCKETS,
    OVERFLOW_LABEL,
    MetricsRegistry,
    merge_snapshots,
    quantile_from_counts,
    set_registry,
    snapshot_to_openmetrics,
)


@pytest.fixture
def reg():
    r = MetricsRegistry()
    prev = set_registry(r)
    yield r
    set_registry(prev)


class TestCounter:
    def test_inc_accumulates(self, reg):
        c = reg.counter("jobs_total", "jobs", ("kind",))
        c.labels(kind="a").inc()
        c.labels(kind="a").inc(2.5)
        c.labels(kind="b").inc()
        snap = reg.snapshot()["metrics"]["jobs_total"]
        vals = {s["labels"]["kind"]: s["value"] for s in snap["series"]}
        assert vals == {"a": 3.5, "b": 1.0}

    def test_negative_inc_raises(self, reg):
        c = reg.counter("x_total")
        with pytest.raises(ValueError):
            c.inc(-1)

    def test_zero_label_sugar_and_label_mismatch(self, reg):
        c = reg.counter("plain_total")
        c.inc()
        assert reg.snapshot()["metrics"]["plain_total"]["series"][0][
            "value"] == 1.0
        labeled = reg.counter("lab_total", labelnames=("k",))
        with pytest.raises(ValueError):
            labeled.inc()          # labels declared: must use .labels()
        with pytest.raises(ValueError):
            labeled.labels(wrong="x")

    def test_kind_and_labelname_mismatch_raise(self, reg):
        reg.counter("m1", labelnames=("a",))
        with pytest.raises(ValueError):
            reg.gauge("m1")
        with pytest.raises(ValueError):
            reg.counter("m1", labelnames=("b",))


class TestGauge:
    def test_set_inc_dec(self, reg):
        g = reg.gauge("temp")
        g.set_value(4.0)
        g.inc(2.0)
        g.dec(1.0)
        s = reg.snapshot()["metrics"]["temp"]["series"][0]
        assert s["value"] == 5.0
        assert s["ts"] > 0

    def test_merge_is_last_writer_wins(self, reg):
        a = MetricsRegistry()
        b = MetricsRegistry()
        a.gauge("v").set_value(1.0)
        b.gauge("v").set_value(2.0)   # written later
        m = merge_snapshots(a.snapshot(), b.snapshot())
        assert m["metrics"]["v"]["series"][0]["value"] == 2.0
        # Commutes: the later stamp wins regardless of argument order.
        m2 = merge_snapshots(b.snapshot(), a.snapshot())
        assert m2["metrics"]["v"]["series"][0]["value"] == 2.0


class TestHistogram:
    def test_counts_sum_and_bucket_assignment(self, reg):
        h = reg.histogram("lat", buckets=(1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 50.0, 500.0):
            h.observe(v)
        s = reg.snapshot()["metrics"]["lat"]["series"][0]
        assert s["counts"] == [1, 1, 1, 1]   # one per bucket + Inf
        assert s["count"] == 4
        assert s["sum"] == pytest.approx(555.5)
        assert s["min"] == 0.5 and s["max"] == 500.0

    def test_nan_observation_ignored(self, reg):
        h = reg.histogram("lat2")
        h.observe(float("nan"))
        h.observe(1.0)
        s = reg.snapshot()["metrics"]["lat2"]["series"][0]
        assert s["count"] == 1 and math.isfinite(s["sum"])

    def test_empty_quantile_is_none(self, reg):
        assert reg.histogram("lat3").quantile(0.5) is None

    @pytest.mark.parametrize("dist", ["loguniform", "lognormal", "const"])
    def test_percentile_accuracy_vs_numpy(self, reg, dist):
        rng = np.random.default_rng(7)
        if dist == "loguniform":
            samples = np.exp(rng.uniform(np.log(1e-3), np.log(50.0),
                                         4000))
        elif dist == "lognormal":
            samples = rng.lognormal(mean=-2.0, sigma=1.5, size=4000)
        else:
            samples = np.full(100, 0.25)
        h = reg.histogram("acc", labelnames=("d",)).labels(d=dist)
        for v in samples:
            h.observe(float(v))
        # Accuracy is bounded by the log-bucket resolution: the estimate
        # must land within one bucket step (x1.9 with slack) of numpy's
        # answer, and inside the observed envelope.
        for q in (0.5, 0.9, 0.99):
            est = h.quantile(q)
            true = float(np.quantile(samples, q))
            assert est is not None
            assert samples.min() <= est <= samples.max()
            assert true / 1.9 <= est <= true * 1.9, (q, est, true)

    def test_quantile_from_counts_standalone(self):
        # The snapshot-side estimator (service_top's path) agrees with
        # the live child's.
        buckets = tuple(DEFAULT_BUCKETS)
        counts = [0] * (len(buckets) + 1)
        counts[10] = 100
        est = quantile_from_counts(buckets, counts, 0.5)
        assert buckets[9] <= est <= buckets[10]


class TestCardinalityGuard:
    def test_overflow_series_aggregates(self, reg):
        c = reg.counter("per_tenant_total", labelnames=("tenant",),
                        max_series=3)
        for i in range(10):
            c.labels(tenant=f"t{i}").inc()
        snap = reg.snapshot()["metrics"]["per_tenant_total"]
        assert snap["overflowed"] == 7
        by = {s["labels"]["tenant"]: s["value"] for s in snap["series"]}
        # 3 real series + ONE shared overflow child carrying t3..t9.
        assert by[OVERFLOW_LABEL] == 7.0
        assert sum(by.values()) == 10.0    # totals never lost
        assert len(by) == 4


class TestOpenMetrics:
    def test_golden_text(self, reg):
        reg.counter("runs_total", "runs completed",
                    ("status",)).labels(status="done").inc(3)
        reg.gauge("queue_depth", "pending runs").set_value(2)
        h = reg.histogram("wait_seconds", "queue wait", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        got = reg.to_openmetrics()
        assert got == (
            "# HELP queue_depth pending runs\n"
            "# TYPE queue_depth gauge\n"
            "queue_depth 2\n"
            "# HELP runs_total runs completed\n"
            "# TYPE runs_total counter\n"
            'runs_total{status="done"} 3\n'
            "# HELP wait_seconds queue wait\n"
            "# TYPE wait_seconds histogram\n"
            'wait_seconds_bucket{le="0.1"} 1\n'
            'wait_seconds_bucket{le="1"} 2\n'
            'wait_seconds_bucket{le="+Inf"} 3\n'
            "wait_seconds_sum 5.55\n"
            "wait_seconds_count 3\n"
            "# EOF\n")

    def test_label_escaping_and_counter_suffix(self, reg):
        reg.counter("odd", "x", ("msg",)).labels(msg='a"b\nc').inc()
        text = reg.to_openmetrics()
        assert 'odd_total{msg="a\\"b\\nc"} 1' in text

    def test_snapshot_json_roundtrip(self, reg):
        reg.counter("a_total").inc()
        reg.histogram("h").observe(1.0)
        snap = reg.snapshot()
        back = json.loads(json.dumps(snap))
        assert back == snap
        assert snapshot_to_openmetrics(back) == reg.to_openmetrics()


def _random_registry(events):
    r = MetricsRegistry()
    for kind, name, labels, v, ts in events:
        if kind == "c":
            r.counter(name, labelnames=tuple(labels)).labels(
                **labels).inc(v)
        elif kind == "g":
            ch = r.gauge(name, labelnames=tuple(labels)).labels(**labels)
            ch.value, ch.ts = v, ts
        else:
            r.histogram(name, labelnames=tuple(labels)).labels(
                **labels).observe(v)
    return r


def _assert_snapshots_equal(a: dict, b: dict):
    """Structural equality with float-sum tolerance: counter values and
    histogram sums are compared approx (float addition re-associates to
    a different last ulp), everything else exactly."""
    assert sorted(a["metrics"]) == sorted(b["metrics"])
    for name in a["metrics"]:
        fa, fb = a["metrics"][name], b["metrics"][name]
        assert fa["type"] == fb["type"]
        assert [s["labels"] for s in fa["series"]] == \
            [s["labels"] for s in fb["series"]]
        for sa, sb in zip(fa["series"], fb["series"]):
            if fa["type"] == "counter":
                assert sb["value"] == pytest.approx(sa["value"])
            elif fa["type"] == "histogram":
                assert sb["counts"] == sa["counts"]
                assert sb["count"] == sa["count"]
                assert sb["sum"] == pytest.approx(sa["sum"])
                assert sb["min"] == sa["min"] and sb["max"] == sa["max"]
            else:
                assert (sb["value"], sb["ts"]) == (sa["value"], sa["ts"])


class TestMerge:
    def _events(self, seed, n=120):
        # Gauge stamps increase with event order so "last written" and
        # "latest stamp" name the same value — the single-registry
        # reference and the merge must then agree exactly.
        rng = np.random.default_rng(seed)
        out = []
        for i in range(n):
            kind = ("c", "g", "h")[int(rng.integers(3))]
            name = f"m{int(rng.integers(3))}_{kind}"
            labels = {"k": f"v{int(rng.integers(4))}"}
            out.append((kind, name, labels,
                        float(rng.uniform(0.001, 100.0)), float(i)))
        return out

    def test_associative_and_commutative(self):
        evs = self._events(0, 240)
        parts = [_random_registry(evs[i::3]).snapshot() for i in range(3)]
        a, b, c = parts
        left = merge_snapshots(merge_snapshots(a, b), c)
        right = merge_snapshots(a, merge_snapshots(b, c))
        swapped = merge_snapshots(c, merge_snapshots(b, a))
        for m in (right, swapped):
            _assert_snapshots_equal(left, m)

    def test_merge_equals_single_registry(self):
        evs = self._events(1, 180)
        whole = _random_registry(evs).snapshot()
        halves = merge_snapshots(_random_registry(evs[::2]).snapshot(),
                                 _random_registry(evs[1::2]).snapshot())
        _assert_snapshots_equal(whole, halves)

    def test_structural_mismatch_raises(self):
        a = MetricsRegistry()
        b = MetricsRegistry()
        a.counter("m")
        b.gauge("m")
        with pytest.raises(ValueError):
            merge_snapshots(a.snapshot(), b.snapshot())

    def test_load_snapshot_folds_in(self):
        a = MetricsRegistry()
        a.counter("n_total").inc(2)
        b = MetricsRegistry()
        b.counter("n_total").inc(3)
        a.load_snapshot(b.snapshot())
        assert a.snapshot()["metrics"]["n_total"]["series"][0][
            "value"] == 5.0


class TestSinkTerminalSnapshot:
    def test_close_writes_metrics_snapshot_to_mirror(self, reg,
                                                     tmp_path):
        from gossipy_tpu.telemetry import TelemetrySink
        reg.counter("done_total").inc()
        path = str(tmp_path / "ev.jsonl")
        sink = TelemetrySink(maxlen=4, jsonl_path=path)
        sink.emit("hello", {})
        sink.close()
        lines = [json.loads(l) for l in open(path)]
        assert [l["kind"] for l in lines] == ["hello", "metrics_snapshot"]
        snap = lines[-1]["data"]["snapshot"]
        assert snap["metrics"]["done_total"]["series"][0]["value"] == 1.0
        # Mirror-only: the live ring and its loss accounting are
        # untouched by the terminal line.
        assert [e.kind for e in sink.events()] == ["hello"]
        assert sink.dropped_events == 0

    def test_close_quiet_with_empty_registry(self, reg, tmp_path):
        from gossipy_tpu.telemetry import TelemetrySink
        path = str(tmp_path / "e.jsonl")
        sink = TelemetrySink(jsonl_path=path)
        sink.close()
        assert open(path).read() == ""


class TestJSONLSchemaV7:
    def test_parse_line_v1_to_v8_roundtrip(self):
        from gossipy_tpu.simulation.events import JSONLinesReceiver
        assert JSONLinesReceiver.SCHEMA == 8
        base = {"round": 1, "sent": 2, "failed": 0, "size": 4,
                "local": None, "global": None}
        v = dict(base)
        by_version = {1: dict(v)}
        for schema, field in ((2, "failed_by_cause"), (3, "probes"),
                              (4, "health"), (5, "chaos"), (6, "perf"),
                              (7, "metrics"), (8, "cohort")):
            v = dict(v)
            v[field] = None
            by_version[schema] = dict(v)
        for schema, row in by_version.items():
            row = dict(row, schema=schema)
            parsed = JSONLinesReceiver.parse_line(json.dumps(row))
            # Every version normalizes to the v8 shape: all fields
            # present, absent ones null, nothing else invented.
            for field in ("failed_by_cause", "probes", "health",
                          "chaos", "perf", "metrics", "cohort"):
                assert field in parsed and parsed[field] is None
            assert parsed["round"] == 1
        # Unknown future fields pass through untouched.
        v9 = dict(by_version[8], schema=9, shiny="new")
        assert JSONLinesReceiver.parse_line(json.dumps(v9))["shiny"] \
            == "new"


@pytest.fixture
def key():
    import jax
    return jax.random.PRNGKey(0)


class TestEngineMetricsFeed:
    def test_counters_match_report_and_jsonl_v7(self, reg, key, tmp_path):
        from gossipy_tpu.analysis.hlo import _make_sim
        from gossipy_tpu.simulation.events import JSONLinesReceiver
        sim = _make_sim(metrics=True, drop_prob=0.2)
        path = str(tmp_path / "run.jsonl")
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rx)
            st = sim.init_nodes(key)
            st, rep1 = sim.start(st, n_rounds=3, key=key)
            st, rep2 = sim.start(st, n_rounds=2, key=key)
        snap = reg.snapshot()["metrics"]
        sent = (int(np.asarray(rep1.sent_per_round).sum())
                + int(np.asarray(rep2.sent_per_round).sum()))
        failed = rep1.failed_messages + rep2.failed_messages
        assert snap["engine_rounds_total"]["series"][0]["value"] == 5
        assert snap["engine_messages_sent_total"]["series"][0][
            "value"] == sent
        by_cause = {s["labels"]["cause"]: s["value"]
                    for s in snap["engine_messages_failed_total"][
                        "series"]}
        assert sum(by_cause.values()) == failed
        assert set(by_cause) == {"drop", "offline", "overflow"}
        rows = [JSONLinesReceiver.parse_line(l) for l in open(path)]
        assert [r["metrics"]["rounds_total"] for r in rows] == \
            [1, 2, 3, 4, 5]
        assert rows[-1]["metrics"]["sent_total"] == sent
        assert rows[-1]["metrics"]["failed_total"] == failed

    def test_metrics_off_feeds_nothing(self, reg, key):
        from gossipy_tpu.analysis.hlo import _make_sim
        sim = _make_sim()
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=2, key=key)
        assert reg.snapshot()["metrics"] == {}

    @pytest.mark.slow
    def test_metrics_on_is_hlo_neutral(self):
        from gossipy_tpu.analysis import assert_identical_hlo
        from gossipy_tpu.analysis.hlo import _make_sim
        assert_identical_hlo(_make_sim(), _make_sim(metrics=True),
                             label="metrics-on")


class TestLoadgenEndToEnd:
    def test_small_sustained_arrival_run(self, reg, tmp_path):
        from gossipy_tpu.service.slo import run_load
        pool = [dict(dataset="spambase", subsample=200, n_nodes=12,
                     n_rounds=3, delta=20, batch_size=8,
                     topology_params={"degree": 4}),
                dict(dataset="spambase", subsample=200, n_nodes=14,
                     n_rounds=3, delta=20, batch_size=8,
                     topology_params={"degree": 4})]
        result = run_load(str(tmp_path / "runs"), pool=pool, n_tenants=3,
                          rate_per_hour=3600.0, seed=0, slice_rounds=2,
                          metrics_dir=str(tmp_path / "metrics"),
                          registry=reg, time_scale=0.001)
        row, queue = result["row"], result["queue"]
        raw = row["raw"]
        assert row["metric"] == "service_slo"
        assert row["unit"] == "tenants/hour"
        # The acceptance trio, present and sane.
        assert raw["tenants_per_hour"] > 0
        assert raw["ttfr_p99_ms"] > 0
        assert raw["round_p99_ms"] > 0
        assert raw["ttfr_p50_ms"] <= raw["ttfr_p99_ms"]
        # Every admitted tenant accounted for.
        assert raw["n_admitted"] == 3
        assert raw["n_failed"] == 0
        assert raw["ttfr_missing"] == []
        assert raw["ttfr_recorded"] == raw["n_admitted"]
        for h in queue.handles():
            assert h.first_round_at is not None
            assert h.first_round_at >= h.submitted_at
            m = json.load(open(h.artifacts["manifest"]))
            slo = m["extra"]["service"]["slo"]
            assert slo["ttfr_seconds"] is not None
            assert slo["rounds_completed"] == 3
        # The metrics artifacts the status board / scrapers consume.
        snap = json.load(open(tmp_path / "metrics" / "metrics.json"))
        assert snap["metrics"]["service_ttfr_seconds"]["series"]
        om = (tmp_path / "metrics" / "metrics.prom").read_text()
        assert om.endswith("# EOF\n")
        assert "service_round_seconds_bucket" in om
        # service_top renders a frame from the snapshot without error.
        import importlib.util
        import pathlib
        spec = importlib.util.spec_from_file_location(
            "service_top", pathlib.Path(__file__).resolve().parents[1]
            / "scripts" / "service_top.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        frame = mod.render(snap, "metrics.json")
        assert "tenants   admitted     3" in frame
        assert "ttfr" in frame
