"""Single-pass fused deliver (fused_merge="multi") engine contracts.

The multi-slot kernel drains a K-slot mailbox cell in ONE pallas launch
followed by ONE vmapped ``handler.update``. Contracts pinned here:

- **Fan-in-1 parity**: on a directed cycle every receiver has at most one
  live message per round, so the compound blend degenerates to the
  per-slot blend — the multi path must reproduce the UNFUSED engine
  bit-for-bit (fp32/bf16) / within dequant tolerance (int8) at
  ``mailbox_slots=4``, including the probe layer's accepted-count and
  staleness-histogram tables bit-for-bit.
- **Accounting independence**: the integer accounting (sent/failed,
  accepted-per-node, staleness histogram) is computed from the mailbox
  tables alone, so it stays bit-equal to the per-slot path even at
  clique fan-in where the params trajectories legitimately diverge
  (compound merge + single train vs interleaved merge+train per slot).
- **Single-launch property**: the traced round program contains exactly
  one pallas_call for fused-multi, zero unfused, two for compact+fused
  (both cond branches) — counted on the jaxpr, not profiled.
"""

import warnings

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import (AntiEntropyProtocol, CreateModelMode,
                              Topology, UniformDelay)
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import GossipSimulator

N = 12
K = 4
DTYPES = ("float32", "bfloat16", "int8")


def directed_cycle(n):
    """Each node sends to exactly one successor: fan-in 1 by construction."""
    return Topology(np.roll(np.eye(n, dtype=bool), 1, axis=1))


def make_sim(fused, n_nodes=N, topology=None, history_dtype="float32",
             **kw):
    rng = np.random.default_rng(11)
    d = 10
    X = rng.normal(size=(24 * n_nodes, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25,
                                                    seed=1), n=n_nodes)
    handler = SGDHandler(model=LogisticRegression(d, 2),
                         loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.1), local_epochs=1,
                         batch_size=8, n_classes=2, input_shape=(d,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    if topology is None:
        topology = directed_cycle(n_nodes)
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "ignore", message=r"mailbox_slots=\d+ may overflow")
        return GossipSimulator(handler, topology, disp.stacked(), delta=100,
                               protocol=AntiEntropyProtocol.PUSH,
                               fused_merge=fused, mailbox_slots=K,
                               history_dtype=history_dtype, **kw)


def run(sim, key, rounds=6):
    st = sim.init_nodes(key, common_init=True)
    st, rep = sim.start(st, n_rounds=rounds, key=key, donate_state=False)
    jax.block_until_ready(st.model.params)
    return st, rep


def assert_params_close(sa, sb, atol):
    for a, b in zip(jax.tree_util.tree_leaves(sa.model.params),
                    jax.tree_util.tree_leaves(sb.model.params)):
        if atol == 0:
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        else:
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=atol)


def assert_accounting_bit_equal(ra, rb):
    assert int(ra.sent_messages) == int(rb.sent_messages)
    assert int(ra.failed_messages) == int(rb.failed_messages)
    np.testing.assert_array_equal(ra.probe_accepted_per_node,
                                  rb.probe_accepted_per_node)
    np.testing.assert_array_equal(ra.probe_stale_hist, rb.probe_stale_hist)


class TestMultiParity:
    @pytest.mark.parametrize("history_dtype", DTYPES)
    def test_cycle_matches_unfused(self, key, history_dtype):
        """K>1 mailbox, fan-in 1: multi == unfused — params exact for
        exact wire formats, within dequant tolerance for int8; probe
        accepted counts and staleness histograms bit-equal."""
        sa, ra = run(make_sim(False, history_dtype=history_dtype,
                              probes=True), key)
        sb, rb = run(make_sim("multi", history_dtype=history_dtype,
                              probes=True), key)
        assert_params_close(sa, sb,
                            atol=0.0 if history_dtype != "int8" else 1e-6)
        assert_accounting_bit_equal(ra, rb)

    def test_cycle_matches_per_slot(self, key):
        """At fan-in 1 the compound and interleaved semantics coincide:
        multi == the legacy per-slot fused path bit-for-bit."""
        sa, ra = run(make_sim("per_slot", probes=True), key)
        sb, rb = run(make_sim("multi", probes=True), key)
        assert_params_close(sa, sb, atol=0.0)
        assert_accounting_bit_equal(ra, rb)

    def test_cycle_with_delays(self, key):
        """Delayed messages accumulate real staleness across the K slots;
        fan-in stays 1 per ROUND on the cycle only without delay, so this
        leg checks the compound path converges rather than bit-parity."""
        sim = make_sim("multi", delay=UniformDelay(0, 150))
        _, rep = run(sim, key, rounds=10)
        acc = rep.curves(local=False)["accuracy"]
        assert acc[-1] > 0.7, acc

    def test_clique_accounting_bit_equal(self, key):
        """Clique fan-in > 1: params legitimately diverge (documented
        compound-merge semantics) but every integer accounting surface
        must be bit-equal to the per-slot fused path."""
        _, ra = run(make_sim("per_slot", topology=Topology.clique(N),
                             probes=True), key)
        _, rb = run(make_sim("multi", topology=Topology.clique(N),
                             probes=True), key)
        assert_accounting_bit_equal(ra, rb)

    def test_true_normalizes_to_multi(self, key):
        sim = make_sim(True)
        assert sim.fused_merge == "multi"


class TestLaunchCount:
    def test_round_program_launch_counts(self):
        """The static single-launch property, counted on the traced round
        program (the same gate scripts/hlo_gate.py enforces in CI):
        unfused traces no pallas_call, fused-multi exactly ONE for the
        whole K-slot mailbox, compact+fused one per cond branch."""
        from gossipy_tpu.analysis.hlo import _make_sim, pallas_launch_count

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=r"mailbox_slots=\d+ may overflow")
            assert pallas_launch_count(_make_sim()) == 0
            assert pallas_launch_count(
                _make_sim(fused_merge=True, mailbox_slots=K)) == 1
            assert pallas_launch_count(
                _make_sim(fused_merge=True, compact_deliver=8,
                          mailbox_slots=K)) == 2
