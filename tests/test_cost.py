"""ISSUE-10: performance observability layer (telemetry.cost).

Covers the acceptance criteria:

- ``perf=None`` (and ``perf=True`` — the layer is host-side only) trace
  byte-identical HLO;
- a perf-enabled CPU run of the 100-node LogReg config produces a
  RunManifest ``perf`` block with non-null FLOPs/bytes/compile stats;
- per-phase time attribution sums to the full round time within 5%;
- analytic-vs-XLA FLOP cross-check within tolerance on LogReg (full
  engine round) and CNN (handler update program) configs;
- the scale ladder emits ≥ 4 predicted-vs-measured rungs on CPU, and an
  injected OOM produces a verdict naming the failing rung/program with
  its ``memory_analysis()`` numbers plus a flight-recorder bundle whose
  own verdict carries the ``perf`` section;
- report schema 6 / JSONL schema 6 round-trip and version tolerance.
"""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

import jax  # noqa: E402
import optax  # noqa: E402

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
    Topology  # noqa: E402
from gossipy_tpu.data import ClassificationDataHandler, \
    DataDispatcher  # noqa: E402
from gossipy_tpu.handlers import SGDHandler, losses  # noqa: E402
from gossipy_tpu.models import LogisticRegression  # noqa: E402
from gossipy_tpu.simulation import GossipSimulator, \
    JSONLinesReceiver  # noqa: E402
from gossipy_tpu.simulation.events import CallbackReceiver  # noqa: E402
from gossipy_tpu.simulation.report import REPORT_SCHEMA, \
    SimulationReport  # noqa: E402
from gossipy_tpu.telemetry.cost import (  # noqa: E402
    CostReport,
    PerfConfig,
    analytic_round_cost,
    cost_report_for,
    differential_phase_attribution,
    hlo_op_phases,
    jaxpr_flops,
    mfu_estimate,
    peak_flops,
    perf_event_row,
    phase_times_from_trace,
)

N = 24
D = 8


def make_data(n_nodes=N, d=D, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(20 * n_nodes, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.int64)
    return X, y


def make_sim(n_nodes=N, d=D, local_epochs=1, **kwargs):
    X, y = make_data(n_nodes, d)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes, eval_on_user=False)
    handler = SGDHandler(
        model=LogisticRegression(d, 2), loss=losses.cross_entropy,
        optimizer=optax.sgd(0.1), local_epochs=local_epochs, batch_size=8,
        n_classes=2, input_shape=(d,),
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    kwargs.setdefault("delta", 20)
    kwargs.setdefault("protocol", AntiEntropyProtocol.PUSH)
    return GossipSimulator(handler,
                           Topology.random_regular(n_nodes, 4, seed=3),
                           disp.stacked(), **kwargs)


class TestPerfConfig:
    def test_coerce(self):
        assert PerfConfig.coerce(None) is None
        assert PerfConfig.coerce(False) is None
        cfg = PerfConfig.coerce(True)
        assert cfg == PerfConfig() and cfg.cost and cfg.timing
        same = PerfConfig(analytic=False)
        assert PerfConfig.coerce(same) is same
        assert PerfConfig.coerce(
            PerfConfig(cost=False, analytic=False, timing=False)) is None
        with pytest.raises(TypeError, match="perf="):
            PerfConfig.coerce("yes")

    def test_to_dict(self):
        d = PerfConfig(timing=False).to_dict()
        assert d == {"cost": True, "analytic": True, "timing": False}


class TestCostReport:
    def test_from_compiled_and_peak_bytes(self):
        import jax.numpy as jnp

        def f(x, y):
            return (x @ y).sum()

        comp = jax.jit(f).lower(jnp.ones((32, 32)),
                                jnp.ones((32, 32))).compile()
        cr = CostReport.from_compiled(comp, label="t", n_rounds=1)
        assert cr.flops and cr.flops > 0
        assert cr.bytes_accessed and cr.bytes_accessed > 0
        assert cr.argument_bytes == 2 * 32 * 32 * 4
        assert cr.peak_bytes == cr.argument_bytes + cr.output_bytes \
            + cr.temp_bytes - (cr.alias_bytes or 0)
        d = cr.to_dict()
        assert d["label"] == "t" and d["peak_bytes"] == cr.peak_bytes

    def test_missing_fields_are_null_safe(self):
        cr = CostReport(label="x")
        assert cr.peak_bytes is None
        assert cr.to_dict()["flops"] is None

    def test_mfu_estimate_null_safety(self):
        assert mfu_estimate(None, 1.0) is None
        assert mfu_estimate(1e9, None) is None
        assert mfu_estimate(1e9, 1.0, "cpu") is None  # no peak entry
        assert mfu_estimate(197e12, 1.0, "TPU v5e") == pytest.approx(1.0)
        assert peak_flops("no-such-chip") is None


class TestHLONeutral:
    def test_perf_off_and_on_trace_identical_hlo(self):
        from gossipy_tpu.analysis.hlo import assert_identical_hlo
        assert_identical_hlo(make_sim(), make_sim(perf=None),
                             label="perf=None")
        # Stronger than the probes/sentinels/chaos contract: perf is
        # host-side only, so even perf=ON must be HLO-neutral.
        assert_identical_hlo(make_sim(), make_sim(perf=True),
                             label="perf=True")


class TestEngineIntegration:
    def test_perf_rows_and_summary(self, key):
        sim = make_sim(perf=True)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=3, key=key, donate_state=False)
        assert rep.perf_round_ms is not None \
            and rep.perf_round_ms.shape == (3,)
        assert np.isfinite(rep.perf_round_ms).all() \
            and (rep.perf_round_ms > 0).all()
        # No CPU entry in the peak table -> MFU estimate is NaN, never a
        # made-up number.
        assert np.isnan(np.asarray(rep.perf_mfu_est)).all()
        ps = sim.perf_summary()
        assert ps["compile_count"] == 1
        assert ps["flops_per_round_xla"] > 0
        assert ps["bytes_per_round_xla"] > 0
        assert ps["hbm_peak_bytes"] > 0
        assert ps["last_run"]["rounds"] == 3
        assert ps["last_run"]["mfu_est"] is None
        assert ps["programs"][0]["label"].startswith("start[3r]")
        # Warm re-drive: no new program, timing updates.
        st, rep2 = sim.start(st, n_rounds=3, key=key, donate_state=False)
        assert sim.perf_summary()["compile_count"] == 1
        assert sim._perf_last["cold"] is False

    def test_perf_off_keeps_everything_null(self, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=2, key=key)
        assert rep.perf_round_ms is None and rep.perf_mfu_est is None
        assert sim.perf_summary() is None
        m = sim.run_manifest().to_dict()
        assert m["perf"] is None and m["config"]["perf"] is None

    def test_manifest_perf_block_100node_logreg_cpu(self, key):
        # The ISSUE-10 acceptance config: 100-node LogReg on CPU with
        # perf on -> non-null FLOPs / bytes / compile stats, null-safe
        # MFU.
        sim = make_sim(n_nodes=100, perf=True)
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=2, key=key)
        m = sim.run_manifest().to_dict()
        perf = m["perf"]
        assert perf is not None
        assert perf["flops_per_round_xla"] > 0
        assert perf["bytes_per_round_xla"] > 0
        assert perf["hbm_peak_bytes"] > 0
        assert perf["compile_count"] >= 1
        assert perf["last_run"]["ms_per_round"] > 0
        assert perf["peak_flops"] is None  # CPU: no peak entry
        assert perf["analytic"]["flops_per_round"] > 0
        assert m["config"]["perf"] == {"cost": True, "analytic": True,
                                       "timing": True}
        json.dumps(m)  # the whole record stays JSON-able

    def test_update_perf_events_and_jsonl(self, key, tmp_path):
        rows_cb = []
        path = str(tmp_path / "run.jsonl")
        sim = make_sim(perf=True)
        sim.add_receiver(CallbackReceiver(rows_cb.append))
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rx)
            st = sim.init_nodes(key)
            sim.start(st, n_rounds=3, key=key)
        assert len(rows_cb) == 3
        assert all(r["perf"]["round_ms"] > 0 for r in rows_cb)
        lines = [JSONLinesReceiver.parse_line(l) for l in open(path)]
        assert all(r["schema"] == 8 for r in lines)
        assert all(r["perf"] is not None and r["perf"]["round_ms"] > 0
                   for r in lines)

    def test_report_roundtrip_and_concatenate(self, key, tmp_path):
        sim = make_sim(perf=True)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=3, key=key)
        assert REPORT_SCHEMA == 7
        path = rep.save(str(tmp_path / "r.json"))
        loaded = SimulationReport.load(path)
        np.testing.assert_allclose(loaded.perf_round_ms,
                                   rep.perf_round_ms)
        cat = SimulationReport.concatenate([loaded, loaded])
        assert cat.perf_round_ms.shape == (6,)
        # A segment without perf rows degrades the concatenation to None
        # (registry contract), never to a wrong array.
        sim2 = make_sim()
        st2 = sim2.init_nodes(key)
        _, rep2 = sim2.start(st2, n_rounds=3, key=key)
        assert SimulationReport.concatenate(
            [rep, rep2]).perf_round_ms is None

    def test_run_repetitions_banks_cost(self):
        sim = make_sim(perf=True)
        keys = jax.random.split(jax.random.PRNGKey(0), 2)
        sim.run_repetitions(2, keys)
        labels = [cr.label for cr in sim._cost_reports]
        assert any(lbl.startswith("run_repetitions[2rx2]")
                   for lbl in labels)


class TestAnalyticCrossCheck:
    def test_logreg_engine_round_within_tolerance(self, key):
        # Full engine round on the LogReg config: the analytic
        # dominant-term count and XLA's post-optimization count are
        # different cost models (XLA adds eval sorting, masking and
        # elementwise work; fusion removes others) — the cross-check
        # guards order-of-magnitude drift, factor 5 band.
        sim = make_sim(n_nodes=32, perf=True)
        st = sim.init_nodes(key)
        cr = cost_report_for(sim, st, key, n_rounds=1)
        a = analytic_round_cost(sim)
        assert a["flops_per_round"] > 0 and cr.flops > 0
        ratio = a["flops_per_round"] / cr.flops
        assert 1 / 5 < ratio < 5, (a["flops_per_round"], cr.flops)
        # Executed estimate scales the deliver pass by expected fan-in;
        # at eval_every=1 (this config) there is no eval amortization
        # pulling the other way, so executed >= counted.
        assert a["flops_per_round_executed"] >= a["flops_per_round"]
        assert a["bytes_per_round"] > 0

    def test_cnn_update_program_within_tolerance(self):
        # CNN config, handler-level: the jaxpr counter must price the
        # conv/einsum training math of CIFAR10Net within a factor of 3
        # of XLA's own count for the SAME one-node update program.
        from gossipy_tpu.models import CIFAR10Net
        handler = SGDHandler(
            model=CIFAR10Net(), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.05), local_epochs=1, batch_size=4,
            n_classes=10, input_shape=(32, 32, 3),
            create_model_mode=CreateModelMode.MERGE_UPDATE)
        key = jax.random.PRNGKey(0)
        st = jax.eval_shape(handler.init, key)
        rng = np.random.default_rng(0)
        data = (rng.normal(size=(4, 32, 32, 3)).astype(np.float32),
                rng.integers(0, 10, 4),
                np.ones(4, np.float32))
        sds = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in data)
        analytic = jaxpr_flops(jax.make_jaxpr(handler.update)(st, sds,
                                                              key))
        comp = jax.jit(handler.update).lower(
            jax.eval_shape(handler.init, key), sds, key).compile()
        xla = CostReport.from_compiled(comp, "cnn-update").flops
        assert analytic > 0 and xla > 0
        ratio = analytic / xla
        assert 1 / 3 < ratio < 3, (analytic, xla)

    def test_jaxpr_flops_scan_multiplies_by_length(self):
        import jax.numpy as jnp

        def body(c, _):
            return c @ c, None

        def once(x):
            return x @ x

        def scanned(x):
            return jax.lax.scan(body, x, None, length=7)[0]

        x = jax.ShapeDtypeStruct((16, 16), jnp.float32)
        f1 = jaxpr_flops(jax.make_jaxpr(once)(x))
        f7 = jaxpr_flops(jax.make_jaxpr(scanned)(x))
        assert f7 == pytest.approx(7 * f1)


class TestPhaseAttribution:
    def test_differential_sums_to_total_within_5pct(self, key):
        att = differential_phase_attribution(
            lambda **ov: make_sim(**ov), rounds=4, key=key)
        phases = att["phases_ms"]
        assert set(phases) == {"eval", "train", "exchange_and_overhead"}
        total = sum(phases.values())
        assert abs(total - att["full_ms"]) <= 0.05 * att["full_ms"], att

    def test_trace_parser_and_hlo_bridge(self, tmp_path):
        import gzip

        # Synthetic perfetto-style trace: one event carries the scope in
        # its metadata (TPU XProf shape), one carries only a bare HLO op
        # name (CPU runtime shape) that the HLO bridge maps, and one is
        # unrelated noise. A mirrored second file must NOT double-count.
        events = [
            {"ph": "X", "dur": 1000.0, "name": "fusion.1",
             "args": {"long_name":
                      "jit(run)/while/body/gossipy.send/dynamic_slice"}},
            {"ph": "X", "dur": 2000.0, "name": "custom-call.7"},
            {"ph": "X", "dur": 500.0, "name": "unrelated.2"},
            {"ph": "M", "name": "process_name"},
        ]
        doc = json.dumps({"traceEvents": events})
        for fname in ("a.trace.json.gz", "perfetto_trace.json.gz"):
            with gzip.open(tmp_path / fname, "wt") as fh:
                fh.write(doc)
        hlo = ('  %custom-call.7 = f32[8]{0} custom-call(), '
               'metadata={op_name="jit(run)/while/body/'
               'gossipy.receive_merge/gossipy.train/dot_general" '
               'source_file="x.py"}\n')
        op_map = hlo_op_phases(hlo)
        # Deepest scope wins: the op nests train inside receive_merge.
        assert op_map == {"custom-call.7": "gossipy.train"}
        out = phase_times_from_trace(str(tmp_path), op_to_phase=op_map)
        assert out == {"gossipy.send": 1.0, "gossipy.train": 2.0}

    def test_trace_parser_returns_none_without_phases(self, tmp_path):
        (tmp_path / "t.json").write_text(json.dumps(
            {"traceEvents": [{"ph": "X", "dur": 5.0, "name": "op.1"}]}))
        assert phase_times_from_trace(str(tmp_path)) is None
        assert phase_times_from_trace(str(tmp_path / "missing")) is None

    def test_perf_event_row(self):
        assert perf_event_row({}) is None
        row = perf_event_row({"perf_round_ms": 1.5,
                              "perf_mfu_est": float("nan")})
        assert row == {"round_ms": 1.5, "mfu_est": None}


def _load_ladder():
    spec = importlib.util.spec_from_file_location(
        "scale_ladder", os.path.join(REPO, "scripts", "scale_ladder.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def ladder(monkeypatch):
    mod = _load_ladder()
    import _virtual_mesh
    # The in-process backend is already CPU under the test harness; the
    # subprocess liveness probe (and its re-exec fallback) would only
    # slow the test down.
    monkeypatch.setattr(_virtual_mesh, "probe_backend_alive",
                        lambda: (True, "test"))
    return mod


class TestScaleLadder:
    def test_smoke_emits_four_predicted_vs_measured_rungs(self, ladder,
                                                          tmp_path):
        out = str(tmp_path / "l")
        rc = ladder.main(["--rungs", "12,16,20,24", "--rounds", "2",
                          "--degree", "3", "--out", out])
        assert rc == 0
        data = json.load(open(os.path.join(out, "ladder.json")))
        assert data["verdict"] is None
        assert len(data["rungs"]) >= 4
        for i, row in enumerate(data["rungs"]):
            assert row["predicted"]["total_bytes"] > 0
            assert row["predicted"]["flops_per_round"] > 0
            assert row["measured"]["ms_per_round"] > 0
            assert row["measured"]["hbm_peak_bytes"] > 0
            assert row["measured"]["flops_per_round_xla"] > 0
            if i > 0:  # linear-in-N prediction from the previous rung
                assert row["predicted"]["ms_per_round"] > 0
        md = open(os.path.join(out, "ladder.md")).read()
        assert md.count("\n| 1") >= 2  # markdown rows present

    def test_injected_oom_verdict_names_rung_and_program(self, ladder,
                                                         tmp_path):
        out = str(tmp_path / "l")
        rc = ladder.main(["--rungs", "12,16", "--rounds", "2",
                          "--degree", "3", "--out", out,
                          "--fail-at", "16"])
        assert rc == 1
        data = json.load(open(os.path.join(out, "ladder.json")))
        v = data["verdict"]
        assert v["failed_rung"] == 16
        assert v["last_healthy_rung"] == 12
        # The failing PROGRAM and its memory_analysis() numbers, banked
        # at compile time — available even though the run died.
        assert v["program"].startswith("start[")
        assert v["memory_analysis"]["peak_bytes"] > 0
        assert v["memory_analysis"]["temp_bytes"] >= 0
        assert "RESOURCE_EXHAUSTED" in v["error"]
        # The flight-recorder bundle exists and its own verdict carries
        # the perf section (ISSUE-10 satellite: dead-run bundles carry
        # the performance context of the failure).
        assert v["bundle"] and os.path.isdir(v["bundle"])
        bundle_verdict = json.load(
            open(os.path.join(v["bundle"], "verdict.json")))
        assert bundle_verdict["kind"] == "exception"
        assert bundle_verdict["perf"] is not None
        assert bundle_verdict["perf"]["compile_count"] >= 1
        assert bundle_verdict["perf"]["hbm_peak_bytes"] > 0


class TestSchemaV6:
    def test_parse_line_fills_perf_for_older_schemas(self):
        v5 = json.dumps({"schema": 5, "round": 3, "sent": 4, "failed": 0,
                         "failed_by_cause": None, "probes": None,
                         "health": None, "chaos": None, "size": 8,
                         "local": None, "global": None})
        row = JSONLinesReceiver.parse_line(v5)
        assert row["perf"] is None and row["chaos"] is None
        v1 = json.dumps({"schema": 1, "round": 1, "sent": 1, "failed": 0,
                         "size": 2, "local": None, "global": None})
        assert JSONLinesReceiver.parse_line(v1)["perf"] is None
        assert JSONLinesReceiver.SCHEMA == 8  # v8: + "cohort"

    def test_report_from_dict_tolerates_missing_perf(self):
        rep = SimulationReport(metric_names=["accuracy"],
                               local_evals=None, global_evals=None,
                               sent=np.ones(2, np.int64),
                               failed=np.zeros(2, np.int64),
                               total_size=4)
        d = rep.to_dict()
        assert d["schema"] == 7 and d["perf_round_ms"] is None
        back = SimulationReport.from_dict(d)
        assert back.perf_round_ms is None

    def test_flight_recorder_verdict_perf_null_without_perf(
            self, key, tmp_path):
        from gossipy_tpu.telemetry import FlightRecorder
        sim = make_sim(sentinels=True)  # perf OFF
        rec = FlightRecorder(str(tmp_path), chunk=2)
        st = sim.init_nodes(key)
        path = rec.write_bundle(sim, st, np.asarray(key), "exception", 0,
                                detail={"error": "t"})
        v = json.load(open(os.path.join(path, "verdict.json")))
        assert v["perf"] is None  # null-safe, not absent
