"""Partition-rule registry (parallel/rules.py): coverage + derivation.

The acceptance contract of ISSUE-14's tentpole: every sharding in
``parallel/`` derives from the rule registry, an unmatched state leaf is
an ERROR (not a silent replicate), and no hand-placed ``PartitionSpec``
exists outside ``rules.py``.
"""

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import MLP
from gossipy_tpu.parallel import (
    STATE_RULES,
    UnmatchedLeafError,
    make_mesh,
    make_shard_and_gather_fns,
    match_partition_rules,
    partition_specs,
    shard_data,
    state_shardings,
)
from gossipy_tpu.parallel.rules import (
    named_leaves,
    resolved_rules_table,
    rules_table,
)
from gossipy_tpu.simulation import GossipSimulator

REPO = Path(__file__).resolve().parents[1]


def build(n_nodes=16, history_dtype="float32"):
    rng = np.random.default_rng(0)
    d = 6
    w = rng.normal(size=d)
    X = rng.normal(size=(n_nodes * 12, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                          n=n_nodes)
    handler = SGDHandler(model=MLP(d, 2, hidden_dims=(8,)),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.2),
                         local_epochs=1, batch_size=4, n_classes=2,
                         input_shape=(d,))
    sim = GossipSimulator(handler, Topology.clique(n_nodes), disp.stacked(),
                          delta=10, protocol=AntiEntropyProtocol.PUSH,
                          history_dtype=history_dtype)
    return sim, disp


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


class TestCoverage:
    def test_every_state_leaf_matches_a_rule(self, key):
        sim, _ = build()
        st = sim.init_nodes(key)
        specs = match_partition_rules(STATE_RULES, st)
        assert jax.tree_util.tree_structure(specs) \
            == jax.tree_util.tree_structure(st)

    def test_int8_sidecar_and_aux_leaves_covered(self, key):
        # The history_scale sidecars and variant aux state are exactly
        # the leaf families a hand-placed scheme forgets.
        from gossipy_tpu.simulation import PENSGossipSimulator
        sim, disp = build(history_dtype="int8")
        st = sim.init_nodes(key)
        table = dict(resolved_rules_table(st))
        scale_rows = [p for p in table if p.startswith("history_scale/")]
        assert scale_rows and all(table[p] == "node_axis@1"
                                  for p in scale_rows)

        n_nodes, d = 16, 6
        rng = np.random.default_rng(0)
        X = rng.normal(size=(n_nodes * 12, d)).astype(np.float32)
        y = (X @ rng.normal(size=d) > 0).astype(np.int64)
        disp = DataDispatcher(
            ClassificationDataHandler(X, y, test_size=0.25), n=n_nodes)
        from gossipy_tpu.core import CreateModelMode
        handler = SGDHandler(model=MLP(d, 2, hidden_dims=(8,)),
                             loss=losses.cross_entropy,
                             optimizer=optax.sgd(0.2), local_epochs=1,
                             batch_size=4, n_classes=2, input_shape=(d,),
                             create_model_mode=CreateModelMode.MERGE_UPDATE)
        pens = PENSGossipSimulator(handler, Topology.clique(n_nodes),
                                   disp.stacked(), delta=10, n_sampled=4,
                                   m_top=2, step1_rounds=3)
        st_p = pens.init_nodes(key)
        table_p = dict(resolved_rules_table(st_p))
        aux_rows = [p for p in table_p if p.startswith("aux/")]
        assert aux_rows and all(table_p[p] == "node_axis@0"
                                for p in aux_rows)

    def test_unmatched_leaf_raises(self):
        tree = {"model": {"params": {"w": jnp.zeros((4, 2))}},
                "mystery_field": jnp.zeros((4,))}
        with pytest.raises(UnmatchedLeafError, match="mystery_field"):
            match_partition_rules(STATE_RULES, tree)

    def test_state_shardings_fails_on_unknown_state_leaf(self, key):
        # The end-to-end coverage contract: a SimState grown a new field
        # (simulated via a raw dict with an unknown key) cannot be
        # silently placed.
        mesh = make_mesh(8)
        with pytest.raises(UnmatchedLeafError):
            partition_specs({"new_sidecar": jnp.zeros((4, 4))}, mesh)


class TestDerivation:
    def test_state_shardings_equal_rule_resolution(self, key):
        sim, _ = build()
        st = sim.init_nodes(key)
        mesh = make_mesh(8)
        sh = state_shardings(st, mesh)
        # Spot-check the resolved families against the table semantics.
        for _, s in named_leaves(jax.tree.map(lambda x: x.spec,
                                              sh.model.params)):
            assert s[0] == "nodes"
        assert sh.mailbox.sender.spec[1] == "nodes"
        assert sh.history_ages.spec[1] == "nodes"
        assert sh.round.spec == ()
        assert sh.phase.spec[0] == "nodes"

    def test_batch_dims_shift(self, key):
        # Megabatch placement: a leading [T] lane axis stays replicated,
        # the node axis moves one right (the scheduler's mesh path).
        sim, _ = build()
        st = sim.init_nodes(key)
        batched = jax.tree.map(
            lambda l: (jnp.broadcast_to(l[None], (3,) + l.shape)
                       if hasattr(l, "ndim") else l), st)
        mesh = make_mesh(8)
        sh = state_shardings(batched, mesh, batch_dims=1)
        k = jax.tree_util.tree_leaves(sh.model.params)[0]
        assert k.spec[0] is None and k.spec[1] == "nodes"
        assert sh.mailbox.sender.spec[2] == "nodes"

    def test_shard_and_gather_fns_roundtrip(self, key):
        sim, _ = build()
        st = sim.init_nodes(key)
        mesh = make_mesh(8)
        shard_fns, gather_fns = make_shard_and_gather_fns(st, mesh)
        placed = jax.tree_util.tree_leaves(
            jax.tree.map(lambda f, l: f(l), shard_fns, st))
        assert len(placed[0].sharding.device_set) == 8
        sharded = jax.tree.map(lambda f, l: f(l), shard_fns, st)
        back = jax.tree.map(lambda f, l: f(l), gather_fns, sharded)
        for a, b in zip(jax.tree_util.tree_leaves(st),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rules_table_stamp_shape(self):
        table = rules_table()
        assert all(len(row) == 2 for row in table)
        pats = [p for p, _ in table]
        assert any("history_scale" in p for p in pats)
        assert any("mailbox" in p for p in pats)

    def test_data_rules(self):
        mesh = make_mesh(8)
        data = {"xtr": np.zeros((16, 3, 4), np.float32),
                "x_eval": np.zeros((40, 4), np.float32)}
        out = shard_data(data, mesh)
        assert out["xtr"].sharding.spec[0] == "nodes"
        assert all(e is None for e in out["x_eval"].sharding.spec)


class TestNoHandPlacedSpecs:
    def test_parallel_package_constructs_specs_only_in_rules(self):
        """No ``PartitionSpec(...)`` / ``P(...)`` constructor call exists
        in parallel/ outside rules.py — the single-source-of-truth
        contract (helpers in rules.py build every spec)."""
        import ast
        pkg = REPO / "gossipy_tpu" / "parallel"
        for f in pkg.glob("*.py"):
            if f.name == "rules.py":
                continue
            tree = ast.parse(f.read_text())
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = (fn.id if isinstance(fn, ast.Name)
                        else fn.attr if isinstance(fn, ast.Attribute)
                        else None)
                assert name not in ("P", "PartitionSpec"), \
                    f"hand-placed PartitionSpec at {f.name}:{node.lineno}"


class TestSchedulerMeshPlacement:
    def test_service_megabatch_places_via_registry(self, tmp_path):
        """GossipService(mesh=): the bucket's stacked states land on the
        mesh with the rule-derived batch_dims=1 placement and tenants
        still finish with correct reports."""
        from gossipy_tpu.config import ExperimentConfig
        from gossipy_tpu.service import GossipService, RunQueue, RunRequest

        rng = np.random.default_rng(1)
        X = rng.normal(size=(240, 8)).astype(np.float32)
        y = (X @ rng.normal(size=8) > 0).astype(np.int64)
        cfg = ExperimentConfig(
            n_nodes=16, model="logreg", topology="random_regular",
            topology_params={"degree": 4}, n_rounds=4, delta=10,
            eval_every=4, seed=1, batch_size=8)
        mesh = make_mesh(8)
        svc = GossipService(out_dir=str(tmp_path), slice_rounds=2,
                            events_jsonl=False, mesh=mesh)
        q = RunQueue()
        h = q.submit(RunRequest("alice", cfg, data=(X, y)))
        session = svc.session(q)
        session.admit_pending()
        rt = session.runtimes[0]
        leaf = jax.tree_util.tree_leaves(rt.states.model.params)[0]
        assert leaf.sharding.spec[1] == "nodes"  # lane axis replicated
        assert len(leaf.sharding.device_set) == 8
        while session.poll():
            pass
        session.finish()
        assert h.status.value == "done"
        assert h.report is not None
