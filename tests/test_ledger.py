"""Run-ledger tests (telemetry.ledger + scripts/ledger.py): CRC
framing, kill-9 torn-tail recovery and repair, merge algebra
(associative/commutative/idempotent), the ingest adapters, the
engine/service opt-in contract, and the forensics CLI — ``diff`` must
NAME the changed config field and metric delta, ``bisect`` must exit
git-bisect-correct codes (0 good / 1 bad / 125 skip)."""

import dataclasses
import importlib.util
import json
import os
import pathlib
import threading

import numpy as np
import pytest

from gossipy_tpu.telemetry.ledger import (
    LEDGER_ENV,
    LEDGER_SCHEMA,
    RunLedger,
    _frame,
    config_fingerprint,
    ingest_bench_capsule,
    ingest_bundle,
    ingest_ladder,
    ingest_manifest,
    ingest_slo_row,
    ingest_trace_report,
    merge_ledger_files,
    merge_ledgers,
    resolve_ledger,
)

REPO = pathlib.Path(__file__).resolve().parents[1]


def load_script(name: str):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "scripts" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


ledger_cli = load_script("ledger")


def make_ledger(tmp_path, name="ledger.jsonl") -> RunLedger:
    return RunLedger(str(tmp_path / name))


# ---------------------------------------------------------------------------
# Framing + crash safety (the tentpole contract)


class TestFraming:
    def test_append_read_roundtrip(self, tmp_path):
        led = make_ledger(tmp_path)
        r1 = led.append({"kind": "engine", "metrics": {"x": 1.5}})
        r2 = led.append({"kind": "bench"})
        doc = led.read()
        assert doc["skipped"] == 0
        assert [r["kind"] for r in doc["rows"]] == ["engine", "bench"]
        # Stamps: schema, a 12-hex run id, a wall timestamp.
        for row in (r1, r2):
            assert row["schema"] == LEDGER_SCHEMA
            assert len(row["run_id"]) == 12
            assert isinstance(row["ts"], float)
        assert doc["rows"][0]["metrics"] == {"x": 1.5}
        assert r1["run_id"] != r2["run_id"]

    def test_explicit_run_id_and_ts_preserved(self, tmp_path):
        led = make_ledger(tmp_path)
        led.append({"kind": "engine", "run_id": "abc123", "ts": 7.0})
        row = led.rows()[0]
        assert row["run_id"] == "abc123" and row["ts"] == 7.0
        assert led.find("abc") == [row] and led.find("zzz") == []

    def test_corrupt_byte_skipped_not_fatal(self, tmp_path):
        led = make_ledger(tmp_path)
        led.append({"kind": "a"})
        led.append({"kind": "b"})
        data = bytearray(open(led.path, "rb").read())
        # Flip one payload byte of the FIRST line: its CRC fails, the
        # second line still reads.
        data[12] ^= 0xFF
        open(led.path, "wb").write(bytes(data))
        doc = led.read()
        assert doc["skipped"] == 1
        assert [r["kind"] for r in doc["rows"]] == ["b"]

    def test_non_dict_payload_skipped(self, tmp_path):
        led = make_ledger(tmp_path)
        led.append({"kind": "a"})
        with open(led.path, "ab") as fh:
            fh.write(_frame("[1,2,3]"))   # valid CRC, wrong shape
        doc = led.read()
        assert doc["skipped"] == 1 and len(doc["rows"]) == 1


class TestCrashSafety:
    def test_torn_tail_skipped_then_repaired_by_next_append(self, tmp_path):
        """The acceptance fixture: a file truncated mid-record reads back
        every complete row, and the NEXT append repairs the tail."""
        led = make_ledger(tmp_path)
        led.append({"kind": "a"})
        led.append({"kind": "b"})
        with open(led.path, "ab") as fh:       # kill -9 mid-append
            fh.write(b'deadbeef {"kind": "torn", "metr')
        doc = led.read()
        assert doc["skipped"] == 1
        assert [r["kind"] for r in doc["rows"]] == ["a", "b"]
        led.append({"kind": "c"})              # repairs, then writes
        doc = led.read()
        assert doc["skipped"] == 0
        assert [r["kind"] for r in doc["rows"]] == ["a", "b", "c"]
        raw = open(led.path, "rb").read()
        assert b"torn" not in raw and raw.endswith(b"\n")

    def test_truncated_final_record(self, tmp_path):
        led = make_ledger(tmp_path)
        for k in ("a", "b", "c"):
            led.append({"kind": k})
        size = os.path.getsize(led.path)
        with open(led.path, "rb+") as fh:      # torn inside row "c"
            fh.truncate(size - 7)
        doc = led.read()
        assert doc["skipped"] == 1
        assert [r["kind"] for r in doc["rows"]] == ["a", "b"]
        led.append({"kind": "d"})
        doc = led.read()
        assert doc["skipped"] == 0
        assert [r["kind"] for r in doc["rows"]] == ["a", "b", "d"]

    def test_missing_file_is_empty_and_parents_created(self, tmp_path):
        led = RunLedger(str(tmp_path / "deep" / "nested" / "l.jsonl"))
        assert led.read() == {"rows": [], "skipped": 0}
        led.append({"kind": "a"})
        assert len(led.rows()) == 1

    def test_concurrent_appends_never_tear(self, tmp_path):
        led = make_ledger(tmp_path)

        def work(i):
            for j in range(10):
                led.append({"kind": "t", "i": i, "j": j})

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        doc = led.read()
        assert doc["skipped"] == 0 and len(doc["rows"]) == 40


# ---------------------------------------------------------------------------
# Merge algebra (the fleet-wide index; satellite 4)


class TestMergeAlgebra:
    @pytest.fixture()
    def abc(self, tmp_path):
        out = []
        for name, kinds in (("a", ("a1", "a2")), ("b", ("b1",)),
                            ("c", ("c1", "c2", "c3"))):
            led = make_ledger(tmp_path, f"{name}.jsonl")
            for k in kinds:
                led.append({"kind": k})
            out.append(led.rows())
        return out

    def test_three_way_associative(self, abc):
        a, b, c = abc
        assert merge_ledgers(merge_ledgers(a, b), c) == \
            merge_ledgers(a, merge_ledgers(b, c))

    def test_commutative(self, abc):
        a, b, c = abc
        assert merge_ledgers(a, b) == merge_ledgers(b, a)
        assert merge_ledgers(merge_ledgers(c, a), b) == \
            merge_ledgers(merge_ledgers(b, c), a)

    def test_idempotent(self, abc):
        a, _, _ = abc
        merged = merge_ledgers(a, a)
        assert merged == merge_ledgers(a, [])   # self-union is a no-op
        assert len(merged) == len(a)
        assert merge_ledgers(merged, a) == merged

    def test_schema_mismatch_raises(self, abc):
        a, b, _ = abc
        drifted = [dict(b[0], schema=LEDGER_SCHEMA + 1)]
        with pytest.raises(ValueError, match="schema"):
            merge_ledgers(a, drifted)

    def test_merge_files_atomic_and_readable(self, tmp_path, abc):
        paths = [str(tmp_path / f"{n}.jsonl") for n in "abc"]
        out = str(tmp_path / "fleet.jsonl")
        n = merge_ledger_files(out, paths)
        assert n == 6
        doc = RunLedger(out).read()
        assert doc["skipped"] == 0 and len(doc["rows"]) == 6
        # Folding the merged file back in changes nothing (idempotent).
        assert merge_ledger_files(out, [out] + paths) == 6


class TestFingerprint:
    def test_observability_knobs_excluded(self):
        base = {"n_nodes": 8, "delta": 10}
        noisy = dict(base, tracing=True, metrics={"x": 1}, perf=True,
                     ledger=True, partition_rules=["r"])
        assert config_fingerprint(base) == config_fingerprint(noisy)

    def test_real_field_changes_it(self):
        assert config_fingerprint({"n_nodes": 8}) != \
            config_fingerprint({"n_nodes": 9})

    def test_key_order_stable_and_none_safe(self):
        assert config_fingerprint({"a": 1, "b": 2}) == \
            config_fingerprint({"b": 2, "a": 1})
        assert config_fingerprint(None) is None


# ---------------------------------------------------------------------------
# Ingest adapters — one per producer


class TestAdapters:
    def test_bench_capsule_forms(self, tmp_path):
        led = make_ledger(tmp_path)
        row = {"metric": "rounds_per_sec", "value": 123.0,
               "unit": "rounds/s",
               "raw": {"backend": "cpu", "degraded": True,
                       "degrade_reason": "cpu fallback",
                       "host_blocked_frac": 0.25, "n_nodes": 64}}
        r1 = ingest_bench_capsule(led, row)                  # bare row
        capsule_path = tmp_path / "BENCH_r3.json"
        capsule_path.write_text(json.dumps({"n": 3, "parsed": row}))
        r2 = ingest_bench_capsule(led, str(capsule_path))    # file path
        assert r1["kind"] == r2["kind"] == "bench"
        assert r1["metrics"]["rounds_per_sec"] == 123.0
        assert r1["metrics"]["host_blocked_frac"] == 0.25
        assert r1["degraded"] is True
        assert r1["failure"]["reason"] == "cpu fallback"
        assert r1["bench_row"] == row                        # lossless
        assert r2["source"] == "BENCH_r3.json"
        assert r1["config"]["n_nodes"] == 64

    def test_ladder_rungs_and_verdict(self, tmp_path):
        led = make_ledger(tmp_path)
        ladder = {"backend": "cpu", "device_kind": "cpu",
                  "rungs": [
                      {"n_nodes": 1024, "cohort_size": 64,
                       "measured": {"ms_per_round": 50.0,
                                    "mfu_est": 0.1}},
                      {"n_nodes": 4096, "failed": True, "measured": {}},
                  ],
                  "verdict": {"kind": "oom", "rung": 4096}}
        rows = ingest_ladder(led, ladder)
        assert [r["kind"] for r in rows] == \
            ["ladder_rung", "ladder_rung", "ladder_verdict"]
        assert rows[0]["metrics"]["rounds_per_sec"] == 20.0
        assert rows[0]["config_fingerprint"]
        assert rows[1]["failure"] == {"kind": "rung_failed"}
        assert rows[2]["failure"]["kind"] == "oom"

    def test_slo_row(self, tmp_path):
        led = make_ledger(tmp_path)
        row = {"metric": "service_slo", "value": 120.0,
               "unit": "tenants/hour",
               "raw": {"ttfr_p50_ms": 80.0, "ttfr_p99_ms": 450.0,
                       "n_admitted": 6, "backend": "cpu"}}
        out = ingest_slo_row(led, row)
        assert out["kind"] == "loadgen"
        assert out["metrics"]["slo_p99_ms"] == 450.0
        assert out["bench_row"] == row
        assert out["config"]["n_admitted"] == 6

    def test_bundle_failure_row(self, tmp_path):
        bundle = tmp_path / "bundle"
        bundle.mkdir()
        (bundle / "verdict.json").write_text(
            json.dumps({"kind": "nonfinite", "round": 7}))
        (bundle / "manifest.json").write_text(json.dumps(
            {"backend": {"backend": "cpu"},
             "config": {"n_nodes": 4, "partition_rules": ["x"]}}))
        led = make_ledger(tmp_path)
        row = ingest_bundle(led, str(bundle))
        assert row["kind"] == "bundle"
        assert row["failure"]["kind"] == "nonfinite"
        assert row["failure"]["verdict"]["round"] == 7
        assert row["config"] == {"n_nodes": 4}   # rules stripped
        assert row["config_fingerprint"]
        assert row["artifacts"]["verdict"]["sha256"]

    def test_trace_report(self, tmp_path):
        led = make_ledger(tmp_path)
        report = {"totals": {"host_blocked_frac": 0.2,
                             "overlap_frac": 0.5, "wall_ms": 10.0},
                  "n_windows": 2}
        row = ingest_trace_report(led, report, run_id="tr0")
        assert row["kind"] == "trace" and row["run_id"] == "tr0"
        assert row["metrics"] == {"host_blocked_frac": 0.2,
                                  "overlap_frac": 0.5}
        assert row["extra"]["n_windows"] == 2

    def test_manifest_artifacts_hashed(self, tmp_path):
        led = make_ledger(tmp_path)
        art = tmp_path / "report.json"
        art.write_text("{}")
        row = ingest_manifest(
            led, {"config": {"n_nodes": 8}, "backend": {"backend": "cpu"}},
            artifacts={"report": str(art),
                       "gone": str(tmp_path / "missing.json")})
        assert row["artifacts"]["report"]["sha256"]
        assert len(row["artifacts"]["report"]["sha256"]) == 16
        assert row["artifacts"]["gone"]["sha256"] is None
        assert row["degraded"] is True    # cpu backend
        # NaN metrics are "not measured", never stored.
        row2 = ingest_manifest(
            led, {"config": {}}, metrics={"final_accuracy": float("nan"),
                                          "mfu_est": None, "ok": 1})
        assert row2["metrics"] == {"ok": 1.0}


class TestResolveContract:
    def test_none_consults_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(LEDGER_ENV, raising=False)
        assert resolve_ledger(None) is None
        path = str(tmp_path / "env.jsonl")
        monkeypatch.setenv(LEDGER_ENV, path)
        led = resolve_ledger(None)
        assert isinstance(led, RunLedger) and led.path == path

    def test_false_is_strictly_off(self, tmp_path, monkeypatch):
        monkeypatch.setenv(LEDGER_ENV, str(tmp_path / "env.jsonl"))
        assert resolve_ledger(False) is None

    def test_path_and_instance_passthrough(self, tmp_path):
        path = str(tmp_path / "x.jsonl")
        led = resolve_ledger(path)
        assert isinstance(led, RunLedger)
        assert resolve_ledger(led) is led


# ---------------------------------------------------------------------------
# Engine wiring (tentpole ingest point #1) + satellite 1 (code_version)


def make_dataset(n_nodes, seed=0):
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    rng = np.random.default_rng(seed)
    w = rng.normal(size=6)
    X = rng.normal(size=(20 * n_nodes, 6)).astype(np.float32)
    y = (2 * (X @ w > 0) - 1).astype(np.float32)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    return DataDispatcher(dh, n=n_nodes)


def small_sim(n_nodes=16, **kwargs):
    from gossipy_tpu.core import (AntiEntropyProtocol, CreateModelMode,
                                  Topology)
    from gossipy_tpu.handlers import PegasosHandler
    from gossipy_tpu.models import AdaLine
    from gossipy_tpu.simulation import GossipSimulator
    handler = PegasosHandler(AdaLine(6), learning_rate=0.01,
                             create_model_mode=CreateModelMode.UPDATE)
    return GossipSimulator(handler, Topology.clique(n_nodes),
                           make_dataset(n_nodes).stacked(), delta=5,
                           protocol=AntiEntropyProtocol.PUSH, **kwargs)


class TestEngineLedger:
    def test_one_row_per_start_sharing_run_id(self, tmp_path, key):
        led = make_ledger(tmp_path)
        sim = small_sim(ledger=led)
        st = sim.init_nodes(key)
        st, _ = sim.start(st, n_rounds=3, key=key)
        st, _ = sim.start(st, n_rounds=2, key=key)
        doc = led.read()
        assert doc["skipped"] == 0 and len(doc["rows"]) == 2
        r1, r2 = doc["rows"]
        assert r1["kind"] == r2["kind"] == "engine"
        # Chunked-run continuity: both segments carry ONE run id.
        assert r1["run_id"] == r2["run_id"]
        assert r1["extra"]["rounds"] == 3 and r2["extra"]["rounds"] == 2
        # Same sim, same config: the fingerprint is stable and pinned.
        assert r1["config_fingerprint"] == r2["config_fingerprint"]
        assert r1["config"]["n_nodes"] == 16
        assert "partition_rules" not in r1["config"]

    def test_env_opt_in_and_false_override(self, tmp_path, key,
                                           monkeypatch):
        path = str(tmp_path / "env.jsonl")
        monkeypatch.setenv(LEDGER_ENV, path)
        sim = small_sim()                      # ledger=None -> env
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=2, key=key)
        assert len(RunLedger(path).rows()) == 1
        off = small_sim(ledger=False)          # strictly off
        assert off.ledger is None

    def test_manifest_carries_code_version_and_ledger_flag(self, tmp_path,
                                                           key):
        # Satellite 1: the RunManifest pins {git_sha, dirty} null-safely,
        # and the config snapshot records whether a ledger was attached.
        sim = small_sim(ledger=make_ledger(tmp_path))
        man = sim.run_manifest().to_dict()
        cv = man.get("code_version")
        assert cv is not None and set(cv) == {"git_sha", "dirty"}
        assert cv["git_sha"] == man["git_rev"]
        assert isinstance(cv["dirty"], bool)
        assert man["config"]["ledger"] is True
        assert small_sim().run_manifest().to_dict()["config"]["ledger"] \
            is False

    def test_ledger_identity_pair_registered(self):
        # The HLO gate's identity matrix proves ledger-on compiles the
        # same bytes as ledger-off (host-sink contract).
        from gossipy_tpu.analysis.hlo import gate_cases
        names = {case[0] for case in gate_cases()["identity"]}
        assert "engine/ledger-on" in names


@pytest.mark.slow
class TestLedgerHLOIdentity:
    def test_ledger_on_is_byte_identical(self, tmp_path):
        from gossipy_tpu.analysis import assert_identical_hlo
        from gossipy_tpu.analysis.hlo import _make_sim
        assert_identical_hlo(
            _make_sim(),
            _make_sim(ledger=RunLedger(str(tmp_path / "l.jsonl"))),
            label="engine/ledger-on")


# ---------------------------------------------------------------------------
# Service wiring: continuous tenant accounting across scheduler restarts


def tenant_data(seed, n=240, d=8):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.int64)
    return X, y


def service_cfg(**over):
    from gossipy_tpu.config import ExperimentConfig
    base = dict(n_nodes=16, model="logreg", handler="sgd",
                topology="random_regular", topology_params={"degree": 4},
                delta=20, n_rounds=6, batch_size=8)
    base.update(over)
    return ExperimentConfig(**base)


class TestServiceLedgerContinuity:
    def test_two_scheduler_sessions_one_ledger(self, tmp_path):
        """Acceptance: tenants served by TWO GossipService instances
        (a restart) land in ONE continuous ledger, each row replayable
        via its pinned experiment config."""
        from gossipy_tpu.config import ExperimentConfig
        from gossipy_tpu.service import GossipService, RunQueue, RunRequest
        path = str(tmp_path / "service.jsonl")

        for session, (tenant, seed) in enumerate(
                [("alice", 1), ("bob", 2)]):
            q = RunQueue()
            q.submit(RunRequest(tenant, service_cfg(seed=seed),
                                data=tenant_data(seed)))
            svc = GossipService(str(tmp_path / f"out{session}"),
                                slice_rounds=4, ledger=path)
            svc.serve(q)

        doc = RunLedger(path).read()
        assert doc["skipped"] == 0
        tenant_rows = [r for r in doc["rows"] if r["kind"] == "tenant"]
        assert {r["extra"]["tenant"] for r in tenant_rows} == \
            {"alice", "bob"}
        for r in tenant_rows:
            assert r["extra"]["status"] == "done"
            assert r["extra"]["rounds_completed"] == 6
            # The pinned config round-trips into a replayable object —
            # what `ledger bisect` feeds run_experiment.
            cfg = ExperimentConfig.from_dict(dict(r["experiment"]))
            assert cfg.n_nodes == 16
            assert "report" in r["artifacts"]
            assert r["artifacts"]["report"]["sha256"]


# ---------------------------------------------------------------------------
# Forensics CLI: list / show / diff / trend / merge


@pytest.fixture(scope="module")
def forensic(tmp_path_factory):
    """Two real engine runs differing in ONE config field (drop_prob),
    reports saved as linked artifacts — the regression-forensics e2e
    fixture."""
    from gossipy_tpu.config import ExperimentConfig, run_experiment
    out = tmp_path_factory.mktemp("forensic")
    data = tenant_data(0, n=240, d=6)
    cfg_a = ExperimentConfig(n_nodes=8, topology="ring",
                             topology_params={"k": 2}, delta=10,
                             batch_size=8, learning_rate=0.5, n_rounds=8)
    cfg_b = dataclasses.replace(cfg_a, drop_prob=0.5)
    led = RunLedger(str(out / "ledger.jsonl"))
    accs = {}
    for name, cfg in (("a", cfg_a), ("b", cfg_b)):
        _, report = run_experiment(cfg, data=data)
        rpath = str(out / f"report_{name}.json")
        report.save(rpath)
        accs[name] = float(report.final("accuracy"))
        ingest_manifest(
            led, {"config": dataclasses.asdict(cfg),
                  "backend": {"backend": "cpu", "device_kind": "cpu"}},
            run_id=f"run{name * 3}000",
            metrics={"final_accuracy": accs[name]},
            artifacts={"report": rpath},
            experiment=dataclasses.asdict(cfg))
    return {"path": led.path, "out": str(out), "accs": accs}


class TestForensicsCLI:
    def test_list_renders_and_filters(self, forensic, tmp_path):
        out = str(tmp_path / "list.md")
        assert ledger_cli.main(["list", forensic["path"],
                                "--out", out]) == 0
        text = open(out).read()
        assert "| run id |" in text and "runaaa000" in text
        assert "2 row(s)" in text
        assert ledger_cli.main(["list", forensic["path"], "--json",
                                "--kind", "engine", "--out", out]) == 0
        assert len(json.load(open(out))) == 2
        assert ledger_cli.main(["list", forensic["path"], "--json",
                                "--kind", "loadgen", "--out", out]) == 0
        assert json.load(open(out)) == []

    def test_show_resolves_prefix_and_index(self, forensic, capsys):
        assert ledger_cli.main(["show", forensic["path"], "runaaa"]) == 0
        assert json.loads(capsys.readouterr().out)["run_id"] == \
            "runaaa000"
        assert ledger_cli.main(["show", forensic["path"], "@-1"]) == 0
        assert json.loads(capsys.readouterr().out)["run_id"] == \
            "runbbb000"
        with pytest.raises(SystemExit, match="no row"):
            ledger_cli.main(["show", forensic["path"], "nope"])

    def test_diff_names_config_field_and_metric_delta(self, forensic):
        """THE acceptance check: the diff names the changed config field
        (drop_prob 0.0 -> 0.5), the final_accuracy delta, and — from the
        linked reports — the first divergent round."""
        rows = RunLedger(forensic["path"]).rows()
        d = ledger_cli.diff_rows(rows[0], rows[1])
        assert d["config_diff"] == {
            "drop_prob": {"a": 0.0, "b": 0.5}}
        assert d["fingerprint_changed"] is True
        acc = d["metric_deltas"]["final_accuracy"]
        assert acc["a"] == forensic["accs"]["a"]
        assert acc["b"] == forensic["accs"]["b"]
        assert acc["delta"] == pytest.approx(acc["b"] - acc["a"])
        # Half the messages dropped: the runs' per-round accounting
        # diverges, and the diff says where.
        fdr = d["first_divergent_round"]
        assert isinstance(fdr, int) and 1 <= fdr <= 8

    def test_diff_cli_expect_config_diff(self, forensic, tmp_path,
                                         capsys):
        assert ledger_cli.main(
            ["diff", forensic["path"], "@0", "@1",
             "--expect-config-diff"]) == 0
        out = capsys.readouterr().out
        assert "drop_prob: 0.0 -> 0.5" in out
        assert "fingerprint CHANGED" in out
        # Two rows with IDENTICAL config: the CI assertion trips.
        led = make_ledger(tmp_path, "same.jsonl")
        for _ in range(2):
            led.append({"kind": "engine", "config": {"n_nodes": 8}})
        assert ledger_cli.main(["diff", led.path, "@0", "@1",
                                "--expect-config-diff"]) == 1

    def test_diff_json_round_trips(self, forensic, capsys):
        assert ledger_cli.main(["diff", forensic["path"], "@0", "@1",
                                "--json"]) == 0
        d = json.loads(capsys.readouterr().out)
        assert "drop_prob" in d["config_diff"]

    def test_trend_gates_on_regression(self, tmp_path, capsys):
        led = make_ledger(tmp_path)
        for v, ts in ((100.0, 1.0), (45.0, 2.0)):   # 55% drop
            led.append({"kind": "bench", "ts": ts, "backend": "cpu",
                        "metrics": {"rounds_per_sec": v}})
        assert ledger_cli.main(["trend", led.path, "--metric",
                                "rounds_per_sec"]) == 1
        capsys.readouterr()
        # Within budget: 10% drop passes the default 15% gate.
        led2 = make_ledger(tmp_path, "ok.jsonl")
        for v, ts in ((100.0, 1.0), (90.0, 2.0)):
            led2.append({"kind": "bench", "ts": ts, "backend": "cpu",
                         "metrics": {"rounds_per_sec": v}})
        assert ledger_cli.main(["trend", led2.path, "--metric",
                                "rounds_per_sec"]) == 0

    def test_merge_cli(self, forensic, tmp_path):
        led2 = make_ledger(tmp_path, "other.jsonl")
        led2.append({"kind": "bench"})
        out = str(tmp_path / "merged.jsonl")
        assert ledger_cli.main(["merge", out, forensic["path"],
                                led2.path]) == 0
        assert len(RunLedger(out).rows()) == 3


# ---------------------------------------------------------------------------
# Bisect: git-bisect-correct exit codes over real replays


@pytest.fixture(scope="module")
def bisect_ledger(tmp_path_factory):
    """A baseline row with a RECORDED final_accuracy from a real run,
    plus replayable rows: one pinning the same (good) config, one
    pinning a config with learning disabled (the seeded regression),
    one with no experiment at all."""
    from gossipy_tpu.config import ExperimentConfig, run_experiment
    out = tmp_path_factory.mktemp("bisect")
    cfg_good = ExperimentConfig(dataset="breast", n_nodes=8,
                                topology="ring", topology_params={"k": 2},
                                delta=10, batch_size=8,
                                learning_rate=0.5, n_rounds=8)
    _, report = run_experiment(cfg_good)
    acc = float(report.final("accuracy"))
    assert acc > 0.8, f"good config failed to learn (acc={acc})"
    led = RunLedger(str(out / "ledger.jsonl"))
    led.append({"kind": "engine", "run_id": "base00000000",
                "metrics": {"final_accuracy": acc},
                "experiment": dataclasses.asdict(cfg_good)})
    led.append({"kind": "engine", "run_id": "good00000000",
                "experiment": dataclasses.asdict(cfg_good)})
    cfg_bad = dataclasses.replace(cfg_good, learning_rate=0.0)
    led.append({"kind": "engine", "run_id": "bad000000000",
                "experiment": dataclasses.asdict(cfg_bad)})
    led.append({"kind": "engine", "run_id": "noexp0000000",
                "metrics": {"final_accuracy": 0.9}})
    return led.path


class TestBisect:
    def test_good_replay_exits_zero(self, bisect_ledger, capsys):
        rc = ledger_cli.main(["bisect", bisect_ledger, "good",
                              "--baseline", "base",
                              "--metric", "final_accuracy"])
        assert rc == 0
        assert json.loads(capsys.readouterr().out)["verdict"] == "good"

    def test_regression_replay_exits_one(self, bisect_ledger, capsys):
        rc = ledger_cli.main(["bisect", bisect_ledger, "bad",
                              "--baseline", "base",
                              "--metric", "final_accuracy"])
        assert rc == 1
        assert json.loads(capsys.readouterr().out)["verdict"] == "BAD"

    def test_unreplayable_rows_exit_skip(self, bisect_ledger):
        # git bisect's skip code (125) — never a false good/bad verdict.
        assert ledger_cli.main(
            ["bisect", bisect_ledger, "noexp", "--baseline", "base",
             "--metric", "final_accuracy"]) == 125
        assert ledger_cli.main(
            ["bisect", bisect_ledger, "good", "--baseline", "noexp",
             "--metric", "rounds_per_sec"]) == 125
        assert ledger_cli.main(
            ["bisect", bisect_ledger, "missing", "--baseline", "base",
             "--metric", "final_accuracy"]) == 125


# ---------------------------------------------------------------------------
# Satellite 3: bench_trend --ledger folding


class TestBenchTrendLedger:
    def test_folds_dedupes_and_orders(self, tmp_path):
        bt = load_script("bench_trend")
        led = make_ledger(tmp_path)
        row1 = {"metric": "rounds_per_sec", "value": 100.0,
                "unit": "rounds/s", "raw": {"backend": "cpu"}}
        row2 = {"metric": "rounds_per_sec", "value": 90.0,
                "unit": "rounds/s", "raw": {"backend": "cpu"}}
        ingest_bench_capsule(led, {"n": 1, "parsed": row1})
        ingest_bench_capsule(led, row2)
        # row1 also reached a BENCH_r capsule: it must NOT fold twice
        # (a row gating against itself would always "regress" 0%).
        entries = [{"source": "BENCH_r1.json", "order": 1, "row": row1}]
        out = bt.load_ledger_rows(led.path, entries)
        assert len(out) == 2
        assert out[0]["source"] == "BENCH_r1.json"
        assert out[1]["source"].startswith("ledger:")
        assert out[1]["row"] == row2
        assert out[1]["order"] > out[0]["order"]
        # Folding again is a no-op (run-id + identity dedup).
        assert len(bt.load_ledger_rows(led.path, out)) == 2

    def test_torn_ledger_never_breaks_trend(self, tmp_path):
        bt = load_script("bench_trend")
        led = make_ledger(tmp_path)
        ingest_bench_capsule(
            led, {"metric": "rounds_per_sec", "value": 50.0,
                  "unit": "rounds/s", "raw": {}})
        with open(led.path, "ab") as fh:
            fh.write(b"deadbeef {torn")
        out = bt.load_ledger_rows(led.path, [])
        assert len(out) == 1 and out[0]["row"]["value"] == 50.0
