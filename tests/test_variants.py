"""Tests for simulator variants: tokenized, all2all, and node behaviors."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.compression import ModelPartition
from gossipy_tpu.core import (
    CreateModelMode,
    Topology,
    UniformDelay,
    uniform_mixing,
)
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.flow_control import (
    PurelyProactiveTokenAccount,
    RandomizedTokenAccount,
    SimpleTokenAccount,
)
from gossipy_tpu.handlers import (
    PartitionedSGDHandler,
    SamplingSGDHandler,
    SGDHandler,
    WeightedSGDHandler,
    losses,
)
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import (
    All2AllGossipSimulator,
    CacheNeighGossipSimulator,
    PartitioningGossipSimulator,
    PassThroughGossipSimulator,
    PENSGossipSimulator,
    SamplingGossipSimulator,
    TokenizedGossipSimulator,
)


def make_dataset(n=320, d=8, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    return X, y


def make_parts(n_nodes=16, d=8, seed=0):
    X, y = make_dataset(d=d, seed=seed)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes)
    return disp.stacked(), d


def sgd_handler(d, mode=CreateModelMode.MERGE_UPDATE, cls=SGDHandler, **kw):
    kw.setdefault("optimizer", optax.sgd(0.5))
    return cls(model=LogisticRegression(d, 2), loss=losses.cross_entropy,
               local_epochs=1, batch_size=8,
               n_classes=2, input_shape=(d,), create_model_mode=mode, **kw)


class TestTokenized:
    def test_purely_proactive_equals_plain_gossip_traffic(self, key):
        data, d = make_parts()
        sim = TokenizedGossipSimulator(
            sgd_handler(d), Topology.clique(16), data, delta=10,
            token_account=PurelyProactiveTokenAccount())
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=6)
        # proactive == 1 => every node sends every round, no reactions.
        assert rep.sent_messages == 6 * 16
        assert rep.curves(local=False)["accuracy"][-1] > 0.8

    def test_simple_account_banks_then_bursts(self, key):
        data, d = make_parts()
        sim = TokenizedGossipSimulator(
            sgd_handler(d), Topology.clique(16), data, delta=10,
            token_account=SimpleTokenAccount(C=3))
        st = sim.init_nodes(key)
        assert "balance" in st.aux
        st, rep = sim.start(st, n_rounds=8)
        # Nodes start at balance 0 < C: first rounds bank tokens, later
        # reactions fire; total traffic is below always-send gossip.
        assert 0 < rep.sent_messages < 8 * 16
        balances = np.asarray(st.aux["balance"])
        assert (balances >= 0).all()

    def test_reaction_utility_uses_sent_time_snapshot(self, key):
        """The reaction utility must see the SENT-time sender snapshot (the
        message payload), not the sender's current-round model — the
        reference computes utility on the received handler
        (simul.py:631-648). Distinguishable only with a snapshot-sensitive
        utility under delay: the sent-round history cell carries age-5
        models, the current round's cell age-0."""
        from gossipy_tpu.core import MessageType
        from gossipy_tpu.flow_control import PurelyReactiveTokenAccount
        data, d = make_parts()
        n = 16
        sim = TokenizedGossipSimulator(
            sgd_handler(d), Topology.clique(n), data, delta=10,
            delay=UniformDelay(0, 30),
            token_account=PurelyReactiveTokenAccount(k=1),
            utility_fun=lambda m, peer: peer.n_updates.astype(jnp.float32))
        st = sim.init_nodes(key)
        D = st.history_ages.shape[0]
        assert D > 3, "delay model must give distinct cells for rounds 0, 2"
        ages = st.history_ages.at[0].set(5).at[2].set(0)
        aux = dict(st.aux)
        aux["balance"] = jnp.full((n,), 10, jnp.int32)
        st = st._replace(history_ages=ages, aux=aux)
        zeros = jnp.zeros((n,), jnp.int32)
        out = sim._post_receive_slot(
            st, jnp.ones((n,), bool),
            jnp.full((n,), int(MessageType.PUSH), jnp.int32),
            zeros,          # sender = node 0
            zeros,          # send_round = 0 (delayed delivery at r=2)
            zeros, key, jnp.int32(2), jnp.int32(0))
        # Sent-time age 5 -> utility 5 -> reactions fire (capped); reading
        # the current cell (age 0) would yield zero reactions.
        per_node = np.asarray(out.aux["pending_reactions"])
        assert (per_node == sim.max_reactions).all()

    def test_randomized_account_runs(self, key):
        data, d = make_parts()
        sim = TokenizedGossipSimulator(
            sgd_handler(d), Topology.random_regular(16, 4), data, delta=10,
            delay=UniformDelay(0, 10),
            token_account=RandomizedTokenAccount(C=20, A=10))
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=10)
        assert np.isfinite(rep.curves(local=False)["accuracy"][-1])


class TestAll2All:
    def test_mixing_converges_and_learns(self, key):
        data, d = make_parts()
        topo = Topology.ring(16, k=2)
        handler = sgd_handler(d, cls=WeightedSGDHandler)
        sim = All2AllGossipSimulator(handler, topo, data, delta=10,
                                     mixing=uniform_mixing(topo))
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=10)
        acc = rep.curves(local=False)["accuracy"]
        assert acc[-1] > 0.85
        # Broadcast traffic: every node pushes to all its peers each round.
        assert rep.sent_messages == 10 * int(topo.degrees.sum())

    def test_update_merge_only_fired_nodes_train(self, key):
        """UPDATE_MERGE: a node that does not time out in a round must be
        untouched that round (node.py:833-843). Async timing makes some
        nodes skip rounds; identity mixing zeroes all peer weights, so
        local training is the only channel that can change params."""
        data, d = make_parts()
        topo = Topology.clique(16)
        handler = sgd_handler(d, mode=CreateModelMode.UPDATE_MERGE,
                              cls=WeightedSGDHandler)
        sim = All2AllGossipSimulator(handler, topo, data, delta=8,
                                     sync=False, mixing=jnp.eye(16))
        st = sim.init_nodes(key)
        # Pin periods 6..13 so nodes with period > delta provably skip
        # rounds (e.g. period 13 has no multiple in [16, 24)).
        periods = 6 + np.arange(16) % 8
        st = st._replace(phase=jnp.asarray(periods, dtype=st.phase.dtype))
        n_nonfired_checked = 0
        for _ in range(8):
            r = int(st.round)
            lo, hi = r * sim.delta, (r + 1) * sim.delta
            first = -(-lo // periods) * periods  # first multiple >= lo
            fires = first < hi
            before = [np.asarray(l) for l in jax.tree.leaves(st.model.params)]
            ages_before = np.asarray(st.model.n_updates)
            st, _ = sim.start(st, n_rounds=1, key=jax.random.fold_in(key, r))
            after = [np.asarray(l) for l in jax.tree.leaves(st.model.params)]
            ages_after = np.asarray(st.model.n_updates)
            changed = np.zeros(16, dtype=bool)
            for b, a in zip(before, after):
                changed |= (b != a).reshape(16, -1).any(axis=1)
            assert not changed[~fires].any(), f"non-fired node trained at r={r}"
            assert (ages_after[~fires] == ages_before[~fires]).all()
            assert changed[fires].all(), f"fired node did not train at r={r}"
            n_nonfired_checked += int((~fires).sum())
        # The config must actually exercise the gate: some node must have
        # skipped some round, or the assertions above were vacuous.
        assert n_nonfired_checked > 0

    def test_mixing_shrinks_consensus_distance(self, key):
        """After mixing rounds, node models must be closer together than
        isolated training (the Koloskova consensus property)."""
        data, d = make_parts()
        topo = Topology.clique(16)
        handler = sgd_handler(d, cls=WeightedSGDHandler)
        sim = All2AllGossipSimulator(handler, topo, data, delta=10,
                                     mixing=uniform_mixing(topo))
        st0 = sim.init_nodes(key)
        st, _ = sim.start(st0, n_rounds=6, donate_state=False)

        def spread(model):
            k = model.params["Dense_0"]["kernel"]
            return float(jnp.linalg.norm(k - k.mean(0, keepdims=True)))

        sim_iso = All2AllGossipSimulator(handler, topo, data, delta=10,
                                         mixing=uniform_mixing(topo),
                                         drop_prob=0.999)
        st_iso, _ = sim_iso.start(st0, n_rounds=6)
        assert spread(st.model) < spread(st_iso.model)


class TestPassThrough:
    def test_runs_and_learns_on_ba_graph(self, key):
        data, d = make_parts()
        sim = PassThroughGossipSimulator(
            sgd_handler(d), Topology.barabasi_albert(16, 2, seed=1), data,
            delta=10)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=14)
        # Pass-through adoption slows individual convergence; the bar is
        # "clearly learning", not vanilla-gossip speed.
        assert rep.curves(local=False)["accuracy"][-1] > 0.75


class TestCacheNeigh:
    def test_models_are_parked_then_consumed(self, key):
        data, d = make_parts()
        sim = CacheNeighGossipSimulator(
            sgd_handler(d), Topology.ring(16, k=1), data, delta=10)
        st = sim.init_nodes(key)
        assert st.aux["cache_valid"].shape == (16, 2)  # ring degree 2
        st, rep = sim.start(st, n_rounds=10)
        assert rep.curves(local=False)["accuracy"][-1] > 0.75
        # Caches are used: some slots occupied at the end (steady flow).
        assert np.asarray(st.aux["cache_valid"]).sum() >= 0


class TestSamplingPartitioning:
    def test_sampling_gossip(self, key):
        data, d = make_parts()
        handler = sgd_handler(d, cls=SamplingSGDHandler, sample_size=0.5)
        sim = SamplingGossipSimulator(handler, Topology.clique(16), data, delta=10)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=10)
        assert rep.curves(local=False)["accuracy"][-1] > 0.8

    def test_partitioning_gossip(self, key):
        data, d = make_parts()
        base = sgd_handler(d)
        template = base.init(key).params
        # Age-divided gradients decay the effective lr ~1/t; the reference
        # config compensates with lr=1 (main_hegedus_2021.py:44).
        handler = sgd_handler(d, cls=PartitionedSGDHandler,
                              partition=ModelPartition(template, 4),
                              optimizer=optax.sgd(1.0))
        sim = PartitioningGossipSimulator(handler, Topology.clique(16), data,
                                          delta=10)
        st = sim.init_nodes(key)
        assert st.model.n_updates.shape == (16, 4)
        st, rep = sim.start(st, n_rounds=20)
        assert rep.curves(local=False)["accuracy"][-1] > 0.8

    def test_partitioning_requires_partitioned_handler(self):
        data, d = make_parts()
        with pytest.raises(AssertionError):
            PartitioningGossipSimulator(sgd_handler(d), Topology.clique(16),
                                        data, delta=10)


class TestPENS:
    def test_two_phase_run(self, key):
        data, d = make_parts(n_nodes=8)
        sim = PENSGossipSimulator(
            sgd_handler(d, mode=CreateModelMode.MERGE_UPDATE),
            Topology.clique(8), data, delta=10,
            n_sampled=4, m_top=2, step1_rounds=5)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=12)
        acc = rep.curves(local=False)["accuracy"]
        assert len(acc) == 12
        assert acc[-1] > 0.75
        # Phase bookkeeping happened.
        assert np.asarray(st.aux["selected"]).sum() > 0
        assert np.asarray(st.aux["neigh_counter"]).sum() > 0

    def test_aux_state_is_degree_bounded(self, key):
        """PENS selection state is [N, max_deg], not [N, N] (the last dense
        N^2 object in the codebase — VERDICT r3 #6)."""
        data, d = make_parts(n_nodes=16)
        sim = PENSGossipSimulator(
            sgd_handler(d, mode=CreateModelMode.MERGE_UPDATE),
            Topology.random_regular(16, 4), data, delta=10,
            n_sampled=3, m_top=1, step1_rounds=4)
        st = sim.init_nodes(key)
        for k in ("selected", "neigh_counter", "best"):
            assert st.aux[k].shape == (16, sim.max_deg)
        assert sim.max_deg == 4

    @pytest.mark.slow
    def test_pens_runs_at_10k_nodes(self, key):
        """The VERDICT r3 #6 'done' bar: PENS at 10k nodes on one device.
        Degree-bounded aux makes the footprint O(N * max_deg); two phase-1
        rounds + the phase switch + one phase-2 round must execute."""
        from gossipy_tpu.core import SparseTopology
        n = 10_000
        rng = np.random.default_rng(0)
        X, y = make_dataset(n=4 * n, d=8, seed=0)
        dh = ClassificationDataHandler(X, y, test_size=0.1, seed=1)
        disp = DataDispatcher(dh, n=n, eval_on_user=False)
        topo = SparseTopology.random_regular(n, 8, seed=3)
        sim = PENSGossipSimulator(
            sgd_handler(8, mode=CreateModelMode.MERGE_UPDATE),
            topo, disp.stacked(), delta=10, sampling_eval=0.01,
            n_sampled=3, m_top=1, step1_rounds=2)
        st = sim.init_nodes(key)
        assert st.aux["selected"].shape == (n, sim.max_deg)
        st, rep = sim.start(st, n_rounds=3)
        assert np.isfinite(rep.curves(local=False)["accuracy"][-1])
        assert np.asarray(st.aux["selected"]).sum() > 0

    def test_run_repetitions_crosses_the_phase_switch(self, key):
        """PENS's multi-seed path must run BOTH phases (the base
        run_repetitions would scan every round under phase 1): full-length
        curves per seed, phase-2 'best' selections populated, and the
        network learns in every repetition."""
        data, d = make_parts()
        sim = PENSGossipSimulator(
            sgd_handler(d, mode=CreateModelMode.MERGE_UPDATE),
            Topology.clique(16), data, delta=10,
            n_sampled=4, m_top=2, step1_rounds=3)
        states, reports = sim.run_repetitions(8, jax.random.split(key, 3))
        assert len(reports) == 3
        for rep in reports:
            acc = rep.curves(local=False)["accuracy"]
            assert len(acc) == 8 and acc[-1] > 0.7
        # The stacked final states carry phase-2 selections per seed.
        assert np.asarray(states.aux["best"]).reshape(3, -1).any(axis=1).all()

    def test_continuation_resumes_phase(self, key):
        # Regression: a second start() must not re-enter phase 1.
        data, d = make_parts(n_nodes=8)
        sim = PENSGossipSimulator(
            sgd_handler(d, mode=CreateModelMode.MERGE_UPDATE),
            Topology.clique(8), data, delta=10,
            n_sampled=4, m_top=2, step1_rounds=5)
        st = sim.init_nodes(key)
        st, _ = sim.start(st, n_rounds=7)  # crosses into phase 2
        counters = np.asarray(st.aux["selected"]).copy()
        st, _ = sim.start(st, n_rounds=4)  # all phase 2
        # Phase-1 bookkeeping must be frozen in phase 2.
        np.testing.assert_array_equal(np.asarray(st.aux["selected"]), counters)

    def test_duplicate_sender_overwrites_cache_slot(self, key):
        # Regression: repeat senders must not occupy multiple buffer slots
        # (reference node.py:777 keys the cache by sender).
        data, d = make_parts(n_nodes=4)
        sim = PENSGossipSimulator(
            sgd_handler(d, mode=CreateModelMode.MERGE_UPDATE),
            Topology.clique(4), data, delta=10,
            n_sampled=3, m_top=1, step1_rounds=50)
        st = sim.init_nodes(key)
        st, _ = sim.start(st, n_rounds=6)
        senders = np.asarray(st.aux["cache_sender"])
        count = np.asarray(st.aux["cache_count"])
        for i in range(4):
            filled = senders[i][senders[i] >= 0]
            assert len(filled) == len(set(filled.tolist()))
            assert count[i] == len(filled)

    def test_requires_merge_update(self, key):
        data, d = make_parts(n_nodes=8)
        with pytest.raises(AssertionError):
            PENSGossipSimulator(sgd_handler(d, mode=CreateModelMode.UPDATE),
                                Topology.clique(8), data, delta=10)


class TestReactiveTokenConservation:
    def test_capped_reactions_do_not_destroy_tokens(self, key):
        """Tokens beyond the per-round reaction cap stay banked: debits must
        equal performed reaction sends (regression for the clip-after-debit
        bug)."""
        from gossipy_tpu.flow_control import GeneralizedTokenAccount
        data, d = make_parts()
        sim = TokenizedGossipSimulator(
            sgd_handler(d), Topology.clique(16), data, delta=10,
            token_account=GeneralizedTokenAccount(C=30, A=1),
            max_reactions=2)
        st = sim.init_nodes(key)
        # Seed large balances so reactive() wants >> max_reactions sends.
        aux = dict(st.aux)
        aux["balance"] = jnp.full((16,), 30, dtype=jnp.int32)
        st = st._replace(aux=aux)
        st2, rep = sim.start(st, n_rounds=1, key=key, donate_state=False)
        spent = np.asarray(st.aux["balance"]) - np.asarray(st2.aux["balance"])
        # Balance may also GROW by 1 for gated proactive sends; reactions can
        # never debit more than the cap.
        assert (spent <= sim.max_reactions).all()
        assert (np.asarray(st2.aux["balance"]) >= 0).all()
