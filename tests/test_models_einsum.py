"""Parity between CIFAR10Net's two conv implementations.

``conv_impl="einsum"`` exists because the engine vmaps the model over the
node axis with per-node weights, where ``nn.Conv`` lowers to tiny-group
grouped convolutions (MXU-hostile on TPU). The einsum form must be a drop-in:
identical parameter tree, equal outputs and gradients up to fp reduction
order, under both the plain and the vmapped (engine-shaped) call.
"""

import jax
import jax.numpy as jnp
import pytest

from gossipy_tpu.models import CIFAR10Net


@pytest.fixture(scope="module")
def setup():
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.fold_in(key, 1), (4, 32, 32, 3))
    params = CIFAR10Net(conv_impl="conv").init(key, x)["params"]
    return key, x, params


def test_param_trees_identical(setup):
    key, x, _ = setup
    t_conv = jax.eval_shape(CIFAR10Net(conv_impl="conv").init, key, x)
    t_ein = jax.eval_shape(CIFAR10Net(conv_impl="einsum").init, key, x)
    assert jax.tree_util.tree_structure(t_conv) == \
        jax.tree_util.tree_structure(t_ein)
    assert [l.shape for l in jax.tree_util.tree_leaves(t_conv)] == \
        [l.shape for l in jax.tree_util.tree_leaves(t_ein)]


def test_forward_parity(setup):
    _, x, params = setup
    y_conv = CIFAR10Net(conv_impl="conv").apply({"params": params}, x)
    y_ein = CIFAR10Net(conv_impl="einsum").apply({"params": params}, x)
    assert jnp.allclose(y_conv, y_ein, atol=1e-4, rtol=1e-4)


def test_grad_parity(setup):
    _, x, params = setup

    def loss(p, impl):
        y = CIFAR10Net(conv_impl=impl).apply({"params": p}, x)
        return (y ** 2).mean()

    g_conv = jax.grad(loss)(params, "conv")
    g_ein = jax.grad(loss)(params, "einsum")
    for a, b in zip(jax.tree_util.tree_leaves(g_conv),
                    jax.tree_util.tree_leaves(g_ein)):
        assert jnp.allclose(a, b, atol=1e-4, rtol=1e-3)


def test_vmapped_per_node_parity(setup):
    """The engine's shape: vmap over a node axis of stacked params."""
    key, x, _ = setup
    n = 3
    stacked = jax.vmap(
        lambda k: CIFAR10Net(conv_impl="conv").init(k, x)["params"]
    )(jax.random.split(key, n))

    def fwd(impl):
        return jax.vmap(
            lambda p: CIFAR10Net(conv_impl=impl).apply({"params": p}, x)
        )(stacked)

    assert jnp.allclose(fwd("conv"), fwd("einsum"), atol=1e-4, rtol=1e-4)


def test_nchw_input_accepted(setup):
    _, x, params = setup
    x_nchw = jnp.transpose(x, (0, 3, 1, 2))
    y = CIFAR10Net(conv_impl="einsum").apply({"params": params}, x_nchw)
    y_ref = CIFAR10Net(conv_impl="einsum").apply({"params": params}, x)
    assert jnp.allclose(y, y_ref)


def test_auto_resolves_to_einsum(setup):
    """auto picks the einsum path on every backend (the vmapped grouped-conv
    pathology is not TPU-specific — 17x slower train slot on CPU too); a
    bogus impl must fail loudly."""
    _, x, params = setup
    y_auto = CIFAR10Net(conv_impl="auto").apply({"params": params}, x)
    y_ein = CIFAR10Net(conv_impl="einsum").apply({"params": params}, x)
    assert jnp.array_equal(y_auto, y_ein)
    with pytest.raises(ValueError, match="conv_impl"):
        CIFAR10Net(conv_impl="wat").apply({"params": params}, x)
