"""Data layer tests: handlers, non-IID assigners, dispatcher stacking."""

import numpy as np
import pytest

from gossipy_tpu.data import (
    AssignmentHandler,
    ClassificationDataHandler,
    ClusteringDataHandler,
    DataDispatcher,
    RecSysDataDispatcher,
    RecSysDataHandler,
    load_classification_dataset,
    load_recsys_dataset,
)


def make_labels(n=1000, c=4, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, c, size=n)


class TestAssignments:
    def _check_partition(self, parts, n_total, disjoint=True):
        all_ids = np.concatenate([p for p in parts if len(p)])
        if disjoint:
            assert len(np.unique(all_ids)) == len(all_ids)
        assert all_ids.max() < n_total

    def test_uniform(self):
        y = make_labels()
        parts = AssignmentHandler(0).uniform(y, 10)
        assert len(parts) == 10
        assert all(len(p) == 100 for p in parts)
        self._check_partition(parts, 1000)

    def test_quantity_skew(self):
        y = make_labels()
        parts = AssignmentHandler(0).quantity_skew(y, 10, min_quantity=5, alpha=4.0)
        sizes = np.array([len(p) for p in parts])
        assert sizes.min() >= 5
        assert sizes.sum() == 1000
        # Power law: strong imbalance expected.
        assert sizes.max() > 3 * sizes.min()
        self._check_partition(parts, 1000)

    def test_classwise_quantity_skew(self):
        y = make_labels()
        parts = AssignmentHandler(0).classwise_quantity_skew(y, 5, alpha=3.0)
        assert sum(len(p) for p in parts) == 1000
        self._check_partition(parts, 1000)

    def test_label_quantity_skew(self):
        y = make_labels(c=6)
        parts = AssignmentHandler(0).label_quantity_skew(y, 8, class_per_client=2)
        self._check_partition(parts, 1000)
        for p in parts:
            if len(p):
                assert len(np.unique(y[p])) <= 2

    def test_label_dirichlet_skew(self):
        y = make_labels(c=4)
        parts = AssignmentHandler(0).label_dirichlet_skew(y, 6, beta=0.1)
        self._check_partition(parts, 1000)
        # Every client holds >= 1 example of each class (the ids[:n] seeding).
        for p in parts:
            assert len(np.unique(y[p])) == 4

    def test_label_pathological_skew(self):
        y = make_labels(c=10)
        parts = AssignmentHandler(0).label_pathological_skew(y, 10, shards_per_client=2)
        assert sum(len(p) for p in parts) == 1000
        self._check_partition(parts, 1000)
        # Most clients see few classes.
        n_classes = [len(np.unique(y[p])) for p in parts]
        assert np.median(n_classes) <= 4


class TestDispatcher:
    def make_handler(self, n=200, d=5, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = rng.integers(0, 3, size=n)
        return ClassificationDataHandler(X, y, test_size=0.2, seed=seed)

    def test_handler_split(self):
        h = self.make_handler()
        assert h.size() == 160
        assert h.eval_size() == 40
        assert h.n_classes == 3
        X, y = h.at([0, 1, 2])
        assert X.shape == (3, 5)
        assert h.at([], eval_set=True) is None

    def test_getitem_api(self):
        d = DataDispatcher(self.make_handler(), n=8)
        train, test = d[0]
        assert train[0].shape[0] == 160 // 8
        assert test[0].shape[0] == 40 // 8
        with pytest.raises(AssertionError):
            d[8]

    def test_stacked_shapes_and_masks(self):
        d = DataDispatcher(self.make_handler(), n=8)
        s = d.stacked()
        assert s["xtr"].shape == (8, 20, 5)
        assert s["mtr"].sum() == 160
        assert s["xte"].shape[0] == 8
        assert s["x_eval"].shape == (40, 5)

    def test_stacked_uneven_shards_padded(self):
        h = self.make_handler()
        d = DataDispatcher(h, n=6, auto_assign=False,
                           assignment=AssignmentHandler.quantity_skew,
                           min_quantity=2, alpha=4.0)
        d.assign(seed=1)
        s = d.stacked()
        sizes = np.array([len(a) for a in d.tr_assignments])
        assert s["xtr"].shape[1] == sizes.max()
        np.testing.assert_array_equal(s["mtr"].sum(axis=1), sizes)
        # Padding rows are zero.
        i = int(sizes.argmin())
        assert (s["xtr"][i, sizes[i]:] == 0).all()

    def test_stacked_pad_to_aligns_labels(self):
        # Regression: ytr/mtr must share xtr's padded length under pad_to.
        d = DataDispatcher(self.make_handler(), n=4)
        s = d.stacked(pad_to=64)
        assert s["xtr"].shape[:2] == s["ytr"].shape == s["mtr"].shape == (4, 64)

    def test_eval_on_user_false(self):
        d = DataDispatcher(self.make_handler(), n=4, eval_on_user=False)
        s = d.stacked()
        assert "xte" not in s
        assert "x_eval" in s


class TestLoaders:
    def test_sklearn_datasets(self):
        for name, c in [("iris", 3), ("breast", 2), ("wine", 3)]:
            X, y = load_classification_dataset(name)
            assert X.dtype == np.float32
            assert len(np.unique(y)) == c
            # normalized
            assert abs(X.mean()) < 0.1

    def test_uci_fallback_deterministic(self, monkeypatch):
        # Force the no-download path so the test is environment-independent.
        import urllib.request

        def no_net(*a, **k):
            raise OSError("no egress")

        monkeypatch.setattr(urllib.request, "urlopen", no_net)
        with pytest.warns(UserWarning):
            X1, y1 = load_classification_dataset("spambase")
        with pytest.warns(UserWarning):
            X2, y2 = load_classification_dataset("spambase")
        assert X1.shape == (4601, 57)
        assert set(np.unique(y1)) == {0, 1}
        np.testing.assert_array_equal(y1, y2)
        np.testing.assert_allclose(X1, X2)

    def test_recsys_loader_and_dispatcher(self):
        with pytest.warns(UserWarning):
            ratings, n_users, n_items = load_recsys_dataset("ml-100k")
        assert n_users == 943
        h = RecSysDataHandler(ratings, n_users, n_items, test_size=0.2, seed=1)
        d = RecSysDataDispatcher(h)
        s = d.stacked()
        assert s["xtr"].shape[0] == 943
        assert s["xtr"].dtype == np.int32
        assert (s["ytr"][s["mtr"] > 0] >= 1).all()
        train, test = d[0]
        assert isinstance(train, list) and isinstance(test, list)

    def test_clustering_handler(self):
        X = np.random.default_rng(0).normal(size=(50, 3)).astype(np.float32)
        y = np.zeros(50, dtype=int)
        h = ClusteringDataHandler(X, y)
        assert h.eval_size() == 50
        Xe, ye = h.get_eval_set()
        np.testing.assert_array_equal(Xe, h.Xtr)  # eval set IS the train set
        assert Xe.shape == (50, 3)


class TestFEMNIST:
    def test_per_writer_assignments_are_disjoint_and_advance(self):
        import warnings
        from gossipy_tpu.data import get_FEMNIST
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            (Xtr, ytr, tr_a), (Xte, yte, te_a) = get_FEMNIST(n_writers=10)
        assert len(tr_a) == len(te_a) == 10
        # The reference's sum_tr/sum_te bug assigned every writer the same
        # rows; here shards must tile the dataset disjointly.
        all_tr = np.concatenate(tr_a)
        assert len(np.unique(all_tr)) == len(all_tr) == len(Xtr)
        assert Xtr.shape[1:] == (28, 28, 1)
        assert ytr.max() < 62

    def test_dispatch_through_set_assignments(self):
        import warnings
        from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher, \
            get_FEMNIST
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            (Xtr, ytr, tr_a), (Xte, yte, te_a) = get_FEMNIST(n_writers=6)
        dh = ClassificationDataHandler(Xtr, ytr, Xte, yte)
        disp = DataDispatcher(dh, n=6, eval_on_user=True, auto_assign=False)
        disp.set_assignments(tr_a, te_a)
        stacked = disp.stacked()
        assert stacked["xtr"].shape[0] == 6
        assert stacked["mtr"].sum() == len(Xtr)
