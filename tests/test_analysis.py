"""Static-analysis layer: tracelint rules + baseline + the HLO gate.

Covers the ISSUE-9 acceptance criteria:

- ``python -m gossipy_tpu.analysis`` exits 0 on the final tree (zero
  unsuppressed, un-baselined findings) and non-zero on a seeded
  violation fixture;
- every taint rule fires on a minimal traced-region violation and stays
  quiet on the static-by-contract counterexamples;
- the registry-completeness meta-test: an injected unregistered
  ``health_bogus`` per-round field is flagged, and a simulated JSONL
  schema v9 bump without a ``parse_line`` branch trips the tolerance
  rule;
- suppression comments, the file pragma, and the baseline waive exactly
  what they claim;
- HLO fingerprints are deterministic, identity pairs hold, and a
  deliberate one-line engine perturbation produces a named
  first-divergent-instruction report.
"""

import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from gossipy_tpu.analysis import (
    Finding,
    baseline_from_findings,
    filter_baselined,
    run_tracelint,
)

REPO = Path(__file__).resolve().parents[1]


def lint(sources=None):
    return run_tracelint(REPO, sources=sources)


def rules_of(findings):
    return sorted({f.rule for f in findings})


TRACED_VIOLATIONS = '''
import jax
import jax.numpy as jnp
import numpy as np
import math

def body(carry, x):
    if carry > 0:                    # host-branch
        carry = carry + 1
    v = float(carry)                 # host-coerce
    w = np.log(carry)                # np-in-trace
    u = math.floor(carry)            # np-in-trace (math too)
    y = carry[:x]                    # traced-slice
    z = carry.item()                 # host-coerce
    return carry, v

def drive(init):
    final, ys = jax.lax.scan(body, init, None, length=3)
    return final
'''


class TestTaintRules:
    def test_all_taint_rules_fire_on_seeded_module(self):
        fs = lint({"gossipy_tpu/_seeded.py": TRACED_VIOLATIONS})
        assert rules_of(fs) == ["host-branch", "host-coerce",
                                "np-in-trace", "traced-slice"]
        by_rule = {}
        for f in fs:
            by_rule.setdefault(f.rule, []).append(f)
        assert len(by_rule["host-coerce"]) == 2    # float() + .item()
        assert len(by_rule["np-in-trace"]) == 2    # np.log + math.floor
        assert all(f.path == "gossipy_tpu/_seeded.py" for f in fs)

    def test_host_code_is_not_linted(self):
        src = '''
def host_only(x):
    if x > 0:          # never traced: no finding
        return float(x)
    return 0.0
'''
        assert lint({"gossipy_tpu/_host.py": src}) == []

    def test_static_by_contract_is_quiet(self):
        src = '''
import jax
import jax.numpy as jnp

def body(carry, flag: bool, k: int):
    if flag:                       # bool-annotated: static
        carry = carry + k
    n = int(carry.shape[0])        # shape access is static
    if carry is None:              # identity test is static
        return carry
    for leaf in jax.tree.leaves(carry):   # host container of leaves
        carry = carry + leaf.sum()
    return carry

def drive(init):
    return jax.lax.fori_loop(0, 3, lambda i, c: body(c, True, 1), init)
'''
        assert lint({"gossipy_tpu/_static_ok.py": src}) == []

    def test_static_argnames_params_are_static(self):
        src = '''
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, static_argnames=("block",))
def kernel(x, block):
    pad = (-x.shape[0]) % block
    if pad:                        # static: block is static_argnames
        x = jnp.pad(x, (0, pad))
    return x
'''
        assert lint({"gossipy_tpu/_statics.py": src}) == []

    def test_io_callback_body_is_host_side(self):
        src = '''
import jax
import jax.numpy as jnp

def step(carry, _):
    def cb(v):
        print(float(v))            # host callback: no finding
    jax.experimental.io_callback(cb, None, carry, ordered=True)
    return carry, ()

def drive(init):
    return jax.lax.scan(step, init, None, length=2)
'''
        assert lint({"gossipy_tpu/_cb.py": src}) == []

    def test_use_after_donate(self):
        src = '''
def go(sim, state, key):
    out, rep = sim.start(state, n_rounds=2, key=key)
    bad = state.round              # donated buffer read
    return out, bad

def ok_rebind(sim, state, key):
    state, rep = sim.start(state, n_rounds=2, key=key)
    return state.round             # rebound: fine

def ok_optout(sim, state, key):
    out, rep = sim.start(state, n_rounds=2, key=key,
                         donate_state=False)
    return state.round             # donation disabled: fine
'''
        fs = lint({"gossipy_tpu/_donate.py": src})
        assert rules_of(fs) == ["use-after-donate"]
        assert len(fs) == 1 and fs[0].line == 4


METRICS_IN_TRACE = '''
import jax
from .telemetry.metrics import get_registry

def body(carry, x):
    get_registry().counter("engine_rounds_total").inc()   # host sink!
    return carry, x

def drive(init):
    return jax.lax.scan(body, init, None, length=2)
'''

METRICS_HOST_OK = '''
import jax
from .telemetry.metrics import get_registry

def step(carry, _):
    def cb(v):
        # io_callback body: a host sink — metrics calls are the point.
        get_registry().counter("engine_rounds_total").inc(float(v))
    jax.experimental.io_callback(cb, None, carry, ordered=True)
    return carry, ()

def drive(init):
    return jax.lax.scan(step, init, None, length=2)

def host_report(n):
    # Plain host code (never traced): also fine.
    get_registry().counter("engine_rounds_total").inc(n)
'''


class TestMetricsInTrace:
    def test_fires_on_registry_call_in_traced_region(self):
        fs = lint({"gossipy_tpu/_mfire.py": METRICS_IN_TRACE})
        assert rules_of(fs) == ["metrics-in-trace"]
        assert all(f.path == "gossipy_tpu/_mfire.py" for f in fs)
        assert "host-side sinks" in fs[0].message

    def test_quiet_in_io_callback_and_host_code(self):
        assert lint({"gossipy_tpu/_mquiet.py": METRICS_HOST_OK}) == []

    def test_tree_is_clean(self):
        # The standing invariant: the engine/scheduler feed the registry
        # strictly host-side (post-run / post-slice), so the real tree
        # has zero metrics-in-trace findings.
        assert [f for f in lint() if f.rule == "metrics-in-trace"] == []

    def test_suppressible_like_any_rule(self):
        src = METRICS_IN_TRACE.replace(
            "# host sink!", "# tracelint: disable=metrics-in-trace")
        assert lint({"gossipy_tpu/_mfire.py": src}) == []


TRACE_IN_TRACE = '''
import jax
from .telemetry.tracing import get_tracer

def body(carry, x):
    get_tracer().counter_event("rounds", value=1.0)   # host sink!
    return carry, x

def drive(init):
    return jax.lax.scan(body, init, None, length=2)
'''

TRACE_HOST_OK = '''
import jax
from .telemetry.tracing import span, get_tracer

def drive(sim, state, key):
    # Host driver spanning AROUND the jitted call: the whole point.
    with span("drive.run", tracer=get_tracer()):
        state, rep = sim.start(state, n_rounds=2, key=key)
    return state, rep

def step(carry, _):
    def cb(v):
        # io_callback body: host-side by contract — tracer calls OK.
        get_tracer().counter_event("rounds", value=float(v))
    jax.experimental.io_callback(cb, None, carry, ordered=True)
    return carry, ()

def traced_drive(init):
    return jax.lax.scan(step, init, None, length=2)
'''


class TestTraceInTrace:
    def test_fires_on_tracer_call_in_traced_region(self):
        fs = lint({"gossipy_tpu/_tfire.py": TRACE_IN_TRACE})
        assert rules_of(fs) == ["trace-in-trace"]
        assert all(f.path == "gossipy_tpu/_tfire.py" for f in fs)
        assert "host-side sink" in fs[0].message

    def test_quiet_in_host_driver_and_io_callback(self):
        assert lint({"gossipy_tpu/_tquiet.py": TRACE_HOST_OK}) == []

    def test_tree_is_clean(self):
        # The standing invariant: engine/cohort/scheduler span strictly
        # host-side (around jitted calls, never inside them), so the
        # real tree has zero trace-in-trace findings.
        assert [f for f in lint() if f.rule == "trace-in-trace"] == []

    def test_suppressible_like_any_rule(self):
        src = TRACE_IN_TRACE.replace(
            "# host sink!", "# tracelint: disable=trace-in-trace")
        assert lint({"gossipy_tpu/_tfire.py": src}) == []


LEDGER_IN_TRACE = '''
import jax
from .telemetry.ledger import resolve_ledger

def body(carry, x):
    resolve_ledger(None).append({"kind": "engine"})   # host sink!
    return carry, x

def drive(init):
    return jax.lax.scan(body, init, None, length=2)
'''

LEDGER_HOST_OK = '''
import jax
from .telemetry.ledger import ingest_manifest, resolve_ledger

def drive(sim, state, key):
    # Post-run host append — the engine/_ledger_append contract.
    state, rep = sim.start(state, n_rounds=2, key=key)
    led = resolve_ledger(None)
    if led is not None:
        ingest_manifest(led, sim.run_manifest(), kind="engine")
    return state, rep

def step(carry, _):
    def cb(v):
        # io_callback body: host-side by contract — ledger calls OK.
        resolve_ledger(None).append({"v": float(v)})
    jax.experimental.io_callback(cb, None, carry, ordered=True)
    return carry, ()

def traced_drive(init):
    return jax.lax.scan(step, init, None, length=2)
'''


class TestLedgerInTrace:
    def test_fires_on_ledger_call_in_traced_region(self):
        fs = lint({"gossipy_tpu/_lfire.py": LEDGER_IN_TRACE})
        assert rules_of(fs) == ["ledger-in-trace"]
        assert all(f.path == "gossipy_tpu/_lfire.py" for f in fs)
        assert "host-side sink" in fs[0].message

    def test_quiet_in_host_driver_and_io_callback(self):
        assert lint({"gossipy_tpu/_lquiet.py": LEDGER_HOST_OK}) == []

    def test_tree_is_clean(self):
        # The standing invariant behind the engine/ledger-on HLO
        # identity pair: every ledger append is post-run host code, so
        # the real tree has zero ledger-in-trace findings.
        assert [f for f in lint() if f.rule == "ledger-in-trace"] == []

    def test_suppressible_like_any_rule(self):
        src = LEDGER_IN_TRACE.replace(
            "# host sink!", "# tracelint: disable=ledger-in-trace")
        assert lint({"gossipy_tpu/_lfire.py": src}) == []


class TestRegistryRules:
    def test_unregistered_per_round_field_is_flagged(self):
        eng_path = REPO / "gossipy_tpu" / "simulation" / "engine.py"
        src = eng_path.read_text() + (
            "\n\ndef _seeded_stats(stats):\n"
            "    stats[\"health_bogus\"] = 1\n")
        fs = lint({"gossipy_tpu/simulation/engine.py": src})
        assert rules_of(fs) == ["registry-field"]
        assert "health_bogus" in fs[0].message

    def test_registered_fields_pass(self):
        # The real tree's stat keys are all registered (this is the
        # standing invariant the rule protects).
        assert [f for f in lint() if f.rule == "registry-field"] == []

    def test_schema_bump_without_parse_line_branch_is_flagged(self):
        ev_path = REPO / "gossipy_tpu" / "simulation" / "events.py"
        src = ev_path.read_text().replace("SCHEMA = 8", "SCHEMA = 9")
        assert "SCHEMA = 9" in src
        fs = lint({"gossipy_tpu/simulation/events.py": src})
        assert rules_of(fs) == ["schema-tolerance"]
        assert "if schema < 9" in fs[0].message

    def test_schema_bump_with_branch_passes(self):
        ev_path = REPO / "gossipy_tpu" / "simulation" / "events.py"
        src = ev_path.read_text().replace("SCHEMA = 8", "SCHEMA = 9")
        src = src.replace(
            "        if schema < 8:",
            "        if schema < 9:\n"
            "            row.setdefault(\"future\", None)\n"
            "        if schema < 8:")
        fs = lint({"gossipy_tpu/simulation/events.py": src})
        assert [f for f in fs if f.rule == "schema-tolerance"] == []


class TestSuppressionAndBaseline:
    def test_line_suppression(self):
        src = TRACED_VIOLATIONS.replace(
            "v = float(carry)                 # host-coerce",
            "v = float(carry)  # tracelint: disable=host-coerce")
        fs = lint({"gossipy_tpu/_seeded.py": src})
        assert len([f for f in fs if f.rule == "host-coerce"]) == 1  # .item

    def test_file_pragma(self):
        src = ("# tracelint: disable-file=all\n") + TRACED_VIOLATIONS
        assert lint({"gossipy_tpu/_seeded.py": src}) == []

    def test_baseline_waives_by_identity_not_line_number(self):
        fs = lint({"gossipy_tpu/_seeded.py": TRACED_VIOLATIONS})
        base = baseline_from_findings(fs)
        assert filter_baselined(fs, base) == []
        # Shift every line down: identical findings still waived.
        shifted = lint({"gossipy_tpu/_seeded.py":
                        "\n\n\n" + TRACED_VIOLATIONS})
        assert filter_baselined(shifted, base) == []
        # A NEW violation is not.
        more = TRACED_VIOLATIONS.replace(
            "return carry, v", "q = bool(carry)\n    return carry, v")
        fs2 = lint({"gossipy_tpu/_seeded.py": more})
        new = filter_baselined(fs2, base)
        assert len(new) == 1 and new[0].rule == "host-coerce"

    def test_committed_baseline_is_empty(self):
        # The tree is clean: the committed baseline waives nothing, so
        # any future finding is NEW by construction.
        base = json.loads(
            (REPO / "gossipy_tpu" / "analysis" / "baseline.json")
            .read_text())
        assert base["findings"] == {}


class TestCLI:
    def test_exits_zero_on_clean_tree(self):
        proc = subprocess.run(
            [sys.executable, "-m", "gossipy_tpu.analysis"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 new" in proc.stdout

    def test_exits_nonzero_on_seeded_violation(self, tmp_path):
        fixture = tmp_path / "repo"
        shutil.copytree(REPO / "gossipy_tpu", fixture / "gossipy_tpu",
                        ignore=shutil.ignore_patterns("__pycache__"))
        target = fixture / "gossipy_tpu" / "simulation" / "engine.py"
        target.write_text(target.read_text() + TRACED_VIOLATIONS)
        proc = subprocess.run(
            [sys.executable, "-m", "gossipy_tpu.analysis",
             "--root", str(fixture),
             "--json", str(tmp_path / "findings.json")],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        out = json.loads((tmp_path / "findings.json").read_text())
        assert out["new"] and {f["rule"] for f in out["new"]} >= {
            "host-coerce", "host-branch"}


class TestFindingIdentity:
    def test_key_is_content_addressed(self):
        a = Finding("host-coerce", "x.py", 10, 0, "m", "v = float(c)")
        b = Finding("host-coerce", "x.py", 99, 4, "m2", "v = float(c)")
        c = Finding("host-coerce", "x.py", 10, 0, "m", "w = float(c)")
        assert a.key == b.key
        assert a.key != c.key


class TestHLOHelpers:
    """Pure-text fingerprint helpers (no jax tracing)."""

    def test_first_divergence_reports_position(self):
        from gossipy_tpu.analysis import first_divergence
        a = "op1\nop2\nop3"
        b = "op1\nopX\nop3"
        div = first_divergence(a, b)
        assert div["instruction"] == 2
        assert div["a"] == "op2" and div["b"] == "opX"
        assert first_divergence(a, a) is None

    def test_canonicalization_strips_locations_only(self):
        from gossipy_tpu.analysis import canonicalize_hlo
        raw = ('module @jit_run {\n'
               '  %0 = stablehlo.add %a, %b loc("eng.py":10:2)\n'
               '#loc1 = loc("x")\n'
               '\n  }\n')
        canon = canonicalize_hlo(raw)
        assert 'loc(' not in canon
        assert 'stablehlo.add %a, %b' in canon
        assert '' not in canon.split("\n")

    def test_golden_manifest_matches_gate_case_names(self):
        from gossipy_tpu.analysis.hlo import gate_cases
        golden = json.loads(
            (REPO / "gossipy_tpu" / "analysis" / "hlo_golden.json")
            .read_text())
        assert set(golden["cases"]) == {
            name for name, _ in gate_cases()["fingerprint"]}


@pytest.mark.slow
class TestHLOGate:
    """Lowering-based checks (each builds + AOT-lowers small programs;
    compile-free but trace-heavy — slow lane, the CI static-analysis job
    runs scripts/hlo_gate.py over the full matrix instead)."""

    def test_fingerprint_deterministic(self):
        from gossipy_tpu.analysis import hlo_fingerprint
        from gossipy_tpu.analysis.hlo import _make_sim
        fp1, _ = hlo_fingerprint(_make_sim())
        fp2, _ = hlo_fingerprint(_make_sim())
        assert fp1 == fp2

    def test_perturbation_names_first_divergent_instruction(self):
        # The acceptance fixture: a one-line engine-config perturbation
        # (mailbox capacity 2 -> 3 changes the deliver fori_loop bounds)
        # must produce a named first-divergent-instruction report.
        from gossipy_tpu.analysis import assert_identical_hlo
        from gossipy_tpu.analysis.hlo import _make_sim
        with pytest.raises(AssertionError) as exc:
            assert_identical_hlo(_make_sim(mailbox_slots=2),
                                 _make_sim(mailbox_slots=3),
                                 label="seeded perturbation")
        msg = str(exc.value)
        assert "canonical instruction" in msg
        assert "sim_a:" in msg and "sim_b:" in msg
