"""Multi-host (multi-controller) execution: a REAL 2-process JAX cluster.

The reference simulates its network inside one Python process (SURVEY
§2.12); here the node axis spans an actual process boundary: two
interpreters form a cluster via ``parallel.init_distributed`` (Gloo
cross-process collectives on the CPU backend — the same multi-controller
mechanics as a TPU pod), build one global mesh, and run the SAME gossip
round program SPMD. The test asserts both processes produce identical,
learning metrics, and that they match a single-process run of the same
configuration on an equal-size virtual mesh.
"""

import json
import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# One fresh interpreter per process + Gloo bootstrap + compile: slow lane.
pytestmark = pytest.mark.slow

# argv: coordinator_address num_processes process_id [mesh_mode].
# num_processes == 1 skips the cluster bootstrap entirely (the
# single-controller comparison run) — no string surgery on this source.
# mesh_mode "2x2d": the 4-process leg — an explicit (dcn, nodes) 2-D mesh
# via make_mesh_2d(4, 2), gossip leg only (the TP/ring legs exercise their
# own meshes in the 2-process test).
_CHILD = """
import json, sys
num_processes = int(sys.argv[2])
mesh_mode = sys.argv[4] if len(sys.argv) > 4 else "1d"
if num_processes > 1:
    from gossipy_tpu.parallel import init_distributed
    init_distributed(coordinator_address=sys.argv[1],
                     num_processes=num_processes,
                     process_id=int(sys.argv[3]))

import jax
import numpy as np
import optax
from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.parallel import make_mesh, make_mesh_2d, shard_data, \\
    shard_state
from gossipy_tpu.simulation import GossipSimulator

assert jax.device_count() == 8, jax.device_count()
if mesh_mode == "2x2d":
    # (dcn=4 hosts, nodes=2 per host): the node axis spans BOTH axes, so
    # neighbor gathers cross every process boundary of the 4-way cluster.
    mesh = make_mesh_2d(4, 2)
else:
    mesh = make_mesh()  # global: spans every process

n, d = 16, 8
rng = np.random.default_rng(0)
w = rng.normal(size=d)
X = rng.normal(size=(n * 12, d)).astype(np.float32)
y = (X @ w > 0).astype(np.int64)
disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25), n=n)
h = SGDHandler(model=LogisticRegression(d, 2), loss=losses.cross_entropy,
               optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
               n_classes=2, input_shape=(d,),
               create_model_mode=CreateModelMode.MERGE_UPDATE)
sim = GossipSimulator(h, Topology.random_regular(n, 4, seed=0),
                      shard_data(disp.stacked(), mesh), delta=8,
                      protocol=AntiEntropyProtocol.PUSH)
state = shard_state(sim.init_nodes(jax.random.PRNGKey(0)), mesh)
state, report = sim.start(state, n_rounds=10, key=jax.random.PRNGKey(1))
acc = report.curves(local=False)["accuracy"]

if mesh_mode == "2x2d":
    print("RESULT " + json.dumps({"proc": int(sys.argv[3]),
                                  "acc": [round(float(a), 6) for a in acc]}),
          flush=True)
    sys.exit(0)

# DP x TP leg: a (nodes, model) mesh whose axes both span the process
# boundary - parameter leaves shard their largest non-node dim over
# "model", contraction psums cross processes.
from gossipy_tpu.models import MLP
from gossipy_tpu.parallel import make_mesh_tp
mesh_tp = make_mesh_tp(4, 2)
h_tp = SGDHandler(model=MLP(d, 2, hidden_dims=(16,)),
                  loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                  local_epochs=1, batch_size=8, n_classes=2,
                  input_shape=(d,),
                  create_model_mode=CreateModelMode.MERGE_UPDATE)
sim_tp = GossipSimulator(h_tp, Topology.random_regular(n, 4, seed=0),
                         shard_data(disp.stacked(), mesh_tp), delta=8,
                         protocol=AntiEntropyProtocol.PUSH)
st_tp = shard_state(sim_tp.init_nodes(jax.random.PRNGKey(2)), mesh_tp)
st_tp, rep_tp = sim_tp.start(st_tp, n_rounds=2, key=jax.random.PRNGKey(3))
acc_tp = rep_tp.curves(local=False)["accuracy"]

# Explicit-collectives leg: ring attention's ppermute schedule over the
# SAME global mesh - on the cluster the ring hops cross the process
# boundary (the DCN path of the comm backend), which GSPMD-only legs
# above never exercise.
from gossipy_tpu.parallel.collectives import ring_attention
kq, kk, kv = jax.random.split(jax.random.PRNGKey(5), 3)
q_r = jax.random.normal(kq, (32, 8))
k_r = jax.random.normal(kk, (32, 8))
v_r = jax.random.normal(kv, (32, 8))
ring_sum = float(jax.jit(
    lambda a, b, c: (ring_attention(a, b, c, mesh, causal=True) ** 2).sum()
)(q_r, k_r, v_r))

print("RESULT " + json.dumps({"proc": int(sys.argv[3]),
                              "acc": [round(float(a), 6) for a in acc],
                              "acc_tp": [round(float(a), 6)
                                         for a in acc_tp],
                              "ring_sum": round(ring_sum, 5)}),
      flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(argv, env):
    return subprocess.Popen([sys.executable, "-c", _CHILD] + argv, env=env,
                            cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _drain_all(procs, timeout):
    """communicate() every child concurrently (a full stderr pipe on one
    child must not deadlock another mid-collective) and always reap."""
    outs = [None] * len(procs)

    def drain(i):
        outs[i] = procs[i].communicate()

    threads = [threading.Thread(target=drain, args=(i,), daemon=True)
               for i in range(len(procs))]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout)
        if any(t.is_alive() for t in threads):
            raise TimeoutError("cluster children did not finish in time")
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs


def _result(out: str):
    line = [l for l in out.splitlines() if l.startswith("RESULT ")][-1]
    return json.loads(line[len("RESULT "):])


def test_two_process_cluster_runs_one_gossip_program():
    from _virtual_mesh import virtual_mesh_env

    env2 = virtual_mesh_env(4, extra_path=REPO)  # 4 local devices/process
    env1 = virtual_mesh_env(8, extra_path=REPO)
    coord = f"127.0.0.1:{_free_port()}"
    # The single-process comparison run is independent: overlap it with the
    # cluster instead of serializing ~20s of interpreter+compile after it.
    procs = [_spawn([coord, "2", "0"], env2), _spawn([coord, "2", "1"], env2),
             _spawn(["unused", "1", "0"], env1)]
    outs = _drain_all(procs, timeout=420)
    for i, p in enumerate(procs):
        assert p.returncode == 0, \
            f"child {i} failed:\n{outs[i][1][-2500:]}"
    acc0 = _result(outs[0][0])["acc"]
    acc1 = _result(outs[1][0])["acc"]
    acc_single = _result(outs[2][0])["acc"]
    # SPMD: both controllers of the one program see identical metrics.
    assert acc0 == acc1
    assert np.isfinite(acc0).all()
    assert acc0[-1] > 0.8  # and the network actually learns
    # The 2-process cluster matches a single-process 8-device run of the
    # same configuration (same global mesh shape -> same program, same key
    # streams) to float32 noise — cross-process (Gloo) reductions may
    # differ from local ones by an ulp.
    np.testing.assert_allclose(acc_single, acc0, atol=1e-5)
    # DP x TP leg: both controllers agree and match the single-process run.
    tp0 = _result(outs[0][0])["acc_tp"]
    tp1 = _result(outs[1][0])["acc_tp"]
    tp_single = _result(outs[2][0])["acc_tp"]
    assert tp0 == tp1 and np.isfinite(tp0).all()
    # Ring-attention leg: the explicit ppermute ring crossed the process
    # boundary and produced the same result as the single-process mesh.
    ring0 = _result(outs[0][0])["ring_sum"]
    ring1 = _result(outs[1][0])["ring_sum"]
    ring_single = _result(outs[2][0])["ring_sum"]
    assert ring0 == ring1
    np.testing.assert_allclose(ring0, ring_single, rtol=1e-5)
    np.testing.assert_allclose(tp_single, tp0, atol=1e-5)


def test_four_process_cluster_2x2_mesh():
    """Round-4 verdict #7: the mesh logic must generalize past the
    pairwise case. Four controllers (2 virtual devices each) form one
    8-device cluster under an explicit (dcn=4, nodes=2) hybrid mesh; the
    node axis spans both mesh axes, so the round program's neighbor
    gathers cross all three process boundaries. All four controllers must
    see identical learning metrics, matching a single-process run of the
    same 2-D mesh shape."""
    from _virtual_mesh import virtual_mesh_env

    env4 = virtual_mesh_env(2, extra_path=REPO)  # 2 local devices/process
    env1 = virtual_mesh_env(8, extra_path=REPO)
    coord = f"127.0.0.1:{_free_port()}"
    procs = [_spawn([coord, "4", str(i), "2x2d"], env4) for i in range(4)]
    procs.append(_spawn(["unused", "1", "0", "2x2d"], env1))
    outs = _drain_all(procs, timeout=420)
    for i, p in enumerate(procs):
        assert p.returncode == 0, \
            f"child {i} failed:\n{outs[i][1][-2500:]}"
    accs = [_result(outs[i][0])["acc"] for i in range(4)]
    acc_single = _result(outs[4][0])["acc"]
    for a in accs[1:]:
        assert a == accs[0]  # one SPMD program, four controllers
    assert np.isfinite(accs[0]).all()
    assert accs[0][-1] > 0.8
    np.testing.assert_allclose(acc_single, accs[0], atol=1e-5)
