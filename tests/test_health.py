"""Numerics sentinels + flight recorder + deterministic replay.

Covers the ISSUE-4 acceptance criteria:

- ``sentinels=None`` traces the identical program (HLO-equality, the
  probes-test pattern) and enabling sentinels does not perturb the
  simulated trajectory;
- a healthy run's health block is provably clean (zero non-finite
  counts, zero trips, clean slots);
- the full failure path: seeded NaN injection trips the sentinel, the
  flight recorder emits a bundle, and ``replay_bundle`` reproduces the
  same first-divergent round and leaf deterministically on CPU;
- exception and watchdog bundles;
- jitted-vs-sequential health parity;
- the report registry round trip for every health array, JSONL schema
  v4 with a version-tolerant reader, ``update_health`` replay/live
  agreement, the ``CallbackReceiver`` satellite, and the telemetry
  sink's ``dropped_events`` counter.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
    Topology, uniform_mixing
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, WeightedSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import (
    All2AllGossipSimulator,
    CallbackReceiver,
    GossipSimulator,
    JSONLinesReceiver,
    SequentialGossipSimulator,
    SimulationEventReceiver,
)
from gossipy_tpu.simulation.report import PER_ROUND_FIELDS, SimulationReport
from gossipy_tpu.telemetry import (
    FlightRecorder,
    HealthCarry,
    SentinelConfig,
    TelemetrySink,
    get_sink,
    replay_bundle,
    set_sink,
)
from gossipy_tpu.telemetry.health import (
    health_event_row,
    health_round_stats,
    nonfinite_counts,
    nonfinite_total,
    per_node_param_norm,
)

N, D = 16, 6


def make_data(seed=0, n_samples=320):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_samples, D)).astype(np.float32)
    y = (X @ rng.normal(size=D) > 0).astype(np.int64)
    return X, y


def make_handler(lr=0.1):
    return SGDHandler(model=LogisticRegression(D, 2),
                      loss=losses.cross_entropy, optimizer=optax.sgd(lr),
                      local_epochs=1, batch_size=8, n_classes=2,
                      input_shape=(D,),
                      create_model_mode=CreateModelMode.MERGE_UPDATE)


def make_stacked(n=N, poison_node=None):
    X, y = make_data()
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n, eval_on_user=False)
    data = dict(disp.stacked())
    if poison_node is not None:
        xtr = np.asarray(data["xtr"]).copy()
        xtr[poison_node] = np.nan  # the seeded NaN injection
        data["xtr"] = xtr
    return data


def make_sim(cls=GossipSimulator, lr=0.1, topo=None, n=N, poison_node=None,
             **kwargs):
    topo = topo or Topology.random_regular(n, 4, seed=3)
    return cls(make_handler(lr), topo, make_stacked(n, poison_node),
               delta=20,
               protocol=kwargs.pop("protocol", AntiEntropyProtocol.PUSH),
               **kwargs)


def run(sim, rounds=5, key=None, **init_kw):
    key = key if key is not None else jax.random.PRNGKey(0)
    st = sim.init_nodes(key, **init_kw)
    return sim.start(st, n_rounds=rounds, key=key)[1]


class TestSentinelConfig:
    def test_coerce(self):
        assert SentinelConfig.coerce(None) is None
        assert SentinelConfig.coerce(False) is None
        assert SentinelConfig.coerce(True) == SentinelConfig()
        cfg = SentinelConfig(divergence=False)
        assert SentinelConfig.coerce(cfg) is cfg
        assert SentinelConfig.coerce(SentinelConfig(
            nonfinite=False, divergence=False, saturation=False)) is None
        with pytest.raises(TypeError):
            SentinelConfig.coerce("nonfinite")
        with pytest.raises(ValueError):
            SentinelConfig(ema_alpha=0.0)
        with pytest.raises(ValueError):
            SentinelConfig(divergence_factor=1.0)


class TestPureMath:
    def test_nonfinite_counts_and_total(self):
        tree = {"a": jnp.array([[1.0, np.nan], [np.inf, 2.0]]),
                "b": jnp.arange(3)}  # int leaf: always finite
        np.testing.assert_array_equal(np.asarray(nonfinite_counts(tree)),
                                      [2, 0])
        assert int(nonfinite_total(tree)) == 2

    def test_per_node_param_norm(self):
        params = {"w": jnp.array([[3.0, 4.0], [0.0, 0.0]])}
        np.testing.assert_allclose(np.asarray(per_node_param_norm(params)),
                                   [5.0, 0.0])

    def test_divergence_flags_and_ema_guard(self):
        cfg = SentinelConfig(nonfinite=False, saturation=False,
                             divergence_factor=10.0, ema_alpha=0.5)
        hc = HealthCarry.zeros(2)
        p0 = {"w": jnp.ones((2, 3))}
        # Round 1 seeds the EMA: no flags however large the norms.
        hc, s1 = health_round_stats(cfg, hc, p0, p0, None, None)
        assert int(s1["health_diverged_per_node"].sum()) == 0
        assert int(s1["health_trip"]) == 0
        # Round 2: node 0 jumps 100x -> flagged; node 1 stays put.
        p1 = {"w": jnp.ones((2, 3)).at[0].mul(100.0)}
        hc, s2 = health_round_stats(cfg, hc, p0, p1, None, None)
        np.testing.assert_array_equal(
            np.asarray(s2["health_diverged_per_node"]), [1, 0])
        assert int(s2["health_trip"]) == 1
        # A non-finite norm must not poison the EMA baseline.
        p_nan = {"w": jnp.full((2, 3), jnp.nan)}
        ema_before = np.asarray(hc.norm_ema)
        hc, _ = health_round_stats(cfg, hc, p1, p_nan, None, None)
        np.testing.assert_array_equal(np.asarray(hc.norm_ema), ema_before)

    def test_skipped_eval_rows_do_not_count(self):
        cfg = SentinelConfig(divergence=False, saturation=False)
        hc = HealthCarry.zeros(2)
        p = {"w": jnp.ones((2, 3))}
        skipped = jnp.full((3,), jnp.nan)  # eval_every skip marker
        _, s = health_round_stats(cfg, hc, p, p, skipped, skipped)
        assert int(s["health_nonfinite_metrics"]) == 0
        genuine = jnp.array([0.5, jnp.nan, 1.0])  # partial NaN = genuine
        _, s = health_round_stats(cfg, HealthCarry.zeros(2), p, p,
                                  genuine, skipped)
        assert int(s["health_nonfinite_metrics"]) == 1

    def test_health_event_row_subsets(self):
        assert health_event_row({}) is None
        row = health_event_row({
            "health_nonfinite_params": np.array([2, 0]),
            "health_nonfinite_delta": np.array([0, 0]),
            "health_nonfinite_metrics": np.int32(0),
            "health_trip": np.int32(1)})
        assert row["nonfinite_params"] == 2 and row["trip"] is True
        assert "diverged" not in row


class TestSentinelsOffIsUntouched:
    def test_default_report_has_no_health_fields(self):
        rep = run(make_sim())
        for name in PER_ROUND_FIELDS:
            if name.startswith("health_"):
                assert getattr(rep, name) is None, name
        assert rep.health_layer_names is None
        assert rep.to_dict()["health_trip"] is None

    def test_sentinels_do_not_perturb_the_trajectory(self):
        rep_off = run(make_sim())
        rep_on = run(make_sim(sentinels=True))
        np.testing.assert_array_equal(rep_off.sent_per_round,
                                      rep_on.sent_per_round)
        np.testing.assert_array_equal(np.asarray(rep_off._global),
                                      np.asarray(rep_on._global))

    def test_sentinels_off_hlo_identical(self):
        """The sentinels=None trace is the same program as one built
        without the argument at all (every addition is behind the
        trace-time gate) — the ISSUE-4 acceptance criterion. Shares the
        hlo_gate backbone (scripts/hlo_gate.py runs the same pair in
        CI); on divergence the first differing instruction is named."""
        from gossipy_tpu.analysis import assert_identical_hlo
        assert_identical_hlo(make_sim(), make_sim(sentinels=None),
                             label="sentinels=None")

    def test_all2all_sentinels_off_hlo_identical(self):
        from gossipy_tpu.analysis import assert_identical_hlo

        def build(**kw):
            topo = Topology.random_regular(N, 4, seed=3)
            handler = WeightedSGDHandler(
                model=LogisticRegression(D, 2), loss=losses.cross_entropy,
                optimizer=optax.sgd(0.1), local_epochs=1, batch_size=8,
                n_classes=2, input_shape=(D,),
                create_model_mode=CreateModelMode.MERGE_UPDATE)
            return All2AllGossipSimulator(handler, topo, make_stacked(),
                                          delta=20,
                                          mixing=uniform_mixing(topo), **kw)
        assert_identical_hlo(build(), build(sentinels=None),
                             label="all2all sentinels=None")


class TestHealthyRunVitals:
    def test_clean_run_is_provably_clean(self):
        rep = run(make_sim(sentinels=True), rounds=6)
        assert (rep.health_trip == 0).all()
        assert int(rep.health_nonfinite_params.sum()) == 0
        assert int(rep.health_nonfinite_delta.sum()) == 0
        assert (rep.health_nonfinite_metrics == 0).all()
        assert (rep.health_first_bad_slot == -1).all()
        assert int(rep.health_diverged_per_node.sum()) == 0
        assert np.isfinite(rep.health_delta_norm).all()
        # The high-water mark is the running max of the delta norms.
        np.testing.assert_allclose(rep.health_delta_hwm,
                                   np.maximum.accumulate(
                                       rep.health_delta_norm), rtol=1e-6)
        # Saturation watermark: monotone, bounded by the mailbox size.
        hwm = rep.health_mailbox_hwm_run
        assert (np.diff(hwm) >= 0).all()
        assert hwm[-1] == rep.mailbox_hwm_per_round.max()
        assert len(rep.health_layer_names) == \
            rep.health_nonfinite_params.shape[1]

    def test_subset_config_only_emits_its_fields(self):
        rep = run(make_sim(sentinels=SentinelConfig(divergence=False,
                                                    saturation=False)))
        assert rep.health_nonfinite_params is not None
        assert rep.health_diverged_per_node is None
        assert rep.health_mailbox_hwm_run is None
        assert rep.health_trip is not None

    def test_divergence_flags_fire_on_host_injected_jump(self):
        """A node whose params jump 1000x mid-run trips the divergence
        sentinel on the next round."""
        sim = make_sim(sentinels=True)
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key)
        st, _ = sim.start(st, n_rounds=3, key=key, donate_state=False)
        boosted = jax.tree.map(
            lambda l: jnp.asarray(np.asarray(l) * np.where(
                np.arange(l.shape[0]).reshape((-1,) + (1,) * (l.ndim - 1))
                == 5, 1000.0, 1.0), l.dtype),
            st.model.params)
        st = st._replace(model=st.model._replace(params=boosted))
        st, rep = sim.start(st, n_rounds=2, key=key)
        assert rep.health_diverged_per_node[0, 5] == 1
        assert rep.health_trip[0] == 1

    def test_run_repetitions_carries_health_per_seed(self):
        sim = make_sim(sentinels=True)
        keys = jax.random.split(jax.random.PRNGKey(0), 3)
        _, reports = sim.run_repetitions(4, keys)
        assert len(reports) == 3
        for rep in reports:
            assert rep.health_trip.shape == (4,)
            assert (rep.health_trip == 0).all()

    def test_all2all_health_block(self):
        topo = Topology.random_regular(N, 4, seed=3)
        handler = WeightedSGDHandler(
            model=LogisticRegression(D, 2), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.1), local_epochs=1, batch_size=8,
            n_classes=2, input_shape=(D,),
            create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim = All2AllGossipSimulator(handler, topo, make_stacked(),
                                     delta=20, mixing=uniform_mixing(topo),
                                     sentinels=True)
        rep = run(sim, rounds=4)
        assert (rep.health_trip == 0).all()
        assert int(rep.health_nonfinite_params.sum()) == 0
        # The All2All branch vital: effective mixing weights all finite.
        assert (rep.health_mix_nonfinite == 0).all()
        # No mailbox slot loop in this round shape.
        assert rep.health_first_bad_slot is None

    def test_manifest_records_sentinel_config(self):
        sim_on = make_sim(sentinels=SentinelConfig(divergence_factor=7.0))
        sim_off = make_sim()
        d = sim_on.run_manifest().to_dict()
        assert d["config"]["sentinels"]["divergence_factor"] == 7.0
        assert sim_off.run_manifest().to_dict()["config"]["sentinels"] \
            is None


class TestReportAndEvents:
    def test_health_arrays_round_trip_and_concatenate(self, tmp_path):
        rep = run(make_sim(sentinels=True), rounds=4)
        path = str(tmp_path / "report.json")
        rep.save(path)
        loaded = SimulationReport.load(path)
        for name in PER_ROUND_FIELDS:
            if not name.startswith("health_"):
                continue
            v = getattr(rep, name)
            if v is None:
                assert getattr(loaded, name) is None, name
                continue
            np.testing.assert_allclose(
                np.asarray(getattr(loaded, name), np.float64),
                np.asarray(v, np.float64), atol=1e-6, err_msg=name)
        assert loaded.health_layer_names == rep.health_layer_names
        cat = SimulationReport.concatenate([loaded, loaded])
        assert cat.health_trip.shape == (8,)
        assert cat.health_nonfinite_params.shape[0] == 8
        assert cat.health_layer_names == rep.health_layer_names

    def test_update_health_replay_and_live_agree(self):
        class Recorder(SimulationEventReceiver):
            def __init__(self, live=False):
                self.live = live
                self.rows = []

            def update_health(self, round, health):
                self.rows.append((round, health))

        def go(live):
            sim = make_sim(sentinels=True)
            rec = Recorder(live=live)
            sim.add_receiver(rec)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key)
            sim.start(st, n_rounds=3, key=key)
            return rec.rows

        replay, live = go(False), go(True)
        assert [r for r, _ in replay] == [1, 2, 3]
        assert replay == live
        for _, row in replay:
            assert row["trip"] is False
            assert row["nonfinite_params"] == 0
            assert "delta_norm" in row and "mailbox_hwm_run" in row

    def test_jsonl_v4_rows_and_reader(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        sim = make_sim(sentinels=True)
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rx)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key)
            sim.start(st, n_rounds=3, key=key)
        rows = [JSONLinesReceiver.parse_line(l) for l in open(path)]
        assert all(r["schema"] == 8 for r in rows)  # v8: + "cohort"
        assert all(r["health"] is not None for r in rows)
        assert all(r["health"]["trip"] is False for r in rows)
        assert all(r["probes"] is None for r in rows)  # probes off here
        # A v3 line normalizes: health comes back null.
        v3 = json.dumps({"schema": 3, "round": 1, "sent": 2, "failed": 0,
                         "failed_by_cause": None, "probes": None,
                         "size": 9, "local": None, "global": None})
        assert JSONLinesReceiver.parse_line(v3)["health"] is None

    def test_callback_receiver_forwards_flat_rows(self):
        rows = []
        sim = make_sim(sentinels=True, probes=True)
        sim.add_receiver(CallbackReceiver(rows.append))
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=3, key=key)
        assert [r["round"] for r in rows] == [1, 2, 3]
        for r in rows:
            assert set(r) >= {"round", "sent", "failed", "size",
                              "failed_by_cause", "probes", "health",
                              "global"}
            assert r["health"]["trip"] is False
            assert r["probes"]["accepted_total"] >= 0

    def test_callback_receiver_live_matches_replay(self):
        def go(live):
            rows = []
            sim = make_sim(sentinels=True)
            sim.add_receiver(CallbackReceiver(rows.append, live=live))
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key)
            sim.start(st, n_rounds=2, key=key)
            return rows
        assert go(False) == go(True)


class TestSinkDroppedEvents:
    def test_ring_counts_evictions(self):
        sink = TelemetrySink(maxlen=4)
        for i in range(7):
            sink.emit("k", {"i": i})
        assert sink.dropped_events == 3
        assert len(sink.events()) == 4
        assert sink.events()[0].data["i"] == 3  # oldest three evicted

    def test_close_records_loss_in_mirror(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        sink = TelemetrySink(maxlen=2, jsonl_path=path)
        for i in range(5):
            sink.emit("k", {"i": i})
        sink.close()
        lines = [json.loads(l) for l in open(path)]
        # The mirror keeps every line; the final one records the ring loss.
        assert len(lines) == 6
        assert lines[-1]["kind"] == "sink_closed"
        assert lines[-1]["data"]["dropped_events"] == 3

    def test_manifest_surfaces_sink_counters(self):
        prev = set_sink(TelemetrySink(maxlen=2))
        try:
            for i in range(5):
                get_sink().emit("k", {"i": i})
            d = make_sim().run_manifest().to_dict()
            assert d["telemetry_sink"]["dropped_events"] == 3
            assert d["telemetry_sink"]["maxlen"] == 2
        finally:
            set_sink(prev)


class TestFlightRecorderAndReplay:
    """The ISSUE-4 end-to-end forensics proof: NaN-injection run ->
    bundle on disk -> replay names the same first-divergent round and
    leaf deterministically."""

    POISON = 3

    def _sim(self):
        return make_sim(sentinels=True, poison_node=self.POISON)

    def test_nan_injection_trips_and_replays_bit_for_bit(self, tmp_path):
        sim = self._sim()
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key, local_train=False)
        rec = FlightRecorder(str(tmp_path), chunk=2)
        st, reports, bundle = rec.run(sim, st, n_rounds=8, key=key)
        assert bundle is not None and os.path.isdir(bundle)
        verdict = json.load(open(os.path.join(bundle, "verdict.json")))
        assert verdict["kind"] == "sentinel"
        assert verdict["first_bad_round"] is not None
        assert verdict["detail"]["nonfinite_params_total"] > 0
        # The bundle is self-describing: manifest + events + checkpoint.
        assert os.path.exists(os.path.join(bundle, "manifest.json"))
        assert os.path.exists(os.path.join(bundle, "events.jsonl"))
        from gossipy_tpu.checkpoint import load_checkpoint_meta
        meta = load_checkpoint_meta(os.path.join(bundle, "checkpoint"))
        assert meta["round"] == verdict["chunk_start_round"]

        # Replay through a FRESH simulator (same config): same first
        # divergent round, a named leaf, the poisoned node implicated.
        replayed = replay_bundle(bundle, self._sim())
        assert replayed["matches_recorded"] is True
        assert replayed["first_bad_round"] == verdict["first_bad_round"]
        assert replayed["trip"] == "nonfinite"
        assert replayed["leaf"] in sim._probe_layer_names()
        assert self.POISON in replayed["nodes"]
        assert replayed["phase"] in ("send", "receive_merge", "reply")

        # Determinism (bit-for-bit on CPU): a second replay produces the
        # identical verdict.
        again = replay_bundle(bundle, self._sim())
        assert again == replayed

    def test_exception_writes_bundle_then_reraises(self, tmp_path):
        sim = make_sim(sentinels=True)
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key)
        boom = RuntimeError("chip fell over")

        original = sim.start
        calls = {"n": 0}

        def flaky_start(*a, **kw):
            calls["n"] += 1
            if calls["n"] >= 2:
                raise boom
            return original(*a, **kw)

        sim.start = flaky_start
        rec = FlightRecorder(str(tmp_path), chunk=2)
        with pytest.raises(RuntimeError, match="chip fell over"):
            rec.run(sim, st, n_rounds=6, key=key)
        assert rec.bundle_path is not None
        verdict = json.load(open(os.path.join(rec.bundle_path,
                                              "verdict.json")))
        assert verdict["kind"] == "exception"
        assert "chip fell over" in verdict["detail"]["error"]
        # The checkpoint is the last HEALTHY chunk boundary (round 2).
        assert verdict["chunk_start_round"] == 2

    def test_watchdog_fires_on_stalled_chunk(self, tmp_path):
        import time
        sim = make_sim(sentinels=True)
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key)
        original = sim.start

        def slow_start(*a, **kw):
            time.sleep(0.6)  # outlives the watchdog deadline
            return original(*a, **kw)

        sim.start = slow_start
        rec = FlightRecorder(str(tmp_path), chunk=4,
                             watchdog_seconds=0.1)
        st, reports, bundle = rec.run(sim, st, n_rounds=4, key=key)
        assert bundle is not None
        verdict = json.load(open(os.path.join(bundle, "verdict.json")))
        assert verdict["kind"] == "watchdog"

    def test_recorder_requires_sentinels(self, tmp_path):
        sim = make_sim()
        with pytest.raises(AssertionError, match="sentinel-enabled"):
            FlightRecorder(str(tmp_path)).run(
                sim, sim.init_nodes(jax.random.PRNGKey(0)), 2,
                jax.random.PRNGKey(0))

    def test_trailing_window_truncation_warns_once(self, tmp_path):
        prev = set_sink(TelemetrySink(maxlen=3))
        try:
            sim = self._sim()
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key, local_train=False)
            rec = FlightRecorder(str(tmp_path), chunk=4,
                                 trailing_rounds=16)
            with pytest.warns(UserWarning, match="trailing window "
                                                "truncated"):
                rec.run(sim, st, n_rounds=8, key=key)
        finally:
            set_sink(prev)

    def test_replay_cli_with_factory(self, tmp_path):
        """scripts/replay_bundle.py end to end via a --factory module."""
        import subprocess
        import sys
        sim = self._sim()
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key, local_train=False)
        rec = FlightRecorder(str(tmp_path / "fr"), chunk=2)
        _, _, bundle = rec.run(sim, st, n_rounds=8, key=key)
        assert bundle is not None
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        factory_dir = tmp_path / "mods"
        factory_dir.mkdir()
        (factory_dir / "bundle_factory.py").write_text(
            f"import sys\nsys.path.insert(0, {repo!r})\n"
            f"sys.path.insert(0, {os.path.dirname(__file__)!r})\n"
            "from test_health import TestFlightRecorderAndReplay\n"
            "def build():\n"
            "    return TestFlightRecorderAndReplay()._sim()\n")
        env = dict(os.environ,
                   PYTHONPATH=str(factory_dir), JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts",
                                          "replay_bundle.py"),
             bundle, "--factory", "bundle_factory:build"],
            capture_output=True, text=True, timeout=300, env=env)
        assert proc.returncode == 0, proc.stderr
        verdict = json.loads(proc.stdout)
        assert verdict["matches_recorded"] is True
        assert verdict["trip"] == "nonfinite"


class TestSequentialParity:
    """Jitted-vs-sequential health parity: the clean regime agrees
    everywhere, and the same seeded NaN injection trips BOTH engines on
    the first round under PUSH_PULL (every firing node merges the reply
    it provoked, so the poisoned node provably trains round one)."""

    def _build(self, cls, poison=None, **kw):
        return make_sim(cls=cls, lr=0.0, topo=Topology.clique(N),
                        protocol=AntiEntropyProtocol.PUSH_PULL,
                        sentinels=True, poison_node=poison, **kw)

    def test_clean_parity(self):
        reps = {}
        for cls, name in ((GossipSimulator, "jit"),
                          (SequentialGossipSimulator, "seq")):
            sim = self._build(cls)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key, local_train=False, common_init=True)
            reps[name] = sim.start(st, n_rounds=4, key=key)[1]
        jit, seq = reps["jit"], reps["seq"]
        # Common init + lr 0: nothing moves, nothing trips — exactly, on
        # both engines.
        for rep in (jit, seq):
            assert (rep.health_trip == 0).all()
            assert int(rep.health_nonfinite_params.sum()) == 0
            np.testing.assert_allclose(rep.health_delta_norm,
                                       np.zeros(4), atol=1e-6)
        np.testing.assert_array_equal(jit.health_diverged_per_node,
                                      seq.health_diverged_per_node)
        assert jit.health_layer_names == seq.health_layer_names

    def test_nan_injection_parity(self):
        trips = {}
        for cls, name in ((GossipSimulator, "jit"),
                          (SequentialGossipSimulator, "seq")):
            sim = self._build(cls, poison=3)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key, local_train=False, common_init=True)
            rep = sim.start(st, n_rounds=3, key=key)[1]
            trips[name] = rep
        for name, rep in trips.items():
            assert rep.health_trip[0] == 1, name
            assert int(rep.health_nonfinite_params[0].sum()) > 0, name
        # Both engines implicate the same leaves on the first round.
        np.testing.assert_array_equal(
            np.asarray(trips["jit"].health_nonfinite_params[0]) > 0,
            np.asarray(trips["seq"].health_nonfinite_params[0]) > 0)
