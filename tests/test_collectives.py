"""Explicit ring-collective comm backend (shard_map + ppermute) vs dense.

Runs on the 8-virtual-device CPU mesh (conftest): the same XLA partitioner
and collective lowering as a real ICI ring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.core import CreateModelMode, Topology, uniform_mixing
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import WeightedSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.parallel import make_mesh, shard_data, shard_state
from gossipy_tpu.parallel.collectives import (ring_all_gather,
                                              ring_mix_pytree,
                                              ring_mixed_matmul)
from gossipy_tpu.simulation import All2AllGossipSimulator
from gossipy_tpu.utils import params_allclose


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    return make_mesh(8)


@pytest.fixture(scope="module")
def mesh4():
    """4-device submesh: the unrolled ring program is half the size of the
    8-hop one, cutting per-test compile time — used by the ring-attention
    cases that don't specifically probe the full mesh."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")
    return make_mesh(4)


def test_ring_all_gather_matches_identity(mesh):
    x = jnp.arange(16 * 5, dtype=jnp.float32).reshape(16, 5)
    out = ring_all_gather(x, mesh)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_ring_matmul_matches_dense(mesh):
    rng = np.random.default_rng(0)
    n, f = 24, 17
    w = jnp.asarray(rng.normal(size=(n, n)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    got = ring_mixed_matmul(w, x, mesh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(w @ x),
                               rtol=1e-5, atol=1e-5)


def test_ring_matmul_2d_mesh():
    """On a 2-D (dcn, nodes) mesh the ring runs over the combined axes —
    every device holds N/8 rows, not N/4."""
    from gossipy_tpu.parallel import make_mesh_2d
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh2 = make_mesh_2d(2, 4)
    rng = np.random.default_rng(4)
    n, f = 16, 9
    w = jnp.asarray(rng.normal(size=(n, n)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    got = ring_mixed_matmul(w, x, mesh2, ("dcn", "nodes"))
    np.testing.assert_allclose(np.asarray(got), np.asarray(w @ x),
                               rtol=1e-5, atol=1e-5)


def test_ring_matmul_rolled_loop(mesh, monkeypatch):
    """Rings larger than _UNROLL_MAX use the fori_loop path; force it on the
    8-device mesh and check it matches the dense product."""
    from gossipy_tpu.parallel import collectives
    monkeypatch.setattr(collectives, "_UNROLL_MAX", 2)
    rng = np.random.default_rng(5)
    n, f = 16, 7
    w = jnp.asarray(rng.normal(size=(n, n)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(ring_mixed_matmul(w, x, mesh)),
                               np.asarray(w @ x), rtol=1e-5, atol=1e-5)
    y = jnp.asarray(rng.normal(size=(16, 3)).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(ring_all_gather(y, mesh)),
                                  np.asarray(y))


def test_ring_matmul_custom_axis_name():
    """A 1-D mesh with a non-default axis name works end to end (the node
    axis entry derives from the mesh, not a hardcoded 'nodes')."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    m = make_mesh(8, axis_name="x")
    rng = np.random.default_rng(6)
    w = jnp.asarray(rng.normal(size=(8, 8)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(8, 5)).astype(np.float32))
    got = ring_mixed_matmul(w, x, m, "x")
    np.testing.assert_allclose(np.asarray(got), np.asarray(w @ x),
                               rtol=1e-5, atol=1e-5)


def test_ring_matmul_under_jit(mesh):
    rng = np.random.default_rng(1)
    n, f = 16, 33
    w = jnp.asarray(rng.normal(size=(n, n)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(n, f)).astype(np.float32))
    got = jax.jit(lambda w, x: ring_mixed_matmul(w, x, mesh))(w, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(w @ x),
                               rtol=1e-5, atol=1e-5)


def test_ring_mix_pytree(mesh):
    rng = np.random.default_rng(2)
    n = 16
    w = jnp.asarray(rng.normal(size=(n, n)).astype(np.float32))
    tree = {"a": jnp.asarray(rng.normal(size=(n, 3, 4)).astype(np.float32)),
            "b": jnp.asarray(rng.normal(size=(n,)).astype(np.float32))}
    got = ring_mix_pytree(w, tree, mesh)
    np.testing.assert_allclose(np.asarray(got["a"]),
                               np.asarray((w @ tree["a"].reshape(n, -1))
                                          .reshape(n, 3, 4)),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got["b"]),
                               np.asarray(w @ tree["b"][:, None])[:, 0],
                               rtol=1e-5, atol=1e-5)


def _make_sim(ring: bool, mesh):
    n, d = 16, 8
    rng = np.random.default_rng(3)
    wvec = rng.normal(size=d)
    X = rng.normal(size=(n * 20, d)).astype(np.float32)
    y = (X @ wvec > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25), n=n)
    topo = Topology.random_regular(n, 4, seed=0)
    handler = WeightedSGDHandler(model=LogisticRegression(d, 2),
                                 loss=losses.cross_entropy,
                                 optimizer=optax.sgd(0.1), local_epochs=1,
                                 batch_size=8, n_classes=2, input_shape=(d,),
                                 create_model_mode=CreateModelMode.MERGE_UPDATE)
    data = shard_data(disp.stacked(), mesh)
    return All2AllGossipSimulator(handler, topo, data, delta=4,
                                  mixing=uniform_mixing(topo),
                                  mesh=mesh, ring_mix=ring)


def test_all2all_ring_equals_dense(mesh):
    """The ring-scheduled mixing produces the same simulation as the dense
    einsum path (same keys; only the matmul schedule differs)."""
    key = jax.random.PRNGKey(7)
    results = []
    for ring in (False, True):
        sim = _make_sim(ring, mesh)
        state = shard_state(sim.init_nodes(key), mesh)
        state, report = sim.start(state, n_rounds=3, key=jax.random.PRNGKey(9))
        results.append((state, report.curves(local=False)["accuracy"][-1]))
    (s_dense, acc_dense), (s_ring, acc_ring) = results
    assert params_allclose(s_dense.model.params, s_ring.model.params,
                           atol=1e-4)
    assert abs(acc_dense - acc_ring) < 1e-5


def dense_attention(q, k, v, causal=False):
    s = (q @ k.T) / np.sqrt(q.shape[1])
    if causal:
        pos = np.arange(q.shape[0])
        s = np.where(pos[None, :] > pos[:, None], -1e30, s)
    p = np.exp(s - s.max(axis=1, keepdims=True))
    p = p / p.sum(axis=1, keepdims=True)
    return p @ v


class TestRingAttention:
    """Sequence-parallel attention: the comm backend generalized beyond the
    gossip exchange (no reference analogue — it has no sequence models)."""

    def test_matches_dense(self, mesh4):
        # mesh4 like the rest of the class; the full 8-device attention
        # ring runs in the driver's dryrun_multichip every round.
        from gossipy_tpu.parallel.collectives import ring_attention
        rng = np.random.default_rng(0)
        s_len, d, dv = 32, 16, 12
        q = rng.normal(size=(s_len, d)).astype(np.float32)
        k = rng.normal(size=(s_len, d)).astype(np.float32)
        v = rng.normal(size=(s_len, dv)).astype(np.float32)
        got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             mesh4)
        np.testing.assert_allclose(np.asarray(got), dense_attention(q, k, v),
                                   rtol=1e-5, atol=1e-5)

    def test_causal_masks_by_global_position(self, mesh4):
        from gossipy_tpu.parallel.collectives import ring_attention
        rng = np.random.default_rng(1)
        s_len, d = 24, 8
        q = rng.normal(size=(s_len, d)).astype(np.float32)
        k = rng.normal(size=(s_len, d)).astype(np.float32)
        v = rng.normal(size=(s_len, d)).astype(np.float32)
        got = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             mesh4, causal=True)
        np.testing.assert_allclose(np.asarray(got),
                                   dense_attention(q, k, v, causal=True),
                                   rtol=1e-5, atol=1e-5)
        # Row 0 may only attend to position 0: output == v[0].
        np.testing.assert_allclose(np.asarray(got)[0], v[0], rtol=1e-5,
                                   atol=1e-5)

    def test_vmapped_over_heads(self, mesh4):
        from gossipy_tpu.parallel.collectives import ring_attention
        rng = np.random.default_rng(2)
        h, s_len, d = 3, 16, 8
        q, k, v = (rng.normal(size=(h, s_len, d)).astype(np.float32)
                   for _ in range(3))
        got = jax.vmap(lambda a, b, c: ring_attention(a, b, c, mesh4))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        want = np.stack([dense_attention(q[i], k[i], v[i]) for i in range(h)])
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-5)

    def test_rolled_loop(self, mesh4, monkeypatch):
        """Pods ring through the fori_loop path (> _UNROLL_MAX devices):
        the (m, l, acc) carry crosses the pcast varying-axes fix-up and the
        causal mask uses a traced hop index — force the path on the
        submesh."""
        from gossipy_tpu.parallel import collectives
        monkeypatch.setattr(collectives, "_UNROLL_MAX", 2)
        rng = np.random.default_rng(4)
        s_len, d = 24, 8
        q = rng.normal(size=(s_len, d)).astype(np.float32)
        k = rng.normal(size=(s_len, d)).astype(np.float32)
        v = rng.normal(size=(s_len, d)).astype(np.float32)
        for causal in (False, True):
            got = collectives.ring_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh4,
                causal=causal)
            np.testing.assert_allclose(
                np.asarray(got), dense_attention(q, k, v, causal=causal),
                rtol=1e-5, atol=1e-5)

    def test_under_jit(self, mesh4):
        from gossipy_tpu.parallel.collectives import ring_attention
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
        f = jax.jit(lambda a: ring_attention(a, a, a, mesh4, causal=True))
        np.testing.assert_allclose(
            np.asarray(f(q)),
            dense_attention(np.asarray(q), np.asarray(q), np.asarray(q),
                            causal=True), rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_gradients_match_dense(self, mesh4):
        """Backward pass (round-3, VERDICT weak #5): grads of a scalar loss
        through the ring schedule equal grads through dense attention, for
        q, k and v. (The shard_map-grad compile is ~25 s on this host ->
        slow lane; the default lane still runs gradients daily through
        test_trains_a_tiny_attention_model.)"""
        rng = np.random.default_rng(7)
        s_len, dim = 16, 8
        q, k, v = (jnp.asarray(rng.normal(size=(s_len, dim))
                               .astype(np.float32)) for _ in range(3))
        tgt = jnp.asarray(rng.normal(size=(s_len, dim)).astype(np.float32))

        def dense_jnp(q, k, v, causal):
            s = (q @ k.T) / np.sqrt(dim)
            if causal:
                pos = jnp.arange(s_len)
                s = jnp.where(pos[None, :] > pos[:, None], -1e30, s)
            p = jax.nn.softmax(s, axis=1)
            return p @ v

        from gossipy_tpu.parallel import collectives
        # One configuration: causal=True covers the mask AND the softmax
        # statistics in the transposed program; the non-causal backward is
        # the same program minus the where (each extra config costs a ~25 s
        # shard_map-grad compile on this host).
        causal = True

        def loss_ring(q, k, v):
            out = collectives.ring_attention(q, k, v, mesh4, causal=causal)
            return jnp.mean((out - tgt) ** 2)

        def loss_dense(q, k, v):
            return jnp.mean((dense_jnp(q, k, v, causal) - tgt) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gr, gd, name in zip(g_ring, g_dense, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gd), rtol=1e-4, atol=1e-5,
                err_msg=f"grad wrt {name}, causal={causal}")

    def test_trains_a_tiny_attention_model(self, mesh4):
        """A minimal consumer: one attention layer trained end-to-end with
        the sequence axis ring-sharded — loss must drop on a retrieval
        task (each position attends back to position 0)."""
        import optax

        from gossipy_tpu.parallel.collectives import ring_attention

        rng = np.random.default_rng(11)
        s_len, dim = 16, 8
        x = jnp.asarray(rng.normal(size=(s_len, dim)).astype(np.float32))
        tgt = jnp.broadcast_to(x[0], (s_len, dim))  # retrieve position 0

        params = {"wq": jnp.eye(dim), "wk": jnp.eye(dim),
                  "wv": jnp.eye(dim)}
        opt = optax.adam(0.05)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                out = ring_attention(x @ p["wq"], x @ p["wk"], x @ p["wv"],
                                     mesh4)
                return jnp.mean((out - tgt) ** 2)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        losses = []
        for _ in range(25):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[-1] < 0.5 * losses[0], losses[::6]
