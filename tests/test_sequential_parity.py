"""Sequential high-fidelity engine vs the ACTUAL torch reference.

The bulk engine's parity contract is distributional-with-envelopes
(test_envelope_parity.py) because bulk-synchronous rounds legitimately
shift information propagation. The sequential engine exists to close
exactly those divergences (same-tick reactions, in-round sequential
state, per-message events), so its contract is TIGHTER than the envelope:
mean accuracy curves within a small flat gap from round 1 (no burn-in
exclusion), and message accounting equal in distribution.
"""

import contextlib
import io

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.flow_control import RandomizedTokenAccount
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import SequentialGossipSimulator

from test_golden_parity import import_reference, make_dataset, D

pytestmark = pytest.mark.parity

N_NODES = 16
N_SEEDS = 5
ROUNDS = 12
TOKEN_ROUNDS = 24


def _ref_curves_and_sent(X, y, token: bool, rounds: int):
    """Reference runs: per-seed accuracy curves + per-round sent counts
    (via a per-message receiver at the reference's own granularity)."""
    import torch
    from gossipy import CACHE, set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    from test_golden_parity import make_sent_per_round_receiver

    curves, sents = [], []
    for seed in range(N_SEEDS):
        CACHE.clear()
        ref_seed(seed)
        dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
        disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
        proto = TorchModelHandler(
            net=RefLogReg(D, 2), optimizer=torch.optim.SGD,
            optimizer_params={"lr": 0.5},
            criterion=torch.nn.CrossEntropyLoss(), local_epochs=1,
            batch_size=8, create_model_mode=RefMode.MERGE_UPDATE)
        nodes = GossipNode.generate(
            data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
            model_proto=proto, round_len=20, sync=True)
        kwargs = dict(nodes=nodes, data_dispatcher=disp, delta=20,
                      protocol=RefProto.PUSH, delay=ConstantDelay(0),
                      online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
        if token:
            from gossipy.flow_control import RandomizedTokenAccount as RefRTA
            from gossipy.simul import TokenizedGossipSimulator as RefTGS
            sim = RefTGS(token_account=RefRTA(C=20, A=10),
                         utility_fun=lambda mh1, mh2, msg: 1, **kwargs)
        else:
            sim = RefSim(**kwargs)
        report = SimulationReport()
        counter = make_sent_per_round_receiver(20, rounds)
        sim.add_receiver(report)
        sim.add_receiver(counter)
        sim.init_nodes(seed=seed)
        with contextlib.redirect_stdout(io.StringIO()):
            sim.start(n_rounds=rounds)
        curves.append([e[1]["accuracy"]
                       for e in report.get_evaluation(False)])
        sents.append(counter.counts.copy())
    return np.asarray(curves, np.float64), np.asarray(sents, np.float64)


def _seq_curves_and_sent(X, y, token: bool, rounds: int):
    curves, sents = [], []
    for seed in range(N_SEEDS):
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=seed)
        disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
        handler = SGDHandler(
            model=LogisticRegression(D, 2), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
            n_classes=2, input_shape=(D,),
            create_model_mode=CreateModelMode.MERGE_UPDATE)
        kwargs = {}
        if token:
            kwargs = dict(token_account=RandomizedTokenAccount(C=20, A=10))
        sim = SequentialGossipSimulator(
            handler, Topology.clique(N_NODES), disp.stacked(), delta=20,
            protocol=AntiEntropyProtocol.PUSH, **kwargs)
        k = jax.random.PRNGKey(seed)
        st = sim.init_nodes(k)
        st, report = sim.start(st, n_rounds=rounds,
                               key=jax.random.fold_in(k, 1))
        curves.append(report.curves(local=False)["accuracy"])
        sents.append(report.sent_per_round)
    return np.asarray(curves, np.float64), np.asarray(sents, np.float64)


class TestSequentialParity:
    def test_vanilla_tight_agreement(self):
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=5)
        ref_c, ref_s = _ref_curves_and_sent(X, y, token=False, rounds=ROUNDS)
        seq_c, seq_s = _seq_curves_and_sent(X, y, token=False, rounds=ROUNDS)
        # Message accounting: exact on a fault-free clique (one send per
        # node per round, both sides).
        np.testing.assert_array_equal(ref_s, np.full_like(ref_s, N_NODES))
        np.testing.assert_array_equal(seq_s, np.full_like(seq_s, N_NODES))
        # Accuracy: tighter than the envelope test's contract — a flat
        # bound on the mean gap with NO burn-in window. Round 1 reflects
        # init-DISTRIBUTION differences (torch vs jax initializers), not
        # loop semantics — measured 0.045-0.068 across PRNG-stream
        # revisions of this engine — and gets its own loose bound; the
        # semantics contract is rounds >= 2 (gap decays to ~0.001 by
        # round 12).
        gap = np.abs(ref_c.mean(0) - seq_c.mean(0))
        assert gap[0] < 0.09, f"round-1 init gap {gap[0]:.3f}"
        assert gap[1:].max() < 0.04, \
            f"sequential-vs-reference mean gap {np.round(gap, 3)}"

    def test_tokenized_same_tick_tight_agreement(self):
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=6)
        ref_c, ref_s = _ref_curves_and_sent(X, y, token=True,
                                            rounds=TOKEN_ROUNDS)
        seq_c, seq_s = _seq_curves_and_sent(X, y, token=True,
                                            rounds=TOKEN_ROUNDS)
        # Flow-control signature FIRST: per-round send-count curves (how
        # many messages, including same-tick reactions, each round) are
        # init-independent and must agree within 2 SEM + a 10%-of-N flat
        # slack from ROUND 1 — this is the same-tick dynamics evidence.
        sgap = np.abs(ref_s.mean(0) - seq_s.mean(0))
        tol = 2.0 * (ref_s.std(0) + seq_s.std(0)) / np.sqrt(N_SEEDS) \
            + 0.10 * N_NODES
        assert (sgap <= tol).all(), \
            f"sent-curve gap {np.round(sgap, 2)} vs tol {np.round(tol, 2)}"
        # Accuracy: while the accounts charge (~C/2 rounds) NO messages
        # flow, so both sides sit frozen at their init plateaus — the
        # plateau offset (measured 0.114) is the torch-vs-jax init
        # DISTRIBUTION, not loop semantics. The contract is therefore on
        # the mixing dynamics once flow starts: the gap must decay to the
        # vanilla-level band by the tail.
        gap = np.abs(ref_c.mean(0) - seq_c.mean(0))
        assert gap[:8].std() < 0.01, \
            "charging-phase plateau should be flat on both sides"
        assert gap[-3:].max() < 0.08, \
            f"tokenized tail gap {np.round(gap[-3:], 3)}"
        # Measured: plateau 0.114 -> 0.051 by round 24 — the init offset
        # washes out through mixing at the expected rate.
        assert gap[-1] < 0.55 * gap[:8].mean(), \
            f"gap must decay after flow starts ({gap[-1]:.3f} vs plateau " \
            f"{gap[:8].mean():.3f})"
