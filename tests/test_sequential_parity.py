"""Sequential high-fidelity engine vs the ACTUAL torch reference.

The bulk engine's parity contract is distributional-with-envelopes
(test_envelope_parity.py) because bulk-synchronous rounds legitimately
shift information propagation. The sequential engine exists to close
exactly those divergences (same-tick reactions, in-round sequential
state, per-message events), so its contract is TIGHTER than the envelope:
mean accuracy curves within a small flat gap from round 1 (no burn-in
exclusion), and message accounting equal in distribution.
"""

import contextlib
import io

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.flow_control import RandomizedTokenAccount
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import CacheNeighGossipSimulator, \
    PassThroughGossipSimulator, SequentialGossipSimulator

from test_golden_parity import import_reference, make_dataset, D

# The torch-reference comparisons below carry the opt-in ``parity`` mark
# (slow; need /root/reference importable). The VARIANT parity class at the
# bottom compares our two engines against each other — no reference, no
# mark, default lane.

N_NODES = 16
N_SEEDS = 5
ROUNDS = 12
TOKEN_ROUNDS = 24


def _ref_curves_and_sent(X, y, token: bool, rounds: int):
    """Reference runs: per-seed accuracy curves + per-round sent counts
    (via a per-message receiver at the reference's own granularity)."""
    import torch
    from gossipy import CACHE, set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    from test_golden_parity import make_sent_per_round_receiver

    curves, sents = [], []
    for seed in range(N_SEEDS):
        CACHE.clear()
        ref_seed(seed)
        dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
        disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
        proto = TorchModelHandler(
            net=RefLogReg(D, 2), optimizer=torch.optim.SGD,
            optimizer_params={"lr": 0.5},
            criterion=torch.nn.CrossEntropyLoss(), local_epochs=1,
            batch_size=8, create_model_mode=RefMode.MERGE_UPDATE)
        nodes = GossipNode.generate(
            data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
            model_proto=proto, round_len=20, sync=True)
        kwargs = dict(nodes=nodes, data_dispatcher=disp, delta=20,
                      protocol=RefProto.PUSH, delay=ConstantDelay(0),
                      online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
        if token:
            from gossipy.flow_control import RandomizedTokenAccount as RefRTA
            from gossipy.simul import TokenizedGossipSimulator as RefTGS
            sim = RefTGS(token_account=RefRTA(C=20, A=10),
                         utility_fun=lambda mh1, mh2, msg: 1, **kwargs)
        else:
            sim = RefSim(**kwargs)
        report = SimulationReport()
        counter = make_sent_per_round_receiver(20, rounds)
        sim.add_receiver(report)
        sim.add_receiver(counter)
        sim.init_nodes(seed=seed)
        with contextlib.redirect_stdout(io.StringIO()):
            sim.start(n_rounds=rounds)
        curves.append([e[1]["accuracy"]
                       for e in report.get_evaluation(False)])
        sents.append(counter.counts.copy())
    return np.asarray(curves, np.float64), np.asarray(sents, np.float64)


def _seq_curves_and_sent(X, y, token: bool, rounds: int):
    curves, sents = [], []
    for seed in range(N_SEEDS):
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=seed)
        disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
        handler = SGDHandler(
            model=LogisticRegression(D, 2), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
            n_classes=2, input_shape=(D,),
            create_model_mode=CreateModelMode.MERGE_UPDATE)
        kwargs = {}
        if token:
            kwargs = dict(token_account=RandomizedTokenAccount(C=20, A=10))
        sim = SequentialGossipSimulator(
            handler, Topology.clique(N_NODES), disp.stacked(), delta=20,
            protocol=AntiEntropyProtocol.PUSH, **kwargs)
        k = jax.random.PRNGKey(seed)
        st = sim.init_nodes(k)
        st, report = sim.start(st, n_rounds=rounds,
                               key=jax.random.fold_in(k, 1))
        curves.append(report.curves(local=False)["accuracy"])
        sents.append(report.sent_per_round)
    return np.asarray(curves, np.float64), np.asarray(sents, np.float64)


@pytest.mark.parity
class TestSequentialParity:
    def test_vanilla_tight_agreement(self):
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=5)
        ref_c, ref_s = _ref_curves_and_sent(X, y, token=False, rounds=ROUNDS)
        seq_c, seq_s = _seq_curves_and_sent(X, y, token=False, rounds=ROUNDS)
        # Message accounting: exact on a fault-free clique (one send per
        # node per round, both sides).
        np.testing.assert_array_equal(ref_s, np.full_like(ref_s, N_NODES))
        np.testing.assert_array_equal(seq_s, np.full_like(seq_s, N_NODES))
        # Accuracy: tighter than the envelope test's contract — a flat
        # bound on the mean gap with NO burn-in window. Round 1 reflects
        # init-DISTRIBUTION differences (torch vs jax initializers), not
        # loop semantics — measured 0.045-0.068 across PRNG-stream
        # revisions of this engine — and gets its own loose bound; the
        # semantics contract is rounds >= 2 (gap decays to ~0.001 by
        # round 12).
        gap = np.abs(ref_c.mean(0) - seq_c.mean(0))
        assert gap[0] < 0.09, f"round-1 init gap {gap[0]:.3f}"
        assert gap[1:].max() < 0.04, \
            f"sequential-vs-reference mean gap {np.round(gap, 3)}"

    def test_tokenized_same_tick_tight_agreement(self):
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=6)
        ref_c, ref_s = _ref_curves_and_sent(X, y, token=True,
                                            rounds=TOKEN_ROUNDS)
        seq_c, seq_s = _seq_curves_and_sent(X, y, token=True,
                                            rounds=TOKEN_ROUNDS)
        # Flow-control signature FIRST: per-round send-count curves (how
        # many messages, including same-tick reactions, each round) are
        # init-independent and must agree within 2 SEM + a 10%-of-N flat
        # slack from ROUND 1 — this is the same-tick dynamics evidence.
        sgap = np.abs(ref_s.mean(0) - seq_s.mean(0))
        tol = 2.0 * (ref_s.std(0) + seq_s.std(0)) / np.sqrt(N_SEEDS) \
            + 0.10 * N_NODES
        assert (sgap <= tol).all(), \
            f"sent-curve gap {np.round(sgap, 2)} vs tol {np.round(tol, 2)}"
        # Accuracy: while the accounts charge (~C/2 rounds) NO messages
        # flow, so both sides sit frozen at their init plateaus — the
        # plateau offset (measured 0.114) is the torch-vs-jax init
        # DISTRIBUTION, not loop semantics. The contract is therefore on
        # the mixing dynamics once flow starts: the gap must decay to the
        # vanilla-level band by the tail.
        gap = np.abs(ref_c.mean(0) - seq_c.mean(0))
        assert gap[:8].std() < 0.01, \
            "charging-phase plateau should be flat on both sides"
        assert gap[-3:].max() < 0.08, \
            f"tokenized tail gap {np.round(gap[-3:], 3)}"
        # Measured: plateau 0.114 -> 0.051 by round 24 — the init offset
        # washes out through mixing at the expected rate.
        assert gap[-1] < 0.55 * gap[:8].mean(), \
            f"gap must decay after flow starts ({gap[-1]:.3f} vs plateau " \
            f"{gap[:8].mean():.3f})"


# ---------------------------------------------------------------------------
# Variant parity: PassThrough / CacheNeigh (jitted subclass vs the
# sequential engine's eager `variant=` replica; no torch reference needed,
# so no `parity` mark — this runs in the default lane).
# ---------------------------------------------------------------------------

VAR_SEEDS = 5
VAR_ROUNDS = 12


def _variant_handler():
    return SGDHandler(
        model=LogisticRegression(D, 2), loss=losses.cross_entropy,
        optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
        n_classes=2, input_shape=(D,),
        create_model_mode=CreateModelMode.MERGE_UPDATE)


def _variant_data(X, y):
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=0)
    return DataDispatcher(dh, n=N_NODES, eval_on_user=False).stacked()


def _seq_variant_curves(data, variant, topo, rounds=VAR_ROUNDS,
                        seeds=VAR_SEEDS):
    curves = []
    for seed in range(seeds):
        sim = SequentialGossipSimulator(
            _variant_handler(), topo, data, delta=20,
            protocol=AntiEntropyProtocol.PUSH, variant=variant)
        k = jax.random.PRNGKey(100 + seed)
        st = sim.init_nodes(k)
        st, rep = sim.start(st, n_rounds=rounds,
                            key=jax.random.fold_in(k, 1))
        curves.append(rep.curves(local=False)["accuracy"])
    return np.asarray(curves, np.float64)


def _jit_variant_curves(data, cls, topo, rounds=VAR_ROUNDS,
                        seeds=VAR_SEEDS):
    sim = cls(_variant_handler(), topo, data, delta=20,
              protocol=AntiEntropyProtocol.PUSH)
    keys = jax.random.split(jax.random.PRNGKey(7), seeds)
    _, reports = sim.run_repetitions(rounds, keys)
    return np.asarray([r.curves(local=False)["accuracy"] for r in reports],
                      np.float64)


def _assert_variant_envelope(jit_c, seq_c, label, burn_frac=0.4,
                             slack=0.05):
    """Cross-engine contract (the envelope discipline of
    test_envelope_parity, applied between OUR two engines): mean accuracy
    curves agree within 2 SEM + a flat slack after burn-in, and both
    sides clearly learn."""
    m_j, s_j = jit_c.mean(0), jit_c.std(0)
    m_s, s_s = seq_c.mean(0), seq_c.std(0)
    assert m_j[-1] > 0.75 and m_s[-1] > 0.75, \
        f"{label}: a side failed to learn (jit {m_j[-1]:.3f}, " \
        f"seq {m_s[-1]:.3f})"
    tail = slice(int(jit_c.shape[1] * burn_frac), None)
    gap = np.abs(m_j[tail] - m_s[tail])
    tol = 2.0 * (s_j[tail] + s_s[tail]) / np.sqrt(jit_c.shape[0]) + slack
    assert (gap <= tol).all(), (
        f"{label}: jitted-vs-sequential mean-curve gap exceeds the seed "
        f"envelope:\njit mean {np.round(m_j, 3)}\n"
        f"seq mean {np.round(m_s, 3)}\n"
        f"gap {np.round(gap, 3)} vs tol {np.round(tol, 3)}")


def _stacked_final_params(models):
    return np.concatenate([
        np.concatenate([np.asarray(l).reshape(-1)
                        for l in jax.tree.leaves(m.params)])
        for m in models])


class TestVariantSequentialParity:
    """ROADMAP fidelity corner (ISSUE-7 satellite): the sequential engine
    replicates the PassThrough/CacheNeigh node behaviors eagerly, so the
    bulk engine's variant subclasses have a high-fidelity counterpart to
    diverge from. Bulk-synchronous rounds and the shuffled sequential tick
    loop legitimately differ per seed (SURVEY.md §7c), so the CROSS-engine
    contract is distributional; the WITHIN-engine reduction — pass-through
    with the accept probability pinned at 1 — is exact."""

    def test_passthrough_on_regular_graph_is_vanilla_bit_for_bit(self):
        # On a clique every accept draw is min(1, deg/deg) = 1, so the
        # variant's only divergence channel (PASS adoption) never fires;
        # the variant draws live on a dedicated host RNG stream, so the
        # trajectory must equal the vanilla sequential run EXACTLY.
        X, y = make_dataset(seed=11)
        data = _variant_data(X, y)
        finals, curves = [], []
        for variant in (None, "passthrough"):
            sim = SequentialGossipSimulator(
                _variant_handler(), Topology.clique(N_NODES), data,
                delta=20, protocol=AntiEntropyProtocol.PUSH,
                variant=variant)
            k = jax.random.PRNGKey(3)
            st = sim.init_nodes(k)
            st, rep = sim.start(st, n_rounds=6,
                                key=jax.random.fold_in(k, 1))
            finals.append(_stacked_final_params(st.models))
            curves.append(rep.curves(local=False)["accuracy"])
        np.testing.assert_array_equal(finals[0], finals[1])
        np.testing.assert_array_equal(curves[0], curves[1])

    def test_passthrough_envelope_on_powerlaw_graph(self):
        # The degree-biased accept/PASS behavior only matters on a
        # heterogeneous graph — the protocol's own use case (Giaretta
        # 2019 hides power-law degree bias).
        X, y = make_dataset(seed=12)
        data = _variant_data(X, y)
        topo = Topology.barabasi_albert(N_NODES, 2, seed=1)
        jit_c = _jit_variant_curves(data, PassThroughGossipSimulator, topo)
        seq_c = _seq_variant_curves(data, "passthrough", topo)
        _assert_variant_envelope(jit_c, seq_c, "passthrough")

    def test_cache_neigh_envelope(self):
        X, y = make_dataset(seed=13)
        data = _variant_data(X, y)
        topo = Topology.ring(N_NODES, k=2)
        jit_c = _jit_variant_curves(data, CacheNeighGossipSimulator, topo)
        seq_c = _seq_variant_curves(data, "cache_neigh", topo)
        _assert_variant_envelope(jit_c, seq_c, "cache_neigh")

    def test_variants_actually_diverge_from_vanilla(self):
        # Engagement proof: on a graph where the variant semantics bind,
        # the eager replicas must CHANGE the trajectory relative to the
        # vanilla sequential run under the same key — otherwise the
        # envelope tests above would pass vacuously.
        X, y = make_dataset(seed=14)
        data = _variant_data(X, y)
        topo = Topology.barabasi_albert(N_NODES, 2, seed=2)
        finals = {}
        for variant in (None, "passthrough", "cache_neigh"):
            sim = SequentialGossipSimulator(
                _variant_handler(), topo, data, delta=20,
                protocol=AntiEntropyProtocol.PUSH, variant=variant)
            k = jax.random.PRNGKey(5)
            st = sim.init_nodes(k)
            st, _ = sim.start(st, n_rounds=6, key=jax.random.fold_in(k, 1))
            finals[variant] = _stacked_final_params(st.models)
        assert not np.array_equal(finals[None], finals["passthrough"])
        assert not np.array_equal(finals[None], finals["cache_neigh"])
        assert not np.array_equal(finals["passthrough"],
                                  finals["cache_neigh"])

    def test_variant_argument_validation(self):
        X, y = make_dataset(seed=15)
        data = _variant_data(X, y)
        with pytest.raises(ValueError, match="unknown sequential variant"):
            SequentialGossipSimulator(
                _variant_handler(), Topology.clique(N_NODES), data,
                variant="pens")
        with pytest.raises(ValueError, match="mutually"):
            SequentialGossipSimulator(
                _variant_handler(), Topology.clique(N_NODES), data,
                variant="passthrough",
                token_account=RandomizedTokenAccount(C=20, A=10))
