"""Distributional (multi-seed envelope) parity for the VARIANT protocols.

VERDICT r3 #7: the single-seed quality band said nothing about the learning
DYNAMICS of PENS and tokenized gossip. Here both sides run S seeds of the
same config and the per-round mean curves must overlap within the combined
seed envelopes (after a burn-in: the bulk-synchronous engine and the
reference's shuffled sequential loop legitimately diverge most in the first
rounds — SURVEY.md §7c).

Reference anchors: PENSNode (node.py:663-785), TokenizedGossipSimulator
(simul.py:506-689).
"""

import contextlib
import io

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.flow_control import RandomizedTokenAccount
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import PENSGossipSimulator, \
    TokenizedGossipSimulator

from test_golden_parity import import_reference, make_dataset, D

pytestmark = pytest.mark.parity

N_NODES = 16
# 10 seeds (round-4 verdict #5: 5 was statistically loose). Ours runs all
# seeds in ONE compiled program (run_repetitions), so the cost lands on the
# reference side only.
N_SEEDS = 10
PENS_ROUNDS = 16
PENS_STEP1 = 8
TOKEN_ROUNDS = 32


def assert_envelopes_overlap(curves_ref, curves_ours, label,
                             burn_frac=0.4, slack=0.02):
    """Mean learning curves must agree within 2 standard errors of the
    mean difference plus a small flat slack on the post-burn-in tail — a
    curve-shape contract, not just a final-accuracy one.

    Round-5 tightening (verdict #5): the tolerance uses the SEM
    (``sigma / sqrt(S)``), not the per-seed scatter, and the flat slack
    dropped 0.06 -> 0.02 — a systematic ~5-point offset now FAILS (the
    mutation test below proves the teeth).
    """
    ref = np.asarray(curves_ref, dtype=np.float64)
    ours = np.asarray(curves_ours, dtype=np.float64)
    assert ref.shape == ours.shape == (N_SEEDS, ref.shape[1]), \
        (label, ref.shape, ours.shape)
    m_r, s_r = ref.mean(0), ref.std(0)
    m_o, s_o = ours.mean(0), ours.std(0)
    tail = slice(int(ref.shape[1] * burn_frac), None)
    gap = np.abs(m_r[tail] - m_o[tail])
    tol = 2.0 * (s_r[tail] + s_o[tail]) / np.sqrt(N_SEEDS) + slack
    assert (gap <= tol).all(), (
        f"{label}: mean-curve gap exceeds the seed envelope on the tail:\n"
        f"ref  mean {np.round(m_r, 3)}\nours mean {np.round(m_o, 3)}\n"
        f"gap {np.round(gap, 3)} vs tol {np.round(tol, 3)}")


def _ref_curve(report) -> list:
    return [e[1]["accuracy"] for e in report.get_evaluation(False)]


def _ref_common(seed, X, y):
    import torch
    from gossipy import CACHE, set_seed as ref_seed
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH

    CACHE.clear()  # process-wide payload cache; stale entries poison reruns
    ref_seed(seed)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    return RefDispatcher(dh, n=N_NODES, eval_on_user=False)


def run_reference_pens_curves(X, y) -> list:
    import torch
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import PENSNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    curves = []
    for seed in range(N_SEEDS):
        disp = _ref_common(seed, X, y)
        proto = TorchModelHandler(
            net=RefLogReg(D, 2), optimizer=torch.optim.SGD,
            optimizer_params={"lr": 0.5},
            criterion=torch.nn.CrossEntropyLoss(), local_epochs=1,
            batch_size=8, create_model_mode=RefMode.MERGE_UPDATE)
        nodes = PENSNode.generate(
            data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
            model_proto=proto, round_len=20, sync=True, n_sampled=4,
            m_top=2, step1_rounds=PENS_STEP1)
        sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                     protocol=RefProto.PUSH, delay=ConstantDelay(0),
                     online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
        report = SimulationReport()
        sim.add_receiver(report)
        sim.init_nodes(seed=seed)
        with contextlib.redirect_stdout(io.StringIO()):
            sim.start(n_rounds=PENS_ROUNDS)
        curves.append(_ref_curve(report))
    return curves


def run_ours_pens_curves(X, y) -> list:
    """All S seeds via the phase-aware run_repetitions — one compiled
    program per phase instead of S sequential two-phase starts."""
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=0)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(
        model=LogisticRegression(D, 2), loss=losses.cross_entropy,
        optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
        n_classes=2, input_shape=(D,),
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = PENSGossipSimulator(
        handler, Topology.clique(N_NODES), disp.stacked(), delta=20,
        protocol=AntiEntropyProtocol.PUSH, n_sampled=4, m_top=2,
        step1_rounds=PENS_STEP1)
    keys = jax.random.split(jax.random.PRNGKey(7), N_SEEDS)
    _, reports = sim.run_repetitions(PENS_ROUNDS, keys)
    return [r.curves(local=False)["accuracy"] for r in reports]


_REF_TOKEN_CACHE: dict = {}


def run_reference_tokenized_curves(X, y, cache_key=None):
    """Per-seed accuracy curves AND per-round sent-message counts (the
    quantity flow control actually changes — verdict r4 #6). Cached per
    dataset: the mutation test reuses the same reference runs."""
    if cache_key in _REF_TOKEN_CACHE:
        return _REF_TOKEN_CACHE[cache_key]
    import torch
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.flow_control import RandomizedTokenAccount as RefRTA
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import SimulationReport, \
        TokenizedGossipSimulator as RefTGS

    from test_golden_parity import make_sent_per_round_receiver

    curves, sents = [], []
    for seed in range(N_SEEDS):
        disp = _ref_common(seed, X, y)
        proto = TorchModelHandler(
            net=RefLogReg(D, 2), optimizer=torch.optim.SGD,
            optimizer_params={"lr": 0.5},
            criterion=torch.nn.CrossEntropyLoss(), local_epochs=1,
            batch_size=8, create_model_mode=RefMode.MERGE_UPDATE)
        nodes = GossipNode.generate(
            data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
            model_proto=proto, round_len=20, sync=True)
        sim = RefTGS(nodes=nodes, data_dispatcher=disp,
                     token_account=RefRTA(C=20, A=10),
                     utility_fun=lambda mh1, mh2, msg: 1,
                     delta=20, protocol=RefProto.PUSH,
                     delay=ConstantDelay(0), online_prob=1.0, drop_prob=0.0,
                     sampling_eval=0.0)
        report = SimulationReport()
        counter = make_sent_per_round_receiver(20, TOKEN_ROUNDS)
        sim.add_receiver(report)
        sim.add_receiver(counter)
        sim.init_nodes(seed=seed)
        with contextlib.redirect_stdout(io.StringIO()):
            sim.start(n_rounds=TOKEN_ROUNDS)
        curves.append(_ref_curve(report))
        sents.append(counter.counts.copy())
    out = (curves, np.asarray(sents, np.float64))
    if cache_key is not None:
        _REF_TOKEN_CACHE[cache_key] = out
    return out


def run_ours_tokenized_curves(X, y, max_reactions: int = 3):
    """All S seeds in ONE compiled program via run_repetitions — the
    multi-seed path this test exists to exercise. ``max_reactions=0`` is
    the mutation knob (reactive sends killed)."""
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(
        model=LogisticRegression(D, 2), loss=losses.cross_entropy,
        optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8,
        n_classes=2, input_shape=(D,),
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = TokenizedGossipSimulator(
        handler, Topology.clique(N_NODES), disp.stacked(), delta=20,
        protocol=AntiEntropyProtocol.PUSH,
        token_account=RandomizedTokenAccount(C=20, A=10),
        max_reactions=max_reactions)
    keys = jax.random.split(jax.random.PRNGKey(42), N_SEEDS)
    _, reports = sim.run_repetitions(TOKEN_ROUNDS, keys)
    return ([r.curves(local=False)["accuracy"] for r in reports],
            np.asarray([r.sent_per_round for r in reports], np.float64))


def assert_sent_curves_close(ref_s, ours_s, label="tokenized sent",
                             lag_tolerance=True):
    """CUMULATIVE send-count curves must track within 2 SEM + 8%.

    Cumulative (not per-round) because the bulk engine delivers token
    reactions NEXT round (documented divergence, variants.py
    _post_deliver): the reaction burst at flow onset lands one round later
    than the reference's same-tick cascade, so per-round curves gap by the
    whole burst (~20 messages) at the onset edge while the running totals
    stay aligned. ``lag_tolerance`` lets OUR cumulative curve lag the
    reference's by at most one round (never lead) — exactly the
    divergence; the sequential engine's parity test passes the per-round
    contract with no allowance at all (test_sequential_parity). A LEVEL
    difference (reactions killed — the mutation test below) accumulates
    linearly and is not rescued by a one-round lag.
    """
    cum_r = np.cumsum(ref_s, axis=1)
    cum_o = np.cumsum(ours_s, axis=1)
    m_r, m_o = cum_r.mean(0), cum_o.mean(0)
    gap = np.abs(m_r - m_o)
    if lag_tolerance:
        lag = np.abs(m_r[:-1] - m_o[1:])    # ours one round behind
        gap = np.minimum(gap, np.concatenate([lag, [gap[-1]]]))
    # 8% relative: the measured transient is a ~6.4% cumulative deficit
    # peaking mid-spend (our capped next-round reactions briefly bank more
    # tokens than the reference's same-tick cascade) that decays to ~1% by
    # the horizon; the mutation deficit (reactions killed) grows to a
    # 20-30% shortfall and fails decisively.
    tol = 2.0 * (cum_r.std(0) + cum_o.std(0)) / np.sqrt(N_SEEDS) \
        + 0.08 * np.maximum(m_r, N_NODES)
    assert (gap <= tol).all(), (
        f"{label}: cumulative send-curve gap {np.round(gap, 1)} vs tol "
        f"{np.round(tol, 1)}")


class TestEnvelopeParity:
    def test_pens_learning_curve_envelope(self):
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=3)
        ref = run_reference_pens_curves(X, y)
        ours = run_ours_pens_curves(X, y)
        # 0.6 burn-in + 0.03 slack under the round-5 SEM tolerance: ours
        # starts from a lower init plateau (torch-vs-jax init
        # distribution, the phenomenon measured at 0.114 in
        # test_sequential_parity) and converges from below with a
        # monotonically decaying gap (0.040 -> 0.022 over the tail); the
        # slack still fails a 5-point systematic offset, which the old
        # 2-sigma+0.06 contract would have passed.
        assert_envelopes_overlap(ref, ours, "PENS", burn_frac=0.6,
                                 slack=0.03)
        assert np.mean([c[-1] for c in ref]) > 0.8
        assert np.mean([c[-1] for c in ours]) > 0.8

    def test_tokenized_learning_curve_envelope(self):
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=4)
        ref, ref_sent = run_reference_tokenized_curves(X, y, cache_key=4)
        ours, ours_sent = run_ours_tokenized_curves(X, y)
        # Burn-in covers the token-charge transient (~C=20 rounds): during
        # it the reference's reactive sends can deliver within the SAME
        # tick while the engine's earliest reactive delivery is next round
        # (documented divergence, variants.py _post_deliver) — a ~1-round
        # information-propagation shift that peaks exactly while the
        # accounts charge, then washes out (measured: mean-curve gap 0.17
        # at round 12 decaying to <0.01 by round 20).
        assert_envelopes_overlap(ref, ours, "tokenized", burn_frac=0.6)
        assert np.mean([c[-1] for c in ref]) > 0.7
        assert np.mean([c[-1] for c in ours]) > 0.7
        # Message-count curves: the quantity flow control changes.
        assert_sent_curves_close(ref_sent, ours_sent)

    def test_tokenized_envelope_has_teeth(self):
        """Mutation check (verdict r4 #5): deliberately break reaction
        accounting (max_reactions=0 kills every reactive send) and the
        send-count contract must FAIL against the same reference runs."""
        try:
            import_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = make_dataset(seed=4)
        _, ref_sent = run_reference_tokenized_curves(X, y, cache_key=4)
        _, mutant_sent = run_ours_tokenized_curves(X, y, max_reactions=0)
        with pytest.raises(AssertionError, match="send-curve"):
            assert_sent_curves_close(ref_sent, mutant_sent)
