"""Chaos layer: scheduled fault injection, dynamic topologies, recovery.

Covers the ISSUE-7 acceptance criteria:

- ``chaos=None`` traces HLO identical to a build without the argument
  (the probes/sentinels discipline), and enabling chaos leaves the
  chaos-free rounds' accounting untouched;
- a partition/heal scenario opens the per-component consensus gap during
  the window and reconverges after the heal, with jitted-vs-sequential
  parity (exact where the regime is deterministic, structural otherwise);
- every fault type (outage, partition, churn, drop/delay spikes) has
  deterministic jitted-vs-sequential agreement on its signature;
- same seed + same ChaosConfig → bit-identical trajectories across
  chunked ``start()`` calls and after a FlightRecorder ``replay_bundle``
  restore mid-episode, with the bundle verdict naming the fault window;
- chaos fields ride the report registry (save → load → concatenate), the
  schema-v5 JSONL, and the ``update_chaos`` event stream;
- the declarative config round-trips through ExperimentConfig, and the
  service packer buckets by schedule SHAPE while tenants vary VALUES.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu.core import (
    AntiEntropyProtocol,
    ConstantDelay,
    CreateModelMode,
    SparseTopology,
    Topology,
    uniform_mixing,
)
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, WeightedSGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import (
    All2AllGossipSimulator,
    ChaosConfig,
    ChurnProcess,
    FaultSpike,
    GossipSimulator,
    JSONLinesReceiver,
    OutageEpisode,
    PartitionEpisode,
    SequentialGossipSimulator,
    SimulationEventReceiver,
    SimulationReport,
    rounds_to_reconverge,
)
from gossipy_tpu.simulation.faults import build_fault_schedule

N, D = 8, 4
HALF = ((0, 1, 2, 3), (4, 5, 6, 7))


def make_data(seed=0, n_samples=160):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n_samples, D)).astype(np.float32)
    y = (X @ rng.normal(size=D) > 0).astype(np.int64)
    return X, y


def make_handler(lr=0.0):
    return SGDHandler(model=LogisticRegression(D, 2),
                      loss=losses.cross_entropy, optimizer=optax.sgd(lr),
                      local_epochs=1, batch_size=8, n_classes=2,
                      input_shape=(D,),
                      create_model_mode=CreateModelMode.MERGE_UPDATE)


def make_sim(cls=GossipSimulator, lr=0.0, topo=None, **kwargs):
    X, y = make_data()
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=N, eval_on_user=False)
    topo = topo if topo is not None else Topology.clique(N)
    return cls(make_handler(lr), topo, disp.stacked(), delta=20,
               protocol=AntiEntropyProtocol.PUSH, **kwargs)


def craft_two_blocks(sim, state, a=1.0, b=3.0):
    """Overwrite params so nodes 0-3 carry the constant ``a`` and 4-7 the
    constant ``b`` — with lr=0 pure averaging, any value outside {a, b,
    their mixtures} proves an unscheduled information path."""
    vals = jnp.where(jnp.arange(N) < 4, a, b)
    params = jax.tree.map(
        lambda l: jnp.broadcast_to(
            vals.reshape((N,) + (1,) * (l.ndim - 1)), l.shape
        ).astype(l.dtype),
        state.model.params)
    return state._replace(model=state.model._replace(params=params))


def craft_two_blocks_seq(state, a=1.0, b=3.0):
    for i in range(N):
        v = a if i < 4 else b
        state.models[i] = state.models[i]._replace(
            params=jax.tree.map(lambda l: jnp.full(l.shape, v, l.dtype),
                                state.models[i].params))
    return state


def first_leaf_values(params):
    """[N] first scalar of each node's first param leaf."""
    leaf = jax.tree_util.tree_leaves(params)[0]
    return np.asarray(leaf).reshape(N, -1)[:, 0]


PARTITION = ChaosConfig(partitions=(
    PartitionEpisode(components=HALF, start=2, stop=5),))


class TestChaosConfig:
    def test_round_trip_and_coerce(self):
        cfg = ChaosConfig(
            outages=(OutageEpisode(nodes=(1, 2), start=0, stop=3),),
            partitions=(PartitionEpisode(components=HALF, start=2,
                                         stop=5),),
            churn=ChurnProcess(keep_frac=0.5, start=1, stop=4, period=2),
            spikes=(FaultSpike(start=3, stop=4, drop_prob=0.9,
                               delay_scale=2.0),))
        d = cfg.to_dict()
        json.dumps(d)  # JSON-able
        back = ChaosConfig.from_dict(d)
        assert back == cfg
        assert ChaosConfig.coerce(None) is None
        assert ChaosConfig.coerce(cfg) is cfg
        assert ChaosConfig.coerce(d) == cfg
        with pytest.raises(TypeError):
            ChaosConfig.coerce("partition")
        assert cfg.horizon == 5
        assert cfg.max_components() == 3  # two listed + implicit
        assert cfg.max_delay_scale() == 2.0

    def test_validation(self):
        with pytest.raises(ValueError, match="schedules nothing"):
            ChaosConfig()
        with pytest.raises(ValueError, match="window"):
            OutageEpisode(nodes=(0,), start=3, stop=3)
        with pytest.raises(ValueError, match="disjoint"):
            PartitionEpisode(components=((0, 1), (1, 2)), start=0, stop=2)
        with pytest.raises(ValueError, match="keep_frac"):
            ChurnProcess(keep_frac=1.5, start=0, stop=2)
        with pytest.raises(ValueError, match="drop_prob"):
            FaultSpike(start=0, stop=1, drop_prob=2.0)
        with pytest.raises(ValueError, match="horizon"):
            ChaosConfig(spikes=(FaultSpike(start=0, stop=9,
                                           drop_prob=0.5),), horizon=3)
        with pytest.raises(ValueError, match="unknown chaos fields"):
            ChaosConfig.from_dict({"partitons": []})

    def test_active_at_names_windows(self):
        cfg = ChaosConfig(
            outages=(OutageEpisode(nodes=(1,), start=1, stop=3),),
            spikes=(FaultSpike(start=2, stop=4, drop_prob=0.5),))
        assert cfg.active_at(0) == []
        kinds = [w["kind"] for w in cfg.active_at(2)]
        assert kinds == ["outage", "spike"]
        assert cfg.active_at(4) == []

    def test_schedule_tables(self):
        topo = Topology.clique(N)
        sched = build_fault_schedule(PARTITION, topo, 0.1)
        assert sched.rows == PARTITION.horizon + 1
        # Trailing baseline row: nothing forced, mask 0, base drop.
        assert not sched.forced_offline[-1].any()
        assert sched.mask_idx[-1] == 0
        assert sched.drop_prob[-1] == np.float32(0.1)
        # Partition rounds share one deduplicated mask.
        assert sched.mask_idx[2] == sched.mask_idx[3] == sched.mask_idx[4]
        assert sched.mask_idx[0] == 0 and sched.mask_idx[1] == 0
        m = sched.edge_masks[sched.mask_idx[2]]
        assert not m[0, 4] and not m[4, 0] and m[0, 1] and m[4, 5]
        # Component ids persist past the heal (the probe keeps measuring
        # the former components' gap so reconvergence is observable).
        assert (sched.component_id[2] == sched.component_id[-1]).all()


class TestChaosOffIsUntouched:
    def test_chaos_off_hlo_identical(self):
        # Shares the hlo_gate backbone (scripts/hlo_gate.py runs the same
        # pair in CI); on divergence the first differing instruction is
        # named.
        from gossipy_tpu.analysis import assert_identical_hlo
        assert_identical_hlo(make_sim(), make_sim(chaos=None),
                             label="chaos=None")

    def test_report_has_no_chaos_fields_by_default(self):
        sim = make_sim(lr=0.1)
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key)
        _, rep = sim.start(st, n_rounds=2, key=key)
        assert rep.chaos_component_gap is None
        assert "chaos" not in rep.failed_per_cause


class TestPartitionHealReconverge:
    """The acceptance scenario: gap opens during the partition, closes
    after the heal, with jitted-vs-sequential parity. lr=0 + crafted
    two-block params make the during-partition regime DETERMINISTIC:
    averaging identical values keeps every node exactly at its block's
    value, so any leak across the cut is a hard failure."""

    def _run(self, cls):
        sim = make_sim(cls=cls, lr=0.0, chaos=PARTITION, probes=True)
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key, local_train=False, common_init=True)
        if cls is GossipSimulator:
            st = craft_two_blocks(sim, st)
        else:
            st = craft_two_blocks_seq(st)
        return sim.start(st, n_rounds=10, key=key)

    @pytest.mark.parametrize("cls", [GossipSimulator,
                                     SequentialGossipSimulator])
    def test_gap_opens_then_reconverges(self, cls):
        st, rep = self._run(cls)
        gap = np.asarray(rep.chaos_component_gap, np.float64)
        # Pre-partition (rounds 0-1): the scheduled component grouping
        # only exists from round 2 (persisting after the heal), so the
        # gap column is structurally 0 before it.
        assert (gap[:2] == 0).all()
        # While the window holds the halves cannot exchange: the gap
        # stays open (within-component averaging drifts the component
        # means, so it wobbles but cannot close); the heal closes it.
        during = gap[2:5]
        assert during.min() > 0.1 * during.max() > 0
        assert gap[-1] < 0.5 * during.max()
        assert rounds_to_reconverge(gap, 5, tol=0.5 * during.max()) \
            is not None

    @pytest.mark.parametrize("cls", [GossipSimulator,
                                     SequentialGossipSimulator])
    def test_no_cross_partition_leak(self, cls):
        """Crafted blocks + a partition from round 0: while the window
        holds, every node's params stay EXACTLY at its block value in
        both engines (averaging identical values is the identity)."""
        cfg = ChaosConfig(partitions=(
            PartitionEpisode(components=HALF, start=0, stop=4),))
        sim = make_sim(cls=cls, lr=0.0, chaos=cfg, probes=True)
        key = jax.random.PRNGKey(1)
        st = sim.init_nodes(key, local_train=False, common_init=True)
        st = (craft_two_blocks(sim, st) if cls is GossipSimulator
              else craft_two_blocks_seq(st))
        st, rep = sim.start(st, n_rounds=3, key=key)
        params = (st.model.params if cls is GossipSimulator
                  else jax.tree.map(lambda *ls: jnp.stack(ls),
                                    *[m.params for m in st.models]))
        vals = first_leaf_values(params)
        np.testing.assert_array_equal(vals[:4], np.full(4, 1.0))
        np.testing.assert_array_equal(vals[4:], np.full(4, 3.0))
        # And the gap equals the crafted block distance, identically in
        # both engines (same pure chaos_round_stats math).
        assert np.allclose(rep.chaos_component_gap,
                           rep.chaos_component_gap[0])
        # Sends kept flowing within components the whole time.
        assert (rep.sent_per_round == N).all()

    def test_all2all_partition_gap(self):
        X, y = make_data()
        dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
        disp = DataDispatcher(dh, n=N, eval_on_user=False)
        topo = Topology.clique(N)
        handler = WeightedSGDHandler(
            model=LogisticRegression(D, 2), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.0), local_epochs=1, batch_size=8,
            n_classes=2, input_shape=(D,),
            create_model_mode=CreateModelMode.MERGE_UPDATE)
        cfg = ChaosConfig(partitions=(
            PartitionEpisode(components=HALF, start=0, stop=3),))
        sim = All2AllGossipSimulator(handler, topo, disp.stacked(),
                                     delta=20, mixing=uniform_mixing(topo),
                                     chaos=cfg, probes=True)
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key, local_train=False, common_init=True)
        st = craft_two_blocks(sim, st)
        st, rep = sim.start(st, n_rounds=6, key=key)
        gap = np.asarray(rep.chaos_component_gap)
        # Broadcast mixing within each half is the identity on crafted
        # blocks; the heal mixes the whole clique in one round.
        np.testing.assert_allclose(gap[:3], gap[0], rtol=1e-5)
        assert gap[0] > 0
        assert gap[-1] < 0.05 * gap[0]
        vals = first_leaf_values(st.model.params)
        assert np.allclose(vals, vals[0])  # full consensus post-heal


class TestOutage:
    CFG = ChaosConfig(outages=(OutageEpisode(nodes=(5, 6, 7), start=1,
                                             stop=4),))

    @pytest.mark.parametrize("cls", [GossipSimulator,
                                     SequentialGossipSimulator])
    def test_forced_nodes_freeze_and_chaos_cause_counts(self, cls):
        sim = make_sim(cls=cls, lr=0.0, chaos=self.CFG)
        key = jax.random.PRNGKey(2)
        st = sim.init_nodes(key, local_train=False)
        pre = (first_leaf_values(st.model.params)
               if cls is GossipSimulator else
               first_leaf_values(jax.tree.map(
                   lambda *ls: jnp.stack(ls),
                   *[m.params for m in st.models])))
        # Run EXACTLY the outage window: rounds 1..3 (round 0 mixes).
        st, rep1 = sim.start(st, n_rounds=1, key=key)
        mid = (first_leaf_values(st.model.params)
               if cls is GossipSimulator else
               first_leaf_values(jax.tree.map(
                   lambda *ls: jnp.stack(ls),
                   *[m.params for m in st.models])))
        st, rep2 = sim.start(st, n_rounds=3, key=key)
        post = (first_leaf_values(st.model.params)
                if cls is GossipSimulator else
                first_leaf_values(jax.tree.map(
                    lambda *ls: jnp.stack(ls),
                    *[m.params for m in st.models])))
        # Forced-offline nodes neither received nor trained: frozen.
        np.testing.assert_array_equal(mid[5:], post[5:])
        # The chaos cause counted their would-be deliveries, only inside
        # the window.
        assert rep1.failed_per_cause["chaos"].sum() == 0
        assert rep2.failed_per_cause["chaos"].sum() > 0
        total = sum(rep2.failed_per_cause.values())
        np.testing.assert_array_equal(total, rep2.failed_per_round)
        # Outage sends are suppressed too: 5 senders instead of 8.
        assert (rep2.sent_per_round == np.array([5, 5, 5])).all()
        assert rep1.sent_per_round[0] == N


class TestSpikesAndChurn:
    @pytest.mark.parametrize("cls", [GossipSimulator,
                                     SequentialGossipSimulator])
    def test_total_drop_spike_window_is_exact(self, cls):
        cfg = ChaosConfig(spikes=(FaultSpike(start=1, stop=3,
                                             drop_prob=1.0),))
        sim = make_sim(cls=cls, lr=0.0, chaos=cfg)
        key = jax.random.PRNGKey(3)
        st = sim.init_nodes(key, local_train=False)
        st, rep = sim.start(st, n_rounds=5, key=key)
        drops = rep.failed_per_cause["drop"]
        # Deterministic signature on both engines: every message sent in
        # the window drops; none outside (base drop_prob = 0).
        np.testing.assert_array_equal(drops[1:3], rep.sent_per_round[1:3])
        assert drops[0] == 0 and (drops[3:] == 0).all()
        assert (rep.sent_per_round == N).all()

    @pytest.mark.parametrize("cls", [GossipSimulator,
                                     SequentialGossipSimulator])
    def test_delay_spike_shifts_staleness(self, cls):
        # Base delay = one round; a 2x spike in rounds [1, 3) makes
        # those sends arrive two rounds stale — bucket 2 traffic exists
        # exactly for spiked sends, on both engines.
        cfg = ChaosConfig(spikes=(FaultSpike(start=1, stop=3,
                                             delay_scale=2.0),))
        sim = make_sim(cls=cls, lr=0.0, chaos=cfg, probes=True,
                       delay=ConstantDelay(20))
        key = jax.random.PRNGKey(4)
        st = sim.init_nodes(key, local_train=False)
        st, rep = sim.start(st, n_rounds=6, key=key)
        hist = np.asarray(rep.probe_stale_hist)
        # Rounds 1,2 sends (spiked) land at rounds 3,4 with staleness 2;
        # unspiked sends land one round later with staleness 1.
        assert hist[3, 2] == N and hist[4, 2] == N
        assert hist[1, 1] == N          # round-0 send, unspiked
        assert hist[5, 1] == N          # round-4 send, after the spike
        assert (hist[:, 0] == 0).all()  # base delay is a full round

    @pytest.mark.parametrize("cls", [GossipSimulator,
                                     SequentialGossipSimulator])
    def test_total_churn_silences_sends(self, cls):
        cfg = ChaosConfig(churn=ChurnProcess(keep_frac=0.0, start=1,
                                             stop=3))
        sim = make_sim(cls=cls, lr=0.0, chaos=cfg)
        key = jax.random.PRNGKey(5)
        st = sim.init_nodes(key, local_train=False)
        st, rep = sim.start(st, n_rounds=5, key=key)
        # keep_frac=0: every edge down in the window — nobody has an
        # alive peer, so nobody sends; edges return at round 3.
        np.testing.assert_array_equal(rep.sent_per_round,
                                      [N, 0, 0, N, N])

    def test_churn_epochs_are_deterministic_and_rewire(self):
        topo = Topology.clique(N)
        cfg = ChaosConfig(churn=ChurnProcess(keep_frac=0.5, start=0,
                                             stop=6, period=2, seed=9))
        s1 = build_fault_schedule(cfg, topo, 0.0)
        s2 = build_fault_schedule(cfg, topo, 0.0)
        for f in ("mask_idx", "edge_masks", "forced_offline"):
            np.testing.assert_array_equal(getattr(s1, f), getattr(s2, f))
        # Period 2: rounds (0,1), (2,3), (4,5) share masks; epochs
        # differ from each other (w.h.p. at 28 pairs, keep 0.5).
        mi = s1.mask_idx
        assert mi[0] == mi[1] and mi[2] == mi[3] and mi[4] == mi[5]
        assert len({int(mi[0]), int(mi[2]), int(mi[4])}) == 3
        # Masks are symmetric modifiers.
        m = s1.edge_masks[mi[0]]
        np.testing.assert_array_equal(m, m.T)

    def test_sparse_topology_masks_are_o_e(self):
        topo = SparseTopology.ring(N, 2)
        cfg = ChaosConfig(partitions=(
            PartitionEpisode(components=HALF, start=0, stop=3),))
        sched = build_fault_schedule(cfg, topo, 0.0)
        assert isinstance(sched.edge_masks, tuple)  # no dense [N, N]
        assert sched.csr_masks.shape[1] == len(topo.indices)
        # And the engine runs on it end to end.
        sim = make_sim(lr=0.0, topo=topo, chaos=cfg, probes=True)
        key = jax.random.PRNGKey(6)
        st = sim.init_nodes(key, local_train=False, common_init=True)
        st = craft_two_blocks(sim, st)
        st, rep = sim.start(st, n_rounds=5, key=key)
        vals = first_leaf_values(st.model.params)
        assert not np.allclose(vals, vals[0])  # ring heals slowly
        gap = np.asarray(rep.chaos_component_gap)
        assert gap[0] > 0 and gap[-1] < gap[0]

    def test_pens_rejects_edge_faults(self):
        from gossipy_tpu.simulation import PENSGossipSimulator
        with pytest.raises(ValueError, match="_select_peers"):
            make_sim(cls=PENSGossipSimulator, lr=0.1, chaos=PARTITION)


class TestDeterminismAndReplay:
    def _mk(self):
        cfg = ChaosConfig(partitions=(
            PartitionEpisode(components=HALF, start=0, stop=6),))
        return make_sim(lr=0.0, chaos=cfg, sentinels=True, probes=True)

    def test_chunked_start_bit_identical(self):
        key = jax.random.PRNGKey(7)
        a = self._mk()
        st = craft_two_blocks(a, a.init_nodes(key, local_train=False,
                                              common_init=True))
        _, rep = a.start(st, n_rounds=8, key=key, donate_state=False)
        b = self._mk()
        st2 = craft_two_blocks(b, b.init_nodes(key, local_train=False,
                                               common_init=True))
        st2, r1 = b.start(st2, n_rounds=3, key=key, donate_state=False)
        st2, r2 = b.start(st2, n_rounds=5, key=key, donate_state=False)
        cat = SimulationReport.concatenate([r1, r2])
        np.testing.assert_array_equal(rep.chaos_component_gap,
                                      cat.chaos_component_gap)
        np.testing.assert_array_equal(rep.sent_per_round,
                                      cat.sent_per_round)
        for c in rep.failed_per_cause:
            np.testing.assert_array_equal(rep.failed_per_cause[c],
                                          cat.failed_per_cause[c])

    def test_chaos_induced_trip_bundle_and_replay(self, tmp_path):
        """The acceptance repro loop: a heal-induced divergence trip is
        captured mid-episode by the flight recorder (bundle names the
        partition window active at the checkpoint round) and replays
        bit-for-bit on a FRESH simulator built from the same config."""
        from gossipy_tpu.telemetry.health import FlightRecorder, \
            replay_bundle
        key = jax.random.PRNGKey(5)
        sim = self._mk()
        # Norm asymmetry: the heal merges norm~57 params into norm~0.7
        # nodes — a >10x jump over their settled EMA trips divergence.
        st = craft_two_blocks(sim, sim.init_nodes(
            key, local_train=False, common_init=True), a=0.5, b=40.0)
        rec = FlightRecorder(str(tmp_path), chunk=4)
        st, reports, bundle = rec.run(sim, st, n_rounds=12, key=key)
        assert bundle is not None
        with open(os.path.join(bundle, "verdict.json")) as fh:
            verdict = json.load(fh)
        assert verdict["kind"] == "sentinel"
        assert verdict["first_bad_round"] >= 6  # at/after the heal
        # The checkpoint round (4, mid-partition) names the window.
        ck = verdict["detail"]["chaos_windows_at_checkpoint"]
        assert [w["kind"] for w in ck] == ["partition"]
        assert ck[0]["start"] == 0 and ck[0]["stop"] == 6

        fresh = self._mk()
        out = replay_bundle(bundle, fresh)
        assert out["matches_recorded"] is True
        assert out["trip"] == "divergence"
        assert out["start_round"] == 4  # restored mid-episode


class ChaosRecorder(SimulationEventReceiver):
    def __init__(self):
        self.rows = []

    def update_chaos(self, round, chaos):
        self.rows.append((round, chaos))


class TestReportEventsAndConfig:
    def _rep(self, **kw):
        sim = make_sim(lr=0.1, chaos=PARTITION, probes=True, **kw)
        key = jax.random.PRNGKey(0)
        st = sim.init_nodes(key)
        return sim, sim.start(st, n_rounds=6, key=key)[1]

    def test_report_round_trip_and_concat(self, tmp_path):
        _, rep = self._rep()
        path = str(tmp_path / "rep.json")
        rep.save(path)
        loaded = SimulationReport.load(path)
        for f in ("chaos_component_gap", "chaos_within_mean",
                  "chaos_active_components"):
            np.testing.assert_allclose(
                np.asarray(getattr(loaded, f), np.float64),
                np.asarray(getattr(rep, f), np.float64), atol=1e-6,
                err_msg=f)
        np.testing.assert_array_equal(loaded.failed_per_cause["chaos"],
                                      rep.failed_per_cause["chaos"])
        cat = SimulationReport.concatenate([loaded, loaded])
        assert cat.chaos_component_gap.shape[0] == 12
        assert cat.failed_per_cause["chaos"].shape[0] == 12

    def test_update_chaos_events_and_jsonl_v5(self, tmp_path):
        sim = make_sim(lr=0.1, chaos=PARTITION, probes=True)
        rec = ChaosRecorder()
        path = str(tmp_path / "run.jsonl")
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rec)
            sim.add_receiver(rx)
            key = jax.random.PRNGKey(0)
            st = sim.init_nodes(key)
            sim.start(st, n_rounds=4, key=key)
        assert [r for r, _ in rec.rows] == [1, 2, 3, 4]
        assert all({"component_gap", "within_mean", "active_components",
                    "failed_chaos"} <= set(row) for _, row in rec.rows)
        rows = [JSONLinesReceiver.parse_line(l) for l in open(path)]
        assert all(r["schema"] == 8 for r in rows)
        assert all(r["chaos"] is not None for r in rows)
        assert all("chaos" in r["failed_by_cause"] for r in rows)
        # Pre-v5 lines normalize with a null chaos field.
        old = json.dumps({"schema": 4, "round": 1, "sent": 0, "failed": 0,
                          "failed_by_cause": None, "probes": None,
                          "health": None, "size": 0, "local": None,
                          "global": None})
        assert JSONLinesReceiver.parse_line(old)["chaos"] is None

    def test_experiment_config_carries_chaos(self):
        from gossipy_tpu.config import ExperimentConfig, run_experiment
        X, y = make_data()
        cfg = ExperimentConfig(
            n_nodes=N, model="logreg", handler="sgd", topology="clique",
            topology_params={}, delta=20, n_rounds=4, seed=3,
            batch_size=8, simulator_params={"probes": True},
            chaos={"partitions": [
                {"components": [list(HALF[0]), list(HALF[1])],
                 "start": 1, "stop": 3}]})
        # Round-trips through JSON like every other field.
        back = ExperimentConfig.from_json(cfg.to_json())
        assert back.chaos == cfg.chaos
        # chaos is tenant-variable: not part of the shape fields.
        assert "chaos" not in cfg.shape_fields()
        _, rep = run_experiment(cfg, data=(X, y))
        assert rep.chaos_component_gap is not None
        assert (np.asarray(rep.chaos_active_components)[1:3] == 2).all()
        bad = ExperimentConfig(
            n_nodes=N, chaos={"nope": 1}, topology="clique",
            topology_params={})
        with pytest.raises(ValueError, match="unknown chaos fields"):
            run_experiment(bad, data=(X, y))


@pytest.mark.slow
class TestServiceChaos:
    def test_same_shape_chaos_tenants_share_a_bucket(self, tmp_path):
        """Two tenants whose chaos configs differ in VALUES (partition
        membership) but not shapes pack into ONE megabatch; each lane's
        trajectory equals its solo run bit-for-bit."""
        import dataclasses

        from gossipy_tpu.config import ExperimentConfig, run_experiment
        from gossipy_tpu.service import GossipService, RunQueue, \
            RunRequest
        X, y = make_data(seed=3)

        def cfg(seed, comps):
            return ExperimentConfig(
                n_nodes=N, model="logreg", handler="sgd",
                topology="clique", topology_params={}, delta=20,
                n_rounds=6, seed=seed, learning_rate=0.2, batch_size=8,
                simulator_params={"probes": True},
                chaos={"partitions": [{"components": comps,
                                       "start": 2, "stop": 4}]})

        ca = cfg(1, [[0, 1, 2, 3], [4, 5, 6, 7]])
        cb = cfg(2, [[0, 2, 4, 6], [1, 3, 5, 7]])
        svc = GossipService(out_dir=str(tmp_path), slice_rounds=3)
        q = RunQueue()
        handles = [q.submit(RunRequest("alice", ca, data=(X, y))),
                   q.submit(RunRequest("bob", cb, data=(X, y)))]
        summary = svc.serve(q)
        assert summary["n_buckets"] == 1
        for h, c in zip(handles, (ca, cb)):
            assert h.status.value == "done"
            solo = dataclasses.replace(
                c, simulator_params={**c.simulator_params,
                                     "sentinels": True})
            _, rep = run_experiment(solo, data=(X, y))
            np.testing.assert_array_equal(
                np.asarray(h.report.chaos_component_gap),
                np.asarray(rep.chaos_component_gap))

    def test_different_horizon_splits_buckets(self, tmp_path):
        from gossipy_tpu.service.packer import build_request, pack
        from gossipy_tpu.service.spec import RunRequest
        from gossipy_tpu.config import ExperimentConfig
        X, y = make_data(seed=3)

        def cfg(seed, stop):
            return ExperimentConfig(
                n_nodes=N, model="logreg", handler="sgd",
                topology="clique", topology_params={}, delta=20,
                n_rounds=6, seed=seed, batch_size=8,
                chaos={"partitions": [{
                    "components": [[0, 1, 2, 3], [4, 5, 6, 7]],
                    "start": 1, "stop": stop}]})

        built = [build_request(RunRequest("a", cfg(1, 3), data=(X, y))),
                 build_request(RunRequest("b", cfg(2, 5), data=(X, y)))]
        assert len(pack(built)) == 2  # horizon differs -> shape splits
