"""Reference parity beyond the SGD configs: exact token-account formula
equivalence and quality-band parity for the k-means and matrix-factorization
handlers (the remaining handler families of SURVEY.md §2.5), each run
through BOTH the reference implementation (imported from /root/reference)
and gossipy_tpu on the same configuration.
"""

import contextlib
import io

import jax
import numpy as np
import pytest

from test_golden_parity import import_reference


def _fresh_reference():
    """Import the reference AND clear its global payload CACHE: it is a
    process-wide singleton keyed by (node id, n_updates), so stale handlers
    from a previous test's simulation would be popped by the next one."""
    import_reference()
    from gossipy import CACHE
    CACHE.clear()

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClusteringDataHandler, DataDispatcher, \
    RecSysDataDispatcher, RecSysDataHandler
from gossipy_tpu.flow_control import GeneralizedTokenAccount, \
    PurelyProactiveTokenAccount, PurelyReactiveTokenAccount, \
    RandomizedTokenAccount, SimpleTokenAccount
from gossipy_tpu.handlers import KMeansHandler, MFHandler
from gossipy_tpu.simulation import GossipSimulator

# Everything here compares against the torch reference; opt-in second lane
# (`pytest -m parity`) so the default lane stays fast.
pytestmark = pytest.mark.parity



def _run_ref_sim(sim, rounds, metric="accuracy", local=False, start_args=()):
    """Wire a reference simulator to a report, run it silenced, and return
    the final mean of ``metric`` (the tail every ref_* config shares)."""
    from gossipy.simul import SimulationReport

    report = SimulationReport()
    sim.add_receiver(report)
    sim.init_nodes(seed=42)
    with contextlib.redirect_stdout(io.StringIO()):
        sim.start(*start_args, n_rounds=rounds)
    return float(report.get_evaluation(local)[-1][1][metric])


def _run_our_sim(sim, rounds, metric="accuracy", local=False):
    """init_nodes + start + final metric, keyed identically across configs."""
    key = jax.random.PRNGKey(42)
    st = sim.init_nodes(key)
    st, report = sim.start(st, n_rounds=rounds, key=key)
    return float(report.curves(local=local)[metric][-1])


class TestTokenAccountFormulas:
    """Our vectorized policies vs the reference's per-object accounts,
    exactly, over a grid of balances (reference flow_control.py:85-236)."""

    BALANCES = list(range(0, 31))

    def _pairs(self):
        from gossipy.flow_control import (
            GeneralizedTokenAccount as RefGTA,
            PurelyProactiveTokenAccount as RefPPTA,
            PurelyReactiveTokenAccount as RefPRTA,
            RandomizedTokenAccount as RefRTA,
            SimpleTokenAccount as RefSTA,
        )
        return [
            (RefPPTA(), PurelyProactiveTokenAccount()),
            (RefPRTA(k=3), PurelyReactiveTokenAccount(k=3)),
            (RefSTA(C=5), SimpleTokenAccount(C=5)),
            (RefGTA(C=20, A=10), GeneralizedTokenAccount(C=20, A=10)),
            (RefRTA(C=20, A=10), RandomizedTokenAccount(C=20, A=10)),
        ]

    def test_proactive_exact(self):
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        for ref, ours in self._pairs():
            got = np.asarray(
                ours.proactive(np.array(self.BALANCES, dtype=np.int32)))
            for i, b in enumerate(self.BALANCES):
                ref.n_tokens = b
                assert got[i] == pytest.approx(float(ref.proactive())), \
                    (type(ref).__name__, b, got[i])

    def test_reactive_exact_deterministic(self):
        """All deterministic reactive rules; for the randomized account the
        balances that are exact multiples of A (zero rounding fraction)."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        key = jax.random.PRNGKey(0)
        for ref, ours in self._pairs():
            deterministic = not isinstance(ours, RandomizedTokenAccount)
            for utility in (0, 1):
                balances = self.BALANCES if deterministic else \
                    [b for b in self.BALANCES if b % ours.A == 0]
                got = np.asarray(ours.reactive(
                    np.array(balances, dtype=np.int32),
                    np.full(len(balances), utility, dtype=np.float32), key))
                for i, b in enumerate(balances):
                    ref.n_tokens = b
                    assert int(got[i]) == int(ref.reactive(utility)), \
                        (type(ref).__name__, b, utility, int(got[i]))

    def test_randomized_reactive_rounding_statistics(self):
        """randRound(a/A): mean over keys approximates the fraction."""
        acct = RandomizedTokenAccount(C=20, A=10)
        b = np.full((2000,), 13, dtype=np.int32)  # a/A = 1.3
        u = np.ones((2000,), dtype=np.float32)
        vals = np.asarray(acct.reactive(b, u, jax.random.PRNGKey(7)))
        assert set(np.unique(vals)) <= {1, 2}
        assert abs(vals.mean() - 1.3) < 0.05


class TestDelayFormulas:
    """Delay models vs the reference (core.py:155-307): constant and linear
    delays are deterministic — compare exactly; uniform compares the
    inclusive range."""

    def test_constant_and_linear_exact(self):
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from gossipy.core import ConstantDelay as RefConst, \
            LinearDelay as RefLinear

        from gossipy_tpu.core import ConstantDelay, LinearDelay

        class Msg:  # the only part of Message a Delay reads
            def __init__(self, size):
                self._size = size

            def get_size(self):
                return self._size

        key = jax.random.PRNGKey(0)
        for d in (0, 1, 7):
            ours = ConstantDelay(d).sample(key, (5,), size=123)
            assert (np.asarray(ours) == RefConst(d).get(Msg(123))).all()
        for timexunit, overhead in ((0, 3), (2, 1), (1, 0)):
            ref = RefLinear(timexunit=timexunit, overhead=overhead)
            ours_d = LinearDelay(timexunit, overhead)
            for size in (1, 57, 1000):
                ours = ours_d.sample(key, (4,), size=size)
                assert (np.asarray(ours) == ref.get(Msg(size))).all(), \
                    (timexunit, overhead, size)
                assert ours_d.max_delay(size) == ref.get(Msg(size))

    def test_uniform_range_matches(self):
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from gossipy.core import UniformDelay as RefUniform

        from gossipy_tpu.core import UniformDelay

        class Msg:
            def get_size(self):
                return 1

        lo, hi = 2, 6
        ref = RefUniform(lo, hi)
        ref_draws = {ref.get(Msg()) for _ in range(300)}
        ours = np.asarray(UniformDelay(lo, hi).sample(
            jax.random.PRNGKey(1), (300,), size=1))
        # Both are inclusive uniform over [lo, hi]: same support.
        assert ref_draws == set(range(lo, hi + 1))
        assert set(ours.tolist()) == set(range(lo, hi + 1))


class TestMixingMatrices:
    """Mixing weights vs the reference (core.py:392-453)."""

    def test_uniform_mixing_weights_exact(self):
        """UniformMixing: weight 1/(deg+1) for self and every peer — our
        dense matrix rows must equal the reference's per-node vectors."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        import networkx as nx
        from gossipy.core import StaticP2PNetwork, UniformMixing

        from gossipy_tpu.core import uniform_mixing

        n = 10
        adj = nx.to_numpy_array(nx.random_regular_graph(4, n, seed=3))
        ref = UniformMixing(StaticP2PNetwork(n, adj))
        w = np.asarray(uniform_mixing(Topology(adj.astype(bool))))
        # Skip node 0: the reference's P2PNetwork.size(0) hits the `if node:`
        # bug (core.py:346-349) and returns num_nodes instead of the degree
        # (a FIXED divergence, see PARITY.md).
        for i in range(1, n):
            vec = ref.get(i)  # [self] + peers, all equal
            assert vec[0] == pytest.approx(w[i, i])
            peers = np.flatnonzero(adj[i])
            np.testing.assert_allclose(w[i, peers], vec[1:], rtol=1e-6)

    def test_metropolis_hastings_divergence_documented(self):
        """The documented MH divergence is real: the reference's rows do NOT
        sum to 1 (non-convergent mixing), ours are doubly stochastic."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        import networkx as nx
        from gossipy.core import MetropolisHastingsMixing, StaticP2PNetwork

        from gossipy_tpu.core import metropolis_hastings_mixing

        n = 10
        adj = nx.to_numpy_array(nx.barabasi_albert_graph(n, 2, seed=3))
        ref = MetropolisHastingsMixing(StaticP2PNetwork(n, adj))
        ref_row_sums = [float(ref.get(i).sum()) for i in range(1, n)]
        assert any(abs(s - 1.0) > 1e-6 for s in ref_row_sums), \
            "reference MH rows unexpectedly sum to 1 — divergence note stale"
        w = np.asarray(metropolis_hastings_mixing(Topology(adj.astype(bool))))
        np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-6)  # rows
        np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-6)  # columns
        np.testing.assert_allclose(w, w.T, atol=1e-6)              # symmetric


class TestAssignmentInvariants:
    """Structural invariants the non-IID assigners must share with the
    reference (data/__init__.py:164-373): both implementations are driven on
    the same labels and must produce partitions with identical structural
    properties (RNG streams differ, so index sets are compared by shape, not
    by value)."""

    def _labels(self, n_ex=600, n_classes=5, seed=0):
        rng = np.random.default_rng(seed)
        return rng.integers(0, n_classes, size=n_ex).astype(np.int64)

    def test_uniform_shard_sizes_match(self):
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from gossipy.data import AssignmentHandler as RefAH

        from gossipy_tpu.data import AssignmentHandler
        y = self._labels()
        n = 13
        ref_parts = RefAH(seed=1).uniform(y, n)
        our_parts = AssignmentHandler(seed=1).uniform(y, n)
        assert [len(p) for p in ref_parts] == [len(p) for p in our_parts]
        # Disjointness on our side (the reference drops the remainder rows;
        # size equality above confirms we match that behavior).
        flat = np.concatenate(our_parts)
        assert len(flat) == len(set(flat.tolist()))

    def test_label_quantity_skew_classes_per_client(self):
        """Every client must see exactly ``class_per_client`` classes on
        BOTH sides (data/__init__.py:257-298)."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from gossipy.data import AssignmentHandler as RefAH

        import torch

        from gossipy_tpu.data import AssignmentHandler
        y = self._labels(n_ex=1000)
        n, k = 10, 2
        ref_parts = RefAH(seed=1).label_quantity_skew(
            torch.tensor(y), n, class_per_client=k)
        our_parts = AssignmentHandler(seed=1).label_quantity_skew(
            y, n, class_per_client=k)
        for parts in (ref_parts, our_parts):
            for p in parts:
                assert len(np.unique(y[np.asarray(p)])) <= k
        # Coverage: all examples of the used classes are assigned once.
        flat = np.concatenate([np.asarray(p) for p in our_parts])
        assert len(flat) == len(set(flat.tolist()))

    def test_label_dirichlet_skew_partition_properties(self):
        """Dirichlet label skew: a full disjoint cover on both sides
        (data/__init__.py:300-335)."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from gossipy.data import AssignmentHandler as RefAH

        import torch

        from gossipy_tpu.data import AssignmentHandler
        y = self._labels(n_ex=1000)
        n = 10
        ref_parts = RefAH(seed=1).label_dirichlet_skew(torch.tensor(y), n,
                                                       beta=0.5)
        our_parts = AssignmentHandler(seed=1).label_dirichlet_skew(y, n, beta=0.5)
        for parts in (ref_parts, our_parts):
            flat = np.concatenate([np.asarray(p) for p in parts])
            assert len(flat) == len(y)
            assert len(set(flat.tolist())) == len(y)


def blobs(n=240, d=2, seed=0):
    rng = np.random.default_rng(seed)
    y = (rng.random(n) < 0.5).astype(np.int64)
    X = rng.normal(size=(n, d)).astype(np.float32) * 0.4 + \
        np.where(y[:, None] > 0, 2.0, -2.0).astype(np.float32)
    return X, y


N_NODES = 12
ROUNDS = 6


def ref_kmeans_nmi(X, y) -> float:
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClusteringDataHandler as RefCluster
    from gossipy.model.handler import KMeansHandler as RefKMeans
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    dh = RefCluster(torch.tensor(X), torch.tensor(y))
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = RefKMeans(k=2, dim=X.shape[1], alpha=0.1, matching="hungarian",
                      create_model_mode=RefMode.MERGE_UPDATE)
    nodes = GossipNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    return _run_ref_sim(sim, ROUNDS, metric="nmi")


def our_kmeans_nmi(X, y) -> float:
    dh = ClusteringDataHandler(X, y)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = KMeansHandler(k=2, dim=X.shape[1], alpha=0.1,
                            matching="hungarian",
                            create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = GossipSimulator(handler, Topology.clique(N_NODES), disp.stacked(),
                          delta=20, protocol=AntiEntropyProtocol.PUSH)
    return _run_our_sim(sim, ROUNDS, metric="nmi")


def synth_ratings(n_users=N_NODES, n_items=30, per_user=16, seed=0):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n_users, 3))
    V = rng.normal(size=(n_items, 3))
    ratings = {}
    for u in range(n_users):
        items = rng.choice(n_items, size=per_user, replace=False)
        raw = U[u] @ V[items].T
        r = np.clip(np.round(3 + raw), 1, 5).astype(np.float64)
        ratings[u] = [(int(i), float(v)) for i, v in zip(items, r)]
    return ratings, n_users, n_items


def ref_mf_rmse(ratings, n_users, n_items) -> float:
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import RecSysDataDispatcher as RefRecDisp
    from gossipy.data.handler import RecSysDataHandler as RefRecDH
    from gossipy.model.handler import MFModelHandler
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    dh = RefRecDH(ratings, n_users, n_items, 0.2, seed=42)
    disp = RefRecDisp(dh)
    disp.assign()
    proto = MFModelHandler(dim=4, n_items=n_items, lam_reg=0.1,
                           learning_rate=0.01,
                           create_model_mode=RefMode.UPDATE)
    nodes = GossipNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(n_users),
        model_proto=proto, round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    return _run_ref_sim(sim, ROUNDS, metric="rmse", local=True)


def our_mf_rmse(ratings, n_users, n_items) -> float:
    dh = RecSysDataHandler(ratings, n_users, n_items, test_size=0.2, seed=42)
    disp = RecSysDataDispatcher(dh)
    handler = MFHandler(dim=4, n_items=n_items, lam_reg=0.1,
                        learning_rate=0.01,
                        create_model_mode=CreateModelMode.UPDATE)
    sim = GossipSimulator(handler, Topology.clique(n_users), disp.stacked(),
                          delta=20, protocol=AntiEntropyProtocol.PUSH)
    return _run_our_sim(sim, ROUNDS, metric="rmse", local=True)


def ref_async_acc(X, y) -> float:
    """Reference async-mode gossip (node.py:79,111-125: ~N(delta, delta/10)
    per-node periods) on the LogReg config."""
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = TorchModelHandler(
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.5}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=8,
        create_model_mode=RefMode.MERGE_UPDATE)
    nodes = GossipNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=False)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    return _run_ref_sim(sim, ROUNDS)


def our_async_acc(X, y) -> float:
    import optax

    from gossipy_tpu.data import ClassificationDataHandler
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression

    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(X.shape[1], 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                         local_epochs=1, batch_size=8, n_classes=2,
                         input_shape=(X.shape[1],),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = GossipSimulator(handler, Topology.clique(N_NODES), disp.stacked(),
                          delta=20, protocol=AntiEntropyProtocol.PUSH,
                          sync=False)
    return _run_our_sim(sim, ROUNDS)


def ref_all2all_acc(X, y) -> float:
    """Reference All2All mixing gossip (simul.py:720-852, node.py:789-870)."""
    import networkx as nx
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, \
        CreateModelMode as RefMode, StaticP2PNetwork, UniformMixing
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import WeightedTMH
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import All2AllGossipNode
    from gossipy.simul import All2AllGossipSimulator as RefA2A, SimulationReport

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    topo = StaticP2PNetwork(
        N_NODES, nx.to_numpy_array(nx.random_regular_graph(4, N_NODES, seed=1)))
    proto = WeightedTMH(
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.1, "weight_decay": 0.01},
        criterion=torch.nn.CrossEntropyLoss(),
        create_model_mode=RefMode.MERGE_UPDATE)
    nodes = All2AllGossipNode.generate(
        data_dispatcher=disp, p2p_net=topo, model_proto=proto,
        round_len=20, sync=True)
    sim = RefA2A(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, sampling_eval=0.0)
    return _run_ref_sim(sim, A2A_ROUNDS, start_args=(UniformMixing(topo),))


A2A_ROUNDS = 14


def our_all2all_acc(X, y) -> float:
    import optax

    from gossipy_tpu.core import uniform_mixing
    from gossipy_tpu.data import ClassificationDataHandler
    from gossipy_tpu.handlers import WeightedSGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import All2AllGossipSimulator

    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    topo = Topology.random_regular(N_NODES, 4, seed=1)
    handler = WeightedSGDHandler(
        model=LogisticRegression(X.shape[1], 2), loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(0.01), optax.sgd(0.1)),
        local_epochs=1, batch_size=32, n_classes=2, input_shape=(X.shape[1],),
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = All2AllGossipSimulator(handler, topo, disp.stacked(), delta=20,
                                 mixing=uniform_mixing(topo))
    return _run_our_sim(sim, A2A_ROUNDS)


def ref_pens_acc(X, y) -> float:
    """Reference PENS two-phase peer selection (node.py:663-785) at small
    scale with a LogReg handler."""
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import PENSNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = TorchModelHandler(
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.5}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=8,
        create_model_mode=RefMode.MERGE_UPDATE)
    nodes = PENSNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True,
        n_sampled=4, m_top=2, step1_rounds=3)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    return _run_ref_sim(sim, PENS_ROUNDS)


PENS_ROUNDS = 8


def our_pens_acc(X, y) -> float:
    import optax

    from gossipy_tpu.data import ClassificationDataHandler
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import PENSGossipSimulator

    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(X.shape[1], 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                         local_epochs=1, batch_size=8, n_classes=2,
                         input_shape=(X.shape[1],),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = PENSGossipSimulator(handler, Topology.clique(N_NODES),
                              disp.stacked(), delta=20,
                              protocol=AntiEntropyProtocol.PUSH,
                              n_sampled=4, m_top=2, step1_rounds=3)
    return _run_our_sim(sim, PENS_ROUNDS)


def ref_passthrough_acc(X, y) -> float:
    """Reference PassThroughNode (Giaretta 2019, node.py:289-392) on a
    degree-skewed Barabasi-Albert topology."""
    import networkx as nx
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import PassThroughNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    topo = StaticP2PNetwork(
        N_NODES, nx.to_numpy_array(nx.barabasi_albert_graph(N_NODES, 3, seed=1)))
    proto = TorchModelHandler(
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.5}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=8,
        create_model_mode=RefMode.MERGE_UPDATE)
    nodes = PassThroughNode.generate(
        data_dispatcher=disp, p2p_net=topo, model_proto=proto,
        round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    return _run_ref_sim(sim, PT_ROUNDS)


# PASS adoptions (no training on the pass branch) slow convergence on the
# degree-skewed topology; both sides need a longer horizon than the plain
# configs.
PT_ROUNDS = 12


def our_passthrough_acc(X, y) -> float:
    import optax

    from gossipy_tpu.data import ClassificationDataHandler
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import PassThroughGossipSimulator

    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(X.shape[1], 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                         local_epochs=1, batch_size=8, n_classes=2,
                         input_shape=(X.shape[1],),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = PassThroughGossipSimulator(
        handler, Topology.barabasi_albert(N_NODES, 3, seed=1),
        disp.stacked(), delta=20, protocol=AntiEntropyProtocol.PUSH)
    return _run_our_sim(sim, PT_ROUNDS)


def ref_sampling_acc(X, y) -> float:
    """Reference SamplingBasedNode + SamplingTMH (node.py:499-562)."""
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import SamplingTMH
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import SamplingBasedNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = SamplingTMH(
        sample_size=0.5,
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.5}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=8,
        create_model_mode=RefMode.MERGE_UPDATE)
    nodes = SamplingBasedNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    return _run_ref_sim(sim, ROUNDS)


def our_sampling_acc(X, y) -> float:
    import optax

    from gossipy_tpu.data import ClassificationDataHandler
    from gossipy_tpu.handlers import SamplingSGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import SamplingGossipSimulator

    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SamplingSGDHandler(
        0.5, model=LogisticRegression(X.shape[1], 2),
        loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
        local_epochs=1, batch_size=8, n_classes=2, input_shape=(X.shape[1],),
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = SamplingGossipSimulator(handler, Topology.clique(N_NODES),
                                  disp.stacked(), delta=20,
                                  protocol=AntiEntropyProtocol.PUSH)
    return _run_our_sim(sim, ROUNDS)


def ref_adaline_acc(X, y) -> float:
    """Reference AdaLineHandler delta rule (handler.py:337-391), ±1 labels."""
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import AdaLineHandler as RefAdaLineHandler
    from gossipy.model.nn import AdaLine as RefAdaLine
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    y_pm = 2 * y - 1
    dh = RefCDH(torch.tensor(X), torch.tensor(y_pm, dtype=torch.float32),
                test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = RefAdaLineHandler(net=RefAdaLine(X.shape[1]), learning_rate=0.01,
                              create_model_mode=RefMode.UPDATE)
    nodes = GossipNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    return _run_ref_sim(sim, ROUNDS)


def our_adaline_acc(X, y) -> float:
    from gossipy_tpu.data import ClassificationDataHandler
    from gossipy_tpu.handlers import AdaLineHandler
    from gossipy_tpu.models import AdaLine

    y_pm = (2 * y - 1).astype(np.float32)
    dh = ClassificationDataHandler(X, y_pm, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = AdaLineHandler(AdaLine(X.shape[1]), 0.01,
                             create_model_mode=CreateModelMode.UPDATE)
    sim = GossipSimulator(handler, Topology.clique(N_NODES), disp.stacked(),
                          delta=20, protocol=AntiEntropyProtocol.PUSH)
    return _run_our_sim(sim, ROUNDS)


def ref_limitedmerge_acc(X, y) -> float:
    """Reference LimitedMergeTMH (Danner 2023, handler.py:690-739)."""
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import LimitedMergeTMH
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim, SimulationReport

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = LimitedMergeTMH(
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.5}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=8, age_diff_threshold=4,
        create_model_mode=RefMode.MERGE_UPDATE)
    nodes = GossipNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=RefProto.PUSH, delay=ConstantDelay(0),
                 online_prob=1.0, drop_prob=0.0, sampling_eval=0.0)
    return _run_ref_sim(sim, ROUNDS)


def our_limitedmerge_acc(X, y) -> float:
    import optax

    from gossipy_tpu.data import ClassificationDataHandler
    from gossipy_tpu.handlers import LimitedMergeSGDHandler, losses
    from gossipy_tpu.models import LogisticRegression

    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = LimitedMergeSGDHandler(
        model=LogisticRegression(X.shape[1], 2), loss=losses.cross_entropy,
        optimizer=optax.sgd(0.5), local_epochs=1, batch_size=8, n_classes=2,
        input_shape=(X.shape[1],), age_diff_threshold=4,
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    sim = GossipSimulator(handler, Topology.clique(N_NODES), disp.stacked(),
                          delta=20, protocol=AntiEntropyProtocol.PUSH)
    return _run_our_sim(sim, ROUNDS)


def ref_sgd_acc(X, y, protocol="PUSH", drop=0.0, online=1.0,
                rounds=ROUNDS, mode="MERGE_UPDATE") -> float:
    """Reference vanilla SGD gossip with configurable protocol and faults."""
    import torch
    from gossipy import set_seed as ref_seed
    from gossipy.core import AntiEntropyProtocol as RefProto, ConstantDelay, \
        CreateModelMode as RefMode, StaticP2PNetwork
    from gossipy.data import DataDispatcher as RefDispatcher
    from gossipy.data.handler import ClassificationDataHandler as RefCDH
    from gossipy.model.handler import TorchModelHandler
    from gossipy.model.nn import LogisticRegression as RefLogReg
    from gossipy.node import GossipNode
    from gossipy.simul import GossipSimulator as RefSim

    ref_seed(42)
    dh = RefCDH(torch.tensor(X), torch.tensor(y), test_size=0.25)
    disp = RefDispatcher(dh, n=N_NODES, eval_on_user=False)
    proto = TorchModelHandler(
        net=RefLogReg(X.shape[1], 2), optimizer=torch.optim.SGD,
        optimizer_params={"lr": 0.5}, criterion=torch.nn.CrossEntropyLoss(),
        local_epochs=1, batch_size=8,
        create_model_mode=getattr(RefMode, mode))
    nodes = GossipNode.generate(
        data_dispatcher=disp, p2p_net=StaticP2PNetwork(N_NODES),
        model_proto=proto, round_len=20, sync=True)
    sim = RefSim(nodes=nodes, data_dispatcher=disp, delta=20,
                 protocol=getattr(RefProto, protocol), delay=ConstantDelay(0),
                 online_prob=online, drop_prob=drop, sampling_eval=0.0)
    return _run_ref_sim(sim, rounds)


def our_sgd_acc(X, y, protocol="PUSH", drop=0.0, online=1.0,
                rounds=ROUNDS, mode="MERGE_UPDATE") -> float:
    import optax

    from gossipy_tpu.data import ClassificationDataHandler
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression

    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(X.shape[1], 2),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                         local_epochs=1, batch_size=8, n_classes=2,
                         input_shape=(X.shape[1],),
                         create_model_mode=getattr(CreateModelMode, mode))
    sim = GossipSimulator(handler, Topology.clique(N_NODES), disp.stacked(),
                          delta=20,
                          protocol=getattr(AntiEntropyProtocol, protocol),
                          drop_prob=drop, online_prob=online)
    return _run_our_sim(sim, rounds)


class TestHandlerFamilies:
    def test_push_pull_same_quality(self):
        """PUSH_PULL replies (the second delivery phase) vs the reference."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=10)
        acc_ref = ref_sgd_acc(X, y, protocol="PUSH_PULL")
        acc_ours = our_sgd_acc(X, y, protocol="PUSH_PULL")
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_faulty_network_same_quality(self):
        """Message drop + node churn (Bernoulli gates both sides)."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=11)
        acc_ref = ref_sgd_acc(X, y, drop=0.1, online=0.9, rounds=10)
        acc_ours = our_sgd_acc(X, y, drop=0.1, online=0.9, rounds=10)
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_update_merge_mode_same_quality(self):
        """UPDATE_MERGE dispatch (train both models, then average)."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=12)
        acc_ref = ref_sgd_acc(X, y, mode="UPDATE_MERGE")
        acc_ours = our_sgd_acc(X, y, mode="UPDATE_MERGE")
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_adaline_same_quality(self):
        """Delta-rule AdaLine learner on ±1 labels."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=8)
        acc_ref = ref_adaline_acc(X, y)
        acc_ours = our_adaline_acc(X, y)
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_limitedmerge_same_quality(self):
        """Danner 2023 age-gap-thresholded merging."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=9)
        acc_ref = ref_limitedmerge_acc(X, y)
        acc_ours = our_limitedmerge_acc(X, y)
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_passthrough_same_quality(self):
        """Giaretta 2019 pass-through on a BA degree-skewed topology."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=6)
        acc_ref = ref_passthrough_acc(X, y)
        acc_ours = our_passthrough_acc(X, y)
        assert acc_ref > 0.7, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.7, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_sampling_same_quality(self):
        """Hegedus 2021 sampled-subset merge exchange."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=7)
        acc_ref = ref_sampling_acc(X, y)
        acc_ours = our_sampling_acc(X, y)
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_reference_cacheneigh_send_crashes(self):
        """Why CacheNeighNode has no golden comparison: the reference's send
        calls ``random.choice(set(...))`` (node.py:449), which raises
        TypeError whenever the neighbor cache is non-empty — the
        neighbor-cache merge path is unrunnable upstream. Our
        ``CacheNeighGossipSimulator`` fixes it by construction
        (test_variants.py covers its behavior)."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        import torch
        from gossipy.core import AntiEntropyProtocol as RefProto, \
            CreateModelMode as RefMode, StaticP2PNetwork
        from gossipy.model.handler import TorchModelHandler
        from gossipy.model.nn import LogisticRegression as RefLogReg
        from gossipy.node import CacheNeighNode

        handler = TorchModelHandler(
            net=RefLogReg(4, 2), optimizer=torch.optim.SGD,
            optimizer_params={"lr": 0.1},
            criterion=torch.nn.CrossEntropyLoss(),
            create_model_mode=RefMode.MERGE_UPDATE)
        handler.init()
        X = torch.zeros((4, 4))
        y = torch.zeros((4,), dtype=torch.long)
        node = CacheNeighNode(idx=0, data=((X, y), None), round_len=10,
                              model_handler=handler,
                              p2p_net=StaticP2PNetwork(2), sync=True)
        peer_key = handler.caching(1)  # a parked neighbor model
        node.local_cache[1] = peer_key
        with pytest.raises(TypeError):
            node.send(0, 1, RefProto.PUSH)

    def test_all2all_same_quality(self):
        """Koloskova-style mixing gossip: reference vs ours on one config."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=4)
        acc_ref = ref_all2all_acc(X, y)
        acc_ours = our_all2all_acc(X, y)
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_pens_same_quality(self):
        """PENS two-phase peer selection: reference vs ours on one config."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=5)
        acc_ref = ref_pens_acc(X, y)
        acc_ours = our_pens_acc(X, y)
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_async_same_quality(self):
        """Async node periods (~N(delta, delta/10)); sub-fires are capped at
        max_fires_per_round on our side (documented divergence)."""
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        from test_golden_parity import make_dataset
        X, y = make_dataset(seed=3)
        acc_ref = ref_async_acc(X, y)
        acc_ours = our_async_acc(X, y)
        assert acc_ref > 0.8, f"reference failed to learn: {acc_ref}"
        assert acc_ours > 0.8, f"ours failed to learn: {acc_ours}"
        assert abs(acc_ours - acc_ref) < 0.1, (acc_ours, acc_ref)

    def test_kmeans_same_quality(self):
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        X, y = blobs()
        nmi_ref = ref_kmeans_nmi(X, y)
        nmi_ours = our_kmeans_nmi(X, y)
        # Well-separated blobs: both must recover the clusters.
        assert nmi_ref > 0.7, f"reference failed to cluster: {nmi_ref}"
        assert nmi_ours > 0.7, f"ours failed to cluster: {nmi_ours}"

    def test_mf_same_quality(self):
        try:
            _fresh_reference()
        except Exception as e:  # pragma: no cover - env-specific
            pytest.skip(f"reference not importable: {e!r}")
        ratings, nu, ni = synth_ratings()
        rmse_ref = ref_mf_rmse(ratings, nu, ni)
        rmse_ours = our_mf_rmse(ratings, nu, ni)
        # Both must beat the trivial constant-3 predictor (~1.3 RMSE on this
        # rating distribution) and land in the same band. Ours trails the
        # reference slightly at short horizons: bulk-synchronous rounds mix
        # one round behind the reference's shuffled in-round propagation
        # (documented divergence, SURVEY.md §7(c)).
        assert rmse_ref < 1.25, f"reference failed to fit: {rmse_ref}"
        assert rmse_ours < 1.25, f"ours failed to fit: {rmse_ours}"
        assert abs(rmse_ours - rmse_ref) < 0.35, (rmse_ours, rmse_ref)
