"""Native C++ graphgen tests (gossipy_tpu/native)."""

import numpy as np
import pytest

from gossipy_tpu import native
from gossipy_tpu.core import Topology

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="g++ unavailable")


class TestGenerators:
    def test_random_regular_is_regular_symmetric(self):
        adj = native.random_regular(200, 6, seed=7)
        assert adj.shape == (200, 200)
        assert (adj == adj.T).all()
        assert not np.diag(adj).any()
        assert (adj.sum(axis=1) == 6).all()

    def test_random_regular_deterministic_per_seed(self):
        a = native.random_regular(100, 4, seed=1)
        b = native.random_regular(100, 4, seed=1)
        c = native.random_regular(100, 4, seed=2)
        assert (a == b).all()
        assert (a != c).any()

    def test_random_regular_invalid_args(self):
        with pytest.raises(ValueError):
            native.random_regular(5, 3, seed=0)  # n*k odd

    def test_barabasi_albert_degrees(self):
        adj = native.barabasi_albert(300, 5, seed=3)
        assert (adj == adj.T).all()
        assert not np.diag(adj).any()
        deg = adj.sum(axis=1)
        assert (deg >= 5).all()          # every non-seed node attaches m edges
        assert deg.max() > 2 * 5         # hubs emerge (power law)
        # connected: BFS reaches everyone
        seen = np.zeros(300, dtype=bool)
        stack = [0]
        seen[0] = True
        while stack:
            v = stack.pop()
            for u in np.where(adj[v])[0]:
                if not seen[u]:
                    seen[u] = True
                    stack.append(int(u))
        assert seen.all()

    def test_erdos_renyi_density(self):
        adj = native.erdos_renyi(400, 0.1, seed=5)
        assert (adj == adj.T).all()
        density = adj.sum() / (400 * 399)
        assert 0.07 < density < 0.13

    def test_ring(self):
        adj = native.ring(10, 2)
        assert (adj.sum(axis=1) == 4).all()
        assert adj[0, 1] and adj[0, 2] and adj[0, 9] and adj[0, 8]


class TestTopologyBackends:
    def test_backend_native_used_and_valid(self):
        t = Topology.random_regular(64, 4, seed=9, backend="native")
        assert (t.degrees == 4).all()

    def test_backend_networkx_matches_reference_stream(self):
        import networkx as nx
        t = Topology.random_regular(50, 4, seed=9, backend="networkx")
        g = nx.random_regular_graph(4, 50, seed=9)
        assert (t.adjacency == nx.to_numpy_array(g).astype(bool)).all()

    def test_auto_threshold(self):
        # below threshold -> networkx stream
        import networkx as nx
        t = Topology.barabasi_albert(40, 3, seed=2)  # auto, small
        g = nx.barabasi_albert_graph(40, 3, seed=2)
        assert (t.adjacency == nx.to_numpy_array(g).astype(bool)).all()
