"""Host span tracer (telemetry.tracing) + the trace_report reduction.

Covers the ISSUE-16 acceptance surface:

- span recording semantics: nesting, per-thread tracks with
  ``thread_name`` metadata, the decorator form (fresh span per call),
  counter/instant/async events, and the single-timing-source contract
  (a handle measures ``duration`` even with no tracer bound);
- Chrome trace-event schema of the saved snapshot (object form,
  complete events carry ts/dur/pid/tid — what Perfetto needs) and the
  atomic tmp+rename write;
- ``merge_traces`` algebra: associative, commutative, deterministic
  over two simulated processes, schema mismatch raises;
- ``trace_report`` attribution on hand-built timelines: window
  detection, host/device interval unions, overlap vs blocked split,
  the ``host.wait`` exclusion, and the exact self-consistency identity
  ``host_blocked + device + unaccounted == wall``;
- the live engine/cohort integration: a traced cohort run's windows
  cover the measured wall within 5%, ``tracing=True`` routes through
  the process default, and (slow) tracing on/off compiles
  byte-identical HLO with <2x overhead on a warm cache.
"""

import json
import os
import threading

import numpy as np
import pytest

from gossipy_tpu.telemetry.tracing import (
    DEVICE_TID,
    TRACE_SCHEMA,
    WAIT_CAT,
    Tracer,
    attach_device_spans,
    ensure_tracer,
    get_tracer,
    merge_traces,
    set_tracer,
    span,
    trace_report,
)


@pytest.fixture
def no_default_tracer():
    prev = set_tracer(None)
    yield
    set_tracer(prev)


def spans_of(snap, name=None):
    out = [e for e in snap["traceEvents"] if e["ph"] == "X"]
    return [e for e in out if e["name"] == name] if name else out


class TestSpanRecording:
    def test_nesting_and_args(self):
        tr = Tracer(process_name="t", pid=7)
        with tr.span("outer", cat="cohort", rounds=3) as outer:
            with tr.span("inner") as inner:
                pass
        assert outer.duration >= inner.duration >= 0.0
        snap = tr.snapshot()
        (o,) = spans_of(snap, "outer")
        (i,) = spans_of(snap, "inner")
        assert o["args"] == {"rounds": 3} and o["cat"] == "cohort"
        assert o["pid"] == i["pid"] == 7
        # Interval containment: inner lies inside outer on the timeline.
        assert o["ts"] <= i["ts"]
        assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1.0

    def test_handle_times_without_tracer(self):
        with span("x", tracer=None) as sp:
            pass
        assert sp.duration is not None and sp.duration >= 0.0
        assert sp.dur_us == pytest.approx(sp.duration * 1e6)

    def test_decorator_fresh_span_per_call(self):
        tr = Tracer()
        calls = []

        @tr.span("work", cat="host")
        def work(v):
            calls.append(v)
            return v * 2

        assert work(2) == 4 and work(3) == 6
        assert calls == [2, 3]
        assert len(spans_of(tr.snapshot(), "work")) == 2

    def test_thread_tracks_get_named(self):
        tr = Tracer()

        def worker():
            with tr.span("w"):
                pass

        t = threading.Thread(target=worker, name="worker-thread")
        t.start()
        t.join()
        with tr.span("m"):
            pass
        snap = tr.snapshot()
        names = {e["args"]["name"] for e in snap["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert "worker-thread" in names and "device" in names
        tids = {e["tid"] for e in spans_of(snap)}
        assert len(tids) == 2 and DEVICE_TID not in tids

    def test_counter_instant_async_events(self):
        tr = Tracer()
        tr.counter_event("queued", value=3)
        tr.counter_event("rates", a=1.0, b=2.0)
        tr.instant("arrival", cat="loadgen", tenant="t0")
        tr.begin_async("tenant", aid="t0", queue_wait_s=0.5)
        tr.async_instant("first_round", aid="t0")
        tr.end_async("tenant", aid="t0", status="done")
        evs = tr.snapshot()["traceEvents"]
        by_ph = {}
        for e in evs:
            by_ph.setdefault(e["ph"], []).append(e)
        assert {e["name"] for e in by_ph["C"]} == {"queued", "rates"}
        assert by_ph["C"][0]["args"] == {"value": 3.0}
        (inst,) = by_ph["i"]
        assert inst["s"] == "t" and inst["args"] == {"tenant": "t0"}
        assert [e["ph"] for e in evs if e.get("id") == "t0"] == \
            ["b", "n", "e"]

    def test_clear_keeps_metadata(self):
        tr = Tracer()
        with tr.span("x"):
            pass
        tr.clear()
        evs = tr.snapshot()["traceEvents"]
        assert evs and all(e["ph"] == "M" for e in evs)


class TestProcessDefault:
    def test_module_span_resolves_default_at_enter(self, no_default_tracer):
        sp = span("late")           # created while no tracer is installed
        tr = Tracer()
        set_tracer(tr)
        with sp:
            pass
        assert len(spans_of(tr.snapshot(), "late")) == 1

    def test_module_span_noop_without_default(self, no_default_tracer):
        with span("orphan") as sp:
            pass
        assert sp.duration is not None and get_tracer() is None

    def test_ensure_tracer_installs_once(self, no_default_tracer):
        a = ensure_tracer()
        assert ensure_tracer() is a and get_tracer() is a


class TestSaveSchema:
    def test_atomic_save_and_chrome_schema(self, tmp_path):
        tr = Tracer(process_name="p")
        with tr.span("seg", cat="cohort", round_start=0, rounds=2):
            pass
        tr.counter_event("c", value=1)
        path = tr.save(str(tmp_path / "trace.json"))
        assert not os.path.exists(path + ".tmp")
        snap = json.load(open(path))
        assert snap["schema"] == TRACE_SCHEMA
        assert snap["displayTimeUnit"] == "ms"
        assert isinstance(snap["traceEvents"], list)
        for ev in snap["traceEvents"]:
            assert {"ph", "name", "pid", "tid"} <= set(ev)
            if ev["ph"] == "X":
                assert isinstance(ev["ts"], float)
                assert isinstance(ev["dur"], float) and ev["dur"] >= 0.0

    def test_snapshot_is_isolated(self):
        tr = Tracer()
        with tr.span("a"):
            pass
        snap = tr.snapshot()
        snap["traceEvents"].clear()
        assert spans_of(tr.snapshot(), "a")


def _fake_process_trace(pid, name_prefix, t0):
    tr = Tracer(process_name=f"proc{pid}", pid=pid)
    tr.add_complete(f"{name_prefix}.win", t0, 1000.0, cat="cohort",
                    tid=1, args={"round_start": 0, "rounds": 1})
    tr.add_complete(f"{name_prefix}.host", t0 + 100, 200.0, cat="cohort",
                    tid=1)
    return tr.snapshot()


class TestMergeTraces:
    def test_commutative_and_associative(self):
        a = _fake_process_trace(1, "a", 1e6)
        b = _fake_process_trace(2, "b", 2e6)
        c = _fake_process_trace(3, "c", 3e6)
        ab = merge_traces(a, b)
        assert ab == merge_traces(b, a)
        assert merge_traces(ab, c) == merge_traces(a, merge_traces(b, c))

    def test_two_process_merge_is_one_timeline(self):
        a = _fake_process_trace(1, "a", 1e6)
        b = _fake_process_trace(2, "b", 2e6)
        m = merge_traces(a, b)
        assert sorted(m["otherData"]["merged_pids"]) == [1, 2]
        # Every event from both inputs survives (multiset union).
        assert len(m["traceEvents"]) == \
            len(a["traceEvents"]) + len(b["traceEvents"])
        # And the merged report sees both windows, never cross-counting
        # pids (each window only attributes same-pid children).
        rep = trace_report(m)
        assert rep["n_windows"] == 2
        assert rep["totals"]["host_busy_ms"] == pytest.approx(0.4)

    def test_schema_mismatch_raises(self):
        a = _fake_process_trace(1, "a", 1e6)
        bad = dict(a, schema=99)
        with pytest.raises(ValueError, match="schema"):
            merge_traces(a, bad)
        with pytest.raises(ValueError, match="schema"):
            merge_traces(bad, a)


class TestTraceReport:
    """Hand-built timelines with exact expected attributions."""

    def _tracer(self):
        return Tracer(process_name="rep", pid=1)

    def test_blocked_overlap_wait_split(self):
        tr = self._tracer()
        # Window [0, 1000]us; device [200, 700]; host work [100, 400]
        # (100..200 blocked, 200..400 overlapped); wait [400, 900]
        # (excluded); nothing else -> unaccounted fills the rest.
        tr.add_complete("w", 0.0, 1000.0, cat="cohort", tid=1,
                        args={"round_start": 0, "rounds": 2})
        tr.add_complete("gather", 100.0, 300.0, cat="cohort", tid=1)
        tr.add_complete("run", 400.0, 500.0, cat=WAIT_CAT, tid=1)
        attach_device_spans(tr, 200.0, 500.0)
        rep = trace_report(tr.snapshot())
        t = rep["totals"]
        assert rep["n_windows"] == 1 and t["rounds"] == 2
        assert t["wall_ms"] == pytest.approx(1.0)
        assert t["device_ms"] == pytest.approx(0.5)
        assert t["host_busy_ms"] == pytest.approx(0.3)
        assert t["overlap_ms"] == pytest.approx(0.2)
        assert t["host_blocked_ms"] == pytest.approx(0.1)
        # wall - device - blocked = 1.0 - 0.5 - 0.1
        assert t["unaccounted_ms"] == pytest.approx(0.4)
        assert t["overlap_frac"] == pytest.approx(0.2 / 0.3, abs=1e-3)
        assert t["host_blocked_frac"] == pytest.approx(0.1, abs=1e-3)
        # Self-consistency is exact by construction.
        assert t["host_blocked_ms"] + t["device_ms"] + \
            t["unaccounted_ms"] == pytest.approx(t["wall_ms"])
        # Per-round rows split the window evenly.
        assert [r["round"] for r in rep["per_round"]] == [1, 2]
        for r in rep["per_round"]:
            assert r["host_blocked_ms"] == pytest.approx(0.05)
            assert r["device_ms"] == pytest.approx(0.25)
            assert r["overlap_frac"] == pytest.approx(0.2 / 0.3, abs=1e-3)

    def test_overlapping_windows_tag_attribution(self):
        """The streaming-pipeline shape: two ``cohort.segment`` windows
        overlapping in time, children routed by their ``window=`` tag —
        NOT by containment (which is ambiguous here), and overlap
        measured against the pid-wide device union so a gather hidden
        under the OTHER window's device time counts as overlapped."""
        tr = self._tracer()
        # Window A [0, 1000], window B [500, 1500] — overlap [500, 1000].
        tr.add_complete("cohort.segment", 0.0, 1000.0, cat="cohort",
                        tid=1, args={"round_start": 0, "rounds": 1,
                                     "streaming": True})
        tr.add_complete("cohort.segment", 500.0, 1000.0, cat="cohort",
                        tid=1, args={"round_start": 1, "rounds": 1,
                                     "streaming": True})
        # A's host work [50, 150] + its device window [200, 900] (the
        # wait span is excluded from host time).
        tr.add_complete("cohort.stage", 50.0, 100.0, cat="cohort",
                        tid=1, args={"window": 0})
        tr.add_complete("cohort.run", 200.0, 700.0, cat=WAIT_CAT,
                        tid=1, args={"window": 0})
        attach_device_spans(tr, 200.0, 700.0, args={"window": 0})
        # B's stager gather [600, 800]: inside BOTH window intervals —
        # containment alone cannot attribute it; the tag routes it to B,
        # where it is fully hidden under A's device time -> pure overlap.
        tr.add_complete("cohort.gather", 600.0, 200.0, cat="cohort",
                        tid=2, args={"window": 1})
        # A's flush scatter [1050, 1150]: AFTER A's interval (inside
        # B's) — the tag still routes it to A, as blocked host time.
        tr.add_complete("cohort.scatter", 1050.0, 100.0, cat="cohort",
                        tid=3, args={"window": 0})
        rep = trace_report(tr.snapshot())
        assert rep["n_windows"] == 2
        a, b = rep["windows"]
        assert (a["round_start"], b["round_start"]) == (0, 1)
        assert a["host_busy_ms"] == pytest.approx(0.2)     # stage+scatter
        assert a["host_blocked_ms"] == pytest.approx(0.2)  # none hidden
        assert a["device_ms"] == pytest.approx(0.7)
        assert a["overlap_ms"] == pytest.approx(0.0)
        assert a["unaccounted_ms"] == pytest.approx(0.1)
        assert b["host_busy_ms"] == pytest.approx(0.2)     # the gather
        assert b["overlap_ms"] == pytest.approx(0.2)       # under A's dev
        assert b["host_blocked_ms"] == pytest.approx(0.0)
        assert b["device_ms"] == pytest.approx(0.0)        # owns none
        assert b["overlap_frac"] == pytest.approx(1.0)
        t = rep["totals"]
        assert t["wall_ms"] == pytest.approx(2.0)
        assert t["overlap_frac"] == pytest.approx(0.5)
        assert t["host_blocked_frac"] == pytest.approx(0.1)
        # Nothing double-counted: each child lands in exactly one window.
        assert t["host_busy_ms"] == pytest.approx(0.4)
        assert [r["round"] for r in rep["per_round"]] == [1, 2]
        ranked = {r["name"]: r["ms"] for r in rep["critical_path"]}
        assert ranked["device.execute"] == pytest.approx(0.7)
        assert ranked["cohort.gather"] == pytest.approx(0.0)

    def test_critical_path_ranks_non_overlapped(self):
        tr = self._tracer()
        tr.add_complete("w", 0.0, 1000.0, cat="engine", tid=1,
                        args={"round_start": 0, "rounds": 1})
        tr.add_complete("engine.report", 800.0, 150.0, cat="engine", tid=1)
        attach_device_spans(tr, 0.0, 600.0)
        rep = trace_report(tr.snapshot())
        ranked = [(r["name"], r["ms"]) for r in rep["critical_path"]]
        assert ranked[0] == ("device.execute", pytest.approx(0.6))
        assert ranked[1] == ("engine.report", pytest.approx(0.15))

    def test_device_phase_tiling(self):
        tr = self._tracer()
        tr.add_complete("w", 0.0, 1000.0, cat="engine", tid=1,
                        args={"round_start": 0, "rounds": 1})
        attach_device_spans(tr, 0.0, 900.0,
                            phase_ms={"phase.train": 2.0, "eval": 1.0})
        devs = [e for e in spans_of(tr.snapshot())
                if e["cat"] == "device"]
        assert {e["name"] for e in devs} == \
            {"device.train", "device.eval"}
        assert sum(e["dur"] for e in devs) == pytest.approx(900.0)
        by = {e["name"]: e["dur"] for e in devs}
        assert by["device.train"] == pytest.approx(600.0)
        assert all(e["tid"] == DEVICE_TID for e in devs)

    def test_empty_trace_reports_zero_windows(self):
        rep = trace_report(Tracer().snapshot())
        assert rep["n_windows"] == 0 and rep["per_round"] == []
        assert rep["totals"]["host_blocked_frac"] is None


def _cohort_sim(tracing=None, n=32, c=8, d=4):
    import optax

    from gossipy_tpu.core import (AntiEntropyProtocol, CreateModelMode,
                                  Topology)
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import CohortConfig, GossipSimulator

    rng = np.random.default_rng(5)
    X = rng.normal(size=(n * 4, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                          n=n, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(d, 2),
                         loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.1), local_epochs=1,
                         batch_size=8, n_classes=2, input_shape=(d,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    return GossipSimulator(handler, Topology.random_regular(n, 4, seed=3),
                           disp.stacked(), delta=10,
                           protocol=AntiEntropyProtocol.PUSH,
                           cohort=CohortConfig(size=c), tracing=tracing)


class TestEngineIntegration:
    def test_cohort_spans_cover_wall_within_5pct(self, no_default_tracer):
        import time

        import jax

        tr = Tracer(process_name="test")
        sim = _cohort_sim(tracing=tr)
        key = jax.random.PRNGKey(3)
        pool = sim.init_cohort_pool(key)
        t0 = time.perf_counter()
        sim.start(pool, n_rounds=4, key=key)
        wall_us = (time.perf_counter() - t0) * 1e6
        snap = tr.snapshot()
        segs = spans_of(snap, "cohort.segment")
        assert len(segs) == 4  # one window per round (segment length 1)
        assert sum(e["dur"] for e in segs) >= 0.95 * \
            sum(e["dur"] for e in spans_of(snap, "cohort.start"))
        # The outer cohort.start span tracks the measured wall within 5%.
        (outer,) = spans_of(snap, "cohort.start")
        assert outer["dur"] == pytest.approx(wall_us, rel=0.05)
        # Every per-round row of the report names its attribution.
        rep = trace_report(snap)
        assert len(rep["per_round"]) == 4
        for row in rep["per_round"]:
            assert row["host_blocked_ms"] >= 0.0
            assert 0.0 <= row["overlap_frac"] <= 1.0
        assert rep["totals"]["unaccounted_frac"] < 0.15

    def test_tracing_true_uses_process_default(self, no_default_tracer):
        sim = _cohort_sim(tracing=True)
        assert sim.tracer is get_tracer() is not None

    def test_tracing_instance_not_installed_globally(
            self, no_default_tracer):
        tr = Tracer()
        sim = _cohort_sim(tracing=tr)
        assert sim.tracer is tr and get_tracer() is None

    @pytest.mark.slow
    def test_tracing_on_is_hlo_neutral(self, no_default_tracer):
        from gossipy_tpu.analysis import assert_identical_hlo
        from gossipy_tpu.analysis.hlo import _make_sim
        assert_identical_hlo(_make_sim(), _make_sim(tracing=True),
                             label="tracing-on")

    @pytest.mark.slow
    def test_tracing_overhead_bounded(self, no_default_tracer):
        # Warm-cache A/B on the same tiny cohort config: tracing must not
        # change the compiled program, so the second run pays only the
        # host-side span cost (bound is generous — CI wall-clock noise on
        # second-scale runs dwarfs the microseconds spans cost).
        import time

        import jax

        key = jax.random.PRNGKey(3)
        sim_off = _cohort_sim()
        pool = sim_off.init_cohort_pool(key)
        sim_off.start(pool, n_rounds=4, key=key)     # compile warmup
        t0 = time.perf_counter()
        sim_off.start(pool, n_rounds=4, key=key)
        off = time.perf_counter() - t0
        sim_on = _cohort_sim(tracing=Tracer())
        sim_on.start(pool, n_rounds=4, key=key)      # warmup (cache hit)
        t0 = time.perf_counter()
        sim_on.start(pool, n_rounds=4, key=key)
        on = time.perf_counter() - t0
        assert on <= 1.5 * off + 0.25, (on, off)
