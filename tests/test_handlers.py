"""Handler unit tests: merge/update semantics vs hand-computed expectations."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gossipy_tpu.compression import ModelPartition, sample_mask, sampled_merge
from gossipy_tpu.core import CreateModelMode
from gossipy_tpu.handlers import (
    AdaLineHandler,
    KMeansHandler,
    LimitedMergeSGDHandler,
    MFHandler,
    ModelState,
    PartitionedSGDHandler,
    PeerModel,
    PegasosHandler,
    SamplingSGDHandler,
    SGDHandler,
    losses,
)
from gossipy_tpu.models import AdaLine, LogisticRegression, MLP


def make_binary_data(n=64, d=8, seed=0, signed=False):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.float32)
    if signed:
        y = 2 * y - 1
    mask = np.ones(n, dtype=np.float32)
    return jnp.asarray(X), jnp.asarray(y), jnp.asarray(mask)


# ---------------------------------------------------------------------------
# SGD handler
# ---------------------------------------------------------------------------

class TestSGDHandler:
    def make(self, d=8, mode=CreateModelMode.MERGE_UPDATE, **kw):
        return SGDHandler(
            model=LogisticRegression(d, 2),
            loss=losses.cross_entropy,
            optimizer=optax.sgd(0.5),
            local_epochs=kw.pop("local_epochs", 2),
            batch_size=kw.pop("batch_size", 16),
            n_classes=2,
            input_shape=(d,),
            create_model_mode=mode,
            **kw,
        )

    def test_init_and_update_improves_accuracy(self, key):
        h = self.make()
        X, y, mask = make_binary_data()
        st = h.init(key)
        acc0 = float(h.evaluate(st, (X, y.astype(jnp.int32), mask))["accuracy"])
        for i in range(15):
            st = h.update(st, (X, y.astype(jnp.int32), mask), jax.random.fold_in(key, i))
        acc1 = float(h.evaluate(st, (X, y.astype(jnp.int32), mask))["accuracy"])
        assert acc1 > acc0
        assert acc1 > 0.85
        assert int(st.n_updates) == 15 * 2 * 4  # epochs * batches

    def test_update_ignores_padding(self, key):
        h = self.make(local_epochs=1, batch_size=8)
        X, y, mask = make_binary_data(n=32)
        # Pad with garbage rows that must not affect training.
        Xp = jnp.concatenate([X, 1e3 * jnp.ones((16, 8))])
        yp = jnp.concatenate([y, jnp.zeros(16)])
        mp = jnp.concatenate([mask, jnp.zeros(16)])
        st = h.init(key)
        st_clean = h.update(st, (X, y.astype(jnp.int32), mask), key)
        st_pad = h.update(st, (Xp, yp.astype(jnp.int32), mp), key)
        # Same data through different batch layouts won't match exactly, but
        # the padded run must stay finite and sane.
        for leaf in jax.tree_util.tree_leaves(st_pad.params):
            assert np.isfinite(np.asarray(leaf)).all()
        assert np.isfinite(
            float(h.evaluate(st_pad, (X, y.astype(jnp.int32), mask))["accuracy"]))
        del st_clean

    def test_merge_is_uniform_average(self, key):
        h = self.make()
        st1 = h.init(key)
        st2 = h.init(jax.random.fold_in(key, 1))
        st1 = st1._replace(n_updates=jnp.int32(5))
        st2 = st2._replace(n_updates=jnp.int32(9))
        merged = h.merge(st1, PeerModel(st2.params, st2.n_updates))
        expect = jax.tree.map(lambda a, b: (a + b) / 2, st1.params, st2.params)
        for a, b in zip(jax.tree_util.tree_leaves(merged.params),
                        jax.tree_util.tree_leaves(expect)):
            assert np.allclose(a, b)
        assert int(merged.n_updates) == 9  # max (handler.py:280)

    def test_call_modes(self, key):
        X, y, mask = make_binary_data()
        data = (X, y.astype(jnp.int32), mask)
        for mode in [CreateModelMode.UPDATE, CreateModelMode.MERGE_UPDATE,
                     CreateModelMode.UPDATE_MERGE, CreateModelMode.PASS]:
            h = self.make(mode=mode)
            st = h.init(key)
            peer_st = h.init(jax.random.fold_in(key, 7))
            peer = PeerModel(peer_st.params, jnp.int32(3))
            out = h.call(st, peer, data, jax.random.fold_in(key, 8))
            assert isinstance(out, ModelState)
            if mode == CreateModelMode.PASS:
                for a, b in zip(jax.tree_util.tree_leaves(out.params),
                                jax.tree_util.tree_leaves(peer.params)):
                    assert np.allclose(a, b)

    def test_batch_size_larger_than_shard(self, key):
        # Regression: batch_size >> S must not crash the padded batching.
        h = self.make(local_epochs=1, batch_size=32)
        X, y, mask = make_binary_data(n=10)
        st = h.init(key)
        st = h.update(st, (X, y.astype(jnp.int32), mask), key)
        assert int(st.n_updates) == 1
        for leaf in jax.tree_util.tree_leaves(st.params):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_mlp_trains(self, key):
        d = 8
        h = SGDHandler(model=MLP(d, 2, hidden_dims=(16,)), loss=losses.cross_entropy,
                       optimizer=optax.sgd(0.3), local_epochs=5, batch_size=16,
                       n_classes=2, input_shape=(d,))
        X, y, mask = make_binary_data(d=d)
        st = h.init(key)
        for i in range(5):
            st = h.update(st, (X, y.astype(jnp.int32), mask), jax.random.fold_in(key, i))
        acc = float(h.evaluate(st, (X, y.astype(jnp.int32), mask))["accuracy"])
        assert acc > 0.9


# ---------------------------------------------------------------------------
# Pegasos / AdaLine
# ---------------------------------------------------------------------------

class TestLinearHandlers:
    def test_pegasos_matches_manual_loop(self, key):
        d, n = 4, 10
        h = PegasosHandler(AdaLine(d), learning_rate=0.1)
        X, y, mask = make_binary_data(n=n, d=d, signed=True)
        st = h.init(key)
        out = h.update(st, (X, y, mask), key)

        # Manual replication of reference handler.py:416-423.
        w = np.zeros(d)
        lam = 0.1
        Xn, yn = np.asarray(X), np.asarray(y)
        for i in range(n):
            t = i + 1
            eta = 1.0 / (t * lam)
            score = w @ Xn[i]
            w = w * (1 - eta * lam)
            if score * yn[i] - 1 < 0:
                w = w + eta * yn[i] * Xn[i]
        assert np.allclose(np.asarray(out.params), w, atol=1e-5)
        assert int(out.n_updates) == n

    def test_pegasos_masked_samples_skipped(self, key):
        d = 4
        h = PegasosHandler(AdaLine(d), learning_rate=0.1)
        X, y, _ = make_binary_data(n=10, d=d, signed=True)
        mask = jnp.asarray([1, 1, 1, 0, 0, 0, 0, 0, 0, 0], dtype=jnp.float32)
        out = h.update(h.init(key), (X, y, mask), key)
        out2 = h.update(h.init(key), (X[:3], y[:3], jnp.ones(3)), key)
        assert np.allclose(np.asarray(out.params), np.asarray(out2.params), atol=1e-6)
        assert int(out.n_updates) == 3

    def test_adaline_update_and_merge(self, key):
        d = 4
        h = AdaLineHandler(AdaLine(d), learning_rate=0.05)
        X, y, mask = make_binary_data(n=20, d=d, signed=True)
        st = h.update(h.init(key), (X, y, mask), key)
        assert int(st.n_updates) == 20
        peer = PeerModel(jnp.ones(d), jnp.int32(7))
        merged = h.merge(st, peer)
        assert np.allclose(np.asarray(merged.params),
                           0.5 * (np.asarray(st.params) + 1.0))
        assert int(merged.n_updates) == 20

    def test_pegasos_learns(self, key):
        d = 8
        h = PegasosHandler(AdaLine(d), learning_rate=0.01)
        X, y, mask = make_binary_data(n=200, d=d, signed=True)
        st = h.init(key)
        for _ in range(3):
            st = h.update(st, (X, y, mask), key)
        res = h.evaluate(st, (X, y, mask))
        assert float(res["accuracy"]) > 0.9
        assert float(res["auc"]) > 0.9


# ---------------------------------------------------------------------------
# Compression: partitioning and sampling
# ---------------------------------------------------------------------------

class TestCompression:
    def test_partition_covers_all_coordinates(self, key):
        h = SGDHandler(model=MLP(6, 3, hidden_dims=(5,)), loss=losses.cross_entropy,
                       n_classes=3, input_shape=(6,))
        params = h.init(key).params
        part = ModelPartition(params, 4)
        ids = np.concatenate([np.asarray(l).ravel()
                              for l in jax.tree_util.tree_leaves(part.part_ids)])
        total = ids.size
        # Every coordinate gets exactly one part; sizes differ by <= 1.
        assert part.sizes.sum() == total
        assert part.sizes.max() - part.sizes.min() <= 1
        assert set(np.unique(ids)) == set(range(4))

    def test_partition_merge_only_touches_partition(self, key):
        h = SGDHandler(model=LogisticRegression(6, 2), loss=losses.cross_entropy,
                       n_classes=2, input_shape=(6,))
        p1 = h.init(key).params
        p2 = jax.tree.map(lambda a: a + 1.0, p1)
        part = ModelPartition(p1, 3)
        merged = part.merge(p1, p2, 1, weights=(1, 1))
        for leaf_m, leaf_1, ids in zip(jax.tree_util.tree_leaves(merged),
                                       jax.tree_util.tree_leaves(p1),
                                       jax.tree_util.tree_leaves(part.part_ids)):
            in_part = np.asarray(ids) == 1
            np.testing.assert_allclose(np.asarray(leaf_m)[~in_part],
                                       np.asarray(leaf_1)[~in_part])
            np.testing.assert_allclose(np.asarray(leaf_m)[in_part],
                                       np.asarray(leaf_1)[in_part] + 0.5,
                                       rtol=1e-6)

    def test_partition_merge_age_weighting(self, key):
        p1 = {"w": jnp.zeros((4,))}
        p2 = {"w": jnp.ones((4,))}
        part = ModelPartition(p1, 1)
        merged = part.merge(p1, p2, 0, weights=(3, 1))
        np.testing.assert_allclose(np.asarray(merged["w"]), 0.25, rtol=1e-6)
        # weights (0,0) -> plain average (sampling.py:228)
        merged = part.merge(p1, p2, 0, weights=(jnp.int32(0), jnp.int32(0)))
        np.testing.assert_allclose(np.asarray(merged["w"]), 0.5, rtol=1e-6)

    def test_sample_mask_fraction_and_merge(self, key):
        params = {"a": jnp.zeros((100, 100)), "b": jnp.zeros((500,))}
        mask = sample_mask(key, params, 0.3)
        frac = np.mean([np.asarray(m).mean() for m in jax.tree_util.tree_leaves(mask)])
        assert abs(frac - 0.3) < 0.05
        p2 = {"a": jnp.ones((100, 100)), "b": jnp.ones((500,))}
        merged = sampled_merge(params, p2, mask)
        a = np.asarray(merged["a"])
        assert set(np.unique(a)).issubset({0.0, 0.5})


# ---------------------------------------------------------------------------
# Partitioned / sampled / limited-merge handlers
# ---------------------------------------------------------------------------

class TestSGDVariants:
    def test_partitioned_handler_roundtrip(self, key):
        d = 6
        base = SGDHandler(model=LogisticRegression(d, 2), loss=losses.cross_entropy,
                          n_classes=2, input_shape=(d,))
        params = base.init(key).params
        part = ModelPartition(params, 4)
        h = PartitionedSGDHandler(part, model=LogisticRegression(d, 2),
                                  loss=losses.cross_entropy, optimizer=optax.sgd(0.1),
                                  local_epochs=1, batch_size=8, n_classes=2,
                                  input_shape=(d,))
        st = h.init(key)
        assert st.n_updates.shape == (4,)
        X, y, mask = make_binary_data(n=16, d=d)
        st = h.update(st, (X, y.astype(jnp.int32), mask), key)
        assert (np.asarray(st.n_updates) == 2).all()  # 2 batches, all parts age together
        peer = PeerModel(jax.tree.map(lambda a: a + 1.0, st.params),
                         jnp.asarray([5, 5, 5, 5], dtype=jnp.int32))
        merged = h.merge(st, peer, extra=jnp.int32(2))
        assert int(merged.n_updates[2]) == 5
        assert int(merged.n_updates[0]) == 2

    def test_sampling_handler_merge(self, key):
        d = 6
        h = SamplingSGDHandler(0.5, model=LogisticRegression(d, 2),
                               loss=losses.cross_entropy, n_classes=2,
                               input_shape=(d,))
        st = h.init(key)
        peer = PeerModel(jax.tree.map(lambda a: a + 2.0, st.params), jnp.int32(3))
        merged = h.merge(st, peer, extra=jax.random.fold_in(key, 9))
        diff = np.concatenate([
            (np.asarray(m) - np.asarray(o)).ravel()
            for m, o in zip(jax.tree_util.tree_leaves(merged.params),
                            jax.tree_util.tree_leaves(st.params))])
        assert set(np.round(np.unique(diff), 5)).issubset({0.0, 1.0})
        assert int(merged.n_updates) == 0  # sampling merge keeps age

    def test_limited_merge_age_gate(self, key):
        d = 4
        h = LimitedMergeSGDHandler(model=LogisticRegression(d, 2),
                                   loss=losses.cross_entropy, n_classes=2,
                                   input_shape=(d,), age_diff_threshold=2)
        st = h.init(key)._replace(n_updates=jnp.int32(10))
        peer_params = jax.tree.map(lambda a: a + 1.0, st.params)
        # Peer too old a gap below: self kept.
        merged = h.merge(st, PeerModel(peer_params, jnp.int32(1)))
        for a, b in zip(jax.tree_util.tree_leaves(merged.params),
                        jax.tree_util.tree_leaves(st.params)):
            assert np.allclose(a, b)
        # Peer much older: adopted wholesale.
        merged = h.merge(st, PeerModel(peer_params, jnp.int32(50)))
        for a, b in zip(jax.tree_util.tree_leaves(merged.params),
                        jax.tree_util.tree_leaves(peer_params)):
            assert np.allclose(a, b)
        # Close ages: age-weighted average.
        merged = h.merge(st, PeerModel(peer_params, jnp.int32(10)))
        for a, b in zip(jax.tree_util.tree_leaves(merged.params),
                        jax.tree_util.tree_leaves(st.params)):
            assert np.allclose(np.asarray(a), np.asarray(b) + 0.5, atol=1e-6)
        # Regression: two age-0 models average instead of zeroing out.
        st0 = st._replace(n_updates=jnp.int32(0))
        merged = h.merge(st0, PeerModel(peer_params, jnp.int32(0)))
        for a, b in zip(jax.tree_util.tree_leaves(merged.params),
                        jax.tree_util.tree_leaves(st.params)):
            assert np.allclose(np.asarray(a), np.asarray(b) + 0.5, atol=1e-6)


# ---------------------------------------------------------------------------
# MF and KMeans
# ---------------------------------------------------------------------------

class TestMFHandler:
    def test_update_reduces_rmse(self, key):
        n_items = 50
        h = MFHandler(dim=4, n_items=n_items, learning_rate=0.05)
        rng = np.random.default_rng(0)
        items = jnp.asarray(rng.integers(0, n_items, size=30))
        ratings = jnp.asarray(rng.uniform(1, 5, size=30).astype(np.float32))
        mask = jnp.ones(30)
        st = h.init(key)
        r0 = float(h.evaluate(st, (items, ratings, mask))["rmse"])
        upd = jax.jit(h.update)  # compile once; 30 eager traces cost ~8 s
        for i in range(30):
            st = upd(st, (items, ratings, mask), key)
        r1 = float(h.evaluate(st, (items, ratings, mask))["rmse"])
        assert r1 < r0
        assert r1 < 1.0
        assert int(st.n_updates) == 1 + 30 * 30

    def test_merge_weighted_average_of_item_state(self, key):
        h = MFHandler(dim=2, n_items=3)
        st = h.init(key)._replace(n_updates=jnp.int32(3))
        peer_params = jax.tree.map(lambda a: a * 0 + 2.0, st.params)
        merged = h.merge(st, PeerModel(peer_params, jnp.int32(1)))
        expect_Y = (np.asarray(st.params["Y"]) * 3 + 2.0 * 1) / 4
        np.testing.assert_allclose(np.asarray(merged["Y"] if isinstance(merged, dict)
                                              else merged.params["Y"]), expect_Y,
                                   rtol=1e-6)
        # User state untouched.
        np.testing.assert_allclose(np.asarray(merged.params["X"]),
                                   np.asarray(st.params["X"]))

    def test_get_size(self):
        h = MFHandler(dim=5, n_items=100)
        assert h.get_size() == 5 * 101  # handler.py:575-576


class TestKMeansHandler:
    def make_blobs(self, seed=0):
        # Blobs inside the unit square: the handler inits centroids ~U(0,1)
        # (reference handler.py:594-595), so data must live at that scale.
        rng = np.random.default_rng(seed)
        centers = np.array([[0.1, 0.1], [0.9, 0.9], [0.1, 0.9]], dtype=np.float32)
        X = np.concatenate([rng.normal(c, 0.05, size=(40, 2)) for c in centers])
        y = np.repeat(np.arange(3), 40)
        return jnp.asarray(X.astype(np.float32)), jnp.asarray(y), jnp.ones(120)

    def test_clustering_improves_nmi(self, key):
        h = KMeansHandler(k=3, dim=2, alpha=0.2)
        X, y, mask = self.make_blobs()
        st = h.init(key)
        for _ in range(50):
            st = h.update(st, (X, y, mask), key)
        res = h.evaluate(st, (X, y, mask))
        assert float(res["nmi"]) > 0.8

    def test_merge_naive_and_matched(self, key):
        h = KMeansHandler(k=3, dim=2, matching="naive")
        c1 = jnp.asarray([[0.0, 0.0], [5.0, 5.0], [0.0, 5.0]])
        c2_permuted = jnp.asarray([[5.1, 5.1], [0.1, 5.1], [0.1, 0.1]])
        st = ModelState(c1, (), jnp.int32(1))
        peer = PeerModel(c2_permuted, jnp.int32(1))
        naive = h.merge(st, peer)
        assert not np.allclose(np.asarray(naive.params), np.asarray(c1), atol=0.5)

        hm = KMeansHandler(k=3, dim=2, matching="hungarian")
        matched = hm.merge(st, peer)
        np.testing.assert_allclose(np.asarray(matched.params), np.asarray(c1),
                                   atol=0.1)


class TestKMeansMatching:
    """Greedy-vs-exact assignment divergence (ISSUE-7 satellite): the
    jitted merge path keeps ``greedy_match`` (shape-static, in-trace); the
    eager path upgrades to the exact Hungarian solver. These tests
    QUANTIFY when the two agree and how far greedy can stray — the
    tradeoff documented in the handler module docstring."""

    @staticmethod
    def _assign_cost(cost, match):
        cost = np.asarray(cost, np.float64)
        return float(cost[np.arange(cost.shape[0]), np.asarray(match)].sum())

    def test_greedy_is_exact_when_well_separated(self):
        # The gossip regime: peers' centroids are noisy copies of the
        # same well-separated truth, so each row's true partner is its
        # global nearest and greedy provably finds the optimum. 50 random
        # instances, k=4: match-for-match identical.
        from gossipy_tpu.handlers.kmeans import exact_match, greedy_match
        rng = np.random.default_rng(0)
        for trial in range(50):
            truth = rng.uniform(-10, 10, size=(4, 3))
            c1 = truth + rng.normal(0, 0.05, size=truth.shape)
            perm = rng.permutation(4)
            c2 = truth[perm] + rng.normal(0, 0.05, size=truth.shape)
            cost = np.sqrt(((c1[:, None] - c2[None]) ** 2).sum(-1))
            g = np.asarray(greedy_match(jnp.asarray(cost, jnp.float32)))
            e = exact_match(cost)
            np.testing.assert_array_equal(g, e, err_msg=f"trial {trial}")

    def test_greedy_divergence_is_unbounded_on_crafted_costs(self):
        # The failure mode: greedy locks the globally-cheapest pair even
        # when it forces an arbitrarily expensive completion. Here
        # greedy pays 100 + 1 where the optimum pays 1 + 1 — a 50x
        # excess, scalable without limit by inflating the corner.
        from gossipy_tpu.handlers.kmeans import exact_match, greedy_match
        cost = np.array([[0.0, 1.0], [1.0, 100.0]])
        g = np.asarray(greedy_match(jnp.asarray(cost, jnp.float32)))
        e = exact_match(cost)
        gc, ec = self._assign_cost(cost, g), self._assign_cost(cost, e)
        np.testing.assert_array_equal(g, [0, 1])  # locks the 0.0 corner
        np.testing.assert_array_equal(e, [1, 0])
        assert gc == 100.0 and ec == 2.0
        assert gc / ec == 50.0

    def test_exact_never_loses_and_quantifies_mean_excess(self):
        # Exact is a true lower bound on every instance; on UNSTRUCTURED
        # random costs (no well-separated geometry) greedy's mean excess
        # is small but nonzero — the quantified gap a hungarian-matching
        # user accepts inside jit.
        from gossipy_tpu.handlers.kmeans import exact_match, greedy_match
        rng = np.random.default_rng(1)
        excess = []
        for _ in range(50):
            cost = rng.uniform(0.1, 1.0, size=(5, 5))
            g = np.asarray(greedy_match(jnp.asarray(cost, jnp.float32)))
            assert np.array_equal(np.sort(g), np.arange(5))  # a permutation
            gc = self._assign_cost(cost, g)
            ec = self._assign_cost(cost, exact_match(cost))
            assert ec <= gc + 1e-9
            excess.append(gc / ec - 1.0)
        assert 0.0 < np.mean(excess) < 0.25, np.mean(excess)

    def test_merge_dispatch_eager_exact_traced_greedy(self):
        # The handler's split: an EAGER merge resolves a crafted
        # ambiguity with the exact solver; the SAME merge under jit keeps
        # the greedy assignment. Geometry: c1 = [0, 10], c2 = [1, -8]
        # gives cost [[1, 8], [9, 18]] — greedy locks the cheap (0, 0)
        # pair and pays 1 + 18 = 19; the optimum crosses over and pays
        # 8 + 9 = 17 — so the two merge paths average DIFFERENT pairs.
        h = KMeansHandler(k=2, dim=1, matching="hungarian")
        st = ModelState(jnp.asarray([[0.0], [10.0]]), (), jnp.int32(1))
        peer = PeerModel(jnp.asarray([[1.0], [-8.0]]), jnp.int32(1))
        eager = np.asarray(h.merge(st, peer).params)
        traced = np.asarray(jax.jit(h.merge)(st, peer).params)
        # Exact pairs (0 with -8, 10 with 1): means [-4, 5.5].
        np.testing.assert_allclose(eager.ravel(), [-4.0, 5.5])
        # Greedy pairs (0 with 1, 10 with -8): means [0.5, 1].
        np.testing.assert_allclose(traced.ravel(), [0.5, 1.0])
        assert not np.allclose(eager, traced)


class TestMixedPrecision:
    def test_bf16_compute_learns_params_stay_f32(self, key):
        import optax
        import jax.numpy as jnp
        from gossipy_tpu.handlers import SGDHandler, losses
        from gossipy_tpu.models import MLP
        rng = np.random.default_rng(0)
        d = 8
        w = rng.normal(size=d)
        X = rng.normal(size=(256, d)).astype(np.float32)
        y = (X @ w > 0).astype(np.int64)
        mask = np.ones(256, dtype=np.float32)
        h = SGDHandler(model=MLP(d, 2, hidden_dims=(16,)),
                       loss=losses.cross_entropy, optimizer=optax.sgd(0.2),
                       local_epochs=5, batch_size=32, n_classes=2,
                       input_shape=(d,), compute_dtype=jnp.bfloat16)
        st = h.init(key)
        st = jax.jit(h.update)(st, (X, y, mask), key)
        leaves = jax.tree_util.tree_leaves(st.params)
        assert all(l.dtype == jnp.float32 for l in leaves)
        acc = h.evaluate(st, (X, y, mask))["accuracy"]
        assert float(acc) > 0.9, float(acc)


class TestRemat:
    def test_remat_is_numerically_identical(self):
        """remat=True recomputes the forward on backward — results must be
        bit-compatible with the stored-activation path (same ops, same
        order), and the jitted update must compile."""
        import numpy as np
        key = jax.random.PRNGKey(7)
        X, y, mask = make_binary_data()
        y = y.astype(jnp.int32)

        def run(remat):
            h = SGDHandler(model=MLP(8, 2, hidden_dims=(16,)),
                           loss=losses.cross_entropy,
                           optimizer=optax.sgd(0.2), local_epochs=2,
                           batch_size=16, n_classes=2, input_shape=(8,),
                           remat=remat)
            st = h.init(key)
            upd = jax.jit(h.update)
            for i in range(3):
                st = upd(st, (X, y, mask), jax.random.fold_in(key, i))
            return st

        a, b = run(False), run(True)
        for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                          jax.tree_util.tree_leaves(b.params)):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       atol=1e-6)
