"""Sharded execution tests on the 8-device virtual CPU mesh."""

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, Topology, UniformDelay
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import MLP
from gossipy_tpu.parallel import make_mesh, shard_data, shard_state, state_shardings
from gossipy_tpu.simulation import GossipSimulator


def build(n_nodes=16, data=None):
    rng = np.random.default_rng(0)
    d = 6
    w = rng.normal(size=d)
    X = rng.normal(size=(n_nodes * 12, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25), n=n_nodes)
    handler = SGDHandler(model=MLP(d, 2, hidden_dims=(8,)),
                         loss=losses.cross_entropy, optimizer=optax.sgd(0.2),
                         local_epochs=1, batch_size=4, n_classes=2,
                         input_shape=(d,))
    stacked = disp.stacked() if data is None else data
    sim = GossipSimulator(handler, Topology.clique(n_nodes), stacked,
                          delta=10, protocol=AntiEntropyProtocol.PUSH,
                          delay=UniformDelay(0, 12))
    return sim, disp


class FakeDev:
    """Stand-in device for placement-logic tests (id + process_index)."""

    def __init__(self, id, process_index):
        self.id = id
        self.process_index = process_index

    def __repr__(self):
        return f"d{self.id}@h{self.process_index}"

    def __eq__(self, other):
        return (self.id, self.process_index) == (other.id, other.process_index)

    def __hash__(self):
        return hash((self.id, self.process_index))


class TestTpDeviceGrid:
    """Host-contiguous TP placement (multi-host make_mesh_tp)."""

    def test_model_groups_stay_intra_host(self):
        from gossipy_tpu.parallel import _tp_device_grid
        devs = [FakeDev(i, i // 4) for i in range(16)]  # 4 hosts x 4 chips
        grid = _tp_device_grid(devs, 8, 2)
        assert grid.shape == (8, 2)
        # Every model-axis row within one host (TP psums ride ICI) ...
        for row in grid:
            assert len({d.process_index for d in row}) == 1
        # ... and the node axis spans all hosts.
        assert {d.process_index for d in grid[:, 0]} == {0, 1, 2, 3}
        # All 16 devices used exactly once.
        assert len({d.id for d in grid.ravel()}) == 16

    def test_interleaved_device_order_is_regrouped(self):
        """jax.devices() order is not host-contiguous on real pods; the
        grid must regroup by process_index, not trust list order."""
        from gossipy_tpu.parallel import _tp_device_grid
        devs = [FakeDev(i, i % 4) for i in range(16)]  # round-robin hosts
        grid = _tp_device_grid(devs, 8, 2)
        for row in grid:
            assert len({d.process_index for d in row}) == 1

    def test_model_axis_exceeding_host_raises(self):
        from gossipy_tpu.parallel import _tp_device_grid
        devs = [FakeDev(i, i // 4) for i in range(16)]
        with pytest.raises(ValueError, match="divide the per-host"):
            _tp_device_grid(devs, 2, 8)  # 8-way TP > 4 chips/host

    def test_uneven_hosts_raise(self):
        from gossipy_tpu.parallel import _tp_device_grid
        devs = [FakeDev(i, 0 if i < 5 else 1) for i in range(8)]
        with pytest.raises(ValueError, match="uneven"):
            _tp_device_grid(devs, 4, 2)

    def test_single_host_matches_plain_reshape(self):
        from gossipy_tpu.parallel import _tp_device_grid
        devs = [FakeDev(i, 0) for i in range(8)]
        grid = _tp_device_grid(devs, 4, 2)
        assert [d.id for d in grid.ravel()] == list(range(8))


def test_mesh_has_8_devices():
    mesh = make_mesh()
    assert mesh.devices.size == 8


def test_sharded_run_matches_unsharded(key):
    sim, disp = build()
    st = sim.init_nodes(key)
    _, rep_plain = sim.start(st, n_rounds=4, key=jax.random.fold_in(key, 1))

    mesh = make_mesh(8)
    sim_sh, _ = build(data=shard_data(disp.stacked(), mesh))
    st_sh = shard_state(sim_sh.init_nodes(key), mesh)
    _, rep_sh = sim_sh.start(st_sh, n_rounds=4, key=jax.random.fold_in(key, 1))

    np.testing.assert_allclose(rep_plain.curves(local=False)["accuracy"],
                               rep_sh.curves(local=False)["accuracy"],
                               rtol=1e-4, atol=1e-5)
    assert rep_plain.sent_messages == rep_sh.sent_messages


def test_pens_sharded_run_matches_unsharded(key):
    """PENS's round-4 degree-bounded aux ([N, max_deg] counters + [N, S]
    model cache) must shard over the node axis like every other leaf and
    reproduce the unsharded two-phase run exactly."""
    from gossipy_tpu.core import CreateModelMode
    from gossipy_tpu.simulation import PENSGossipSimulator

    def build_pens(data=None):
        n_nodes, d = 16, 6
        rng = np.random.default_rng(0)
        w = rng.normal(size=d)
        X = rng.normal(size=(n_nodes * 12, d)).astype(np.float32)
        y = (X @ w > 0).astype(np.int64)
        disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                              n=n_nodes)
        handler = SGDHandler(model=MLP(d, 2, hidden_dims=(8,)),
                             loss=losses.cross_entropy,
                             optimizer=optax.sgd(0.2), local_epochs=1,
                             batch_size=4, n_classes=2, input_shape=(d,),
                             create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim = PENSGossipSimulator(
            handler, Topology.clique(n_nodes),
            disp.stacked() if data is None else data, delta=10,
            n_sampled=4, m_top=2, step1_rounds=3)
        return sim, disp

    sim, disp = build_pens()
    st = sim.init_nodes(key)
    _, rep_plain = sim.start(st, n_rounds=5, key=jax.random.fold_in(key, 1))

    mesh = make_mesh(8)
    sim_sh, _ = build_pens(data=shard_data(disp.stacked(), mesh))
    st_sh = shard_state(sim_sh.init_nodes(key), mesh)
    assert st_sh.aux["selected"].sharding.spec[0] == "nodes"
    _, rep_sh = sim_sh.start(st_sh, n_rounds=5,
                             key=jax.random.fold_in(key, 1))

    np.testing.assert_allclose(rep_plain.curves(local=False)["accuracy"],
                               rep_sh.curves(local=False)["accuracy"],
                               rtol=1e-4, atol=1e-5)


def test_state_shardings_structure(key):
    sim, _ = build()
    st = sim.init_nodes(key)
    mesh = make_mesh(8)
    sh = state_shardings(st, mesh)
    # Model params: node axis leading.
    specs = jax.tree_util.tree_leaves(
        jax.tree.map(lambda s: s.spec, sh.model.params))
    assert all(s[0] == "nodes" for s in specs)
    # Mailbox: node axis second.
    assert sh.mailbox.sender.spec[1] == "nodes"


def test_sharded_state_is_distributed(key):
    sim, _ = build()
    mesh = make_mesh(8)
    st = shard_state(sim.init_nodes(key), mesh)
    leaf = jax.tree_util.tree_leaves(st.model.params)[0]
    assert len(leaf.sharding.device_set) == 8


def test_fused_multi_sharded_matches_unsharded(key):
    """GossipSimulator(mesh=) + fused_merge="multi": the deliver phase
    runs the multi-slot kernel inside a shard_map ring over the node axis
    (parallel.collectives.sharded_gather_merge_multi). The ring rewrites
    the left-to-right K-slot fold into its composed linear form, so the
    sharded trajectory matches the unsharded fused run up to fp
    reassociation — with bit-equal sent/failed accounting."""
    import warnings

    from gossipy_tpu.core import CreateModelMode
    from gossipy_tpu.models import LogisticRegression

    def build_fused(mesh=None, data=None):
        n_nodes, d = 16, 6
        rng = np.random.default_rng(2)
        X = rng.normal(size=(n_nodes * 12, d)).astype(np.float32)
        y = (X @ rng.normal(size=d) > 0).astype(np.int64)
        disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                              n=n_nodes)
        handler = SGDHandler(model=LogisticRegression(d, 2),
                             loss=losses.cross_entropy,
                             optimizer=optax.sgd(0.2), local_epochs=1,
                             batch_size=4, n_classes=2, input_shape=(d,),
                             create_model_mode=CreateModelMode.MERGE_UPDATE)
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=r"mailbox_slots=\d+ may overflow")
            return GossipSimulator(
                handler, Topology.clique(n_nodes),
                disp.stacked() if data is None else data, delta=10,
                protocol=AntiEntropyProtocol.PUSH, fused_merge="multi",
                mailbox_slots=4, mesh=mesh), disp

    sim, disp = build_fused()
    st = sim.init_nodes(key)
    fs, rep_plain = sim.start(st, n_rounds=4, key=jax.random.fold_in(key, 1))

    mesh = make_mesh(8)
    sim_sh, _ = build_fused(mesh=mesh, data=shard_data(disp.stacked(), mesh))
    st_sh = shard_state(sim_sh.init_nodes(key), mesh)
    fs_sh, rep_sh = sim_sh.start(st_sh, n_rounds=4,
                                 key=jax.random.fold_in(key, 1))

    for a, b in zip(jax.tree_util.tree_leaves(fs.model.params),
                    jax.tree_util.tree_leaves(fs_sh.model.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    assert rep_plain.sent_messages == rep_sh.sent_messages
    assert rep_plain.failed_messages == rep_sh.failed_messages


def test_2d_mesh_run_matches_unsharded(key):
    """(dcn, nodes) 2-D mesh: node axis sharded over hosts x chips."""
    from gossipy_tpu.parallel import make_mesh_2d
    sim, disp = build()
    st = sim.init_nodes(key)
    _, rep_plain = sim.start(st, n_rounds=3, key=jax.random.fold_in(key, 1))

    mesh = make_mesh_2d(n_hosts=2, devices_per_host=4)
    assert mesh.shape == {"dcn": 2, "nodes": 4}
    sim_sh, _ = build(data=shard_data(disp.stacked(), mesh))
    st_sh = shard_state(sim_sh.init_nodes(key), mesh)
    leaf = jax.tree_util.tree_leaves(st_sh.model.params)[0]
    assert len(leaf.sharding.device_set) == 8
    _, rep_sh = sim_sh.start(st_sh, n_rounds=3, key=jax.random.fold_in(key, 1))
    np.testing.assert_allclose(rep_plain.curves(local=False)["accuracy"],
                               rep_sh.curves(local=False)["accuracy"],
                               rtol=1e-4, atol=1e-5)


def test_tp_mesh_run_matches_unsharded(key):
    """(nodes, model) TP mesh: node axis DP x model-axis tensor parallelism."""
    from gossipy_tpu.parallel import make_mesh_tp
    sim, disp = build()
    st = sim.init_nodes(key)
    _, rep_plain = sim.start(st, n_rounds=3, key=jax.random.fold_in(key, 1))

    mesh = make_mesh_tp(4, 2)
    assert mesh.shape == {"nodes": 4, "model": 2}
    sim_sh, _ = build(data=shard_data(disp.stacked(), mesh))
    st_sh = shard_state(sim_sh.init_nodes(key), mesh)
    # The MLP hidden kernel [N, 6, 8] must carry the model axis on its
    # feature dimension; the node dimension stays on "nodes" alone.
    kernel = st_sh.model.params["Dense_0"]["kernel"]
    assert kernel.sharding.spec == ("nodes", None, "model")
    assert len(kernel.sharding.device_set) == 8
    _, rep_sh = sim_sh.start(st_sh, n_rounds=3, key=jax.random.fold_in(key, 1))
    np.testing.assert_allclose(rep_plain.curves(local=False)["accuracy"],
                               rep_sh.curves(local=False)["accuracy"],
                               rtol=1e-4, atol=1e-5)


def test_sim_save_load_roundtrip(tmp_path, key):
    sim, _ = build()
    st = sim.init_nodes(key)
    st, _ = sim.start(st, n_rounds=2, key=key)
    path = sim.save(str(tmp_path / "ck"), st, key=key)
    restored, rkey = sim.load(path, key)
    np.testing.assert_array_equal(np.asarray(key), np.asarray(rkey))
    for a, b in zip(jax.tree_util.tree_leaves(st),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_load_into_tp_mesh(tmp_path, key):
    """A checkpoint taken unsharded restores directly INTO a DP x TP mesh
    (values unchanged, placement per state_shardings) and the run continues
    with the same results as the unsharded continuation."""
    from gossipy_tpu.parallel import make_mesh_tp
    sim, disp = build()
    st = sim.init_nodes(key)
    st, _ = sim.start(st, n_rounds=2, key=key)
    path = sim.save(str(tmp_path / "ck"), st, key=key)
    _, rep_plain = sim.start(st, n_rounds=2, key=jax.random.fold_in(key, 9),
                             donate_state=False)

    mesh = make_mesh_tp(4, 2)
    sim_sh, _ = build(data=shard_data(disp.stacked(), mesh))
    restored, _ = sim_sh.load(path, key, mesh=mesh)
    kernel = restored.model.params["Dense_0"]["kernel"]
    assert kernel.sharding.spec == ("nodes", None, "model")
    for a, b in zip(jax.tree_util.tree_leaves(st),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    _, rep_sh = sim_sh.start(restored, n_rounds=2,
                             key=jax.random.fold_in(key, 9))
    np.testing.assert_allclose(rep_plain.curves(local=False)["accuracy"],
                               rep_sh.curves(local=False)["accuracy"],
                               rtol=1e-4, atol=1e-5)
