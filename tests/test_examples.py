"""Smoke tests for the reproduction scripts (examples/).

Each script must run end-to-end at tiny scale and print the one-line JSON
summary. Runs through subprocess with the CPU backend (examples default to
whatever backend the environment provides; tests must not depend on TPU)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

# Each case is a fresh interpreter + compile: the file costs ~5 min, so it
# runs in the opt-in `-m examples` lane (README "Running the tests").
pytestmark = pytest.mark.examples

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = [
    ("main_ormandi_2013.py", ["--nodes", "24", "--rounds", "2"]),
    ("main_danner_2023.py", ["--nodes", "12", "--rounds", "2"]),
    ("main_all2all.py", ["--nodes", "12", "--rounds", "2"]),
    ("main_cifar10_100nodes.py",
     ["--nodes", "4", "--rounds", "1", "--subsample", "400"]),
    # Round-3 (VERDICT weak #6): every reproduction script executes in CI.
    ("main_giaretta_2019.py",
     ["--nodes", "16", "--rounds", "2", "--variant", "passthrough"]),
    ("main_hegedus_2021.py", ["--nodes", "12", "--rounds", "2"]),
    ("main_hegedus_2020.py", ["--rounds", "2"]),
    ("main_berta_2014.py", ["--nodes", "24", "--rounds", "2"]),
    ("main_onoszko_2021.py",
     ["--nodes", "4", "--rounds", "1", "--subsample", "100",
      "--step1-rounds", "1"]),
    # Round-5: the bulk-vs-sequential fidelity audit workflow.
    ("audit_fidelity.py",
     ["--nodes", "8", "--rounds", "3", "--seeds", "1", "--tokenized"]),
]


def run_example(script, args, expect_json=True):
    from _virtual_mesh import virtual_mesh_env  # conftest puts REPO on sys.path
    env = virtual_mesh_env(1)  # CPU backend, TPU plugin stripped
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)] + args,
        capture_output=True, text=True, timeout=500, env=env, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    if not expect_json:
        return out.stdout
    last = [l for l in out.stdout.strip().splitlines() if l.startswith("{")][-1]
    return json.loads(last)


@pytest.mark.parametrize("script,args", CASES, ids=[c[0] for c in CASES])
def test_example_smoke(script, args):
    summary = run_example(script, args)
    assert summary["rounds"] >= 1
    assert "final" in summary
    assert all(np.isfinite(v) for v in summary["final"].values()), summary


def test_service_example_smoke(tmp_path):
    """The multi-tenant scheduler demo (its own summary shape: the
    script itself asserts bucket counts, solo parity and the eviction)."""
    summary = run_example(
        "main_service.py",
        ["--nodes", "16", "--rounds", "6", "--slice", "3",
         "--out", str(tmp_path)])
    assert summary["n_buckets"] == 2
    assert summary["megabatch_step_programs"] == 2
    assert summary["tenants"]["alice"]["status"] == "done"
    assert summary["tenants"]["mallory"]["status"] == "evicted"
    assert summary["tenants"]["mallory"]["bundle"]


def test_config_runner_smoke(tmp_path):
    """main_from_config runs an experiment from a JSON file end to end."""
    from gossipy_tpu.config import ExperimentConfig
    p = tmp_path / "tiny.json"
    ExperimentConfig(dataset="breast_cancer", n_nodes=8, delta=10,
                     topology="ring", topology_params={"k": 2},
                     batch_size=16, learning_rate=0.3,
                     n_rounds=3).to_json(str(p))
    summary = run_example("main_from_config.py", [str(p)])
    assert summary["rounds"] == 3 and summary["repetitions"] == 1
    assert np.isfinite(summary["final"]["accuracy"])


def test_ring_attention_demo_smoke():
    """The ring-attention training demo learns its retrieval task (the
    long-context consumer, VERDICT round-2 weak #5)."""
    out = run_example("demo_ring_attention.py",
                      ["--devices", "4", "--seq-len", "32", "--dim", "8",
                       "--steps", "30"])
    assert out["demo"] == "ring_attention_training"
    assert out["learned"] is True, out


def test_baseline_smoke():
    """baseline.py prints its own JSON (centralized quality anchors), not
    the standard summary line."""
    summary = run_example("baseline.py",
                          ["--rounds", "5", "--dataset", "breast"])
    for side in ("flax_mlp", "sklearn_mlp"):
        assert side in summary, summary
        assert np.isfinite(summary[side]["accuracy"])


def test_config_runner_recsys_reports_local_rmse(tmp_path):
    """A recsys config (user-wise evaluation only) must still report rounds
    and a final metric through main_from_config (regression: the runner
    printed rounds=0 reading the empty global curves)."""
    import dataclasses

    from gossipy_tpu.config import ExperimentConfig
    cfg = ExperimentConfig.from_json(
        os.path.join(REPO, "examples", "configs", "hegedus_2020.json"))
    p = tmp_path / "recsys_tiny.json"
    dataclasses.replace(cfg, n_rounds=2).to_json(str(p))
    summary = run_example("main_from_config.py", [str(p)])
    assert summary["rounds"] == 2
    assert np.isfinite(summary["final"]["rmse"])


def test_example_repetitions_smoke():
    """--repetitions runs the vmapped batch and reports mean finals."""
    summary = run_example("main_ormandi_2013.py",
                          ["--nodes", "16", "--rounds", "2",
                           "--repetitions", "3"])
    assert summary["repetitions"] == 3
    assert all(np.isfinite(v) for v in summary["final"].values()), summary
