"""Gossip-as-a-service: packer bucketing + scheduler end-to-end.

Covers the multi-tenant subsystem's two contracts:

- **Packing** (service/packer.py): runs fuse into one bucket exactly when
  their compiled-program shape signatures match — seeds, data values and
  fault rates may differ; population, model, wire format and topology
  content may not.
- **Scheduling** (service/scheduler.py): a bucket executes as ONE
  tenant-vmapped megabatch program whose per-tenant results equal the
  solo ``run_experiment`` trajectories; a tenant whose lane trips the
  numerics sentinels is evicted with a flight-recorder repro bundle
  (deterministically replayable) while its co-tenant finishes clean.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from gossipy_tpu.config import ExperimentConfig, run_experiment
from gossipy_tpu.service import (
    GossipService,
    RunQueue,
    RunRequest,
    RunStatus,
    build_request,
    pack,
)

D_FEATURES = 8


def tenant_data(seed: int, n: int = 240, d: int = D_FEATURES,
                poison: bool = False):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.int64)
    if poison:
        # Non-finite feature rows: the first local update propagates the
        # inf into that tenant's params, tripping the nonfinite sentinel.
        X[: n // 8] = np.inf
    return X, y


def base_cfg(**over) -> ExperimentConfig:
    base = dict(n_nodes=16, model="logreg", handler="sgd",
                topology="random_regular", topology_params={"degree": 4},
                delta=20, n_rounds=6, batch_size=8)
    base.update(over)
    return ExperimentConfig(**base)


def build(tenant: str, cfg: ExperimentConfig, data_seed: int = 1,
          poison: bool = False):
    return build_request(RunRequest(tenant, cfg,
                                    data=tenant_data(data_seed,
                                                     poison=poison)))


class TestPacker:
    def test_variable_fields_fuse(self):
        # Different seed, data values and fault rates: one bucket.
        built = [
            build("a", base_cfg(seed=1), data_seed=1),
            build("b", base_cfg(seed=2, drop_prob=0.2), data_seed=2),
            build("c", base_cfg(seed=3, online_prob=0.8, n_rounds=9),
                  data_seed=3),
        ]
        buckets = pack(built)
        assert len(buckets) == 1
        assert buckets[0].tenants == ["a", "b", "c"]
        assert len({r.signature.digest for r in built}) == 1

    def test_shape_fields_split(self):
        # Population, model and wire format each change the compiled
        # program: three more buckets.
        built = [
            build("a", base_cfg(seed=1)),
            build("n", base_cfg(seed=1, n_nodes=24), data_seed=2),
            build("m", base_cfg(seed=1, model="mlp"), data_seed=3),
            build("w", base_cfg(
                seed=1, simulator_params={"history_dtype": "bfloat16"}),
                data_seed=4),
        ]
        assert len(pack(built)) == 4

    def test_topology_content_splits(self):
        # Same builder kind, different degree: the closed-over adjacency
        # differs, so the runs must not share a program.
        built = [
            build("a", base_cfg(seed=1)),
            build("d", base_cfg(seed=1,
                                topology_params={"degree": 6}),
                  data_seed=2),
        ]
        assert len(pack(built)) == 2

    def test_data_shape_splits(self):
        # Same config shape fields, different stacked-data geometry
        # (bigger per-tenant shard): separate buckets.
        a = build("a", base_cfg(seed=1))
        b = build_request(RunRequest("big", base_cfg(seed=1),
                                     data=tenant_data(2, n=480)))
        assert len(pack([a, b])) == 2

    def test_sentinel_injection_in_signature(self):
        # The service injects sentinels=True; a tenant explicitly opting
        # OUT traces a different program and buckets apart.
        a = build("a", base_cfg(seed=1))
        assert a.sim.sentinels is not None
        off = build_request(RunRequest(
            "off", base_cfg(seed=1,
                            simulator_params={"sentinels": False}),
            data=tenant_data(2)))
        assert off.sim.sentinels is None
        assert len(pack([a, off])) == 2

    def test_unservable_simulators_rejected(self):
        for kind in ("sequential", "pens"):
            with pytest.raises(ValueError, match="cannot be served"):
                RunRequest("t", base_cfg(simulator=kind))

    def test_queue_rejects_duplicate_live_tenant(self):
        q = RunQueue()
        q.submit(RunRequest("t", base_cfg()))
        with pytest.raises(ValueError, match="already has"):
            q.submit(RunRequest("t", base_cfg(seed=2)))


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """One scheduler run shared by the e2e assertions: two same-bucket
    tenants — ``good`` and ``bad`` (poisoned data) — plus the solo
    reference trajectory for ``good``."""
    out = tmp_path_factory.mktemp("service")
    cfg_good = base_cfg(seed=1)
    cfg_bad = base_cfg(seed=2)
    q = RunQueue()
    h_good = q.submit(RunRequest("good", cfg_good, data=tenant_data(1)))
    h_bad = q.submit(RunRequest("bad", cfg_bad,
                                data=tenant_data(2, poison=True)))
    svc = GossipService(str(out), slice_rounds=4)
    summary = svc.serve(q)

    solo_cfg = dataclasses.replace(
        cfg_good, simulator_params={"sentinels": True})
    _, solo_report = run_experiment(solo_cfg, data=tenant_data(1))
    return {"out": str(out), "summary": summary, "good": h_good,
            "bad": h_bad, "cfg_bad": cfg_bad, "solo": solo_report}


class TestSchedulerE2E:
    def test_one_bucket_one_step_program(self, served):
        s = served["summary"]
        assert s["n_buckets"] == 1
        assert s["megabatch_step_programs"] == 1
        b = s["buckets"][0]
        assert sorted(b["tenants"]) == ["bad", "good"]
        # jit-cache proof: the shared step fn compiled exactly once.
        assert b["step_jit_cache_size"] in (1, None)
        assert "compilation_cache" in b

    def test_co_tenant_completes_clean_and_matches_solo(self, served):
        h = served["good"]
        assert h.status is RunStatus.DONE
        assert h.rounds_completed == 6
        rep = h.report
        assert int(np.sum(rep.health_trip)) == 0
        np.testing.assert_allclose(
            served["solo"].curves(local=False)["accuracy"],
            rep.curves(local=False)["accuracy"], atol=2e-5)
        np.testing.assert_array_equal(served["solo"].sent_per_round,
                                      rep.sent_per_round)

    def test_poisoned_tenant_evicted_with_bundle(self, served):
        h = served["bad"]
        assert h.status is RunStatus.EVICTED
        assert h.bundle_path is not None and os.path.isdir(h.bundle_path)
        with open(os.path.join(h.bundle_path, "verdict.json")) as fh:
            verdict = json.load(fh)
        assert verdict["kind"] == "sentinel"
        assert verdict["first_bad_round"] == 0
        assert verdict["detail"]["tenant"] == "bad"
        assert verdict["detail"]["nonfinite_params_total"] > 0
        # The truncated report stops at the tripped round.
        assert h.rounds_completed == 1
        assert int(np.asarray(h.report.health_trip)[-1]) > 0

    def test_per_tenant_artifacts(self, served):
        from gossipy_tpu.simulation.events import JSONLinesReceiver
        for name in ("good", "bad"):
            h = served[name]
            assert os.path.isfile(h.artifacts["report"])
            assert os.path.isfile(h.artifacts["manifest"])
            with open(h.artifacts["events"]) as fh:
                rows = [JSONLinesReceiver.parse_line(l) for l in fh]
            assert len(rows) == h.rounds_completed
            assert rows[0]["round"] == 1
            assert all(r["health"] is not None for r in rows)
        # The evicted tenant's last row carries the trip.
        assert rows[-1]["health"]["trip"] is True

    def test_per_tenant_manifest_attribution(self, served):
        with open(served["bad"].artifacts["manifest"]) as fh:
            m = json.load(fh)
        assert m["config"]["tenant"] == "bad"
        assert m["config"]["seed"] == 2
        svc = m["extra"]["service"]
        assert svc["bucket"] == served["summary"]["buckets"][0]["bucket"]
        assert sorted(svc["bucket_tenants"]) == ["bad", "good"]
        assert svc["status"] == "evicted"
        assert "bucket_compilation_cache" in svc
        assert "data_shapes" in svc["signature"]

    def test_bundle_replays_deterministically(self, served):
        # The bundle's lane checkpoint + the tenant's own config/data
        # rebuild the failure: replay names the recorded first bad round.
        from gossipy_tpu.config import build_experiment
        from gossipy_tpu.telemetry.health import replay_bundle
        cfg = dataclasses.replace(
            served["cfg_bad"], simulator_params={"sentinels": True})
        sim, _ = build_experiment(cfg, tenant_data(2, poison=True))
        verdict = replay_bundle(served["bad"].bundle_path, sim,
                                localize=False)
        assert verdict["first_bad_round"] == 0
        assert verdict["matches_recorded"] is True
        assert verdict["trip"] == "nonfinite"

    def test_tenant_tagged_sink_routing(self, served):
        from gossipy_tpu.telemetry import get_sink
        sink = get_sink()
        mine = sink.events(kind="round",
                           where=lambda e: e.data.get("tenant") == "good")
        if mine:  # ring may have been rotated by other tests
            assert all(e.data["tenant"] == "good" for e in mine)
        evs = sink.events(kind="tenant_evicted")
        assert any(e.data["tenant"] == "bad" for e in evs)
