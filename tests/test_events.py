"""Event-stream tests (reference Observer pattern, simul.py:37-177)."""

import numpy as np

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import PegasosHandler
from gossipy_tpu.models import AdaLine
from gossipy_tpu.simulation import GossipSimulator, SimulationEventReceiver


def make_sim(n_nodes=8, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=6)
    X = rng.normal(size=(160, 6)).astype(np.float32)
    y = (2 * (X @ w > 0) - 1).astype(np.float32)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=n_nodes)
    handler = PegasosHandler(AdaLine(6), learning_rate=0.01,
                             create_model_mode=CreateModelMode.UPDATE)
    return GossipSimulator(handler, Topology.clique(n_nodes), disp.stacked(),
                           delta=10, protocol=AntiEntropyProtocol.PUSH)


class Recorder(SimulationEventReceiver):
    def __init__(self, live=False):
        self.live = live
        self.rounds = []
        self.messages = []
        self.evals = []
        self.ended = 0

    def update_message(self, round, sent, failed, size):
        self.messages.append((round, sent, failed, size))

    def update_evaluation(self, round, on_user, metrics):
        self.evals.append((round, on_user, metrics))

    def update_timestep(self, round):
        self.rounds.append(round)

    def update_end(self):
        self.ended += 1


class TestReplayEvents:
    def test_rounds_and_totals_match_report(self, key):
        sim = make_sim()
        rec = Recorder()
        sim.add_receiver(rec)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=5, key=key)
        assert rec.rounds == [1, 2, 3, 4, 5]
        assert rec.ended == 1
        assert sum(m[1] for m in rec.messages) == report.sent_messages
        assert sum(m[3] for m in rec.messages) == report.total_size
        # Both local (on_user) and global evaluations stream through.
        assert any(e[1] for e in rec.evals) and any(not e[1] for e in rec.evals)

    def test_receivers_are_per_instance(self, key):
        # Reference quirk fixed: _receivers was a CLASS attribute shared by
        # all senders (simul.py:94); here each simulator owns its list.
        sim1, sim2 = make_sim(), make_sim()
        rec = Recorder()
        sim1.add_receiver(rec)
        assert sim2._receivers_list() == []

    def test_remove_receiver(self, key):
        sim = make_sim()
        rec = Recorder()
        sim.add_receiver(rec)
        sim.remove_receiver(rec)
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=2, key=key)
        assert rec.rounds == []

    def test_resumed_run_continues_round_numbers(self, key):
        sim = make_sim()
        rec = Recorder()
        sim.add_receiver(rec)
        st = sim.init_nodes(key)
        st, _ = sim.start(st, n_rounds=3, key=key)
        st, _ = sim.start(st, n_rounds=2, key=key)
        assert rec.rounds == [1, 2, 3, 4, 5]


class TestLiveEvents:
    def test_live_receiver_fires_during_run(self, key):
        sim = make_sim()
        rec = Recorder(live=True)
        sim.add_receiver(rec)
        st = sim.init_nodes(key)
        st, report = sim.start(st, n_rounds=4, key=key)
        assert rec.rounds == [1, 2, 3, 4]
        assert sum(m[1] for m in rec.messages) == report.sent_messages
        # Live receivers are not double-notified by the replay pass.
        assert len(rec.rounds) == 4
        assert rec.ended == 1

    def test_live_and_replay_coexist(self, key):
        sim = make_sim()
        live, replay = Recorder(live=True), Recorder()
        sim.add_receiver(live)
        sim.add_receiver(replay)
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=3, key=key)
        assert live.rounds == replay.rounds == [1, 2, 3]
        assert live.messages == replay.messages


class TestProfiler:
    def test_profile_dir_writes_trace(self, tmp_path, key):
        sim = make_sim()
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=2, key=key, profile_dir=str(tmp_path / "prof"))
        import os
        found = []
        for root, _, files in os.walk(tmp_path / "prof"):
            found.extend(files)
        assert found, "profiler trace produced no files"


def test_jsonlines_receiver_writes_rows(tmp_path, key):
    import json

    from gossipy_tpu.simulation import JSONLinesReceiver

    sim = make_sim()
    path = str(tmp_path / "metrics.jsonl")
    rec = JSONLinesReceiver(path)
    sim.add_receiver(rec)
    st = sim.init_nodes(key)
    st, report = sim.start(st, n_rounds=4, key=key)
    rec.close()
    rows = [json.loads(l) for l in open(path)]
    assert len(rows) == 4
    assert [r["round"] for r in rows] == [1, 2, 3, 4]
    assert sum(r["sent"] for r in rows) == report.sent_messages
    accs = [r["global"]["accuracy"] for r in rows]
    assert all(0.0 <= a <= 1.0 for a in accs)


def test_jsonlines_receiver_context_manager(tmp_path, key):
    import json

    from gossipy_tpu.simulation import JSONLinesReceiver

    sim = make_sim()
    path = str(tmp_path / "metrics.jsonl")
    with JSONLinesReceiver(path) as rec:
        sim.add_receiver(rec)
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=2, key=key)
    assert rec._fh.closed  # context exit closes the sink
    assert len([json.loads(l) for l in open(path)]) == 2


def test_live_falls_back_to_replay_without_host_callbacks(key, monkeypatch):
    """Backends without host send/recv (e.g. the tunneled TPU runtime) must
    not hang on live receivers: the engine falls back to post-run replay."""
    import warnings as _warnings

    from gossipy_tpu.simulation import engine as engine_mod

    monkeypatch.setattr(engine_mod, "_HOST_CALLBACKS_SUPPORTED", False)
    sim = make_sim()
    rec = Recorder(live=True)
    sim.add_receiver(rec)
    st = sim.init_nodes(key)
    with _warnings.catch_warnings(record=True) as w:
        _warnings.simplefilter("always")
        st, report = sim.start(st, n_rounds=3, key=key)
    assert any("live event receivers fall back" in str(x.message) for x in w)
    # Every event still arrived (replayed after the run).
    assert rec.rounds == [1, 2, 3]
