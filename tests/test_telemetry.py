"""Telemetry subsystem tests: traced failure causes, phase scopes, run
manifest, report serialization, event sink, and the live-delivery path."""

import json
import warnings

import jax
import numpy as np
import pytest

from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, Topology
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import PegasosHandler
from gossipy_tpu.models import AdaLine
from gossipy_tpu.simulation import (
    GossipSimulator,
    JSONLinesReceiver,
    ProgressReceiver,
    SequentialGossipSimulator,
    SimulationEventReceiver,
    SimulationReport,
)
from gossipy_tpu.telemetry import (
    FAILURE_CAUSES,
    ROUND_PHASES,
    FailureCounts,
    RunManifest,
    TelemetrySink,
    get_sink,
    phases_in_text,
    set_sink,
)

N_FAULTY = 100


def make_dataset(n_nodes, seed=0, n_samples=None):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=6)
    X = rng.normal(size=(n_samples or 20 * n_nodes, 6)).astype(np.float32)
    y = (2 * (X @ w > 0) - 1).astype(np.float32)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    return DataDispatcher(dh, n=n_nodes)


def make_handler():
    return PegasosHandler(AdaLine(6), learning_rate=0.01,
                          create_model_mode=CreateModelMode.UPDATE)


def faulty_sim(n_nodes=N_FAULTY, **kwargs):
    """The acceptance config: all three failure causes active (drop draw,
    offline receivers, and a 1-slot mailbox that must overflow at clique
    fan-in)."""
    kwargs.setdefault("drop_prob", 0.3)
    kwargs.setdefault("online_prob", 0.7)
    kwargs.setdefault("mailbox_slots", 1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # undersized mailbox is the point
        return GossipSimulator(make_handler(), Topology.clique(n_nodes),
                               make_dataset(n_nodes).stacked(), delta=10,
                               protocol=AntiEntropyProtocol.PUSH, **kwargs)


class TestFailureCounts:
    def test_elementwise_add_and_total(self):
        a = FailureCounts(1, 2, 3)
        b = FailureCounts(10, 20, 30)
        c = a + b
        assert (c.drop, c.offline, c.overflow) == (11, 22, 33)
        assert c.total() == 66
        assert sum([a, b]).total() == 66  # __radd__ supports sum()

    def test_cause_names(self):
        assert set(FailureCounts.zeros().as_dict()) == set(FAILURE_CAUSES)
        assert FAILURE_CAUSES == ("drop", "offline", "overflow")


class TestPerCauseCounters:
    def test_engine_causes_sum_to_failed_bitwise(self, key):
        """Acceptance: on a faulty 100-node config every cause array is
        nonzero where expected and the per-round cause sum equals the
        legacy ``failed`` array bit-for-bit."""
        sim = faulty_sim()
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=5, key=key)
        assert rep.failed_per_cause is not None
        assert set(rep.failed_per_cause) == set(FAILURE_CAUSES)
        total = sum(rep.failed_per_cause.values())
        np.testing.assert_array_equal(total, rep.failed_per_round)
        # All three causes fire under this config: drop_prob=0.3,
        # online_prob=0.7, and a 1-slot mailbox at clique fan-in.
        for cause in FAILURE_CAUSES:
            assert rep.failed_per_cause[cause].sum() > 0, cause

    def test_sequential_causes_sum_to_failed_bitwise(self, key):
        """The high-fidelity engine emits the same breakdown (overflow is
        structurally zero: its queues are unbounded, like the
        reference's)."""
        n = N_FAULTY
        sim = SequentialGossipSimulator(
            make_handler(), Topology.clique(n),
            make_dataset(n, n_samples=4 * n).stacked(), delta=4,
            drop_prob=0.3, online_prob=0.7)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=2, key=key)
        assert rep.failed_per_cause is not None
        total = sum(rep.failed_per_cause.values())
        np.testing.assert_array_equal(total, rep.failed_per_round)
        assert rep.failed_per_cause["drop"].sum() > 0
        assert rep.failed_per_cause["offline"].sum() > 0
        assert rep.failed_per_cause["overflow"].sum() == 0

    def test_no_fault_config_has_zero_causes(self, key):
        sim = faulty_sim(n_nodes=16, drop_prob=0.0, online_prob=1.0,
                         mailbox_slots=None)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=3, key=key)
        assert rep.failed_messages == 0
        for cause in FAILURE_CAUSES:
            assert rep.failed_per_cause[cause].sum() == 0

    def test_all2all_causes_sum_to_failed(self, key):
        import optax

        from gossipy_tpu.core import uniform_mixing
        from gossipy_tpu.handlers import WeightedSGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import All2AllGossipSimulator

        n = 12
        rng = np.random.default_rng(0)
        w = rng.normal(size=6)
        X = rng.normal(size=(20 * n, 6)).astype(np.float32)
        y = (X @ w > 0).astype(np.int64)
        disp = DataDispatcher(
            ClassificationDataHandler(X, y, test_size=0.25, seed=1), n=n,
            eval_on_user=False)
        handler = WeightedSGDHandler(
            model=LogisticRegression(6, 2), loss=losses.cross_entropy,
            optimizer=optax.sgd(0.1), local_epochs=1, batch_size=8,
            n_classes=2, input_shape=(6,),
            create_model_mode=CreateModelMode.MERGE_UPDATE)
        topo = Topology.random_regular(n, 4, seed=1)
        sim = All2AllGossipSimulator(handler, topo, disp.stacked(), delta=5,
                                     mixing=uniform_mixing(topo),
                                     drop_prob=0.2, online_prob=0.8)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=4, key=key)
        total = sum(rep.failed_per_cause.values())
        np.testing.assert_array_equal(total, rep.failed_per_round)
        assert rep.failed_per_cause["drop"].sum() > 0
        assert rep.failed_per_cause["offline"].sum() > 0
        assert rep.failed_per_cause["overflow"].sum() == 0


class TestRoundDiagnostics:
    def test_mailbox_hwm_bounded_by_slots(self, key):
        sim = faulty_sim(n_nodes=24, mailbox_slots=3)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=4, key=key)
        hwm = rep.mailbox_hwm_per_round
        assert hwm is not None and hwm.shape == (4,)
        assert (hwm >= 1).all()   # clique fan-in: someone always receives
        assert (hwm <= 3).all()   # bounded by the slot capacity

    def test_compact_wide_indicator(self, key):
        # Explicit small capacity: slot 0 (clique fan-in ~everyone)
        # overflows it and runs wide; higher slots run compact.
        sim = faulty_sim(n_nodes=32, drop_prob=0.0, online_prob=1.0,
                         mailbox_slots=4, compact_deliver=4)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=4, key=key)
        assert rep.compact_slots_per_round is not None
        assert (rep.wide_slots_per_round >= 1).all()  # slot 0 goes wide
        occupied = rep.compact_slots_per_round + rep.wide_slots_per_round
        assert (occupied >= 1).all() and (occupied <= 4 + 2).all()

    def test_wide_only_when_compaction_off(self, key):
        sim = faulty_sim(n_nodes=16, drop_prob=0.0, online_prob=1.0,
                         mailbox_slots=2, compact_deliver=False)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=3, key=key)
        assert (rep.compact_slots_per_round == 0).all()
        assert rep.wide_slots_per_round.sum() >= 1


class TestReport:
    def _report(self, **kw):
        defaults = dict(
            metric_names=["accuracy"],
            local_evals=None,
            global_evals=np.array([[0.5], [np.nan], [0.7]], np.float32),
            sent=np.array([3, 4, 5]), failed=np.array([1, 0, 2]),
            total_size=12)
        defaults.update(kw)
        return SimulationReport(**defaults)

    def test_final_unknown_metric_returns_nan(self):
        rep = self._report()
        assert np.isnan(rep.final("no_such_metric"))
        assert np.isnan(rep.final("no_such_metric", local=True))
        assert rep.final("accuracy") == pytest.approx(0.7)

    def test_to_dict_is_strict_json(self, tmp_path):
        rep = self._report(failed_by_cause={
            "drop": np.array([1, 0, 1]), "offline": np.array([0, 0, 1]),
            "overflow": np.array([0, 0, 0])})
        d = rep.to_dict()
        # allow_nan=False: NaN eval rows must have become nulls.
        text = json.dumps(d, allow_nan=False)
        back = json.loads(text)
        assert back["schema"] == 7  # v7: + cohort arrays (absent when off)
        assert back["global_evals"][1] == [None]
        assert back["failed_per_cause"]["drop"] == [1, 0, 1]
        path = rep.save(str(tmp_path / "report.json"))
        assert json.load(open(path))["sent_per_round"] == [3, 4, 5]

    def test_concatenate_preserves_causes(self):
        a = self._report(failed_by_cause={
            "drop": np.array([1, 0, 1]), "offline": np.array([0, 0, 1]),
            "overflow": np.array([0, 0, 0])})
        b = self._report(failed_by_cause={
            "drop": np.array([2, 2, 0]), "offline": np.array([0, 1, 0]),
            "overflow": np.array([1, 0, 0])})
        cat = SimulationReport.concatenate([a, b])
        assert cat.sent_per_round.shape == (6,)
        assert cat.failed_per_cause["drop"].tolist() == [1, 0, 1, 2, 2, 0]
        assert cat.total_size == 24
        # A segment without causes drops the breakdown rather than lying.
        c = self._report()
        assert SimulationReport.concatenate([a, c]).failed_per_cause is None

    def test_attach_wall_clock_ema_skips_cold_round(self):
        rep = self._report()
        # Round 1 took 10 s (compile), rounds 2-3 took 0.1 s each.
        rep.attach_wall_clock(0.0, [10.0, 10.1, 10.2])
        assert rep.wall_clock_seconds_per_round == pytest.approx(
            [10.0, 0.1, 0.1])
        assert rep.rounds_per_sec_ema == pytest.approx(10.0, rel=1e-3)


class TestPhaseScopes:
    def test_compiled_hlo_contains_all_four_scopes(self, key):
        from gossipy_tpu.analysis import compiled_text
        sim = faulty_sim(n_nodes=12, drop_prob=0.0, online_prob=1.0,
                         mailbox_slots=2)
        st = sim.init_nodes(key)
        txt = compiled_text(sim, st, key, n_rounds=2)
        assert phases_in_text(txt) == list(ROUND_PHASES)

    def test_profiler_trace_contains_scopes(self, tmp_path, key):
        """Acceptance: an XProf trace captured via profile_dir= carries the
        named phase scopes."""
        from gossipy_tpu.telemetry import phases_in_trace_dir
        sim = faulty_sim(n_nodes=12, drop_prob=0.0, online_prob=1.0,
                         mailbox_slots=2)
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=2, key=key,
                  profile_dir=str(tmp_path / "prof"))
        found = phases_in_trace_dir(str(tmp_path / "prof"))
        assert found == list(ROUND_PHASES), found


class TestRunManifest:
    def test_from_simulator_collects_config(self, key):
        sim = faulty_sim(n_nodes=16)
        man = sim.run_manifest(extra={"note": "test"})
        d = man.to_dict()
        assert d["schema"] == 1
        assert d["config"]["n_nodes"] == 16
        assert d["config"]["protocol"] == "PUSH"
        assert d["config"]["drop_prob"] == pytest.approx(0.3)
        assert d["backend"]["backend"] == "cpu"
        assert d["versions"]["jax"] == jax.__version__
        assert d["memory_budget"]["total_bytes"] > 0
        assert d["extra"] == {"note": "test"}
        # Repo checkouts have a git rev; the field is best-effort elsewhere.
        assert d["git_rev"] is None or isinstance(d["git_rev"], str)
        json.dumps(d, allow_nan=False)  # strict JSON

    def test_compile_seconds_recorded_after_cold_start(self, key, tmp_path):
        sim = faulty_sim(n_nodes=12)
        assert sim.last_compile_seconds is None
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=2, key=key)
        assert sim.last_compile_seconds is not None
        assert sim.last_compile_seconds > 0
        man = RunManifest.from_simulator(sim)
        assert man.compile_seconds == sim.last_compile_seconds
        path = man.save(str(tmp_path / "manifest.json"))
        assert json.load(open(path))["config"]["n_nodes"] == 12


class TestTelemetrySink:
    def test_mailbox_undersized_emits_event(self):
        prev = set_sink(TelemetrySink())
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                faulty_sim(n_nodes=32, mailbox_slots=1)
            evs = get_sink().events(kind="mailbox_undersized")
            assert len(evs) == 1
            assert evs[0].data["mailbox_slots"] == 1
            assert evs[0].data["p_overflow_per_node_round"] > 1e-3
        finally:
            set_sink(prev)

    def test_sink_jsonl_mirror_and_ring(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        sink = TelemetrySink(maxlen=2, jsonl_path=path)
        for i in range(3):
            sink.emit("k", {"i": i})
        sink.close()
        assert [e.data["i"] for e in sink.events()] == [1, 2]  # ring bound
        assert sink.dropped_events == 1  # the ring evicted i=0
        rows = [json.loads(l) for l in open(path)]
        # The mirror keeps every emitted line, and close() appends one
        # sink_closed record of the ring's loss.
        assert [r["data"]["i"] for r in rows[:3]] == [0, 1, 2]
        assert rows[-1]["kind"] == "sink_closed"
        assert rows[-1]["data"]["dropped_events"] == 1


class Recorder(SimulationEventReceiver):
    def __init__(self, live=False):
        self.live = live
        self.rounds = []
        self.causes = []
        self.messages = []

    def update_message(self, round, sent, failed, size):
        self.messages.append((round, sent, failed))

    def update_failure_causes(self, round, causes):
        self.causes.append((round, dict(causes)))

    def update_timestep(self, round):
        self.rounds.append(round)


class TestLiveDelivery:
    """End-to-end coverage of the live io_callback path (satellite)."""

    def test_live_receiver_sees_every_round_in_order(self, key):
        sim = faulty_sim(n_nodes=12)
        rec = Recorder(live=True)
        sim.add_receiver(rec)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=6, key=key)
        assert rec.rounds == [1, 2, 3, 4, 5, 6]       # every round, in order
        assert len(rec.rounds) == 6                    # no double delivery
        # Causes stream live and match the report's arrays per round.
        assert [r for r, _ in rec.causes] == [1, 2, 3, 4, 5, 6]
        for i, (_, causes) in enumerate(rec.causes):
            assert causes["drop"] == rep.failed_per_cause["drop"][i]
            assert sum(causes.values()) == rep.failed_per_round[i]

    def test_replay_does_not_double_deliver_to_live(self, key):
        sim = faulty_sim(n_nodes=12)
        live, replay = Recorder(live=True), Recorder()
        sim.add_receiver(live)
        sim.add_receiver(replay)
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=4, key=key)
        assert live.rounds == replay.rounds == [1, 2, 3, 4]
        assert live.messages == replay.messages
        assert live.causes == replay.causes

    def test_live_run_attaches_wall_clock(self, key):
        sim = faulty_sim(n_nodes=12)
        sim.add_receiver(Recorder(live=True))
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=5, key=key)
        assert rep.wall_clock_seconds_per_round is not None
        assert rep.wall_clock_seconds_per_round.shape == (5,)
        assert rep.rounds_per_sec_ema > 0

    def test_non_live_run_has_no_wall_clock(self, key):
        sim = faulty_sim(n_nodes=12)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=3, key=key)
        assert rep.wall_clock_seconds_per_round is None
        assert rep.rounds_per_sec_ema is None


class TestReceivers:
    def test_jsonl_rows_carry_schema_and_causes(self, tmp_path, key):
        sim = faulty_sim(n_nodes=12)
        path = str(tmp_path / "m.jsonl")
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rx)
            st = sim.init_nodes(key)
            st, rep = sim.start(st, n_rounds=4, key=key)
        rows = [json.loads(l) for l in open(path)]
        assert len(rows) == 4
        for i, row in enumerate(rows):
            assert row["schema"] == 8  # v8: + "cohort" (null when off)
            assert set(row["failed_by_cause"]) == set(FAILURE_CAUSES)
            assert sum(row["failed_by_cause"].values()) == row["failed"]
            assert row["failed"] == rep.failed_per_round[i]

    def test_progress_line_shows_throughput_and_fail_rate(self, key,
                                                          capsys):
        sim = faulty_sim(n_nodes=12)
        sim.add_receiver(ProgressReceiver(every=2, metric="accuracy"))
        st = sim.init_nodes(key)
        sim.start(st, n_rounds=4, key=key)
        out = capsys.readouterr().out
        lines = [l for l in out.splitlines() if l.startswith("[round")]
        assert len(lines) == 2
        assert "r/s" in lines[0] and "failed" in lines[0]
        assert "%" in lines[0]
