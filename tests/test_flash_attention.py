"""Flash-attention pallas kernel vs the jnp formulation (interpreter mode).

The kernel's claim is layout, not math: identical blockwise-softmax update
with the score block VMEM-resident. So every test is an equality against
the dense/jnp reference — forward, gradients, causal masking by global
position, query-row padding, and the ring integration.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossipy_tpu.ops.attention import flash_attention, flash_hop_update, \
    hop_update_reference


def dense_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = (q.astype(jnp.float32) @ k.astype(jnp.float32).T) / np.sqrt(d)
    if causal:
        i = jnp.arange(q.shape[0])[:, None]
        j = jnp.arange(k.shape[0])[None, :]
        s = jnp.where(j > i, -1e30, s)
    p = jax.nn.softmax(s, axis=-1)
    return (p @ v.astype(jnp.float32)).astype(q.dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype", [
    jnp.float32,
    pytest.param(jnp.bfloat16, marks=pytest.mark.slow),  # lane budget
])
def test_flash_matches_dense(causal, dtype):
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    S, D = 32, 16
    q = jax.random.normal(kq, (S, D), dtype)
    k = jax.random.normal(kk, (S, D), dtype)
    v = jax.random.normal(kv, (S, D), dtype)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = dense_attention(q, k, v, causal=causal)
    tol = 1e-5 if dtype == jnp.float32 else 3e-2
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=tol)


def test_flash_pads_query_rows():
    """sl not divisible by block_q: padded rows must not leak into output."""
    key = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(key, 3)
    S, D = 24, 8  # block_q=16 -> one padded block of 8 rows
    q = jax.random.normal(kq, (S, D))
    k = jax.random.normal(kk, (S, D))
    v = jax.random.normal(kv, (S, D))
    out = flash_attention(q, k, v, causal=True, interpret=True, block_q=16)
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow  # lane budget; the ragged-tail math is the same path
@pytest.mark.parametrize("causal", [False, True])
def test_flash_tiles_and_pads_key_blocks(causal):
    """block_k < S with a ragged tail (24 = 16 + 8 padded) must stream the
    carry through VMEM scratch across k blocks without the padded tail
    poisoning the statistics."""
    key = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(key, 3)
    S, D = 24, 8
    q = jax.random.normal(kq, (S, D))
    k = jax.random.normal(kk, (S, D))
    v = jax.random.normal(kv, (S, D))
    out = flash_attention(q, k, v, causal=causal, interpret=True,
                          block_q=8, block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_hop_update_matches_reference_mid_stream():
    """A hop with a NON-initial carry (mid-ring state) must rescale the
    incoming statistics exactly like the jnp body."""
    key = jax.random.PRNGKey(2)
    ks = jax.random.split(key, 6)
    sl, D = 16, 8
    q = jax.random.normal(ks[0], (sl, D))
    k_c = jax.random.normal(ks[1], (sl, D))
    v_c = jax.random.normal(ks[2], (sl, D))
    m = jax.random.normal(ks[3], (sl,))
    l = jax.nn.softplus(jax.random.normal(ks[4], (sl,)))
    acc = jax.random.normal(ks[5], (sl, D))
    scale = 1.0 / np.sqrt(D)
    got = flash_hop_update(q, k_c, v_c, m, l, acc, 16, 32, scale,
                           causal=True, interpret=True)
    want = hop_update_reference(q, k_c, v_c, m, l, acc, 16, 32, scale,
                                causal=True)
    for g, w in zip(got, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-5)


@pytest.mark.slow  # lane budget; the slow ring test covers grads too
@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    """The custom vjp (recompute backward) must match autodiff through the
    dense formulation."""
    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    S, D = 16, 8
    q = jax.random.normal(kq, (S, D))
    k = jax.random.normal(kk, (S, D))
    v = jax.random.normal(kv, (S, D))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal,
                                interpret=True) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=causal) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=1e-4)


@pytest.mark.slow
def test_ring_attention_flash_equals_jnp_path():
    """ring_attention(flash=True) — the kernel per hop, under shard_map on
    the virtual mesh — must equal the inline-jnp path, values and grads.
    (~1 min: interpreter-mode kernel grads under the ring scan; slow
    lane.)"""
    from gossipy_tpu.parallel import make_mesh
    from gossipy_tpu.parallel.collectives import ring_attention

    mesh = make_mesh(4)
    key = jax.random.PRNGKey(4)
    kq, kk, kv = jax.random.split(key, 3)
    S, D = 32, 8
    q = jax.random.normal(kq, (S, D))
    k = jax.random.normal(kk, (S, D))
    v = jax.random.normal(kv, (S, D))

    for causal in (False, True):
        a = ring_attention(q, k, v, mesh, causal=causal, flash=True)
        b = ring_attention(q, k, v, mesh, causal=causal, flash=False)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    def loss(fn_flash):
        def f(q, k, v):
            return (ring_attention(q, k, v, mesh, causal=True,
                                   flash=fn_flash) ** 2).sum()
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    for gf, gj in zip(loss(True), loss(False)):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gj),
                                   atol=1e-4)

    # Heads axis via vmap (the documented multi-head pattern) over the
    # kernel path.
    H = 3
    qh, kh, vh = (jax.random.normal(key, (3, H, S, D)))
    ah = jax.vmap(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True,
                                                 flash=True))(qh, kh, vh)
    bh = jax.vmap(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True,
                                                 flash=False))(qh, kh, vh)
    np.testing.assert_allclose(np.asarray(ah), np.asarray(bh), atol=1e-5)
