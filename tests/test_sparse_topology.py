"""SparseTopology (CSR neighbor lists) and the native edge-list generators.

The scale-breaking representation: O(E) memory where the dense Topology —
and the reference's StaticP2PNetwork (core.py:311-361) — need O(N^2).
"""

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu import native
from gossipy_tpu.core import AntiEntropyProtocol, SparseTopology, Topology

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native graphgen unavailable")


def canon_set(edges):
    return {tuple(sorted(p)) for p in np.asarray(edges).tolist()}


class TestEdgeGenerators:
    def test_random_regular_degrees_and_simplicity(self):
        e = native.random_regular_edges(600, 8, seed=1)
        assert e.shape == (600 * 8 // 2, 2)
        deg = np.bincount(np.concatenate([e[:, 0], e[:, 1]]), minlength=600)
        assert (deg == 8).all()
        assert (e[:, 0] != e[:, 1]).all()
        assert len(canon_set(e)) == len(e)  # no duplicate edges

    def test_random_regular_reproducible_per_seed(self):
        a = native.random_regular_edges(200, 4, seed=7)
        b = native.random_regular_edges(200, 4, seed=7)
        c = native.random_regular_edges(200, 4, seed=8)
        assert (a == b).all()
        assert canon_set(a) != canon_set(c)

    def test_random_regular_invalid(self):
        with pytest.raises(ValueError):
            native.random_regular_edges(5, 3, seed=0)  # n*k odd

    def test_erdos_renyi_count_and_simplicity(self):
        e = native.erdos_renyi_edges(1500, 0.01, seed=2)
        exp = 0.01 * 1500 * 1499 / 2
        assert abs(len(e) - exp) < 6 * np.sqrt(exp)
        assert (e[:, 0] < e[:, 1]).all()  # upper triangle, so simple
        assert len(canon_set(e)) == len(e)

    def test_barabasi_albert_edge_count(self):
        n, m = 1000, 5
        e = native.barabasi_albert_edges(n, m, seed=3)
        assert len(e) == m * (n - m - 1) + m
        assert len(canon_set(e)) == len(e)
        deg = np.bincount(np.concatenate([e[:, 0], e[:, 1]]), minlength=n)
        assert (deg >= 1).all()  # connected seed star reaches everyone
        assert deg.max() > 3 * m  # hubs exist (preferential attachment)


class TestSparseTopology:
    def test_dense_roundtrip(self):
        t = Topology.ring(64, k=2)
        sp = SparseTopology.from_dense(t)
        assert (sp.to_dense().adjacency == t.adjacency).all()
        assert (sp.degrees == t.degrees).all()
        assert sp.get_peers(5) == t.get_peers(5)
        assert sp.size() == 64 and sp.size(5) == t.size(5)

    def test_sparse_ring_matches_dense_ring(self):
        for n, k in [(9, 2), (10, 5), (64, 3)]:
            sp = SparseTopology.ring(n, k)
            assert (sp.to_dense().adjacency ==
                    Topology.ring(n, k).adjacency).all(), (n, k)

    def test_sample_peers_valid_and_isolated_minus_one(self, key):
        edges = np.array([[0, 1], [1, 2], [2, 0]])  # node 3 isolated
        sp = SparseTopology(4, edges)
        peers = np.asarray(sp.sample_peers(key))
        nbr = [set(sp.get_peers(i)) for i in range(4)]
        assert all(int(peers[i]) in nbr[i] for i in range(3))
        assert peers[3] == -1

    def test_sample_peers_roughly_uniform(self, key):
        sp = SparseTopology(5, np.array([[0, 1], [0, 2], [0, 3], [0, 4]]))
        draws = jax.vmap(sp.sample_peers)(jax.random.split(key, 800))
        counts = np.bincount(np.asarray(draws)[:, 0], minlength=5)[1:]
        assert counts.min() > 100  # ~200 each; any missing arm would be 0

    def test_dense_feature_raises_clearly(self):
        sp = SparseTopology.ring(8, 1)
        with pytest.raises(AttributeError, match="dense"):
            _ = sp.adjacency
        with pytest.raises(AttributeError, match="dense"):
            _ = sp.adjacency_dev

    def test_scale_50k_is_cheap(self):
        sp = SparseTopology.random_regular(50_000, 20, seed=42)
        assert (sp.degrees == 20).all()
        # O(E) footprint: 2E int32 indices = 4 MB (dense would be 2.5 GB).
        assert sp.indices.nbytes == 50_000 * 20 * 4


class TestEngineOnSparse:
    def test_gossip_learns_on_sparse_topology(self, key):
        from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
        from gossipy_tpu.handlers import SGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import GossipSimulator

        rng = np.random.default_rng(0)
        d, n = 8, 32
        w = rng.normal(size=d)
        X = rng.normal(size=(n * 12, d)).astype(np.float32)
        y = (X @ w > 0).astype(np.int64)
        disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                              n=n)
        h = SGDHandler(model=LogisticRegression(d, 2),
                       loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                       local_epochs=1, batch_size=8, n_classes=2,
                       input_shape=(d,))
        sim = GossipSimulator(h, SparseTopology.random_regular(n, 6, seed=1),
                              disp.stacked(), delta=10,
                              protocol=AntiEntropyProtocol.PUSH)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=15)
        assert rep.curves(local=False)["accuracy"][-1] > 0.8
