"""SparseTopology (CSR neighbor lists) and the native edge-list generators.

The scale-breaking representation: O(E) memory where the dense Topology —
and the reference's StaticP2PNetwork (core.py:311-361) — need O(N^2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gossipy_tpu import native
from gossipy_tpu.core import AntiEntropyProtocol, SparseTopology, Topology

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native graphgen unavailable")


def canon_set(edges):
    return {tuple(sorted(p)) for p in np.asarray(edges).tolist()}


class TestEdgeGenerators:
    def test_random_regular_degrees_and_simplicity(self):
        e = native.random_regular_edges(600, 8, seed=1)
        assert e.shape == (600 * 8 // 2, 2)
        deg = np.bincount(np.concatenate([e[:, 0], e[:, 1]]), minlength=600)
        assert (deg == 8).all()
        assert (e[:, 0] != e[:, 1]).all()
        assert len(canon_set(e)) == len(e)  # no duplicate edges

    def test_random_regular_reproducible_per_seed(self):
        a = native.random_regular_edges(200, 4, seed=7)
        b = native.random_regular_edges(200, 4, seed=7)
        c = native.random_regular_edges(200, 4, seed=8)
        assert (a == b).all()
        assert canon_set(a) != canon_set(c)

    def test_random_regular_invalid(self):
        with pytest.raises(ValueError):
            native.random_regular_edges(5, 3, seed=0)  # n*k odd

    def test_erdos_renyi_count_and_simplicity(self):
        e = native.erdos_renyi_edges(1500, 0.01, seed=2)
        exp = 0.01 * 1500 * 1499 / 2
        assert abs(len(e) - exp) < 6 * np.sqrt(exp)
        assert (e[:, 0] < e[:, 1]).all()  # upper triangle, so simple
        assert len(canon_set(e)) == len(e)

    def test_barabasi_albert_edge_count(self):
        n, m = 1000, 5
        e = native.barabasi_albert_edges(n, m, seed=3)
        assert len(e) == m * (n - m - 1) + m
        assert len(canon_set(e)) == len(e)
        deg = np.bincount(np.concatenate([e[:, 0], e[:, 1]]), minlength=n)
        assert (deg >= 1).all()  # connected seed star reaches everyone
        assert deg.max() > 3 * m  # hubs exist (preferential attachment)


class TestSparseTopology:
    def test_dense_roundtrip(self):
        t = Topology.ring(64, k=2)
        sp = SparseTopology.from_dense(t)
        assert (sp.to_dense().adjacency == t.adjacency).all()
        assert (sp.degrees == t.degrees).all()
        assert sp.get_peers(5) == t.get_peers(5)
        assert sp.size() == 64 and sp.size(5) == t.size(5)

    def test_sparse_ring_matches_dense_ring(self):
        for n, k in [(9, 2), (10, 5), (64, 3)]:
            sp = SparseTopology.ring(n, k)
            assert (sp.to_dense().adjacency ==
                    Topology.ring(n, k).adjacency).all(), (n, k)

    def test_sample_peers_valid_and_isolated_minus_one(self, key):
        edges = np.array([[0, 1], [1, 2], [2, 0]])  # node 3 isolated
        sp = SparseTopology(4, edges)
        peers = np.asarray(sp.sample_peers(key))
        nbr = [set(sp.get_peers(i)) for i in range(4)]
        assert all(int(peers[i]) in nbr[i] for i in range(3))
        assert peers[3] == -1

    def test_sample_peers_roughly_uniform(self, key):
        sp = SparseTopology(5, np.array([[0, 1], [0, 2], [0, 3], [0, 4]]))
        draws = jax.vmap(sp.sample_peers)(jax.random.split(key, 800))
        counts = np.bincount(np.asarray(draws)[:, 0], minlength=5)[1:]
        assert counts.min() > 100  # ~200 each; any missing arm would be 0

    def test_dense_feature_raises_clearly(self):
        sp = SparseTopology.ring(8, 1)
        with pytest.raises(AttributeError, match="dense"):
            _ = sp.adjacency
        with pytest.raises(AttributeError, match="dense"):
            _ = sp.adjacency_dev

    def test_scale_50k_is_cheap(self):
        sp = SparseTopology.random_regular(50_000, 20, seed=42)
        assert (sp.degrees == 20).all()
        # O(E) footprint: 2E int32 indices = 4 MB (dense would be 2.5 GB).
        assert sp.indices.nbytes == 50_000 * 20 * 4


class TestEngineOnSparse:
    def test_gossip_learns_on_sparse_topology(self, key):
        from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
        from gossipy_tpu.handlers import SGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import GossipSimulator

        rng = np.random.default_rng(0)
        d, n = 8, 32
        w = rng.normal(size=d)
        X = rng.normal(size=(n * 12, d)).astype(np.float32)
        y = (X @ w > 0).astype(np.int64)
        disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                              n=n)
        h = SGDHandler(model=LogisticRegression(d, 2),
                       loss=losses.cross_entropy, optimizer=optax.sgd(0.5),
                       local_epochs=1, batch_size=8, n_classes=2,
                       input_shape=(d,))
        sim = GossipSimulator(h, SparseTopology.random_regular(n, 6, seed=1),
                              disp.stacked(), delta=10,
                              protocol=AntiEntropyProtocol.PUSH)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=15)
        assert rep.curves(local=False)["accuracy"][-1] > 0.8


def _logreg_setup(n=24, d=8, seed=0, samples_per_node=12):
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import losses
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    X = rng.normal(size=(n * samples_per_node, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                          n=n)
    return disp, d


class TestSparseMixing:
    """O(E) mixing weights + the segment-sum All2All merge (round-3: the
    Koloskova variant past the dense wall, VERDICT next #5)."""

    def _topos(self, n=24, degree=6):
        dense = Topology.random_regular(n, degree, seed=3)
        return dense, SparseTopology.from_dense(dense)

    def test_uniform_weights_match_dense(self):
        from gossipy_tpu.core import SparseMixing, uniform_mixing
        dense, sparse = self._topos()
        wd = np.asarray(uniform_mixing(dense))
        ws = uniform_mixing(sparse)
        assert isinstance(ws, SparseMixing)
        np.testing.assert_allclose(np.asarray(ws.self_w), np.diag(wd),
                                   rtol=1e-6)
        got = np.zeros_like(wd)
        got[np.asarray(ws.rows), np.asarray(ws.senders)] = \
            np.asarray(ws.edge_w)
        np.fill_diagonal(got, np.diag(wd))
        np.testing.assert_allclose(got, wd, rtol=1e-6)

    def test_metropolis_weights_match_dense(self):
        from gossipy_tpu.core import metropolis_hastings_mixing
        dense, sparse = self._topos()
        wd = np.asarray(metropolis_hastings_mixing(dense))
        ws = metropolis_hastings_mixing(sparse)
        got = np.zeros_like(wd)
        got[np.asarray(ws.rows), np.asarray(ws.senders)] = \
            np.asarray(ws.edge_w)
        np.fill_diagonal(got, np.asarray(ws.self_w))
        np.testing.assert_allclose(got, wd, rtol=1e-6, atol=1e-7)

    def test_all2all_sparse_equals_dense(self, key):
        """Same config, no faults: the segment-sum path must produce the
        same simulation as the dense einsum (summation order differs ->
        allclose, not equal)."""
        import optax as _optax
        from gossipy_tpu.core import CreateModelMode, uniform_mixing
        from gossipy_tpu.handlers import WeightedSGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import All2AllGossipSimulator
        from gossipy_tpu.utils import params_allclose

        dense, sparse = self._topos()
        disp, d = _logreg_setup(n=dense.num_nodes)
        h = WeightedSGDHandler(model=LogisticRegression(d, 2),
                               loss=losses.cross_entropy,
                               optimizer=_optax.sgd(0.3), local_epochs=1,
                               batch_size=8, n_classes=2, input_shape=(d,),
                               create_model_mode=CreateModelMode.MERGE_UPDATE)
        results = []
        for topo in (dense, sparse):
            sim = All2AllGossipSimulator(h, topo, disp.stacked(), delta=8,
                                         mixing=uniform_mixing(topo))
            st = sim.init_nodes(key)
            st, rep = sim.start(st, n_rounds=4, key=jax.random.PRNGKey(5))
            results.append((st, rep.curves(local=False)["accuracy"][-1]))
        (s_dense, acc_d), (s_sparse, acc_s) = results
        assert params_allclose(s_dense.model.params, s_sparse.model.params,
                               atol=1e-4)
        assert abs(acc_d - acc_s) < 1e-6

    def test_all2all_sparse_with_faults_learns(self, key):
        """Drop/churn on the sparse path: edge-wise Bernoulli gates keep the
        mix a convex combination (row renormalization) and learning still
        proceeds."""
        import optax as _optax
        from gossipy_tpu.core import CreateModelMode, uniform_mixing
        from gossipy_tpu.handlers import WeightedSGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import All2AllGossipSimulator

        sparse = SparseTopology.random_regular(24, 6, seed=9)
        disp, d = _logreg_setup(n=24)
        h = WeightedSGDHandler(model=LogisticRegression(d, 2),
                               loss=losses.cross_entropy,
                               optimizer=_optax.sgd(0.5), local_epochs=1,
                               batch_size=8, n_classes=2, input_shape=(d,),
                               create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim = All2AllGossipSimulator(h, sparse, disp.stacked(), delta=8,
                                     mixing=uniform_mixing(sparse),
                                     drop_prob=0.2, online_prob=0.8)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=12, key=jax.random.PRNGKey(6))
        acc = rep.curves(local=False)["accuracy"][-1]
        assert np.isfinite(acc) and acc > 0.75

    def test_sparse_mixing_scale_50k_construction(self):
        """The O(E) objects at 50k nodes: mixing build is sub-second and
        carries 2E edge weights, no [N, N] anywhere."""
        import time
        from gossipy_tpu.core import SparseMixing, uniform_mixing
        n, deg = 50_000, 20
        topo = SparseTopology.random_regular(n, deg, seed=1)
        t0 = time.perf_counter()
        mix = uniform_mixing(topo)
        dt = time.perf_counter() - t0
        assert isinstance(mix, SparseMixing)
        assert mix.edge_w.shape == (n * deg,)
        assert dt < 5.0


class TestCacheNeighOnSparse:
    def _sim(self, topo, n=16):
        import optax as _optax
        from gossipy_tpu.core import CreateModelMode
        from gossipy_tpu.handlers import SGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import CacheNeighGossipSimulator

        disp, d = _logreg_setup(n=n)
        h = SGDHandler(model=LogisticRegression(d, 2),
                       loss=losses.cross_entropy, optimizer=_optax.sgd(0.3),
                       local_epochs=1, batch_size=8, n_classes=2,
                       input_shape=(d,),
                       create_model_mode=CreateModelMode.MERGE_UPDATE)
        return CacheNeighGossipSimulator(h, topo, disp.stacked(), delta=8)

    def test_neighbor_table_matches_dense(self):
        """The padded [N, max_deg] slot layout is identical for a dense
        Topology and its CSR view (both sorted neighbor order) — no [N, N]
        slot table exists on either path. (Exact run equality between the
        two is not expected: peer SAMPLING consumes differently-shaped RNG
        draws per topology representation.)"""
        dense = Topology.random_regular(16, 4, seed=2)
        sparse = SparseTopology.from_dense(dense)
        sd = self._sim(dense)
        ss = self._sim(sparse)
        np.testing.assert_array_equal(np.asarray(sd.nbr_table),
                                      np.asarray(ss.nbr_table))
        assert sd.nbr_table.shape == (16, 4)

    def test_parking_slots_by_sender(self, key):
        """_apply_receive parks a peer model in the sender's slot; a sender
        that is not a neighbor parks nothing."""
        from gossipy_tpu.simulation.engine import PeerModel

        dense = Topology.random_regular(12, 4, seed=6)
        sim = self._sim(SparseTopology.from_dense(dense), n=12)
        st = sim.init_nodes(key)
        n = 12
        peer = PeerModel(st.model.params, st.model.n_updates)
        # Every node claims sender = its own first neighbor.
        senders = np.asarray(sim.nbr_table)[:, 0].copy()
        st2 = sim._apply_receive(st, peer, jnp.asarray(senders),
                                 jnp.ones(n, bool), None)
        assert bool(st2.aux["cache_valid"][:, 0].all())
        # A non-neighbor sender must not park anywhere.
        non_nbr = []
        tbl = np.asarray(sim.nbr_table)
        for i in range(n):
            cand = next(j for j in range(n)
                        if j != i and j not in tbl[i])
            non_nbr.append(cand)
        st3 = sim._apply_receive(st, peer, jnp.asarray(non_nbr, np.int32),
                                 jnp.ones(n, bool), None)
        assert not bool(st3.aux["cache_valid"].any())

    def test_learns_on_sparse(self, key):
        import optax as _optax
        from gossipy_tpu.core import CreateModelMode
        from gossipy_tpu.handlers import SGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import CacheNeighGossipSimulator

        sparse = SparseTopology.random_regular(32, 6, seed=5)
        disp, d = _logreg_setup(n=32)
        h = SGDHandler(model=LogisticRegression(d, 2),
                       loss=losses.cross_entropy, optimizer=_optax.sgd(0.5),
                       local_epochs=1, batch_size=8, n_classes=2,
                       input_shape=(d,),
                       create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim = CacheNeighGossipSimulator(h, sparse, disp.stacked(), delta=8)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=12, key=jax.random.PRNGKey(7))
        assert rep.curves(local=False)["accuracy"][-1] > 0.8


class TestSparseMixFormulations:
    """The two O(E) All2All merge forms (padded gather+einsum vs edge-list
    segment-sum) must agree with each other and with the dense einsum."""

    def _build(self, topo, key, form="auto"):
        import optax as _optax
        from gossipy_tpu.core import CreateModelMode, uniform_mixing
        from gossipy_tpu.handlers import WeightedSGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import All2AllGossipSimulator

        disp, d = _logreg_setup(n=topo.num_nodes)
        h = WeightedSGDHandler(model=LogisticRegression(d, 2),
                               loss=losses.cross_entropy,
                               optimizer=_optax.sgd(0.3), local_epochs=1,
                               batch_size=8, n_classes=2, input_shape=(d,),
                               create_model_mode=CreateModelMode.MERGE_UPDATE)
        sim = All2AllGossipSimulator(h, topo, disp.stacked(), delta=8,
                                     mixing=uniform_mixing(topo),
                                     sparse_mix_form=form)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=3, key=jax.random.PRNGKey(8))
        return sim, st, rep.curves(local=False)["accuracy"][-1]

    def test_padded_and_segment_forms_agree(self, key):
        from gossipy_tpu.utils import params_allclose
        topo = SparseTopology.random_regular(24, 6, seed=1)
        sim_pad, st_pad, acc_pad = self._build(topo, key, form="padded")
        assert sim_pad._sparse_padded
        sim_seg, st_seg, acc_seg = self._build(topo, key, form="segment")
        assert not sim_seg._sparse_padded
        assert params_allclose(st_pad.model.params, st_seg.model.params,
                               atol=1e-5)
        # Accuracy quantizes to 1/n_samples; different summation orders can
        # flip a borderline sample — params_allclose above is the real
        # equivalence check, this is a sanity band.
        assert abs(acc_pad - acc_seg) < 0.05

    def test_auto_form_by_backend(self, key):
        import jax as _jax
        topo = SparseTopology.random_regular(12, 4, seed=2)
        sim, _, acc = self._build(topo, key, form="auto")
        # auto = padded only on TPU (measured: segment wins on CPU).
        assert sim._sparse_padded == (_jax.default_backend() == "tpu")
        assert np.isfinite(acc)

    def test_hub_graph_requires_segment_form(self, key):
        # Star graph: one hub of degree n-1 vs mean ~2 — padding to
        # max_deg would be O(N * max_deg); auto must pick segment-sum and
        # an explicit 'padded' request must refuse.
        n = 24
        edges = np.stack([np.zeros(n - 1, np.int64),
                          np.arange(1, n, dtype=np.int64)], axis=1)
        topo = SparseTopology(n, edges)
        sim, st, acc = self._build(topo, key)
        assert not sim._sparse_padded
        assert np.isfinite(acc)
        with pytest.raises(ValueError, match="heavy-tailed"):
            self._build(topo, key, form="padded")
