"""Active-cohort mode (simulation.cohort): semantics, scale, round-trips.

The ISSUE-14 acceptance pair this file carries:

- a CPU test running cohort mode at NOMINAL N >= 1M with C <= 4096
  materialized, converging on the pure-averaging sanity check;
- ``cohort=None`` traces byte-identical HLO (also enforced by the
  ``engine/cohort-off`` pair in ``scripts/hlo_gate.py``'s grid).
"""

import json
import os

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import (
    AntiEntropyProtocol,
    CreateModelMode,
    SparseTopology,
    Topology,
)
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import (
    CohortConfig,
    CohortPool,
    GossipSimulator,
    JSONLinesReceiver,
    NominalTopology,
)
from gossipy_tpu.simulation.cohort import pool_bytes, sample_cohort

D = 6


def make_data(n_shards, seed=0, samples_per=8):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=D)
    X = rng.normal(size=(n_shards * samples_per, D)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                          n=n_shards, eval_on_user=False)
    return disp.stacked()


def make_handler(lr=0.1):
    return SGDHandler(model=LogisticRegression(D, 2),
                      loss=losses.cross_entropy, optimizer=optax.sgd(lr),
                      local_epochs=1, batch_size=8, n_classes=2,
                      input_shape=(D,),
                      create_model_mode=CreateModelMode.MERGE_UPDATE)


def make_sim(nominal=64, cohort=16, lr=0.1, topo=None, data_shards=None,
             **kw):
    data = make_data(data_shards or min(nominal, 64))
    topo = topo or Topology.random_regular(nominal, 6, seed=3)
    return GossipSimulator(make_handler(lr), topo, data, delta=20,
                           protocol=AntiEntropyProtocol.PUSH,
                           cohort=(CohortConfig(size=cohort)
                                   if cohort else None), **kw)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


class TestCohortConfig:
    def test_coerce(self):
        assert CohortConfig.coerce(None) is None
        cfg = CohortConfig(size=8)
        assert CohortConfig.coerce(cfg) is cfg
        assert CohortConfig.coerce(8) == cfg
        assert CohortConfig.coerce({"size": 8}) == cfg
        with pytest.raises(ValueError):
            CohortConfig.coerce(True)
        with pytest.raises(ValueError):
            CohortConfig(size=1)
        with pytest.raises(ValueError):
            CohortConfig(size=8, peer_mode="bogus")
        with pytest.raises(ValueError):
            CohortConfig.from_dict({"size": 8, "bogus": 1})

    def test_dict_roundtrip(self):
        cfg = CohortConfig(size=32, rounds_per_cohort=2,
                           peer_mode="induced")
        assert CohortConfig.from_dict(cfg.to_dict()) == cfg

    def test_rejections(self, key):
        with pytest.raises(ValueError, match="exceeds the nominal"):
            make_sim(nominal=8, cohort=16)
        with pytest.raises(ValueError, match="mutually"):
            from gossipy_tpu.simulation import ChaosConfig, OutageEpisode
            make_sim(nominal=64, cohort=16, chaos=ChaosConfig(
                outages=(OutageEpisode(nodes=(0,), start=1, stop=2),),
                horizon=3))
        sim = make_sim()
        with pytest.raises(ValueError, match="init_cohort_pool"):
            sim.init_nodes(key)
        with pytest.raises(ValueError, match="cohort"):
            sim.run_repetitions(2, jax.random.split(key, 2))
        plain = make_sim(cohort=None)
        with pytest.raises(ValueError, match="init_nodes"):
            plain.init_cohort_pool(key)

    def test_nominal_topology_refuses_structure(self):
        t = NominalTopology(100)
        assert t.num_nodes == 100
        with pytest.raises(AttributeError, match="population size"):
            t.degrees
        with pytest.raises(ValueError, match="real topology"):
            GossipSimulator(
                make_handler(), NominalTopology(64), make_data(64),
                delta=20, cohort=CohortConfig(size=8,
                                              peer_mode="induced"))


class TestCohortOffIsAbsent:
    def test_cohort_off_hlo_identical(self):
        """cohort=None traces the byte-identical program (the gate's
        engine/cohort-off identity pair; first divergent instruction
        named on failure)."""
        from gossipy_tpu.analysis import assert_identical_hlo
        absent = GossipSimulator(
            make_handler(), Topology.random_regular(64, 6, seed=3),
            make_data(64), delta=20,
            protocol=AntiEntropyProtocol.PUSH)
        assert_identical_hlo(make_sim(cohort=None), absent,
                             label="cohort=None")

    def test_default_report_has_no_cohort_fields(self, key):
        sim = make_sim(cohort=None)
        st = sim.init_nodes(key)
        _, rep = sim.start(st, n_rounds=3, key=key)
        assert rep.cohort_coverage is None
        assert rep.cohort_active_nodes is None
        assert rep.to_dict()["cohort_coverage"] is None


class TestResampleRounds:
    def test_accounting_and_coverage(self, key):
        sim = make_sim(nominal=64, cohort=16)
        pool = sim.init_cohort_pool(key)
        pool, rep = sim.start(pool, n_rounds=8, key=key)
        assert (rep.sent_per_round == 16).all()
        assert (rep.cohort_active_nodes == 16).all()
        cov = rep.cohort_coverage
        assert (np.diff(cov) >= -1e-9).all()
        assert np.isclose(cov[-1], pool.touched.mean())
        assert int(np.asarray(pool.round)) == 8

    def test_cohort_schedule_deterministic(self, key):
        a = sample_cohort(key, 5, 1000, 64)
        b = sample_cohort(key, 5, 1000, 64)
        np.testing.assert_array_equal(a, b)
        c = sample_cohort(key, 6, 1000, 64)
        assert not np.array_equal(a, c)
        assert np.unique(a).size == 64
        # Large-N rejection path: still C uniques, deterministic.
        big = sample_cohort(key, 0, 10_000_000, 4096)
        assert np.unique(big).size == 4096
        np.testing.assert_array_equal(
            big, sample_cohort(key, 0, 10_000_000, 4096))

    def test_caller_pool_not_mutated(self, key):
        sim = make_sim(nominal=64, cohort=16)
        pool0 = sim.init_cohort_pool(key)
        before = [np.array(l) for l in
                  jax.tree_util.tree_leaves(pool0.model)]
        sim.start(pool0, n_rounds=4, key=key)
        for a, b in zip(before, jax.tree_util.tree_leaves(pool0.model)):
            np.testing.assert_array_equal(a, b)

    def test_events_jsonl_v8_cohort_rows(self, key, tmp_path):
        sim = make_sim(nominal=64, cohort=16)
        path = str(tmp_path / "run.jsonl")
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rx)
            pool = sim.init_cohort_pool(key)
            sim.start(pool, n_rounds=4, key=key)
        rows = [JSONLinesReceiver.parse_line(l) for l in open(path)]
        assert len(rows) == 4
        for r in rows:
            assert r["schema"] == 8
            assert r["cohort"]["active_nodes"] == 16
            assert 0 < r["cohort"]["coverage"] <= 1

    def test_manifest_carries_cohort_and_rules(self, key):
        sim = make_sim(nominal=64, cohort=16)
        m = sim.run_manifest().to_dict()
        assert m["config"]["cohort"] == {"size": 16,
                                        "rounds_per_cohort": 1,
                                        "peer_mode": "resample",
                                        "prefetch": 0,
                                        "pool_dir": None}
        assert m["config"]["nominal_n"] == 64
        assert m["config"]["topology"] == "Topology"
        assert any("history_scale" in p
                   for p, _ in m["config"]["partition_rules"])
        mb = m["memory_budget"]
        assert mb["cohort_pool_resident"] == pool_bytes(sim)
        assert mb["nominal_n"] == 64 and mb["cohort_size"] == 16

    def test_config_roundtrip_and_run_experiment(self):
        from gossipy_tpu.config import ExperimentConfig, run_experiment
        rng = np.random.default_rng(1)
        X = rng.normal(size=(400, D)).astype(np.float32)
        y = (X @ rng.normal(size=D) > 0).astype(np.int64)
        cfg = ExperimentConfig(n_nodes=48, model="logreg",
                               topology="random_regular",
                               topology_params={"degree": 4},
                               cohort={"size": 12}, n_rounds=5, delta=10,
                               batch_size=8, seed=3)
        cfg2 = ExperimentConfig.from_json(cfg.to_json())
        assert cfg2.cohort == {"size": 12}
        pool, rep = run_experiment(cfg2, data=(X, y))
        assert isinstance(pool, CohortPool)
        assert (rep.cohort_active_nodes == 12).all()
        with pytest.raises(ValueError, match="simulator 'gossip'"):
            run_experiment(ExperimentConfig(
                n_nodes=48, simulator="all2all", cohort={"size": 12}),
                data=(X, y))
        with pytest.raises(ValueError, match="repetition"):
            ExperimentConfig(n_nodes=48, cohort={"size": 12},
                             repetitions=2)

    def test_service_rejects_cohort(self):
        from gossipy_tpu.config import ExperimentConfig
        from gossipy_tpu.service import RunRequest
        cfg = ExperimentConfig(n_nodes=48, cohort={"size": 12})
        with pytest.raises(ValueError, match="megabatch"):
            RunRequest("alice", cfg)

    def test_sentinels_compose(self, key):
        sim = make_sim(nominal=64, cohort=16, sentinels=True)
        pool = sim.init_cohort_pool(key)
        pool, rep = sim.start(pool, n_rounds=4, key=key)
        assert rep.health_trip is not None
        assert (rep.health_trip == 0).all()


class TestInducedSubgraph:
    def test_induced_runs_and_respects_edges(self, key):
        # A ring at nominal 64 with a 32-node cohort: the induced
        # subgraph has SOME edges but also isolated nodes — sends from
        # isolated nodes are skipped, so sent < C on typical rounds,
        # and never exceeds C.
        topo = SparseTopology.ring(64)
        sim = GossipSimulator(
            make_handler(), topo, make_data(64), delta=20,
            protocol=AntiEntropyProtocol.PUSH,
            cohort=CohortConfig(size=32, peer_mode="induced"))
        pool = sim.init_cohort_pool(key)
        pool, rep = sim.start(pool, n_rounds=6, key=key)
        assert (rep.sent_per_round <= 32).all()
        assert rep.sent_per_round.sum() > 0
        assert (rep.cohort_active_nodes == 32).all()

    def test_full_cohort_induced_equals_population_graph(self, key):
        # C == N: the induced subgraph IS the whole graph every round —
        # every node has ring neighbors, so every node sends.
        topo = SparseTopology.ring(24)
        sim = GossipSimulator(
            make_handler(), topo, make_data(24), delta=20,
            protocol=AntiEntropyProtocol.PUSH,
            cohort=CohortConfig(size=24, peer_mode="induced"))
        pool = sim.init_cohort_pool(key)
        pool, rep = sim.start(pool, n_rounds=4, key=key)
        assert (rep.sent_per_round == 24).all()


class TestMillionNodePool:
    def test_nominal_1m_pure_averaging_converges(self, key):
        """The acceptance rung: nominal N = 1M, C = 4096 materialized,
        lr = 0 (the local update is a no-op — the run is pure sampled
        gossip averaging). Each round contracts the pool's parameter
        variance by ~C/(2N); over 30 rounds the total variance must
        visibly shrink while only [4096]-wide state ever exists on
        device."""
        n, c, rounds = 1_000_000, 4096, 30
        sim = GossipSimulator(
            make_handler(lr=0.0), NominalTopology(n), make_data(64),
            delta=20, protocol=AntiEntropyProtocol.PUSH,
            eval_every=rounds, sampling_eval=0.01,
            cohort=CohortConfig(size=c))
        assert sim.n_nodes == c and sim.nominal_n == n
        pool = sim.init_cohort_pool(key)

        def pool_variance(p):
            flats = [np.asarray(l).reshape(n, -1)
                     for l in jax.tree_util.tree_leaves(p.model.params)]
            flat = np.concatenate(flats, axis=1)
            return float(((flat - flat.mean(0)) ** 2).sum())

        v0 = pool_variance(pool)
        assert v0 > 0
        pool, rep = sim.start(pool, n_rounds=rounds, key=key)
        v1 = pool_variance(pool)
        # ~C/(2N) contraction per round => >= ~4% over 30 rounds; assert
        # a conservative bound plus strict decrease.
        assert v1 < 0.97 * v0, (v0, v1)
        # Coverage accounting at scale: ~ rounds*C/N of the pool touched
        # (minus overlaps), monotone.
        cov = rep.cohort_coverage
        assert (np.diff(cov) >= -1e-9).all()
        expected = rounds * c / n
        assert 0.5 * expected < cov[-1] <= expected + 1e-9
        # The materialized prediction names why this mode exists: the
        # full-population round state would be ~N/C times the active.
        mb = sim.memory_budget()
        assert mb["cohort_materialized_prediction"] \
            > 20 * mb["cohort_active_total"]

    def test_pool_checkpoint_roundtrip_at_scale(self, key, tmp_path):
        # Mid-run save/restore with the cheap zero template (no O(N)
        # init on restore) — pool intact bit-for-bit.
        n, c = 1_000_000, 256
        sim = GossipSimulator(
            make_handler(lr=0.0), NominalTopology(n), make_data(64),
            delta=20, protocol=AntiEntropyProtocol.PUSH, eval_every=4,
            cohort=CohortConfig(size=c))
        pool = sim.init_cohort_pool(key, common_init=True)
        pool, _ = sim.start(pool, n_rounds=2, key=key)
        path = sim.save(str(tmp_path / "ck"), pool, key=key)
        restored, rkey = sim.load(path, key)
        assert int(np.asarray(restored.round)) == 2
        for a, b in zip(jax.tree_util.tree_leaves(pool),
                        jax.tree_util.tree_leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # Continuation equals the uninterrupted run.
        cont, _ = sim.start(restored, n_rounds=2, key=rkey)
        direct, _ = sim.start(pool, n_rounds=2, key=key)
        for a, b in zip(jax.tree_util.tree_leaves(cont.model),
                        jax.tree_util.tree_leaves(direct.model)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestReportRoundTrip:
    def test_cohort_fields_survive_save_load_concatenate(self, key):
        from gossipy_tpu.simulation.report import SimulationReport
        sim = make_sim(nominal=64, cohort=16)
        pool = sim.init_cohort_pool(key)
        pool, r1 = sim.start(pool, n_rounds=3, key=key)
        pool, r2 = sim.start(pool, n_rounds=3, key=key)
        d = r1.to_dict()
        json.dumps(d)  # strict-JSON clean
        back = SimulationReport.from_dict(d)
        np.testing.assert_allclose(back.cohort_coverage,
                                   r1.cohort_coverage, rtol=1e-6)
        assert back.cohort_active_nodes.dtype.kind == "i"
        cat = SimulationReport.concatenate([r1, r2])
        assert cat.cohort_coverage.shape == (6,)
        assert (cat.cohort_active_nodes == 16).all()


def make_stream_sim(nominal=96, cohort=24, prefetch=0, rpc=1,
                    pool_dir=None, lr=0.1):
    """A cohort sim with the streaming-pipeline knobs exposed."""
    return GossipSimulator(
        make_handler(lr), Topology.random_regular(nominal, 6, seed=3),
        make_data(min(nominal, 64)), delta=20,
        protocol=AntiEntropyProtocol.PUSH,
        cohort=CohortConfig(size=cohort, rounds_per_cohort=rpc,
                            prefetch=prefetch, pool_dir=pool_dir))


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture(scope="module")
def serial_oracle8():
    """The 8-round SERIAL pool+report for the make_stream_sim config —
    the one oracle the streaming/mesh equivalence tests compare against
    (shared: each serial rerun would re-trace the segment program)."""
    key = jax.random.PRNGKey(0)
    sim = make_stream_sim(prefetch=0)
    return sim.start(sim.init_cohort_pool(key), n_rounds=8, key=key)


class TestStreamingPipeline:
    """CohortConfig(prefetch=k): the double-buffered driver must be a
    pure scheduling change — bit-identical pools to the serial loop."""

    def test_prefetch_config_validation(self):
        with pytest.raises(ValueError):
            CohortConfig(size=8, prefetch=-1)
        with pytest.raises(ValueError):
            CohortConfig(size=8, pool_dir=123)
        cfg = CohortConfig.coerce({"size": 8, "prefetch": 3,
                                   "pool_dir": "/tmp/x"})
        assert cfg.prefetch == 3 and cfg.pool_dir == "/tmp/x"
        assert CohortConfig.coerce(cfg.to_dict()) == cfg

    def test_streaming_equals_serial_bit_for_bit(self, key,
                                                 serial_oracle8):
        """K segments streamed at a shallow and a deep depth == the
        serial schedule, every pool leaf bit-identical (model, phase,
        node keys, touched, round) and the report rows equal too.
        (Depths in between ride the tail/overlap/checkpoint tests.)"""
        p_serial, r_serial = serial_oracle8
        for prefetch in (1, 4):
            st = make_stream_sim(prefetch=prefetch)
            p_stream, r_stream = st.start(st.init_cohort_pool(key),
                                          n_rounds=8, key=key)
            _leaves_equal(p_serial, p_stream)
            np.testing.assert_array_equal(r_serial.sent_per_round,
                                          r_stream.sent_per_round)
            np.testing.assert_allclose(r_serial.cohort_coverage,
                                       r_stream.cohort_coverage, rtol=0)
            np.testing.assert_array_equal(r_serial.cohort_active_nodes,
                                          r_stream.cohort_active_nodes)

    def test_streaming_tail_segment(self, key):
        """rounds not divisible by rounds_per_cohort: the short tail
        segment streams identically too."""
        sa = make_stream_sim(prefetch=0, rpc=3)
        sb = make_stream_sim(prefetch=2, rpc=3)
        pa, _ = sa.start(sa.init_cohort_pool(key), n_rounds=7, key=key)
        pb, _ = sb.start(sb.init_cohort_pool(key), n_rounds=7, key=key)
        _leaves_equal(pa, pb)

    def test_streaming_overlapping_cohorts_patch(self, key):
        """Small N/C ratio forces consecutive cohorts to intersect, so
        staged gathers MUST be patched with in-flight outputs — the
        exact hazard the pending/recent overlay protocol exists for."""
        sa = make_stream_sim(nominal=32, cohort=16, prefetch=0)
        sb = make_stream_sim(nominal=32, cohort=16, prefetch=3)
        pa, _ = sa.start(sa.init_cohort_pool(key), n_rounds=10, key=key)
        pb, _ = sb.start(sb.init_cohort_pool(key), n_rounds=10, key=key)
        _leaves_equal(pa, pb)

    def test_streaming_checkpoint_midrun(self, key, tmp_path,
                                         serial_oracle8):
        """save/load mid-run UNDER prefetch, continue streamed ==
        straight-through serial (the shared oracle)."""
        s1 = make_stream_sim(prefetch=2)
        pool, _ = s1.start(s1.init_cohort_pool(key), n_rounds=4, key=key)
        path = s1.save(str(tmp_path / "ck"), pool, key=key)
        restored, rkey = s1.load(path, key)
        cont, _ = s1.start(restored, n_rounds=4, key=rkey)
        _leaves_equal(cont, serial_oracle8[0])


class TestMeshShardedRounds:
    """start(..., mesh=): [C]-wide rounds sharded along the node axis
    through the parallel/rules.py registry."""

    def _mesh(self, n=8):
        from gossipy_tpu.parallel import make_mesh
        return make_mesh(n)

    def test_mesh_equals_unsharded(self, key, serial_oracle8):
        mesh = self._mesh()
        ss = make_stream_sim()
        ps, _ = ss.start(ss.init_cohort_pool(key), n_rounds=8, key=key,
                         mesh=mesh)
        _leaves_equal(serial_oracle8[0], ps)

    def test_mesh_with_prefetch(self, key, serial_oracle8):
        """mesh + prefetch compose: sharded streamed == serial oracle."""
        mesh = self._mesh()
        ss = make_stream_sim(prefetch=2)
        ps, _ = ss.start(ss.init_cohort_pool(key), n_rounds=8, key=key,
                         mesh=mesh)
        _leaves_equal(serial_oracle8[0], ps)

    def test_mesh_divisibility_enforced(self, key):
        sim = make_stream_sim(cohort=20)  # 20 % 8 != 0
        pool = sim.init_cohort_pool(key)
        with pytest.raises(ValueError, match="not divisible"):
            sim.start(pool, n_rounds=1, key=key, mesh=self._mesh())

    def test_non_cohort_mesh_rejected(self, key):
        sim = make_sim(nominal=16, cohort=None, data_shards=16)
        st = sim.init_nodes(key)
        with pytest.raises(ValueError, match="cohort"):
            sim.start(st, n_rounds=1, key=key, mesh=self._mesh())

    def test_no_hand_placed_specs_in_cohort(self):
        """The mesh path must place every array through the
        parallel/rules.py registry: no PartitionSpec constructor call
        exists in simulation/cohort.py (or engine.py)."""
        import ast as _ast
        import pathlib
        pkg = pathlib.Path(__file__).resolve().parent.parent \
            / "gossipy_tpu" / "simulation"
        for f in (pkg / "cohort.py", pkg / "engine.py"):
            tree = _ast.parse(f.read_text())
            for node in _ast.walk(tree):
                if not isinstance(node, _ast.Call):
                    continue
                fn = node.func
                name = (fn.id if isinstance(fn, _ast.Name)
                        else fn.attr if isinstance(fn, _ast.Attribute)
                        else None)
                assert name not in ("P", "PartitionSpec"), \
                    f"hand-placed PartitionSpec at {f.name}:{node.lineno}"


class TestDiskBackedPool:
    """CohortConfig(pool_dir=...): sparse mmap pools — nominal N bounded
    by storage, not RAM."""

    def test_create_run_resume(self, key, tmp_path):
        pd = str(tmp_path / "pool")
        s1 = make_stream_sim(prefetch=2, pool_dir=pd)
        pool = s1.init_cohort_pool(key)
        assert isinstance(jax.tree_util.tree_leaves(pool.model)[0],
                          np.memmap)
        pool, _ = s1.start(pool, n_rounds=4, key=key)
        # Reopening the directory resumes at the stored round.
        s2 = make_stream_sim(prefetch=0, pool_dir=pd)
        pool2 = s2.init_cohort_pool(key)
        assert int(np.asarray(pool2.round)) == 4

    def test_checkpoint_restore_continue_deterministic(self, key,
                                                       tmp_path):
        """Checkpoints are file copies; a restored run continues exactly
        like an uninterrupted disk-backed run with the same key."""
        pd1 = str(tmp_path / "a")
        s1 = make_stream_sim(prefetch=2, pool_dir=pd1)
        mid, _ = s1.start(s1.init_cohort_pool(key), n_rounds=3, key=key)
        ck = s1.save(str(tmp_path / "ck"), mid, key=key)
        restored, rkey = s1.load(ck)
        fin_a, _ = s1.start(restored, n_rounds=3, key=rkey)
        pd2 = str(tmp_path / "b")
        s2 = make_stream_sim(prefetch=2, pool_dir=pd2)
        fin_b, _ = s2.start(s2.init_cohort_pool(key), n_rounds=6,
                            key=key)
        _leaves_equal(fin_a.model, fin_b.model)
        np.testing.assert_array_equal(np.asarray(fin_a.touched),
                                      np.asarray(fin_b.touched))

    def test_local_train_rejected(self, key, tmp_path):
        sim = make_stream_sim(pool_dir=str(tmp_path / "p"))
        with pytest.raises(ValueError, match="local_train"):
            sim.init_cohort_pool(key, local_train=True)

    @pytest.mark.slow
    def test_nominal_too_large_for_ram(self, key, tmp_path):
        """Nominal N whose dense float32 pool (~23 GB of model rows
        alone) cannot be a RAM numpy array: the sparse mmap pool runs a
        short streamed segment loop with bounded disk allocation."""
        import resource
        n, c = 50_000_000, 32
        pd = str(tmp_path / "big")
        sim = GossipSimulator(
            make_handler(0.1), NominalTopology(n), make_data(64),
            delta=20, protocol=AntiEntropyProtocol.PUSH,
            cohort=CohortConfig(size=c, prefetch=2, pool_dir=pd))
        pool = sim.init_cohort_pool(key)
        pool, _ = sim.start(pool, n_rounds=3, key=key)
        assert int(np.asarray(pool.round)) == 3
        logical = sum(os.stat(os.path.join(pd, f)).st_size
                      for f in os.listdir(pd))
        allocated = sum(os.stat(os.path.join(pd, f)).st_blocks * 512
                        for f in os.listdir(pd))
        assert logical > 2e9          # nominal-sized address space
        assert allocated < 5e8, allocated  # but sparse on disk
        rss_gb = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1e6
        assert rss_gb < 8, rss_gb     # and never materialized in RAM
        # Checkpoints stay O(written rows): hole-preserving copies.
        ck = sim.save(str(tmp_path / "ck"), pool, key=key)
        ck_alloc = sum(os.stat(os.path.join(ck, f)).st_blocks * 512
                       for f in os.listdir(ck))
        assert ck_alloc < 5e8, ck_alloc
        restored, _ = sim.load(ck)
        assert int(np.asarray(restored.round)) == 3
