"""Active-cohort mode (simulation.cohort): semantics, scale, round-trips.

The ISSUE-14 acceptance pair this file carries:

- a CPU test running cohort mode at NOMINAL N >= 1M with C <= 4096
  materialized, converging on the pure-averaging sanity check;
- ``cohort=None`` traces byte-identical HLO (also enforced by the
  ``engine/cohort-off`` pair in ``scripts/hlo_gate.py``'s grid).
"""

import json

import jax
import numpy as np
import optax
import pytest

from gossipy_tpu.core import (
    AntiEntropyProtocol,
    CreateModelMode,
    SparseTopology,
    Topology,
)
from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
from gossipy_tpu.handlers import SGDHandler, losses
from gossipy_tpu.models import LogisticRegression
from gossipy_tpu.simulation import (
    CohortConfig,
    CohortPool,
    GossipSimulator,
    JSONLinesReceiver,
    NominalTopology,
)
from gossipy_tpu.simulation.cohort import pool_bytes, sample_cohort

D = 6


def make_data(n_shards, seed=0, samples_per=8):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=D)
    X = rng.normal(size=(n_shards * samples_per, D)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                          n=n_shards, eval_on_user=False)
    return disp.stacked()


def make_handler(lr=0.1):
    return SGDHandler(model=LogisticRegression(D, 2),
                      loss=losses.cross_entropy, optimizer=optax.sgd(lr),
                      local_epochs=1, batch_size=8, n_classes=2,
                      input_shape=(D,),
                      create_model_mode=CreateModelMode.MERGE_UPDATE)


def make_sim(nominal=64, cohort=16, lr=0.1, topo=None, data_shards=None,
             **kw):
    data = make_data(data_shards or min(nominal, 64))
    topo = topo or Topology.random_regular(nominal, 6, seed=3)
    return GossipSimulator(make_handler(lr), topo, data, delta=20,
                           protocol=AntiEntropyProtocol.PUSH,
                           cohort=(CohortConfig(size=cohort)
                                   if cohort else None), **kw)


@pytest.fixture
def key():
    return jax.random.PRNGKey(0)


class TestCohortConfig:
    def test_coerce(self):
        assert CohortConfig.coerce(None) is None
        cfg = CohortConfig(size=8)
        assert CohortConfig.coerce(cfg) is cfg
        assert CohortConfig.coerce(8) == cfg
        assert CohortConfig.coerce({"size": 8}) == cfg
        with pytest.raises(ValueError):
            CohortConfig.coerce(True)
        with pytest.raises(ValueError):
            CohortConfig(size=1)
        with pytest.raises(ValueError):
            CohortConfig(size=8, peer_mode="bogus")
        with pytest.raises(ValueError):
            CohortConfig.from_dict({"size": 8, "bogus": 1})

    def test_dict_roundtrip(self):
        cfg = CohortConfig(size=32, rounds_per_cohort=2,
                           peer_mode="induced")
        assert CohortConfig.from_dict(cfg.to_dict()) == cfg

    def test_rejections(self, key):
        with pytest.raises(ValueError, match="exceeds the nominal"):
            make_sim(nominal=8, cohort=16)
        with pytest.raises(ValueError, match="mutually"):
            from gossipy_tpu.simulation import ChaosConfig, OutageEpisode
            make_sim(nominal=64, cohort=16, chaos=ChaosConfig(
                outages=(OutageEpisode(nodes=(0,), start=1, stop=2),),
                horizon=3))
        sim = make_sim()
        with pytest.raises(ValueError, match="init_cohort_pool"):
            sim.init_nodes(key)
        with pytest.raises(ValueError, match="cohort"):
            sim.run_repetitions(2, jax.random.split(key, 2))
        plain = make_sim(cohort=None)
        with pytest.raises(ValueError, match="init_nodes"):
            plain.init_cohort_pool(key)

    def test_nominal_topology_refuses_structure(self):
        t = NominalTopology(100)
        assert t.num_nodes == 100
        with pytest.raises(AttributeError, match="population size"):
            t.degrees
        with pytest.raises(ValueError, match="real topology"):
            GossipSimulator(
                make_handler(), NominalTopology(64), make_data(64),
                delta=20, cohort=CohortConfig(size=8,
                                              peer_mode="induced"))


class TestCohortOffIsAbsent:
    def test_cohort_off_hlo_identical(self):
        """cohort=None traces the byte-identical program (the gate's
        engine/cohort-off identity pair; first divergent instruction
        named on failure)."""
        from gossipy_tpu.analysis import assert_identical_hlo
        absent = GossipSimulator(
            make_handler(), Topology.random_regular(64, 6, seed=3),
            make_data(64), delta=20,
            protocol=AntiEntropyProtocol.PUSH)
        assert_identical_hlo(make_sim(cohort=None), absent,
                             label="cohort=None")

    def test_default_report_has_no_cohort_fields(self, key):
        sim = make_sim(cohort=None)
        st = sim.init_nodes(key)
        _, rep = sim.start(st, n_rounds=3, key=key)
        assert rep.cohort_coverage is None
        assert rep.cohort_active_nodes is None
        assert rep.to_dict()["cohort_coverage"] is None


class TestResampleRounds:
    def test_accounting_and_coverage(self, key):
        sim = make_sim(nominal=64, cohort=16)
        pool = sim.init_cohort_pool(key)
        pool, rep = sim.start(pool, n_rounds=8, key=key)
        assert (rep.sent_per_round == 16).all()
        assert (rep.cohort_active_nodes == 16).all()
        cov = rep.cohort_coverage
        assert (np.diff(cov) >= -1e-9).all()
        assert np.isclose(cov[-1], pool.touched.mean())
        assert int(np.asarray(pool.round)) == 8

    def test_cohort_schedule_deterministic(self, key):
        a = sample_cohort(key, 5, 1000, 64)
        b = sample_cohort(key, 5, 1000, 64)
        np.testing.assert_array_equal(a, b)
        c = sample_cohort(key, 6, 1000, 64)
        assert not np.array_equal(a, c)
        assert np.unique(a).size == 64
        # Large-N rejection path: still C uniques, deterministic.
        big = sample_cohort(key, 0, 10_000_000, 4096)
        assert np.unique(big).size == 4096
        np.testing.assert_array_equal(
            big, sample_cohort(key, 0, 10_000_000, 4096))

    def test_caller_pool_not_mutated(self, key):
        sim = make_sim(nominal=64, cohort=16)
        pool0 = sim.init_cohort_pool(key)
        before = [np.array(l) for l in
                  jax.tree_util.tree_leaves(pool0.model)]
        sim.start(pool0, n_rounds=4, key=key)
        for a, b in zip(before, jax.tree_util.tree_leaves(pool0.model)):
            np.testing.assert_array_equal(a, b)

    def test_events_jsonl_v8_cohort_rows(self, key, tmp_path):
        sim = make_sim(nominal=64, cohort=16)
        path = str(tmp_path / "run.jsonl")
        with JSONLinesReceiver(path) as rx:
            sim.add_receiver(rx)
            pool = sim.init_cohort_pool(key)
            sim.start(pool, n_rounds=4, key=key)
        rows = [JSONLinesReceiver.parse_line(l) for l in open(path)]
        assert len(rows) == 4
        for r in rows:
            assert r["schema"] == 8
            assert r["cohort"]["active_nodes"] == 16
            assert 0 < r["cohort"]["coverage"] <= 1

    def test_manifest_carries_cohort_and_rules(self, key):
        sim = make_sim(nominal=64, cohort=16)
        m = sim.run_manifest().to_dict()
        assert m["config"]["cohort"] == {"size": 16,
                                        "rounds_per_cohort": 1,
                                        "peer_mode": "resample"}
        assert m["config"]["nominal_n"] == 64
        assert m["config"]["topology"] == "Topology"
        assert any("history_scale" in p
                   for p, _ in m["config"]["partition_rules"])
        mb = m["memory_budget"]
        assert mb["cohort_pool_resident"] == pool_bytes(sim)
        assert mb["nominal_n"] == 64 and mb["cohort_size"] == 16

    def test_config_roundtrip_and_run_experiment(self):
        from gossipy_tpu.config import ExperimentConfig, run_experiment
        rng = np.random.default_rng(1)
        X = rng.normal(size=(400, D)).astype(np.float32)
        y = (X @ rng.normal(size=D) > 0).astype(np.int64)
        cfg = ExperimentConfig(n_nodes=48, model="logreg",
                               topology="random_regular",
                               topology_params={"degree": 4},
                               cohort={"size": 12}, n_rounds=5, delta=10,
                               batch_size=8, seed=3)
        cfg2 = ExperimentConfig.from_json(cfg.to_json())
        assert cfg2.cohort == {"size": 12}
        pool, rep = run_experiment(cfg2, data=(X, y))
        assert isinstance(pool, CohortPool)
        assert (rep.cohort_active_nodes == 12).all()
        with pytest.raises(ValueError, match="simulator 'gossip'"):
            run_experiment(ExperimentConfig(
                n_nodes=48, simulator="all2all", cohort={"size": 12}),
                data=(X, y))
        with pytest.raises(ValueError, match="repetition"):
            ExperimentConfig(n_nodes=48, cohort={"size": 12},
                             repetitions=2)

    def test_service_rejects_cohort(self):
        from gossipy_tpu.config import ExperimentConfig
        from gossipy_tpu.service import RunRequest
        cfg = ExperimentConfig(n_nodes=48, cohort={"size": 12})
        with pytest.raises(ValueError, match="megabatch"):
            RunRequest("alice", cfg)

    def test_sentinels_compose(self, key):
        sim = make_sim(nominal=64, cohort=16, sentinels=True)
        pool = sim.init_cohort_pool(key)
        pool, rep = sim.start(pool, n_rounds=4, key=key)
        assert rep.health_trip is not None
        assert (rep.health_trip == 0).all()


class TestInducedSubgraph:
    def test_induced_runs_and_respects_edges(self, key):
        # A ring at nominal 64 with a 32-node cohort: the induced
        # subgraph has SOME edges but also isolated nodes — sends from
        # isolated nodes are skipped, so sent < C on typical rounds,
        # and never exceeds C.
        topo = SparseTopology.ring(64)
        sim = GossipSimulator(
            make_handler(), topo, make_data(64), delta=20,
            protocol=AntiEntropyProtocol.PUSH,
            cohort=CohortConfig(size=32, peer_mode="induced"))
        pool = sim.init_cohort_pool(key)
        pool, rep = sim.start(pool, n_rounds=6, key=key)
        assert (rep.sent_per_round <= 32).all()
        assert rep.sent_per_round.sum() > 0
        assert (rep.cohort_active_nodes == 32).all()

    def test_full_cohort_induced_equals_population_graph(self, key):
        # C == N: the induced subgraph IS the whole graph every round —
        # every node has ring neighbors, so every node sends.
        topo = SparseTopology.ring(24)
        sim = GossipSimulator(
            make_handler(), topo, make_data(24), delta=20,
            protocol=AntiEntropyProtocol.PUSH,
            cohort=CohortConfig(size=24, peer_mode="induced"))
        pool = sim.init_cohort_pool(key)
        pool, rep = sim.start(pool, n_rounds=4, key=key)
        assert (rep.sent_per_round == 24).all()


class TestMillionNodePool:
    def test_nominal_1m_pure_averaging_converges(self, key):
        """The acceptance rung: nominal N = 1M, C = 4096 materialized,
        lr = 0 (the local update is a no-op — the run is pure sampled
        gossip averaging). Each round contracts the pool's parameter
        variance by ~C/(2N); over 30 rounds the total variance must
        visibly shrink while only [4096]-wide state ever exists on
        device."""
        n, c, rounds = 1_000_000, 4096, 30
        sim = GossipSimulator(
            make_handler(lr=0.0), NominalTopology(n), make_data(64),
            delta=20, protocol=AntiEntropyProtocol.PUSH,
            eval_every=rounds, sampling_eval=0.01,
            cohort=CohortConfig(size=c))
        assert sim.n_nodes == c and sim.nominal_n == n
        pool = sim.init_cohort_pool(key)

        def pool_variance(p):
            flats = [np.asarray(l).reshape(n, -1)
                     for l in jax.tree_util.tree_leaves(p.model.params)]
            flat = np.concatenate(flats, axis=1)
            return float(((flat - flat.mean(0)) ** 2).sum())

        v0 = pool_variance(pool)
        assert v0 > 0
        pool, rep = sim.start(pool, n_rounds=rounds, key=key)
        v1 = pool_variance(pool)
        # ~C/(2N) contraction per round => >= ~4% over 30 rounds; assert
        # a conservative bound plus strict decrease.
        assert v1 < 0.97 * v0, (v0, v1)
        # Coverage accounting at scale: ~ rounds*C/N of the pool touched
        # (minus overlaps), monotone.
        cov = rep.cohort_coverage
        assert (np.diff(cov) >= -1e-9).all()
        expected = rounds * c / n
        assert 0.5 * expected < cov[-1] <= expected + 1e-9
        # The materialized prediction names why this mode exists: the
        # full-population round state would be ~N/C times the active.
        mb = sim.memory_budget()
        assert mb["cohort_materialized_prediction"] \
            > 20 * mb["cohort_active_total"]

    def test_pool_checkpoint_roundtrip_at_scale(self, key, tmp_path):
        # Mid-run save/restore with the cheap zero template (no O(N)
        # init on restore) — pool intact bit-for-bit.
        n, c = 1_000_000, 256
        sim = GossipSimulator(
            make_handler(lr=0.0), NominalTopology(n), make_data(64),
            delta=20, protocol=AntiEntropyProtocol.PUSH, eval_every=4,
            cohort=CohortConfig(size=c))
        pool = sim.init_cohort_pool(key, common_init=True)
        pool, _ = sim.start(pool, n_rounds=2, key=key)
        path = sim.save(str(tmp_path / "ck"), pool, key=key)
        restored, rkey = sim.load(path, key)
        assert int(np.asarray(restored.round)) == 2
        for a, b in zip(jax.tree_util.tree_leaves(pool),
                        jax.tree_util.tree_leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # Continuation equals the uninterrupted run.
        cont, _ = sim.start(restored, n_rounds=2, key=rkey)
        direct, _ = sim.start(pool, n_rounds=2, key=key)
        for a, b in zip(jax.tree_util.tree_leaves(cont.model),
                        jax.tree_util.tree_leaves(direct.model)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestReportRoundTrip:
    def test_cohort_fields_survive_save_load_concatenate(self, key):
        from gossipy_tpu.simulation.report import SimulationReport
        sim = make_sim(nominal=64, cohort=16)
        pool = sim.init_cohort_pool(key)
        pool, r1 = sim.start(pool, n_rounds=3, key=key)
        pool, r2 = sim.start(pool, n_rounds=3, key=key)
        d = r1.to_dict()
        json.dumps(d)  # strict-JSON clean
        back = SimulationReport.from_dict(d)
        np.testing.assert_allclose(back.cohort_coverage,
                                   r1.cohort_coverage, rtol=1e-6)
        assert back.cohort_active_nodes.dtype.kind == "i"
        cat = SimulationReport.concatenate([r1, r2])
        assert cat.cohort_coverage.shape == (6,)
        assert (cat.cohort_active_nodes == 16).all()
