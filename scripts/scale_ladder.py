"""Self-forensic scale ladder: predicted-vs-measured cost per N rung.

Walks an N ladder (default 1k → 2k → 5k → 10k → 20k → 50k → 100k) of the
``bench.py --scale`` configuration (SparseTopology CSR, LogReg SGD, PUSH,
capped evaluation) and records, per rung:

- **predicted** memory (:meth:`GossipSimulator.memory_budget`, the
  construction-time paper budget) and per-round FLOPs
  (:func:`telemetry.cost.analytic_round_cost`, the model-side estimate),
  plus a linear-in-N time prediction from the first measured rung;
- **measured** ms/round, rounds/s and MFU estimate (the engine's
  ``perf=`` timing), and the compiled program's OWN account of itself —
  ``cost_analysis()`` FLOPs and ``memory_analysis()`` peak bytes, banked
  at compile time by the perf layer.

Every rung runs under the :class:`~gossipy_tpu.telemetry.FlightRecorder`
with sentinels on, so the ~50k on-TPU crash the ROADMAP still carries
produces, instead of a lost traceback: an exception repro bundle, and a
ladder verdict naming the failing rung, the failing program and its
``memory_analysis()`` numbers, and the last healthy rung. The banked
evidence means the crash is attributable even when the process dies
without a traceback — the crash-forensics gap ``bench.py --scale``'s
phase stamps only narrated.

Artifacts (``--out DIR``):

- ``ladder.json`` — ``{"rungs": [...], "verdict": {...} | null}``
- ``ladder.md`` — BASELINE.md-ready markdown rows
- ``rung_<N>/bundle_*/`` — the flight-recorder bundle of a failed rung

Usage (repo root):
    python scripts/scale_ladder.py                  # the full ladder
    python scripts/scale_ladder.py --smoke          # 4 tiny CPU rungs
    python scripts/scale_ladder.py --rungs 1000,5000,20000 --rounds 50
    python scripts/scale_ladder.py --smoke --fail-at 24   # forensics demo:
        # rung 24 raises at execution time (after its program compiled,
        # the realistic OOM shape) and the verdict names it

Exit codes: 0 clean ladder, 1 a rung failed (verdict written), 2 usage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

DEFAULT_RUNGS = (1_000, 2_000, 5_000, 10_000, 20_000, 50_000, 100_000)
SMOKE_RUNGS = (16, 24, 32, 48)
# --cohort rungs: NOMINAL populations at a fixed materialized cohort C —
# the rungs the materialized ladder cannot climb at all (simulation.cohort
# decouples per-round cost from N; the interesting measure per rung is
# pool-residency bytes vs the materialized prediction).
COHORT_RUNGS = (1_000_000, 10_000_000)
SMOKE_COHORT_RUNGS = (20_000, 50_000)


def build_rung_sim(n_nodes: int, degree: int, rounds: int,
                   history_dtype: str = "float32"):
    """One rung's simulator: the ``bench.py --scale`` configuration with
    sentinels (FlightRecorder contract) and perf (cost/timing banking)
    on. Synthetic spambase-shaped data, 4 samples/node, eval capped the
    same way ``bench._scale_harness`` caps it — the metric is engine
    cost, not the learning curve."""
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
        SparseTopology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    d = 57
    rng = np.random.default_rng(42)
    w = rng.normal(size=d)
    X = rng.normal(size=(4 * n_nodes, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    eval_cap = min(2048, max(1, int(0.2 * len(X))))
    disp = DataDispatcher(
        ClassificationDataHandler(X, y, test_size=eval_cap / len(X)),
        n=n_nodes, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(d, 2),
                         loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.1),
                         local_epochs=1, batch_size=4, n_classes=2,
                         input_shape=(d,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    topo = SparseTopology.random_regular(n_nodes, min(degree, n_nodes - 1),
                                         seed=42)
    return GossipSimulator(handler, topo, disp.stacked(), delta=100,
                           protocol=AntiEntropyProtocol.PUSH,
                           sampling_eval=0.01, eval_every=rounds,
                           history_dtype=history_dtype,
                           sentinels=True, perf=True)


def build_cohort_rung_sim(nominal_n: int, cohort_size: int, rounds: int,
                          history_dtype: str = "float32",
                          prefetch: int = 0):
    """A --cohort rung's simulator: the same LogReg round shape at a
    fixed materialized cohort C over a NOMINAL population of nominal_n
    (NominalTopology — resample-mode cohorts never read edges, so no
    O(N) graph is built). The data bank is 4C shards; node i reads shard
    i % P (the cohort scaling story, not a shortcut)."""
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import CohortConfig, GossipSimulator, \
        NominalTopology

    d = 57
    cohort_size = min(cohort_size, nominal_n)
    pool_shards = min(nominal_n, 4 * cohort_size)
    rng = np.random.default_rng(42)
    w = rng.normal(size=d)
    X = rng.normal(size=(4 * pool_shards, d)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    eval_cap = min(2048, max(1, int(0.2 * len(X))))
    disp = DataDispatcher(
        ClassificationDataHandler(X, y, test_size=eval_cap / len(X)),
        n=pool_shards, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(d, 2),
                         loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.1),
                         local_epochs=1, batch_size=4, n_classes=2,
                         input_shape=(d,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    return GossipSimulator(handler, NominalTopology(nominal_n),
                           disp.stacked(), delta=100,
                           protocol=AntiEntropyProtocol.PUSH,
                           sampling_eval=0.01, eval_every=rounds,
                           history_dtype=history_dtype,
                           cohort=CohortConfig(size=cohort_size,
                                               prefetch=prefetch),
                           sentinels=True, perf=True)


def _stamp(msg: str) -> None:
    # The bench.py --scale discipline: phase-stamped progress so a dead
    # run's last words name where it died even without a traceback.
    print(f"[ladder] {time.strftime('%H:%M:%S')} {msg}",
          file=sys.stderr, flush=True)


def _inject_fault(sim, n_nodes: int) -> None:
    """--fail-at: make this rung's run raise AT EXECUTION TIME — after
    its round program compiled and banked its CostReport — the realistic
    OOM shape (XLA allocates the big buffers when the program runs, not
    when it compiles). The hook rides the perf layer's post-run timing
    call, so the recorder sees an exception out of ``sim.start`` exactly
    like a real RESOURCE_EXHAUSTED."""
    def boom(*a, **k):
        raise RuntimeError(
            f"RESOURCE_EXHAUSTED: injected ladder fault at rung "
            f"{n_nodes} (--fail-at)")
    sim._attach_perf_stats = boom


def run_rung(n_nodes: int, degree: int, rounds: int, out_dir: str,
             history_dtype: str, fail: bool,
             prev: dict | None, cohort_size: int | None = None,
             prefetch: int = 0) -> dict:
    """Run one rung; returns its ladder row. Raises on rung failure with
    ``row_so_far`` / ``bundle`` attached to the exception (the driver
    turns that into the verdict). With ``cohort_size`` the rung runs in
    active-cohort mode: ``n_nodes`` is the NOMINAL population, the row
    gains ``nominal_n`` + pool-residency-vs-materialized accounting, and
    the measured columns price the [C]-wide segment loop."""
    import jax

    from gossipy_tpu.telemetry import FlightRecorder

    row: dict = {"n_nodes": n_nodes, "degree": degree, "rounds": rounds,
                 "history_dtype": history_dtype}
    _stamp(f"rung {n_nodes}: building topology+simulator")
    t0 = time.perf_counter()
    if cohort_size:
        row["nominal_n"] = n_nodes
        row["cohort_size"] = min(cohort_size, n_nodes)
        if prefetch:
            row["prefetch"] = prefetch
        sim = build_cohort_rung_sim(n_nodes, cohort_size, rounds,
                                    history_dtype, prefetch=prefetch)
    else:
        sim = build_rung_sim(n_nodes, degree, rounds, history_dtype)
    row["build_seconds"] = round(time.perf_counter() - t0, 2)

    budget = sim.memory_budget()
    analytic = None
    try:
        from gossipy_tpu.telemetry import analytic_round_cost
        analytic = analytic_round_cost(sim)
    except Exception:
        pass
    row["predicted"] = {
        "total_bytes": budget.get("total_bytes"),
        "history_ring_bytes": budget.get("history_ring_bytes"),
        "eval_peak_bytes": budget.get("eval_peak_bytes"),
        # Cohort rungs: the pool-residency-vs-materialized pair (None on
        # materialized rungs).
        "pool_resident_bytes": budget.get("cohort_pool_resident"),
        "materialized_prediction_bytes":
            budget.get("cohort_materialized_prediction"),
        "flops_per_round": (analytic or {}).get("flops_per_round"),
        "flops_per_round_executed":
            (analytic or {}).get("flops_per_round_executed"),
        # Linear-in-N extrapolation from the previous measured rung: the
        # sparse round program's dominant terms all scale with N, so a
        # super-linear measured/predicted ratio is itself a finding. A
        # cohort rung's round is [C]-wide at fixed C — the prediction is
        # FLAT in nominal N, and a measured slope is itself a finding
        # (it would mean the pool gathers, not the round, dominate).
        "ms_per_round": (
            None if prev is None or not prev.get("measured")
            else prev["measured"]["ms_per_round"]
            * (1.0 if cohort_size else n_nodes / prev["n_nodes"])),
    }
    _stamp(f"rung {n_nodes}: predicted "
           f"{(budget.get('total_bytes') or 0) / 2**20:.1f} MB, "
           f"analytic {(row['predicted']['flops_per_round'] or 0) / 1e6:.1f}"
           " MFLOP/round")

    # Predict-and-refuse BEFORE any state is built or launched: a rung
    # whose construction-time budget exceeds the device (or
    # $GOSSIPY_TPU_MEMORY_LIMIT) limit becomes a named ladder verdict
    # instead of an opaque allocator OOM mid-run.
    try:
        sim.check_memory_budget()
    except Exception as e:
        e.ladder_row = row  # type: ignore[attr-defined]
        e.ladder_sim = sim  # type: ignore[attr-defined]
        raise

    suffix = f"_p{prefetch}" if prefetch else ""
    rung_dir = os.path.join(out_dir, f"rung_{n_nodes}{suffix}")
    os.makedirs(rung_dir, exist_ok=True)
    rec = FlightRecorder(rung_dir, chunk=rounds)
    key = jax.random.PRNGKey(42)
    if cohort_size:
        _stamp(f"rung {n_nodes}: init_cohort_pool (C {row['cohort_size']})")
        state = sim.init_cohort_pool(key)
    else:
        _stamp(f"rung {n_nodes}: init_nodes")
        state = sim.init_nodes(key)
    if fail:
        _inject_fault(sim, n_nodes)
    _stamp(f"rung {n_nodes}: compile + {rounds}-round run "
           "(flight recorder armed)")
    try:
        state, reports, bundle = rec.run(sim, state, n_rounds=rounds,
                                         key=key)
    except Exception as e:
        e.ladder_row = row  # type: ignore[attr-defined]
        e.ladder_bundle = rec.bundle_path  # type: ignore[attr-defined]
        e.ladder_sim = sim  # type: ignore[attr-defined]
        raise
    if bundle is not None:
        e = RuntimeError(f"rung {n_nodes}: sentinel tripped "
                         f"(bundle at {bundle})")
        e.ladder_row = row  # type: ignore[attr-defined]
        e.ladder_bundle = bundle  # type: ignore[attr-defined]
        e.ladder_sim = sim  # type: ignore[attr-defined]
        raise e

    last = sim._perf_last or {}
    cr = sim._cost_reports[-1].to_dict() if sim._cost_reports else {}
    ms = last.get("ms_per_round")
    row["measured"] = {
        "ms_per_round": ms,
        "rounds_per_sec": (round(1e3 / ms, 3) if ms else None),
        "mfu_est": last.get("mfu_est"),
        "flops_per_round_xla": cr.get("flops"),
        "bytes_per_round_xla": cr.get("bytes_accessed"),
        "hbm_peak_bytes": cr.get("peak_bytes"),
        "temp_bytes": cr.get("temp_bytes"),
        "compile_seconds": sim.last_compile_seconds,
        "program": cr.get("label"),
    }
    pred_ms = row["predicted"]["ms_per_round"]
    if pred_ms and ms:
        row["time_predicted_over_measured"] = round(pred_ms / ms, 3)
    pred_b = row["predicted"]["total_bytes"]
    meas_b = row["measured"]["hbm_peak_bytes"]
    if pred_b and meas_b:
        row["memory_predicted_over_measured"] = round(pred_b / meas_b, 3)
    _stamp(f"rung {n_nodes}: {ms and round(ms, 2)} ms/round, "
           f"hbm peak {(meas_b or 0) / 2**20:.1f} MB")
    return row


def _verdict_for(exc: Exception, n_nodes: int,
                 last_healthy: int | None) -> dict:
    """The ladder verdict: name the failing rung, the failing program
    and its memory_analysis numbers (banked at compile time — available
    even when the failure lost its traceback), and the last healthy
    rung. Falls back to the construction-time memory budget when the
    rung died before its program compiled."""
    sim = getattr(exc, "ladder_sim", None)
    row = getattr(exc, "ladder_row", None) or {}
    program = None
    memory = None
    if sim is not None and getattr(sim, "_cost_reports", None):
        cr = sim._cost_reports[-1]
        program = cr.label
        memory = {k: v for k, v in cr.to_dict().items()
                  if k.endswith("_bytes") or k == "peak_bytes"}
    if memory is None:
        program = "uncompiled (failed before/at compile)"
        memory = {"memory_budget_fallback": row.get("predicted")}
    verdict = {
        "failed_rung": n_nodes,
        "last_healthy_rung": last_healthy,
        "program": program,
        "memory_analysis": memory,
        "predicted": row.get("predicted"),
        "error": repr(exc)[:500],
        "bundle": getattr(exc, "ladder_bundle", None),
    }
    # A memory-budget refusal (predict-and-refuse, engine
    # check_memory_budget) is a NAMED degrade, not a crash: the verdict
    # carries the dominant budget term so the ladder.md reader knows
    # which knob (N, history depth, cohort mode) to turn.
    if type(exc).__name__ == "MemoryBudgetExceeded":
        verdict["degrade_reason"] = "memory_budget_refused"
        verdict["dominant_term"] = getattr(exc, "dominant_term", None)
        verdict["predicted_bytes"] = getattr(exc, "predicted_bytes", None)
        verdict["limit_bytes"] = getattr(exc, "limit_bytes", None)
        verdict["program"] = "refused before launch (memory budget)"
    return verdict


def _markdown(rows: list, verdict: dict | None) -> str:
    lines = [
        "| N | nominal_n | predicted MB | pool MB | hbm peak MB | "
        "ms/round | rounds/s | MFU est | stream× | pred/meas time |",
        "|---|---|---|---|---|---|---|---|---|---|",
    ]

    def mb(v):
        return f"{v / 2**20:.1f}" if v else "—"
    for r in rows:
        m = r.get("measured") or {}
        p = r.get("predicted") or {}
        mfu = m.get("mfu_est")
        # Materialized rungs: N IS the materialized width and nominal_n
        # repeats it; cohort rungs materialize only C and carry the
        # nominal population + pool residency here. --stream pairs show
        # the prefetch depth next to the width and the measured speedup
        # over their serial twin.
        width = r.get("cohort_size") or r["n_nodes"]
        wcell = (f"{width:,} (pf {r['prefetch']})"
                 if r.get("prefetch") else f"{width:,}")
        spd = r.get("stream_speedup")
        lines.append(
            f"| {wcell} "
            f"| {r.get('nominal_n', r['n_nodes']):,} "
            f"| {mb(p.get('total_bytes'))} "
            f"| {mb(p.get('pool_resident_bytes'))} "
            f"| {mb(m.get('hbm_peak_bytes'))} "
            f"| {m.get('ms_per_round') and round(m['ms_per_round'], 2)} "
            f"| {m.get('rounds_per_sec') or '—'} "
            f"| {f'{mfu:.4f}' if mfu is not None else 'null'} "
            f"| {f'{spd:.2f}x' if spd else ''} "
            f"| {r.get('time_predicted_over_measured') or '—'} |")
    if verdict is not None:
        lines.append("")
        refused = verdict.get("degrade_reason") == "memory_budget_refused"
        lines.append(
            f"**{'REFUSED' if refused else 'FAILED'}** at rung "
            f"{verdict['failed_rung']:,} "
            f"(last healthy: {verdict['last_healthy_rung']}): "
            f"program `{verdict['program']}`, "
            + (f"dominant budget term `{verdict.get('dominant_term')}`, "
               if refused else "")
            + f"`{verdict['error']}`")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rungs", default=None,
                    help="comma-separated node counts "
                         "(default: 1k,2k,5k,10k,20k,50k,100k)")
    ap.add_argument("--smoke", action="store_true",
                    help=f"tiny CPU rungs {SMOKE_RUNGS} (CI smoke)")
    ap.add_argument("--rounds", type=int, default=None,
                    help="rounds per rung (default 100; 3 with --smoke)")
    ap.add_argument("--degree", type=int, default=None,
                    help="regular-graph degree (default 20; 4 with "
                         "--smoke, whose rungs are too small for 20)")
    ap.add_argument("--cohort", action="store_true",
                    help="active-cohort rungs: nominal N in "
                         f"{COHORT_RUNGS} at a fixed materialized C "
                         "(--cohort-size); ladder.md gains the nominal_n "
                         "and pool-residency columns")
    ap.add_argument("--cohort-size", type=int, default=None,
                    help="materialized cohort width C for --cohort "
                         "(default 1024; 64 with --smoke)")
    ap.add_argument("--stream", action="store_true",
                    help="with --cohort: run each rung as a serial + "
                         "streaming (prefetch) pair; the streaming row "
                         "gains stream_speedup over its serial twin")
    ap.add_argument("--prefetch", type=int, default=2,
                    help="prefetch depth for --stream rows (default 2)")
    ap.add_argument("--out", default="ladder-artifacts")
    ap.add_argument("--history-dtype", default="float32",
                    choices=("float32", "bfloat16", "int8"))
    ap.add_argument("--fail-at", type=int, default=None, metavar="N",
                    help="inject an execution-time fault at rung N "
                         "(forensics self-test: the verdict must name it)")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="run-ledger file to append one digest row per "
                         "rung to (default: $GOSSIPY_TPU_LEDGER)")
    args = ap.parse_args(argv)

    if args.rungs:
        try:
            rungs = tuple(int(x) for x in args.rungs.split(","))
        except ValueError:
            print(f"[ladder] unparsable --rungs {args.rungs!r}",
                  file=sys.stderr)
            return 2
        if any(r < 2 for r in rungs):
            print("[ladder] rungs must be >= 2", file=sys.stderr)
            return 2
    elif args.cohort:
        rungs = SMOKE_COHORT_RUNGS if args.smoke else COHORT_RUNGS
    else:
        rungs = SMOKE_RUNGS if args.smoke else DEFAULT_RUNGS
    rounds = args.rounds or (3 if args.smoke else 100)
    degree = args.degree or (4 if args.smoke else 20)
    cohort_size = None
    if args.cohort:
        cohort_size = args.cohort_size or (64 if args.smoke else 1024)
    if args.stream and not args.cohort:
        print("[ladder] --stream requires --cohort", file=sys.stderr)
        return 2
    os.makedirs(args.out, exist_ok=True)

    # A wedged accelerator tunnel must degrade to CPU, not hang the
    # ladder (the bench.py / profile_round.py discipline).
    import _virtual_mesh
    ok, detail = _virtual_mesh.probe_backend_alive()
    if not ok:
        print(f"[ladder] backend unreachable ({detail}); re-exec on CPU",
              file=sys.stderr)
        env = _virtual_mesh.virtual_mesh_env(1, extra_path=_REPO)
        os.execve(sys.executable, [sys.executable] + sys.argv, env)

    import jax

    from gossipy_tpu import enable_compilation_cache
    enable_compilation_cache()
    _stamp(f"backend {jax.default_backend()} "
           f"({jax.devices()[0].device_kind}); rungs {rungs}, "
           f"{rounds} rounds/rung")

    rows: list = []
    verdict = None
    last_healthy = None
    for n in rungs:
        try:
            row = run_rung(n, degree, rounds, args.out,
                           args.history_dtype, fail=(args.fail_at == n),
                           prev=rows[-1] if rows else None,
                           cohort_size=cohort_size)
            if args.stream:
                # The rung's streaming twin: same config + prefetch.
                # Both rows land on the ladder; the streaming one prices
                # the pipeline against its serial sibling.
                srow = run_rung(n, degree, rounds, args.out,
                                args.history_dtype, fail=False,
                                prev=None, cohort_size=cohort_size,
                                prefetch=args.prefetch)
                ser_ms = (row.get("measured") or {}).get("ms_per_round")
                st_ms = (srow.get("measured") or {}).get("ms_per_round")
                if ser_ms and st_ms:
                    srow["stream_speedup"] = round(ser_ms / st_ms, 3)
                    _stamp(f"rung {n}: stream pair "
                           f"{srow['stream_speedup']}x (serial "
                           f"{ser_ms:.2f} -> prefetch {st_ms:.2f} "
                           "ms/round)")
        except Exception as e:
            verdict = _verdict_for(e, n, last_healthy)
            rows.append(getattr(e, "ladder_row", None)
                        or {"n_nodes": n, "failed": True})
            _stamp(f"rung {n} FAILED: {verdict['error']} "
                   f"(program {verdict['program']}; "
                   f"bundle {verdict['bundle']})")
            break
        rows.append(row)
        if args.stream:
            rows.append(srow)
        last_healthy = n

    out = {"schema": 2,  # v2: + nominal_n/cohort_size/pool columns
           "backend": jax.default_backend(),
           "cohort_size": cohort_size,
           "device_kind": jax.devices()[0].device_kind,
           "rounds_per_rung": rounds,
           "rungs": rows,
           "verdict": verdict}
    path = os.path.join(args.out, "ladder.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=2)
        fh.write("\n")
    md_path = os.path.join(args.out, "ladder.md")
    with open(md_path, "w") as fh:
        fh.write(_markdown([r for r in rows if "predicted" in r], verdict))
    _stamp(f"wrote {path} and {md_path} "
           f"({len(rows)} rungs{'; VERDICT' if verdict else ''})")
    try:
        # Run-ledger ingest (telemetry.ledger): one digest row per rung
        # plus a failure row for the verdict — opt-in via --ledger or
        # the GOSSIPY_TPU_LEDGER env var, best-effort.
        from gossipy_tpu.telemetry.ledger import (ingest_ladder,
                                                  resolve_ledger)
        led = resolve_ledger(args.ledger or None)
        if led is not None:
            n = len(ingest_ladder(led, out, path=path))
            _stamp(f"ledger: {n} row(s) -> {led.path}")
    except Exception as e:
        _stamp(f"ledger ingest failed: {e!r}")
    return 1 if verdict else 0


if __name__ == "__main__":
    sys.exit(main())
