"""Gossip-as-a-service CLI: JSON run specs in, per-tenant artifacts out.

The service front door for batch submission (docs/service.md): each spec
file carries one tenant spec or a ``{"tenants": [...]}`` list; the
scheduler packs every tenant into shape buckets (one compiled megabatch
program per bucket), drives them cooperatively, and writes per-tenant
``report.json`` / ``manifest.json`` / ``events.jsonl`` (plus a
``bundle_*/`` flight-recorder directory for any sentinel-evicted tenant)
under ``--out/<tenant>/``, with a ``service_summary.json`` at the root.

Spec format (see :mod:`gossipy_tpu.service.spec`)::

    {"tenant": "alice-lr01",
     "config": { ... ExperimentConfig fields ... },
     "n_rounds": 200}

Stdout carries ONE summary JSON line (bench.py's contract style); the
human-readable per-tenant table goes to stderr. Exit status: 0 when every
tenant ended DONE or EVICTED (eviction is the service WORKING — the
tenant's failure was isolated and its bundle written), 1 when any tenant
FAILED (its bucket's program raised or its spec didn't build).

Usage::

    python scripts/serve.py specs/*.json --out runs/
    python scripts/serve.py all.json --out runs/ --slice 50
    python scripts/serve.py all.json --out runs/ --metrics-dir runs/metrics
    # ... and in another terminal:
    python scripts/service_top.py runs/metrics
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def load_specs(paths: list[str]) -> list:
    """Parse spec files into RunRequests (single-object or tenant-list
    files both accepted; tenant names must be unique across all files)."""
    from gossipy_tpu.service import RunRequest

    requests = []
    for path in paths:
        with open(path) as fh:
            doc = json.load(fh)
        specs = doc["tenants"] if isinstance(doc, dict) and "tenants" in doc \
            else [doc]
        for spec in specs:
            requests.append(RunRequest.from_spec(spec))
    seen = set()
    for r in requests:
        if r.tenant in seen:
            raise ValueError(f"duplicate tenant name {r.tenant!r} across "
                             "the given specs")
        seen.add(r.tenant)
    return requests


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("specs", nargs="+", help="JSON spec file(s)")
    ap.add_argument("--out", default="service-runs",
                    help="artifact root (one subdir per tenant)")
    ap.add_argument("--slice", type=int, default=25,
                    help="rounds per cooperative scheduling slice")
    ap.add_argument("--no-repro", action="store_true",
                    help="skip per-slice last-healthy host copies "
                         "(faster; evictions lose their repro bundles)")
    ap.add_argument("--metrics-dir", default=None,
                    help="write the SLO metrics registry here: a fresh "
                         "metrics.json snapshot every scheduling cycle "
                         "(tail it with scripts/service_top.py) plus a "
                         "final OpenMetrics metrics.prom export")
    args = ap.parse_args()

    # Shared persistent compilation cache across service processes: the
    # scheduler's whole economy is compiled-program reuse.
    from gossipy_tpu import enable_compilation_cache
    enable_compilation_cache()

    from gossipy_tpu.service import GossipService, RunQueue, RunStatus

    requests = load_specs(args.specs)
    queue = RunQueue()
    handles = [queue.submit(r) for r in requests]
    svc = GossipService(args.out, slice_rounds=args.slice,
                        keep_repro=not args.no_repro,
                        metrics_dir=args.metrics_dir)
    summary = svc.serve(queue)

    for h in handles:
        line = (f"[serve] {h.tenant}: {h.status.value} "
                f"({h.rounds_completed}/{h.request.rounds} rounds)")
        if h.report is not None:
            try:
                acc = h.report.final("accuracy")
                line += f" accuracy={acc:.4f}"
            except Exception:
                pass
        if h.bundle_path:
            line += f" bundle={h.bundle_path}"
        if h.error:
            line += f" error={h.error}"
        print(line, file=sys.stderr)
    print(f"[serve] {summary['n_tenants']} tenant(s) in "
          f"{summary['n_buckets']} bucket(s), "
          f"{summary['wall_seconds']}s -> {summary['summary_path']}",
          file=sys.stderr)

    print(json.dumps({
        "n_tenants": summary["n_tenants"],
        "n_buckets": summary["n_buckets"],
        "megabatch_step_programs": summary["megabatch_step_programs"],
        "wall_seconds": summary["wall_seconds"],
        "tenants": {h.tenant: h.status.value for h in handles},
        "out_dir": summary["out_dir"],
    }))
    return 1 if any(h.status is RunStatus.FAILED for h in handles) else 0


if __name__ == "__main__":
    sys.exit(main())
