#!/usr/bin/env bash
# The deferred TPU measurement list (round-2/3 VERDICT "deliver the TPU
# evidence"): run every bench mode on the real chip and append the raw JSON
# lines to BENCH_TPU_EVIDENCE.jsonl for BASELINE.md.
#
# Each mode's outer timeout is sized as probe (150s) + the watchdog deadline
# bench.py computes for that mode + CPU-fallback headroom, so even a mid-run
# tunnel wedge ends inside the budget with a labeled degraded row (bench.py
# kills the wedged accelerator child itself and re-runs on CPU).
#
# Usage: bash scripts/run_tpu_evidence.sh   (from the repo root)
set -u
cd "$(dirname "$0")/.."
OUT=BENCH_TPU_EVIDENCE.jsonl
echo "# $(date -Is) tpu evidence run" >> "$OUT"
# Single source of truth for the budget: bench.py owns the mode-aware
# watchdog deadline (main(), incl. any GOSSIPY_TPU_BENCH_DEADLINE override);
# the script queries it with --print-deadline (jax-free, answers even while
# the tunnel is wedged) and derives the outer timeout as probe (150s) +
# deadline + CPU-fallback headroom (1200s), so the two can never drift.
run_mode() {  # run_mode [bench args...]
    local d t
    d=$(python bench.py --print-deadline "$@") || d=4000
    t=$((d + 1350))
    echo "=== $(date -Is) bench.py $* (deadline ${d}s, timeout ${t}s)" >&2
    timeout -k 60 "$t" python bench.py "$@" 2> >(tail -5 >&2) | tail -1 | \
        tee -a "$OUT"
}
run_mode                           # north-star
run_mode --mfu 50
run_mode --scale 50000
run_mode --scale 100000            # CPU fallback alone is ~12 min
run_mode --scale-all2all 50000
run_mode --fused-regime            # two full CNN-clique compiles
run_mode --ring-attn 8192          # flash kernel vs XLA dense attention
# Phase attribution for the MFU attack (VERDICT #2) — grab it while the
# tunnel is up; rows are self-labeled with backend/device_kind.
for pargs in "" "--cnn"; do
    echo "=== $(date -Is) profile_round.py $pargs" >&2
    # shellcheck disable=SC2086
    timeout -k 60 2400 python scripts/profile_round.py $pargs \
        2> >(tail -3 >&2) | tail -1 | tee -a "$OUT"
done
echo "done; rows appended to $OUT" >&2
