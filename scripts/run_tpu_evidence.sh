#!/usr/bin/env bash
# The deferred TPU measurement list (round-2/3 VERDICT "deliver the TPU
# evidence"): run every bench mode on the real chip and append the raw JSON
# lines to BENCH_TPU_EVIDENCE.jsonl for BASELINE.md. Each mode is
# timeout-guarded; bench.py itself degrades to a labeled CPU fallback if the
# tunnel dies mid-list, so a partial run still records labeled rows.
#
# Usage: bash scripts/run_tpu_evidence.sh   (from the repo root)
set -u
cd "$(dirname "$0")/.."
OUT=BENCH_TPU_EVIDENCE.jsonl
echo "# $(date -Is) tpu evidence run" >> "$OUT"
for args in "" "--mfu 50" "--scale 50000" "--scale 100000" \
            "--scale-all2all 50000" "--fused-regime"; do
    echo "=== bench.py $args" >&2
    # shellcheck disable=SC2086
    timeout 3000 python bench.py $args 2> >(tail -5 >&2) | tail -1 | \
        tee -a "$OUT"
done
echo "done; rows appended to $OUT" >&2
