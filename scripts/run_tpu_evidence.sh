#!/usr/bin/env bash
# The deferred TPU measurement list (round-2/3 VERDICT "deliver the TPU
# evidence"): run every bench mode on the real chip and append the raw JSON
# lines to BENCH_TPU_EVIDENCE.jsonl for BASELINE.md.
#
# Ordering is tunnel-window-aware (2026-07-31: the tunnel stayed healthy for
# ~30 min, long enough for exactly two modes, then wedged): the modes still
# missing a genuine TPU row run FIRST, cheapest first, so a short window
# banks the most new evidence; already-captured modes rerun at the end as
# second samples. Full per-mode stderr lands in evidence_logs/ (the earlier
# tail-5 filter truncated the one traceback of the --scale on-TPU crash).
#
# Usage: bash scripts/run_tpu_evidence.sh   (from the repo root)
set -u
cd "$(dirname "$0")/.."
OUT=BENCH_TPU_EVIDENCE.jsonl
# Disable bench.py's internal probe-retry loop for the WHOLE script —
# including the --print-deadline queries, so the derived outer timeouts
# don't carry an unused poll budget. This script's outer loop
# (poll_and_capture_evidence.sh) already polls; a mid-list wedge should
# degrade fast and let the next attempt retry.
export GOSSIPY_TPU_BENCH_PROBE_POLL=0
# One log dir per attempt: the poll loop reruns this script on every
# successful probe, and a plain truncating redirect would destroy attempt
# N's traceback the moment attempt N+1 starts.
LOGDIR=evidence_logs/$(date +%Y%m%dT%H%M%S)
mkdir -p "$LOGDIR"
echo "# $(date -Is) tpu evidence run (logs: $LOGDIR)" >> "$OUT"
# Single source of truth for the budget: bench.py owns the mode-aware
# watchdog deadline (main(), incl. any GOSSIPY_TPU_BENCH_DEADLINE override);
# the script queries it with --print-deadline (jax-free, answers even while
# the tunnel is wedged) and derives the outer timeout as probe (150s) +
# deadline + CPU-fallback headroom (1200s), so the two can never drift.
# run_script <tag> <timeout_s> <cmd...>: the one place the invocation
# policy lives — timestamp header, traceback filtering off, full stderr to
# $LOGDIR/<tag>.err (streamed live via tee), last stdout line appended to
# $OUT.
run_script() {
    local tag=$1 t=$2
    shift 2
    echo "=== $(date -Is) $* (timeout ${t}s)" >&2
    # tee keeps the full traceback on disk AND streams progress live — a
    # 27-minute mode inside a short tunnel window must stay observable.
    JAX_TRACEBACK_FILTERING=off timeout -k 60 "$t" "$@" \
        2> >(tee "$LOGDIR/$tag.err" >&2) | tail -1 | tee -a "$OUT"
}
run_mode() {  # run_mode [bench args...]
    local d
    d=$(python bench.py --print-deadline "$@") || d=4000
    run_script "$(echo "mode${*:-_northstar}" | tr ' /' '__')" \
        $((d + 1350)) python bench.py "$@"
}
# --- still missing a genuine TPU row, cheapest first ---
# MFU attack rows FIRST: bench_mfu's config changed again in round 5
# (compact_deliver default-on; round 4 added eval_every=5 + einsum convs),
# so these are NEW measurements, not reruns — the r3 row (0.0039,
# eval_every=1, grouped-conv, full-width passes) is a different program.
# --mfu-wide is the same round-5 program with compaction off: the pair is
# the on-chip A/B for the compaction win (CPU A/B: 3.25x).
run_mode --mfu 50
run_mode --mfu-wide 50
run_mode --mfu-reps 8              # seed-batched throughput (MXU-filling)
run_mode --mfu-all2all 50          # the one-einsum-merge MFU upper end
run_mode --ring-attn 8192          # flash kernel vs XLA dense attention
# Phase attribution for the MFU attack (VERDICT #1); rows are self-labeled.
run_script profile_northstar 2400 python scripts/profile_round.py
run_script profile_cnn 2400 python scripts/profile_round.py --cnn
# Component attribution for the r3 261 ms/round MFU row (eval vmap-vs-map,
# merge/train slots, snapshot) — ~1 min of device time after compiles.
run_script microbench 2400 python scripts/microbench_components.py
run_mode --fused-regime            # two full CNN-clique compiles
run_mode --scale-all2all 50000
# The --scale modes crashed on-TPU in the 10:14 window (rc=1 at 27 min /
# 2.5 min; traceback lost to the old tail-5 filter) — run them late so a
# short window is not burned on a known-crashing mode, with full stderr
# kept this time.
run_mode --scale 50000
run_mode --scale 100000
# --- second sample of a row already captured 2026-07-31 10:14-10:45 ---
run_mode                           # north-star (720.32 r/s captured)
echo "done; rows appended to $OUT" >&2
