#!/usr/bin/env bash
# Poll the tunneled TPU backend and capture the deferred bench evidence the
# moment it comes back. Retry-aware: if the tunnel is up just long enough to
# pass the probe but every bench row still degrades to the CPU fallback
# (half-wedged relay), the attempt does NOT count — keep polling until at
# least one genuine accelerator row lands or the probe budget runs out.
#
# Usage: bash scripts/poll_and_capture_evidence.sh [max_probes] [sleep_s]
set -u
cd "$(dirname "$0")/.."
MAX=${1:-40}
SLEEP=${2:-300}
OUT=BENCH_TPU_EVIDENCE.jsonl
for i in $(seq 1 "$MAX"); do
    date -Is
    # -k: a backend-init hang inside a GIL-holding C call never processes
    # SIGTERM (observed: a probe outlived its timeout by 20+ min); escalate
    # to SIGKILL.
    if timeout -k 30 240 python -c \
        "import jax; assert jax.devices()[0].platform != 'cpu'" \
        2>/dev/null; then
        echo "probe $i: tunnel alive; running the evidence list"
        lines_before=$( [ -f "$OUT" ] && wc -l < "$OUT" || echo 0 )
        bash scripts/run_tpu_evidence.sh
        # Only rows appended by THIS attempt count — stale genuine rows
        # from an earlier capture must not mask an all-degraded run.
        if [ -f "$OUT" ] && tail -n +"$((lines_before + 1))" "$OUT" | \
           grep '"degraded": false' | grep -qv '"backend": "cpu"'; then
            echo "genuine accelerator rows captured; done"
            exit 0
        fi
        echo "probe passed but every row degraded (half-wedged tunnel);" \
             "continuing to poll"
    else
        echo "probe $i failed; sleeping $SLEEP"
    fi
    sleep "$SLEEP"
done
echo "gave up after $MAX probes"
exit 1
