"""Produce the CI run's inspectable trace artifacts.

Runs a tiny (seconds on one CPU core) probe- and sentinel-enabled gossip
simulation under the flight recorder and writes, into ``--out DIR``:

- ``report.json`` — the full :meth:`SimulationReport.save` record (probe
  AND health arrays included; round-trips through
  ``SimulationReport.load``),
- ``manifest.json`` — the run's :class:`RunManifest` (config, versions,
  backend, memory budget, probes, sentinels, sink counters, and the
  ``perf`` block — XLA cost/memory numbers + timing, null-safe on CPU),
- ``events.jsonl`` — the schema-v7 per-round JSONL rows,
- ``bundle_*/`` — ONLY when the run trips a sentinel or raises: the
  flight-recorder repro bundle (checkpoint + manifest + verdict +
  trailing events), which the CI workflow uploads so a red smoke run
  ships its own forensics. ``scripts/replay_bundle.py --demo <bundle>``
  replays it.

``.github/workflows/ci.yml`` uploads the directory on every run, so each
CI run leaves a machine-readable trace of what the engine computed — not
just a green check. The script exits non-zero on any internal
inconsistency (a cheap end-to-end smoke on top of the artifact).

Usage: ``python scripts/ci_smoke_artifact.py --out ci-artifacts``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_smoke_sim(nodes: int = 16, probes: bool = True,
                    sentinels: bool = True, perf: bool = True):
    """The CI smoke configuration, factored out so
    ``scripts/replay_bundle.py --demo`` can rebuild the IDENTICAL
    simulator to replay a smoke-run bundle (the replay contract: same
    config, same data, same topology seed)."""
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
        Topology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    rng = np.random.default_rng(42)
    d = 12
    X = rng.normal(size=(20 * nodes, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) > 0).astype(np.int64)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=42)
    disp = DataDispatcher(dh, n=nodes, eval_on_user=False)
    handler = SGDHandler(
        model=LogisticRegression(d, 2), loss=losses.cross_entropy,
        optimizer=optax.sgd(0.1), local_epochs=1, batch_size=8, n_classes=2,
        input_shape=(d,), create_model_mode=CreateModelMode.MERGE_UPDATE)
    return GossipSimulator(
        handler, Topology.random_regular(nodes, 4, seed=42),
        disp.stacked(), delta=20, protocol=AntiEntropyProtocol.PUSH,
        probes=probes, sentinels=sentinels, perf=perf)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="ci-artifacts",
                    help="output directory (created if absent)")
    ap.add_argument("--rounds", type=int, default=6)
    ap.add_argument("--nodes", type=int, default=16)
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    import jax

    from gossipy_tpu.simulation import JSONLinesReceiver
    from gossipy_tpu.simulation.report import SimulationReport
    from gossipy_tpu.telemetry import FlightRecorder

    sim = build_smoke_sim(args.nodes)

    key = jax.random.PRNGKey(42)
    state = sim.init_nodes(key)
    jsonl_path = os.path.join(args.out, "events.jsonl")
    recorder = FlightRecorder(args.out, chunk=args.rounds)
    with JSONLinesReceiver(jsonl_path) as rx:
        sim.add_receiver(rx)
        state, reports, bundle = recorder.run(sim, state,
                                              n_rounds=args.rounds, key=key)
    report = reports[0] if len(reports) == 1 else \
        SimulationReport.concatenate(reports)

    report_path = report.save(os.path.join(args.out, "report.json"))
    manifest_path = sim.run_manifest(
        extra={"ci_smoke": True}).save(os.path.join(args.out,
                                                    "manifest.json"))
    if bundle is not None:
        # A tripped smoke run still writes every artifact, then fails
        # loudly — the workflow uploads the bundle for replay.
        print("[ci-smoke] SENTINEL TRIPPED — flight-recorder bundle at "
              f"{bundle}", file=sys.stderr)
        sys.exit(2)

    # Consistency gates: the artifacts must actually round-trip.
    loaded = SimulationReport.load(report_path)
    assert np.array_equal(loaded.sent_per_round, report.sent_per_round)
    assert np.array_equal(loaded.probe_stale_hist, report.probe_stale_hist)
    hist_sums = report.probe_stale_hist.sum(axis=1)
    accepted = report.probe_accepted_per_node.sum(axis=1)
    assert np.array_equal(hist_sums, accepted), (hist_sums, accepted)
    # Health block: a healthy smoke run is provably clean end to end.
    assert np.array_equal(loaded.health_trip, report.health_trip)
    assert (report.health_trip == 0).all(), report.health_trip
    assert int(report.health_nonfinite_params.sum()) == 0
    assert (report.health_first_bad_slot == -1).all()
    assert np.isfinite(report.health_delta_norm).all()
    assert report.health_layer_names == loaded.health_layer_names
    rows = [JSONLinesReceiver.parse_line(l) for l in open(jsonl_path)]
    assert len(rows) == args.rounds
    assert all(r["probes"] is not None for r in rows)
    assert all(r["health"] is not None for r in rows)
    assert all(r["health"]["trip"] is False for r in rows)
    manifest = json.load(open(manifest_path))
    assert manifest["config"]["probes"] is not None
    assert manifest["config"]["sentinels"] is not None
    # Performance-observability block: present and null-safe on CPU —
    # real FLOP/byte/compile numbers, MFU null (no CPU peak entry), and
    # the per-round perf rows in the report/JSONL (ISSUE-10 acceptance).
    perf = manifest["perf"]
    assert perf is not None and perf["config"]["timing"]
    assert perf["flops_per_round_xla"] and perf["flops_per_round_xla"] > 0
    assert perf["bytes_per_round_xla"] and perf["bytes_per_round_xla"] > 0
    assert perf["compile_count"] >= 1
    assert perf["hbm_peak_bytes"] and perf["hbm_peak_bytes"] > 0
    assert perf["last_run"] is not None \
        and perf["last_run"]["ms_per_round"] > 0
    assert perf["analytic"] is not None \
        and perf["analytic"]["flops_per_round"] > 0
    assert np.isfinite(report.perf_round_ms).all() \
        and (report.perf_round_ms > 0).all()
    assert np.array_equal(loaded.perf_round_ms, report.perf_round_ms)
    assert all(r["perf"] is not None and r["perf"]["round_ms"] > 0
               for r in rows)
    print(f"[ci-smoke] wrote {report_path}, {manifest_path}, {jsonl_path} "
          f"({args.rounds} rounds, {args.nodes} nodes, "
          f"{int(accepted.sum())} accepted merges, 0 sentinel trips)")


if __name__ == "__main__":
    main()
