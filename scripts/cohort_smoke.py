"""CI smoke for active-cohort mode (simulation.cohort): accounting proofs.

Runs a small cohort simulation (nominal N = 96, C = 24, zero fault rates)
and self-checks the properties the ISSUE-14 acceptance names:

1. **Sampled-round accounting** — with ``drop_prob=0`` / ``online_prob=1``
   / sync PUSH in resample mode, every cohort node fires exactly once per
   round at a valid peer: ``sent`` per round must equal C exactly and the
   run's ``failed`` must be zero.
2. **Sequential-engine cohort replay, bit-for-bit where applicable** —
   the same cohort schedule (``cohort.sample_cohort`` is deterministic in
   ``(key, round)``) replayed through :class:`SequentialGossipSimulator`
   over each round's C-node sub-population produces the SAME integer
   accounting sums (sent per round == C, failed == 0): the two engines'
   message counters agree exactly at zero fault rates even though their
   PRNG streams differ.
3. **Chunked determinism** — one 10-round run equals two 5-round runs
   bit-for-bit (pool leaves AND per-round counters): round randomness
   keys on the absolute round, cohort draws on ``(key, round)``.
4. **Checkpoint round-trip mid-run** — save the pool at round 5 via
   ``sim.save``, restore via ``sim.load`` (zero-filled pool template),
   continue: identical to the uninterrupted run, pool intact.
5. **Coverage accounting** — ``cohort_coverage`` is monotone
   non-decreasing, equals ``touched.mean()`` at the end, and
   ``cohort_active_nodes`` is C on every round.
6. **Trace accounting** — a traced run (telemetry.tracing) emits a
   Perfetto-loadable ``trace.json`` whose ``trace_report`` names
   per-round ``host_blocked_ms`` / ``overlap_frac`` for every round,
   with the attribution self-consistent: ``host_blocked + device +
   unaccounted == wall`` exactly, and the untraced gap small
   (``unaccounted_frac`` < 0.15 — the spans cover the wall).
7. **Streaming A/B** — a heavier config (C = 768, rounds_per_cohort = 2,
   device-bound segments) run serial then with ``prefetch=8``, both
   traced: the streamed pool is BIT-IDENTICAL to the serial one, the
   streaming trace's ``overlap_frac`` exceeds 0.3 while the serial one
   stays ~0, and both trace reports land in the artifacts
   (``trace_report_serial.json`` / ``trace_report_stream.json``).
8. **Nominal-100M disk pool** — ``CohortConfig(pool_dir=...)`` at
   nominal N = 100,000,000: a short streamed run completes with the
   sparse pool files allocating < 1 GB on disk (logical size ~7 GB)
   and peak RSS far below the 2.9 GB a dense float32 pool of that
   population would need — the pool was never materialized in RAM.

Artifacts (``--out DIR``): ``cohort_smoke.json`` with every checked sum,
plus ``trace.json`` / ``trace_report.json`` from the traced run and the
serial/stream A/B trace reports.
Exit 0 = all checks pass.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

N_NOMINAL, C, ROUNDS, D = 96, 24, 10, 6


def build(cohort=True):
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
        Topology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import CohortConfig, GossipSimulator

    rng = np.random.default_rng(7)
    w = rng.normal(size=D)
    X = rng.normal(size=(N_NOMINAL * 6, D)).astype(np.float32)
    y = (X @ w > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.25),
                          n=N_NOMINAL, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(D, 2),
                         loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.1), local_epochs=1,
                         batch_size=8, n_classes=2, input_shape=(D,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    topo = Topology.random_regular(N_NOMINAL, 6, seed=3)
    return GossipSimulator(
        handler, topo, disp.stacked(), delta=20,
        protocol=AntiEntropyProtocol.PUSH,
        cohort=CohortConfig(size=C) if cohort else None), disp


def seq_replay_accounting(sim, key, rounds):
    """Replay the SAME cohort schedule through the sequential engine:
    per round, rebuild the C-node sub-population (gathered data, clique
    world — the resample-mode peer universe) and run ONE eager round.
    Returns the per-round sent/failed sums."""
    import jax

    from gossipy_tpu.core import AntiEntropyProtocol, Topology
    from gossipy_tpu.simulation import SequentialGossipSimulator
    from gossipy_tpu.simulation.cohort import sample_cohort

    sent, failed = [], []
    for r in range(rounds):
        idx = sample_cohort(key, r, N_NOMINAL, C)
        data_c = {k: (np.asarray(v) if k in ("x_eval", "y_eval")
                      else np.asarray(v)[idx])
                  for k, v in sim.data.items()}
        seq = SequentialGossipSimulator(
            sim.handler, Topology.clique(C), data_c, delta=sim.delta,
            protocol=AntiEntropyProtocol.PUSH)
        st = seq.init_nodes(jax.random.fold_in(key, r), local_train=False)
        _, rep = seq.start(st, n_rounds=1, key=jax.random.fold_in(key, r))
        sent.append(int(rep.sent_per_round.sum()))
        failed.append(int(rep.failed_per_round.sum()))
    return sent, failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="cohort-smoke-artifacts")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)

    import jax

    key = jax.random.PRNGKey(11)
    sim, _ = build()
    pool0 = sim.init_cohort_pool(key)
    record: dict = {"nominal_n": N_NOMINAL, "cohort_size": C,
                    "rounds": ROUNDS}

    # One uninterrupted run (keep pool0 pristine: cohort_start copies).
    pool_a, rep = sim.start(pool0, n_rounds=ROUNDS, key=key)

    # 1. sampled-round accounting.
    assert (rep.sent_per_round == C).all(), rep.sent_per_round
    assert rep.failed_per_round.sum() == 0, rep.failed_per_round
    record["sent_per_round"] = rep.sent_per_round.tolist()
    record["failed_total"] = int(rep.failed_per_round.sum())

    # 5. coverage accounting.
    cov = rep.cohort_coverage
    assert (np.diff(cov) >= -1e-9).all(), cov
    assert np.isclose(cov[-1], float(pool_a.touched.mean())), \
        (cov[-1], pool_a.touched.mean())
    assert (rep.cohort_active_nodes == C).all()
    record["coverage_final"] = float(cov[-1])

    # 2. sequential-engine cohort replay: integer accounting sums match
    # bit-for-bit at zero fault rates (the "where applicable" regime —
    # both engines deliver every generated message).
    seq_sent, seq_failed = seq_replay_accounting(sim, key, ROUNDS)
    assert seq_sent == rep.sent_per_round.tolist(), (
        seq_sent, rep.sent_per_round.tolist())
    assert sum(seq_failed) == int(rep.failed_per_round.sum()) == 0
    record["seq_replay_sent"] = seq_sent

    # 3. chunked determinism.
    pool_b, rep1 = sim.start(pool0, n_rounds=ROUNDS // 2, key=key)
    pool_b, rep2 = sim.start(pool_b, n_rounds=ROUNDS - ROUNDS // 2,
                             key=key)
    np.testing.assert_array_equal(
        np.concatenate([rep1.sent_per_round, rep2.sent_per_round]),
        rep.sent_per_round)
    for a, b in zip(jax.tree_util.tree_leaves(pool_a.model),
                    jax.tree_util.tree_leaves(pool_b.model)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    record["chunked_bit_identical"] = True

    # 4. checkpoint round-trip mid-run (pool intact, continuation exact).
    pool_c, _ = sim.start(pool0, n_rounds=ROUNDS // 2, key=key)
    ck = sim.save(os.path.join(args.out, "ck"), pool_c, key=key)
    restored, rkey = sim.load(ck, key)
    assert int(np.asarray(restored.round)) == ROUNDS // 2
    np.testing.assert_array_equal(np.asarray(restored.touched),
                                  np.asarray(pool_c.touched))
    pool_d, _ = sim.start(restored, n_rounds=ROUNDS - ROUNDS // 2,
                          key=rkey)
    for a, b in zip(jax.tree_util.tree_leaves(pool_a.model),
                    jax.tree_util.tree_leaves(pool_d.model)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    record["checkpoint_roundtrip"] = True

    # 6. trace accounting: the same run traced emits a Perfetto-loadable
    # timeline whose critical-path report accounts for the wall.
    from gossipy_tpu.telemetry.tracing import Tracer, trace_report
    sim.tracer = Tracer(process_name="cohort_smoke")
    sim.start(pool0, n_rounds=ROUNDS, key=key)
    snap = sim.tracer.snapshot()
    trace_path = sim.tracer.save(os.path.join(args.out, "trace.json"))
    sim.tracer = None

    # Chrome trace-event schema: object form, complete events carry
    # ts/dur/pid/tid (what Perfetto needs to lay out tracks).
    assert isinstance(snap["traceEvents"], list) and snap["traceEvents"]
    for ev in snap["traceEvents"]:
        assert {"ph", "name", "pid", "tid"} <= set(ev), ev
        if ev["ph"] == "X":
            assert "ts" in ev and "dur" in ev, ev

    report = trace_report(snap)
    assert report["n_windows"] >= 1
    assert len(report["per_round"]) == ROUNDS, report["per_round"]
    for row in report["per_round"]:
        assert "host_blocked_ms" in row and "overlap_frac" in row, row
    tot = report["totals"]
    # Self-consistency: host_blocked + device + unaccounted == wall is
    # exact by construction, so a small unaccounted gap IS the claim
    # that host + device + overlap cover the wall.
    gap = abs(tot["wall_ms"] - tot["host_blocked_ms"]
              - tot["device_ms"] - tot["unaccounted_ms"])
    assert gap < 1.0, (gap, tot)
    assert tot["unaccounted_frac"] is not None \
        and tot["unaccounted_frac"] < 0.15, tot
    with open(os.path.join(args.out, "trace_report.json"), "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    record["trace"] = {"path": os.path.basename(trace_path),
                       "n_windows": report["n_windows"],
                       "host_blocked_frac": tot["host_blocked_frac"],
                       "overlap_frac": tot["overlap_frac"],
                       "unaccounted_frac": tot["unaccounted_frac"]}

    # 7. streaming A/B: bit-identity + overlap. The tiny config above is
    # dispatch-bound (sub-ms host work per segment), so the A/B runs a
    # heavier, device-bound shape where the pipeline has something to
    # hide: C=768 nodes x 2 rounds/cohort segments, [C, 32, 256] data
    # gathers, prefetch deep enough that every gather queues under a
    # long-running segment.
    def build_ab(prefetch, tracing=None):
        import optax

        from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode
        from gossipy_tpu.data import ClassificationDataHandler, \
            DataDispatcher
        from gossipy_tpu.handlers import SGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import CohortConfig, GossipSimulator, \
            NominalTopology

        d, c = 256, 768
        rng = np.random.default_rng(7)
        w = rng.normal(size=d)
        X = rng.normal(size=(4 * c * 32, d)).astype(np.float32)
        y = (X @ w > 0).astype(np.int64)
        disp = DataDispatcher(
            ClassificationDataHandler(X, y, test_size=0.1),
            n=4 * c, eval_on_user=False)
        h = SGDHandler(model=LogisticRegression(d, 2),
                       loss=losses.cross_entropy,
                       optimizer=optax.sgd(0.1), local_epochs=3,
                       batch_size=8, n_classes=2, input_shape=(d,),
                       create_model_mode=CreateModelMode.MERGE_UPDATE)
        return GossipSimulator(
            h, NominalTopology(100_000), disp.stacked(), delta=20,
            protocol=AntiEntropyProtocol.PUSH, sampling_eval=0.01,
            eval_every=10_000,
            cohort=CohortConfig(size=c, rounds_per_cohort=2,
                                prefetch=prefetch),
            tracing=tracing)

    from gossipy_tpu.telemetry.tracing import Tracer as _Tracer
    ab_rounds = 24
    ab_fracs = {}
    ab_leaves = {}
    for tag, pf in (("serial", 0), ("stream", 8)):
        tr_ab = _Tracer(process_name=f"cohort_smoke.{tag}")
        sim_ab = build_ab(pf, tracing=tr_ab)
        p_ab, _ = sim_ab.start(sim_ab.init_cohort_pool(key),
                               n_rounds=ab_rounds, key=key)
        rep_ab = trace_report(tr_ab.snapshot())
        with open(os.path.join(args.out,
                               f"trace_report_{tag}.json"), "w") as fh:
            json.dump(rep_ab, fh, indent=2)
            fh.write("\n")
        ab_fracs[tag] = rep_ab["totals"]["overlap_frac"] or 0.0
        ab_leaves[tag] = [np.asarray(x)
                          for x in jax.tree_util.tree_leaves(p_ab)]
    assert len(ab_leaves["serial"]) == len(ab_leaves["stream"])
    for a, b in zip(ab_leaves["serial"], ab_leaves["stream"]):
        np.testing.assert_array_equal(a, b)
    assert ab_fracs["stream"] > 0.3, (
        f"streaming overlap_frac {ab_fracs['stream']} <= 0.3 — the "
        "prefetch pipeline is not hiding host work behind compute")
    record["stream_ab"] = {"rounds": ab_rounds, "prefetch": 8,
                           "bit_identical": True,
                           "overlap_frac_serial": ab_fracs["serial"],
                           "overlap_frac_stream": ab_fracs["stream"]}

    # 8. nominal-100M disk-backed pool: a short streamed run over a
    # sparse mmap pool — bounded RAM and bounded disk, at a population
    # three orders past what a dense host pool could hold.
    import resource
    import shutil
    import tempfile

    tmp_root = tempfile.mkdtemp(prefix="cohort_pool_")
    try:
        import optax

        from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode
        from gossipy_tpu.data import ClassificationDataHandler, \
            DataDispatcher
        from gossipy_tpu.handlers import SGDHandler, losses
        from gossipy_tpu.models import LogisticRegression
        from gossipy_tpu.simulation import CohortConfig, GossipSimulator, \
            NominalTopology

        rng = np.random.default_rng(7)
        w = rng.normal(size=D)
        X = rng.normal(size=(128 * 8, D)).astype(np.float32)
        y = (X @ w > 0).astype(np.int64)
        disp = DataDispatcher(
            ClassificationDataHandler(X, y, test_size=0.25),
            n=128, eval_on_user=False)
        h = SGDHandler(model=LogisticRegression(D, 2),
                       loss=losses.cross_entropy,
                       optimizer=optax.sgd(0.1), local_epochs=1,
                       batch_size=8, n_classes=2, input_shape=(D,),
                       create_model_mode=CreateModelMode.MERGE_UPDATE)
        pool_dir = os.path.join(tmp_root, "pool100m")
        sim_mm = GossipSimulator(
            h, NominalTopology(100_000_000), disp.stacked(), delta=20,
            protocol=AntiEntropyProtocol.PUSH, sampling_eval=0.01,
            eval_every=10_000,
            cohort=CohortConfig(size=32, prefetch=2, pool_dir=pool_dir))
        assert sim_mm.memory_budget()["cohort_pool_disk_backed"]
        p_mm, _ = sim_mm.start(sim_mm.init_cohort_pool(key), n_rounds=4,
                               key=key)
        assert int(np.asarray(p_mm.round)) == 4
        logical = alloc = 0
        for f in os.listdir(pool_dir):
            st = os.stat(os.path.join(pool_dir, f))
            logical += st.st_size
            alloc += st.st_blocks * 512
        rss_gb = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1e6
        assert logical > 2e9, logical    # nominal-sized address space...
        assert alloc < 1e9, alloc        # ...never materialized on disk
        assert rss_gb < 8, rss_gb        # ...nor in RAM
        record["pool_100m"] = {"nominal_n": 100_000_000,
                               "logical_bytes": logical,
                               "allocated_bytes": alloc,
                               "peak_rss_gb": round(rss_gb, 2)}
    finally:
        shutil.rmtree(tmp_root, ignore_errors=True)

    path = os.path.join(args.out, "cohort_smoke.json")
    with open(path, "w") as fh:
        json.dump(record, fh, indent=2)
        fh.write("\n")
    print(f"[cohort-smoke] all checks passed; wrote {path}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
