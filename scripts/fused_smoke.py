#!/usr/bin/env python
"""Fused-deliver smoke: parity + single-launch property + the bench row.

The PR-20 acceptance gate, runnable anywhere the CPU interpreter runs
(CI has no TPU — pallas interpreter mode executes the SAME launch
schedule, so everything here except absolute wall-clock is meaningful):

1. **Parity** — a directed-cycle fan-in-1 config at ``mailbox_slots=4``
   driven for several rounds: ``fused_merge="multi"`` (one pallas launch
   drains all K slots) must reproduce the unfused XLA gather+blend
   deliver — params bit-equal for fp32, within dequant tolerance for
   int8 — with sent/failed accounting bit-equal. The exhaustive dtype /
   topology / probe-histogram matrix lives in pytest
   (tests/test_fused_deliver.py); this is the end-to-end canary.

2. **Single-launch HLO property** — ``pallas_launch_count`` over the
   jaxpr of the round program: unfused traces ZERO pallas calls, fused
   multi exactly ONE (the whole mailbox in one kernel), compact+fused
   two (both branches of the live-count cond are traced; each drains in
   one launch). Counting the traced program makes this a static
   property, not a profile.

3. **Bench row** — ``bench.bench_fused_regime`` at smoke size (K=4):
   asserts the row stamps ``raw.deliver_bytes_moved`` (multi strictly
   below per_slot below/equal plain) and the deliver-phase ms A/B with
   the multi leg strictly below per_slot (the K->1 launch collapse is a
   ~2x systematic interpreter-schedule gap, not timing noise). The row
   lands in ``--out``/fused_row.json (bench_trend ``--row``-ready) and,
   with ``--ledger``, as a digest row in the shared run ledger.

Exit 0 all gates green, 1 on any violated invariant.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import sys
import time
import warnings

from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

K = 4          # mailbox depth — the multi-slot kernel's design point
PARITY_N = 12  # directed-cycle nodes (fan-in 1 -> bit-exact fp32 parity)
PARITY_ROUNDS = 6


def _stamp(msg: str) -> None:
    print(f"[fused_smoke] {msg}", file=sys.stderr)


def _parity_sim(fused, history_dtype="float32"):
    import numpy as np
    import optax

    from gossipy_tpu.core import (AntiEntropyProtocol, CreateModelMode,
                                  Topology)
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    rng = np.random.default_rng(7)
    X = rng.normal(size=(PARITY_N * 24, 30)).astype(np.float32)
    y = (X @ rng.normal(size=30) > 0).astype(np.int64)
    disp = DataDispatcher(ClassificationDataHandler(X, y, test_size=0.2),
                          n=PARITY_N, eval_on_user=False)
    handler = SGDHandler(model=LogisticRegression(30, 2),
                         loss=losses.cross_entropy,
                         optimizer=optax.sgd(0.1), local_epochs=1,
                         batch_size=8, n_classes=2, input_shape=(30,),
                         create_model_mode=CreateModelMode.MERGE_UPDATE)
    # Directed cycle: every node receives from exactly one peer, so the
    # unfused slot-iterated blend and the one-launch multi kernel walk
    # numerically identical reductions (fan-in 1 -> no reassociation).
    cycle = Topology(np.roll(np.eye(PARITY_N, dtype=bool), 1, axis=1))
    return GossipSimulator(handler, cycle, disp.stacked(), delta=100,
                           protocol=AntiEntropyProtocol.PUSH,
                           fused_merge=fused, mailbox_slots=K,
                           history_dtype=history_dtype)


def _run(sim, rounds=PARITY_ROUNDS):
    import jax
    key = jax.random.PRNGKey(0)
    state = sim.init_nodes(key, common_init=True)
    state, report = sim.start(state, n_rounds=rounds, key=key,
                              donate_state=False)
    jax.block_until_ready(state.model.params)
    return state, report


def check_parity(report: dict) -> list:
    import jax
    import numpy as np

    failures = []
    for dtype, tol in (("float32", 0.0), ("int8", 1e-6)):
        sims = {leg: _parity_sim(fused, history_dtype=dtype)
                for leg, fused in (("unfused", False), ("multi", "multi"))}
        out = {leg: _run(sim) for leg, sim in sims.items()}
        (s_u, r_u), (s_m, r_m) = out["unfused"], out["multi"]
        diffs = [float(np.max(np.abs(np.asarray(a, dtype=np.float64)
                                     - np.asarray(b, dtype=np.float64))))
                 for a, b in zip(jax.tree.leaves(s_u.model.params),
                                 jax.tree.leaves(s_m.model.params))]
        max_diff = max(diffs)
        sent_eq = (int(r_u.sent_messages) == int(r_m.sent_messages)
                   and int(r_u.failed_messages) == int(r_m.failed_messages))
        report.setdefault("parity", {})[dtype] = {
            "max_abs_diff": max_diff, "tolerance": tol,
            "sent": int(r_m.sent_messages),
            "failed": int(r_m.failed_messages),
            "accounting_bit_equal": sent_eq,
        }
        if max_diff > tol:
            failures.append(f"parity[{dtype}]: fused-multi diverged from "
                            f"unfused by {max_diff:g} (> {tol:g})")
        if not sent_eq:
            failures.append(f"parity[{dtype}]: sent/failed accounting "
                            "differs between fused and unfused")
        _stamp(f"parity {dtype}: max|diff| {max_diff:g} (tol {tol:g}), "
               f"sent {int(r_m.sent_messages)} "
               f"{'OK' if max_diff <= tol and sent_eq else 'FAIL'}")
    return failures


def check_launch_counts(report: dict) -> list:
    from gossipy_tpu.analysis.hlo import _make_sim, pallas_launch_count

    failures = []
    cases = [
        ("unfused", lambda: _make_sim(), 0),
        ("fused-multi",
         lambda: _make_sim(fused_merge=True, mailbox_slots=K), 1),
        # compact+fused traces BOTH branches of the live-count cond;
        # each deliver drains the mailbox in one launch.
        ("fused-compact",
         lambda: _make_sim(fused_merge=True, compact_deliver=8,
                           mailbox_slots=K), 2),
    ]
    for name, build, want in cases:
        got = pallas_launch_count(build(), n_rounds=2)
        report.setdefault("launch", {})[name] = {"want": want, "got": got}
        if got != want:
            failures.append(f"launch[{name}]: {got} pallas launches in the "
                            f"round program, expected {want} — the fused "
                            "deliver must drain the whole mailbox in one "
                            "kernel launch")
        _stamp(f"launch {name}: {got} (want {want}) "
               f"{'OK' if got == want else 'FAIL'}")
    return failures


def check_bench_row(report: dict, out_dir: str) -> list:
    import bench

    failures = []
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        bench.bench_fused_regime(rounds=2, n=8)
    row = None
    for line in buf.getvalue().splitlines():
        line = line.strip()
        if line.startswith("{"):
            row = json.loads(line)
    if row is None:
        return ["bench: bench_fused_regime emitted no JSON row"]
    with open(os.path.join(out_dir, "fused_row.json"), "w") as fh:
        json.dump(row, fh, indent=2)
        fh.write("\n")
    raw = row.get("raw") or {}
    dms = raw.get("deliver_ms_per_round") or {}
    dbm = raw.get("deliver_bytes_moved") or {}
    report["bench"] = {"metric": row.get("metric"),
                       "deliver_ms_per_round": dms,
                       "deliver_bytes_moved": dbm,
                       "mailbox_slots": raw.get("mailbox_slots")}
    if raw.get("mailbox_slots") != K:
        failures.append(f"bench: row mailbox_slots={raw.get('mailbox_slots')}"
                        f", expected {K}")
    if not (dbm.get("multi") and dbm.get("per_slot") and dbm.get("plain")):
        failures.append("bench: raw.deliver_bytes_moved missing a leg")
    elif not dbm["multi"] < dbm["per_slot"] <= dbm["plain"]:
        failures.append(f"bench: bytes-moved model out of order: {dbm}")
    if dms.get("multi") is None or dms.get("per_slot") is None:
        failures.append(f"bench: deliver-phase trace missing a leg: {dms}")
    elif not dms["multi"] < dms["per_slot"]:
        failures.append(f"bench: multi deliver phase {dms['multi']} ms not "
                        f"strictly below per_slot {dms['per_slot']} ms — "
                        "the K->1 launch collapse should be a systematic "
                        "schedule gap, not noise")
    _stamp(f"bench: deliver ms {dms}, bytes {dbm.get('multi')}/"
           f"{dbm.get('per_slot')}/{dbm.get('plain')} "
           f"{'OK' if not failures else 'FAIL'}")
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="fused-artifacts")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="run-ledger file to append the bench row's digest "
                         "to (shared with the other smokes)")
    ap.add_argument("--skip-bench", action="store_true",
                    help="parity + launch counts only (fast lane)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    warnings.filterwarnings(
        "ignore", message=r"mailbox_slots=\d+ may overflow")
    os.makedirs(args.out, exist_ok=True)

    import jax
    t0 = time.time()
    report: dict = {"backend": jax.default_backend(),
                    "mailbox_slots": K, "failures": []}
    _stamp(f"backend {jax.default_backend()}, K={K}")

    failures = []
    failures += check_parity(report)
    failures += check_launch_counts(report)
    if not args.skip_bench:
        failures += check_bench_row(report, args.out)
        if args.ledger and "bench" in report:
            try:
                from gossipy_tpu.telemetry.ledger import (
                    ingest_bench_capsule, resolve_ledger)
                led = resolve_ledger(args.ledger)
                row_path = os.path.join(args.out, "fused_row.json")
                if led is not None and os.path.exists(row_path):
                    ingest_bench_capsule(led, row_path,
                                         source="fused_smoke")
                    _stamp(f"ledger: bench row -> {led.path}")
            except Exception as e:
                _stamp(f"ledger ingest failed: {e!r}")

    report["failures"] = failures
    report["elapsed_seconds"] = round(time.time() - t0, 2)
    with open(os.path.join(args.out, "fused_smoke.json"), "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    for f in failures:
        _stamp(f"FAIL: {f}")
    _stamp(f"{'FAILED' if failures else 'PASSED'} in "
           f"{report['elapsed_seconds']}s")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
