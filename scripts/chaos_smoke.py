"""CI chaos smoke: partition → heal → prove reconvergence, with artifacts.

Runs a tiny (seconds on one CPU core) chaos-scenario gossip simulation —
the population split into two components at round 3, healed at round 6 —
with consensus probes and the scheduled-fault layer on, then SELF-CHECKS
the recovery evidence:

- the per-round partition consensus gap (``chaos_component_gap``) is ~0
  before the partition, OPENS while it holds, and RECONVERGES after the
  heal (:func:`gossipy_tpu.simulation.rounds_to_reconverge` names the
  round count);
- the jitted trajectory is bit-identical when re-run chunked through two
  ``start()`` calls crossing the heal boundary (chaos determinism);
- the sequential high-fidelity engine agrees on the structural story
  (gap open during the window, closed after) for the same config.

Writes into ``--out DIR``: ``report.json`` (the full chaos-enabled
SimulationReport, schema v5), ``chaos_verdict.json`` (the self-check
summary: per-round gap, rounds-to-reconverge, both engines' verdicts) and
``events.jsonl`` (schema-v5 rows with the ``chaos`` field). Exits
non-zero on any failed check; ``.github/workflows/ci.yml`` uploads the
directory either way so a red run ships its own evidence.

Usage: ``python scripts/chaos_smoke.py --out chaos-artifacts``
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

N_NODES = 16
PART_START, PART_STOP = 3, 6   # partition at round 3, heal at round 6
ROUNDS = 14


def build(cls, **kwargs):
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
        Topology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import LogisticRegression
    from gossipy_tpu.simulation.faults import ChaosConfig, PartitionEpisode

    rng = np.random.default_rng(7)
    D = 6
    X = rng.normal(size=(480, D)).astype(np.float32)
    y = (X @ rng.normal(size=D) > 0).astype(np.int64)
    dh = ClassificationDataHandler(X, y, test_size=0.25, seed=1)
    disp = DataDispatcher(dh, n=N_NODES, eval_on_user=False)
    handler = SGDHandler(
        model=LogisticRegression(D, 2), loss=losses.cross_entropy,
        optimizer=optax.sgd(0.2), local_epochs=1, batch_size=16,
        n_classes=2, input_shape=(D,),
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    half = N_NODES // 2
    chaos = ChaosConfig(partitions=(PartitionEpisode(
        components=(tuple(range(half)), tuple(range(half, N_NODES))),
        start=PART_START, stop=PART_STOP),))
    return cls(handler, Topology.clique(N_NODES), disp.stacked(),
               delta=20, protocol=AntiEntropyProtocol.PUSH,
               probes=True, chaos=chaos, **kwargs)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="chaos-artifacts")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)

    import jax

    from gossipy_tpu.simulation import (
        GossipSimulator,
        JSONLinesReceiver,
        SequentialGossipSimulator,
        rounds_to_reconverge,
    )

    checks: dict = {}
    failures: list = []

    def check(name, ok, detail=None):
        checks[name] = {"ok": bool(ok), "detail": detail}
        if not ok:
            failures.append(name)
        print(f"[chaos-smoke] {'ok ' if ok else 'FAIL'} {name}"
              + (f" ({detail})" if detail is not None else ""))

    key = jax.random.PRNGKey(11)

    # One-shot jitted run (with JSONL so the artifact carries the schema
    # v5 chaos rows).
    sim = build(GossipSimulator)
    events_path = os.path.join(args.out, "events.jsonl")
    if os.path.exists(events_path):
        os.remove(events_path)
    with JSONLinesReceiver(events_path) as rx:
        sim.add_receiver(rx)
        st = sim.init_nodes(key)
        st, rep = sim.start(st, n_rounds=ROUNDS, key=key,
                            donate_state=False)
        sim.remove_receiver(rx)
    rep.save(os.path.join(args.out, "report.json"))

    gap = np.asarray(rep.chaos_component_gap, dtype=np.float64)
    pre = float(gap[:PART_START].max())
    during = float(gap[PART_START:PART_STOP].min())
    # Post-heal the gap decays toward the ongoing-SGD noise floor (the
    # halves keep training on disjoint shards), so reconvergence is the
    # post-heal MINIMUM dipping well under the partition peak.
    post = float(gap[PART_STOP:].min())
    peak = float(gap[PART_START:PART_STOP].max())
    check("gap_opens_during_partition", during > max(10.0 * pre, 1e-4),
          f"pre<= {pre:.2e}, during>= {during:.3f}")
    check("gap_closes_after_heal", post < 0.25 * peak,
          f"peak {peak:.3f} -> post-heal min {post:.3f}")
    recon = rounds_to_reconverge(gap, PART_STOP, tol=0.25 * peak)
    check("reconverges_within_report", recon is not None,
          f"rounds_to_reconverge={recon}")

    # Chunked determinism across the heal boundary: 5 + (ROUNDS-5) rounds
    # through two start() calls must reproduce the one-shot trajectory
    # bit for bit (randomness and the schedule key on absolute rounds).
    sim2 = build(GossipSimulator)
    st2 = sim2.init_nodes(key)
    st2, r1 = sim2.start(st2, n_rounds=5, key=key, donate_state=False)
    st2, r2 = sim2.start(st2, n_rounds=ROUNDS - 5, key=key,
                         donate_state=False)
    chunked_gap = np.concatenate([np.asarray(r1.chaos_component_gap),
                                  np.asarray(r2.chaos_component_gap)])
    check("chunked_resume_bit_identical",
          np.array_equal(chunked_gap, gap)
          and np.array_equal(
              np.concatenate([r1.sent_per_round, r2.sent_per_round]),
              rep.sent_per_round))

    # Sequential-engine structural parity on the same scenario.
    seq = build(SequentialGossipSimulator)
    sst = seq.init_nodes(key)
    sst, srep = seq.start(sst, n_rounds=ROUNDS, key=key)
    sgap = np.asarray(srep.chaos_component_gap, dtype=np.float64)
    speak = float(sgap[PART_START:PART_STOP].max())
    spost = float(sgap[PART_STOP:].min())
    check("sequential_gap_opens_and_closes",
          float(sgap[PART_START:PART_STOP].min()) > 1e-4
          and spost < 0.25 * speak,
          f"seq peak {speak:.3f} -> post-heal min {spost:.3f}")

    verdict = {
        "n_nodes": N_NODES,
        "partition": {"start": PART_START, "stop": PART_STOP},
        "rounds": ROUNDS,
        "gap_per_round": [round(float(g), 6) for g in gap],
        "sequential_gap_per_round": [round(float(g), 6) for g in sgap],
        "rounds_to_reconverge_after_heal": recon,
        "failed_by_cause_keys": sorted(rep.failed_per_cause),
        "checks": checks,
        "ok": not failures,
    }
    with open(os.path.join(args.out, "chaos_verdict.json"), "w") as fh:
        json.dump(verdict, fh, indent=2)
        fh.write("\n")

    if failures:
        print(f"[chaos-smoke] FAILED checks: {failures}", file=sys.stderr)
        return 1
    print(f"[chaos-smoke] all checks passed; gap peak {peak:.3f}, "
          f"reconverged {recon} round(s) after heal; artifacts in "
          f"{args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
