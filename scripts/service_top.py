"""service_top: live terminal status board over the SLO metrics snapshot.

Tails the ``metrics.json`` a running ``scripts/serve.py --metrics-dir``
(or ``scripts/loadgen.py``) refreshes every scheduling cycle and renders
the service's vitals in place — tenants admitted/finished/evicted,
queue-wait / time-to-first-round / per-round latency percentiles (the
registry's own log-bucket estimator), per-bucket compile+round costs and
the per-tenant fair-share table (tenant-seconds, the future fair-share
scheduler's currency). Stdlib-only; reads are snapshot-atomic because the
writer renames a tmp file into place.

Usage::

    python scripts/service_top.py runs/metrics          # watch (2s)
    python scripts/service_top.py runs/metrics/metrics.json --interval 1
    python scripts/service_top.py runs/metrics --once   # one frame (CI)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from gossipy_tpu.telemetry.metrics import quantile_from_counts  # noqa: E402


def _series(snap: dict, name: str) -> list:
    fam = snap.get("metrics", {}).get(name)
    return fam.get("series", []) if fam else []


def _counter_total(snap: dict, name: str, **labels) -> float:
    total = 0.0
    for s in _series(snap, name):
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            total += s["value"]
    return total


def _counter_by(snap: dict, name: str, label: str) -> dict:
    out: dict = {}
    for s in _series(snap, name):
        key = s["labels"].get(label, "")
        out[key] = out.get(key, 0.0) + s["value"]
    return out


def _hist_pct(snap: dict, name: str, q: float, **labels):
    fam = snap.get("metrics", {}).get(name)
    if fam is None:
        return None
    counts, lo, hi = None, None, None
    for s in fam["series"]:
        if not all(s["labels"].get(k) == v for k, v in labels.items()):
            continue
        counts = (s["counts"] if counts is None
                  else [a + b for a, b in zip(counts, s["counts"])])
        if s.get("min") is not None:
            lo = s["min"] if lo is None else min(lo, s["min"])
        if s.get("max") is not None:
            hi = s["max"] if hi is None else max(hi, s["max"])
    if counts is None:
        return None
    return quantile_from_counts(fam["buckets"], counts, q, lo=lo, hi=hi)


def _ms(v) -> str:
    return f"{v * 1e3:10.1f}" if v is not None else "         -"


def host_blocked_by_bucket(snap: dict, trace_path: str) -> dict:
    """Per-bucket host-blocked percentage, bucket digest -> percent.

    Preferred source is the live trace's ``host_blocked%/<bucket>``
    counter events (the scheduler emits one per slice; the LAST event
    per bucket is the current value) — they update every slice, not
    every snapshot. Falls back to the ``service_host_blocked_frac``
    gauge in the metrics snapshot when no trace.json sits next to
    metrics.json (tracing off)."""
    out: dict = {}
    try:
        with open(trace_path) as fh:
            events = json.load(fh).get("traceEvents", [])
        last_ts: dict = {}
        for e in events:
            name = e.get("name", "")
            if e.get("ph") == "C" and name.startswith("host_blocked%/"):
                bucket = name.split("/", 1)[1]
                ts = e.get("ts", 0.0)
                if ts >= last_ts.get(bucket, -1.0):
                    last_ts[bucket] = ts
                    out[bucket] = float(e.get("args", {}).get("value", 0.0))
        if out:
            return out
    except (OSError, json.JSONDecodeError, ValueError):
        pass
    for s in _series(snap, "service_host_blocked_frac"):
        bucket = s["labels"].get("bucket", "")
        out[bucket] = float(s["value"]) * 100.0
    return out


def render(snap: dict, path: str, host_blk: dict = None) -> str:
    host_blk = host_blk or {}
    age = time.time() - snap.get("ts", 0.0)
    admitted = _counter_total(snap, "service_tenants_admitted_total")
    by_status = _counter_by(snap, "service_tenants_finished_total",
                            "status")
    finished = sum(by_status.values())
    evictions = _counter_by(snap, "service_evictions_total", "cause")
    rounds = _counter_total(snap, "service_rounds_total")

    lines = [
        f"gossipy_tpu service  ·  {path}  ·  snapshot age {age:5.1f}s",
        "",
        f"tenants   admitted {int(admitted):5d}   "
        f"running {int(admitted - finished):5d}   "
        + "   ".join(f"{k} {int(v)}" for k, v in sorted(by_status.items()))
        + (f"   evictions[{', '.join(f'{k}:{int(v)}' for k, v in sorted(evictions.items()))}]"
           if evictions else ""),
        f"rounds    harvested {int(rounds)}",
        "",
        "latency (ms)        p50        p90        p99",
    ]
    for label, metric in (("queue wait", "service_queue_wait_seconds"),
                          ("ttfr", "service_ttfr_seconds"),
                          ("round", "service_round_seconds"),
                          ("slice", "service_slice_seconds")):
        lines.append(f"  {label:<14}"
                     + "".join(_ms(_hist_pct(snap, metric, q))
                               for q in (0.5, 0.9, 0.99)))

    buckets = sorted({s["labels"]["bucket"]
                      for s in _series(snap, "service_rounds_total")})
    if buckets:
        lines += ["", "bucket     rounds   round p99 (ms)  "
                      "compile init/step (s)  host blk%"]
        compile_by = {(s["labels"]["bucket"], s["labels"]["program"]):
                      s["value"]
                      for s in _series(snap, "service_compile_seconds")}
        for b in buckets[:12]:
            r = _counter_total(snap, "service_rounds_total", bucket=b)
            p99 = _hist_pct(snap, "service_round_seconds", 0.99, bucket=b)
            ci = compile_by.get((b, "init"))
            cs = compile_by.get((b, "step"))
            hb = host_blk.get(b)
            lines.append(
                f"  {b:<9}{int(r):7d} {_ms(p99)}       "
                f"{ci if ci is not None else 0:6.2f} / "
                f"{cs if cs is not None else 0:6.2f}"
                + (f"      {hb:6.1f}" if hb is not None
                   else "           -"))

    shares = [(s["labels"].get("tenant", "?"), s["value"])
              for s in _series(snap, "service_tenant_seconds_total")]
    if shares:
        total = sum(v for _, v in shares) or 1.0
        ttfr = {s["labels"].get("tenant"): s["value"]
                for s in _series(snap, "service_tenant_ttfr_seconds")}
        lines += ["", "tenant            seconds   share    ttfr (s)"]
        for name, v in sorted(shares, key=lambda x: -x[1])[:15]:
            t = ttfr.get(name)
            lines.append(f"  {name:<15}{v:9.3f}  {v / total:6.1%}"
                         f"   {t:9.3f}" if t is not None else
                         f"  {name:<15}{v:9.3f}  {v / total:6.1%}"
                         f"           -")
        if len(shares) > 15:
            lines.append(f"  ... {len(shares) - 15} more")

    engine = _counter_by(snap, "engine_rounds_total", "simulator")
    if engine:
        lines += ["", "engine    " + "   ".join(
            f"{k}: {int(v)} rounds" for k, v in sorted(engine.items()))]
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="metrics dir or metrics.json path")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no screen clearing)")
    args = ap.parse_args()

    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.json")

    def frame() -> str:
        try:
            with open(path) as fh:
                snap = json.load(fh)
        except FileNotFoundError:
            return f"waiting for {path} ..."
        except json.JSONDecodeError:
            return f"{path}: partial write, retrying ..."
        trace_path = os.path.join(os.path.dirname(path), "trace.json")
        return render(snap, path,
                      host_blk=host_blocked_by_bucket(snap, trace_path))

    if args.once:
        out = frame()
        print(out)
        return 1 if out.startswith("waiting for") else 0
    try:
        while True:
            # ANSI home+clear keeps the board in place without curses.
            sys.stdout.write("\x1b[H\x1b[2J" + frame() + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
