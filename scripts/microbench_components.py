"""Component microbenchmarks for the CNN round program (the MFU attack).

The MFU row (BASELINE.md round 3: 0.0039, 261 ms/round) says the flagship
CNN round program is overhead-bound, not FLOP-bound. This script times the
round's candidate cost centers IN ISOLATION on whatever backend is live, so
one short tunnel window attributes the ms/round to a component and ranks
the rewrite candidates:

- ``eval_vmap``:   global eval exactly as the engine runs it — vmap of the
                   forward over per-node params. Since round 4 the default
                   ``CIFAR10Net`` conv_impl is the im2col/einsum form, so
                   this vmaps to batched matmuls; the grouped-conv
                   (batch_group_count) lowering the r3 MFU row measured now
                   lives in the ``*_alt`` rows below.
- ``eval_vmap_alt`` / ``train_slot_alt``: the same shapes under
                   ``conv_impl="conv"`` (vmapped ``nn.Conv`` -> tiny-group
                   grouped convolutions) — the r4 A/B attributing the
                   einsum-conv win on this chip (CPU datapoint: train slot
                   12.3 s conv vs 0.72 s einsum at 8 nodes).
- ``eval_map``:    same computation as a sequential ``lax.map`` over nodes —
                   each conv keeps its natural [E] batch shape. If this beats
                   eval_vmap on TPU, the batched-weights lowering is the MFU
                   problem, not the eval schedule.
- ``eval_single``: ONE node's params on the same [E] eval batch — the
                   irreducible conv-forward floor (x n_eval_nodes for the
                   fair comparison).
- ``merge_slot``:  the deliver slot's gather+blend half — fetch every
                   node's peer snapshot from the [D, N, ...] history ring
                   and average it into the local params (the engine's
                   unfused MERGE step, engine.py ``_gather_peer`` +
                   ``handler.call``'s merge).
- ``train_slot``:  the deliver slot's update half — the vmapped local-SGD
                   pass over all N nodes (the engine's per-slot
                   ``handler.update``).
- ``train_slot_compact``: the round-5 compacted slot pass at the derived
                   capacity — valid-first argsort, gather of the live
                   rows, the [cap]-wide update, scatter back (the
                   ``compact_deliver`` path that replaced full-width
                   masked passes for slots >= 1; CPU A/B: 3.25x on the
                   whole 64-node CNN round).
- ``snapshot``:    the per-round history-ring write (dynamic_update_slice
                   of all N nodes' params), timed with the ring donated so
                   it measures the in-place write the scanned round
                   performs, not a ring copy.

Prints ONE JSON line with per-component ms. Backend-labeled like the bench
rows; off-TPU it is a smoke test of the harness, not a measurement.

Usage (repo root):
    python scripts/microbench_components.py            # CNN config sizes
    python scripts/microbench_components.py --small    # CPU smoke sizes
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _timed(fn, *args, reps: int = 10) -> float:
    """Compile, then steady-state ms per call."""
    import jax
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps * 1e3


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--small", action="store_true",
                    help="tiny sizes (CPU smoke test)")
    ap.add_argument("--reps", type=int, default=10)
    args = ap.parse_args()

    import _virtual_mesh
    ok, detail = _virtual_mesh.probe_backend_alive()
    if not ok:
        print(f"[micro] backend unreachable ({detail}); re-exec on CPU",
              file=sys.stderr)
        env = _virtual_mesh.virtual_mesh_env(1, extra_path=_REPO)
        # Shrink to the smoke sizes: the full CNN config takes tens of
        # minutes on this 1-core host and the CPU row is only a harness
        # check anyway (same convention as bench.py's --_degraded).
        argv = [sys.executable] + sys.argv
        if "--small" not in argv:
            argv.append("--small")
        os.execve(sys.executable, argv, env)

    import jax
    import jax.numpy as jnp
    import optax

    from gossipy_tpu import enable_compilation_cache
    from gossipy_tpu.core import CreateModelMode
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import CIFAR10Net

    enable_compilation_cache()

    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu and not args.small:
        # The probe can pass while jax still defaults to CPU (no TPU plugin,
        # or the plugin itself falls back): the full 100-node CNN config
        # would burn tens of minutes here for a row that is only a harness
        # check — shrink, mirroring bench.py's DEGRADED convention.
        print(f"[micro] backend is {jax.default_backend()!r}, not tpu; "
              "shrinking to --small sizes (pass --small explicitly to "
              "silence)", file=sys.stderr)
        args.small = True
    if args.small:
        n_nodes, n_eval_nodes, e_sz, shard = 8, 2, 64, 32
    else:
        # bench_mfu's config: 100 nodes, 10 sampled eval nodes, 1280-sample
        # eval set, 128-sample shards (bench.py bench_mfu).
        n_nodes, n_eval_nodes, e_sz, shard = 100, 10, 1280, 128
    dtype = jnp.bfloat16 if on_tpu else None

    handler = SGDHandler(
        model=CIFAR10Net(), loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(1e-3), optax.sgd(0.05)),
        local_epochs=1, batch_size=32, n_classes=10, input_shape=(32, 32, 3),
        create_model_mode=CreateModelMode.MERGE_UPDATE, compute_dtype=dtype)

    key = jax.random.PRNGKey(0)
    states = jax.vmap(handler.init)(jax.random.split(key, n_nodes))
    rng = np.random.default_rng(0)
    xe = jnp.asarray(rng.normal(size=(e_sz, 32, 32, 3)), jnp.float32)
    ye = jnp.asarray(rng.integers(0, 10, e_sz))
    me = jnp.ones((e_sz,), jnp.float32)
    xtr = jnp.asarray(rng.normal(size=(n_nodes, shard, 32, 32, 3)), jnp.float32)
    ytr = jnp.asarray(rng.integers(0, 10, (n_nodes, shard)))
    mtr = jnp.ones((n_nodes, shard), jnp.float32)

    eval_states = jax.tree.map(lambda l: l[:n_eval_nodes], states)

    def eval_vmap(st):
        return jax.vmap(lambda m: handler.evaluate(m, (xe, ye, me)))(st)

    def eval_map(st):
        return jax.lax.map(lambda m: handler.evaluate(m, (xe, ye, me)), st)

    one_state = jax.tree.map(lambda l: l[0], states)

    def eval_single(st):
        return handler.evaluate(st, (xe, ye, me))

    def train_slot(st):
        keys = jax.random.split(jax.random.PRNGKey(1), n_nodes)
        return jax.vmap(handler.update)(st, (xtr, ytr, mtr), keys)

    D = 2
    hist = jax.tree.map(
        lambda l: jnp.broadcast_to(l, (D,) + l.shape).copy(), states.params)
    senders = jnp.asarray(rng.integers(0, n_nodes, n_nodes), jnp.int32)

    def merge_slot(p, h):
        peer = jax.tree.map(lambda hb: hb[0, senders], h)
        return jax.tree.map(lambda a, b: 0.5 * a + 0.5 * b, p, peer)

    def snapshot(h, p):
        return jax.tree.map(
            lambda hb, pb: jax.lax.dynamic_update_index_in_dim(hb, pb, 1, 0),
            h, p)

    def _timed_donated(fn, h, p, reps: int) -> float:
        """Steady-state ms for the ring write with ``h`` donated — each
        rep's output ring is threaded back in, so XLA updates the buffer
        in place exactly as the scanned round program does."""
        f = jax.jit(fn, donate_argnums=0)
        h = f(h, p)
        jax.block_until_ready(h)
        t0 = time.perf_counter()
        for _ in range(reps):
            h = f(h, p)
        jax.block_until_ready(h)
        return (time.perf_counter() - t0) / reps * 1e3

    # A/B the conv lowering (round 4): the engine's auto conv_impl is
    # einsum (vmapped nn.Conv lowers to tiny-group grouped convs — measured
    # 17x slower train on CPU); measure the conv impl on the same
    # eval/train shapes so the attribution is direct on this chip.
    alt_impl = "conv"
    alt_handler = SGDHandler(
        model=CIFAR10Net(conv_impl=alt_impl), loss=losses.cross_entropy,
        optimizer=optax.chain(optax.add_decayed_weights(1e-3), optax.sgd(0.05)),
        local_epochs=1, batch_size=32, n_classes=10, input_shape=(32, 32, 3),
        create_model_mode=CreateModelMode.MERGE_UPDATE, compute_dtype=dtype)

    def eval_vmap_alt(st):
        return jax.vmap(lambda m: alt_handler.evaluate(m, (xe, ye, me)))(st)

    def train_slot_alt(st):
        keys = jax.random.split(jax.random.PRNGKey(1), n_nodes)
        return jax.vmap(alt_handler.update)(st, (xtr, ytr, mtr), keys)

    # The compacted slot pass (engine _apply_receive_compact): 48/100 is
    # the derived capacity at the bench config's fan-in; ~26% of nodes
    # carry a live second-arrival slot (Poisson(1)).
    cap = max(8, int(-(-0.48 * n_nodes) // 8) * 8)
    valid = jnp.asarray(rng.random(n_nodes) < 0.26)

    def train_slot_compact(st):
        order = jnp.argsort(jnp.where(valid, 0, 1), stable=True)
        idx = jax.lax.slice_in_dim(order, 0, cap)
        sub = jax.tree.map(lambda l: l[idx], st)
        keys = jax.random.split(jax.random.PRNGKey(1), n_nodes)[idx]
        out = jax.vmap(handler.update)(sub, (xtr[idx], ytr[idx], mtr[idx]),
                                       keys)
        return jax.tree.map(lambda full, part: full.at[idx].set(part),
                            st, out)

    res = {
        "eval_vmap_ms": round(_timed(eval_vmap, eval_states,
                                     reps=args.reps), 3),
        "eval_vmap_alt_ms": round(_timed(eval_vmap_alt, eval_states,
                                         reps=args.reps), 3),
        "train_slot_alt_ms": round(_timed(train_slot_alt, states,
                                          reps=args.reps), 3),
        "eval_map_ms": round(_timed(eval_map, eval_states,
                                    reps=args.reps), 3),
        "eval_single_x_nodes_ms": round(
            _timed(eval_single, one_state, reps=args.reps) * n_eval_nodes, 3),
        "merge_slot_ms": round(_timed(merge_slot, states.params, hist,
                                      reps=args.reps), 3),
        "train_slot_ms": round(_timed(train_slot, states,
                                      reps=args.reps), 3),
        "train_slot_compact_ms": round(_timed(train_slot_compact, states,
                                              reps=args.reps), 3),
        "compact_cap": cap,
        "snapshot_ms": round(_timed_donated(snapshot, hist, states.params,
                                            args.reps), 3),
    }
    print(json.dumps({
        "metric": "cnn_component_ms",
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "n_nodes": n_nodes, "n_eval_nodes": n_eval_nodes,
        "eval_set": e_sz, "shard": shard,
        "dtype": "bfloat16" if dtype is not None else "float32",
        "alt_conv_impl": alt_impl,
        "components": res,
        "note": "eval_vmap is the engine's path; eval_single x nodes is the "
                "conv floor; mfu row context: 261 ms/round full program",
    }))


if __name__ == "__main__":
    main()
