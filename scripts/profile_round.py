"""Where does the ms/round go? Phase attribution for the round program.

Times the compiled round scan in three configurations — full round,
evaluation disabled (``eval_every`` past the horizon), and a doubled
local-epoch count (the extra epoch's cost isolates one epoch of training;
``local_epochs=0`` is not "train off" — it still takes one reference-
semantics step) — and differences them into a train/exchange/eval
breakdown, alongside XLA's own per-round FLOP and bytes-accessed counts
from ``cost_analysis`` on the AOT-compiled program. This is the first tool
to reach for when attacking the MFU number on real hardware (VERDICT
round-2 #2): it says whether the round is train-bound, eval-bound, or
exchange-bound before any kernel work starts.

The round program's phases are additionally wrapped in ``jax.named_scope``
(:mod:`gossipy_tpu.telemetry.scopes`), so the differential numbers can be
cross-checked against direct attribution: the JSON row reports which phase
scopes the compiled HLO carries, and with ``--trace`` the dumped XProf
trace is scanned for the same names — open it in
TensorBoard/XProf and the named phase bands give per-op timing the
differencing can only approximate.

Usage (repo root):
    python scripts/profile_round.py              # north-star LogReg config
    python scripts/profile_round.py --cnn        # flagship CIFAR CNN config
    python scripts/profile_round.py --nodes 100 --rounds 200
    python scripts/profile_round.py --trace /tmp/trace   # + jax.profiler dump

Runs on whatever backend initializes (CPU rows are labeled); safe under a
wedged tunnel — the backend probe degrades to CPU instead of hanging.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_sim(cnn: bool, n_nodes: int, local_epochs: int = 1,
              eval_every: int = 1, sampling_eval: float = 0.0,
              probes: bool = False):
    import jax.numpy as jnp
    import optax

    from gossipy_tpu.core import AntiEntropyProtocol, CreateModelMode, \
        Topology
    from gossipy_tpu.data import ClassificationDataHandler, DataDispatcher
    from gossipy_tpu.handlers import SGDHandler, losses
    from gossipy_tpu.models import CIFAR10Net, LogisticRegression
    from gossipy_tpu.simulation import GossipSimulator

    rng = np.random.default_rng(0)
    if cnn:
        n_train, n_test = 128 * n_nodes, 1280
        X = rng.normal(size=(n_train, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 10, n_train)
        Xte = rng.normal(size=(n_test, 32, 32, 3)).astype(np.float32)
        yte = rng.integers(0, 10, n_test)
        dh = ClassificationDataHandler(X, y, Xte, yte)
        model, n_classes, in_shape = CIFAR10Net(), 10, (32, 32, 3)
        # bf16 is the TPU measurement dtype; on CPU it is emulated ~10x
        # slower, so the labeled fallback profiles in fp32 (bench_mfu's
        # degraded-path convention).
        import jax
        dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else None
    else:
        d = 57
        X = rng.normal(size=(46 * n_nodes, d)).astype(np.float32)
        y = (X @ rng.normal(size=d) > 0).astype(np.int64)
        dh = ClassificationDataHandler(X, y, test_size=0.2, seed=42)
        model, n_classes, in_shape = LogisticRegression(d, 2), 2, (d,)
        dtype = None
    handler = SGDHandler(
        model=model, loss=losses.cross_entropy, optimizer=optax.sgd(0.1),
        local_epochs=local_epochs, batch_size=32, n_classes=n_classes,
        input_shape=in_shape, compute_dtype=dtype,
        create_model_mode=CreateModelMode.MERGE_UPDATE)
    disp = DataDispatcher(dh, n=n_nodes, eval_on_user=False)
    return GossipSimulator(
        handler,
        Topology.random_regular(n_nodes, min(20, n_nodes - 1), seed=42,
                                backend="networkx"),
        disp.stacked(), delta=100, protocol=AntiEntropyProtocol.PUSH,
        eval_every=eval_every, sampling_eval=sampling_eval, probes=probes)


def time_config(rounds: int, **kwargs) -> float:
    """Steady-state ms/round for one configuration (compile + timed run)."""
    import jax

    sim = build_sim(**kwargs)
    key = jax.random.PRNGKey(42)
    state = sim.init_nodes(key)
    s2, _ = sim.start(state, n_rounds=rounds, key=key,  # compile + warm
                      donate_state=False)
    jax.block_until_ready(s2.model.params)
    t0 = time.perf_counter()
    s3, _ = sim.start(state, n_rounds=rounds, key=key)
    jax.block_until_ready(s3.model.params)
    return (time.perf_counter() - t0) / rounds * 1e3


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--cnn", action="store_true",
                    help="flagship CIFAR CNN config (default: north-star "
                         "LogReg)")
    ap.add_argument("--nodes", type=int, default=None)
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--trace", metavar="DIR", default=None,
                    help="also dump a jax.profiler trace of the full round")
    ap.add_argument("--probes", action="store_true",
                    help="also time the round with the gossip-dynamics "
                         "probes on (telemetry.probes) and report their "
                         "marginal ms/round")
    args = ap.parse_args()

    import _virtual_mesh
    ok, detail = _virtual_mesh.probe_backend_alive()
    if not ok:
        print(f"[profile] backend unreachable ({detail}); re-exec on CPU",
              file=sys.stderr)
        env = _virtual_mesh.virtual_mesh_env(1, extra_path=_REPO)
        os.execve(sys.executable, [sys.executable] + sys.argv, env)

    import jax

    from gossipy_tpu import enable_compilation_cache
    enable_compilation_cache()

    n_nodes = args.nodes or (100 if not args.cnn else 100)
    rounds = args.rounds or (20 if args.cnn else 200)
    sampling = 0.1 if args.cnn else 0.0

    # XLA's own counts on the AOT-compiled 1-round program, captured as
    # the same telemetry.cost.CostReport the perf= layer banks.
    from gossipy_tpu.telemetry import cost_report_for
    sim = build_sim(args.cnn, n_nodes, sampling_eval=sampling)
    key = jax.random.PRNGKey(42)
    state = sim.init_nodes(key)
    cr = cost_report_for(sim, state, key, n_rounds=1, label="profile/1r")
    # Phase-scope cross-check: the named scopes the round program carries
    # (telemetry.scopes). All four in ROUND_PHASES should appear — a
    # missing one means the differential attribution below is the only
    # signal left for that phase.
    from gossipy_tpu.telemetry import ROUND_PHASES, phases_in_text
    try:
        compiled = sim.lower_start(state, n_rounds=1, key=key).compile()
        scopes_in_hlo = phases_in_text(compiled.as_text())
    except Exception:  # some backends cannot re-serialize the executable
        scopes_in_hlo = None

    # Differential attribution (telemetry.cost): eval structurally
    # toggled, one epoch isolated, exchange = the remainder — the
    # host-timer fallback that needs no profiler support.
    from gossipy_tpu.telemetry import differential_phase_attribution
    attribution = differential_phase_attribution(
        lambda **ov: build_sim(args.cnn, n_nodes, sampling_eval=sampling,
                               **ov),
        rounds=rounds, key=key)
    full = attribution["full_ms"]
    phases_ms = attribution["phases_ms"]
    probed = None
    if args.probes:
        probed = time_config(rounds, cnn=args.cnn, n_nodes=n_nodes,
                             sampling_eval=sampling, probes=True)

    flops = cr.flops if cr is not None else None
    bytes_ac = cr.bytes_accessed if cr is not None else None
    kind = jax.devices()[0].device_kind
    print(json.dumps({
        "config": "cnn" if args.cnn else "north-star",
        "backend": jax.default_backend(),
        "device_kind": kind,
        "n_nodes": n_nodes,
        "rounds_per_call": rounds,
        "ms_per_round": {
            "full": round(full, 3),
            "eval": round(phases_ms["eval"], 3),
            "train_one_epoch": round(phases_ms["train"], 3),
            "exchange_and_overhead":
                round(phases_ms["exchange_and_overhead"], 3),
            **({"probes_marginal": round(probed - full, 3)}
               if probed is not None else {}),
        },
        "note": attribution["note"],
        "phase_scopes_in_hlo": scopes_in_hlo,
        "phase_scopes_expected": list(ROUND_PHASES),
        "xla_per_round": {
            "gflops": (round(flops / 1e9, 3)
                       if flops is not None else None),
            "gbytes_accessed": (round(bytes_ac / 1e9, 3)
                                if bytes_ac is not None else None),
        },
        "hbm_peak_bytes": cr.peak_bytes if cr is not None else None,
        "achieved_gflops_per_s": (round(flops / (full / 1e3) / 1e9, 1)
                                  if flops is not None else None),
    }))

    if args.trace:
        sim = build_sim(args.cnn, n_nodes, sampling_eval=sampling)
        state = sim.init_nodes(key)
        s2, _ = sim.start(state, n_rounds=rounds, key=key,  # compile first
                          donate_state=False)
        jax.block_until_ready(s2.model.params)
        # Ask for the perfetto JSON alongside the xplane protobufs: the
        # per-phase reducer below parses it (older jax without the kwarg
        # still dumps the xplane trace for TensorBoard).
        try:
            tracer = jax.profiler.trace(args.trace,
                                        create_perfetto_trace=True)
        except TypeError:
            tracer = jax.profiler.trace(args.trace)
        with tracer:
            s3, _ = sim.start(state, n_rounds=rounds, key=key)
            jax.block_until_ready(s3.model.params)
        print(f"[profile] trace written to {args.trace}", file=sys.stderr)
        # Direct per-phase attribution from the scoped trace — the
        # primary signal when profiling is on (the differential numbers
        # above are the cross-check / fallback).
        from gossipy_tpu.telemetry import phase_times_from_trace, \
            phases_in_trace_dir
        from gossipy_tpu.telemetry.cost import hlo_op_phases
        # The CPU runtime's JSON traces carry bare HLO op names without
        # scope metadata — bridge them through the compiled program's own
        # op_name metadata (TPU XProf dumps match on the scope directly).
        try:
            op_map = hlo_op_phases(
                sim.lower_start(s3, n_rounds=rounds, key=key)
                .compile().as_text())
        except Exception:
            op_map = None
        per_phase = phase_times_from_trace(args.trace, op_to_phase=op_map)
        if per_phase is not None:
            total = rounds  # trace covers `rounds` rounds
            print("[profile] trace per-phase ms/round: "
                  + json.dumps({p: round(v / total, 3)
                                for p, v in per_phase.items()}),
                  file=sys.stderr)
        else:
            print("[profile] trace carries no parsable phase durations "
                  "(presence check below; differential attribution is "
                  "the timing source)", file=sys.stderr)
        in_trace = phases_in_trace_dir(args.trace)
        missing = [p for p in ROUND_PHASES if p not in in_trace]
        print(f"[profile] phase scopes in trace: {in_trace}"
              + (f" (missing: {missing})" if missing else " (all present)"),
              file=sys.stderr)


if __name__ == "__main__":
    main()
