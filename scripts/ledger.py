"""Run-ledger forensics CLI: list / show / diff / trend / bisect / merge.

The ledger (``gossipy_tpu.telemetry.ledger``) is the crash-safe
append-only index every producer appends a digest row to — engine runs,
service tenants, bench rows, ladder rungs, loadgen SLO rows, flight-
recorder crash bundles. This CLI answers the forensic questions on top:

``list PATH``
    Markdown table of every row (filter ``--kind/--backend/--config
    k=v``; ``--metric NAME`` adds that metric's column and drops rows
    without it; ``--json`` for machines).
``show PATH RUN_ID``
    The full row (abbreviated run ids accepted, git style; ``@i``
    indexes rows in file order, ``@-1`` is the newest).
``diff PATH A B``
    What changed between two runs: config-field diff (dotted keys),
    headline metric deltas, code versions — and, when both rows link a
    live report.json artifact, the FIRST DIVERGENT ROUND of the two
    runs' per-round accounting (sent/failed/eval curves).
    ``--expect-config-diff`` exits 1 unless at least one config field
    differs (the CI smoke assertion).
``trend PATH --metric M``
    bench_trend's regression gate generalized to any ledger metric:
    per-backend groups, latest non-degraded row vs best prior,
    ``--max-regress`` budget.
``bisect PATH ROW --baseline BASE``
    A ``git bisect run`` helper: replays ROW's pinned experiment config
    (``run_experiment``) at the CURRENT checkout, measures the headline
    metric and exits git-bisect style — 0 (good) when within ``--tol``
    of BASE's recorded value, 1 (bad) when worse, 125 (skip) when the
    row carries no replayable config or the replay itself fails::

        git bisect start BAD GOOD
        git bisect run python scripts/ledger.py bisect ledger.jsonl \\
            <row> --baseline <base> --metric final_accuracy

``merge OUT IN [IN...]``
    Fold several per-process/per-pod ledgers into one fleet-wide index
    (associative, commutative, idempotent — ``merge_ledgers``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# Metrics where smaller is better (bisect/trend direction; everything
# else — rounds/sec, MFU, speedups, accuracy — regresses DOWN).
_LOWER_BETTER = ("_ms", "_seconds", "host_blocked_frac")


def _lower_is_better(metric: str) -> bool:
    return metric.endswith(_LOWER_BETTER)


def _load(path: str):
    from gossipy_tpu.telemetry.ledger import RunLedger
    led = RunLedger(path)
    doc = led.read()
    if doc["skipped"]:
        print(f"[ledger] {path}: skipped {doc['skipped']} torn/corrupt "
              "line(s)", file=sys.stderr)
    return doc["rows"]


def _resolve(rows: list, ref: str) -> dict:
    """One row from a ``@i`` index or a run-id prefix; ambiguity and
    misses are hard errors (forensics must never guess)."""
    if ref.startswith("@"):
        try:
            return rows[int(ref[1:])]
        except (ValueError, IndexError):
            raise SystemExit(f"ledger: no row at index {ref!r} "
                             f"({len(rows)} rows)")
    hits = [r for r in rows
            if str(r.get("run_id", "")).startswith(ref)]
    if not hits:
        raise SystemExit(f"ledger: no row with run id {ref!r}")
    if len(hits) > 1:
        ids = ", ".join(str(r.get("run_id")) for r in hits[:8])
        raise SystemExit(f"ledger: run id {ref!r} is ambiguous ({ids})")
    return hits[0]


def _flatten(d: dict, prefix: str = "") -> dict:
    out: dict = {}
    for k in sorted(d, key=str):
        v = d[k]
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def _fmt_ts(ts) -> str:
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))
    except (TypeError, ValueError, OverflowError):
        return "?"


def _match_filters(row: dict, args) -> bool:
    if args.kind and row.get("kind") != args.kind:
        return False
    if args.backend and row.get("backend") != args.backend:
        return False
    if getattr(args, "metric", None) and \
            args.metric not in (row.get("metrics") or {}):
        return False
    for spec in getattr(args, "config", None) or []:
        field, _, want = spec.partition("=")
        flat = _flatten(row.get("config") or {})
        if str(flat.get(field)) != want:
            return False
    return True


# -- list / show -------------------------------------------------------------

def cmd_list(args) -> int:
    rows = [r for r in _load(args.path) if _match_filters(r, args)]
    if args.json:
        out = json.dumps(rows, indent=2)
    else:
        metric_cols = [args.metric] if args.metric else \
            ["rounds_per_sec", "final_accuracy", "slo_p99_ms"]
        head = (["run id", "when", "kind", "backend", "config"]
                + metric_cols + ["failure"])
        lines = ["# Run ledger — " + os.path.basename(args.path), "",
                 "| " + " | ".join(head) + " |",
                 "|" + "---|" * len(head)]
        for r in rows:
            metrics = r.get("metrics") or {}
            cells = [str(r.get("run_id", "?")), _fmt_ts(r.get("ts")),
                     str(r.get("kind", "?")),
                     str(r.get("backend") or ""),
                     str(r.get("config_fingerprint") or "")[:8]]
            for m in metric_cols:
                v = metrics.get(m)
                cells.append(f"{v:.4g}" if isinstance(v, float) else
                             ("" if v is None else str(v)))
            fail = r.get("failure") or {}
            cells.append(str(fail.get("kind", "")) if fail else "")
            lines.append("| " + " | ".join(cells) + " |")
        lines.append("")
        lines.append(f"{len(rows)} row(s)")
        out = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(out)
        print(f"[ledger] {len(rows)} row(s) -> {args.out}",
              file=sys.stderr)
    else:
        print(out)
    return 0


def cmd_show(args) -> int:
    row = _resolve(_load(args.path), args.run_id)
    print(json.dumps(row, indent=2, sort_keys=True))
    return 0


# -- diff --------------------------------------------------------------------

def _first_divergent_round(row_a: dict, row_b: dict):
    """1-based first round where the two runs' per-round accounting
    (sent/failed, then the eval curves) differs, via the rows' linked
    report.json artifacts — None when either report is not live or the
    runs never diverge over their common prefix."""
    import numpy as np

    from gossipy_tpu.simulation.report import SimulationReport
    reports = []
    for row in (row_a, row_b):
        path = ((row.get("artifacts") or {}).get("report") or {}) \
            .get("path")
        if not path or not os.path.exists(path):
            return None
        try:
            reports.append(SimulationReport.load(path))
        except Exception:
            return None
    ra, rb = reports
    series = [(ra.sent_per_round, rb.sent_per_round),
              (ra.failed_per_round, rb.failed_per_round)]
    ca = ra.curves(local=False, drop_nan=False)
    cb = rb.curves(local=False, drop_nan=False)
    for name in ca:
        if name in cb:
            series.append((ca[name], cb[name]))
    first = None
    for a, b in series:
        a, b = np.asarray(a, float), np.asarray(b, float)
        n = min(len(a), len(b))
        if n == 0:
            continue
        a, b = a[:n], b[:n]
        neq = ~((a == b) | (np.isnan(a) & np.isnan(b)))
        idx = np.nonzero(neq)[0]
        if len(idx):
            r = int(idx[0]) + 1
            first = r if first is None else min(first, r)
    return first


def diff_rows(row_a: dict, row_b: dict) -> dict:
    """The forensic diff between two ledger rows (pure function — the
    e2e test and the CLI share it)."""
    flat_a = _flatten(row_a.get("config") or {})
    flat_b = _flatten(row_b.get("config") or {})
    config_diff = {
        k: {"a": flat_a.get(k), "b": flat_b.get(k)}
        for k in sorted(set(flat_a) | set(flat_b))
        if flat_a.get(k) != flat_b.get(k)
    }
    ma, mb = row_a.get("metrics") or {}, row_b.get("metrics") or {}
    metric_deltas = {}
    for k in sorted(set(ma) | set(mb)):
        a, b = ma.get(k), mb.get(k)
        entry: dict = {"a": a, "b": b}
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            entry["delta"] = b - a
            if a:
                entry["pct"] = (b - a) / abs(a)
        metric_deltas[k] = entry
    cv = {side: ((row.get("code_version") or {}).get("git_sha"))
          for side, row in (("a", row_a), ("b", row_b))}
    return {
        "a": row_a.get("run_id"), "b": row_b.get("run_id"),
        "kinds": [row_a.get("kind"), row_b.get("kind")],
        "fingerprint_changed": (row_a.get("config_fingerprint")
                                != row_b.get("config_fingerprint")),
        "config_diff": config_diff,
        "metric_deltas": metric_deltas,
        "code_version": cv,
        "first_divergent_round": _first_divergent_round(row_a, row_b),
    }


def cmd_diff(args) -> int:
    rows = _load(args.path)
    d = diff_rows(_resolve(rows, args.a), _resolve(rows, args.b))
    if args.json:
        print(json.dumps(d, indent=2))
    else:
        print(f"ledger diff {d['a']} ({d['kinds'][0]}) -> "
              f"{d['b']} ({d['kinds'][1]})")
        print(f"  code: {d['code_version']['a']} -> "
              f"{d['code_version']['b']}  fingerprint "
              f"{'CHANGED' if d['fingerprint_changed'] else 'same'}")
        if d["config_diff"]:
            print("  config:")
            for k, v in d["config_diff"].items():
                print(f"    {k}: {v['a']!r} -> {v['b']!r}")
        else:
            print("  config: identical")
        for k, v in d["metric_deltas"].items():
            pct = f" ({v['pct']:+.1%})" if "pct" in v else ""
            print(f"  {k}: {v['a']} -> {v['b']}{pct}")
        if d["first_divergent_round"] is not None:
            print(f"  first divergent round: "
                  f"{d['first_divergent_round']} (from linked reports)")
    if args.expect_config_diff and not d["config_diff"]:
        print("[ledger] diff: expected config fields to differ, none do",
              file=sys.stderr)
        return 1
    return 0


# -- trend -------------------------------------------------------------------

def cmd_trend(args) -> int:
    """bench_trend's gate over any ledger metric: ledger rows become
    pseudo bench rows and flow through the same analyze()."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from bench_trend import analyze
    entries = []
    rows = [r for r in _load(args.path) if _match_filters(r, args)]
    rows.sort(key=lambda r: r.get("ts") or 0.0)
    unit = "ms" if args.metric.endswith("_ms") else ""
    for order, r in enumerate(rows):
        v = (r.get("metrics") or {}).get(args.metric)
        if v is None:
            continue
        entries.append({
            "source": f"{r.get('run_id', '?')}[{r.get('kind', '?')}]",
            "order": order,
            "row": {"metric": args.metric, "value": v, "unit": unit,
                    "raw": {"backend": r.get("backend", "unrecorded"),
                            "degraded": bool(r.get("degraded"))}}})
    table, regressions = analyze(entries, args.max_regress)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(table)
        print(f"[ledger] trend: {len(entries)} row(s) -> {args.out}",
              file=sys.stderr)
    else:
        print(table)
    for r in regressions:
        print(f"[ledger] REGRESSION: {r}", file=sys.stderr)
    return 1 if regressions else 0


# -- bisect ------------------------------------------------------------------

def _replay_metric(row: dict, metric: str):
    """Re-run the row's pinned experiment config at the current checkout
    and measure ``metric``. Returns a float, or raises (callers map
    failures to exit 125 — git bisect's skip)."""
    import time as _time

    from gossipy_tpu.config import ExperimentConfig, run_experiment
    cfg = ExperimentConfig.from_dict(dict(row["experiment"]))
    t0 = _time.perf_counter()
    _state, report = run_experiment(cfg)
    wall = _time.perf_counter() - t0
    if isinstance(report, list):  # cfg.repetitions > 1
        report = report[0]
    if metric == "final_accuracy":
        for name in ("accuracy", "auc", "f1"):
            v = report.final(name)
            if v == v:
                return float(v)
        raise RuntimeError("replay produced no finite eval metric")
    if metric == "rounds_per_sec":
        # Includes compile time — coarse, but consistent across the
        # bisected commits; keep --tol generous for this metric.
        return float(cfg.n_rounds) / max(wall, 1e-9)
    raise RuntimeError(f"bisect cannot measure metric {metric!r}")


def cmd_bisect(args) -> int:
    SKIP = 125
    try:
        rows = _load(args.path)
        row = _resolve(rows, args.row)
        base = _resolve(rows, args.baseline)
    except SystemExit as e:
        print(f"[bisect] skip: {e}", file=sys.stderr)
        return SKIP
    baseline = (base.get("metrics") or {}).get(args.metric)
    if not isinstance(baseline, (int, float)):
        print(f"[bisect] skip: baseline row {base.get('run_id')} has no "
              f"recorded {args.metric}", file=sys.stderr)
        return SKIP
    if not isinstance(row.get("experiment"), dict):
        print(f"[bisect] skip: row {row.get('run_id')} carries no "
              "replayable experiment config", file=sys.stderr)
        return SKIP
    try:
        measured = _replay_metric(row, args.metric)
    except Exception as e:
        print(f"[bisect] skip: replay failed: {e!r}", file=sys.stderr)
        return SKIP
    lib = _lower_is_better(args.metric)
    if lib:
        bad = measured > baseline * (1.0 + args.tol)
    else:
        bad = measured < baseline * (1.0 - args.tol)
    verdict = "BAD" if bad else "good"
    print(f"[bisect] {args.metric}: measured {measured:.6g} vs baseline "
          f"{baseline:.6g} (tol {args.tol:.0%}, "
          f"{'lower' if lib else 'higher'}-is-better) -> {verdict}",
          file=sys.stderr)
    print(json.dumps({"metric": args.metric, "measured": measured,
                      "baseline": baseline, "tol": args.tol,
                      "verdict": verdict}))
    return 1 if bad else 0


# -- merge -------------------------------------------------------------------

def cmd_merge(args) -> int:
    from gossipy_tpu.telemetry.ledger import merge_ledger_files
    n = merge_ledger_files(args.out, args.inputs)
    print(f"[ledger] merged {len(args.inputs)} file(s) -> {args.out} "
          f"({n} rows)", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("list", help="markdown table of rows")
    p.add_argument("path")
    p.add_argument("--kind", default=None)
    p.add_argument("--backend", default=None)
    p.add_argument("--metric", default=None,
                   help="only rows carrying this metric; adds its column")
    p.add_argument("--config", action="append", default=[],
                   metavar="FIELD=VALUE",
                   help="filter on a (dotted) config field (repeatable)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--out", default=None)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("show", help="one full row")
    p.add_argument("path")
    p.add_argument("run_id", help="run-id prefix or @index")
    p.set_defaults(fn=cmd_show)

    p = sub.add_parser("diff", help="config + metric diff of two rows")
    p.add_argument("path")
    p.add_argument("a", help="run-id prefix or @index")
    p.add_argument("b", help="run-id prefix or @index")
    p.add_argument("--json", action="store_true")
    p.add_argument("--expect-config-diff", action="store_true",
                   help="exit 1 unless at least one config field differs")
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser("trend",
                       help="bench_trend's gate over any ledger metric")
    p.add_argument("path")
    p.add_argument("--metric", required=True)
    p.add_argument("--kind", default=None)
    p.add_argument("--backend", default=None)
    p.add_argument("--config", action="append", default=[],
                   metavar="FIELD=VALUE")
    p.add_argument("--max-regress", type=float, default=0.15)
    p.add_argument("--out", default=None)
    p.set_defaults(fn=cmd_trend)

    p = sub.add_parser("bisect", help="git bisect run helper")
    p.add_argument("path")
    p.add_argument("row", help="row to replay (run-id prefix or @index)")
    p.add_argument("--baseline", required=True,
                   help="row whose recorded metric is the good value")
    p.add_argument("--metric", default="final_accuracy",
                   choices=("final_accuracy", "rounds_per_sec"))
    p.add_argument("--tol", type=float, default=0.15,
                   help="tolerated fractional regression (default 0.15)")
    p.set_defaults(fn=cmd_bisect)

    p = sub.add_parser("merge", help="fold ledgers into one index")
    p.add_argument("out")
    p.add_argument("inputs", nargs="+")
    p.set_defaults(fn=cmd_merge)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
