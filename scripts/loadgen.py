"""Sustained mixed-shape arrival harness: the service SLO benchmark.

Drives :func:`gossipy_tpu.service.slo.run_load` — Poisson tenant
arrivals over a mixed-shape spec pool, served open-loop by an
incremental :class:`~gossipy_tpu.service.scheduler.ServiceSession`
(arrivals interleave with running buckets, so queue-wait and
time-to-first-round are measured under real contention) — and emits the
``service_slo`` bench row the ROADMAP's always-on-service item names as
its "Done" evidence::

    {"metric": "service_slo", "value": <tenants/hour>,
     "unit": "tenants/hour",
     "raw": {"tenants_per_hour", "ttfr_p50_ms", "ttfr_p99_ms",
             "round_p50_ms", "round_p99_ms", "queue_wait_p99_ms",
             "n_admitted", "ttfr_missing": [], ...}}

Stdout carries the ONE row JSON line (bench.py's contract style); the
human-readable account goes to stderr. Artifacts under ``--out``:
per-tenant report/manifest/events (the normal service layout),
``slo_row.json`` (the row), ``metrics/metrics.json`` +
``metrics/metrics.prom`` (registry snapshot + OpenMetrics export —
tail the former live with ``scripts/service_top.py``), and
``metrics/trace.json`` + ``trace_report.json`` (the host span timeline
(telemetry.tracing) and its critical-path account; the row carries
``raw.host_blocked_frac`` from it).

Exit status: 0 only when every tenant that was admitted finished (DONE
or EVICTED) AND has a recorded time-to-first-round (the acceptance
invariant); 1 otherwise.

Usage::

    python scripts/loadgen.py --out load-runs --tenants 6 --rate 1200
    python scripts/loadgen.py --out load-runs --pool pool.json \
        --tenants 20 --rate 600 --time-scale 0.01
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="load-runs",
                    help="artifact root (service layout + slo_row.json)")
    ap.add_argument("--pool", default=None,
                    help="JSON file: list of ExperimentConfig template "
                         "dicts (default: the built-in two-shape pool)")
    ap.add_argument("--tenants", type=int, default=6,
                    help="number of tenants to generate from the pool")
    ap.add_argument("--rate", type=float, default=1200.0,
                    help="offered Poisson arrival rate, tenants/hour")
    ap.add_argument("--time-scale", type=float, default=1.0,
                    help="compress the arrival schedule by this factor "
                         "(0.01 = 100x faster than nominal; reported "
                         "offered rate is adjusted accordingly)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slice", type=int, default=3,
                    help="rounds per cooperative scheduling slice")
    ap.add_argument("--rounds", type=int, default=6,
                    help="rounds per tenant (built-in pool only)")
    ap.add_argument("--metrics-dir", default=None,
                    help="metrics snapshot/export dir "
                         "(default: <out>/metrics)")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="run-ledger file (telemetry.ledger): every "
                         "finalized tenant appends a digest row and the "
                         "service_slo row lands as the run's index entry "
                         "(default: $GOSSIPY_TPU_LEDGER)")
    args = ap.parse_args()

    from gossipy_tpu import enable_compilation_cache
    enable_compilation_cache()
    from gossipy_tpu.service.slo import default_spec_pool, run_load

    if args.pool:
        with open(args.pool) as fh:
            pool = json.load(fh)
        if not isinstance(pool, list) or not pool:
            raise SystemExit(f"--pool {args.pool}: expected a non-empty "
                             "JSON list of config dicts")
    else:
        pool = default_spec_pool(n_rounds=args.rounds)

    from gossipy_tpu.telemetry.tracing import Tracer, trace_report

    from gossipy_tpu.telemetry.ledger import ingest_slo_row, resolve_ledger

    metrics_dir = args.metrics_dir or os.path.join(args.out, "metrics")
    tracer = Tracer(process_name="loadgen")
    ledger = resolve_ledger(args.ledger or None)
    result = run_load(args.out, pool=pool, n_tenants=args.tenants,
                      rate_per_hour=args.rate, seed=args.seed,
                      slice_rounds=args.slice, metrics_dir=metrics_dir,
                      time_scale=args.time_scale, tracing=tracer,
                      ledger=ledger)
    row, queue = result["row"], result["queue"]

    # Final trace + critical-path report: the session already refreshed
    # metrics_dir/trace.json each poll cycle; save the complete timeline
    # and fold host-efficiency into the bench row so bench_trend carries
    # it next to the tenants/hour it explains.
    os.makedirs(metrics_dir, exist_ok=True)
    trace_path = tracer.save(os.path.join(metrics_dir, "trace.json"))
    report = trace_report(tracer.snapshot())
    report_path = os.path.join(args.out, "trace_report.json")
    with open(report_path, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    tot = report["totals"]
    row["raw"]["host_blocked_frac"] = tot["host_blocked_frac"]
    row["raw"]["trace_overlap_frac"] = tot["overlap_frac"]
    # Self-consistency of the attribution (host_blocked + device +
    # unaccounted == wall is exact by construction; the service loop has
    # untraced admission/build host work, so only the identity — not a
    # tight unaccounted bound — is asserted here).
    trace_ok = (report["n_windows"] >= 1
                and tot["host_blocked_ms"] is not None
                and tot["overlap_frac"] is not None
                and abs(tot["wall_ms"] - tot["host_blocked_ms"]
                        - tot["device_ms"] - tot["unaccounted_ms"]) < 1.0)
    print(f"[loadgen] trace: {trace_path} -> {report_path} "
          f"(host_blocked {tot['host_blocked_ms']} ms, "
          f"overlap {tot['overlap_frac']:.1%}, windows "
          f"{report['n_windows']})", file=sys.stderr)
    try:
        # Backend stamp (bench.py emit() convention) so bench_trend
        # groups this row with its hardware peers, not across backends.
        import jax
        row["raw"]["backend"] = jax.default_backend()
        row["raw"]["device_kind"] = jax.devices()[0].device_kind
    except Exception:
        pass

    for h in queue.handles():
        ttfr = (f"{h.first_round_at - h.submitted_at:.3f}s"
                if h.first_round_at is not None else "MISSING")
        print(f"[loadgen] {h.tenant}: {h.status.value} "
              f"({h.rounds_completed}/{h.request.rounds} rounds) "
              f"ttfr={ttfr}", file=sys.stderr)
    raw = row["raw"]
    print(f"[loadgen] {raw['n_admitted']} admitted / "
          f"{raw['n_failed']} failed-to-build in "
          f"{raw['wall_seconds']}s -> {row['value']} tenants/hour, "
          f"ttfr p99 {raw['ttfr_p99_ms']} ms, "
          f"round p99 {raw['round_p99_ms']} ms", file=sys.stderr)
    print(f"[loadgen] metrics: {metrics_dir}/metrics.json (+ .prom); "
          f"tail with: python scripts/service_top.py {metrics_dir}",
          file=sys.stderr)

    row_path = os.path.join(args.out, "slo_row.json")
    with open(row_path, "w") as fh:
        json.dump(row, fh, indent=2)
        fh.write("\n")
    print(json.dumps(row))

    if ledger is not None:
        try:
            # The run's index entry (telemetry.ledger): tenants/hour +
            # SLO percentiles + the trace headline, with slo_row.json /
            # trace_report.json as hashed artifacts. The per-tenant rows
            # landed at each finalize above.
            lrow = ingest_slo_row(ledger, row, artifacts={
                "slo_row": row_path, "trace_report": report_path})
            print(f"[loadgen] ledger: row {lrow['run_id']} -> "
                  f"{ledger.path}", file=sys.stderr)
        except Exception as e:
            print(f"[loadgen] ledger ingest failed: {e!r}",
                  file=sys.stderr)

    # Acceptance invariant: every admitted tenant has a recorded TTFR
    # and nothing failed outright.
    ok = (not raw["ttfr_missing"]
          and raw["n_admitted"] == raw["ttfr_recorded"]
          and raw["n_failed"] == 0
          and raw["n_admitted"] == raw["n_done"] + raw["n_evicted"])
    if not ok:
        print(f"[loadgen] SLO invariant violated: "
              f"missing_ttfr={raw['ttfr_missing']} "
              f"failed={raw['n_failed']}", file=sys.stderr)
    if not trace_ok:
        print(f"[loadgen] trace invariant violated: "
              f"windows={report['n_windows']} totals={tot}",
              file=sys.stderr)
    return 0 if ok and trace_ok else 1


if __name__ == "__main__":
    sys.exit(main())
