"""trace_report: critical-path analysis of a host span trace.

Reads one or more ``trace.json`` files written by the host span tracer
(:mod:`gossipy_tpu.telemetry.tracing` — engine/cohort runs with
``tracing=``, the service scheduler, ``scripts/loadgen.py``), reduces
them with :func:`~gossipy_tpu.telemetry.tracing.trace_report`, and
writes ``trace_report.json`` next to the (first) input:

- **totals** — wall_ms, host_busy_ms, host_blocked_ms, device_ms,
  overlap_ms, unaccounted_ms, plus host_blocked_frac / overlap_frac /
  unaccounted_frac over every recorded run window;
- **per_round** — the same attribution divided by each window's round
  count: per-round host_blocked_ms / device_ms / overlap_frac;
- **critical_path** — span names ranked by their exclusive
  contribution to the non-overlapped timeline (what to optimize next).

Multiple inputs are merged first (``merge_traces`` — associative, so
per-process service traces reduce in any order) and analyzed as ONE
timeline; windows from different pids never overlap-count each other.

Streaming-cohort traces (``CohortConfig(prefetch=k)``) record
``cohort.segment`` windows that OVERLAP in time — segment t+1's sample
and gather run while segment t executes. Attribution stays exact:
spans carry a ``window=<round_start>`` tag binding them to their
segment, and overlap/blocked time is measured against the pid-wide
device union, so a gather hidden behind a neighboring segment's run
counts as overlap (see :func:`~gossipy_tpu.telemetry.tracing.
trace_report`). Serial traces are reduced identically — their numbers
do not change.

``--bench-row`` stamps ``raw.host_blocked_frac`` (and
``raw.trace_overlap_frac``) into an existing bench-row JSON file in
place, so ``scripts/bench_trend.py`` can fold host-efficiency into the
trend ledger next to the throughput number it explains.

Usage::

    python scripts/trace_report.py runs/trace.json
    python scripts/trace_report.py p0/trace.json p1/trace.json \
        --out merged_report.json
    python scripts/trace_report.py runs/trace.json \
        --bench-row runs/slo_row.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gossipy_tpu.telemetry.tracing import merge_traces, trace_report  # noqa: E402


def load_traces(paths: list) -> dict:
    merged = None
    for path in paths:
        with open(path) as fh:
            snap = json.load(fh)
        if "traceEvents" not in snap:
            raise SystemExit(f"{path}: not a Chrome trace object "
                             "(no 'traceEvents' key)")
        merged = snap if merged is None else merge_traces(merged, snap)
    return merged


def summarize(report: dict) -> str:
    t = report["totals"]

    def frac(key):
        v = t.get(key)
        return f"{v:.1%}" if v is not None else "n/a"

    lines = [
        f"windows analyzed      {report['n_windows']}"
        f"  ({t['rounds']} rounds)",
        f"wall                  {t['wall_ms']:>10.1f} ms",
        f"device                {t['device_ms']:>10.1f} ms",
        f"host busy             {t['host_busy_ms']:>10.1f} ms"
        f"  (overlap with device: {frac('overlap_frac')})",
        f"host blocked          {t['host_blocked_ms']:>10.1f} ms"
        f"  ({frac('host_blocked_frac')} of wall)",
        f"unaccounted           {t['unaccounted_ms']:>10.1f} ms"
        f"  ({frac('unaccounted_frac')} of wall)",
    ]
    pr = report.get("per_round") or []
    if pr:
        n = len(pr)
        hb = sum(r["host_blocked_ms"] for r in pr) / n
        dv = sum(r["device_ms"] for r in pr) / n
        lines.append(f"per round (mean)      host_blocked {hb:.2f} ms "
                     f"| device {dv:.2f} ms")
    cp = report.get("critical_path") or []
    if cp:
        lines.append("critical path (non-overlapped ms):")
        for entry in cp[:10]:
            fr = (f"{entry['frac']:.1%}" if entry.get("frac") is not None
                  else "n/a")
            lines.append(f"  {entry['name']:<28} {entry['ms']:>10.1f}"
                         f"  ({fr})")
    return "\n".join(lines)


def stamp_bench_row(row_path: str, report: dict) -> None:
    """Fold the trace totals into an existing bench row IN PLACE
    (capsule ``{"parsed": row}`` files and bare rows both work)."""
    with open(row_path) as fh:
        doc = json.load(fh)
    row = doc.get("parsed", doc)
    if "metric" not in row:
        raise SystemExit(f"--bench-row {row_path}: not a bench row "
                         "(no 'metric' field)")
    raw = row.setdefault("raw", {})
    t = report["totals"]
    raw["host_blocked_frac"] = t["host_blocked_frac"]
    raw["trace_overlap_frac"] = t["overlap_frac"]
    raw["trace_host_blocked_ms"] = t["host_blocked_ms"]
    tmp = row_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, row_path)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="+",
                    help="trace.json file(s); several are merged "
                         "(merge_traces) before analysis")
    ap.add_argument("--out", default=None,
                    help="report path (default: trace_report.json next "
                         "to the first input)")
    ap.add_argument("--bench-row", default=None,
                    help="bench-row JSON to stamp raw.host_blocked_frac "
                         "into, in place")
    args = ap.parse_args()

    snap = load_traces(args.traces)
    report = trace_report(snap)
    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(args.traces[0])),
        "trace_report.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(report, fh, indent=2)
        fh.write("\n")
    os.replace(tmp, out)
    print(summarize(report))
    print(f"[trace_report] report -> {out}", file=sys.stderr)
    if args.bench_row:
        stamp_bench_row(args.bench_row, report)
        print(f"[trace_report] stamped host_blocked_frac into "
              f"{args.bench_row}", file=sys.stderr)
    if report["n_windows"] == 0:
        print("[trace_report] WARNING: no run windows in trace — totals "
              "are empty (was the traced segment ever entered?)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
