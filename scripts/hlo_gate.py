#!/usr/bin/env python
"""HLO-stability gate: the engine's round program across the feature grid.

Consolidates the previously scattered HLO-identity checks into one matrix
runner (gossipy_tpu/analysis/hlo.py supplies the matrix and the
canonicalized-fingerprint helpers):

1. **Identity pairs** — ``probes=None`` / ``sentinels=None`` /
   ``chaos=None`` (engine + All2All) must trace the byte-identical
   program as a build without the argument. Enforced unconditionally; on
   mismatch the FIRST divergent HLO instruction is printed and written to
   the ``--report`` JSON.

2. **Golden fingerprints** — every grid case (probes/sentinels/chaos on,
   history dtypes, All2All dense/padded/segment formulations) is hashed
   (canonicalized StableHLO) and compared against the committed manifest
   ``gossipy_tpu/analysis/hlo_golden.json``. HLO text is not stable
   across jax releases, so hashes are only compared when the manifest's
   recorded jax version AND backend match this process; otherwise the
   comparison is skipped with a warning (the identity pairs still gate).
   Regenerate after a deliberate program change or a jax bump with
   ``--update-golden``.

3. **Recompilation storm check** — drives a small sim for three chunked
   ``start()`` calls and reads the jit-cache event counters
   (``gossipy_tpu.compilation_cache_stats()``): re-driving the same
   (shapes, rounds) program must not re-trace. A second distinct chunk
   size is allowed one compile; anything beyond fails.

Exit codes: 0 all gates green (or skipped-with-warning), 1 divergence /
storm, 2 usage or environment error.
"""

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

GOLDEN = REPO / "gossipy_tpu" / "analysis" / "hlo_golden.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update-golden", action="store_true",
                    help="rewrite the golden manifest from this process's "
                         "fingerprints")
    ap.add_argument("--golden", default=str(GOLDEN))
    ap.add_argument("--report", default=None,
                    help="write a JSON divergence/summary report here")
    ap.add_argument("--skip-cache-check", action="store_true")
    ap.add_argument("--n-rounds", type=int, default=2)
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from gossipy_tpu.analysis.hlo import (
        first_divergence,
        gate_cases,
        hlo_fingerprint,
        lower_text,
        pallas_launch_count,
    )

    t0 = time.time()
    cases = gate_cases()
    report: dict = {"jax": jax.__version__,
                    "backend": jax.default_backend(),
                    "identity": {}, "fingerprint": {}, "launch": {},
                    "failures": []}
    failed = False

    print(f"[hlo_gate] jax {jax.__version__} backend "
          f"{jax.default_backend()}; {len(cases['identity'])} identity "
          f"pairs, {len(cases['fingerprint'])} fingerprint cases, "
          f"{len(cases.get('launch', []))} launch-count cases")

    for name, build, want in cases.get("launch", []):
        got = pallas_launch_count(build(), n_rounds=args.n_rounds)
        report["launch"][name] = {"want": want, "got": got}
        if got == want:
            print(f"[hlo_gate] launch-count {name}: {got} OK")
        else:
            failed = True
            report["failures"].append(f"launch:{name}")
            print(f"[hlo_gate] launch-count {name}: {got} != {want} — the "
                  "fused deliver must drain the whole mailbox in the "
                  "declared number of pallas launches")

    for name, build_a, build_b in cases["identity"]:
        key = jax.random.PRNGKey(0)
        sim_a, sim_b = build_a(), build_b()
        state = sim_a.init_nodes(key)
        ta = lower_text(sim_a, state, key, args.n_rounds)
        tb = lower_text(sim_b, state, key, args.n_rounds)
        div = first_divergence(ta, tb, "default", "feature_off")
        report["identity"][name] = {"identical": div is None,
                                    "divergence": div}
        if div is None:
            print(f"[hlo_gate] identity {name}: OK")
        else:
            failed = True
            report["failures"].append(f"identity:{name}")
            print(f"[hlo_gate] identity {name}: DIVERGED at canonical "
                  f"instruction {div['instruction']}:\n"
                  f"    default:     {div['default']}\n"
                  f"    feature_off: {div['feature_off']}")

    golden_path = Path(args.golden)
    golden = json.loads(golden_path.read_text()) \
        if golden_path.exists() else None
    fingerprints = {}
    for name, build in cases["fingerprint"]:
        fp, _ = hlo_fingerprint(build(), n_rounds=args.n_rounds)
        fingerprints[name] = fp
        report["fingerprint"][name] = fp
        print(f"[hlo_gate] fingerprint {name}: {fp}")

    if args.update_golden:
        golden_path.write_text(json.dumps({
            "jax": jax.__version__,
            "backend": jax.default_backend(),
            "n_rounds": args.n_rounds,
            "cases": fingerprints,
        }, indent=2, sort_keys=True) + "\n")
        print(f"[hlo_gate] golden manifest rewritten -> {golden_path}")
    elif golden is None:
        print("[hlo_gate] WARNING: no golden manifest; run with "
              "--update-golden to record one (identity pairs still gate)")
    elif golden.get("jax") != jax.__version__ or \
            golden.get("backend") != jax.default_backend():
        print("[hlo_gate] WARNING: golden recorded under jax "
              f"{golden.get('jax')}/{golden.get('backend')}, this process "
              f"is {jax.__version__}/{jax.default_backend()} — HLO text "
              "is not stable across jax releases, skipping hash "
              "comparison (identity pairs still gate). Regenerate with "
              "--update-golden after reviewing the program change.")
        report["golden_skipped"] = True
    else:
        for name, fp in fingerprints.items():
            want = golden["cases"].get(name)
            if want is None:
                print(f"[hlo_gate] WARNING: case {name} not in golden "
                      "manifest (new case?) — add it with --update-golden")
            elif want != fp:
                failed = True
                report["failures"].append(f"fingerprint:{name}")
                print(f"[hlo_gate] fingerprint {name}: CHANGED "
                      f"{want} -> {fp}. If deliberate, regenerate with "
                      "--update-golden; otherwise an engine change "
                      "perturbed this program's HLO.")
        stale = set(golden["cases"]) - set(fingerprints)
        if stale:
            print(f"[hlo_gate] WARNING: golden has stale cases {sorted(stale)}")

    if not args.skip_cache_check:
        misses = _recompilation_storm_check(args.n_rounds)
        report["jit_compiles_per_phase"] = misses
        # Phase layout: [cold chunk1, warm chunk1 again, chunk2 (new
        # n_rounds -> one legitimate compile), chunk2 again].
        ok = misses[1] == 0 and misses[3] == 0
        if not ok:
            failed = True
            report["failures"].append("recompilation-storm")
            print("[hlo_gate] recompilation storm: per-phase compile "
                  f"counts {misses} (re-driving an already-compiled "
                  "program must not re-trace)")
        else:
            print(f"[hlo_gate] jit-cache: per-phase compiles {misses} OK")

    report["elapsed_seconds"] = round(time.time() - t0, 2)
    if args.report:
        Path(args.report).write_text(json.dumps(report, indent=2) + "\n")
    print(f"[hlo_gate] {'FAILED' if failed else 'PASSED'} in "
          f"{report['elapsed_seconds']}s")
    return 1 if failed else 0


def _recompilation_storm_check(n_rounds: int) -> list:
    """Compile counts per drive phase via jax.monitoring events."""
    import jax

    from gossipy_tpu.analysis.hlo import _make_sim

    counts = {"n": 0}

    def listener(event, **kw):
        if "compil" in event.rsplit("/", 1)[-1]:
            counts["n"] += 1

    try:
        jax.monitoring.register_event_listener(listener)
    except Exception:
        print("[hlo_gate] WARNING: jax.monitoring unavailable; "
              "skipping the recompilation check")
        return [0, 0, 0, 0]

    sim = _make_sim()
    key = jax.random.PRNGKey(0)
    state = sim.init_nodes(key)
    phases = []
    for rounds in (n_rounds, n_rounds, n_rounds + 1, n_rounds + 1):
        before = counts["n"]
        state, _ = sim.start(state, n_rounds=rounds, key=key)
        jax.block_until_ready(state.model.params)
        phases.append(counts["n"] - before)
    return phases


if __name__ == "__main__":
    sys.exit(main())
