"""bench_trend: fold bench rows into a trend ledger + regression gate.

Reads every ``BENCH_r*.json`` driver capsule under ``--root`` (the
``{"n": …, "parsed": <bench row>}`` files the PR driver banks) plus any
``--row`` files (bare bench-row JSON — e.g. ``scripts/loadgen.py``'s
``slo_row.json`` with the ``service_slo`` metric) plus, with
``--ledger PATH``, the run ledger's bench-bearing rows
(``telemetry.ledger``; deduplicated by run id and against the
capsules, so a row that reached both sources never gates against
itself) and produces:

- a BASELINE.md-ready markdown trend table, one section per metric,
  rows grouped by backend (a CPU-degraded 44 r/s row must never be
  "compared" against an accelerator 823 r/s row — cross-backend deltas
  are environment noise, not regressions);
- a regression gate: within each (metric, backend) group, the LATEST
  non-degraded row is compared against the BEST prior non-degraded row;
  a drop worse than ``--max-regress`` (default 15%) exits nonzero and
  names the offender. Degraded rows are shown but never gate (their
  label already says the measurement is not the real one).

"Better" direction is per-metric: units measuring time (``ms``, ``s``,
``seconds``) regress UP, everything else (rounds/s, tenants/hour,
speedup factors, MFU fractions) regresses DOWN.

Usage::

    python scripts/bench_trend.py                      # repo root, print
    python scripts/bench_trend.py --out trend.md
    python scripts/bench_trend.py --row load-runs/slo_row.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_LOWER_BETTER_UNITS = {"s", "ms", "seconds", "milliseconds"}


def lower_is_better(row: dict) -> bool:
    unit = str(row.get("unit", "")).lower()
    metric = str(row.get("metric", ""))
    return unit in _LOWER_BETTER_UNITS or \
        metric.endswith(("_seconds", "_ms"))


def load_rows(root: str, extra: list) -> list:
    """Every bench row found, as ``{"source", "order", "row"}`` dicts —
    capsules sorted by their ``n``, extra rows appended after (they are
    the freshest measurements)."""
    out = []
    capsules = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    for path in capsules:
        try:
            doc = json.load(open(path))
        except (OSError, json.JSONDecodeError) as e:
            print(f"[trend] skipping {path}: {e!r}", file=sys.stderr)
            continue
        row = doc.get("parsed")
        if not isinstance(row, dict) or "metric" not in row:
            continue
        m = re.search(r"BENCH_r(\d+)", os.path.basename(path))
        out.append({"source": os.path.basename(path),
                    "order": int(m.group(1)) if m else 0, "row": row})
    next_order = max((r["order"] for r in out), default=0) + 1
    for path in extra:
        doc = json.load(open(path))
        row = doc.get("parsed", doc)   # capsule or bare row
        if "metric" not in row:
            raise SystemExit(f"--row {path}: not a bench row "
                             "(no 'metric' field)")
        out.append({"source": os.path.basename(path),
                    "order": next_order, "row": row})
        next_order += 1
    return out


def load_ledger_rows(path: str, entries: list) -> list:
    """Fold a run ledger's bench-bearing rows (``bench_row`` payloads —
    bench.py emits, loadgen SLO rows) in after the capsule/extra
    entries, ordered by append time and deduplicated by run id against
    nothing — ledger run ids are unique — and by exact row identity
    against the capsules (a row that reached BOTH a BENCH_r capsule and
    the ledger must not gate against itself)."""
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _repo not in sys.path:
        sys.path.insert(0, _repo)
    from gossipy_tpu.telemetry.ledger import RunLedger
    doc = RunLedger(path).read()
    if doc["skipped"]:
        print(f"[trend] {path}: skipped {doc['skipped']} torn line(s)",
              file=sys.stderr)
    seen_rows = {json.dumps(e["row"], sort_keys=True) for e in entries}
    seen_ids: set = set()
    next_order = max((e["order"] for e in entries), default=0) + 1
    ledger_rows = [r for r in doc["rows"]
                   if isinstance(r.get("bench_row"), dict)
                   and "metric" in r["bench_row"]]
    ledger_rows.sort(key=lambda r: r.get("ts") or 0.0)
    out = list(entries)
    for r in ledger_rows:
        rid = r.get("run_id")
        if rid in seen_ids:
            continue
        seen_ids.add(rid)
        canon = json.dumps(r["bench_row"], sort_keys=True)
        if canon in seen_rows:
            continue
        seen_rows.add(canon)
        out.append({"source": f"ledger:{rid}", "order": next_order,
                    "row": r["bench_row"]})
        next_order += 1
    return out


def _group_key(row: dict) -> tuple:
    raw = row.get("raw") or {}
    return (row["metric"], str(raw.get("backend", "unrecorded")))


def _degraded(row: dict) -> bool:
    return bool((row.get("raw") or {}).get("degraded"))


def analyze(entries: list, max_regress: float) -> tuple[str, list]:
    """(markdown trend table, regression list). Regressions compare the
    latest non-degraded row per (metric, backend) group against the best
    prior non-degraded row in the same group."""
    groups: dict[tuple, list] = {}
    for e in entries:
        groups.setdefault(_group_key(e["row"]), []).append(e)

    lines = ["# Bench trend", ""]
    regressions = []
    for (metric, backend) in sorted(groups):
        es = sorted(groups[(metric, backend)], key=lambda e: e["order"])
        lines += [f"## {metric} ({backend})", "",
                  "| source | value | unit | host blk% | stream× "
                  "| deliver× | deliver MB | degraded | note |",
                  "|---|---:|---|---:|---:|---:|---:|---|---|"]
        clean = [e for e in es if not _degraded(e["row"])]
        best_prior = None
        if len(clean) >= 2:
            prior = clean[:-1]
            vals = [e["row"]["value"] for e in prior]
            best_prior = (min(vals) if lower_is_better(clean[-1]["row"])
                          else max(vals))
        for e in es:
            row = e["row"]
            note = ""
            if clean and e is clean[-1] and best_prior is not None:
                lib = lower_is_better(row)
                delta = (best_prior - row["value"]) / best_prior \
                    if lib else (row["value"] - best_prior) / best_prior
                note = f"{delta:+.1%} vs best prior ({best_prior})"
                if delta < -max_regress:
                    regressions.append(
                        f"{metric} ({backend}): {e['source']} = "
                        f"{row['value']} {row.get('unit', '')} is "
                        f"{-delta:.1%} worse than best prior "
                        f"{best_prior} (> {max_regress:.0%} budget)")
                    note += "  **REGRESSION**"
            reason = (row.get("raw") or {}).get("degrade_reason", "")
            # host_blocked_frac: stamped by scripts/trace_report.py /
            # bench.py when the run was traced (telemetry.tracing) —
            # how much of the wall the host spent off the device's
            # critical path. Blank for untraced rows.
            hbf = (row.get("raw") or {}).get("host_blocked_frac")
            hbf_cell = f"{float(hbf) * 100:.1f}" if hbf is not None else ""
            # stream_speedup: bench.py --cohort's prefetch-pipeline A/B
            # (streaming wall vs serial wall, same config). Blank for
            # rows without a streaming variant.
            spd = (row.get("raw") or {}).get("stream_speedup")
            spd_cell = f"{float(spd):.2f}" if spd is not None else ""
            # deliver_ms_per_round / deliver_bytes_moved: bench.py
            # --fused-regime's per-leg deliver-phase A/B. deliver× is
            # the multi-slot kernel's gain over the per-slot fused leg
            # (same config, same trace harness); deliver MB is the
            # multi leg's modelled bytes moved per deliver phase. Blank
            # for rows without the fused A/B.
            dms = (row.get("raw") or {}).get("deliver_ms_per_round") or {}
            dlv_cell = ""
            if dms.get("per_slot") and dms.get("multi"):
                dlv_cell = f"{float(dms['per_slot']) / float(dms['multi']):.2f}"
            dbm = (row.get("raw") or {}).get("deliver_bytes_moved") or {}
            dmb_cell = f"{float(dbm['multi']) / 1e6:.1f}" \
                if dbm.get("multi") is not None else ""
            lines.append(
                f"| {e['source']} | {row['value']} "
                f"| {row.get('unit', '')} "
                f"| {hbf_cell} "
                f"| {spd_cell} "
                f"| {dlv_cell} "
                f"| {dmb_cell} "
                f"| {'yes — ' + reason if _degraded(row) else ''} "
                f"| {note} |")
        lines.append("")
    if not groups:
        lines.append("(no bench rows found)")
    return "\n".join(lines) + "\n", regressions


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--row", action="append", default=[],
                    help="extra bench-row JSON file (repeatable), e.g. "
                         "loadgen's slo_row.json")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="run-ledger file (telemetry.ledger): fold its "
                         "bench-bearing rows in alongside the capsules, "
                         "deduplicated by run id")
    ap.add_argument("--out", default=None,
                    help="write the markdown table here (default: stdout)")
    ap.add_argument("--max-regress", type=float, default=0.15,
                    help="gate threshold as a fraction (default 0.15)")
    args = ap.parse_args()

    entries = load_rows(args.root, args.row)
    if args.ledger:
        entries = load_ledger_rows(args.ledger, entries)
    table, regressions = analyze(entries, args.max_regress)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(table)
        print(f"[trend] {len(entries)} row(s) -> {args.out}",
              file=sys.stderr)
    else:
        print(table)
    for r in regressions:
        print(f"[trend] REGRESSION: {r}", file=sys.stderr)
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
