"""Replay a flight-recorder bundle and localize the first divergent op.

Restores a bundle written by
:class:`gossipy_tpu.telemetry.FlightRecorder` (the last healthy
``SimState`` checkpoint + PRNG key + the trailing telemetry window) into
a freshly built simulator and replays forward deterministically — round
randomness is keyed on the absolute round number, so the replay follows
the recorded trajectory bit-for-bit on the same backend. Prints a JSON
verdict naming:

- the first divergent round (must equal the recorded verdict's —
  ``matches_recorded`` says so),
- the first non-finite parameter leaf and the affected node ids,
- the engine phase (send / receive_merge / reply) that introduced the
  first non-finite value, found by re-executing the offending round
  eagerly (``jax.disable_jit``) phase by phase.

The bundle does not carry the dataset or handler (a checkpoint is state,
not code), so the caller names a FACTORY that rebuilds the simulator
with the recorded configuration (the bundle's ``manifest.json``
``config`` block documents it):

    python scripts/replay_bundle.py <bundle-dir> --factory mymod:build_sim
    python scripts/replay_bundle.py <bundle-dir> --demo   # CI smoke config

The factory is an importable ``module:callable`` returning a
sentinel-enabled simulator. Exit status: 0 when the replay verdict
matches the recorded one (or the bundle recorded no sentinel round —
exception/watchdog bundles), 1 on mismatch.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _load_factory(spec: str):
    mod_name, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"--factory expects module:callable, got {spec!r}")
    return getattr(importlib.import_module(mod_name), attr)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="flight-recorder bundle directory")
    ap.add_argument("--factory", default=None,
                    help="module:callable returning the simulator the "
                         "bundle was recorded from (sentinels enabled)")
    ap.add_argument("--demo", action="store_true",
                    help="rebuild the CI smoke simulator "
                         "(scripts/ci_smoke_artifact.py config)")
    ap.add_argument("--max-rounds", type=int, default=None,
                    help="replay at most this many rounds past the "
                         "checkpoint (default: up to the recorded "
                         "first-bad round, or 64)")
    ap.add_argument("--no-localize", action="store_true",
                    help="skip the eager per-phase localization pass")
    args = ap.parse_args()

    if args.demo == (args.factory is not None):
        raise SystemExit("pass exactly one of --factory or --demo")

    from gossipy_tpu.telemetry import replay_bundle

    if args.demo:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from ci_smoke_artifact import build_smoke_sim
        sim = build_smoke_sim()
    else:
        sim = _load_factory(args.factory)()

    with open(os.path.join(args.bundle, "verdict.json")) as fh:
        recorded = json.load(fh)
    print(f"[replay] bundle kind={recorded['kind']} "
          f"chunk_start_round={recorded['chunk_start_round']} "
          f"recorded first_bad_round={recorded['first_bad_round']}",
          file=sys.stderr)

    verdict = replay_bundle(args.bundle, sim, max_rounds=args.max_rounds,
                            localize=not args.no_localize)
    print(json.dumps(verdict, indent=2))
    if verdict["matches_recorded"] is False:
        print("[replay] MISMATCH: the replayed first-divergent round "
              "differs from the recorded one — was the factory built with "
              "the recorded config (see the bundle's manifest.json) and "
              "run on the same backend?", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
